"""genmodel breadth: non-tree MOJO writers/readers, POJO codegen, and the
EasyPredict row API.

Reference wire formats (re-derived from the READERS, not copied):
- GLM MOJO 1.00 — hex/genmodel/algos/glm/GlmMojoReader.java kv set
  (use_all_factor_levels, cats, cat_modes, cat_offsets, nums, num_means,
  mean_imputation, beta, family, link) and GlmMojoModelBase.score0's beta
  layout: per-cat indicator blocks first (skipping level 0 when
  use_all_factor_levels=false), then numerics, intercept LAST; data rows
  arrive cats-first (DataInfo column reordering).
- KMeans MOJO 1.00 — algos/kmeans/KMeansMojoReader.java (standardize,
  standardize_means/mults/modes, center_num, center_i arrays).
- DeepLearning MOJO 1.10 — algos/deeplearning/DeeplearningMojoReader.java
  (nums/cats/cat_offsets/norm_mul/norm_sub/activation/
  neural_network_sizes, weight_layer{i}/bias_layer{i}).
- POJO codegen — hex/tree/TreeJCodeGen.java emits one Java class per
  model with nested if/else per tree; we emit the same *shape* of source
  (compile-checked only when a JDK exists; golden-file otherwise).
- EasyPredict row API — hex/genmodel/easy/EasyPredictModelWrapper.java
  (RowData dict → typed prediction).

Array kv values use Java's Arrays.toString format ("[a, b, c]"), the
format AbstractMojoWriter.writekv emits and ModelMojoReader parses.
"""
from __future__ import annotations

import uuid as _uuid
import zipfile
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _jarr(vals, quote: bool = False) -> str:
    if quote:
        # JSON-escape so names containing '"' or ',' roundtrip
        import json
        return "[" + ", ".join(json.dumps(str(v)) for v in vals) + "]"
    return "[" + ", ".join(str(v) for v in vals) + "]"


def _parse_jarr(s: str, typ=float):
    s = s.strip()
    if '"' in s:
        # quoted string array — written JSON-escaped by _jarr
        import json
        return [typ(v) for v in json.loads(s)]
    if s.startswith("["):
        s = s[1:-1]
    return [typ(v.strip()) for v in s.split(",") if v.strip()]


def _split_design(model):
    """Cats-first column reordering (DataInfo): returns (cat_idx,
    num_idx) into model.feature_names."""
    cat_idx = [i for i, c in enumerate(model.feature_is_cat) if c]
    num_idx = [i for i, c in enumerate(model.feature_is_cat) if not c]
    return cat_idx, num_idx


def _beta_glm_layout(model) -> Tuple[np.ndarray, List[int], List[float]]:
    """Map our expand_design-ordered beta (original column order, enum
    blocks inline) to the genmodel layout: cat blocks first, then nums,
    intercept last. Returns (beta, cat_offsets, num_means)."""
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    # index our exp_names: cat level j of col n is "n.<lvl>"; numeric is n
    pos = {n: i for i, n in enumerate(model.exp_names)}
    beta_src = np.asarray(model.beta, dtype=np.float64)
    out: List[float] = []
    cat_offsets = [0]
    for ci in cat_idx:
        n = names[ci]
        dom = list(model.cat_domains.get(n, ()))
        for lvl in dom[1:]:                     # level 0 skipped
            key = f"{n}.{lvl}"
            out.append(float(beta_src[pos[key]]) if key in pos else 0.0)
        cat_offsets.append(cat_offsets[-1] + max(len(dom) - 1, 0))
    num_means = []
    for ni in num_idx:
        n = names[ni]
        out.append(float(beta_src[pos[n]]))
        num_means.append(float(model.impute_means.get(n, 0.0)))
    out.append(float(model.intercept_value))
    return np.asarray(out), cat_offsets, num_means


def _ini_header(model, algo: str, algorithm: str, category: str,
                columns: List[str], mojo_version: str,
                extra_kv: List[str]) -> Tuple[str, List[Tuple[str, List[str]]]]:
    n_features = len(columns) - (1 if model.response else 0)
    ini = ["[info]",
           "h2o_version = 3.46.0.1",
           f"mojo_version = {mojo_version}",
           "license = Apache License Version 2.0",
           f"algo = {algo}",
           f"algorithm = {algorithm}",
           f"category = {category}",
           f"uuid = {int(_uuid.uuid4()) % (1 << 63)}",
           f"supervised = {'true' if model.response else 'false'}",
           f"n_features = {n_features}",
           f"n_classes = {max(model.nclasses, 1)}",
           f"n_columns = {len(columns)}",
           "balance_classes = false",
           "default_threshold = 0.5",
           "prior_class_distrib = null",
           "model_class_distrib = null",
           "timestamp = 2026-01-01 00:00:00",
           "escape_domain_values = false",
           "_genmodel_encoding = AUTO",
           ] + extra_kv
    dom_lines = ["", "[columns]"] + columns + ["", "[domains]"]
    dom_files: List[Tuple[str, List[str]]] = []
    di = 0
    for ci, name in enumerate(columns):
        dom = None
        if name == model.response and model.response_domain:
            dom = list(model.response_domain)
        elif name in model.cat_domains:
            dom = list(model.cat_domains[name])
        if dom:
            fn = f"d{di:03d}.txt"
            dom_lines.append(f"{ci}: {len(dom)} {fn}")
            dom_files.append((fn, dom))
            di += 1
    return "\n".join(ini + dom_lines) + "\n", dom_files


def _write_zip(path: str, ini_text: str,
               dom_files: List[Tuple[str, List[str]]],
               blobs: Optional[Dict[str, bytes]] = None) -> str:
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("model.ini", ini_text)
        for fn, dom in dom_files:
            zf.writestr(f"domains/{fn}",
                        "\n".join(str(d) for d in dom) + "\n")
        for name, data in (blobs or {}).items():
            zf.writestr(name, data)
    return path


# ---------------- GLM ---------------------------------------------------

def export_mojo_glm(model, path: str) -> str:
    if model.family == "multinomial":
        raise ValueError("multinomial GLM MOJO export not supported yet")
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    beta, cat_offsets, num_means = _beta_glm_layout(model)
    cat_modes = [0] * len(cat_idx)
    columns = ([names[i] for i in cat_idx] + [names[i] for i in num_idx]
               + ([model.response] if model.response else []))
    link = {"gaussian": "identity", "binomial": "logit", "poisson": "log",
            "gamma": "log"}[model.family]
    extra = [
        "use_all_factor_levels = false",
        f"cats = {len(cat_idx)}",
        f"cat_modes = {_jarr(cat_modes)}",
        f"cat_offsets = {_jarr(cat_offsets)}",
        f"nums = {len(num_idx)}",
        f"num_means = {_jarr(num_means)}",
        "mean_imputation = true",
        f"beta = {_jarr(beta.tolist())}",
        f"family = {model.family}",
        f"link = {link}",
        "tweedie_link_power = 0.0",
    ]
    ini, doms = _ini_header(model, "glm", "Generalized Linear Model",
                            "Binomial" if model.nclasses == 2
                            else "Regression", columns, "1.00", extra)
    return _write_zip(path, ini, doms)


class GlmMojoScorer:
    """Standalone scorer for a GLM MOJO (GlmMojoModel.glmScore0)."""

    def __init__(self, kv: Dict[str, str], columns, domains, response):
        self.cats = int(kv["cats"])
        self.nums = int(kv["nums"])
        self.cat_offsets = _parse_jarr(kv["cat_offsets"], int)
        self.cat_modes = _parse_jarr(kv.get("cat_modes", "[]"), int)
        self.num_means = _parse_jarr(kv.get("num_means", "[]"), float)
        self.beta = np.asarray(_parse_jarr(kv["beta"], float))
        self.family = kv["family"]
        self.link = kv.get("link", "identity")
        self.columns = columns
        self.domains = domains
        self.response = response
        self.nclasses = 2 if self.family == "binomial" else 1

    def score(self, row: np.ndarray) -> np.ndarray:
        data = np.asarray(row, dtype=np.float64).copy()
        for i in range(self.cats):
            if np.isnan(data[i]):
                data[i] = self.cat_modes[i]
        for i in range(self.nums):
            if np.isnan(data[self.cats + i]):
                data[self.cats + i] = self.num_means[i]
        eta = 0.0
        for i in range(self.cats):
            code = int(data[i])
            if code != 0:               # level 0 skipped
                ival = self.cat_offsets[i] + code - 1
                if ival < self.cat_offsets[i + 1]:
                    eta += self.beta[ival]
        noff = self.cat_offsets[self.cats] if self.cats else 0
        for i in range(self.nums):
            eta += self.beta[noff + i] * data[self.cats + i]
        eta += self.beta[-1]
        mu = {"identity": lambda e: e,
              "logit": lambda e: 1.0 / (1.0 + np.exp(-e)),
              "log": np.exp}[self.link](eta)
        if self.family == "binomial":
            return np.array([float(mu > 0.5), 1.0 - mu, mu])
        return np.array([mu])


# ---------------- KMeans ------------------------------------------------

def export_mojo_kmeans(model, path: str) -> str:
    # our KMeans trains on the expanded standardized design; centers_raw
    # are in expanded-column space (exp_names)
    columns = list(model.feature_names)
    centers = np.asarray(model.centers_raw, dtype=np.float64)
    means = np.asarray(model.xm, dtype=np.float64)
    mults = 1.0 / np.maximum(np.asarray(model.xs, dtype=np.float64), 1e-12)
    extra = [
        "standardize = true",
        f"standardize_means = {_jarr(means.tolist())}",
        f"standardize_mults = {_jarr(mults.tolist())}",
        f"standardize_modes = {_jarr([0] * len(means))}",
        f"center_num = {centers.shape[0]}",
    ]
    extra += [f"center_{i} = {_jarr(c.tolist())}"
              for i, c in enumerate(centers)]
    ini, doms = _ini_header(model, "kmeans", "K-means", "Clustering",
                            columns, "1.00", extra)
    return _write_zip(path, ini, doms)


class KMeansMojoScorer:
    def __init__(self, kv: Dict[str, str], columns, domains, response):
        self.standardize = kv.get("standardize", "true") == "true"
        self.means = np.asarray(_parse_jarr(kv["standardize_means"]))
        self.mults = np.asarray(_parse_jarr(kv["standardize_mults"]))
        n = int(kv["center_num"])
        self.centers = np.stack([
            np.asarray(_parse_jarr(kv[f"center_{i}"])) for i in range(n)])
        self.nclasses = 1
        self.columns = columns

    def score(self, row: np.ndarray) -> np.ndarray:
        x = np.asarray(row, dtype=np.float64)
        x = np.where(np.isnan(x), self.means, x)
        xs = (x - self.means) * self.mults if self.standardize else x
        cs = (self.centers - self.means[None, :]) * self.mults[None, :] \
            if self.standardize else self.centers
        d = ((cs - xs[None, :]) ** 2).sum(1)
        return np.array([float(np.argmin(d))])


# ---------------- DeepLearning -----------------------------------------

def export_mojo_deeplearning(model, path: str) -> str:
    """MLP MOJO (mojo 1.10 kv set). Our net: list of (W [in, out], b)
    float32; genmodel stores row-major [out*in] weight blobs per layer."""
    if model.task == "autoencoder":
        raise ValueError("autoencoder MOJO export not supported")
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    columns = ([names[i] for i in cat_idx] + [names[i] for i in num_idx]
               + ([model.response] if model.response else []))
    # expanded design is standardized over ALL expanded cols; genmodel
    # normalizes only numerics (norm_sub/mul over nums) — we export the
    # expanded-space stats and mark all expanded cols numeric-like via
    # cat_offsets on the ORIGINAL enum blocks
    pos = {n: i for i, n in enumerate(model.exp_names)}
    cat_offsets = [0]
    perm: List[int] = []
    for ci in cat_idx:
        n = names[ci]
        dom = list(model.cat_domains.get(n, ()))
        block = [pos[f"{n}.{lvl}"] for lvl in dom[1:] if f"{n}.{lvl}" in pos]
        perm.extend(block)
        cat_offsets.append(cat_offsets[-1] + len(block))
    num_perm = [pos[names[ni]] for ni in num_idx]
    perm_all = perm + num_perm
    xm = np.asarray(model.xm, dtype=np.float64)
    xs = np.asarray(model.xs, dtype=np.float64)
    units = [len(perm_all)] + list(model.hidden) + [
        model.nclasses if model.nclasses > 1 else 1]
    act_map = {"rectifier": "Rectifier", "tanh": "Tanh", "maxout": "Maxout"}
    extra = [
        "mini_batch_size = 1",
        f"nums = {len(num_idx)}",
        f"cats = {len(cat_idx)}",
        f"cat_offsets = {_jarr(cat_offsets)}",
        f"norm_mul = {_jarr((1.0 / np.maximum(xs[perm_all], 1e-12)).tolist())}",
        f"norm_sub = {_jarr(xm[perm_all].tolist())}",
        "norm_resp_mul = null",
        "norm_resp_sub = null",
        "use_all_factor_levels = false",
        f"activation = {act_map.get(model.activation, 'Rectifier')}",
        f"distribution = {model.dist_name}",
        "mean_imputation = true",
        f"cat_modes = {_jarr([0] * len(cat_idx))}",
        f"neural_network_sizes = {_jarr(units)}",
        f"hidden_dropout_ratios = {_jarr([0.0] * len(model.hidden))}",
    ]
    # weights: reorder input layer rows by perm_all (original exp order →
    # cats-first order); genmodel blob is row-major [out, in]
    for li, layer in enumerate(model.net):
        Wn = np.asarray(layer["W"], dtype=np.float64)
        b = np.asarray(layer["b"], dtype=np.float64).reshape(-1)
        if li == 0:
            Wn = Wn[np.asarray(perm_all)]
        extra.append(f"weight_layer{li} = {_jarr(Wn.T.reshape(-1).tolist())}")
        extra.append(f"bias_layer{li} = {_jarr(b.tolist())}")
    ini, doms = _ini_header(
        model, "deeplearning", "Deep Learning", "Binomial"
        if model.nclasses == 2 else "Multinomial" if model.nclasses > 2
        else "Regression", columns, "1.10", extra)
    return _write_zip(path, ini, doms)


class DeepLearningMojoScorer:
    def __init__(self, kv: Dict[str, str], columns, domains, response):
        self.cats = int(kv["cats"])
        self.nums = int(kv["nums"])
        self.cat_offsets = _parse_jarr(kv["cat_offsets"], int)
        self.norm_mul = np.asarray(_parse_jarr(kv["norm_mul"]))
        self.norm_sub = np.asarray(_parse_jarr(kv["norm_sub"]))
        self.units = _parse_jarr(kv["neural_network_sizes"], int)
        self.activation = kv["activation"]
        self.distribution = kv.get("distribution", "gaussian")
        self.layers = []
        for li in range(len(self.units) - 1):
            w = np.asarray(_parse_jarr(kv[f"weight_layer{li}"]))
            b = np.asarray(_parse_jarr(kv[f"bias_layer{li}"]))
            self.layers.append(
                (w.reshape(self.units[li + 1], self.units[li]), b))
        self.columns = columns
        self.domains = domains
        k = self.units[-1]
        self.nclasses = k if k > 1 else 1

    def score(self, row: np.ndarray) -> np.ndarray:
        data = np.asarray(row, dtype=np.float64)
        vec = np.zeros(self.units[0])
        for i in range(self.cats):
            code = int(data[i]) if np.isfinite(data[i]) else 0
            if code != 0:
                ival = self.cat_offsets[i] + code - 1
                if ival < self.cat_offsets[i + 1]:
                    vec[ival] = 1.0
        noff = self.cat_offsets[self.cats] if self.cats else 0
        for i in range(self.nums):
            v = data[self.cats + i]
            vec[noff + i] = 0.0 if not np.isfinite(v) else v
        vec = (vec - self.norm_sub) * self.norm_mul
        h = vec
        for li, (W, b) in enumerate(self.layers):
            h = W @ h + b
            if li < len(self.layers) - 1:
                if self.activation == "Tanh":
                    h = np.tanh(h)
                else:
                    h = np.maximum(h, 0.0)
        if self.nclasses > 1:
            e = np.exp(h - h.max())
            p = e / e.sum()
            return np.concatenate([[float(np.argmax(p))], p])
        if self.distribution == "bernoulli":
            p1 = 1.0 / (1.0 + np.exp(-h[0]))
            return np.array([float(p1 > 0.5), 1 - p1, p1])
        return np.array([h[0]])


# ---------------- POJO codegen (TreeJCodeGen analog) --------------------

def pojo_source(model, class_name: Optional[str] = None) -> str:
    """Emit Java source scoring a GBM/DRF model — the
    hex/tree/TreeJCodeGen.java role: one static method per tree with the
    nested if/else descent, a score0 summing them. Compiles against
    h2o-genmodel's GenModel when a JDK is present; golden-file checked
    otherwise."""
    from h2o3_tpu import telemetry
    algo = model.algo
    cls = class_name or f"{algo}_pojo_{abs(hash(model.key)) % 10 ** 8}"
    # one counted pytree fetch for the codegen arrays (export-time D2H
    # must show up in the transfer budgets like every other fetch)
    feat, thr, nal, spl, val = (np.asarray(a) for a in telemetry.device_get(
        (model._feat, model._thr, model._na_left, model._is_split,
         model._value), pipeline="export"))
    K = model.nclasses if model.nclasses > 2 else 1
    T = model.ntrees_built
    names = list(model.feature_names)

    def emit_node(t, m, indent) -> List[str]:
        pad = "  " * indent
        if not spl[t, m]:
            return [f"{pad}return {val[t, m]!r}f;"]
        f = int(feat[t, m])
        cond = f"Double.isNaN(data[{f}]) ? {str(bool(nal[t, m])).lower()}" \
               f" : data[{f}] < {thr[t, m]!r}f"
        out = [f"{pad}if ({cond}) {{"]
        out += emit_node(t, 2 * m + 1, indent + 1)
        out += [f"{pad}}} else {{"]
        out += emit_node(t, 2 * m + 2, indent + 1)
        out += [f"{pad}}}"]
        return out

    lines = [
        "// Auto-generated POJO scorer (hex/tree/TreeJCodeGen shape);",
        "// score0 contract matches hex/genmodel/GenModel.score0.",
        f"public class {cls} {{",
        f"  public static final String[] NAMES = {{"
        + ", ".join(f'"{n}"' for n in names) + "};",
        f"  public static final int NTREES = {T};",
        f"  public static final int NCLASSES = {max(model.nclasses, 1)};",
    ]
    for t in range(T * K):
        lines.append(f"  static float tree_{t}(double[] data) {{")
        lines += emit_node(t, 0, 2)
        lines.append("  }")
    if K == 1:
        f0 = float(np.asarray(model.f0).reshape(-1)[0]) \
            if model.algo == "gbm" else 0.0
        lines += [
            "  public static double[] score0(double[] data, double[] preds) {",
            f"    double f = {f0!r};",
            f"    for (int t = 0; t < {T}; t++) f += scoreTree(t, data);",
        ]
        if model.nclasses == 2:
            lines += [
                "    double p1 = 1.0 / (1.0 + Math.exp(-f));",
                "    preds[0] = p1 > 0.5 ? 1 : 0; preds[1] = 1 - p1; "
                "preds[2] = p1;",
            ]
        else:
            lines += ["    preds[0] = f;"]
        lines += ["    return preds;", "  }"]
    else:
        lines += [
            "  public static double[] score0(double[] data, double[] preds) {",
            f"    double[] margin = new double[{K}];",
            f"    for (int t = 0; t < {T}; t++)",
            f"      for (int k = 0; k < {K}; k++)",
            f"        margin[k] += scoreTree(t * {K} + k, data);",
            "    double max = Double.NEGATIVE_INFINITY, sum = 0;",
            f"    for (int k = 0; k < {K}; k++) max = Math.max(max, margin[k]);",
            f"    for (int k = 0; k < {K}; k++) {{ "
            "preds[k + 1] = Math.exp(margin[k] - max); sum += preds[k + 1]; }",
            f"    for (int k = 0; k < {K}; k++) preds[k + 1] /= sum;",
            "    preds[0] = 0;",
            "    return preds;",
            "  }",
        ]
    # dispatch table (javac rejects methods > 64KB; per-tree methods keep
    # each unit small — the same reason TreeJCodeGen splits classes)
    lines.append("  static float scoreTree(int t, double[] data) {")
    lines.append("    switch (t) {")
    for t in range(T * K):
        lines.append(f"      case {t}: return tree_{t}(data);")
    lines.append("      default: throw new IllegalArgumentException();")
    lines.append("    }")
    lines.append("  }")
    lines.append("}")
    return "\n".join(lines) + "\n"


def pojo_source_glm(model, class_name: Optional[str] = None) -> str:
    """GLM POJO (water/util/JCodeGen + GLM's POJO emit): the cats-first
    beta layout from the MOJO writer, scored with the same skip-level-0
    indicator logic as GlmMojoModel.glmScore0."""
    if model.family not in ("gaussian", "binomial", "poisson", "gamma"):
        raise ValueError(
            f"GLM POJO export supports gaussian/binomial/poisson/gamma "
            f"(got family='{model.family}')")
    cls = class_name or f"glm_pojo_{abs(hash(model.key)) % 10 ** 8}"
    beta, cat_offsets, num_means = _beta_glm_layout(model)
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    columns = [names[i] for i in cat_idx] + [names[i] for i in num_idx]
    link = {"gaussian": "eta", "binomial": "1.0 / (1.0 + Math.exp(-eta))",
            "poisson": "Math.exp(eta)", "gamma": "Math.exp(eta)"}[
                model.family]
    lines = [
        "// Auto-generated GLM POJO (water/util/JCodeGen shape);",
        "// beta layout matches GlmMojoModelBase (cats first, intercept",
        "// last, level 0 of each factor dropped).",
        f"public class {cls} {{",
        "  public static final String[] NAMES = {"
        + ", ".join(f'"{n}"' for n in columns) + "};",
        "  public static final double[] BETA = {"
        + ", ".join(repr(float(v)) for v in beta) + "};",
        "  public static final int[] CAT_OFFSETS = {"
        + ", ".join(str(v) for v in cat_offsets) + "};",
        "  public static final double[] NUM_MEANS = {"
        + ", ".join(repr(float(v)) for v in num_means) + "};",
        f"  public static final int CATS = {len(cat_idx)};",
        f"  public static final int NUMS = {len(num_idx)};",
        "  public static double[] score0(double[] data, double[] preds) {",
        "    double eta = 0.0;",
        "    for (int i = 0; i < CATS; i++) {",
        "      int code = Double.isNaN(data[i]) ? 0 : (int) data[i];",
        "      if (code != 0) {",
        "        int ival = CAT_OFFSETS[i] + code - 1;",
        "        if (ival < CAT_OFFSETS[i + 1]) eta += BETA[ival];",
        "      }",
        "    }",
        "    int noff = CATS > 0 ? CAT_OFFSETS[CATS] : 0;",
        "    for (int i = 0; i < NUMS; i++) {",
        "      double v = data[CATS + i];",
        "      if (Double.isNaN(v)) v = NUM_MEANS[i];",
        "      eta += BETA[noff + i] * v;",
        "    }",
        "    eta += BETA[BETA.length - 1];",
        f"    double mu = {link};",
    ]
    if model.nclasses == 2:
        lines += ["    preds[0] = mu > 0.5 ? 1 : 0;",
                  "    preds[1] = 1.0 - mu; preds[2] = mu;"]
    else:
        lines += ["    preds[0] = mu;"]
    lines += ["    return preds;", "  }", "}"]
    return "\n".join(lines) + "\n"


def export_pojo(model, path: str, class_name: Optional[str] = None) -> str:
    if getattr(model, "algo", "") == "glm":
        src = pojo_source_glm(model, class_name)
    else:
        src = pojo_source(model, class_name)
    with open(path, "w") as f:
        f.write(src)
    return path


# ---------------- EasyPredict row API ----------------------------------

def build_domain_luts(columns: Sequence[str],
                      cat_domains: Dict[str, Sequence[str]]
                      ) -> Dict[str, Dict[str, int]]:
    """Per-column label→code lookup tables for the categorical columns.
    Built once per model (deploy/wrapper construction) so batch encoding
    is O(1) per label instead of the O(|domain|) list.index scan."""
    return {c: {str(lab): i for i, lab in enumerate(cat_domains[c])}
            for c in columns if cat_domains.get(c)}


def rows_to_matrix(rows: Sequence[Dict[str, Any]], columns: Sequence[str],
                   cat_domains: Dict[str, Sequence[str]], *,
                   convert_unknown_categorical_levels_to_na: bool = True,
                   convert_invalid_numbers_to_na: bool = False,
                   unknown_seen: Optional[Dict[str, int]] = None,
                   luts: Optional[Dict[str, Dict[str, int]]] = None,
                   out: Optional[np.ndarray] = None) -> np.ndarray:
    """Vectorized RowData encoding: a batch of {column: value} dicts →
    [n, F] float matrix in training column order — the
    EasyPredictModelWrapper dict→array contract applied to whole
    batches (the serve codec's hot path). Per column: enum labels map
    through the training-domain LUT, unknown levels → NA (or raise,
    per the convert_unknown flag), missing columns / None → NA.

    Int-coded enum levels honor the SAME unknown-level policy as
    string labels: a numeric code outside [0, cardinality) — or a
    non-integral one — is an unknown level, not a silent pass-through
    (the old single-row path forwarded any float verbatim, so an
    out-of-domain code could route down a tree branch that training
    never built).

    ``out`` may be a caller-provided (padded) buffer with >= n rows;
    rows past len(rows) are left untouched."""
    n = len(rows)
    F = len(columns)
    if out is None:
        out = np.full((n, F), np.nan, np.float64)
    else:
        out[:n, :] = np.nan
    if luts is None:
        luts = build_domain_luts(columns, cat_domains)
    for j, c in enumerate(columns):
        lut = luts.get(c)
        if lut is None:
            # numeric column: one-shot asarray fast path, element-wise
            # fallback only when a value refuses to parse
            vals = [r.get(c) for r in rows]
            try:
                col = np.asarray(
                    [np.nan if v is None else v for v in vals],
                    dtype=np.float64)
            except (TypeError, ValueError):
                if not convert_invalid_numbers_to_na:
                    raise
                col = np.full(n, np.nan, np.float64)
                for i, v in enumerate(vals):
                    if v is None:
                        continue
                    try:
                        col[i] = float(v)
                    except (TypeError, ValueError):
                        pass
            out[:n, j] = col
            continue
        ncat = len(lut)
        unknown = 0
        for i, r in enumerate(rows):
            v = r.get(c)
            if v is None:
                continue
            if isinstance(v, str):
                code = lut.get(v, -1)
            else:
                try:
                    fv = float(v)
                except (TypeError, ValueError):
                    code = -1
                else:
                    if np.isnan(fv):
                        continue            # numeric NA → NA level
                    code = int(fv) if (np.isfinite(fv) and fv == int(fv)
                                       and 0 <= fv < ncat) else -1
            if code < 0:
                # unseen level: NA when configured (default), else a
                # PredictUnknownCategoricalLevelException analog
                if not convert_unknown_categorical_levels_to_na:
                    raise ValueError(
                        f"unknown categorical level {v!r} for column "
                        f"'{c}' (set convert_unknown_categorical_levels"
                        f"_to_na=True to map to NA)")
                unknown += 1
                continue
            out[i, j] = code
        if unknown and unknown_seen is not None:
            unknown_seen[c] = unknown_seen.get(c, 0) + unknown
    return out


class EasyPredictModelWrapper:
    """Row-dict scoring over any of our models OR a loaded MOJO scorer —
    hex/genmodel/easy/EasyPredictModelWrapper.java's RowData contract:
    values may be numbers or category LABELS; unknown categoricals map
    to NA; missing columns are NA."""

    def __init__(self, model, convert_unknown_categorical_levels_to_na:
                 bool = True, convert_invalid_numbers_to_na: bool = False,
                 enable_contributions: bool = False,
                 enable_leaf_assignment: bool = False):
        """Config mirrors EasyPredictModelWrapper.Config
        (hex/genmodel/easy/EasyPredictModelWrapper.java): unknown-level
        handling, invalid-number handling, and contributions/leaf
        pass-through for tree models."""
        self.model = model
        self.columns = list(getattr(model, "feature_names", None)
                            or getattr(model, "columns", []))
        self.cat_domains = dict(getattr(model, "cat_domains", {}) or {})
        self.response_domain = list(
            getattr(model, "response_domain", None) or [])
        self.convert_unknown_categorical_levels_to_na = bool(
            convert_unknown_categorical_levels_to_na)
        self.convert_invalid_numbers_to_na = bool(
            convert_invalid_numbers_to_na)
        self.unknown_categorical_levels_seen: Dict[str, int] = {}
        self._luts = build_domain_luts(self.columns, self.cat_domains)
        self.enable_contributions = bool(enable_contributions)
        self.enable_leaf_assignment = bool(enable_leaf_assignment)
        if enable_contributions and not hasattr(model,
                                                "predict_contributions"):
            raise ValueError("enable_contributions: this model has no "
                             "TreeSHAP support (GBM/DRF/XGBoost only)")

    def _row_to_array(self, row: Dict[str, Any]) -> np.ndarray:
        return rows_to_matrix(
            [row], self.columns, self.cat_domains,
            convert_unknown_categorical_levels_to_na=self
            .convert_unknown_categorical_levels_to_na,
            convert_invalid_numbers_to_na=self.convert_invalid_numbers_to_na,
            unknown_seen=self.unknown_categorical_levels_seen,
            luts=self._luts)[0]

    def predict_row(self, row: Dict[str, Any]) -> Dict[str, Any]:
        arr = self._row_to_array(row)
        m = self.model
        if hasattr(m, "score") and not hasattr(m, "_predict_matrix"):
            preds = np.asarray(m.score(arr))
        else:
            import jax.numpy as jnp
            out = np.asarray(m._predict_matrix(jnp.asarray(arr[None, :])))[0]
            if m.nclasses >= 2:
                preds = np.concatenate([[float(np.argmax(out))], out])
            else:
                preds = np.asarray([float(out)]).reshape(-1)
        nclasses = getattr(m, "nclasses", 1)
        if nclasses >= 2:
            label_idx = int(preds[0])
            label = (self.response_domain[label_idx]
                     if self.response_domain else str(label_idx))
            probs = {(self.response_domain[k] if self.response_domain
                      else str(k)): float(p)
                     for k, p in enumerate(preds[1:])}
            out_d = {"label": label, "classProbabilities": probs}
        else:
            out_d = {"value": float(preds[0])}
        out_d.update(self._tree_extras(arr))
        return out_d

    def _tree_extras(self, arr: np.ndarray) -> Dict[str, Any]:
        """contributions / leafNodeAssignments pass-through (the
        Config.setEnableContributions / setEnableLeafAssignment
        behaviors of the reference wrapper)."""
        extras: Dict[str, Any] = {}
        m = self.model
        if self.enable_contributions:
            from h2o3_tpu.models.treeshap import tree_shap_contributions
            phi, bias = tree_shap_contributions(
                arr[None, :], m._feat, m._thr, m._na_left, m._is_split,
                m._node_w, m._value, m.max_depth, len(self.columns),
                tree_scale=m._contrib_scale())
            extras["contributions"] = {
                **{c: float(phi[0, i]) for i, c in enumerate(self.columns)},
                "BiasTerm": float(bias + m._contrib_f0())}
        if self.enable_leaf_assignment and hasattr(m, "_feat"):
            from h2o3_tpu.models.treeshap import leaf_node_assignment
            paths = leaf_node_assignment(arr[None, :], m._feat, m._thr,
                                         m._na_left, m._is_split,
                                         m.max_depth, kind="Path")
            extras["leafNodeAssignments"] = [str(p) for p in paths[0]]
        return extras


# ---------------- CoxPH -------------------------------------------------

def export_mojo_coxph(model, path: str) -> str:
    """CoxPH MOJO (hex/genmodel/algos/coxph/CoxPHMojoWriter wire role:
    coefficients over the cats-first genmodel layout + design means; no
    JVM in this image, so parity is the reader-contract round-trip —
    recorded limitation). The GLM layout machinery is reused: CoxPH has
    no intercept, so the trailing layout slot carries 0."""
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    if not hasattr(model, "intercept_value"):
        model.intercept_value = 0.0          # partial likelihood: none
    beta, cat_offsets, num_means = _beta_glm_layout(model)
    cols = ([names[i] for i in cat_idx] + [names[i] for i in num_idx]
            + ([model.response] if model.response else []))
    kv = [f"cats = {len(cat_idx)}",
          f"cat_offsets = {_jarr(cat_offsets)}",
          f"nums = {len(num_idx)}",
          f"num_means = {_jarr(num_means)}",
          f"beta = {_jarr(beta.tolist())}",
          "use_all_factor_levels = false"]
    ini, doms = _ini_header(model, "coxph", "CoxPH", "CoxPH", cols,
                            "1.00", kv)
    return _write_zip(path, ini, doms)


class CoxPHMojoScorer:
    """Linear predictor over the cats-first layout (the genmodel
    CoxPHMojoModel score0 contract: preds[0] = lp, centered on the
    numeric design means)."""

    def __init__(self, kv, columns, domains, response):
        self.columns = [c for c in columns if c != response]
        self.cats = int(kv["cats"])
        self.nums = int(kv["nums"])
        self.cat_offsets = _parse_jarr(kv["cat_offsets"], int)
        self.num_means = _parse_jarr(kv.get("num_means", "[]"), float)
        self.beta = np.asarray(_parse_jarr(kv["beta"]))
        self.cat_domains = domains
        self.nclasses = 1

    def score(self, row: np.ndarray) -> np.ndarray:
        data = np.asarray(row, dtype=np.float64).copy()
        lp = 0.0
        for i in range(self.cats):
            if np.isnan(data[i]):
                continue                       # NA level: no indicator
            code = int(data[i])
            if code != 0:                      # level 0 dropped
                ival = self.cat_offsets[i] + code - 1
                if ival < self.cat_offsets[i + 1]:
                    lp += self.beta[ival]
        noff = self.cat_offsets[self.cats] if self.cats else 0
        for i in range(self.nums):
            v = data[self.cats + i]
            if np.isnan(v):
                v = self.num_means[i]
            lp += self.beta[noff + i] * (v - self.num_means[i])
        return np.array([float(lp)])


# ---------------- Word2Vec ---------------------------------------------

def export_mojo_word2vec(model, path: str) -> str:
    """Word2Vec MOJO (hex/genmodel/algos/word2vec/WordEmbeddingModel
    role): vocab + [V, D] embedding block."""
    vecs = np.asarray(model.vectors, np.float32)
    kv = [f"vec_size = {vecs.shape[1]}",
          f"vocab_size = {len(model.vocab)}"]
    cols = ["word"]
    ini, doms = _ini_header(model, "word2vec", "Word2Vec", "WordEmbedding",
                            cols, "1.00", kv)
    blobs = {"vectors.bin": vecs.tobytes(),
             "vocab.txt": ("\n".join(model.vocab) + "\n").encode()}
    return _write_zip(path, ini, doms, blobs)


class Word2VecMojoScorer:
    def __init__(self, kv, columns, domains, response, blobs=None):
        self.vec_size = int(kv["vec_size"])
        vocab = (blobs or {}).get("vocab.txt", b"").decode().splitlines()
        raw = (blobs or {}).get("vectors.bin", b"")
        self.vectors = np.frombuffer(raw, np.float32).reshape(
            len(vocab), self.vec_size) if vocab else np.zeros((0, 0))
        self.index = {w: i for i, w in enumerate(vocab)}
        self.nclasses = 1
        self.columns = list(columns)
        self.cat_domains = domains

    def transform(self, word: str) -> np.ndarray:
        i = self.index.get(word)
        return (self.vectors[i] if i is not None
                else np.full(self.vec_size, np.nan))

    def score(self, row: np.ndarray) -> np.ndarray:
        raise ValueError("word2vec MOJOs embed words (use .transform), "
                         "they do not score rows")


# ---------------- GLRM --------------------------------------------------

def export_mojo_glrm(model, path: str) -> str:
    """GLRM MOJO (hex/genmodel/algos/glrm/GlrmMojoWriter role):
    archetypes + scaling; scoring solves the row's X by proximal
    iterations like GlrmMojoModel.impute_data."""
    Y = np.asarray(model.archetypes_y, np.float64)
    # expansion layout (exp_names order): per raw column, either its
    # numeric slot or its dropped-first one-hot block
    layout = []
    pos = {n: i for i, n in enumerate(model.exp_names)}
    for n in model.feature_names:
        if n in model.cat_domains:
            dom = list(model.cat_domains[n])
            idxs = [pos.get(f"{n}.{lvl}", -1) for lvl in dom[1:]]
            layout.append(("cat", idxs))
        elif n in pos:
            layout.append(("num", [pos[n]]))
    import json as _json
    kv = [f"k = {Y.shape[0]}",
          f"ncolX = {Y.shape[1]}",
          f"exp_names = {','.join(model.exp_names)}",
          f"xm = {_jarr(model._xm)}",
          f"xs = {_jarr(model._xs)}"]
    cols = list(model.feature_names)
    ini, doms = _ini_header(model, "glrm", "GLRM",
                            "DimReduction", cols, "1.10", kv)
    return _write_zip(path, ini, doms,
                      {"archetypes.bin": Y.astype(np.float64).tobytes(),
                       "layout.json": _json.dumps(layout).encode()})


class GlrmMojoScorer:
    def __init__(self, kv, columns, domains, response, blobs=None):
        import json as _json
        self.k = int(kv["k"])
        ncol = int(kv["ncolX"])
        self.Y = np.frombuffer((blobs or {})["archetypes.bin"],
                               np.float64).reshape(self.k, ncol)
        self.xm = np.asarray(_parse_jarr(kv["xm"]))
        self.xs = np.asarray(_parse_jarr(kv["xs"]))
        lay = (blobs or {}).get("layout.json")
        self.layout = _json.loads(lay.decode()) if lay else             [("num", [i]) for i in range(ncol)]
        self.columns = list(columns)
        self.cat_domains = domains
        self.nclasses = 1

    def _expand(self, row: np.ndarray) -> np.ndarray:
        """Raw column-ordered row → expand_design space (dropped-first
        one-hot per categorical, numeric passthrough)."""
        out = np.zeros(self.Y.shape[1])
        for ci, (kind, idxs) in enumerate(self.layout):
            v = row[ci] if ci < len(row) else np.nan
            if kind == "num":
                if idxs[0] >= 0:
                    out[idxs[0]] = 0.0 if np.isnan(v) else v
            else:
                if not np.isnan(v):
                    code = int(v)
                    if 1 <= code <= len(idxs) and idxs[code - 1] >= 0:
                        out[idxs[code - 1]] = 1.0
        return out

    def score(self, row: np.ndarray) -> np.ndarray:
        """Returns the row's k archetype coefficients (X row) by ridge
        least squares against Y (GlrmMojoModel x-solve role)."""
        a = (self._expand(np.asarray(row, np.float64)) - self.xm) \
            / np.maximum(self.xs, 1e-12)
        a = np.nan_to_num(a)
        G = self.Y @ self.Y.T + 1e-6 * np.eye(self.k)
        return np.linalg.solve(G, self.Y @ a)


# ---------------- IsolationForest --------------------------------------

def export_mojo_isofor(model, path: str) -> str:
    """IsolationForest MOJO: the v1.40 compressed-tree format the tree
    writer already emits (hex/genmodel/algos/isofor/IsolationForest
    MojoModel reads trees + min/max path length)."""
    from h2o3_tpu import telemetry
    from h2o3_tpu.mojo import _compress_tree
    feat, thr, spl = (np.asarray(a) for a in telemetry.device_get(
        (model._feat, model._thr, model._is_split), pipeline="export"))
    T = feat.shape[0]
    nal = np.zeros_like(spl)
    M = feat.shape[1]
    # leaf value = node depth (complete-array index → depth): scoring
    # averages the reached leaves' depths into the path length
    dv = np.floor(np.log2(np.arange(M) + 1)).astype(np.float32)
    blobs = {}
    for t in range(T):
        data, aux = _compress_tree(feat[t], thr[t], nal[t], spl[t], dv)
        blobs[f"trees/t00_{t:03d}.bin"] = data
        blobs[f"trees/t00_{t:03d}_aux.bin"] = aux
    kv = [f"n_trees = {T}",
          "n_trees_per_class = 1",
          f"min_path_length = {int(getattr(model, 'min_path_length', 0))}",
          f"max_path_length = {int(getattr(model, 'max_path_length', 0))}"]
    cols = list(model.feature_names)
    ini, doms = _ini_header(model, "isofor", "Isolation Forest",
                            "AnomalyDetection", cols, "1.40", kv)
    return _write_zip(path, ini, doms, blobs)


# ---------------- GAM ---------------------------------------------------

def export_mojo_gam(model, path: str) -> str:
    """GAM MOJO (hex/genmodel/algos/gam/GamMojoWriter role): the inner
    GLM's coefficients + the spline config (knots per gam column) so a
    reader can re-expand and score."""
    import json as _json
    inner = model.inner
    beta, cat_off, means_list = _beta_glm_layout(inner)
    kv = [f"cat_offsets = {_jarr(cat_off)}",
          f"num_means = {_jarr(means_list)}",
          f"family = {inner.family}",
          f"link = family_default",
          f"gam_columns = {','.join(model.gam_columns)}",
          f"bs = {_jarr([int(model.bs_map.get(c) or 0) for c in model.gam_columns])}",
          f"beta = {_jarr(beta)}",
          f"intercept = {inner.intercept_value}",
          f"exp_names = {','.join(inner.exp_names)}"]
    cols = list(model.feature_names) + ([model.response]
                                        if model.response else [])
    ini, doms = _ini_header(model, "gam", "GAM",
                            ("Binomial" if model.nclasses == 2
                             else "Regression"), cols, "1.00", kv)
    knots_blob = _json.dumps({k: list(map(float, v))
                              for k, v in model.knots.items()}).encode()
    return _write_zip(path, ini, doms, {"knots.json": knots_blob})


# ---------------- StackedEnsemble --------------------------------------

def export_mojo_ensemble(model, path: str) -> str:
    """StackedEnsemble MOJO (hex/genmodel/algos/ensemble/
    StackedEnsembleMojoWriter role): base model MOJOs nested under
    models/ + the metalearner MOJO + the base-model order."""
    import os as _os
    import tempfile as _tmp
    from h2o3_tpu.mojo import export_mojo
    blobs = {}
    names = []
    with _tmp.TemporaryDirectory() as td:
        for i, bm in enumerate(model.base_models):
            p = _os.path.join(td, f"base_{i}.zip")
            export_mojo(bm, p)
            with open(p, "rb") as f:
                blobs[f"models/base_{i}.zip"] = f.read()
            names.append(f"base_{i}")
        mp = _os.path.join(td, "meta.zip")
        export_mojo(model.meta_model, mp)
        with open(mp, "rb") as f:
            blobs["models/metalearner.zip"] = f.read()
    kv = [f"base_models = {','.join(names)}",
          f"n_base_models = {len(names)}"]
    cols = list(model.feature_names) + ([model.response]
                                        if model.response else [])
    ini, doms = _ini_header(model, "ensemble", "StackedEnsemble",
                            ("Binomial" if model.nclasses == 2 else
                             "Multinomial" if model.nclasses > 2
                             else "Regression"), cols, "1.00", kv)
    return _write_zip(path, ini, doms, blobs)


# ---------------- PCA ---------------------------------------------------
# hex/genmodel/algos/pca/PCAMojoReader: eigenvector matrix + the same
# standardization block the kmeans reader carries; score = projection
# of the standardized (NA-imputed) row onto k components.

def export_mojo_pca(model, path: str) -> str:
    if len(model.exp_names) != len(model.feature_names):
        raise NotImplementedError(
            "PCA MOJO export requires a numeric-only design: this model "
            "trained on an expanded (categorical) design and the MOJO "
            "row format carries raw columns (export the scores frame, "
            "or one-hot the frame before training)")
    columns = list(model.feature_names)
    ev = np.asarray(model.eigvec, np.float64)          # [Fe, k]
    extra = [
        "standardize = true",
        f"pca_means = {_jarr(np.asarray(model.xm, np.float64).tolist())}",
        f"pca_mults = {_jarr((1.0 / np.maximum(np.asarray(model.xs, np.float64), 1e-12)).tolist())}",
        f"k = {ev.shape[1]}",
    ] + [f"eigvec_{j} = {_jarr(ev[:, j].tolist())}"
         for j in range(ev.shape[1])]
    ini, doms = _ini_header(model, "pca", "Principal Components Analysis",
                            "DimReduction", columns, "1.00", extra)
    return _write_zip(path, ini, doms)


class PcaMojoScorer:
    def __init__(self, kv: Dict[str, str], columns, domains, response):
        self.means = np.asarray(_parse_jarr(kv["pca_means"]))
        self.mults = np.asarray(_parse_jarr(kv["pca_mults"]))
        k = int(kv["k"])
        self.eigvec = np.stack(
            [np.asarray(_parse_jarr(kv[f"eigvec_{j}"]))
             for j in range(k)], axis=1)               # [Fe, k]
        self.nclasses = 1
        self.columns = columns

    def score(self, row: np.ndarray) -> np.ndarray:
        x = np.asarray(row, np.float64)
        x = np.where(np.isnan(x), self.means, x)
        xs = (x - self.means) * self.mults
        return xs @ self.eigvec


# ---------------- Isotonic ----------------------------------------------
# hex/genmodel/algos/isotonic/IsotonicRegressionMojoReader: threshold
# knots; score = piecewise-linear interpolation clamped to [min, max].

def export_mojo_isotonic(model, path: str) -> str:
    columns = list(model.feature_names) + [model.response]
    tx = np.asarray(model.thresholds_x, np.float64)
    ty = np.asarray(model.thresholds_y, np.float64)
    extra = [
        f"thresholds_x = {_jarr(tx.tolist())}",
        f"thresholds_y = {_jarr(ty.tolist())}",
        f"min_x = {tx.min()}", f"max_x = {tx.max()}",
    ]
    ini, doms = _ini_header(model, "isotonic", "Isotonic Regression",
                            "Regression", columns, "1.00", extra)
    return _write_zip(path, ini, doms)


class IsotonicMojoScorer:
    def __init__(self, kv: Dict[str, str], columns, domains, response):
        self.tx = np.asarray(_parse_jarr(kv["thresholds_x"]))
        self.ty = np.asarray(_parse_jarr(kv["thresholds_y"]))
        self.nclasses = 1
        self.columns = columns

    def score(self, row: np.ndarray) -> np.ndarray:
        x = float(np.asarray(row, np.float64)[0])
        if np.isnan(x):
            return np.array([np.nan])
        return np.array([float(np.interp(x, self.tx, self.ty))])


# ---------------- PSVM --------------------------------------------------
# hex/genmodel/algos/psvm/KernelSvmMojoReader: support vectors + alphas
# + rho; score = sum_i alpha_i*y_i*K(sv_i, x) + b with the Gaussian
# kernel. Both of this build's regimes serialize: mode=exact carries
# the SVs, mode=rff carries the factorized (W, phase, beta) triple.

def export_mojo_psvm(model, path: str) -> str:
    if len(model.exp_names) != len(model.feature_names):
        raise NotImplementedError(
            "PSVM MOJO export requires a numeric-only design: this "
            "model trained on an expanded (categorical) design and the "
            "MOJO row format carries raw columns")
    columns = list(model.feature_names) + [model.response]
    extra = [
        f"svm_b = {model.b}",
        f"svm_means = {_jarr(np.asarray(model._xm, np.float64).tolist())}",
        f"svm_stds = {_jarr(np.asarray(model._xs, np.float64).tolist())}",
    ]
    blobs: Dict[str, bytes] = {}
    if getattr(model, "alpha_y", None) is not None:
        extra += [f"svm_mode = exact", f"svm_gamma = {model.gamma}",
                  f"sv_count = {model.sv_X.shape[0]}"]
        blobs["svm/sv_x.bin"] = np.asarray(
            model.sv_X, "<f8").tobytes()
        blobs["svm/alpha_y.bin"] = np.asarray(
            model.alpha_y, "<f8").tobytes()
    else:
        extra += ["svm_mode = rff",
                  f"rff_rank = {model.W.shape[1] if model.W is not None else 0}"]
        if model.W is not None:
            blobs["svm/rff_w.bin"] = np.asarray(model.W, "<f8").tobytes()
            blobs["svm/rff_phase.bin"] = np.asarray(
                model.phase, "<f8").tobytes()
        blobs["svm/beta.bin"] = np.asarray(model.beta, "<f8").tobytes()
    ini, doms = _ini_header(model, "psvm", "Support Vector Machine",
                            "Binomial", columns, "1.00", extra)
    return _write_zip(path, ini, doms, blobs=blobs)


class PsvmMojoScorer:
    def __init__(self, kv: Dict[str, str], columns, domains, response,
                 blobs=None):
        self.b = float(kv["svm_b"])
        self.means = np.asarray(_parse_jarr(kv["svm_means"]))
        self.stds = np.asarray(_parse_jarr(kv["svm_stds"]))
        self.mode = kv.get("svm_mode", "exact")
        F = len(self.means)
        if self.mode == "exact":
            self.gamma = float(kv["svm_gamma"])
            n = int(kv["sv_count"])
            self.sv = np.frombuffer(
                blobs["svm/sv_x.bin"], "<f8").reshape(n, -1)
            self.ay = np.frombuffer(blobs["svm/alpha_y.bin"], "<f8")
        else:
            r = int(kv["rff_rank"])
            self.W = (np.frombuffer(blobs["svm/rff_w.bin"],
                                    "<f8").reshape(F, r) if r else None)
            self.phase = (np.frombuffer(blobs["svm/rff_phase.bin"],
                                        "<f8") if r else None)
            self.beta = np.frombuffer(blobs["svm/beta.bin"], "<f8")
        self.nclasses = 2
        self.columns = columns

    def score(self, row: np.ndarray) -> np.ndarray:
        x = np.asarray(row, np.float64)
        x = np.where(np.isnan(x), self.means, x)
        xs = (x - self.means) / self.stds
        if self.mode == "exact":
            d2 = ((self.sv - xs[None, :]) ** 2).sum(1)
            dec = float(np.exp(-self.gamma * d2) @ self.ay + self.b)
        elif self.W is not None:
            z = np.sqrt(2.0 / self.W.shape[1]) * np.cos(
                xs @ self.W + self.phase)
            dec = float(z @ self.beta + self.b)
        else:
            dec = float(xs @ self.beta + self.b)
        p1 = 1.0 / (1.0 + np.exp(-2.0 * dec))
        return np.array([1.0 if dec >= 0 else 0.0, 1.0 - p1, p1])


# ---------------- TargetEncoder -----------------------------------------
# hex/genmodel/algos/targetencoder/TargetEncoderMojoReader: per-column
# category->(numerator, denominator) tables + prior + blending knobs;
# scoring-time transform is te = blend(sum/cnt, prior) per level (NA and
# unseen levels fall back to the prior).

def export_mojo_targetencoder(model, path: str) -> str:
    columns = list(model.feature_names) + [model.response]
    p = model.params
    extra = [
        f"te_prior = {model.prior}",
        f"te_blending = {'true' if p.get('blending', True) else 'false'}",
        f"te_inflection_point = {float(p.get('inflection_point', 10.0))}",
        f"te_smoothing = {float(p.get('smoothing', 20.0))}",
        f"te_cols = {_jarr(list(model.encodings), quote=True)}",
    ]
    blobs: Dict[str, bytes] = {}
    for c, (s, n) in model.encodings.items():
        blobs[f"te/{c}_sum.bin"] = np.asarray(s, "<f8").tobytes()
        blobs[f"te/{c}_cnt.bin"] = np.asarray(n, "<f8").tobytes()
    ini, doms = _ini_header(model, "targetencoder", "TargetEncoder",
                            "TargetEncoder", columns, "1.00", extra)
    return _write_zip(path, ini, doms, blobs=blobs)


class TargetEncoderMojoScorer:
    """Transforms a row's categorical codes to their blended encodings
    (EasyPredict transformWithTargetEncoding analog)."""

    def __init__(self, kv: Dict[str, str], columns, domains, response,
                 blobs=None):
        self.prior = float(kv["te_prior"])
        self.blending = kv.get("te_blending", "true") == "true"
        self.infl = float(kv.get("te_inflection_point", 10.0))
        self.smooth = float(kv.get("te_smoothing", 20.0))
        # _parse_jarr JSON-decodes quoted arrays — no extra stripping,
        # which would corrupt names that genuinely contain quotes
        self.te_cols = _parse_jarr(kv["te_cols"], typ=str)
        self.tables = {}
        for c in self.te_cols:
            s = np.frombuffer(blobs[f"te/{c}_sum.bin"], "<f8")
            n = np.frombuffer(blobs[f"te/{c}_cnt.bin"], "<f8")
            self.tables[c] = (s, n)
        self.columns = columns
        self.nclasses = 1

    def encode(self, col: str, code: float) -> float:
        s, n = self.tables[col]
        if not (0 <= code < len(n)) or code != code:
            return self.prior
        i = int(code)
        cnt = n[i]
        if cnt <= 0:
            return self.prior
        est = s[i] / cnt
        if not self.blending:
            return float(est)
        lam = 1.0 / (1.0 + np.exp((self.infl - cnt) / self.smooth))
        return float(lam * est + (1.0 - lam) * self.prior)

    def score(self, row: np.ndarray) -> np.ndarray:
        if not hasattr(self, "_col_idx"):
            self._col_idx = [self.columns.index(c) for c in self.te_cols]
        return np.asarray([self.encode(c, float(row[idx]))
                           for c, idx in zip(self.te_cols, self._col_idx)])
