"""genmodel breadth: non-tree MOJO writers/readers, POJO codegen, and the
EasyPredict row API.

Reference wire formats (re-derived from the READERS, not copied):
- GLM MOJO 1.00 — hex/genmodel/algos/glm/GlmMojoReader.java kv set
  (use_all_factor_levels, cats, cat_modes, cat_offsets, nums, num_means,
  mean_imputation, beta, family, link) and GlmMojoModelBase.score0's beta
  layout: per-cat indicator blocks first (skipping level 0 when
  use_all_factor_levels=false), then numerics, intercept LAST; data rows
  arrive cats-first (DataInfo column reordering).
- KMeans MOJO 1.00 — algos/kmeans/KMeansMojoReader.java (standardize,
  standardize_means/mults/modes, center_num, center_i arrays).
- DeepLearning MOJO 1.10 — algos/deeplearning/DeeplearningMojoReader.java
  (nums/cats/cat_offsets/norm_mul/norm_sub/activation/
  neural_network_sizes, weight_layer{i}/bias_layer{i}).
- POJO codegen — hex/tree/TreeJCodeGen.java emits one Java class per
  model with nested if/else per tree; we emit the same *shape* of source
  (compile-checked only when a JDK exists; golden-file otherwise).
- EasyPredict row API — hex/genmodel/easy/EasyPredictModelWrapper.java
  (RowData dict → typed prediction).

Array kv values use Java's Arrays.toString format ("[a, b, c]"), the
format AbstractMojoWriter.writekv emits and ModelMojoReader parses.
"""
from __future__ import annotations

import uuid as _uuid
import zipfile
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _jarr(vals) -> str:
    return "[" + ", ".join(str(v) for v in vals) + "]"


def _parse_jarr(s: str, typ=float):
    s = s.strip()
    if s.startswith("["):
        s = s[1:-1]
    return [typ(v.strip()) for v in s.split(",") if v.strip()]


def _split_design(model):
    """Cats-first column reordering (DataInfo): returns (cat_idx,
    num_idx) into model.feature_names."""
    cat_idx = [i for i, c in enumerate(model.feature_is_cat) if c]
    num_idx = [i for i, c in enumerate(model.feature_is_cat) if not c]
    return cat_idx, num_idx


def _beta_glm_layout(model) -> Tuple[np.ndarray, List[int], List[float]]:
    """Map our expand_design-ordered beta (original column order, enum
    blocks inline) to the genmodel layout: cat blocks first, then nums,
    intercept last. Returns (beta, cat_offsets, num_means)."""
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    # index our exp_names: cat level j of col n is "n.<lvl>"; numeric is n
    pos = {n: i for i, n in enumerate(model.exp_names)}
    beta_src = np.asarray(model.beta, dtype=np.float64)
    out: List[float] = []
    cat_offsets = [0]
    for ci in cat_idx:
        n = names[ci]
        dom = list(model.cat_domains.get(n, ()))
        for lvl in dom[1:]:                     # level 0 skipped
            key = f"{n}.{lvl}"
            out.append(float(beta_src[pos[key]]) if key in pos else 0.0)
        cat_offsets.append(cat_offsets[-1] + max(len(dom) - 1, 0))
    num_means = []
    for ni in num_idx:
        n = names[ni]
        out.append(float(beta_src[pos[n]]))
        num_means.append(float(model.impute_means.get(n, 0.0)))
    out.append(float(model.intercept_value))
    return np.asarray(out), cat_offsets, num_means


def _ini_header(model, algo: str, algorithm: str, category: str,
                columns: List[str], mojo_version: str,
                extra_kv: List[str]) -> Tuple[str, List[Tuple[str, List[str]]]]:
    n_features = len(columns) - (1 if model.response else 0)
    ini = ["[info]",
           "h2o_version = 3.46.0.1",
           f"mojo_version = {mojo_version}",
           "license = Apache License Version 2.0",
           f"algo = {algo}",
           f"algorithm = {algorithm}",
           f"category = {category}",
           f"uuid = {int(_uuid.uuid4()) % (1 << 63)}",
           f"supervised = {'true' if model.response else 'false'}",
           f"n_features = {n_features}",
           f"n_classes = {max(model.nclasses, 1)}",
           f"n_columns = {len(columns)}",
           "balance_classes = false",
           "default_threshold = 0.5",
           "prior_class_distrib = null",
           "model_class_distrib = null",
           "timestamp = 2026-01-01 00:00:00",
           "escape_domain_values = false",
           "_genmodel_encoding = AUTO",
           ] + extra_kv
    dom_lines = ["", "[columns]"] + columns + ["", "[domains]"]
    dom_files: List[Tuple[str, List[str]]] = []
    di = 0
    for ci, name in enumerate(columns):
        dom = None
        if name == model.response and model.response_domain:
            dom = list(model.response_domain)
        elif name in model.cat_domains:
            dom = list(model.cat_domains[name])
        if dom:
            fn = f"d{di:03d}.txt"
            dom_lines.append(f"{ci}: {len(dom)} {fn}")
            dom_files.append((fn, dom))
            di += 1
    return "\n".join(ini + dom_lines) + "\n", dom_files


def _write_zip(path: str, ini_text: str,
               dom_files: List[Tuple[str, List[str]]],
               blobs: Optional[Dict[str, bytes]] = None) -> str:
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("model.ini", ini_text)
        for fn, dom in dom_files:
            zf.writestr(f"domains/{fn}",
                        "\n".join(str(d) for d in dom) + "\n")
        for name, data in (blobs or {}).items():
            zf.writestr(name, data)
    return path


# ---------------- GLM ---------------------------------------------------

def export_mojo_glm(model, path: str) -> str:
    if model.family == "multinomial":
        raise ValueError("multinomial GLM MOJO export not supported yet")
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    beta, cat_offsets, num_means = _beta_glm_layout(model)
    cat_modes = [0] * len(cat_idx)
    columns = ([names[i] for i in cat_idx] + [names[i] for i in num_idx]
               + ([model.response] if model.response else []))
    link = {"gaussian": "identity", "binomial": "logit", "poisson": "log",
            "gamma": "log"}[model.family]
    extra = [
        "use_all_factor_levels = false",
        f"cats = {len(cat_idx)}",
        f"cat_modes = {_jarr(cat_modes)}",
        f"cat_offsets = {_jarr(cat_offsets)}",
        f"nums = {len(num_idx)}",
        f"num_means = {_jarr(num_means)}",
        "mean_imputation = true",
        f"beta = {_jarr(beta.tolist())}",
        f"family = {model.family}",
        f"link = {link}",
        "tweedie_link_power = 0.0",
    ]
    ini, doms = _ini_header(model, "glm", "Generalized Linear Model",
                            "Binomial" if model.nclasses == 2
                            else "Regression", columns, "1.00", extra)
    return _write_zip(path, ini, doms)


class GlmMojoScorer:
    """Standalone scorer for a GLM MOJO (GlmMojoModel.glmScore0)."""

    def __init__(self, kv: Dict[str, str], columns, domains, response):
        self.cats = int(kv["cats"])
        self.nums = int(kv["nums"])
        self.cat_offsets = _parse_jarr(kv["cat_offsets"], int)
        self.cat_modes = _parse_jarr(kv.get("cat_modes", "[]"), int)
        self.num_means = _parse_jarr(kv.get("num_means", "[]"), float)
        self.beta = np.asarray(_parse_jarr(kv["beta"], float))
        self.family = kv["family"]
        self.link = kv.get("link", "identity")
        self.columns = columns
        self.domains = domains
        self.response = response
        self.nclasses = 2 if self.family == "binomial" else 1

    def score(self, row: np.ndarray) -> np.ndarray:
        data = np.asarray(row, dtype=np.float64).copy()
        for i in range(self.cats):
            if np.isnan(data[i]):
                data[i] = self.cat_modes[i]
        for i in range(self.nums):
            if np.isnan(data[self.cats + i]):
                data[self.cats + i] = self.num_means[i]
        eta = 0.0
        for i in range(self.cats):
            code = int(data[i])
            if code != 0:               # level 0 skipped
                ival = self.cat_offsets[i] + code - 1
                if ival < self.cat_offsets[i + 1]:
                    eta += self.beta[ival]
        noff = self.cat_offsets[self.cats] if self.cats else 0
        for i in range(self.nums):
            eta += self.beta[noff + i] * data[self.cats + i]
        eta += self.beta[-1]
        mu = {"identity": lambda e: e,
              "logit": lambda e: 1.0 / (1.0 + np.exp(-e)),
              "log": np.exp}[self.link](eta)
        if self.family == "binomial":
            return np.array([float(mu > 0.5), 1.0 - mu, mu])
        return np.array([mu])


# ---------------- KMeans ------------------------------------------------

def export_mojo_kmeans(model, path: str) -> str:
    # our KMeans trains on the expanded standardized design; centers_raw
    # are in expanded-column space (exp_names)
    columns = list(model.feature_names)
    centers = np.asarray(model.centers_raw, dtype=np.float64)
    means = np.asarray(model.xm, dtype=np.float64)
    mults = 1.0 / np.maximum(np.asarray(model.xs, dtype=np.float64), 1e-12)
    extra = [
        "standardize = true",
        f"standardize_means = {_jarr(means.tolist())}",
        f"standardize_mults = {_jarr(mults.tolist())}",
        f"standardize_modes = {_jarr([0] * len(means))}",
        f"center_num = {centers.shape[0]}",
    ]
    extra += [f"center_{i} = {_jarr(c.tolist())}"
              for i, c in enumerate(centers)]
    ini, doms = _ini_header(model, "kmeans", "K-means", "Clustering",
                            columns, "1.00", extra)
    return _write_zip(path, ini, doms)


class KMeansMojoScorer:
    def __init__(self, kv: Dict[str, str], columns, domains, response):
        self.standardize = kv.get("standardize", "true") == "true"
        self.means = np.asarray(_parse_jarr(kv["standardize_means"]))
        self.mults = np.asarray(_parse_jarr(kv["standardize_mults"]))
        n = int(kv["center_num"])
        self.centers = np.stack([
            np.asarray(_parse_jarr(kv[f"center_{i}"])) for i in range(n)])
        self.nclasses = 1
        self.columns = columns

    def score(self, row: np.ndarray) -> np.ndarray:
        x = np.asarray(row, dtype=np.float64)
        x = np.where(np.isnan(x), self.means, x)
        xs = (x - self.means) * self.mults if self.standardize else x
        cs = (self.centers - self.means[None, :]) * self.mults[None, :] \
            if self.standardize else self.centers
        d = ((cs - xs[None, :]) ** 2).sum(1)
        return np.array([float(np.argmin(d))])


# ---------------- DeepLearning -----------------------------------------

def export_mojo_deeplearning(model, path: str) -> str:
    """MLP MOJO (mojo 1.10 kv set). Our net: list of (W [in, out], b)
    float32; genmodel stores row-major [out*in] weight blobs per layer."""
    if model.task == "autoencoder":
        raise ValueError("autoencoder MOJO export not supported")
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    columns = ([names[i] for i in cat_idx] + [names[i] for i in num_idx]
               + ([model.response] if model.response else []))
    # expanded design is standardized over ALL expanded cols; genmodel
    # normalizes only numerics (norm_sub/mul over nums) — we export the
    # expanded-space stats and mark all expanded cols numeric-like via
    # cat_offsets on the ORIGINAL enum blocks
    pos = {n: i for i, n in enumerate(model.exp_names)}
    cat_offsets = [0]
    perm: List[int] = []
    for ci in cat_idx:
        n = names[ci]
        dom = list(model.cat_domains.get(n, ()))
        block = [pos[f"{n}.{lvl}"] for lvl in dom[1:] if f"{n}.{lvl}" in pos]
        perm.extend(block)
        cat_offsets.append(cat_offsets[-1] + len(block))
    num_perm = [pos[names[ni]] for ni in num_idx]
    perm_all = perm + num_perm
    xm = np.asarray(model.xm, dtype=np.float64)
    xs = np.asarray(model.xs, dtype=np.float64)
    units = [len(perm_all)] + list(model.hidden) + [
        model.nclasses if model.nclasses > 1 else 1]
    act_map = {"rectifier": "Rectifier", "tanh": "Tanh", "maxout": "Maxout"}
    extra = [
        "mini_batch_size = 1",
        f"nums = {len(num_idx)}",
        f"cats = {len(cat_idx)}",
        f"cat_offsets = {_jarr(cat_offsets)}",
        f"norm_mul = {_jarr((1.0 / np.maximum(xs[perm_all], 1e-12)).tolist())}",
        f"norm_sub = {_jarr(xm[perm_all].tolist())}",
        "norm_resp_mul = null",
        "norm_resp_sub = null",
        "use_all_factor_levels = false",
        f"activation = {act_map.get(model.activation, 'Rectifier')}",
        f"distribution = {model.dist_name}",
        "mean_imputation = true",
        f"cat_modes = {_jarr([0] * len(cat_idx))}",
        f"neural_network_sizes = {_jarr(units)}",
        f"hidden_dropout_ratios = {_jarr([0.0] * len(model.hidden))}",
    ]
    # weights: reorder input layer rows by perm_all (original exp order →
    # cats-first order); genmodel blob is row-major [out, in]
    for li, layer in enumerate(model.net):
        Wn = np.asarray(layer["W"], dtype=np.float64)
        b = np.asarray(layer["b"], dtype=np.float64).reshape(-1)
        if li == 0:
            Wn = Wn[np.asarray(perm_all)]
        extra.append(f"weight_layer{li} = {_jarr(Wn.T.reshape(-1).tolist())}")
        extra.append(f"bias_layer{li} = {_jarr(b.tolist())}")
    ini, doms = _ini_header(
        model, "deeplearning", "Deep Learning", "Binomial"
        if model.nclasses == 2 else "Multinomial" if model.nclasses > 2
        else "Regression", columns, "1.10", extra)
    return _write_zip(path, ini, doms)


class DeepLearningMojoScorer:
    def __init__(self, kv: Dict[str, str], columns, domains, response):
        self.cats = int(kv["cats"])
        self.nums = int(kv["nums"])
        self.cat_offsets = _parse_jarr(kv["cat_offsets"], int)
        self.norm_mul = np.asarray(_parse_jarr(kv["norm_mul"]))
        self.norm_sub = np.asarray(_parse_jarr(kv["norm_sub"]))
        self.units = _parse_jarr(kv["neural_network_sizes"], int)
        self.activation = kv["activation"]
        self.distribution = kv.get("distribution", "gaussian")
        self.layers = []
        for li in range(len(self.units) - 1):
            w = np.asarray(_parse_jarr(kv[f"weight_layer{li}"]))
            b = np.asarray(_parse_jarr(kv[f"bias_layer{li}"]))
            self.layers.append(
                (w.reshape(self.units[li + 1], self.units[li]), b))
        self.columns = columns
        self.domains = domains
        k = self.units[-1]
        self.nclasses = k if k > 1 else 1

    def score(self, row: np.ndarray) -> np.ndarray:
        data = np.asarray(row, dtype=np.float64)
        vec = np.zeros(self.units[0])
        for i in range(self.cats):
            code = int(data[i]) if np.isfinite(data[i]) else 0
            if code != 0:
                ival = self.cat_offsets[i] + code - 1
                if ival < self.cat_offsets[i + 1]:
                    vec[ival] = 1.0
        noff = self.cat_offsets[self.cats] if self.cats else 0
        for i in range(self.nums):
            v = data[self.cats + i]
            vec[noff + i] = 0.0 if not np.isfinite(v) else v
        vec = (vec - self.norm_sub) * self.norm_mul
        h = vec
        for li, (W, b) in enumerate(self.layers):
            h = W @ h + b
            if li < len(self.layers) - 1:
                if self.activation == "Tanh":
                    h = np.tanh(h)
                else:
                    h = np.maximum(h, 0.0)
        if self.nclasses > 1:
            e = np.exp(h - h.max())
            p = e / e.sum()
            return np.concatenate([[float(np.argmax(p))], p])
        if self.distribution == "bernoulli":
            p1 = 1.0 / (1.0 + np.exp(-h[0]))
            return np.array([float(p1 > 0.5), 1 - p1, p1])
        return np.array([h[0]])


# ---------------- POJO codegen (TreeJCodeGen analog) --------------------

def pojo_source(model, class_name: Optional[str] = None) -> str:
    """Emit Java source scoring a GBM/DRF model — the
    hex/tree/TreeJCodeGen.java role: one static method per tree with the
    nested if/else descent, a score0 summing them. Compiles against
    h2o-genmodel's GenModel when a JDK is present; golden-file checked
    otherwise."""
    import jax
    algo = model.algo
    cls = class_name or f"{algo}_pojo_{abs(hash(model.key)) % 10 ** 8}"
    feat = np.asarray(jax.device_get(model._feat))
    thr = np.asarray(jax.device_get(model._thr))
    nal = np.asarray(jax.device_get(model._na_left))
    spl = np.asarray(jax.device_get(model._is_split))
    val = np.asarray(jax.device_get(model._value))
    K = model.nclasses if model.nclasses > 2 else 1
    T = model.ntrees_built
    names = list(model.feature_names)

    def emit_node(t, m, indent) -> List[str]:
        pad = "  " * indent
        if not spl[t, m]:
            return [f"{pad}return {val[t, m]!r}f;"]
        f = int(feat[t, m])
        cond = f"Double.isNaN(data[{f}]) ? {str(bool(nal[t, m])).lower()}" \
               f" : data[{f}] < {thr[t, m]!r}f"
        out = [f"{pad}if ({cond}) {{"]
        out += emit_node(t, 2 * m + 1, indent + 1)
        out += [f"{pad}}} else {{"]
        out += emit_node(t, 2 * m + 2, indent + 1)
        out += [f"{pad}}}"]
        return out

    lines = [
        "// Auto-generated POJO scorer (hex/tree/TreeJCodeGen shape);",
        "// score0 contract matches hex/genmodel/GenModel.score0.",
        f"public class {cls} {{",
        f"  public static final String[] NAMES = {{"
        + ", ".join(f'"{n}"' for n in names) + "};",
        f"  public static final int NTREES = {T};",
        f"  public static final int NCLASSES = {max(model.nclasses, 1)};",
    ]
    for t in range(T * K):
        lines.append(f"  static float tree_{t}(double[] data) {{")
        lines += emit_node(t, 0, 2)
        lines.append("  }")
    if K == 1:
        f0 = float(np.asarray(model.f0).reshape(-1)[0]) \
            if model.algo == "gbm" else 0.0
        lines += [
            "  public static double[] score0(double[] data, double[] preds) {",
            f"    double f = {f0!r};",
            f"    for (int t = 0; t < {T}; t++) f += scoreTree(t, data);",
        ]
        if model.nclasses == 2:
            lines += [
                "    double p1 = 1.0 / (1.0 + Math.exp(-f));",
                "    preds[0] = p1 > 0.5 ? 1 : 0; preds[1] = 1 - p1; "
                "preds[2] = p1;",
            ]
        else:
            lines += ["    preds[0] = f;"]
        lines += ["    return preds;", "  }"]
    else:
        lines += [
            "  public static double[] score0(double[] data, double[] preds) {",
            f"    double[] margin = new double[{K}];",
            f"    for (int t = 0; t < {T}; t++)",
            f"      for (int k = 0; k < {K}; k++)",
            f"        margin[k] += scoreTree(t * {K} + k, data);",
            "    double max = Double.NEGATIVE_INFINITY, sum = 0;",
            f"    for (int k = 0; k < {K}; k++) max = Math.max(max, margin[k]);",
            f"    for (int k = 0; k < {K}; k++) {{ "
            "preds[k + 1] = Math.exp(margin[k] - max); sum += preds[k + 1]; }",
            f"    for (int k = 0; k < {K}; k++) preds[k + 1] /= sum;",
            "    preds[0] = 0;",
            "    return preds;",
            "  }",
        ]
    # dispatch table (javac rejects methods > 64KB; per-tree methods keep
    # each unit small — the same reason TreeJCodeGen splits classes)
    lines.append("  static float scoreTree(int t, double[] data) {")
    lines.append("    switch (t) {")
    for t in range(T * K):
        lines.append(f"      case {t}: return tree_{t}(data);")
    lines.append("      default: throw new IllegalArgumentException();")
    lines.append("    }")
    lines.append("  }")
    lines.append("}")
    return "\n".join(lines) + "\n"


def pojo_source_glm(model, class_name: Optional[str] = None) -> str:
    """GLM POJO (water/util/JCodeGen + GLM's POJO emit): the cats-first
    beta layout from the MOJO writer, scored with the same skip-level-0
    indicator logic as GlmMojoModel.glmScore0."""
    if model.family not in ("gaussian", "binomial", "poisson", "gamma"):
        raise ValueError(
            f"GLM POJO export supports gaussian/binomial/poisson/gamma "
            f"(got family='{model.family}')")
    cls = class_name or f"glm_pojo_{abs(hash(model.key)) % 10 ** 8}"
    beta, cat_offsets, num_means = _beta_glm_layout(model)
    cat_idx, num_idx = _split_design(model)
    names = model.feature_names
    columns = [names[i] for i in cat_idx] + [names[i] for i in num_idx]
    link = {"gaussian": "eta", "binomial": "1.0 / (1.0 + Math.exp(-eta))",
            "poisson": "Math.exp(eta)", "gamma": "Math.exp(eta)"}[
                model.family]
    lines = [
        "// Auto-generated GLM POJO (water/util/JCodeGen shape);",
        "// beta layout matches GlmMojoModelBase (cats first, intercept",
        "// last, level 0 of each factor dropped).",
        f"public class {cls} {{",
        "  public static final String[] NAMES = {"
        + ", ".join(f'"{n}"' for n in columns) + "};",
        "  public static final double[] BETA = {"
        + ", ".join(repr(float(v)) for v in beta) + "};",
        "  public static final int[] CAT_OFFSETS = {"
        + ", ".join(str(v) for v in cat_offsets) + "};",
        "  public static final double[] NUM_MEANS = {"
        + ", ".join(repr(float(v)) for v in num_means) + "};",
        f"  public static final int CATS = {len(cat_idx)};",
        f"  public static final int NUMS = {len(num_idx)};",
        "  public static double[] score0(double[] data, double[] preds) {",
        "    double eta = 0.0;",
        "    for (int i = 0; i < CATS; i++) {",
        "      int code = Double.isNaN(data[i]) ? 0 : (int) data[i];",
        "      if (code != 0) {",
        "        int ival = CAT_OFFSETS[i] + code - 1;",
        "        if (ival < CAT_OFFSETS[i + 1]) eta += BETA[ival];",
        "      }",
        "    }",
        "    int noff = CATS > 0 ? CAT_OFFSETS[CATS] : 0;",
        "    for (int i = 0; i < NUMS; i++) {",
        "      double v = data[CATS + i];",
        "      if (Double.isNaN(v)) v = NUM_MEANS[i];",
        "      eta += BETA[noff + i] * v;",
        "    }",
        "    eta += BETA[BETA.length - 1];",
        f"    double mu = {link};",
    ]
    if model.nclasses == 2:
        lines += ["    preds[0] = mu > 0.5 ? 1 : 0;",
                  "    preds[1] = 1.0 - mu; preds[2] = mu;"]
    else:
        lines += ["    preds[0] = mu;"]
    lines += ["    return preds;", "  }", "}"]
    return "\n".join(lines) + "\n"


def export_pojo(model, path: str, class_name: Optional[str] = None) -> str:
    if getattr(model, "algo", "") == "glm":
        src = pojo_source_glm(model, class_name)
    else:
        src = pojo_source(model, class_name)
    with open(path, "w") as f:
        f.write(src)
    return path


# ---------------- EasyPredict row API ----------------------------------

class EasyPredictModelWrapper:
    """Row-dict scoring over any of our models OR a loaded MOJO scorer —
    hex/genmodel/easy/EasyPredictModelWrapper.java's RowData contract:
    values may be numbers or category LABELS; unknown categoricals map
    to NA; missing columns are NA."""

    def __init__(self, model):
        self.model = model
        self.columns = list(getattr(model, "feature_names", None)
                            or getattr(model, "columns", []))
        self.cat_domains = dict(getattr(model, "cat_domains", {}) or {})
        self.response_domain = list(
            getattr(model, "response_domain", None) or [])

    def _row_to_array(self, row: Dict[str, Any]) -> np.ndarray:
        out = np.full(len(self.columns), np.nan)
        for i, c in enumerate(self.columns):
            if c not in row or row[c] is None:
                continue
            v = row[c]
            dom = self.cat_domains.get(c)
            if dom:
                if isinstance(v, str):
                    try:
                        out[i] = list(dom).index(v)
                    except ValueError:
                        out[i] = np.nan       # unseen level → NA
                else:
                    out[i] = float(v)
            else:
                out[i] = float(v)
        return out

    def predict_row(self, row: Dict[str, Any]) -> Dict[str, Any]:
        arr = self._row_to_array(row)
        m = self.model
        if hasattr(m, "score") and not hasattr(m, "_predict_matrix"):
            preds = np.asarray(m.score(arr))
        else:
            import jax.numpy as jnp
            out = np.asarray(m._predict_matrix(jnp.asarray(arr[None, :])))[0]
            if m.nclasses >= 2:
                preds = np.concatenate([[float(np.argmax(out))], out])
            else:
                preds = np.asarray([float(out)]).reshape(-1)
        nclasses = getattr(m, "nclasses", 1)
        if nclasses >= 2:
            label_idx = int(preds[0])
            label = (self.response_domain[label_idx]
                     if self.response_domain else str(label_idx))
            probs = {(self.response_domain[k] if self.response_domain
                      else str(k)): float(p)
                     for k, p in enumerate(preds[1:])}
            return {"label": label, "classProbabilities": probs}
        return {"value": float(preds[0])}
