"""Job — async work units with progress/cancel, polled by clients.

Reference: water/Job.java:24 — DKV-stored job objects with _work/_worked
progress, JobStatus, cancellation, exceptions, polled via GET /3/Jobs.
Here: a host-side registry of Job objects; training runs on a worker
thread so REST/interactive polling stays responsive (device work is
dispatched asynchronously by JAX anyway).

Long-running servers churn through thousands of jobs (every parse,
train, predict and micro-batch admin call makes one), so the registry
evicts terminal jobs beyond a bounded tail (H2O3_JOBS_KEEP, default
512) — the water/Job analog stores jobs in the DKV where the cleaner
eventually reclaims them; here eviction rides on registration.

Supervision (the SURVEY L1/L2 heartbeat analog, single-process): every
progress update is a heartbeat; a lazily-started watchdog thread marks
RUNNING jobs with no heartbeat for ``stall_timeout_secs`` as STALLED
(visible on /3/Jobs and the ``h2o3_jobs_stalled`` gauge) and enforces
``max_runtime_secs`` by requesting cancellation — the loops that poll
``cancel_requested`` (tree chunks, streamed level passes, CV folds)
then exit cooperatively. Failures carry STRUCTURED info (exception
class + message + the failed pipeline stage from the innermost open
span) alongside the raw traceback, so clients don't parse stack text.
"""
from __future__ import annotations

import os
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, Optional

RUNNING = "RUNNING"
# a boot-time restart-recovery resume (h2o3_tpu.recovery): semantically
# RUNNING — supervised by the watchdog, pollable on /3/Jobs — but
# distinguishable so clients can tell a recovered train from a fresh one
RECOVERING = "RECOVERING"
# waiting in the training scheduler's run queue (h2o3_tpu.sched): not
# yet dispatched, so the watchdog does not supervise it (max_runtime
# and stall detection count RUN time, not queue wait) and the registry
# never evicts it
QUEUED = "QUEUED"
DONE = "DONE"
FAILED = "FAILED"
CANCELLED = "CANCELLED"

_TERMINAL = (DONE, FAILED, CANCELLED)
_ACTIVE = (RUNNING, RECOVERING)

_REGISTRY: Dict[str, "Job"] = {}
_LOCK = threading.Lock()


def _bb(job: "Job", state: str, reason: str = "") -> None:
    """Flight-recorder append (ISSUE 19): job lifecycle transitions in
    the blackbox ring, keyed by job key + trace id so the cluster
    timeline threads one train across replicas. Advisory."""
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.record("job_state", member=job.key,
                        payload=f"{state} {reason}".strip()[:144],
                        trace_id=job.trace_id)
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass


class JobCancelled(Exception):
    """Raised inside cooperative cancellation points (streamed level
    passes) to unwind a cancelled job's work loop cleanly."""


class JobPreempted(JobCancelled):
    """Raised by a checkpointable train loop after it committed a
    resumable in-training checkpoint in response to ``Job.preempt()``
    (h2o3_tpu.sched checkpoint-based preemption). The scheduler catches
    the unwind and REQUEUES the entry — the job is not terminal and its
    waiters are not released."""


def _jobs_keep() -> int:
    try:
        return int(os.environ.get("H2O3_JOBS_KEEP", "512") or 512)
    except ValueError:
        return 512


def _stall_default() -> float:
    """Default heartbeat-stall threshold in seconds; 0 disables stall
    detection (the default — opt in via H2O3_JOB_STALL_SECS)."""
    try:
        return float(os.environ.get("H2O3_JOB_STALL_SECS", "0") or 0)
    except ValueError:
        return 0.0


def _evict_terminal_locked(keep: int) -> None:
    """Drop the OLDEST terminal jobs beyond ``keep`` (insertion order —
    dicts preserve it). Running jobs are never evicted regardless of
    age: a poller must always be able to find its live job."""
    terminal = [k for k, j in _REGISTRY.items() if j.status in _TERMINAL]
    for k in terminal[: max(len(terminal) - keep, 0)]:
        del _REGISTRY[k]


# ---------------- watchdog --------------------------------------------
#
# One daemon thread per process, started lazily the first time a job
# that needs supervision (max_runtime_secs or stall detection) is
# registered — test suites that never opt in never grow a thread.

_WATCHDOG: Optional[threading.Thread] = None


def _watch_tick() -> float:
    try:
        return max(float(os.environ.get("H2O3_JOB_WATCH_TICK", "1.0")
                         or 1.0), 0.01)
    except ValueError:
        return 1.0


def _ensure_watchdog() -> None:
    global _WATCHDOG
    with _LOCK:
        if _WATCHDOG is not None and _WATCHDOG.is_alive():
            return
        _WATCHDOG = threading.Thread(target=_watch_loop, daemon=True,
                                     name="job-watchdog")
        _WATCHDOG.start()


def _watch_loop() -> None:
    from h2o3_tpu import telemetry
    from h2o3_tpu.log import warn
    stalled_gauge = telemetry.gauge(
        "h2o3_jobs_stalled", help="RUNNING jobs with no recent progress "
        "heartbeat")
    timeout_ctr = telemetry.counter(
        "h2o3_jobs_runtime_exceeded_total",
        help="jobs cancelled for exceeding max_runtime_secs")
    while True:
        time.sleep(_watch_tick())
        # monotonic supervision clocks: max_runtime/stall are DURATIONS —
        # an NTP step on the wall clock must not cancel a healthy train
        # or mark every job stalled (h2o3-lint: monotonic-durations)
        now = time.monotonic()
        n_stalled = 0
        for j in list_jobs():
            # undispatched = waiting in the scheduler queue (a recovery
            # resume keeps its RECOVERING badge there): no worker is
            # running it, so neither budget applies yet
            if j.status not in _ACTIVE or not j._dispatched:
                continue
            if (j.max_runtime_secs and not j.cancel_requested
                    and j.run_seconds() > j.max_runtime_secs):
                warn("job %s exceeded max_runtime_secs=%.1f — cancelling",
                     j.key, j.max_runtime_secs)
                timeout_ctr.inc()
                j.cancel(reason=f"max_runtime_secs="
                                f"{j.max_runtime_secs:g} exceeded")
            stall = j.stall_timeout_secs
            if stall and now - j.last_progress_mono > stall:
                # the stall flag is part of the _mutex-guarded progress
                # protocol (update()/set_progress() clear it under the
                # lock) — writing it bare here raced a concurrent
                # heartbeat and could leave a progressing job marked
                # stalled (caught by h2o3-lint's lock-discipline rule)
                with j._mutex:
                    fresh = now - j.last_progress_mono <= stall
                    if not fresh and not j.stalled:
                        j.stalled = True
                        warn("job %s stalled: no progress for %.1fs "
                             "(threshold %.1fs)", j.key,
                             now - j.last_progress_mono, stall)
                if not fresh:
                    n_stalled += 1
            elif j.stalled:
                with j._mutex:
                    j.stalled = False      # heartbeat resumed
        stalled_gauge.set(n_stalled)


class Job:
    def __init__(self, description: str, work: float = 1.0,
                 key: Optional[str] = None,
                 max_runtime_secs: float = 0.0,
                 stall_timeout_secs: Optional[float] = None):
        self.key = key or f"$job_{uuid.uuid4().hex[:12]}"
        self.description = description
        self.status = RUNNING
        self._work = float(work)
        self._worked = 0.0
        self.start_time = time.time()          # reported epoch (/3/Jobs)
        self.start_mono = time.monotonic()     # duration/deadline math
        self.end_time: Optional[float] = None
        self._end_mono: Optional[float] = None
        self.exception: Optional[str] = None
        # structured failure info (/3/Jobs): class + message + pipeline
        # stage, so clients don't have to parse the traceback string
        self.exception_type: Optional[str] = None
        self.exception_msg: Optional[str] = None
        self.exception_obj: Optional[BaseException] = None
        self.failed_stage: Optional[str] = None
        self.result: Any = None
        self._cancel_requested = False
        self.cancel_reason: Optional[str] = None
        # checkpoint-based preemption (h2o3_tpu.sched): a SEPARATE flag
        # from cancellation — cancel is user intent (terminal), preempt
        # is a scheduler request to yield at the next checkpointable
        # commit and be requeued. Loops poll both.
        self._preempt_requested = False
        self.preempt_reason: Optional[str] = None
        self.preempt_count = 0           # completed preempt/resume cycles
        self.queue_wait_s: Optional[float] = None
        # run time accumulated over COMPLETED run segments (preempt/
        # resume cycles); the current segment is measured off start_mono
        self._run_accum_s = 0.0
        # False only while waiting in the scheduler queue: the watchdog
        # must not supervise (stall/max_runtime) a job that has no
        # worker yet — queue wait is not run time
        self._dispatched = True
        self._thread: Optional[threading.Thread] = None
        # terminal-state latch: join() on a scheduler-run job (no owned
        # thread) waits on this instead of a Thread handle; preemption
        # requeues WITHOUT setting it, so waiters sleep through the
        # whole preempt/resume cycle
        self._done_evt = threading.Event()
        # trace propagation (ISSUE 8): capture the creating thread's
        # bound trace id (the REST handler set it from the traceparent
        # header) — or mint one — so a background build's spans and the
        # /3/Jobs entry link back to the request that started it
        from h2o3_tpu.telemetry import trace as _trace
        self.trace_id: str = _trace.current_trace_id() or \
            _trace.new_trace_id()
        # supervision state: every progress write is a heartbeat
        self.max_runtime_secs = float(max_runtime_secs or 0.0)
        self.stall_timeout_secs = (_stall_default()
                                   if stall_timeout_secs is None
                                   else float(stall_timeout_secs))
        self.last_progress_mono = self.start_mono
        self.stalled = False
        # per-job mutex: _worked is read by REST pollers and bumped by
        # the worker thread (often from several CV/fold threads at
        # once) — `self._worked += w` is a read-modify-write that loses
        # updates without it (water/Job.update is an AtomicLong add)
        self._mutex = threading.Lock()
        with _LOCK:
            _REGISTRY[self.key] = self
            _evict_terminal_locked(_jobs_keep())
        if self.max_runtime_secs or self.stall_timeout_secs:
            _ensure_watchdog()

    # -- progress -------------------------------------------------------
    @property
    def progress(self) -> float:
        with self._mutex:
            if self.status in (DONE,):
                return 1.0
            return min(self._worked / self._work, 1.0) if self._work else 0.0

    def update(self, worked: float):
        with self._mutex:
            self._worked += worked
            self.last_progress_mono = time.monotonic()
            self.stalled = False       # any progress IS the heartbeat

    def set_progress(self, frac: float):
        with self._mutex:
            self._worked = frac * self._work
            self.last_progress_mono = time.monotonic()
            self.stalled = False

    # -- lifecycle ------------------------------------------------------
    def _record_failure(self, exc: BaseException) -> None:
        self.exception = traceback.format_exc()
        self.exception_type = type(exc).__name__
        self.exception_msg = str(exc)
        # the live exception object: foreground train() re-raises
        # parameter-validation errors TYPED (ValueError stays ValueError
        # through the scheduler hop) instead of join()'s RuntimeError.
        # Tracebacks are DROPPED — on the exception AND its
        # __cause__/__context__ chain: each frame pins the failed
        # build's locals (dataset-sized device arrays) in the job
        # registry for as long as the job lives, and the full trace
        # text is already captured in self.exception above
        seen = set()
        link = exc
        while link is not None and id(link) not in seen:
            seen.add(id(link))
            link.__traceback__ = None
            link = link.__cause__ or link.__context__
        self.exception_obj = exc
        # failed stage = the INNERMOST span this exception unwound
        # through on the worker thread (spans note it in __exit__;
        # phase contexts have already popped by catch time, so
        # current_span() alone would miss it); falls back to whatever
        # span is still open
        try:
            from h2o3_tpu import telemetry
            self.failed_stage = telemetry.last_error_span(exc)
            if self.failed_stage is None:
                sp = telemetry.current_span()
                self.failed_stage = sp.name if sp is not None else None
        except Exception:   # noqa: BLE001 — diagnostics must not mask
            self.failed_stage = None

    def run(self, fn: Callable[["Job"], Any], background: bool = False) -> "Job":
        def body():
            try:
                terminal = self.execute_scheduled(fn)
            except BaseException:
                # KeyboardInterrupt/SystemExit on the job thread: still
                # turn terminal and stamp the end clocks (the old
                # finally's guarantee) — a non-terminal job is never
                # evicted and its msec grows forever
                if self.status not in _TERMINAL:
                    self.status = FAILED
                    self.exception_msg = "job body unwound on a " \
                                         "BaseException"
                    self.end_time = time.time()
                    self._end_mono = time.monotonic()
                    self._done_evt.set()
                raise
            if not terminal:
                # a JobPreempted unwind with no scheduler to requeue it
                # (inline/H2O3_SCHED=0 run): finalize as CANCELLED, the
                # pre-scheduler meaning of that exception family
                self.status = CANCELLED
                self.end_time = time.time()
                self._end_mono = time.monotonic()
                self._done_evt.set()
        if background:
            self._thread = threading.Thread(target=body, daemon=True)
            self._thread.start()
        else:
            body()
        return self

    # -- scheduler lifecycle (h2o3_tpu.sched) ---------------------------

    def mark_queued(self) -> "Job":
        """Enter the training scheduler's run queue: not yet dispatched,
        so the supervision clocks don't tick (the watchdog skips
        undispatched jobs even when recovery re-badges them
        RECOVERING)."""
        self.status = QUEUED
        self._dispatched = False
        _bb(self, "QUEUED")
        return self

    def mark_dispatched(self) -> None:
        """Leave the queue for a worker: restart the supervision clocks
        so max_runtime/stall budgets count RUN time, and record how long
        the entry waited (the queue-wait histogram's sample)."""
        now = time.monotonic()
        wait = now - self.start_mono
        self.queue_wait_s = (self.queue_wait_s or 0.0) + max(wait, 0.0)
        self.start_mono = now
        # the heartbeat clock is part of the _mutex-guarded progress
        # protocol (update/set_progress write it under the lock; the
        # watchdog's stall check races it) — restart it under the lock.
        # status/start_mono stay bare like every other writer in this
        # module (single-writer per lifecycle phase).
        with self._mutex:
            self.last_progress_mono = now
        self._dispatched = True
        if self.status != RECOVERING:   # recovery resumes keep badge
            self.status = RUNNING
        _bb(self, self.status, f"waited={wait:.2f}s")

    def execute_scheduled(self, fn: Callable[["Job"], Any]) -> bool:
        """THE job lifecycle protocol: run ``fn(self)`` on the calling
        thread, map its outcome to a terminal status, stamp the end
        clocks and release join()ers. ``run()`` delegates here (one
        implementation, not two). The single scheduler-specific arm: a
        ``JobPreempted`` unwind leaves the job NON-terminal and returns
        False — the scheduler requeues the entry and this job's waiters
        keep sleeping through the resume cycle. Returns True when the
        job reached a terminal state."""
        from h2o3_tpu.telemetry import trace as _trace
        try:
            with _trace.trace_context(self.trace_id):
                self.result = fn(self)
            # a preempt that raced the finish line: the train COMPLETED,
            # so the request is moot — never requeue a finished model
            self._preempt_requested = False
            self.status = DONE if not self._cancel_requested else CANCELLED
        except JobPreempted:
            if not self._cancel_requested:
                return False
            self.status = CANCELLED      # user cancel wins over preempt
        except JobCancelled:
            self.status = CANCELLED
        except Exception as e:
            self.status = FAILED
            self._record_failure(e)
        self.end_time = time.time()
        self._end_mono = time.monotonic()
        self._done_evt.set()
        _bb(self, self.status, self.exception_msg or "")
        return True

    def mark_requeued(self) -> None:
        """Back into the queue after a preemption unwind: bank the
        finished run segment (max_runtime_secs and /3/Jobs msec are
        CUMULATIVE across preempt/resume cycles — a resume must not get
        a fresh budget), clear the preempt request, and restart the
        clock as a queue-wait anchor."""
        now = time.monotonic()
        self._run_accum_s += max(now - self.start_mono, 0.0)
        self._preempt_requested = False
        self.preempt_count += 1
        self.start_mono = now
        self._dispatched = False
        self.status = QUEUED
        _bb(self, "REQUEUED", f"cycle={self.preempt_count}")

    def run_seconds(self) -> float:
        """Cumulative RUN time across preempt/resume cycles — the
        quantity max_runtime_secs budgets. Queue wait never counts:
        while undispatched only the banked segments are reported."""
        if not self._dispatched:
            return self._run_accum_s
        end = self._end_mono if self._end_mono is not None \
            else time.monotonic()
        return self._run_accum_s + max(end - self.start_mono, 0.0)

    def join(self, timeout: Optional[float] = None):
        if self._thread:
            self._thread.join(timeout)
        elif self.status not in _TERMINAL:
            # scheduler-run job: no owned thread — wait on the terminal
            # latch (survives preempt/resume cycles, which requeue
            # without setting it)
            self._done_evt.wait(timeout)
        if self.status == FAILED:
            raise RuntimeError(f"Job {self.key} failed:\n{self.exception}")
        return self.result

    def cancel(self, reason: Optional[str] = None):
        self._cancel_requested = True
        if reason and not self.cancel_reason:
            self.cancel_reason = reason
        _bb(self, "CANCEL_REQUESTED", reason or "")

    def preempt(self, reason: Optional[str] = None):
        """Scheduler request: yield at the next checkpoint commit and
        get requeued. Distinct from cancel() — the job is NOT over."""
        self.preempt_reason = reason
        self._preempt_requested = True
        _bb(self, "PREEMPT_REQUESTED", reason or "")

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_requested

    @property
    def preempt_requested(self) -> bool:
        return self._preempt_requested

    def duration_ms(self) -> int:
        """Elapsed run time in ms from the monotonic clock — the
        /3/Jobs ``msec`` field used to subtract wall-clock epochs and
        mis-reported across NTP slew. Cumulative across preempt/resume
        cycles; frozen at the banked total while requeued."""
        return int(self.run_seconds() * 1000)


def get_job(key: str) -> Optional[Job]:
    with _LOCK:
        return _REGISTRY.get(key)


def list_jobs():
    with _LOCK:
        return list(_REGISTRY.values())


def registry_size() -> int:
    with _LOCK:
        return len(_REGISTRY)
