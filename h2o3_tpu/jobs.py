"""Job — async work units with progress/cancel, polled by clients.

Reference: water/Job.java:24 — DKV-stored job objects with _work/_worked
progress, JobStatus, cancellation, exceptions, polled via GET /3/Jobs.
Here: a host-side registry of Job objects; training runs on a worker
thread so REST/interactive polling stays responsive (device work is
dispatched asynchronously by JAX anyway).

Long-running servers churn through thousands of jobs (every parse,
train, predict and micro-batch admin call makes one), so the registry
evicts terminal jobs beyond a bounded tail (H2O3_JOBS_KEEP, default
512) — the water/Job analog stores jobs in the DKV where the cleaner
eventually reclaims them; here eviction rides on registration.

Supervision (the SURVEY L1/L2 heartbeat analog, single-process): every
progress update is a heartbeat; a lazily-started watchdog thread marks
RUNNING jobs with no heartbeat for ``stall_timeout_secs`` as STALLED
(visible on /3/Jobs and the ``h2o3_jobs_stalled`` gauge) and enforces
``max_runtime_secs`` by requesting cancellation — the loops that poll
``cancel_requested`` (tree chunks, streamed level passes, CV folds)
then exit cooperatively. Failures carry STRUCTURED info (exception
class + message + the failed pipeline stage from the innermost open
span) alongside the raw traceback, so clients don't parse stack text.
"""
from __future__ import annotations

import os
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, Optional

RUNNING = "RUNNING"
# a boot-time restart-recovery resume (h2o3_tpu.recovery): semantically
# RUNNING — supervised by the watchdog, pollable on /3/Jobs — but
# distinguishable so clients can tell a recovered train from a fresh one
RECOVERING = "RECOVERING"
DONE = "DONE"
FAILED = "FAILED"
CANCELLED = "CANCELLED"

_TERMINAL = (DONE, FAILED, CANCELLED)
_ACTIVE = (RUNNING, RECOVERING)

_REGISTRY: Dict[str, "Job"] = {}
_LOCK = threading.Lock()


class JobCancelled(Exception):
    """Raised inside cooperative cancellation points (streamed level
    passes) to unwind a cancelled job's work loop cleanly."""


def _jobs_keep() -> int:
    try:
        return int(os.environ.get("H2O3_JOBS_KEEP", "512") or 512)
    except ValueError:
        return 512


def _stall_default() -> float:
    """Default heartbeat-stall threshold in seconds; 0 disables stall
    detection (the default — opt in via H2O3_JOB_STALL_SECS)."""
    try:
        return float(os.environ.get("H2O3_JOB_STALL_SECS", "0") or 0)
    except ValueError:
        return 0.0


def _evict_terminal_locked(keep: int) -> None:
    """Drop the OLDEST terminal jobs beyond ``keep`` (insertion order —
    dicts preserve it). Running jobs are never evicted regardless of
    age: a poller must always be able to find its live job."""
    terminal = [k for k, j in _REGISTRY.items() if j.status in _TERMINAL]
    for k in terminal[: max(len(terminal) - keep, 0)]:
        del _REGISTRY[k]


# ---------------- watchdog --------------------------------------------
#
# One daemon thread per process, started lazily the first time a job
# that needs supervision (max_runtime_secs or stall detection) is
# registered — test suites that never opt in never grow a thread.

_WATCHDOG: Optional[threading.Thread] = None


def _watch_tick() -> float:
    try:
        return max(float(os.environ.get("H2O3_JOB_WATCH_TICK", "1.0")
                         or 1.0), 0.01)
    except ValueError:
        return 1.0


def _ensure_watchdog() -> None:
    global _WATCHDOG
    with _LOCK:
        if _WATCHDOG is not None and _WATCHDOG.is_alive():
            return
        _WATCHDOG = threading.Thread(target=_watch_loop, daemon=True,
                                     name="job-watchdog")
        _WATCHDOG.start()


def _watch_loop() -> None:
    from h2o3_tpu import telemetry
    from h2o3_tpu.log import warn
    stalled_gauge = telemetry.gauge(
        "h2o3_jobs_stalled", help="RUNNING jobs with no recent progress "
        "heartbeat")
    timeout_ctr = telemetry.counter(
        "h2o3_jobs_runtime_exceeded_total",
        help="jobs cancelled for exceeding max_runtime_secs")
    while True:
        time.sleep(_watch_tick())
        # monotonic supervision clocks: max_runtime/stall are DURATIONS —
        # an NTP step on the wall clock must not cancel a healthy train
        # or mark every job stalled (h2o3-lint: monotonic-durations)
        now = time.monotonic()
        n_stalled = 0
        for j in list_jobs():
            if j.status not in _ACTIVE:
                continue
            if (j.max_runtime_secs and not j.cancel_requested
                    and now - j.start_mono > j.max_runtime_secs):
                warn("job %s exceeded max_runtime_secs=%.1f — cancelling",
                     j.key, j.max_runtime_secs)
                timeout_ctr.inc()
                j.cancel(reason=f"max_runtime_secs="
                                f"{j.max_runtime_secs:g} exceeded")
            stall = j.stall_timeout_secs
            if stall and now - j.last_progress_mono > stall:
                # the stall flag is part of the _mutex-guarded progress
                # protocol (update()/set_progress() clear it under the
                # lock) — writing it bare here raced a concurrent
                # heartbeat and could leave a progressing job marked
                # stalled (caught by h2o3-lint's lock-discipline rule)
                with j._mutex:
                    fresh = now - j.last_progress_mono <= stall
                    if not fresh and not j.stalled:
                        j.stalled = True
                        warn("job %s stalled: no progress for %.1fs "
                             "(threshold %.1fs)", j.key,
                             now - j.last_progress_mono, stall)
                if not fresh:
                    n_stalled += 1
            elif j.stalled:
                with j._mutex:
                    j.stalled = False      # heartbeat resumed
        stalled_gauge.set(n_stalled)


class Job:
    def __init__(self, description: str, work: float = 1.0,
                 key: Optional[str] = None,
                 max_runtime_secs: float = 0.0,
                 stall_timeout_secs: Optional[float] = None):
        self.key = key or f"$job_{uuid.uuid4().hex[:12]}"
        self.description = description
        self.status = RUNNING
        self._work = float(work)
        self._worked = 0.0
        self.start_time = time.time()          # reported epoch (/3/Jobs)
        self.start_mono = time.monotonic()     # duration/deadline math
        self.end_time: Optional[float] = None
        self._end_mono: Optional[float] = None
        self.exception: Optional[str] = None
        # structured failure info (/3/Jobs): class + message + pipeline
        # stage, so clients don't have to parse the traceback string
        self.exception_type: Optional[str] = None
        self.exception_msg: Optional[str] = None
        self.failed_stage: Optional[str] = None
        self.result: Any = None
        self._cancel_requested = False
        self.cancel_reason: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        # trace propagation (ISSUE 8): capture the creating thread's
        # bound trace id (the REST handler set it from the traceparent
        # header) — or mint one — so a background build's spans and the
        # /3/Jobs entry link back to the request that started it
        from h2o3_tpu.telemetry import trace as _trace
        self.trace_id: str = _trace.current_trace_id() or \
            _trace.new_trace_id()
        # supervision state: every progress write is a heartbeat
        self.max_runtime_secs = float(max_runtime_secs or 0.0)
        self.stall_timeout_secs = (_stall_default()
                                   if stall_timeout_secs is None
                                   else float(stall_timeout_secs))
        self.last_progress_mono = self.start_mono
        self.stalled = False
        # per-job mutex: _worked is read by REST pollers and bumped by
        # the worker thread (often from several CV/fold threads at
        # once) — `self._worked += w` is a read-modify-write that loses
        # updates without it (water/Job.update is an AtomicLong add)
        self._mutex = threading.Lock()
        with _LOCK:
            _REGISTRY[self.key] = self
            _evict_terminal_locked(_jobs_keep())
        if self.max_runtime_secs or self.stall_timeout_secs:
            _ensure_watchdog()

    # -- progress -------------------------------------------------------
    @property
    def progress(self) -> float:
        with self._mutex:
            if self.status in (DONE,):
                return 1.0
            return min(self._worked / self._work, 1.0) if self._work else 0.0

    def update(self, worked: float):
        with self._mutex:
            self._worked += worked
            self.last_progress_mono = time.monotonic()
            self.stalled = False       # any progress IS the heartbeat

    def set_progress(self, frac: float):
        with self._mutex:
            self._worked = frac * self._work
            self.last_progress_mono = time.monotonic()
            self.stalled = False

    # -- lifecycle ------------------------------------------------------
    def _record_failure(self, exc: BaseException) -> None:
        self.exception = traceback.format_exc()
        self.exception_type = type(exc).__name__
        self.exception_msg = str(exc)
        # failed stage = the INNERMOST span this exception unwound
        # through on the worker thread (spans note it in __exit__;
        # phase contexts have already popped by catch time, so
        # current_span() alone would miss it); falls back to whatever
        # span is still open
        try:
            from h2o3_tpu import telemetry
            self.failed_stage = telemetry.last_error_span(exc)
            if self.failed_stage is None:
                sp = telemetry.current_span()
                self.failed_stage = sp.name if sp is not None else None
        except Exception:   # noqa: BLE001 — diagnostics must not mask
            self.failed_stage = None

    def run(self, fn: Callable[["Job"], Any], background: bool = False) -> "Job":
        def body():
            # re-bind the creator's trace id on the worker thread so
            # every span the build records carries it
            from h2o3_tpu.telemetry import trace as _trace
            try:
                with _trace.trace_context(self.trace_id):
                    self.result = fn(self)
                self.status = DONE if not self._cancel_requested else CANCELLED
            except JobCancelled:
                self.status = CANCELLED
            except Exception as e:
                self.status = FAILED
                self._record_failure(e)
            finally:
                self.end_time = time.time()
                self._end_mono = time.monotonic()
        if background:
            self._thread = threading.Thread(target=body, daemon=True)
            self._thread.start()
        else:
            body()
        return self

    def join(self, timeout: Optional[float] = None):
        if self._thread:
            self._thread.join(timeout)
        if self.status == FAILED:
            raise RuntimeError(f"Job {self.key} failed:\n{self.exception}")
        return self.result

    def cancel(self, reason: Optional[str] = None):
        self._cancel_requested = True
        if reason and not self.cancel_reason:
            self.cancel_reason = reason

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_requested

    def duration_ms(self) -> int:
        """Elapsed run time in ms from the monotonic clock — the
        /3/Jobs ``msec`` field used to subtract wall-clock epochs and
        mis-reported across NTP slew."""
        end = self._end_mono if self._end_mono is not None \
            else time.monotonic()
        return int((end - self.start_mono) * 1000)


def get_job(key: str) -> Optional[Job]:
    with _LOCK:
        return _REGISTRY.get(key)


def list_jobs():
    with _LOCK:
        return list(_REGISTRY.values())


def registry_size() -> int:
    with _LOCK:
        return len(_REGISTRY)
