"""Job — async work units with progress/cancel, polled by clients.

Reference: water/Job.java:24 — DKV-stored job objects with _work/_worked
progress, JobStatus, cancellation, exceptions, polled via GET /3/Jobs.
Here: a host-side registry of Job objects; training runs on a worker
thread so REST/interactive polling stays responsive (device work is
dispatched asynchronously by JAX anyway).

Long-running servers churn through thousands of jobs (every parse,
train, predict and micro-batch admin call makes one), so the registry
evicts terminal jobs beyond a bounded tail (H2O3_JOBS_KEEP, default
512) — the water/Job analog stores jobs in the DKV where the cleaner
eventually reclaims them; here eviction rides on registration.
"""
from __future__ import annotations

import os
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, Optional

RUNNING = "RUNNING"
DONE = "DONE"
FAILED = "FAILED"
CANCELLED = "CANCELLED"

_TERMINAL = (DONE, FAILED, CANCELLED)

_REGISTRY: Dict[str, "Job"] = {}
_LOCK = threading.Lock()


def _jobs_keep() -> int:
    try:
        return int(os.environ.get("H2O3_JOBS_KEEP", "512") or 512)
    except ValueError:
        return 512


def _evict_terminal_locked(keep: int) -> None:
    """Drop the OLDEST terminal jobs beyond ``keep`` (insertion order —
    dicts preserve it). Running jobs are never evicted regardless of
    age: a poller must always be able to find its live job."""
    terminal = [k for k, j in _REGISTRY.items() if j.status in _TERMINAL]
    for k in terminal[: max(len(terminal) - keep, 0)]:
        del _REGISTRY[k]


class Job:
    def __init__(self, description: str, work: float = 1.0, key: Optional[str] = None):
        self.key = key or f"$job_{uuid.uuid4().hex[:12]}"
        self.description = description
        self.status = RUNNING
        self._work = float(work)
        self._worked = 0.0
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        self.exception: Optional[str] = None
        self.result: Any = None
        self._cancel_requested = False
        self._thread: Optional[threading.Thread] = None
        # per-job mutex: _worked is read by REST pollers and bumped by
        # the worker thread (often from several CV/fold threads at
        # once) — `self._worked += w` is a read-modify-write that loses
        # updates without it (water/Job.update is an AtomicLong add)
        self._mutex = threading.Lock()
        with _LOCK:
            _REGISTRY[self.key] = self
            _evict_terminal_locked(_jobs_keep())

    # -- progress -------------------------------------------------------
    @property
    def progress(self) -> float:
        with self._mutex:
            if self.status in (DONE,):
                return 1.0
            return min(self._worked / self._work, 1.0) if self._work else 0.0

    def update(self, worked: float):
        with self._mutex:
            self._worked += worked

    def set_progress(self, frac: float):
        with self._mutex:
            self._worked = frac * self._work

    # -- lifecycle ------------------------------------------------------
    def run(self, fn: Callable[["Job"], Any], background: bool = False) -> "Job":
        def body():
            try:
                self.result = fn(self)
                self.status = DONE if not self._cancel_requested else CANCELLED
            except Exception:
                self.status = FAILED
                self.exception = traceback.format_exc()
            finally:
                self.end_time = time.time()
        if background:
            self._thread = threading.Thread(target=body, daemon=True)
            self._thread.start()
        else:
            body()
        return self

    def join(self, timeout: Optional[float] = None):
        if self._thread:
            self._thread.join(timeout)
        if self.status == FAILED:
            raise RuntimeError(f"Job {self.key} failed:\n{self.exception}")
        return self.result

    def cancel(self):
        self._cancel_requested = True

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_requested


def get_job(key: str) -> Optional[Job]:
    with _LOCK:
        return _REGISTRY.get(key)


def list_jobs():
    with _LOCK:
        return list(_REGISTRY.values())


def registry_size() -> int:
    with _LOCK:
        return len(_REGISTRY)
