"""AutoML — budgeted model-and-ensemble search over the builder zoo.

Reference: h2o-automl/src/main/java/ai/h2o/automl/AutoML.java:49 (driver
loop, work planning :420, execution plan :403), ModelingStepsRegistry /
ModelingStep (the pluggable step SPI), the default plan in
modeling/{XGBoost,GBM,GLM,DRF,DeepLearning,StackedEnsemble}StepsProvider
(XGB defaults + grids, GBM defaults + grids, DRF + XRT, GLM, DL grids,
two stacked ensembles: best-of-family and all), leaderboard ranked by CV
metric, events/EventLog.java (audit trail).

TPU re-design: pure orchestration over the existing estimators — each
step trains with nfolds CV (holdouts kept for the ensembles) on the
chip; budgets (max_models / max_runtime_secs) gate between steps exactly
like WorkAllocations. The step plan mirrors the reference's default
sequence at reduced grid sizes (each model saturates the chip, so fewer,
better-budgeted points beat the reference's thread-parallel sprawl)."""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu import dkv
from h2o3_tpu.log import info

from h2o3_tpu.models.grid import _LESS_IS_BETTER, sort_models


def _default_steps(nclasses: int) -> List[Dict]:
    """The reference's default execution plan (StepDefinition defaults),
    sized for sequential single-chip execution."""
    clf = nclasses > 1
    steps: List[Dict] = [
        {"algo": "xgboost", "id": "XGBoost_def_1",
         "params": {"ntrees": 50, "max_depth": 8, "eta": 0.3,
                    "subsample": 0.8, "colsample_bytree": 0.8}},
        {"algo": "gbm", "id": "GBM_def_1",
         "params": {"ntrees": 50, "max_depth": 6, "learn_rate": 0.1,
                    "sample_rate": 0.8, "col_sample_rate": 0.8}},
        {"algo": "gbm", "id": "GBM_def_2",
         "params": {"ntrees": 50, "max_depth": 3, "learn_rate": 0.1}},
        {"algo": "drf", "id": "DRF_def_1",
         "params": {"ntrees": 50, "max_depth": 10}},
        {"algo": "glm", "id": "GLM_def_1",
         "params": ({"family": "binomial"} if nclasses == 2 else {})
         | {"alpha": 0.5, "lambda_search": True, "nlambdas": 10}},
        {"algo": "drf", "id": "XRT_def_1",           # extremely-random analog
         "params": {"ntrees": 50, "max_depth": 10, "mtries": 1}},
        {"algo": "deeplearning", "id": "DL_def_1",
         "params": {"hidden": [64, 64], "epochs": 15}},
        {"algo": "gbm", "id": "GBM_grid_1",
         "grid": {"max_depth": [4, 8], "learn_rate": [0.05, 0.2]},
         "params": {"ntrees": 40}},
    ]
    if nclasses > 2:
        # GLM/SE multinomial pending — drop them from the plan
        steps = [s for s in steps if s["algo"] != "glm"]
    return steps


class H2OAutoML:
    """h2o-py H2OAutoML surface: train(...) then .leaderboard / .leader."""

    def __init__(self, max_models: Optional[int] = None,
                 max_runtime_secs: Optional[float] = None,
                 max_runtime_secs_per_model: Optional[float] = None,
                 nfolds: int = 3, seed: int = -1,
                 sort_metric: Optional[str] = None,
                 include_algos: Optional[Sequence[str]] = None,
                 exclude_algos: Optional[Sequence[str]] = None,
                 project_name: Optional[str] = None, **_ignored):
        if not max_models and not max_runtime_secs:
            max_runtime_secs = 3600.0
        self.max_models = max_models
        self.max_runtime_secs = max_runtime_secs
        self.max_runtime_secs_per_model = max_runtime_secs_per_model
        self.nfolds = int(nfolds)
        self.seed = seed
        self.sort_metric = sort_metric
        self.include_algos = ([a.lower() for a in include_algos]
                              if include_algos else None)
        self.exclude_algos = ([a.lower() for a in exclude_algos]
                              if exclude_algos else None)
        self.project_name = project_name or dkv.unique_key("automl")
        self.models: List = []
        self.event_log: List[Dict] = []
        self._leader = None

    # -- events (ai/h2o/automl/events/EventLog.java) --------------------

    def _log(self, stage: str, msg: str):
        self.event_log.append({"timestamp": time.time(), "stage": stage,
                               "message": msg})
        info("automl[%s] %s: %s", self.project_name, stage, msg)

    def _algo_allowed(self, algo: str) -> bool:
        if self.include_algos is not None:
            return (algo in self.include_algos
                    or (algo == "drf" and "xrt" in self.include_algos))
        if self.exclude_algos is not None:
            return algo not in self.exclude_algos
        return True

    def _budget_left(self, t0: float) -> bool:
        if self.max_models and len(self.models) >= self.max_models:
            return False
        if self.max_runtime_secs and time.time() - t0 > self.max_runtime_secs:
            return False
        return True

    # -- driver (AutoML.java:403-457 plan execution) --------------------

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, leaderboard_frame=None):
        from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
        from h2o3_tpu.models.drf import H2ORandomForestEstimator
        from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
        from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
        from h2o3_tpu.models.grid import H2OGridSearch
        from h2o3_tpu.models.xgboost import H2OXGBoostEstimator
        builders = {"xgboost": H2OXGBoostEstimator,
                    "gbm": H2OGradientBoostingEstimator,
                    "drf": H2ORandomForestEstimator,
                    "glm": H2OGeneralizedLinearEstimator,
                    "deeplearning": H2ODeepLearningEstimator}
        rvec = training_frame.vec(y)
        nclasses = rvec.cardinality if rvec.type == "enum" else 1
        t0 = time.time()
        self._log("init", f"AutoML build started: y={y}, "
                          f"nfolds={self.nfolds}")
        for step in _default_steps(nclasses):
            if not self._budget_left(t0):
                self._log("budget", "model/time budget exhausted")
                break
            algo = step["algo"]
            if not self._algo_allowed(algo):
                continue
            params = dict(step.get("params") or {})
            params.setdefault("seed", self.seed)
            params["nfolds"] = self.nfolds
            try:
                if "grid" in step:
                    grid = H2OGridSearch(
                        builders[algo](**params), step["grid"],
                        search_criteria={
                            "strategy": "RandomDiscrete",
                            "max_models": (self.max_models
                                           - len(self.models)
                                           if self.max_models else 0),
                            "max_runtime_secs": (
                                self.max_runtime_secs
                                - (time.time() - t0)
                                if self.max_runtime_secs else 0),
                            "seed": self.seed})
                    grid.train(x=x, y=y, training_frame=training_frame,
                               validation_frame=validation_frame)
                    for m in grid.models:
                        self._register(m, f"{step['id']}_{len(self.models)}")
                else:
                    est = builders[algo](**params)
                    model = self._train_budgeted(
                        est, x, y, training_frame, validation_frame)
                    self._register(model, step["id"])
                self._log("model", f"built {step['id']}")
            except Exception as e:  # noqa: BLE001 — plan keeps going
                self._log("skip", f"{step['id']} failed: {e}")
        # stacked ensembles (best-of-family + all), binomial/regression
        if nclasses <= 2 and len(self.models) >= 2:
            self._build_ensembles(x, y, training_frame)
        self._rank()
        self._log("done", f"AutoML build done: {len(self.models)} models, "
                          f"leader={self.leader.key if self.leader else None}")
        return self

    def _train_budgeted(self, est, x, y, training_frame, validation_frame):
        """Train one step, cancelling at max_runtime_secs_per_model (the
        WorkAllocations per-step budget)."""
        cap = self.max_runtime_secs_per_model
        if not cap:
            # train(background=False) joins internally and raises on FAILED
            est.train(x=x, y=y, training_frame=training_frame,
                      validation_frame=validation_frame)
            return est.model
        est.train(x=x, y=y, training_frame=training_frame,
                  validation_frame=validation_frame, background=True)
        t0 = time.time()
        while est.job.status == "RUNNING":
            if time.time() - t0 > cap:
                est.job.cancel()
            time.sleep(0.2)
        return est.job.join()  # raises on FAILED

    def _register(self, model, step_id: str):
        model.key = f"{self.project_name}_{step_id}"
        model.output["automl_step"] = step_id
        # family tag distinguishes xgboost from gbm (the XGBoost estimator
        # produces a GBMModel whose .algo is 'gbm')
        model.output["automl_family"] = step_id.split("_")[0].lower()
        dkv.put(model.key, "model", model)
        self.models.append(model)

    def _build_ensembles(self, x, y, training_frame):
        from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
        with_cv = [m for m in self.models
                   if m.output.get("cross_validation_holdout_predictions")
                   is not None]
        if len(with_cv) < 2:
            return
        self._rank()
        best_of_family: List = []
        seen = set()
        for m in self.models:
            fam = m.output.get("automl_family", m.algo)
            if m in with_cv and fam not in seen:
                best_of_family.append(m)
                seen.add(fam)
        for name, base in (("BestOfFamily", best_of_family), ("AllModels",
                                                              with_cv)):
            if len(base) < 2:
                continue
            try:
                se = H2OStackedEnsembleEstimator(base_models=base)
                se.train(x=x, y=y, training_frame=training_frame)
                self._register(se.model, f"StackedEnsemble_{name}")
                self._log("ensemble", f"built StackedEnsemble_{name} over "
                                      f"{len(base)} base models")
            except Exception as e:  # noqa: BLE001
                self._log("skip", f"StackedEnsemble_{name} failed: {e}")

    # -- leaderboard ----------------------------------------------------

    def _metric_name(self) -> str:
        if self.sort_metric:
            return self.sort_metric.lower()
        if not self.models:
            return "auc"
        m = self.models[0]
        if m.nclasses == 2:
            return "auc"
        if m.nclasses > 2:
            return "logloss"
        return "mean_residual_deviance"

    def _metric_of(self, model, name):
        from h2o3_tpu.models.grid import _metric_of
        return _metric_of(model, name)

    def _rank(self):
        if not self.models:
            return
        metric = self._metric_name()
        sort_models(self.models, metric, metric not in _LESS_IS_BETTER)
        self._leader = self.models[0] if self.models else None

    @property
    def leader(self):
        return self._leader

    @property
    def leaderboard(self) -> List[Dict]:
        metric = self._metric_name()
        return [{"model_id": m.key, metric: self._metric_of(m, metric)}
                for m in self.models]

    def predict(self, frame):
        if self.leader is None:
            raise RuntimeError("AutoML built no models (all steps failed "
                               "or were excluded) — see .event_log")
        return self.leader.predict(frame)
