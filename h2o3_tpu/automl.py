"""AutoML — budgeted model-and-ensemble search over the builder zoo.

Reference: h2o-automl/src/main/java/ai/h2o/automl/AutoML.java:49 (driver
loop, work planning :420, execution plan :403, exploitation ratio
:346,457), ModelingStepsRegistry.java / ModelingStep.java /
StepDefinition.java (the pluggable step SPI), the default plan in
modeling/{XGBoost,GBM,GLM,DRF,DeepLearning,StackedEnsemble}StepsProvider,
hex/leaderboard/Leaderboard.java (single-metric-source ranked table with
extension columns), preprocessing/TargetEncoding.java (optional TE step),
events/EventLog.java (audit trail).

TPU re-design: pure orchestration over the existing estimators — each
step trains with nfolds CV (holdouts kept for the ensembles) on the
chip; budgets (max_models / max_runtime_secs) gate between steps exactly
like WorkAllocations. The plan is DATA (StepDefinition dicts from
registered providers), not code: callers can pass ``modeling_plan`` or
register new providers via ``register_modeling_steps`` — the
ModelingStepsRegistry SPI."""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu import dkv
from h2o3_tpu.log import info

from h2o3_tpu.models.grid import _LESS_IS_BETTER


# ---------------- step provider registry (ModelingStepsRegistry SPI) ----

# provider name -> fn(ctx) -> list of StepDefinition dicts
# ctx carries nclasses / nfolds / seed so providers can adapt the family
_STEP_PROVIDERS: Dict[str, Callable[[Dict], List[Dict]]] = {}


def register_modeling_steps(name: str, fn: Callable[[Dict], List[Dict]]):
    """Register a step provider (ai/h2o/automl/ModelingStepsRegistry
    service loading; StepDefinition alias semantics). ``fn(ctx)`` returns
    StepDefinition dicts: {"algo", "id", "params"} or {"algo", "id",
    "grid", "params"}."""
    _STEP_PROVIDERS[name.lower()] = fn
    return fn


def _xgboost_steps(ctx):
    return [
        {"algo": "xgboost", "id": "XGBoost_def_1",
         "params": {"ntrees": 50, "max_depth": 8, "eta": 0.3,
                    "subsample": 0.8, "colsample_bytree": 0.8}},
    ]


def _gbm_steps(ctx):
    return [
        {"algo": "gbm", "id": "GBM_def_1",
         "params": {"ntrees": 50, "max_depth": 6, "learn_rate": 0.1,
                    "sample_rate": 0.8, "col_sample_rate": 0.8}},
        {"algo": "gbm", "id": "GBM_def_2",
         "params": {"ntrees": 50, "max_depth": 3, "learn_rate": 0.1}},
    ]


def _gbm_grid_steps(ctx):
    return [
        {"algo": "gbm", "id": "GBM_grid_1",
         "grid": {"max_depth": [4, 8], "learn_rate": [0.05, 0.2]},
         "params": {"ntrees": 40}},
    ]


def _drf_steps(ctx):
    return [
        {"algo": "drf", "id": "DRF_def_1",
         "params": {"ntrees": 50, "max_depth": 10}},
        {"algo": "drf", "id": "XRT_def_1",      # extremely-random analog
         "params": {"ntrees": 50, "max_depth": 10, "mtries": 1}},
    ]


def _glm_steps(ctx):
    fam = ("binomial" if ctx["nclasses"] == 2 else
           "multinomial" if ctx["nclasses"] > 2 else "gaussian")
    params = {"family": fam, "alpha": 0.5, "lambda_search": True,
              "nlambdas": 10}
    if ctx["nclasses"] > 2:
        # multinomial lambda path is one fit per lambda; keep it tight
        params = {"family": fam, "alpha": 0.0, "Lambda": 1e-4}
    return [{"algo": "glm", "id": "GLM_def_1", "params": params}]


def _lr_annealing_step(leader, aml):
    params = {k: v for k, v in leader.params.items()
              if k in ("max_depth", "sample_rate", "col_sample_rate",
                       "min_rows", "nbins")}
    params.update({"ntrees": int(leader.params.get("ntrees", 50) * 2),
                   "learn_rate":
                       float(leader.params.get("learn_rate", 0.1)) / 2})
    return [{"id": f"{leader.output.get('automl_family', 'gbm')}"
                   f"_lr_annealing",
             "algo": leader.output.get("automl_family", "gbm"),
             "params": params}]


def _forest_deepen_step(leader, aml):
    params = {k: v for k, v in leader.params.items()
              if k in ("sample_rate", "mtries", "min_rows", "nbins")}
    params.update({"ntrees": int(leader.params.get("ntrees", 50) * 2),
                   "max_depth":
                       int(leader.params.get("max_depth", 20)) + 4})
    return [{"id": "drf_deepened", "algo": "drf", "params": params}]


def _glm_refine_step(leader, aml):
    lam = leader.params.get("Lambda") or [0.0]
    base = float(lam[0] if isinstance(lam, (list, tuple)) else lam)
    return [{"id": "glm_lambda_refine", "algo": "glm",
             "params": {"family": leader.params.get("family", "auto"),
                        "alpha": [0.5],
                        "Lambda": [max(base / 10.0, 1e-6)]}}]


# the exploitation PLAN IS DATA (AutoML.java:403-457 per-algo
# exploitation steps): family → provider(leader, aml) → step dicts
EXPLOITATION_STEPS: Dict[str, Callable] = {
    "gbm": _lr_annealing_step,
    "xgboost": _lr_annealing_step,
    "drf": _forest_deepen_step,
    "xrt": _forest_deepen_step,
    "glm": _glm_refine_step,
}


def _deeplearning_steps(ctx):
    return [
        {"algo": "deeplearning", "id": "DL_def_1",
         "params": {"hidden": [64, 64], "epochs": 15}},
    ]


register_modeling_steps("xgboost", _xgboost_steps)
register_modeling_steps("gbm", _gbm_steps)
register_modeling_steps("gbm_grids", _gbm_grid_steps)
register_modeling_steps("drf", _drf_steps)
register_modeling_steps("glm", _glm_steps)
register_modeling_steps("deeplearning", _deeplearning_steps)

# the default execution plan IS data (StepDefinition list — the reference
# default: XGB defaults, GBM defaults, DRF/XRT, GLM, DL, grids, SEs)
DEFAULT_MODELING_PLAN: List[str] = [
    "xgboost", "gbm", "drf", "glm", "deeplearning", "gbm_grids",
]


# ---------------- leaderboard (hex/leaderboard/Leaderboard.java) --------

class Leaderboard:
    """Metric-ranked model table with extension columns.

    Ranking uses ONE metric source for every row — cross-validation
    metrics when every model has them, else the leaderboard frame, else
    validation, else training — never a mix (Leaderboard.java sort-metric
    consistency: models scored on different data must not be compared)."""

    EXTENSIONS = ("training_time_ms", "algo")

    def __init__(self, models: Sequence, metric: str,
                 leaderboard_frame=None):
        self.metric = metric
        self.source = None
        self.rows: List[Dict] = []
        self._models = list(models)
        self._frame = leaderboard_frame
        self._build()

    def _metrics_obj(self, m, source: str):
        if source == "xval":
            return m.cross_validation_metrics
        if source == "leaderboard":
            return m.model_performance(self._frame)
        if source == "valid":
            return m.validation_metrics
        return m.training_metrics

    def _pick_source(self) -> str:
        if self._frame is not None:
            return "leaderboard"
        if all(m.cross_validation_metrics is not None
               for m in self._models):
            return "xval"
        if all(m.validation_metrics is not None for m in self._models):
            return "valid"
        return "train"

    def _build(self):
        if not self._models:
            return
        self.source = self._pick_source()
        vals = []
        for m in self._models:
            mm = self._metrics_obj(m, self.source)
            v = getattr(mm, self.metric, None)
            if v is None and self.metric == "mean_residual_deviance":
                v = getattr(mm, "mse", None)
            vals.append(float("nan") if v is None else float(v))
        order = np.argsort([v if self.metric in _LESS_IS_BETTER else -v
                            for v in vals], kind="stable")
        self._models = [self._models[i] for i in order]
        for m, v in zip(self._models, [vals[i] for i in order]):
            row = {"model_id": m.key, self.metric: v,
                   "algo": m.output.get("automl_family", m.algo),
                   "training_time_ms": int(m.run_time * 1000),
                   "metric_source": self.source}
            self.rows.append(row)

    @property
    def models(self) -> List:
        return self._models

    # sequence-of-row-dicts surface (legacy callers iterate/index)
    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.rows[i]

    def to_frame(self):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import T_STR, Vec
        if not self.rows:
            return Frame([], [])
        return Frame(
            ["model_id", self.metric, "algo", "training_time_ms"],
            [Vec.from_numpy(np.asarray([r["model_id"] for r in self.rows],
                                       dtype=object), vtype=T_STR),
             Vec.from_numpy(np.asarray([r[self.metric] for r in self.rows],
                                       dtype=np.float64)),
             Vec.from_numpy(np.asarray([r["algo"] for r in self.rows],
                                       dtype=object), vtype=T_STR),
             Vec.from_numpy(np.asarray([r["training_time_ms"]
                                        for r in self.rows]))])


# ---------------- driver ------------------------------------------------

class H2OAutoML:
    """h2o-py H2OAutoML surface: train(...) then .leaderboard / .leader."""

    def __init__(self, max_models: Optional[int] = None,
                 max_runtime_secs: Optional[float] = None,
                 max_runtime_secs_per_model: Optional[float] = None,
                 nfolds: int = 3, seed: int = -1,
                 sort_metric: Optional[str] = None,
                 include_algos: Optional[Sequence[str]] = None,
                 exclude_algos: Optional[Sequence[str]] = None,
                 project_name: Optional[str] = None,
                 modeling_plan: Optional[Sequence] = None,
                 exploitation_ratio: float = -1.0,
                 preprocessing: Optional[Sequence[str]] = None,
                 recovery_dir: Optional[str] = None,
                 **_ignored):
        if not max_models and not max_runtime_secs:
            max_runtime_secs = 3600.0
        self.max_models = max_models
        self.max_runtime_secs = max_runtime_secs
        self.max_runtime_secs_per_model = max_runtime_secs_per_model
        self.nfolds = int(nfolds)
        self.seed = seed
        self.sort_metric = sort_metric
        self.include_algos = ([a.lower() for a in include_algos]
                              if include_algos else None)
        self.exclude_algos = ([a.lower() for a in exclude_algos]
                              if exclude_algos else None)
        self.project_name = project_name or dkv.unique_key("automl")
        self.modeling_plan = list(modeling_plan or DEFAULT_MODELING_PLAN)
        self.exploitation_ratio = float(exploitation_ratio)
        self.preprocessing = [str(s).lower() for s in (preprocessing or [])]
        # hex/faulttolerance/Recovery.java: AutoML state persists per
        # completed step; a restarted build with the same recovery_dir
        # reloads finished models and resumes the plan
        self.recovery_dir = recovery_dir
        self.models: List = []
        self.event_log: List[Dict] = []
        self._leaderboard: Optional[Leaderboard] = None
        self._leaderboard_frame = None
        self._te_model = None

    # -- events (ai/h2o/automl/events/EventLog.java) --------------------

    def _log(self, stage: str, msg: str):
        self.event_log.append({"timestamp": time.time(), "stage": stage,
                               "message": msg})
        info("automl[%s] %s: %s", self.project_name, stage, msg)

    def _algo_allowed(self, algo: str) -> bool:
        if self.include_algos is not None:
            return (algo in self.include_algos
                    or (algo == "drf" and "xrt" in self.include_algos))
        if self.exclude_algos is not None:
            return algo not in self.exclude_algos
        return True

    def _budget_left(self, t0: float) -> bool:
        # t0 is a time.monotonic() anchor: max_runtime_secs is a
        # duration budget and must not move with NTP slew
        if self.max_models and len(self.models) >= self.max_models:
            return False
        if self.max_runtime_secs and \
                time.monotonic() - t0 > self.max_runtime_secs:
            return False
        return True

    # -- preprocessing (ai/h2o/automl/preprocessing/TargetEncoding.java) -

    def _apply_target_encoding(self, x, y, training_frame):
        """Optional TE step: encode high-cardinality categoricals with
        KFold strategy; returns (x', frame') with encoded columns swapped
        in for tree/linear steps (TargetEncoding.java encodeAllColumns)."""
        from h2o3_tpu.models.targetencoder import H2OTargetEncoderEstimator
        names = x or [n for n in training_frame.names if n != y]
        cats = [n for n in names
                if training_frame.vec(n).type == "enum"
                and training_frame.vec(n).cardinality > 10]
        if not cats:
            return x, training_frame
        # leave-one-out leakage handling: needs no fold column and keeps
        # each row's own target out of its encoding (TargetEncoding.java
        # uses the AutoML fold column with kfold; LOO is the fold-free
        # equivalent)
        te = H2OTargetEncoderEstimator(
            data_leakage_handling="leave_one_out", seed=self.seed)
        te.train(x=cats, y=y, training_frame=training_frame)
        enc = te.model.transform(training_frame, as_training=True)
        self._te_model = te.model
        new_x = [n for n in names if n not in cats] + \
            [f"{c}_te" for c in cats if f"{c}_te" in enc.names]
        self._log("preprocessing",
                  f"target-encoded {len(cats)} high-cardinality columns")
        return new_x, enc

    # -- driver (AutoML.java:403-457 plan execution) --------------------

    def _builders(self):
        from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
        from h2o3_tpu.models.drf import H2ORandomForestEstimator
        from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
        from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
        from h2o3_tpu.models.xgboost import H2OXGBoostEstimator
        return {"xgboost": H2OXGBoostEstimator,
                "gbm": H2OGradientBoostingEstimator,
                "drf": H2ORandomForestEstimator,
                "glm": H2OGeneralizedLinearEstimator,
                "deeplearning": H2ODeepLearningEstimator}

    def _plan_steps(self, ctx) -> List[Dict]:
        """Resolve the modeling plan (names or inline StepDefinitions)
        through the provider registry — StepDefinition/alias semantics."""
        steps: List[Dict] = []
        for entry in self.modeling_plan:
            if isinstance(entry, dict) and "algo" in entry:
                steps.append(entry)          # inline StepDefinition
                continue
            name = str(entry).lower()
            provider = _STEP_PROVIDERS.get(name)
            if provider is None:
                self._log("plan", f"unknown step provider '{name}' skipped")
                continue
            steps.extend(provider(ctx))
        return steps

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, leaderboard_frame=None):
        """Drive the plan with every child train tagged BULK priority
        under this project's fair-share group (ISSUE 15): AutoML
        children queue behind interactive trains and one project cannot
        starve another tenant's children in the bulk class."""
        from h2o3_tpu import sched
        with sched.submit_context(priority="bulk",
                                  share=self.project_name):
            return self._train_driver(x, y, training_frame,
                                      validation_frame, leaderboard_frame)

    def _train_driver(self, x, y, training_frame, validation_frame,
                      leaderboard_frame):
        builders = self._builders()
        rvec = training_frame.vec(y)
        nclasses = rvec.cardinality if rvec.type == "enum" else 1
        t0 = time.monotonic()
        self._leaderboard_frame = leaderboard_frame
        self._log("init", f"AutoML build started: y={y}, "
                          f"nfolds={self.nfolds}")
        if "target_encoding" in self.preprocessing:
            try:
                x, training_frame = self._apply_target_encoding(
                    x, y, training_frame)
            except Exception as e:  # noqa: BLE001
                self._log("skip", f"target encoding failed: {e}")
        ctx = {"nclasses": nclasses, "nfolds": self.nfolds,
               "seed": self.seed}
        self._data_fp = [y, list(training_frame.names),
                         int(training_frame.nrow)]
        resume = self._load_recovery()
        # exploitation budget carve-out (AutoML.java:346,457): a slice of
        # the time budget reserved for fine-tuning the exploration leader
        exploit_secs = 0.0
        explore_deadline = None
        if self.exploitation_ratio > 0 and self.max_runtime_secs:
            exploit_secs = self.exploitation_ratio * self.max_runtime_secs
            explore_deadline = t0 + self.max_runtime_secs - exploit_secs
        for step in self._plan_steps(ctx):
            if not self._budget_left(t0):
                self._log("budget", "model/time budget exhausted")
                break
            if explore_deadline and time.monotonic() > explore_deadline:
                self._log("budget", "exploration budget exhausted "
                                    "(exploitation reserve)")
                break
            algo = step["algo"]
            if not self._algo_allowed(algo):
                continue
            if step["id"] in resume.get("steps_done", []):
                n = self._resume_step(step["id"], resume)
                self._log("resume", f"step {step['id']}: {n} model(s) "
                                    f"reloaded from recovery_dir")
                continue
            params = dict(step.get("params") or {})
            params.setdefault("seed", self.seed)
            params["nfolds"] = self.nfolds
            try:
                if "grid" in step:
                    from h2o3_tpu.models.grid import H2OGridSearch
                    grid = H2OGridSearch(
                        builders[algo](**params), step["grid"],
                        search_criteria={
                            "strategy": "RandomDiscrete",
                            "max_models": (self.max_models
                                           - len(self.models)
                                           if self.max_models else 0),
                            "max_runtime_secs": (
                                self.max_runtime_secs
                                - (time.monotonic() - t0)
                                if self.max_runtime_secs else 0),
                            "seed": self.seed})
                    grid.train(x=x, y=y, training_frame=training_frame,
                               validation_frame=validation_frame)
                    for m in grid.models:
                        self._register(m, f"{step['id']}_{len(self.models)}")
                else:
                    est = builders[algo](**params)
                    model = self._train_budgeted(
                        est, x, y, training_frame, validation_frame)
                    self._register(model, step["id"])
                self._log("model", f"built {step['id']}")
                self._checkpoint_step(step["id"])
            except Exception as e:  # noqa: BLE001 — plan keeps going
                self._log("skip", f"{step['id']} failed: {e}")
        if self.exploitation_ratio > 0 and self.models:
            self._exploitation(x, y, training_frame, validation_frame, t0)
        # stacked ensembles (best-of-family + all)
        if nclasses >= 1 and len(self.models) >= 2:
            self._build_ensembles(x, y, training_frame)
        self._rank(final=True)
        self._log("done", f"AutoML build done: {len(self.models)} models, "
                          f"leader={self.leader.key if self.leader else None}")
        return self

    def _exploitation(self, x, y, training_frame, validation_frame, t0):
        """Exploitation phase (AutoML.java:403-457 exploitation step
        family): the PLAN IS DATA — per-family providers in
        EXPLOITATION_STEPS derive refinement steps from the current
        family leader; each runs on the remaining budget."""
        self._rank()
        by_family = {}
        for m in self.models:
            fam = m.output.get("automl_family")
            if fam and fam not in by_family:
                by_family[fam] = m      # models are rank-ordered
        builders = self._builders()
        resume = self._load_recovery()
        for fam, provider in EXPLOITATION_STEPS.items():
            if not self._budget_left(t0):
                break
            leader = by_family.get(fam)
            if leader is None:
                continue
            for step in provider(leader, self):
                if step["id"] in resume.get("steps_done", []):
                    n = self._resume_step(step["id"], resume)
                    self._log("resume", f"exploitation {step['id']}: "
                                        f"{n} model(s) reloaded")
                    continue
                if not self._budget_left(t0):
                    break
                algo = step.get("algo", fam)
                if algo not in builders:
                    continue
                params = dict(step["params"])
                params.setdefault("seed", self.seed)
                params["nfolds"] = self.nfolds
                try:
                    est = builders[algo](**params)
                    model = self._train_budgeted(
                        est, x, y, training_frame, validation_frame)
                    self._register(model, step["id"])
                    self._log("exploitation", f"built {step['id']} "
                                              f"from {fam} leader")
                    self._checkpoint_step(step["id"])
                except Exception as e:  # noqa: BLE001
                    self._log("skip", f"exploitation {step['id']} "
                                      f"failed: {e}")

    # -- fault tolerance (hex/faulttolerance/Recovery.java) -------------

    def _recovery_paths(self):
        import os
        man = os.path.join(self.recovery_dir,
                           f"{self.project_name}.automl.json")
        return self.recovery_dir, man

    def _config_fp(self) -> str:
        import json as _json
        # budgets (max_models/max_runtime) are NOT identity: a resume
        # may extend them (Recovery.java resumes with remaining budget).
        # The TRAINING DATA IS identity: models from a different frame
        # or response must never ride into the new leaderboard
        return _json.dumps(
            {"plan": [str(e) for e in self.modeling_plan],
             "nfolds": self.nfolds, "seed": self.seed,
             "data": getattr(self, "_data_fp", None)}, sort_keys=True)

    def _load_recovery(self) -> Dict:
        if not self.recovery_dir:
            return {}
        import json as _json
        import os
        os.makedirs(self.recovery_dir, exist_ok=True)
        _, man = self._recovery_paths()
        if not os.path.exists(man):
            return {}
        try:
            with open(man) as f:
                state = _json.load(f)
        except (OSError, _json.JSONDecodeError):
            return {}
        if state.get("config") != self._config_fp():
            self._log("resume", "recovery state ignored: AutoML config "
                                "changed since the saved run")
            return {}
        return state

    def _resume_step(self, step_id: str, state: Dict) -> int:
        from h2o3_tpu.persist import load_model
        n = 0
        for key, path in state.get("models", {}).items():
            mstep = state.get("model_steps", {}).get(key, "")
            # grid steps register per-model ids like '<step>_<n>'
            if mstep != step_id and not mstep.startswith(step_id + "_"):
                continue
            try:
                m = load_model(path)
                m.key = key
                dkv.put(key, "model", m)
                self.models.append(m)
                n += 1
            except Exception as e:  # noqa: BLE001
                self._log("resume", f"could not reload {key}: {e}")
        return n

    def _checkpoint_step(self, step_id: str):
        """Persist every model of the completed step + the manifest."""
        if not self.recovery_dir:
            return
        import json as _json
        import os
        from h2o3_tpu.persist import save_model
        _, man = self._recovery_paths()
        state = self._load_recovery() or {
            "config": self._config_fp(), "steps_done": [],
            "models": {}, "model_steps": {}}
        for m in self.models:
            if m.key in state["models"]:
                continue
            try:
                path = save_model(m, self.recovery_dir, force=True,
                                  filename=m.key)
                state["models"][m.key] = path
                state["model_steps"][m.key] = m.output.get("automl_step",
                                                           step_id)
            except Exception as e:  # noqa: BLE001
                self._log("resume", f"could not persist {m.key}: {e}")
        if step_id not in state["steps_done"]:
            state["steps_done"].append(step_id)
        tmp = man + ".part"
        with open(tmp, "w") as f:
            _json.dump(state, f)
        os.replace(tmp, man)

    def _train_budgeted(self, est, x, y, training_frame, validation_frame):
        """Train one step, cancelling at max_runtime_secs_per_model (the
        WorkAllocations per-step budget)."""
        cap = self.max_runtime_secs_per_model
        if not cap:
            # train(background=False) joins internally and raises on FAILED
            est.train(x=x, y=y, training_frame=training_frame,
                      validation_frame=validation_frame)
            return est.model
        est.train(x=x, y=y, training_frame=training_frame,
                  validation_frame=validation_frame, background=True)
        from h2o3_tpu import jobs as jobs_mod
        job = est.job
        while job.status in (jobs_mod.QUEUED, jobs_mod.RUNNING,
                             jobs_mod.RECOVERING):
            # the per-model budget counts RUN time, not scheduler queue
            # wait (duration_ms restarts at dispatch) — a queued step
            # must not burn its budget waiting behind an interactive
            # train
            if (job.status != jobs_mod.QUEUED
                    and job.duration_ms() / 1000.0 > cap):
                job.cancel()
            time.sleep(0.2)
        return job.join()  # raises on FAILED

    def _register(self, model, step_id: str):
        model.key = f"{self.project_name}_{step_id}"
        model.output["automl_step"] = step_id
        # family tag distinguishes xgboost from gbm (the XGBoost estimator
        # produces a GBMModel whose .algo is 'gbm')
        model.output["automl_family"] = step_id.split("_")[0].lower()
        dkv.put(model.key, "model", model)
        self.models.append(model)

    def _build_ensembles(self, x, y, training_frame):
        from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
        with_cv = [m for m in self.models
                   if m.output.get("cross_validation_holdout_predictions")
                   is not None]
        if len(with_cv) < 2:
            return
        self._rank()
        best_of_family: List = []
        seen = set()
        for m in self.models:
            fam = m.output.get("automl_family", m.algo)
            if m in with_cv and fam not in seen:
                best_of_family.append(m)
                seen.add(fam)
        for name, base in (("BestOfFamily", best_of_family), ("AllModels",
                                                              with_cv)):
            if len(base) < 2:
                continue
            try:
                se = H2OStackedEnsembleEstimator(base_models=base)
                se.train(x=x, y=y, training_frame=training_frame)
                self._register(se.model, f"StackedEnsemble_{name}")
                self._log("ensemble", f"built StackedEnsemble_{name} over "
                                      f"{len(base)} base models")
            except Exception as e:  # noqa: BLE001
                self._log("skip", f"StackedEnsemble_{name} failed: {e}")

    # -- leaderboard ----------------------------------------------------

    def _metric_name(self) -> str:
        if self.sort_metric:
            return self.sort_metric.lower()
        if not self.models:
            return "auc"
        m = self.models[0]
        if m.nclasses == 2:
            return "auc"
        if m.nclasses > 2:
            return "logloss"
        return "mean_residual_deviance"

    def _rank(self, final: bool = False):
        """Intermediate ranks (exploitation / ensemble ordering) use the
        cheap CV/valid/train source; only the FINAL rank scores the
        leaderboard frame — scoring every model on it once, not once per
        _rank call."""
        self._leaderboard = Leaderboard(
            self.models, self._metric_name(),
            self._leaderboard_frame if final else None)
        self.models = self._leaderboard.models

    @property
    def leader(self):
        return self.models[0] if self.models else None

    @property
    def leaderboard(self) -> Leaderboard:
        if self._leaderboard is None:
            self._rank(final=True)
        return self._leaderboard

    def predict(self, frame):
        if self.leader is None:
            raise RuntimeError("AutoML built no models (all steps failed "
                               "or were excluded) — see .event_log")
        if self._te_model is not None:
            frame = self._te_model.transform(frame)
        return self.leader.predict(frame)
