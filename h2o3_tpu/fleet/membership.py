"""Dynamic replica membership: the gossiped member table.

Reference: H2O-3's L1 cloud runtime — Paxos-formed membership with
heartbeats, where every node learns the cloud's shape from the beat
stream and a silent node is voted out (SURVEY §L1/§L2). The serving
fleet here is N independent serve replicas (separate JAX processes)
plus a front router; there is no shared runtime, so membership is a
TABLE the router owns and replicas maintain by pushing heartbeats:

- **join**: a replica announces itself (``POST /3/Fleet/join`` against
  a seed from ``H2O3_FLEET_SEEDS`` — no static peer list anywhere
  else). Admission hands back an *incarnation* token (the membership
  epoch at admission) that fences every later heartbeat.
- **heartbeat**: every ``H2O3_FLEET_HEARTBEAT_MS`` the replica pushes
  its incarnation, load (batcher queue fill), deployments and
  circuit-breaker states. The circuit payload is the push-gossip
  channel: an open circuit reaches the router on the NEXT beat and
  every peer on the beat after (sub-scrape shed latency — the
  scrape-pull path in serve/fleet.py is now the fallback, not the
  vehicle).
- **suspicion → eviction**: phi-style accrual over the member's
  OBSERVED beat arrivals (mean interval learned per member, seeded
  from its declared rate). One missed beat crosses the suspect
  threshold — the router sheds routed traffic immediately — and one
  more evicts: the member leaves the table, the epoch bumps, and the
  eviction callbacks fire (circuit entries for that source drop,
  telemetry stops merging its series).
- **epoch fencing**: every view change (join/leave/evict/routable
  flip) bumps a monotonic epoch. A heartbeat carrying a stale
  incarnation — the member was evicted, or this is a late packet from
  a previous life of the same member id — is rejected with
  :class:`StaleEpochError` (409 over REST, the agent re-joins) so a
  dead epoch can never resurrect a member or overwrite its successor.

The table is transport-free by design: REST handlers (api/server.py)
and in-process tests drive the same methods. All interval math is
monotonic; wall times appear only as reported join stamps.
"""
from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Member", "MemberTable", "StaleEpochError", "UnknownMemberError",
           "heartbeat_ms", "seeds",
           "ALIVE", "JOINING", "SUSPECT", "LEFT", "EVICTED"]

JOINING = "joining"      # admitted, not yet routable (warming)
ALIVE = "alive"
SUSPECT = "suspect"      # missed ~one beat: shed routed traffic
LEFT = "left"            # graceful leave (terminal, removed)
EVICTED = "evicted"      # failure-detected removal (terminal, removed)

# ln(10): the phi accrual below reports -log10 of the survival
# probability of the current beat gap under an exponential model
_LN10 = math.log(10.0)


def heartbeat_ms() -> float:
    """Fleet heartbeat period (``H2O3_FLEET_HEARTBEAT_MS``, default
    500). Malformed values fall back — membership must not break on a
    typo'd knob."""
    try:
        v = float(os.environ.get("H2O3_FLEET_HEARTBEAT_MS", "500") or 500)
        return v if v > 0 else 500.0
    except ValueError:
        return 500.0


def seeds() -> List[str]:
    """Fleet seed endpoints (``H2O3_FLEET_SEEDS`` as comma-separated
    host:port entries) — where a joining replica finds the router.
    This is the ONE place the env is read; everything downstream goes
    through the member table (fleet-peer-discipline lint rule)."""
    raw = os.environ.get("H2O3_FLEET_SEEDS", "")
    return [p.strip() for p in raw.split(",") if p.strip()]


def _bb(kind: str, member: str = "", payload: str = "",
        epoch: Optional[int] = None) -> None:
    """Flight-recorder append (ISSUE 19): membership decisions are the
    first thing a post-mortem reads, so every epoch bump lands in the
    blackbox ring. Advisory — the recorder never breaks the table."""
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.record(kind, member=member, payload=payload, epoch=epoch)
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass


class UnknownMemberError(KeyError):
    """Heartbeat/leave for a member the table does not hold (never
    joined, or already evicted) — the sender must (re)join."""


class StaleEpochError(RuntimeError):
    """A heartbeat carried an incarnation token from a dead epoch —
    a late packet from an evicted life of this member id. Rejected so
    it cannot resurrect the member or overwrite its successor; maps to
    409 over REST and the agent re-joins."""

    def __init__(self, msg: str, current_incarnation: int):
        super().__init__(msg)
        self.current_incarnation = int(current_incarnation)


@dataclass
class Member:
    member_id: str                    # e.g. "12345@host" — gossip source id
    base_url: str                     # http://host:port of its REST surface
    incarnation: int                  # table epoch at admission (the fence)
    heartbeat_s: float                # declared beat period
    state: str = JOINING
    routable: bool = False            # warm cold-start complete
    deployments: Tuple[str, ...] = ()
    load: float = 0.0                 # batcher fill fraction (0..1+)
    circuit: List[dict] = field(default_factory=list)
    # fleet-scheduler gossip (versioned payload — fleet/sched.py parses
    # it; None / malformed → the member is no-headroom/local-only)
    sched: Optional[dict] = None
    joined_wall: float = 0.0          # reported epoch stamp (not math)
    # wall-clock skew estimated from the heartbeat exchange (reported
    # beat wall minus receipt wall, seconds; includes one-way network
    # latency). None until the member reports a wall stamp. The cluster
    # timeline merge corrects and flags on this — never math here.
    skew_s: Optional[float] = None
    last_beat: float = 0.0            # monotonic
    beats: int = 0
    # observed inter-arrival window for the phi estimator
    intervals: deque = field(default_factory=lambda: deque(maxlen=16))

    def mean_interval(self) -> float:
        if len(self.intervals) >= 3:
            return max(sum(self.intervals) / len(self.intervals), 1e-3)
        return max(self.heartbeat_s, 1e-3)

    def phi(self, now: float) -> float:
        """Phi accrual: -log10 P(gap >= now-last_beat) under an
        exponential arrival model with the member's learned mean
        interval. phi ≈ 0.43 at one mean interval of silence, rising
        without bound — thresholds below are expressed in missed-beat
        multiples of the same mean for operator legibility."""
        gap = max(now - self.last_beat, 0.0)
        return gap / (self.mean_interval() * _LN10)

    def missed_beats(self, now: float) -> float:
        return max(now - self.last_beat, 0.0) / self.mean_interval()


def _suspect_after() -> float:
    """Missed-beat multiple that marks a member suspect (default 1.0 —
    one silent beat period sheds its routed traffic) plus a fixed 30%
    jitter allowance for scheduler delay."""
    try:
        v = float(os.environ.get("H2O3_FLEET_SUSPECT_BEATS", "1") or 1)
    except ValueError:
        v = 1.0
    return max(v, 0.5) + 0.3


def _evict_after() -> float:
    """Missed-beat multiple that evicts (default 2.0 — one beat beyond
    suspicion, the "one-heartbeat eviction" contract) plus the same
    jitter allowance."""
    try:
        v = float(os.environ.get("H2O3_FLEET_EVICT_BEATS", "2") or 2)
    except ValueError:
        v = 2.0
    return max(v, 1.0) + 0.3


class MemberTable:
    """The router's authoritative membership view. Thread-safe; every
    mutation that changes what a router would decide bumps ``epoch``.

    ``on_depart`` callbacks fire OUTSIDE the table lock with
    ``(member, reason)`` for every leave/eviction — serve/fleet.py
    drops the departed source's circuit entries there and telemetry
    stops merging its series (the stale-departed-series fix)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._members: Dict[str, Member] = {}
        self._epoch = 0
        self._departed: deque = deque(maxlen=32)   # (member_id, reason,
        #                                             epoch, base_url)
        self.on_depart: List[Callable[[Member, str], None]] = []

    # -- view -----------------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._mu:
            return self._epoch

    def members(self) -> List[Member]:
        with self._mu:
            return list(self._members.values())

    def get(self, member_id: str) -> Optional[Member]:
        with self._mu:
            return self._members.get(member_id)

    def live_members(self) -> List[Member]:
        """Members a router may dispatch to: routable, beating, not
        suspect. Sweeps first so the verdict reflects the beat stream
        as of NOW, not the last mutation."""
        self.sweep()
        with self._mu:
            return [m for m in self._members.values()
                    if m.state == ALIVE and m.routable]

    def view(self) -> Dict[str, object]:
        """The ``GET /3/Fleet`` body: epoch-stamped member list with
        per-member suspicion, plus recent departures (evicted members
        stay visible here — flagged, not resurrected)."""
        self.sweep()
        now = time.monotonic()
        with self._mu:
            return {
                "epoch": self._epoch,
                "heartbeat_ms": heartbeat_ms(),
                "members": [{
                    "member_id": m.member_id,
                    "base_url": m.base_url,
                    "incarnation": m.incarnation,
                    "state": m.state,
                    "routable": m.routable,
                    "deployments": list(m.deployments),
                    "load": round(m.load, 4),
                    "sched": m.sched,
                    "beats": m.beats,
                    "phi": round(m.phi(now), 3),
                    "missed_beats": round(m.missed_beats(now), 2),
                    "joined": m.joined_wall,
                    "skew_s": (round(m.skew_s, 6)
                               if m.skew_s is not None else None),
                } for m in self._members.values()],
                "departed": [{"member_id": mid, "reason": reason,
                              "epoch": ep, "base_url": url}
                             for (mid, reason, ep, url) in self._departed],
            }

    def snapshot(self) -> Dict[str, object]:
        """Transferable table state for router-tier gossip (ISSUE 20):
        everything a peer router needs to route — incarnations included,
        so an agent that fails its beat stream over to the peer keeps
        beating with its ORIGINAL token and is accepted without a
        rejoin. Beat freshness travels as ``age_s`` (seconds since the
        last observed beat): monotonic clocks don't cross processes,
        ages do, and the receiving router's phi detector resumes from
        ``now - age_s``."""
        self.sweep()
        now = time.monotonic()
        with self._mu:
            return {
                "version": 1,
                "epoch": self._epoch,
                "members": [{
                    "member_id": m.member_id,
                    "base_url": m.base_url,
                    "incarnation": m.incarnation,
                    "heartbeat_s": m.heartbeat_s,
                    "state": m.state,
                    "routable": m.routable,
                    "deployments": list(m.deployments),
                    "load": m.load,
                    "circuit": list(m.circuit),
                    "sched": m.sched,
                    "joined_wall": m.joined_wall,
                    "skew_s": m.skew_s,
                    "beats": m.beats,
                    "age_s": max(now - m.last_beat, 0.0),
                } for m in self._members.values()],
                "departed": [{"member_id": mid, "reason": reason,
                              "epoch": ep, "base_url": url}
                             for (mid, reason, ep, url) in self._departed],
            }

    def absorb(self, snap: Dict[str, object], source: str = "") -> int:
        """Merge a peer router's :meth:`snapshot` into this table —
        the router-tier gossip receive path (ISSUE 20). The membership
        rules are the table's own, applied across routers:

        - **unknown member** → adopted with its ORIGINAL incarnation
          (NOT re-minted: the agent's beat token must keep working
          against every router in the tier).
        - **higher incarnation wins** — the peer saw a rejoin this
          router missed; the record is replaced wholesale.
        - **same incarnation** → the FRESHEST beat wins (smallest
          ``age_s``); staler gossip cannot roll back load/circuit or
          resurrect routability the local beat stream already updated.
        - **lower incarnation** → fenced off, exactly like a stale
          heartbeat.

        Evictions do NOT propagate: each router runs its own detector
        on the absorbed freshness, so one router's partitioned view
        cannot evict a member every other router still hears. On any
        change the local epoch aligns to ``max(local, peer)`` so
        ring-epoch comparisons across the tier converge. Returns the
        number of member records adopted or refreshed."""
        now = time.monotonic()
        recs = snap.get("members") or []
        peer_epoch = int(snap.get("epoch", 0) or 0)
        changed = 0
        adopted: List[Tuple[str, int, int]] = []
        with self._mu:
            for rec in recs:
                try:
                    mid = str(rec["member_id"])
                    inc = int(rec["incarnation"])
                    age = max(float(rec.get("age_s", 0.0)), 0.0)
                except (KeyError, TypeError, ValueError):
                    continue            # malformed record: skip, not raise
                state = str(rec.get("state", ALIVE))
                if state in (LEFT, EVICTED):
                    continue            # terminal states never absorb
                local = self._members.get(mid)
                if local is not None and inc < local.incarnation:
                    continue            # dead-epoch gossip: fenced
                if local is not None and inc == local.incarnation \
                        and (now - local.last_beat) <= age:
                    continue            # local beat stream is fresher
                m = Member(
                    member_id=mid,
                    base_url=str(rec.get("base_url", "")).rstrip("/"),
                    incarnation=inc,
                    heartbeat_s=max(float(rec.get("heartbeat_s",
                                                  heartbeat_ms() / 1e3)),
                                    1e-3),
                    state=state if state in (JOINING, ALIVE, SUSPECT)
                    else ALIVE,
                    routable=bool(rec.get("routable", False)),
                    deployments=tuple(rec.get("deployments") or ()),
                    load=float(rec.get("load", 0.0) or 0.0),
                    circuit=list(rec.get("circuit") or []),
                    sched=rec.get("sched")
                    if isinstance(rec.get("sched"), dict) else None,
                    joined_wall=float(rec.get("joined_wall", 0.0) or 0.0),
                    skew_s=rec.get("skew_s"),
                    last_beat=now - age,
                    beats=int(rec.get("beats", 0) or 0),
                )
                if local is not None:
                    # keep the locally-learned arrival cadence: gossip
                    # refreshes state, not the phi estimator's window
                    m.intervals = local.intervals
                self._members[mid] = m
                changed += 1
                if local is None or inc != local.incarnation:
                    adopted.append((mid, inc, peer_epoch))
            if changed and peer_epoch > self._epoch:
                self._epoch = peer_epoch
            epoch = self._epoch
        for mid, inc, _ in adopted:
            _bb("member_join", mid,
                payload=f"via=gossip src={source} inc={inc}", epoch=epoch)
        if changed:
            self._publish_gauges()
        return changed

    # -- mutation -------------------------------------------------------

    def join(self, member_id: str, base_url: str, *,
             heartbeat_s: Optional[float] = None,
             deployments: Tuple[str, ...] = (),
             routable: bool = False) -> Member:
        """Admit (or re-admit) a member. A join under an id the table
        already holds REPLACES the old record with a fresh incarnation
        — the rejoin-after-eviction path — and any late heartbeat from
        the previous life is fenced off by the incarnation mismatch."""
        hb = float(heartbeat_s if heartbeat_s is not None
                   else heartbeat_ms() / 1000.0)
        with self._mu:
            self._epoch += 1
            m = Member(member_id=member_id, base_url=base_url.rstrip("/"),
                       incarnation=self._epoch, heartbeat_s=max(hb, 1e-3),
                       state=ALIVE if routable else JOINING,
                       routable=bool(routable),
                       deployments=tuple(deployments),
                       joined_wall=time.time(),
                       last_beat=time.monotonic())
            self._members[member_id] = m
        _bb("member_join", member_id,
            payload=f"inc={m.incarnation} routable={int(m.routable)}",
            epoch=m.incarnation)
        self._publish_gauges()
        return m

    def heartbeat(self, member_id: str, incarnation: int, *,
                  load: float = 0.0,
                  deployments: Optional[Tuple[str, ...]] = None,
                  circuit: Optional[List[dict]] = None,
                  routable: Optional[bool] = None,
                  sched: Optional[dict] = None,
                  wall: Optional[float] = None) -> Member:
        """Record one beat. Raises :class:`UnknownMemberError` when the
        member is not in the table (evicted / never joined — the
        sender must join) and :class:`StaleEpochError` when the
        incarnation token belongs to a dead epoch."""
        now = time.monotonic()
        with self._mu:
            m = self._members.get(member_id)
            if m is None:
                raise UnknownMemberError(
                    f"member '{member_id}' is not in the table — join "
                    f"first (evicted members must rejoin)")
            if int(incarnation) != m.incarnation:
                _bb("incarnation_fence", member_id,
                    payload=f"beat_inc={int(incarnation)} "
                            f"table_inc={m.incarnation}",
                    epoch=m.incarnation)
                raise StaleEpochError(
                    f"heartbeat from '{member_id}' carries incarnation "
                    f"{incarnation} but the table holds "
                    f"{m.incarnation} — a packet from a dead epoch "
                    f"cannot resurrect or overwrite the member",
                    current_incarnation=m.incarnation)
            if wall is not None:
                m.skew_s = float(wall) - time.time()  # h2o3-lint: allow[monotonic-durations] cross-host wall-clock skew IS the measurand (includes one-way latency; flagged, never corrected silently)
            if m.beats > 0:
                gap = max(now - m.last_beat, 1e-6)
                # a resumption gap (the member was silent past the
                # suspect line) is a STALL, not an arrival-cadence
                # sample — folding it into the phi window would
                # inflate the learned mean and desensitize the
                # detector by exactly the events it exists to catch
                if gap < m.mean_interval() * _suspect_after():
                    m.intervals.append(gap)
            m.last_beat = now
            m.beats += 1
            m.load = float(load)
            if deployments is not None:
                m.deployments = tuple(deployments)
            if circuit is not None:
                m.circuit = list(circuit)
            if sched is not None:
                m.sched = dict(sched) if isinstance(sched, dict) \
                    else None
            became_routable = False
            if routable is not None and bool(routable) != m.routable:
                m.routable = bool(routable)
                became_routable = True
            state_flip = m.state == SUSPECT
            if m.state in (SUSPECT, JOINING) and m.routable:
                m.state = ALIVE
            flipped = became_routable or state_flip
            if flipped:
                self._epoch += 1       # the routable set changed
                epoch = self._epoch
        if flipped:
            _bb("member_flip", member_id,
                payload=f"routable={int(m.routable)} state={m.state}",
                epoch=epoch)
        self._publish_gauges()
        return m

    def leave(self, member_id: str) -> bool:
        """Graceful departure; fires the depart callbacks so the
        member's circuit entries and telemetry series expire NOW, not
        after a TTL."""
        return self._remove(member_id, "left")

    def sweep(self) -> List[Member]:
        """Run the failure detector: mark suspects, evict the silent.
        Called lazily from every routing decision and view (plus the
        router's ticker) — eviction latency is bounded by the busiest
        of traffic and the ticker, never only by traffic."""
        now = time.monotonic()
        suspect_at, evict_at = _suspect_after(), _evict_after()
        evicted: List[Member] = []
        suspected: List[Tuple[Member, float, int]] = []
        flipped = False
        with self._mu:
            for m in list(self._members.values()):
                missed = m.missed_beats(now)
                if missed >= evict_at:
                    evicted.append(m)
                elif missed >= suspect_at and m.state == ALIVE:
                    m.state = SUSPECT
                    self._epoch += 1
                    suspected.append((m, missed, self._epoch))
                    flipped = True
        for m, missed, ep in suspected:
            _bb("member_suspect", m.member_id,
                payload=f"missed_beats={missed:.2f}", epoch=ep)
        for m in evicted:
            self._remove(m.member_id, "evicted",
                         expect_incarnation=m.incarnation,
                         stale_after=evict_at)
        if flipped and not evicted:
            self._publish_gauges()
        return evicted

    def _remove(self, member_id: str, reason: str,
                expect_incarnation: Optional[int] = None,
                stale_after: Optional[float] = None) -> bool:
        with self._mu:
            m = self._members.get(member_id)
            if m is None:
                return False
            if expect_incarnation is not None \
                    and m.incarnation != expect_incarnation:
                return False          # a fresh incarnation won the race
            if stale_after is not None and \
                    m.missed_beats(time.monotonic()) < stale_after:
                # freshness recheck under the lock (the PR-10 watchdog
                # race class): a beat that landed between the sweep's
                # snapshot and this removal proves the member alive —
                # evicting it anyway would churn the epoch and force a
                # needless rejoin of a healthy replica
                return False
            del self._members[member_id]
            self._epoch += 1
            m.state = EVICTED if reason == "evicted" else LEFT
            self._departed.append((member_id, reason, self._epoch,
                                   m.base_url))
            depart_epoch = self._epoch
        _bb("member_evict" if reason == "evicted" else "member_leave",
            member_id, payload=f"reason={reason} inc={m.incarnation}",
            epoch=depart_epoch)
        if reason == "evicted":
            try:
                from h2o3_tpu import telemetry
                telemetry.counter(
                    "h2o3_fleet_evictions_total",
                    help="members removed by the failure detector").inc()
            except Exception:   # noqa: BLE001 — telemetry never breaks this
                pass
        for cb in list(self.on_depart):
            try:
                cb(m, reason)
            except Exception:   # noqa: BLE001 — callbacks are advisory
                pass
        self._publish_gauges()
        return True

    def departed(self) -> List[Dict[str, object]]:
        """Recent leave/eviction records — the scrape-meta flag for
        series that stopped merging (telemetry peers_evicted)."""
        with self._mu:
            return [{"member_id": mid, "reason": reason, "epoch": ep,
                     "base_url": url}
                    for (mid, reason, ep, url) in self._departed]

    def reset(self) -> None:
        """Drop everything (tests)."""
        with self._mu:
            self._members.clear()
            self._departed.clear()
            self._epoch = 0
        self._publish_gauges()

    # -- telemetry ------------------------------------------------------

    def _publish_gauges(self) -> None:
        try:
            from h2o3_tpu import telemetry
            with self._mu:
                counts = {ALIVE: 0, JOINING: 0, SUSPECT: 0}
                for m in self._members.values():
                    counts[m.state] = counts.get(m.state, 0) + 1
                epoch = self._epoch
            for st, c in counts.items():
                telemetry.gauge("h2o3_fleet_members", {"state": st},
                                help="fleet member count by state").set(c)
            telemetry.gauge("h2o3_fleet_epoch",
                            help="membership view epoch").set(epoch)
        except Exception:   # noqa: BLE001 — telemetry never breaks the table
            pass
