"""Client-side key affinity: zero-hop dispatch (ISSUE 20).

The paper's reference computes key→home-node hashing *on every node*
(``water/Key.java:91``) — clients land one hop from their data because
the hash is universal, not because a proxy forwards them. This module
gives our clients the same property over REST: ``GET /3/Fleet/ring``
exposes the router tier's consistent-hash view (member ids, virtual
point count, membership epoch), the client rebuilds the EXACT ring
(:class:`~h2o3_tpu.fleet.router.ConsistentHashRing` — same blake2b
scheme, same virtual-point layout, bit-identical homes) and dispatches
scoring straight to the home replica's own ``/3/Predictions`` surface,
skipping the router proxy hop entirely.

Staleness is self-correcting without polling: every scoring response
from a fleet replica carries ``X-H2O3-Fleet-Epoch`` (the epoch the
replica last heard from a router). When it disagrees with the epoch the
client's ring was cut under, the client refreshes the ring before the
next request — the answered request is still valid (the replica served
it), so the fast path never pays a blocking round trip to discover
churn. Hard failures (connect refused, 5xx, an empty ring) fall back to
ANY router — the proxy path with its own failover — so affinity is an
optimization, never a correctness dependency.

``zero_hop_ratio()`` reports the fraction of requests that went direct
— the bench's ``fleet.zero_hop_ratio`` metric (steady-state ≥ 0.9 is
the acceptance bar).
"""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence

from h2o3_tpu.fleet.router import ConsistentHashRing, _norm_url

__all__ = ["RingView", "AffinityClient"]


class RingView:
    """One epoch's ring as the client sees it: the home() verdicts are
    bit-identical to the router's (same member-id set, same point
    count, same hash)."""

    def __init__(self, epoch: int, points: int,
                 members: Sequence[dict]):
        self.epoch = int(epoch)
        self.points = int(points)
        self.base_urls: Dict[str, str] = {
            str(m["member_id"]): str(m.get("base_url", "")).rstrip("/")
            for m in members}
        self.ring = ConsistentHashRing(sorted(self.base_urls),
                                       points=self.points)

    def home(self, key: str) -> Optional[str]:
        """The home member id for a routing key (None on an empty
        ring)."""
        return self.ring.home(key)

    def home_url(self, key: str) -> Optional[str]:
        mid = self.home(key)
        return self.base_urls.get(mid) if mid else None


class AffinityClient:
    """Key-affine scoring client: hash client-side, dispatch straight
    to the home replica, fall back to any router on epoch mismatch or
    connect failure. Thread-safe; one instance per fleet."""

    def __init__(self, router_urls, points: Optional[int] = None,
                 timeout_s: float = 10.0):
        if isinstance(router_urls, str):
            router_urls = [router_urls]
        self._routers: List[str] = []
        for u in router_urls:
            nu = _norm_url(u)
            if nu and nu not in self._routers:
                self._routers.append(nu)
        if not self._routers:
            raise ValueError("AffinityClient needs at least one router "
                             "url")
        self._points = points
        self.timeout_s = float(timeout_s)
        self._mu = threading.Lock()
        self._view: Optional[RingView] = None
        self._router_idx = 0
        self._stale = False
        # dispatch accounting: zero_hop = answered by the home replica
        # directly; routed = fell back through a router proxy
        self.zero_hop = 0
        self.routed = 0

    # -- ring maintenance ------------------------------------------------

    def refresh(self) -> RingView:
        """Fetch the ring from the first answering router. Raises when
        no router answers — the caller still has the stale view (if
        any) and the routed fallback."""
        last: Optional[BaseException] = None
        for _ in range(len(self._routers)):
            url = self._routers[self._router_idx % len(self._routers)]
            try:
                body = self._get_json(f"{url}/3/Fleet/ring")
                view = RingView(body.get("epoch", 0),
                                self._points or body.get("points", 64),
                                body.get("members") or [])
                with self._mu:
                    self._view = view
                    self._stale = False
                return view
            except Exception as e:   # noqa: BLE001 — try the next router
                last = e
                self._router_idx += 1
        raise last if last is not None else RuntimeError(
            "no router answered /3/Fleet/ring")

    def view(self) -> Optional[RingView]:
        with self._mu:
            return self._view

    def _current_view(self) -> Optional[RingView]:
        with self._mu:
            view, stale = self._view, self._stale
        if view is None or stale:
            try:
                return self.refresh()
            except Exception:   # noqa: BLE001 — routed fallback remains
                return view
        return view

    def _note_epoch(self, headers, view: RingView) -> None:
        """An answering replica reported a different fleet epoch than
        the ring we hashed under: mark the view stale so the NEXT
        request refreshes (this one already got its valid answer)."""
        ep = headers.get("X-H2O3-Fleet-Epoch")
        if ep is None:
            return
        try:
            if int(ep) != view.epoch:
                with self._mu:
                    self._stale = True
        except ValueError:
            pass

    # -- scoring ---------------------------------------------------------

    @staticmethod
    def routing_key(model: str, key: Optional[str]) -> str:
        """The router's routing-key spelling, verbatim (parity is
        asserted by tests over 10k keys)."""
        return f"{model}|{key}" if key else model

    def predict_rows(self, model: str, rows: Sequence[dict], *,
                     key: Optional[str] = None,
                     timeout_ms: Optional[float] = None,
                     fmt: str = "rows",
                     lane: Optional[str] = None):
        """Score rows zero-hop when possible. Returns the replica's
        response body (dict for ``rows``/``columnar``, NDJSON str for
        ``stream``). Falls back to the routed path on any direct-path
        failure — affinity never turns a servable request into an
        error the proxy path would have absorbed."""
        timeout_s = (float(timeout_ms) / 1000.0
                     if timeout_ms is not None else self.timeout_s)
        view = self._current_view()
        if view is not None:
            url = view.home_url(self.routing_key(model, key))
            if url:
                try:
                    out = self._predict_direct(url, model, rows, fmt,
                                               lane, timeout_s, view)
                    with self._mu:
                        self.zero_hop += 1
                    return out
                except urllib.error.HTTPError as e:
                    # the replica ANSWERED: only retryable-by-another-
                    # replica verdicts (shed 503 / not-deployed 404)
                    # reroute; application errors surface as-is
                    if e.code not in (503, 404):
                        raise
                    with self._mu:
                        self._stale = True
                except Exception:   # noqa: BLE001 — replica gone: reroute
                    with self._mu:
                        self._stale = True
        return self._predict_routed(model, rows, key, fmt, lane,
                                    timeout_s)

    def _predict_direct(self, base_url: str, model: str,
                        rows: Sequence[dict], fmt: str,
                        lane: Optional[str], timeout_s: float,
                        view: RingView):
        url = (f"{base_url}/3/Predictions/models/"
               f"{urllib.parse.quote(model)}/rows")
        if fmt != "rows":
            url += f"?format={urllib.parse.quote(fmt)}"
        body, headers = self._post(url, {"rows": list(rows)}, lane,
                                   timeout_s)
        self._note_epoch(headers, view)
        return body

    def _predict_routed(self, model: str, rows: Sequence[dict],
                        key: Optional[str], fmt: str,
                        lane: Optional[str], timeout_s: float):
        payload: Dict[str, object] = {"rows": list(rows)}
        if key is not None:
            payload["key"] = key
        if fmt != "rows":
            payload["format"] = fmt
        last: Optional[BaseException] = None
        for _ in range(len(self._routers)):
            url = self._routers[self._router_idx % len(self._routers)]
            try:
                body, _hdrs = self._post(
                    f"{url}/3/Fleet/models/"
                    f"{urllib.parse.quote(model)}/rows",
                    payload, lane, timeout_s)
                with self._mu:
                    self.routed += 1
                    self._stale = True   # next request re-pins the ring
                return body
            except urllib.error.HTTPError:
                with self._mu:
                    self.routed += 1
                raise                  # the router's verdict is final
            except Exception as e:   # noqa: BLE001 — this router is down
                last = e
                self._router_idx += 1
        raise last if last is not None else RuntimeError(
            "no router reachable for routed dispatch")

    # -- accounting ------------------------------------------------------

    def zero_hop_ratio(self) -> float:
        with self._mu:
            total = self.zero_hop + self.routed
            return (self.zero_hop / total) if total else 0.0

    # -- transport -------------------------------------------------------

    def _get_json(self, url: str) -> dict:
        """attempts=1: the client's router ROTATION is the retry."""
        from h2o3_tpu import resilience

        def _call():
            with urllib.request.urlopen(url,
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read().decode())

        return resilience.retry_transient(
            _call, site="fleet.affinity", attempts=1)

    @staticmethod
    def _post(url: str, payload: dict, lane: Optional[str],
              timeout_s: float):
        """attempts=1: the direct→routed fallback (and the routed
        path's own rotation) IS the retry policy — a same-replica
        retry would double the cost of a sick home."""
        from h2o3_tpu import resilience
        headers = {"Content-Type": "application/json"}
        if lane:
            headers["X-H2O3-Lane"] = lane
        data = json.dumps(payload).encode()

        def _call():
            req = urllib.request.Request(url, data=data, method="POST",
                                         headers=headers)
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                raw = r.read().decode()
                ctype = r.headers.get("Content-Type") or ""
                if "json" in ctype and not ctype.startswith(
                        "application/x-ndjson"):
                    return json.loads(raw), r.headers
                return raw, r.headers

        return resilience.retry_transient(
            _call, site="fleet.affinity", attempts=1)
