"""h2o3_tpu.fleet — the serving fleet's front door.

Dynamic replica membership (join/leave/heartbeat against an
epoch-numbered member table, phi-style suspicion, one-heartbeat
eviction) plus a consistent-hash front router with least-loaded
fallback, single failover and warm cold-start. REST surface:
``GET/POST /3/Fleet/*`` (api/server.py).

Process-wide singletons: a process that answers ``/3/Fleet/join`` IS a
router (``router()`` lazily owns the member table); a serve replica
runs one ``FleetAgent``. Both are optional — a process that never
touches the fleet pays nothing.
"""
from __future__ import annotations

import threading
from typing import Optional

from h2o3_tpu.fleet.affinity import AffinityClient, RingView
from h2o3_tpu.fleet.agent import FleetAgent
from h2o3_tpu.fleet.membership import (Member, MemberTable,
                                       StaleEpochError,
                                       UnknownMemberError, heartbeat_ms,
                                       seeds)
from h2o3_tpu.fleet.router import (ConsistentHashRing,
                                   FleetRouter,
                                   FleetUnavailableError,
                                   ReplicaDispatchError, RouterError,
                                   RouterTier)

__all__ = ["AffinityClient", "ConsistentHashRing", "FleetAgent",
           "FleetRouter", "RingView",
           "FleetUnavailableError", "Member", "MemberTable",
           "ReplicaDispatchError", "RouterError", "RouterTier",
           "StaleEpochError",
           "UnknownMemberError", "active_router", "heartbeat_ms",
           "router", "reset", "seeds", "start_router_tier"]

_ROUTER: Optional[FleetRouter] = None
_MU = threading.Lock()


def router() -> FleetRouter:
    """This process's front router (created on first use — the
    /3/Fleet REST handlers and the bench share it). Wires the member
    table's departure callbacks into the serve circuit store and the
    telemetry peer source exactly once."""
    global _ROUTER
    with _MU:
        if _ROUTER is None:
            r = FleetRouter()
            _wire(r)
            r.start_ticker()
            _ROUTER = r
        return _ROUTER


def active_router() -> Optional[FleetRouter]:
    """The process router if one exists — NEVER creates one. The fleet
    scheduler's placement path reads membership through this so a
    replica that merely submits trains does not become a router."""
    with _MU:
        return _ROUTER


def _wire(r: FleetRouter) -> None:
    # churn hygiene (ISSUE 13 satellites): a departed member's circuit
    # gossip drops NOW (not after its TTL) and the telemetry cluster
    # scrape stops merging its series, flagging it in the scrape meta
    from h2o3_tpu.serve import fleet as serve_fleet

    def _on_depart(member, reason):
        serve_fleet.drop_source(member.member_id)

    r.table.on_depart.append(_on_depart)
    # fleet scheduler (ISSUE 18): an evicted member's RUNNING
    # checkpointing trains re-queue fleet-wide from their manifests,
    # and the router process places its own submissions fleet-wide too
    from h2o3_tpu.fleet import sched as fleet_sched

    r.table.on_depart.append(fleet_sched.on_member_departed)
    fleet_sched.install_hooks()
    from h2o3_tpu.telemetry import snapshot as telesnap

    def _peer_view():
        live = [m.base_url for m in r.table.members()
                if m.state in ("alive", "suspect")]
        return live, r.table.departed()

    telesnap.PEER_SOURCE = _peer_view


def start_router_tier(self_url: str,
                      peers: Optional[list] = None,
                      warm_boot: bool = True) -> RouterTier:
    """Join this process's router to the router tier (ISSUE 20): peers
    default to ``H2O3_FLEET_SEEDS`` minus ``self_url``. Warm-boots the
    member table + deployment registry from the first answering peer
    (or the disk snapshot) BEFORE the gossip loop starts, so a bounced
    router's first routed request hits a populated table."""
    r = router()
    tier = r.tier
    if tier is None:
        tier = RouterTier(r, self_url, peers=peers)
    if warm_boot:
        tier.warm_boot()
    tier.start()
    return tier


def reset() -> None:
    """Tear down the process router (tests)."""
    global _ROUTER
    with _MU:
        r = _ROUTER
        _ROUTER = None
    if r is not None:
        r.stop_ticker()
        if r.tier is not None:
            r.tier.stop()
            r.tier = None
        r.table.reset()
        from h2o3_tpu.telemetry import snapshot as telesnap
        telesnap.PEER_SOURCE = None
    from h2o3_tpu.fleet import sched as fleet_sched
    fleet_sched.reset()
