"""Replica-side fleet agent: join, warm up, heartbeat, leave.

The reference's every-node heartbeat thread (SURVEY §L1) — each serve
replica runs one :class:`FleetAgent` that:

1. **joins** the router found at the first reachable
   ``H2O3_FLEET_SEEDS`` entry (``POST /3/Fleet/join``), admitted as
   ``joining`` — NOT routable;
2. **pre-warms** before taking traffic (warm cold-start): the join
   response carries the fleet's registry snapshot, and the agent
   deploys every model it can resolve with ``warm=True`` — compiles
   land in the shared persistent compile cache
   (``H2O3_COMPILE_CACHE_DIR``, cluster_boot.setup_compilation_cache),
   so a restarted replica's warmup is a cache read, and the first
   ROUTED request compiles zero XLA modules;
3. **heartbeats** every ``H2O3_FLEET_HEARTBEAT_MS``: incarnation token
   (epoch fence), batcher load, deployment list, and this replica's
   circuit-breaker states (``serve.circuit_states()``) — the push
   gossip channel. The response piggybacks every PEER's circuit state,
   which feeds ``serve.fleet.observe_peer_states`` so an open circuit
   anywhere sheds load here within two beats (sub-scrape latency; the
   telemetry-scrape pull in serve/fleet.py is now the fallback);
4. on a 409 (stale incarnation — this agent was evicted, e.g. a long
   GC pause or network partition healed) it **re-joins** with a fresh
   incarnation rather than beating into the void;
5. **leaves** gracefully on ``stop()`` so the router evicts nothing
   and peers expire this source's gossip immediately.

All agent→router HTTP rides ``resilience.retry_transient`` with an
explicit deadline (fleet-peer-discipline).
"""
from __future__ import annotations

import json
import os
import socket
import threading
import urllib.request
from typing import Dict, List, Optional

from h2o3_tpu.fleet.membership import heartbeat_ms, seeds

__all__ = ["FleetAgent"]


def _default_member_id() -> str:
    try:
        host = socket.gethostname()
    except OSError:
        host = "?"
    return f"{os.getpid()}@{host}"


def _post_json(url: str, payload: dict, *, timeout_s: float,
               site: str, attempts: int = 3) -> dict:
    """One control-plane POST behind the shared transient-retry policy.
    The socket timeout doubles as the per-attempt deadline; the whole
    call is bounded by retry_transient's backoff schedule."""
    from h2o3_tpu import resilience
    data = json.dumps(payload).encode()

    def _call():
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read().decode())

    return resilience.retry_transient(_call, site=site, attempts=attempts)


class FleetAgent:
    def __init__(self, base_url: str, *,
                 router_url: Optional[str] = None,
                 member_id: Optional[str] = None,
                 heartbeat_s: Optional[float] = None,
                 prewarm: bool = True):
        self.base_url = base_url.rstrip("/")
        self.member_id = member_id or _default_member_id()
        self.heartbeat_s = float(heartbeat_s if heartbeat_s is not None
                                 else heartbeat_ms() / 1000.0)
        self._router_url = (router_url.rstrip("/") if router_url
                            else None)
        # router-tier rotation (ISSUE 20): index into _router_urls();
        # a connect-class beat failure advances it so the beat stream
        # fails over to a peer router carrying the SAME incarnation
        # token (peer routers absorb tokens via gossip, so no rejoin)
        self._url_idx = 0
        self.prewarm = bool(prewarm)
        self.incarnation: Optional[int] = None
        self.routable = False
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- control plane ---------------------------------------------------

    def _router_urls(self) -> List[str]:
        """Every router this agent may talk to: the explicit url (if
        any) followed by all H2O3_FLEET_SEEDS entries, deduped (the
        only env-sourced peer read lives in membership.seeds). With a
        router TIER behind the seeds, any entry accepts this agent's
        beats — the tier gossips incarnations, so failing the stream
        over needs no rejoin."""
        urls: List[str] = []
        if self._router_url:
            urls.append(self._router_url)
        for s in seeds():
            u = s if s.startswith(("http://", "https://")) \
                else f"http://{s}"
            u = u.rstrip("/")
            if u not in urls:
                urls.append(u)
        if not urls:
            raise RuntimeError(
                "no fleet router configured — pass router_url or set "
                "H2O3_FLEET_SEEDS=host:port[,host:port]")
        return urls

    def router_url(self) -> str:
        """The CURRENT router endpoint (rotation advances on connect
        failure — see :meth:`_rotate_router`)."""
        urls = self._router_urls()
        return urls[self._url_idx % len(urls)]

    def _rotate_router(self, reason: str) -> None:
        """Advance the beat stream to the next router in the tier.
        A no-op with a single configured router; records a
        ``router_handoff`` flight-recorder event otherwise — the
        post-mortem's 'which front door heard this replica when'."""
        urls = self._router_urls()
        if len(urls) < 2:
            return
        old = urls[self._url_idx % len(urls)]
        self._url_idx = (self._url_idx + 1) % len(urls)
        new = urls[self._url_idx % len(urls)]
        try:
            from h2o3_tpu.telemetry import blackbox
            blackbox.record("router_handoff", self.member_id,
                            payload=f"from={old} to={new} "
                                    f"reason={reason}")
        except Exception:   # noqa: BLE001 — recorder is advisory
            pass

    @staticmethod
    def _note_epoch(out: dict) -> None:
        """Stamp the fleet epoch from a join/heartbeat response into
        serve.fleet so scoring responses can carry it
        (``X-H2O3-Fleet-Epoch`` — the client-affinity staleness
        signal)."""
        try:
            from h2o3_tpu.serve import fleet as serve_fleet
            ep = out.get("epoch")
            if ep is not None:
                serve_fleet.note_fleet_epoch(int(ep))
        except Exception:   # noqa: BLE001 — the header is advisory
            pass

    def join(self) -> dict:
        """Announce this replica; returns the join response (epoch,
        incarnation, registry snapshot). Deployment list reflects what
        is ALREADY deployed locally — prewarm() below may grow it
        before the routable beat."""
        from h2o3_tpu import serve
        body = {
            "member_id": self.member_id,
            "base_url": self.base_url,
            "heartbeat_ms": self.heartbeat_s * 1000.0,
            "deployments": [d.key for d in serve.deployments()],
            "routable": False,
        }
        urls = self._router_urls()
        out = None
        last: Optional[BaseException] = None
        for i in range(len(urls)):
            url = urls[self._url_idx % len(urls)]
            try:
                out = _post_json(f"{url}/3/Fleet/join", body,
                                 timeout_s=max(self.heartbeat_s * 4, 2.0),
                                 site="fleet.join",
                                 attempts=1 if len(urls) > 1 else 3)
                break
            except Exception as e:   # noqa: BLE001 — try the next router
                last = e
                if i < len(urls) - 1:
                    self._rotate_router(f"join: {type(e).__name__}")
        if out is None:
            raise last if last is not None else RuntimeError(
                "fleet join failed with no router reachable")
        self.incarnation = int(out.get("incarnation", 0))
        self._note_epoch(out)
        try:
            # stamp the flight recorder's ambient identity: every event
            # this replica appends from now on carries the admitted
            # epoch + incarnation (the merge's causal fence)
            from h2o3_tpu.telemetry import blackbox
            blackbox.set_identity(epoch=int(out.get("epoch", 0) or 0),
                                  incarnation=self.incarnation)
        except Exception:   # noqa: BLE001 — flight recorder is advisory
            pass
        return out

    def _prewarm(self, snapshot: Optional[dict]) -> dict:
        """Warm cold-start: deploy everything in the fleet registry
        snapshot that this process can resolve, compile-warm, BEFORE
        the routable beat. Never raises — a model this replica cannot
        resolve is reported, not fatal (the router simply won't route
        that model here, via the heartbeat's deployment list)."""
        from h2o3_tpu import serve
        if not snapshot:
            return {"deployed": [], "skipped": []}
        try:
            return serve.prewarm_from_snapshot(snapshot)
        except Exception as e:   # noqa: BLE001 — warmup is best-effort
            self.last_error = f"prewarm: {e!r}"
            return {"deployed": [], "skipped": [], "error": repr(e)}

    def _beat_payload(self) -> dict:
        import time
        from h2o3_tpu import serve
        deps = serve.deployments()
        load = max((d.batcher.load_factor for d in deps), default=0.0)
        payload = {
            "member_id": self.member_id,
            "incarnation": self.incarnation,
            "load": round(load, 4),
            "deployments": [d.key for d in deps],
            "circuit": serve.circuit_states(),
            "routable": self.routable,
            # the heartbeat exchange doubles as the cluster timeline's
            # skew estimator: the router subtracts its receipt wall
            # clock from this stamp (ISSUE 19 flight recorder)
            "wall": time.time(),
        }
        try:
            # fleet-scheduler gossip: admission headroom, per-class
            # queue depths, running count (versioned; a beat without it
            # just marks this replica local-only — never fails the beat)
            from h2o3_tpu.fleet import sched as fleet_sched
            payload["sched"] = fleet_sched.local_sched_payload()
        except Exception as e:   # noqa: BLE001 — beats outrank gossip
            self.last_error = f"sched payload: {e!r}"
        return payload

    def beat_once(self) -> bool:
        """One heartbeat; ingests the response's piggybacked peer
        circuit gossip. Returns False when the beat could not be
        delivered (the loop just tries again next tick) and re-joins
        on an incarnation fence rejection."""
        import urllib.error
        from h2o3_tpu.serve import fleet as serve_fleet
        try:
            out = _post_json(
                f"{self.router_url()}/3/Fleet/heartbeat",
                self._beat_payload(),
                timeout_s=max(self.heartbeat_s * 2, 1.0),
                site="fleet.heartbeat", attempts=1)
        except urllib.error.HTTPError as e:
            if e.code in (404, 409):
                # evicted (or router restarted): rejoin with a fresh
                # incarnation — a dead epoch's token must not be
                # reused. Returns False either way: join admits this
                # member as NOT routable, so the routable beat has not
                # been delivered yet (start()'s wait contract) — the
                # next tick's beat carries it
                self.last_error = f"heartbeat fenced ({e.code}); rejoining"
                try:
                    from h2o3_tpu.telemetry import blackbox
                    blackbox.record("incarnation_fence", self.member_id,
                                    payload=f"http={e.code} rejoining")
                except Exception:   # noqa: BLE001 — recorder is advisory
                    pass
                try:
                    self.join()
                except Exception as e2:   # noqa: BLE001 — next tick retries
                    self.last_error = f"rejoin failed: {e2!r}"
                return False
            self.last_error = f"heartbeat: {e!r}"
            return False
        except Exception as e:   # noqa: BLE001 — router may be restarting
            # connect-class failure: this front door is gone (or
            # bouncing) — fail the beat stream over to the next router
            # in the tier; our incarnation token travels via gossip so
            # the peer accepts the next beat without a rejoin
            self.last_error = f"heartbeat: {e!r}"
            self._rotate_router(f"beat: {type(e).__name__}")
            return False
        # push gossip: every peer's circuit states, grouped by source —
        # an open circuit on any replica sheds load HERE now, without
        # waiting for a telemetry scrape
        gossip: Dict[str, List[dict]] = {}
        for ent in out.get("gossip") or []:
            src = str(ent.get("source") or "?")
            gossip.setdefault(src, []).append(ent)
        for src, states in gossip.items():
            serve_fleet.observe_peer_states(
                states, src, self_process=(src == self.member_id))
        # fleet-scheduler gossip: the router's merged placement view
        # rides the same response — every replica sees every other
        # replica's headroom at heartbeat latency
        fs = out.get("fleet_sched")
        if fs is not None:
            from h2o3_tpu.fleet import sched as fleet_sched
            fleet_sched.observe_fleet_view(fs, self.member_id)
        self._note_epoch(out)
        return True

    # -- lifecycle -------------------------------------------------------

    def start(self, wait_routable_s: float = 0.0) -> "FleetAgent":
        """Join → prewarm → mark routable → heartbeat loop (daemon
        thread). ``wait_routable_s`` > 0 blocks until the routable
        beat was delivered (tests / scripted bring-up)."""
        out = self.join()
        if self.prewarm:
            self._prewarm(out.get("registry"))
        # fleet scheduler: this process is now addressable by the fleet
        # — identify it and route local submissions/preemptions through
        # the placement hooks (no-ops until a fleet view arrives)
        from h2o3_tpu.fleet import sched as fleet_sched
        fleet_sched.set_local_member(self.member_id, self.base_url)
        fleet_sched.install_hooks()
        self.routable = True
        routable_sent = threading.Event()

        def _loop():
            while not self._stop.is_set():
                if self.beat_once():
                    routable_sent.set()
                self._stop.wait(self.heartbeat_s)

        self._thread = threading.Thread(target=_loop, daemon=True, name="fleet-agent")  # h2o3-lint: allow[sched-discipline] the heartbeat loop is the fleet's liveness signal — it must never queue behind training admission
        self._thread.start()
        if wait_routable_s > 0:
            routable_sent.wait(wait_routable_s)
        return self

    def stop(self, leave: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(max(self.heartbeat_s * 4, 2.0))
        if leave and self.incarnation is not None:
            try:
                _post_json(f"{self.router_url()}/3/Fleet/leave",
                           {"member_id": self.member_id,
                            "incarnation": self.incarnation},
                           timeout_s=2.0, site="fleet.leave", attempts=1)
            except Exception as e:   # noqa: BLE001 — the detector will evict
                self.last_error = f"leave: {e!r}"
