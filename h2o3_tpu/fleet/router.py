"""The fleet front door: consistent-hash routing over live members.

Reference: H2O-3's L2 key-hashed dispatch — every key has a home node
computed from the cloud's member list, and work for that key lands
there (SURVEY §L1/§L2). Here the router owns a
:class:`~h2o3_tpu.fleet.membership.MemberTable` and dispatches scoring
requests over the live, routable members:

- **home replica**: consistent hashing (a hash ring with
  ``H2O3_FLEET_RING_POINTS`` virtual points per member, default 64) of
  the request's routing key — membership change moves only ~1/N of the
  key space, so replica-local caches and batch coalescing stay warm
  across churn.
- **least-loaded fallback**: a request whose home replica is not live,
  does not serve the model, or reports an open circuit for it falls
  back to the least-loaded live member that can take it.
- **single failover**: a dispatch that fails in a *provably
  not-executed* way (connect refused/reset, a shed 503) retries ONCE
  on the next live replica, under the request's remaining deadline.
  Failure modes where the request may have executed (mid-response
  errors, deadline blowouts) are NOT retried — scoring is idempotent
  but the caller's latency budget is not, and proxied mutations
  (deploy/undeploy) never retry at all.
- **load shedding**: an empty live set, or a live set whose every
  member reports a full batcher queue, sheds with 503 + ``Retry-After``
  (one heartbeat interval — the soonest membership can change).

Every routing decision pins the membership ``epoch`` it was made
under; the failover path re-reads it so a decision from a dead epoch
is never retried blindly (the fleet-peer-discipline lint rule
machine-checks both).

Cross-replica HTTP goes through ``resilience.retry_transient`` with an
explicit per-call deadline — the same one policy every other network
seam in the repo uses.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from h2o3_tpu.fleet.membership import (ALIVE, Member, MemberTable,
                                       heartbeat_ms, seeds)
from h2o3_tpu.serve import lanes as lanes_mod

__all__ = ["ConsistentHashRing", "FleetRouter", "RouterTier",
           "RouterError", "FleetUnavailableError", "ReplicaDispatchError"]


def _bb(kind: str, member: str = "", payload: str = "",
        epoch: Optional[int] = None) -> None:
    """Flight-recorder append for the router plane (ISSUE 20):
    tier membership moves, ring publications and lane sheds are what a
    front-door post-mortem reads first. Advisory — the recorder never
    breaks routing."""
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.record(kind, member=member, payload=payload, epoch=epoch)
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass


class RouterError(RuntimeError):
    http_status = 500


class FleetUnavailableError(RouterError):
    """No live replica can absorb this request: empty live set, every
    queue full, or failover exhausted. 503 + Retry-After."""
    http_status = 503

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class ReplicaDispatchError(RouterError):
    """The chosen replica answered with an application error (the
    request DID execute there, or may have) — surfaced as-is, never
    retried onto another replica."""

    def __init__(self, msg: str, http_status: int = 500,
                 body: Optional[dict] = None):
        super().__init__(msg)
        self.http_status = int(http_status)
        self.body = body or {}


def _ring_points() -> int:
    try:
        v = int(os.environ.get("H2O3_FLEET_RING_POINTS", "64") or 64)
        return v if v > 0 else 64
    except ValueError:
        return 64


def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.blake2b(
        s.encode(), digest_size=8).digest(), "big")


class ConsistentHashRing:
    """Classic virtual-node hash ring. Stability contract (asserted by
    tests/test_fleet_router.py): removing one of N members re-homes
    only the removed member's ~1/N key share; every other key keeps
    its home."""

    def __init__(self, member_ids: Sequence[str],
                 points: Optional[int] = None):
        self.points = points or _ring_points()
        ring: List[Tuple[int, str]] = []
        for mid in member_ids:
            for i in range(self.points):
                ring.append((_hash64(f"{mid}#{i}"), mid))
        ring.sort()
        self._hashes = [h for h, _ in ring]
        self._owners = [m for _, m in ring]

    def home(self, key: str) -> Optional[str]:
        if not self._hashes:
            return None
        i = bisect_left(self._hashes, _hash64(key))
        if i == len(self._hashes):
            i = 0
        return self._owners[i]


class FleetRouter:
    """One per front-door process. Owns the member table, keeps a hash
    ring per membership epoch, and proxies scoring to the chosen
    replica. ``dispatch`` is injectable for transport-free tests; the
    default POSTs to the member's REST surface."""

    def __init__(self, table: Optional[MemberTable] = None,
                 dispatch: Optional[Callable] = None):
        self.table = table if table is not None else MemberTable()
        self._dispatch = dispatch or self._http_dispatch
        self._ring_mu = threading.Lock()
        self._ring_epoch = -1
        self._ring: Optional[ConsistentHashRing] = None
        self._ticker: Optional[threading.Timer] = None
        self._ticking = False
        # last ring epoch served to a client (``GET /3/Fleet/ring``) —
        # a new epoch's first publication is a flight-recorder event
        self._published_epoch = -1
        # the router tier this process belongs to (None = solo router)
        self.tier: Optional["RouterTier"] = None

    # -- failure-detector ticker ---------------------------------------

    def start_ticker(self) -> None:
        """Sweep the member table once per heartbeat interval so a dead
        replica is evicted even when no traffic is flowing (routing
        decisions sweep lazily; idle fleets need the clock)."""
        self._ticking = True
        self._tick()

    def _tick(self) -> None:
        if not self._ticking:
            return
        try:
            self.table.sweep()
            # fleet scheduler: queued local work drains to members with
            # headroom even when no join/gossip event triggers it
            from h2o3_tpu.fleet import sched as fleet_sched
            fleet_sched.router_tick(self.table)
        finally:
            t = threading.Timer(heartbeat_ms() / 1000.0, self._tick)
            t.daemon = True
            self._ticker = t
            t.start()

    def stop_ticker(self) -> None:
        self._ticking = False
        t = self._ticker
        if t is not None:
            t.cancel()

    # -- ring -----------------------------------------------------------

    def _ring_for(self, epoch: int,  # h2o3-lint: allow[blackbox-discipline] ring cache memoization, not a fence move — the epoch was advanced (and recorded) by the member table; first publication records ring_published
                  members: Sequence[Member]) -> ConsistentHashRing:
        with self._ring_mu:
            if self._ring is None or self._ring_epoch != epoch:
                self._ring = ConsistentHashRing(
                    sorted(m.member_id for m in members))
                self._ring_epoch = epoch
            return self._ring

    def ring_snapshot(self) -> Dict[str, object]:
        """The ``GET /3/Fleet/ring`` body (ISSUE 20): everything a
        client needs to compute key→home **bit-identically** to this
        router — the live routable member set (sorted ids + base urls),
        the virtual-point count, and the membership epoch the view was
        cut under. A client hashes with the same blake2b scheme
        (:class:`ConsistentHashRing`), dispatches straight to the home
        replica, and refreshes when the epoch it pinned goes stale."""
        self.table.sweep()
        epoch = self.table.epoch
        live = sorted(self.table.live_members(),
                      key=lambda m: m.member_id)
        snap = {
            "epoch": epoch,
            "points": _ring_points(),
            "heartbeat_ms": heartbeat_ms(),
            "members": [{"member_id": m.member_id,
                         "base_url": m.base_url,
                         "deployments": list(m.deployments)}
                        for m in live],
        }
        if epoch != self._published_epoch:
            self._published_epoch = epoch
            _bb("ring_published", payload=f"members={len(live)} "
                                          f"points={snap['points']}",
                epoch=epoch)
        return snap

    # -- routing decisions ----------------------------------------------

    @staticmethod
    def _serves(m: Member, model: str) -> bool:
        """A member with an empty deployment list is assumed universal
        (a hand-built table, or a replica still resolving models) —
        the dispatch-side 404 failover is the backstop if it turns
        out to hold nothing. One that lists deployments must list the
        model. An open piggybacked circuit for the model disqualifies
        — the replica itself would only 503."""
        if m.deployments and model not in m.deployments:
            return False
        for c in m.circuit:
            if c.get("model") == model and c.get("state") == "open":
                return False
        return True

    def route(self, model: str, key: Optional[str] = None,
              exclude: Sequence[str] = (),
              lane: Optional[str] = None) -> Tuple[Member, int]:
        """Pick the target replica for one request: the routing key's
        home on the consistent-hash ring when it is eligible, else the
        least-loaded eligible live member. Returns ``(member, epoch)``
        — the epoch the decision was made under fences the failover
        path against deciding from a dead view.

        ``lane`` (ISSUE 20) caps the load a non-interactive request may
        route into: a bulk request only sees replicas whose reported
        queue fill is under the bulk budget fraction, so a bulk flood
        sheds at the front door while interactive still routes into the
        headroom the budget reserved."""
        lane = lanes_mod.normalize(lane)
        epoch = self.table.epoch
        live = [m for m in self.table.live_members()
                if m.member_id not in exclude]
        retry_s = heartbeat_ms() / 1000.0
        if not live:
            raise FleetUnavailableError(
                f"no live routable replica for '{model}' "
                f"(membership epoch {epoch})", retry_after_s=retry_s)
        eligible = [m for m in live if self._serves(m, model)]
        if not eligible:
            raise FleetUnavailableError(
                f"no live replica serves '{model}' (of {len(live)} "
                f"live; circuits open or model not deployed)",
                retry_after_s=retry_s)
        budget = lanes_mod.budget_fraction(lane)
        with_room = [m for m in eligible if m.load < budget]
        if not with_room:
            if budget < 1.0 and any(m.load < 1.0 for m in eligible):
                # the lane's budget is the binding constraint, not the
                # whole fleet: shed THIS class, keep interactive routing
                _bb("lane_shed", payload=f"lane={lane} model={model} "
                                         f"budget={budget} at=router",
                    epoch=epoch)
                raise FleetUnavailableError(
                    f"every replica serving '{model}' is beyond the "
                    f"'{lane}' lane budget ({budget}) — shedding this "
                    f"class", retry_after_s=retry_s)
            raise FleetUnavailableError(
                f"every live replica serving '{model}' reports a full "
                f"queue — shedding", retry_after_s=retry_s)
        ring = self._ring_for(self.table.epoch,
                              self.table.live_members())
        home_id = ring.home(f"{model}|{key}" if key else model)
        for m in with_room:
            if m.member_id == home_id:
                return m, epoch
        return min(with_room, key=lambda m: (m.load, m.member_id)), epoch

    # -- dispatch + failover --------------------------------------------

    def _call_dispatch(self, member: Member, model: str,
                       rows: Sequence[dict], deadline: float,
                       fmt: str, lane: str) -> dict:
        """Invoke the (injectable) dispatch callable. Format and lane
        ride as kwargs ONLY when non-default so the pre-existing
        4-positional dispatch signature (tests inject those) keeps
        working unchanged."""
        kw = {}
        if fmt != "rows":
            kw["fmt"] = fmt
        if lane != lanes_mod.DEFAULT_LANE:
            kw["lane"] = lane
        return self._dispatch(member, model, rows, deadline, **kw)

    def predict_rows(self, model: str, rows: Sequence[dict], *,
                     key: Optional[str] = None,
                     timeout_ms: Optional[float] = None,
                     fmt: str = "rows",
                     lane: Optional[str] = None) -> dict:
        """Routed scoring with single failover. Returns the replica's
        response body plus routing metadata (``_fleet``). The failover
        re-routes under the CURRENT epoch (the first decision's epoch
        may be dead — that is the point of re-reading it) and respects
        the request's remaining deadline.

        ``fmt`` selects the response shape (``rows`` | ``columnar`` |
        ``stream``) — ALL shapes ride this same failover path (ISSUE
        20 satellite: columnar/streaming used to go direct and die
        with the replica). ``lane`` is the deadline class."""
        lane = lanes_mod.normalize(lane)
        timeout_s = (float(timeout_ms) / 1000.0 if timeout_ms is not None
                     else 10.0)
        deadline = time.monotonic() + timeout_s
        member, epoch = self.route(model, key=key, lane=lane)
        try:
            out = self._call_dispatch(member, model, rows, deadline,
                                      fmt, lane)
            out["_fleet"] = {"member": member.member_id, "epoch": epoch,
                             "failover": False}
            return out
        except ReplicaDispatchError:
            raise                       # executed (or may have): no retry
        except FleetUnavailableError:
            raise
        except Exception as e:          # noqa: BLE001 — classified below
            if not _safe_to_failover(e):
                raise RouterError(
                    f"dispatch to {member.member_id} failed "
                    f"non-retryably: {e}") from e
            return self._failover(model, rows, key=key, deadline=deadline,
                                  failed=member, first_epoch=epoch,
                                  cause=e, fmt=fmt, lane=lane)

    def _failover(self, model: str, rows: Sequence[dict], *,
                  key: Optional[str], deadline: float, failed: Member,
                  first_epoch: int, cause: BaseException,
                  fmt: str = "rows", lane: str = "interactive") -> dict:
        """One retry on the next live replica. The membership epoch is
        re-read: if the table already noticed the death the failed
        member is gone from the live set anyway; if not, it is
        excluded explicitly and reported suspect so the detector hears
        about the failure one beat early."""
        self.table.sweep()
        epoch = self.table.epoch
        remaining = deadline - time.monotonic()
        if remaining <= 0.001:
            raise FleetUnavailableError(
                f"dispatch to {failed.member_id} failed ({cause}) with "
                f"no deadline left for failover",
                retry_after_s=heartbeat_ms() / 1000.0)
        member, epoch = self.route(model, key=key,
                                   exclude=(failed.member_id,),
                                   lane=lane)
        try:
            out = self._call_dispatch(member, model, rows, deadline,
                                      fmt, lane)
        except ReplicaDispatchError:
            raise
        except Exception as e:          # noqa: BLE001 — single failover
            raise FleetUnavailableError(
                f"failover to {member.member_id} also failed ({e}; "
                f"first: {cause} on {failed.member_id}, epoch "
                f"{first_epoch}->{epoch})",
                retry_after_s=heartbeat_ms() / 1000.0) from e
        out["_fleet"] = {"member": member.member_id, "epoch": epoch,
                         "failover": True,
                         "failed_member": failed.member_id}
        try:
            from h2o3_tpu import telemetry
            telemetry.counter(
                "h2o3_router_failover_total",
                help="routed requests that failed over to a second "
                     "replica").inc()
        except Exception:   # noqa: BLE001 — telemetry never breaks routing
            pass
        return out

    # -- transport -------------------------------------------------------

    @staticmethod
    def _http_dispatch(member: Member, model: str,
                       rows: Sequence[dict], deadline: float,
                       fmt: str = "rows",
                       lane: str = "interactive") -> dict:
        """POST the rows to the member's own predictions endpoint. The
        per-call socket timeout is the request's REMAINING deadline,
        and the call rides ``retry_transient`` (attempts=1: the
        router's failover IS the retry policy for scoring — a same-
        replica retry would double the latency cost of a sick host).
        Non-row formats ride a ``format`` query param and the lane
        travels as the ``X-H2O3-Lane`` header — the same wire shape
        clients use, so routed and direct scoring stay bit-identical."""
        from h2o3_tpu import resilience
        url = (f"{member.base_url}/3/Predictions/models/"
               f"{urllib.parse.quote(model)}/rows")
        if fmt != "rows":
            url += f"?format={urllib.parse.quote(fmt)}"
        payload = json.dumps({"rows": list(rows)}).encode()

        def _call():
            timeout = max(deadline - time.monotonic(), 0.001)
            req = urllib.request.Request(
                url, data=payload, method="POST",
                headers={"Content-Type": "application/json",
                         "X-H2O3-Lane": lane})
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    body = r.read().decode()
                    ctype = (r.headers.get("Content-Type") or "")
                    if "json" in ctype and not ctype.startswith(
                            "application/x-ndjson"):
                        return json.loads(body)
                    # streamed scoring (NDJSON) passes through opaque:
                    # the routed endpoint replies it verbatim, so
                    # routed and direct streams stay bit-identical
                    return {"__raw": body, "__content_type": ctype
                            or "application/octet-stream"}
            except urllib.error.HTTPError as e:
                body = {}
                try:
                    body = json.loads(e.read().decode())
                except Exception:   # noqa: BLE001 — body is best-effort
                    pass
                if e.code in (503, 404):
                    # 503: the replica shed (queue full / circuit
                    # open); 404: it does not hold the model (a stale
                    # deployment list, or a warm-up that resolved
                    # nothing). Either way it provably never scored
                    # the rows — safe to fail over to a replica that
                    # can, instead of surfacing a 404 for a model the
                    # rest of the fleet serves.
                    raise ReplicaShedError(
                        f"{member.member_id} shed with {e.code}: "
                        f"{body.get('msg', '')}")
                raise ReplicaDispatchError(
                    f"{member.member_id} answered {e.code}: "
                    f"{body.get('msg', e.reason)}",
                    http_status=e.code, body=body)

        return resilience.retry_transient(
            _call, site="fleet.dispatch", attempts=1)


class ReplicaShedError(RuntimeError):
    """A replica's OWN admission control rejected the request (503) —
    provably not executed, so the router may fail over."""


# connect-class failures: the request provably never reached the
# replica's handler, so a second replica may safely take it
_CONNECT_MARKERS = ("connection refused", "connection reset",
                    "connection aborted", "errno 111", "errno 104",
                    "name or service not known", "no route to host",
                    "remote end closed connection")


def _safe_to_failover(exc: BaseException) -> bool:
    if isinstance(exc, ReplicaShedError):
        return True
    if isinstance(exc, (ConnectionRefusedError, ConnectionResetError,
                        ConnectionAbortedError)):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _CONNECT_MARKERS)


# -- the router tier (ISSUE 20) -----------------------------------------

def _norm_url(u: str) -> str:
    u = str(u or "").strip().rstrip("/")
    if u and "://" not in u:
        u = f"http://{u}"
    return u


def _tier_snapshot_path() -> Optional[str]:
    """Disk fallback for warm-boot when no peer router answers: the
    last gossiped table+registry snapshot, under the shared recovery
    root (``None`` when recovery is off — tier state is then
    peer-only)."""
    try:
        from h2o3_tpu import recovery
        root = recovery.recovery_dir()
    except Exception:   # noqa: BLE001 — recovery is optional
        root = None
    return os.path.join(root, "fleet_router_snapshot.json") if root \
        else None


class RouterTier:
    """Membership gossip among N router processes (ISSUE 20): every
    router owns a full :class:`MemberTable` (agents beat ONE router;
    the others learn via snapshots), any router answers any key, and a
    restarting router warm-boots its table + deployment registry from
    any peer instead of serving an empty-table 503 window until the
    replicas' next beats rebuild it.

    The gossip reuses the table's own membership rules verbatim
    (:meth:`MemberTable.absorb` — epoch-fenced, incarnation-fenced),
    adds nothing: no vector clocks, no anti-entropy rounds beyond the
    per-heartbeat snapshot exchange. Peer reachability transitions are
    flight-recorder events (``router_join`` / ``router_handoff``)."""

    def __init__(self, router: FleetRouter, self_url: str,
                 peers: Optional[Sequence[str]] = None):
        self.router = router
        self.self_url = _norm_url(self_url)
        raw = peers if peers is not None else seeds()
        self._peers: List[str] = []
        for p in raw:
            u = _norm_url(p)
            if u and u != self.self_url and u not in self._peers:
                self._peers.append(u)
        self._mu = threading.Lock()
        # last gossip outcome per peer: None = never tried, True/False
        self._reachable: Dict[str, Optional[bool]] = \
            {u: None for u in self._peers}
        self._ticking = False
        self._timer: Optional[threading.Timer] = None
        router.tier = self

    # -- view ------------------------------------------------------------

    def peers(self) -> List[str]:
        with self._mu:
            return list(self._peers)

    def note_peer(self, url: str) -> None:
        """A router we did not know about gossiped to us — adopt it as
        a peer (elastic tier membership)."""
        u = _norm_url(url)
        if not u or u == self.self_url:
            return
        with self._mu:
            if u in self._peers:
                return
            self._peers.append(u)
            self._reachable[u] = True
        _bb("router_join", member=u, payload="via=gossip discovered=1",
            epoch=self.router.table.epoch)

    # -- warm boot -------------------------------------------------------

    def warm_boot(self) -> str:
        """Populate the table + registry before serving: from the
        first peer router that answers, else from the disk snapshot,
        else cold (the pre-tier behavior: wait for replica beats).
        Returns the source used (``peer:<url>`` | ``disk`` | ``cold``)
        — the regression test asserts a bounced router answers its
        first routed request without a shed window."""
        for url in self.peers():
            body = self._get_json(f"{url}/3/Fleet/snapshot")
            if body and isinstance(body.get("snapshot"), dict):
                n = self.router.table.absorb(body["snapshot"],
                                             source=url)
                self._prewarm(body.get("registry"))
                with self._mu:
                    self._reachable[url] = True
                _bb("router_join", member=self.self_url,
                    payload=f"warm_boot=peer src={url} absorbed={n}",
                    epoch=self.router.table.epoch)
                return f"peer:{url}"
        path = _tier_snapshot_path()
        if path and os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as f:
                    body = json.load(f)
                n = self.router.table.absorb(
                    body.get("snapshot") or {}, source="disk")
                self._prewarm(body.get("registry"))
                _bb("router_join", member=self.self_url,
                    payload=f"warm_boot=disk absorbed={n}",
                    epoch=self.router.table.epoch)
                return "disk"
            except Exception:   # noqa: BLE001 — corrupt snapshot: cold boot
                pass
        _bb("router_join", member=self.self_url, payload="warm_boot=cold",
            epoch=self.router.table.epoch)
        return "cold"

    @staticmethod
    def _prewarm(registry: Optional[dict]) -> None:
        """Deploy the registry snapshot's models so the first routed
        request after a bounce compiles nothing (the warm cold-start
        contract extended to routers)."""
        if not registry:
            return
        try:
            from h2o3_tpu.serve import service
            service.prewarm_from_snapshot(registry)
        except Exception:   # noqa: BLE001 — prewarm is best-effort
            pass

    # -- gossip ----------------------------------------------------------

    def gossip_once(self) -> int:
        """One anti-entropy round: push our snapshot to every peer,
        absorb each answering peer's snapshot from the response (the
        exchange is symmetric so a one-way partition still converges
        the reachable side), persist the merged view to disk for the
        no-peer warm-boot fallback. Returns records absorbed."""
        snap = self.router.table.snapshot()
        registry = self._registry_snapshot()
        payload = {"source": self.self_url, "snapshot": snap,
                   "registry": registry}
        absorbed = 0
        for url in self.peers():
            body = self._post_json(f"{url}/3/Fleet/gossip", payload)
            ok = body is not None
            with self._mu:
                was = self._reachable.get(url)
                self._reachable[url] = ok
            if ok and isinstance(body.get("snapshot"), dict):
                absorbed += self.router.table.absorb(body["snapshot"],
                                                     source=url)
            if ok and was is False:
                _bb("router_join", member=url, payload="via=gossip "
                    "recovered=1", epoch=self.router.table.epoch)
            elif not ok and was in (True, None):
                # the peer stopped answering: its keys are now ours
                # (any router answers any key — this records WHEN the
                # tier lost a front door, for the post-mortem timeline)
                _bb("router_handoff", member=url,
                    payload="peer_unreachable=1",
                    epoch=self.router.table.epoch)
        self._persist(snap, registry)
        return absorbed

    @staticmethod
    def _registry_snapshot() -> Optional[dict]:
        try:
            from h2o3_tpu.serve import service
            return service.registry_snapshot()
        except Exception:   # noqa: BLE001 — registry is optional here
            return None

    def _persist(self, snap: dict, registry: Optional[dict]) -> None:
        path = _tier_snapshot_path()
        if not path:
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"snapshot": snap, "registry": registry}, f)
            os.replace(tmp, path)
        except Exception:   # noqa: BLE001 — disk fallback is advisory
            pass

    # -- ticker ----------------------------------------------------------

    def start(self) -> None:
        """Gossip once per heartbeat interval (the same cadence the
        failure detector runs at — a peer's view is never staler than
        one beat plus one network hop)."""
        self._ticking = True
        self._gossip_tick()

    def _gossip_tick(self) -> None:
        if not self._ticking:
            return
        try:
            self.gossip_once()
        except Exception:   # noqa: BLE001 — gossip must not kill the timer
            pass
        finally:
            t = threading.Timer(heartbeat_ms() / 1000.0,
                                self._gossip_tick)
            t.daemon = True
            self._timer = t
            t.start()

    def stop(self) -> None:
        self._ticking = False
        t = self._timer
        if t is not None:
            t.cancel()

    # -- transport -------------------------------------------------------

    @staticmethod
    def _get_json(url: str, timeout_s: float = 2.0) -> Optional[dict]:
        """attempts=1: the gossip CADENCE is the retry policy — an
        unreachable peer is a reachability state, not an error."""
        from h2o3_tpu import resilience

        def _call():
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                return json.loads(r.read().decode())

        try:
            return resilience.retry_transient(
                _call, site="fleet.tier", attempts=1)
        except Exception:   # noqa: BLE001 — unreachable peer is a state
            return None

    @staticmethod
    def _post_json(url: str, payload: dict,
                   timeout_s: float = 2.0) -> Optional[dict]:
        from h2o3_tpu import resilience
        data = json.dumps(payload).encode()

        def _call():
            req = urllib.request.Request(
                url, data=data, method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return json.loads(r.read().decode())

        try:
            return resilience.retry_transient(
                _call, site="fleet.tier", attempts=1)
        except Exception:   # noqa: BLE001 — unreachable peer is a state
            return None
