"""The fleet front door: consistent-hash routing over live members.

Reference: H2O-3's L2 key-hashed dispatch — every key has a home node
computed from the cloud's member list, and work for that key lands
there (SURVEY §L1/§L2). Here the router owns a
:class:`~h2o3_tpu.fleet.membership.MemberTable` and dispatches scoring
requests over the live, routable members:

- **home replica**: consistent hashing (a hash ring with
  ``H2O3_FLEET_RING_POINTS`` virtual points per member, default 64) of
  the request's routing key — membership change moves only ~1/N of the
  key space, so replica-local caches and batch coalescing stay warm
  across churn.
- **least-loaded fallback**: a request whose home replica is not live,
  does not serve the model, or reports an open circuit for it falls
  back to the least-loaded live member that can take it.
- **single failover**: a dispatch that fails in a *provably
  not-executed* way (connect refused/reset, a shed 503) retries ONCE
  on the next live replica, under the request's remaining deadline.
  Failure modes where the request may have executed (mid-response
  errors, deadline blowouts) are NOT retried — scoring is idempotent
  but the caller's latency budget is not, and proxied mutations
  (deploy/undeploy) never retry at all.
- **load shedding**: an empty live set, or a live set whose every
  member reports a full batcher queue, sheds with 503 + ``Retry-After``
  (one heartbeat interval — the soonest membership can change).

Every routing decision pins the membership ``epoch`` it was made
under; the failover path re-reads it so a decision from a dead epoch
is never retried blindly (the fleet-peer-discipline lint rule
machine-checks both).

Cross-replica HTTP goes through ``resilience.retry_transient`` with an
explicit per-call deadline — the same one policy every other network
seam in the repo uses.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from h2o3_tpu.fleet.membership import (ALIVE, Member, MemberTable,
                                       heartbeat_ms)

__all__ = ["ConsistentHashRing", "FleetRouter", "RouterError",
           "FleetUnavailableError", "ReplicaDispatchError"]


class RouterError(RuntimeError):
    http_status = 500


class FleetUnavailableError(RouterError):
    """No live replica can absorb this request: empty live set, every
    queue full, or failover exhausted. 503 + Retry-After."""
    http_status = 503

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class ReplicaDispatchError(RouterError):
    """The chosen replica answered with an application error (the
    request DID execute there, or may have) — surfaced as-is, never
    retried onto another replica."""

    def __init__(self, msg: str, http_status: int = 500,
                 body: Optional[dict] = None):
        super().__init__(msg)
        self.http_status = int(http_status)
        self.body = body or {}


def _ring_points() -> int:
    try:
        v = int(os.environ.get("H2O3_FLEET_RING_POINTS", "64") or 64)
        return v if v > 0 else 64
    except ValueError:
        return 64


def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.blake2b(
        s.encode(), digest_size=8).digest(), "big")


class ConsistentHashRing:
    """Classic virtual-node hash ring. Stability contract (asserted by
    tests/test_fleet_router.py): removing one of N members re-homes
    only the removed member's ~1/N key share; every other key keeps
    its home."""

    def __init__(self, member_ids: Sequence[str],
                 points: Optional[int] = None):
        self.points = points or _ring_points()
        ring: List[Tuple[int, str]] = []
        for mid in member_ids:
            for i in range(self.points):
                ring.append((_hash64(f"{mid}#{i}"), mid))
        ring.sort()
        self._hashes = [h for h, _ in ring]
        self._owners = [m for _, m in ring]

    def home(self, key: str) -> Optional[str]:
        if not self._hashes:
            return None
        i = bisect_left(self._hashes, _hash64(key))
        if i == len(self._hashes):
            i = 0
        return self._owners[i]


class FleetRouter:
    """One per front-door process. Owns the member table, keeps a hash
    ring per membership epoch, and proxies scoring to the chosen
    replica. ``dispatch`` is injectable for transport-free tests; the
    default POSTs to the member's REST surface."""

    def __init__(self, table: Optional[MemberTable] = None,
                 dispatch: Optional[Callable] = None):
        self.table = table if table is not None else MemberTable()
        self._dispatch = dispatch or self._http_dispatch
        self._ring_mu = threading.Lock()
        self._ring_epoch = -1
        self._ring: Optional[ConsistentHashRing] = None
        self._ticker: Optional[threading.Timer] = None
        self._ticking = False

    # -- failure-detector ticker ---------------------------------------

    def start_ticker(self) -> None:
        """Sweep the member table once per heartbeat interval so a dead
        replica is evicted even when no traffic is flowing (routing
        decisions sweep lazily; idle fleets need the clock)."""
        self._ticking = True
        self._tick()

    def _tick(self) -> None:
        if not self._ticking:
            return
        try:
            self.table.sweep()
            # fleet scheduler: queued local work drains to members with
            # headroom even when no join/gossip event triggers it
            from h2o3_tpu.fleet import sched as fleet_sched
            fleet_sched.router_tick(self.table)
        finally:
            t = threading.Timer(heartbeat_ms() / 1000.0, self._tick)
            t.daemon = True
            self._ticker = t
            t.start()

    def stop_ticker(self) -> None:
        self._ticking = False
        t = self._ticker
        if t is not None:
            t.cancel()

    # -- ring -----------------------------------------------------------

    def _ring_for(self, epoch: int,
                  members: Sequence[Member]) -> ConsistentHashRing:
        with self._ring_mu:
            if self._ring is None or self._ring_epoch != epoch:
                self._ring = ConsistentHashRing(
                    sorted(m.member_id for m in members))
                self._ring_epoch = epoch
            return self._ring

    # -- routing decisions ----------------------------------------------

    @staticmethod
    def _serves(m: Member, model: str) -> bool:
        """A member with an empty deployment list is assumed universal
        (a hand-built table, or a replica still resolving models) —
        the dispatch-side 404 failover is the backstop if it turns
        out to hold nothing. One that lists deployments must list the
        model. An open piggybacked circuit for the model disqualifies
        — the replica itself would only 503."""
        if m.deployments and model not in m.deployments:
            return False
        for c in m.circuit:
            if c.get("model") == model and c.get("state") == "open":
                return False
        return True

    def route(self, model: str, key: Optional[str] = None,
              exclude: Sequence[str] = ()) -> Tuple[Member, int]:
        """Pick the target replica for one request: the routing key's
        home on the consistent-hash ring when it is eligible, else the
        least-loaded eligible live member. Returns ``(member, epoch)``
        — the epoch the decision was made under fences the failover
        path against deciding from a dead view."""
        epoch = self.table.epoch
        live = [m for m in self.table.live_members()
                if m.member_id not in exclude]
        retry_s = heartbeat_ms() / 1000.0
        if not live:
            raise FleetUnavailableError(
                f"no live routable replica for '{model}' "
                f"(membership epoch {epoch})", retry_after_s=retry_s)
        eligible = [m for m in live if self._serves(m, model)]
        if not eligible:
            raise FleetUnavailableError(
                f"no live replica serves '{model}' (of {len(live)} "
                f"live; circuits open or model not deployed)",
                retry_after_s=retry_s)
        with_room = [m for m in eligible if m.load < 1.0]
        if not with_room:
            raise FleetUnavailableError(
                f"every live replica serving '{model}' reports a full "
                f"queue — shedding", retry_after_s=retry_s)
        ring = self._ring_for(self.table.epoch,
                              self.table.live_members())
        home_id = ring.home(f"{model}|{key}" if key else model)
        for m in with_room:
            if m.member_id == home_id:
                return m, epoch
        return min(with_room, key=lambda m: (m.load, m.member_id)), epoch

    # -- dispatch + failover --------------------------------------------

    def predict_rows(self, model: str, rows: Sequence[dict], *,
                     key: Optional[str] = None,
                     timeout_ms: Optional[float] = None) -> dict:
        """Routed scoring with single failover. Returns the replica's
        response body plus routing metadata (``_fleet``). The failover
        re-routes under the CURRENT epoch (the first decision's epoch
        may be dead — that is the point of re-reading it) and respects
        the request's remaining deadline."""
        timeout_s = (float(timeout_ms) / 1000.0 if timeout_ms is not None
                     else 10.0)
        deadline = time.monotonic() + timeout_s
        member, epoch = self.route(model, key=key)
        try:
            out = self._dispatch(member, model, rows, deadline)
            out["_fleet"] = {"member": member.member_id, "epoch": epoch,
                             "failover": False}
            return out
        except ReplicaDispatchError:
            raise                       # executed (or may have): no retry
        except FleetUnavailableError:
            raise
        except Exception as e:          # noqa: BLE001 — classified below
            if not _safe_to_failover(e):
                raise RouterError(
                    f"dispatch to {member.member_id} failed "
                    f"non-retryably: {e}") from e
            return self._failover(model, rows, key=key, deadline=deadline,
                                  failed=member, first_epoch=epoch,
                                  cause=e)

    def _failover(self, model: str, rows: Sequence[dict], *,
                  key: Optional[str], deadline: float, failed: Member,
                  first_epoch: int, cause: BaseException) -> dict:
        """One retry on the next live replica. The membership epoch is
        re-read: if the table already noticed the death the failed
        member is gone from the live set anyway; if not, it is
        excluded explicitly and reported suspect so the detector hears
        about the failure one beat early."""
        self.table.sweep()
        epoch = self.table.epoch
        remaining = deadline - time.monotonic()
        if remaining <= 0.001:
            raise FleetUnavailableError(
                f"dispatch to {failed.member_id} failed ({cause}) with "
                f"no deadline left for failover",
                retry_after_s=heartbeat_ms() / 1000.0)
        member, epoch = self.route(model, key=key,
                                   exclude=(failed.member_id,))
        try:
            out = self._dispatch(member, model, rows, deadline)
        except ReplicaDispatchError:
            raise
        except Exception as e:          # noqa: BLE001 — single failover
            raise FleetUnavailableError(
                f"failover to {member.member_id} also failed ({e}; "
                f"first: {cause} on {failed.member_id}, epoch "
                f"{first_epoch}->{epoch})",
                retry_after_s=heartbeat_ms() / 1000.0) from e
        out["_fleet"] = {"member": member.member_id, "epoch": epoch,
                         "failover": True,
                         "failed_member": failed.member_id}
        try:
            from h2o3_tpu import telemetry
            telemetry.counter(
                "h2o3_router_failover_total",
                help="routed requests that failed over to a second "
                     "replica").inc()
        except Exception:   # noqa: BLE001 — telemetry never breaks routing
            pass
        return out

    # -- transport -------------------------------------------------------

    @staticmethod
    def _http_dispatch(member: Member, model: str,
                       rows: Sequence[dict], deadline: float) -> dict:
        """POST the rows to the member's own predictions endpoint. The
        per-call socket timeout is the request's REMAINING deadline,
        and the call rides ``retry_transient`` (attempts=1: the
        router's failover IS the retry policy for scoring — a same-
        replica retry would double the latency cost of a sick host)."""
        from h2o3_tpu import resilience
        url = (f"{member.base_url}/3/Predictions/models/"
               f"{urllib.parse.quote(model)}/rows")
        payload = json.dumps({"rows": list(rows)}).encode()

        def _call():
            timeout = max(deadline - time.monotonic(), 0.001)
            req = urllib.request.Request(
                url, data=payload, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read().decode())
            except urllib.error.HTTPError as e:
                body = {}
                try:
                    body = json.loads(e.read().decode())
                except Exception:   # noqa: BLE001 — body is best-effort
                    pass
                if e.code in (503, 404):
                    # 503: the replica shed (queue full / circuit
                    # open); 404: it does not hold the model (a stale
                    # deployment list, or a warm-up that resolved
                    # nothing). Either way it provably never scored
                    # the rows — safe to fail over to a replica that
                    # can, instead of surfacing a 404 for a model the
                    # rest of the fleet serves.
                    raise ReplicaShedError(
                        f"{member.member_id} shed with {e.code}: "
                        f"{body.get('msg', '')}")
                raise ReplicaDispatchError(
                    f"{member.member_id} answered {e.code}: "
                    f"{body.get('msg', e.reason)}",
                    http_status=e.code, body=body)

        return resilience.retry_transient(
            _call, site="fleet.dispatch", attempts=1)


class ReplicaShedError(RuntimeError):
    """A replica's OWN admission control rejected the request (503) —
    provably not executed, so the router may fail over."""


# connect-class failures: the request provably never reached the
# replica's handler, so a second replica may safely take it
_CONNECT_MARKERS = ("connection refused", "connection reset",
                    "connection aborted", "errno 111", "errno 104",
                    "name or service not known", "no route to host",
                    "remote end closed connection")


def _safe_to_failover(exc: BaseException) -> bool:
    if isinstance(exc, ReplicaShedError):
        return True
    if isinstance(exc, (ConnectionRefusedError, ConnectionResetError,
                        ConnectionAbortedError)):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _CONNECT_MARKERS)
