"""Fleet scheduler: cluster-wide training placement, preempt-migrate,
and elastic membership (ISSUE 18).

The reference H2O-3 cloud schedules work against ALL nodes as one
resource pool (water/Paxos.java membership + the priority ForkJoin
ladder, water/H2O.java:1532); until this module each of our subsystems
was per-process-complete but fleet-incomplete: PR 15's scheduler admits
trains against one process's HBM budget, PR 13's member table knows
every replica's load and epoch, and PR 9 proved in-training checkpoints
resume bit-identically in a different process. This module is the seam
that fuses them:

1. **Fleet placement** — every heartbeat gossips the replica's sched
   payload (admission headroom, queue depth per priority class, running
   count) into the member table; the heartbeat RESPONSE carries the
   router's merged fleet view back, so every replica sees every other
   replica's headroom at heartbeat latency. A train submitted to any
   replica is placed on the member with admission headroom (local wins
   ties; no headroom anywhere → queue locally with the fleet snapshot
   recorded on the entry), and grid/AutoML waves — bulk class with a
   non-default share group — ROUND-ROBIN across local + remote slots so
   one grid's children land on every replica with headroom.
2. **Preempt-MIGRATE** — a preempted train's DKV ``<key>_ckpt`` is
   exported as a durable artifact and handed (with the job's priority
   class, share group and trace id) to a replica with headroom, where it
   resumes bit-identically; the LOCAL job key keeps reporting on
   /3/Jobs via a proxy that mirrors the remote job's status/progress and
   finalizes the local job from the remote result artifact.
3. **Elastic membership** — a replica joining mid-grid triggers a
   rebalance that steals queued children and hands them over; an
   evicted replica's RUNNING checkpointing trains are re-queued
   fleet-wide from their last chunk commit via the recovery manifests
   (which now record the owning member, priority class and share).

Degradation contract (mixed-version fleets, satellite 2): the sched
payload carries ``schema_version``; unknown keys are ignored and a
member whose payload is missing, unparseable or from an incompatible
version is treated as no-headroom/local-only — a fleet of old replicas
behaves exactly like PR 15's per-process scheduler.

Transfer plane: artifacts (frames, migrated checkpoints, results) move
through the shared recovery root (``H2O3_RECOVERY_DIR``) — the same
durable store boot recovery already requires — so placement degrades to
local-only when no shared root is configured.

Threading: all async work (proxy polling, rebalance, evict-requeue)
runs on one bounded ThreadPoolExecutor — the sched-discipline lint rule
covers this package, so no raw ``threading.Thread`` here.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

SCHED_SCHEMA_VERSION = 1

# algos whose (y, x, training_frame) submissions round-trip through the
# recovery/estimator seam — the remote-submit payload is exactly the
# recovery-manifest shape, so the supported set is recovery's
_REMOTE_ALGOS = ("gbm", "drf", "xgboost")

_MU = threading.Lock()
_LOCAL: Dict[str, Optional[str]] = {"member_id": None, "base_url": None}
# replica-side copy of the router's merged fleet view (piggybacked on
# the heartbeat response); mono stamps freshness
_GOSSIP: Dict[str, Any] = {"view": None, "mono": 0.0}
_COUNTERS: Dict[str, int] = {
    "remote_submits": 0, "remote_received": 0, "migrations": 0,
    "rebalanced": 0, "evict_requeues": 0}
_RR: Dict[str, int] = {}            # share group -> round-robin cursor
# (member_id, depart_epoch) departure records this process already
# raced a requeue lease for — the lease arbitrates across processes,
# this set stops one process re-racing the same gossip record per beat
_SEEN_DEPARTED: set = set()
_REBAL: Dict[str, float] = {"last": 0.0}
_FRAMES: Dict[str, Tuple[float, Any]] = {}   # path -> (mtime, Frame)
_EXEC = None
_EXEC_MU = threading.Lock()
_REMOTE_TLS = threading.local()     # on=True while ingesting a remote
#                                     submission (placement must not
#                                     re-place it — ping-pong fence)


def _executor():
    global _EXEC
    with _EXEC_MU:
        if _EXEC is None:
            import concurrent.futures as cf
            _EXEC = cf.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="fleet-sched")
        return _EXEC


def _knob_s(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def set_local_member(member_id: str, base_url: Optional[str]) -> None:
    """Identify this process in the fleet (FleetAgent.start)."""
    with _MU:
        _LOCAL["member_id"] = member_id
        _LOCAL["base_url"] = base_url


def local_member_id() -> str:
    with _MU:
        mid = _LOCAL["member_id"]
    # same formula as FleetAgent._default_member_id and the chaos
    # harness's victim computation — a process that never started an
    # agent still stamps a stable identity into recovery manifests
    return mid or f"{os.getpid()}@{socket.gethostname()}"


def counters() -> Dict[str, int]:
    with _MU:
        return dict(_COUNTERS)


def _count(name: str, n: int = 1) -> None:
    with _MU:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def _bb(kind: str, member: str = "", payload: str = "",
        trace_id: Optional[str] = None, epoch: Optional[int] = None
        ) -> None:
    """Flight-recorder append (ISSUE 19): every placement / hand-off /
    migrate / requeue decision lands in the blackbox ring so a chaos
    post-mortem can read WHY the fleet moved work. Advisory."""
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.record(kind, member=member, payload=payload,
                        trace_id=trace_id, epoch=epoch)
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass


def _xfer_dir() -> Optional[str]:
    """Durable transfer root shared by the fleet: the recovery root.
    No shared root → no remote submits, placement stays local-only."""
    from h2o3_tpu import recovery
    return recovery.recovery_dir()


# ---------------- heartbeat payload (satellite 2: versioned) -----------

def local_sched_payload() -> Dict[str, Any]:
    """What this replica's heartbeat gossips into the member table."""
    from h2o3_tpu import sched
    s = sched.scheduler()
    return {
        "schema_version": SCHED_SCHEMA_VERSION,
        "headroom_bytes": s.headroom_bytes(),
        "queue_depth": s.class_depths(),
        "running": s.running_count(),
        "accepting": bool(sched.enabled() and not s.paused),
    }


def parse_sched_payload(raw: Any) -> Optional[Dict[str, Any]]:
    """Validate a gossiped sched payload. Returns None — meaning "treat
    the replica as no-headroom/local-only" — for anything that is not a
    well-formed payload of a known-compatible schema version. Unknown
    keys are ignored; a missing optional key takes its default."""
    if not isinstance(raw, dict):
        return None
    try:
        ver = int(raw.get("schema_version"))
    except (TypeError, ValueError):
        return None
    if ver < 1:
        return None

    def _num(v) -> Optional[int]:
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return int(v)

    head = _num(raw.get("headroom_bytes"))
    running = _num(raw.get("running"))
    if head is None or running is None:
        return None
    qd_raw = raw.get("queue_depth")
    depth = {}
    for cls in ("interactive", "bulk", "background"):
        v = _num(qd_raw.get(cls)) if isinstance(qd_raw, dict) else None
        depth[cls] = v if v is not None and v >= 0 else 0
    return {"schema_version": ver, "headroom_bytes": head,
            "queue_depth": depth, "running": max(running, 0),
            "accepting": bool(raw.get("accepting", True))}


def fleet_view_from_table(table) -> Dict[str, Any]:
    """The router's merged placement view, shipped back to replicas in
    every heartbeat response. Payloads are parsed ROUTER-side so a
    malformed member degrades identically everywhere."""
    members = []
    for m in table.members():
        members.append({
            "member_id": m.member_id,
            "base_url": m.base_url,
            "state": m.state,
            "routable": bool(m.routable),
            "sched": parse_sched_payload(m.sched),
        })
    # recent departures ride the view too: survivors race for the
    # evict-requeue lease off this list (router-less requeue, ISSUE 19)
    return {"epoch": table.epoch, "members": members,
            "departed": table.departed()}


def observe_fleet_view(view: Any, self_id: str) -> None:
    """Replica-side ingest of the heartbeat response's fleet view."""
    if not isinstance(view, dict) or not isinstance(
            view.get("members"), list):
        return
    with _MU:
        _GOSSIP["view"] = view
        _GOSSIP["mono"] = time.monotonic()
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.set_identity(epoch=int(view.get("epoch") or 0))
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass
    # router-less evict requeue: ANY survivor that sees an eviction in
    # the gossiped view races for the victim's lease (once per
    # departure record per process; the lease arbitrates cross-process)
    try:
        for dep in view.get("departed") or []:
            if not isinstance(dep, dict) or dep.get("reason") != "evicted":
                continue
            mid = str(dep.get("member_id") or "")
            if not mid or mid == self_id:
                continue
            key = (mid, int(dep.get("epoch") or 0))
            with _MU:
                if key in _SEEN_DEPARTED:
                    continue
                _SEEN_DEPARTED.add(key)
            _executor().submit(_requeue_departed, mid, key[1])
    except Exception:   # noqa: BLE001 — gossip ingest must never throw
        pass
    # elastic membership: a member with headroom appearing while work
    # is queued here absorbs it (throttled; runs off-thread)
    try:
        from h2o3_tpu import sched
        if sched.scheduler().queue_depth() > 0 and \
                _eligible_members(view, self_id):
            maybe_rebalance("gossip")
    except Exception:   # noqa: BLE001 — gossip ingest must never throw
        pass


def _gossip_ttl_s() -> float:
    from h2o3_tpu.fleet import membership
    return max(5.0 * membership.heartbeat_ms() / 1000.0, 3.0)


def current_view() -> Optional[Dict[str, Any]]:
    """The freshest fleet view this process can see: the local router's
    table when this process IS a router (never creates one), else the
    last gossiped view if fresh. None → local-only placement."""
    from h2o3_tpu import fleet
    r = fleet.active_router()
    if r is not None:
        view = fleet_view_from_table(r.table)
        if view["members"]:
            return view
    with _MU:
        view, mono = _GOSSIP["view"], _GOSSIP["mono"]
    if view is not None and time.monotonic() - mono < _gossip_ttl_s():
        return view
    return None


# ---------------- placement --------------------------------------------

def _eligible_members(view: Dict[str, Any],
                      self_id: str) -> List[Dict[str, Any]]:
    """Members a train could be handed to: alive, routable, advertising
    a parseable + accepting sched payload. A member with missing sched
    fields is local-only by the satellite-2 degradation contract."""
    out = []
    for m in view.get("members") or []:
        if not isinstance(m, dict) or m.get("member_id") == self_id:
            continue
        if m.get("state") != "alive" or not m.get("routable"):
            continue
        sch = m.get("sched")
        if isinstance(sch, dict) and "schema_version" not in sch:
            sch = parse_sched_payload(sch)   # raw (un-parsed) table row
        elif not isinstance(sch, dict):
            sch = parse_sched_payload(sch)
        if sch is None or not sch.get("accepting", True):
            continue
        out.append({**m, "sched": sch})
    return out


def _fits(sch: Dict[str, Any], need_bytes: int) -> bool:
    head = sch.get("headroom_bytes", 0)
    return head < 0 or head >= max(int(need_bytes), 0)


def _headroom_key(m: Dict[str, Any]):
    sch = m["sched"]
    # prefer unlimited (-1) members, then most headroom, then least
    # running, then stable id order
    return (sch["headroom_bytes"] < 0, sch["headroom_bytes"],
            -sch["running"], m["member_id"])


def _local_headroom_bytes() -> int:
    """Local admission headroom, honoring the idle-admit rule: an idle
    scheduler admits ANY estimate, so an idle local process always wins
    placement ties."""
    from h2o3_tpu import sched
    s = sched.scheduler()
    if s.running_count() == 0 and s.queue_depth() == 0:
        return -1
    return s.headroom_bytes()


def place_for_submit(pr_name: str, share: str, need_bytes: int
                     ) -> Tuple[Optional[Dict[str, Any]],
                                Optional[Dict[str, Any]]]:
    """The fleet placement decision for one submission. Returns
    ``(placement, fleet_snapshot)``: placement is ``{"member", "epoch"}``
    when the train should run remotely (pinned to the membership epoch
    the decision was made under), None when it should run locally;
    fleet_snapshot is recorded on the local entry when NO member had
    headroom (the queue-locally-with-evidence contract)."""
    view = current_view()
    if view is None:
        return None, None                    # fleet absent → local-only
    epoch = int(view.get("epoch") or 0)
    self_id = local_member_id()
    eligible = _eligible_members(view, self_id)
    cands = [m for m in eligible if _fits(m["sched"], need_bytes)]
    local_head = _local_headroom_bytes()
    local_fits = local_head < 0 or local_head >= need_bytes
    # grid/AutoML waves (bulk class, non-default share group) SPREAD:
    # round-robin the wave's children across local + every fitting
    # member so one grid fans out instead of serializing locally
    if pr_name == "bulk" and share != "default" and cands:
        slots: List[Optional[Dict[str, Any]]] = []
        if local_fits:
            slots.append(None)               # the local slot
        slots.extend(sorted(cands, key=lambda m: m["member_id"]))
        with _MU:
            cursor = _RR.get(share, 0)
            _RR[share] = cursor + 1
        pick = slots[cursor % len(slots)]
        if pick is None:
            _bb("placement", self_id,
                payload=f"local rr share={share}", epoch=epoch)
            return None, None
        _bb("placement", pick["member_id"],
            payload=f"rr share={share} head="
                    f"{pick['sched']['headroom_bytes']}", epoch=epoch)
        return {"member": pick, "epoch": epoch}, None
    if local_fits:
        return None, None                    # local wins ties
    if cands:
        best = max(cands, key=_headroom_key)
        _bb("placement", best["member_id"],
            payload=f"remote need={need_bytes} head="
                    f"{best['sched']['headroom_bytes']}", epoch=epoch)
        return {"member": best, "epoch": epoch}, None
    # no headroom anywhere: queue locally, snapshot the evidence
    snapshot = {
        "epoch": epoch, "no_headroom": True, "time": time.time(),
        "members": [{"member_id": m["member_id"],
                     "headroom_bytes": m["sched"]["headroom_bytes"]}
                    for m in eligible]}
    _bb("placement", self_id,
        payload=f"no_headroom need={need_bytes} "
                f"members={len(eligible)}", epoch=epoch)
    return None, snapshot


# ---------------- peer HTTP (fleet-peer-discipline idiom) --------------

def _post_json(url: str, payload: Dict[str, Any], *, timeout_s: float,
               site: str, attempts: int = 2) -> Dict[str, Any]:
    from h2o3_tpu import resilience
    data = json.dumps(payload).encode()

    def _call():
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read().decode())

    return resilience.retry_transient(_call, site=site,
                                      attempts=attempts)


def _get_json(url: str, *, timeout_s: float, site: str,
              attempts: int = 1) -> Dict[str, Any]:
    from h2o3_tpu import resilience

    def _call():
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read().decode())

    return resilience.retry_transient(_call, site=site,
                                      attempts=attempts)


# ---------------- remote submission ------------------------------------

def _result_path(model_key: str) -> Optional[str]:
    root = _xfer_dir()
    if not root:
        return None
    return os.path.join(root, "fleet", "results", f"{model_key}.zip")


def _export_frame(frame) -> Optional[Tuple[str, str]]:
    """Durable frame artifact under the transfer root, reused across a
    wave's children (key + nrow fingerprint the content well enough for
    the in-session case; recovery's signature scheme guards the
    cross-boot case)."""
    root = _xfer_dir()
    if root is None:
        return None
    key = getattr(frame, "key", None)
    nrow = getattr(frame, "nrow", None)
    if not key or not nrow:
        return None
    d = os.path.join(root, "fleet", "frames")
    art_key = f"{key}__{nrow}"
    path = os.path.join(d, f"{art_key}.zip")
    if not os.path.exists(path):
        from h2o3_tpu.persist import save_frame
        os.makedirs(d, exist_ok=True)
        path = save_frame(frame, d, force=True, key=art_key)
    return path, str(key)


def _submit_eligible(builder, kwargs: Dict[str, Any]) -> bool:
    if getattr(builder, "algo", "") not in _REMOTE_ALGOS:
        return False
    if kwargs.get("validation_frame") is not None:
        return False
    if _xfer_dir() is None:
        return False
    frame = kwargs.get("training_frame")
    return frame is not None and getattr(frame, "key", None) is not None


def _build_submit_payload(builder, job, kwargs: Dict[str, Any],
                          pr_name: str, share: str,
                          checkpoint_path: Optional[str] = None
                          ) -> Optional[Dict[str, Any]]:
    exported = _export_frame(kwargs.get("training_frame"))
    if exported is None:
        return None
    frame_path, frame_key = exported
    from h2o3_tpu.persist import _json_safe
    params = dict(builder.params)
    for k in ("training_frame", "validation_frame", "response_column"):
        params.pop(k, None)
    model_key = builder._model_key()
    params["model_id"] = model_key
    if checkpoint_path:
        params["checkpoint"] = checkpoint_path
    return {
        "schema_version": SCHED_SCHEMA_VERSION,
        "algo": builder.algo,
        "params": _json_safe(params),
        "y": kwargs.get("y"),
        "x": list(kwargs["x"]) if kwargs.get("x") else None,
        "frame_path": frame_path,
        "frame_key": frame_key,
        "priority": pr_name,
        "share": share,
        "trace_id": getattr(job, "trace_id", None),
        "model_key": model_key,
        "result_path": _result_path(model_key),
        "resuming": bool(getattr(builder, "_resuming", False)
                         or checkpoint_path),
        "submitter": local_member_id(),
    }


def _submit_timeout_s() -> float:
    return _knob_s("H2O3_FLEET_SCHED_SUBMIT_TIMEOUT_S", 10.0)


def _hand_off(entry, member: Dict[str, Any],
              checkpoint_path: Optional[str] = None,
              pre_proxy=None, migrated: bool = False) -> bool:
    """POST one entry's submission to a member; on success the local
    entry becomes a proxy for the remote job. False → caller keeps the
    entry local (and no entry/job state was touched). ``pre_proxy``
    runs between acceptance and the first proxy poll — migration uses
    it to bank the preempted run segment exactly once."""
    from h2o3_tpu.sched import core as sched_core
    pr_name = sched_core.PRIORITY_NAMES[entry.priority]
    payload = _build_submit_payload(entry.builder, entry.job,
                                    entry.kwargs, pr_name, entry.share,
                                    checkpoint_path=checkpoint_path)
    if payload is None:
        return False
    try:
        out = _post_json(f"{member['base_url']}/3/FleetSched/submit",
                         payload, timeout_s=_submit_timeout_s(),
                         site="fleet.sched.submit", attempts=1)
    except Exception as e:   # noqa: BLE001 — local queue is the fallback
        from h2o3_tpu.log import warn
        warn("fleet-sched: hand-off of %s to %s failed: %r",
             entry.job.key, member.get("member_id"), e)
        return False
    if not isinstance(out, dict) or not out.get("ok"):
        return False
    entry.remote_member = member.get("member_id")
    if pre_proxy is not None:
        pre_proxy()
    _count("remote_submits")
    _bb("remote_submit_sent", str(member.get("member_id") or ""),
        payload=f"job={entry.job.key} ckpt={int(bool(checkpoint_path))}",
        trace_id=getattr(entry.job, "trace_id", None))
    _start_proxy(entry, member, str(out.get("job_key")),
                 payload["model_key"], payload["result_path"],
                 migrated=migrated)
    return True


def _placer_hook(builder, job, kwargs: Dict[str, Any], pr_name: str,
                 share: str, est, caller_runs: bool):
    """Installed as sched.core.PLACER. Returns ``(entry, snapshot)``:
    a fully-proxied remote Entry (submit() returns it without queueing)
    or ``(None, snapshot-or-None)`` for the local path."""
    if getattr(_REMOTE_TLS, "on", False):
        return None, None     # remotely-placed trains never re-place
    if not _submit_eligible(builder, kwargs):
        return None, None
    placement, snapshot = place_for_submit(pr_name, share, est.bytes)
    if placement is None:
        return None, snapshot
    from h2o3_tpu.sched import core as sched_core
    entry = sched_core.Entry(
        builder, job, kwargs, sched_core.PRIORITY_LEVELS[pr_name],
        share, est, seq=0, caller_runs=caller_runs)
    job.mark_queued()
    if not _hand_off(entry, placement["member"]):
        return None, None                    # fall back to local queue
    return entry, None


# ---------------- target-side ingest -----------------------------------

def _load_frame_cached(path: str):
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = 0.0
    with _MU:
        hit = _FRAMES.get(path)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    from h2o3_tpu.persist import load_frame
    frame = load_frame(path)
    with _MU:
        _FRAMES[path] = (mtime, frame)
    return frame


def handle_remote_submit(b: Dict[str, Any]) -> Dict[str, Any]:
    """Target side of POST /3/FleetSched/submit: reconstruct the
    submission and run it through THIS process's scheduler under the
    original priority class, share group and trace id. The result is
    registered in the local DKV and exported to ``result_path`` so the
    submitter's proxy (or the chaos harness) can finalize from it."""
    from h2o3_tpu import dkv, recovery
    from h2o3_tpu.log import info
    algo = str(b.get("algo") or "")
    model_key = str(b.get("model_key") or "")
    if not model_key:
        raise ValueError("fleet submit needs model_key")
    result_path = b.get("result_path")
    # fast path: an evict-requeue whose checkpoint already holds every
    # requested tree — register the artifact, no training needed
    if b.get("register_artifact"):
        from h2o3_tpu.persist import load_model, save_model
        model = load_model(str(b["register_artifact"]))
        model.key = model_key
        dkv.put(model_key, "model", model)
        if result_path:
            os.makedirs(os.path.dirname(result_path), exist_ok=True)
            save_model(model, os.path.dirname(result_path), force=True,
                       filename=os.path.basename(result_path))
        _count("remote_received")
        _bb("remote_submit_accepted", str(b.get("submitter") or ""),
            payload=f"model={model_key} from_artifact=1",
            trace_id=b.get("trace_id") or None)
        return {"ok": True, "job_key": None, "model_key": model_key,
                "member_id": local_member_id(),
                "completed_from_artifact": True}
    cls = recovery._estimator_class(algo)
    if cls is None:
        raise ValueError(f"fleet submit: unsupported algo '{algo}'")
    frame_path = str(b.get("frame_path") or "")
    if not frame_path or not os.path.exists(frame_path):
        raise ValueError(f"fleet submit: frame artifact missing "
                         f"({frame_path or 'no path'})")
    frame = _load_frame_cached(frame_path)
    params = dict(b.get("params") or {})
    params["model_id"] = model_key
    pr = b.get("priority")
    from h2o3_tpu import sched
    if pr not in sched.PRIORITY_LEVELS:
        pr = "bulk"
    share = str(b.get("share") or "fleet")
    from h2o3_tpu.telemetry import trace as _trace
    trace_id = b.get("trace_id") or None
    est = cls(**params)
    _REMOTE_TLS.on = True
    resuming = bool(b.get("resuming"))
    if resuming:
        recovery._RESUME_CTX.on = True       # RECOVERING badge on /3/Jobs
    try:
        with sched.submit_context(priority=pr, share=share):
            if trace_id:
                with _trace.trace_context(trace_id):
                    est.train(y=b.get("y"), x=b.get("x") or None,
                              training_frame=frame, background=True)
            else:
                est.train(y=b.get("y"), x=b.get("x") or None,
                          training_frame=frame, background=True)
    finally:
        _REMOTE_TLS.on = False
        if resuming:
            recovery._RESUME_CTX.on = False
    job = est.job
    _count("remote_received")
    _bb("remote_submit_accepted", str(b.get("submitter") or ""),
        payload=f"model={model_key} job={job.key} "
                f"resuming={int(resuming)}",
        trace_id=trace_id)
    info("fleet-sched: accepted %s %s from %s (priority=%s share=%s)",
         algo, model_key, b.get("submitter"), pr, share)
    _executor().submit(_finish_remote, job, model_key, result_path)
    return {"ok": True, "job_key": job.key, "model_key": model_key,
            "member_id": local_member_id()}


def _finish_remote(job, model_key: str,
                   result_path: Optional[str]) -> None:
    """Export a remotely-submitted train's result once it completes so
    the submitting replica can finalize its proxy job from it."""
    try:
        model = job.join()
        if model is None:
            return
        from h2o3_tpu import dkv
        model.key = model_key
        dkv.put(model_key, "model", model)
        if result_path:
            from h2o3_tpu.persist import save_model
            os.makedirs(os.path.dirname(result_path), exist_ok=True)
            save_model(model, os.path.dirname(result_path), force=True,
                       filename=os.path.basename(result_path))
    except Exception as e:   # noqa: BLE001 — status travels via /3/Jobs
        from h2o3_tpu.log import warn
        warn("fleet-sched: result export for %s failed: %r",
             model_key, e)


# ---------------- submitter-side proxy ---------------------------------

def _proxy_fail_s() -> float:
    return _knob_s("H2O3_FLEET_SCHED_PROXY_FAIL_S", 10.0)


def _start_proxy(entry, member: Dict[str, Any], remote_job_key: str,
                 model_key: str, result_path: Optional[str],
                 migrated: bool = False) -> None:
    _executor().submit(_proxy_loop, entry, member, remote_job_key,
                       model_key, result_path, migrated)


def _finalize_proxy_failure(entry, msg: str) -> None:
    from h2o3_tpu import jobs as jobs_mod
    job = entry.job
    job.status = jobs_mod.FAILED
    job.exception_msg = msg
    job.end_time = time.time()
    job._end_mono = time.monotonic()
    job._done_evt.set()
    _proxy_done(entry)


def _proxy_done(entry) -> None:
    """Job finalized FIRST, then the entry turns terminal, then the
    scheduler cv wakes: run_to_completion/wait_any block on the cv and
    the grid drain reads job.status/result the moment done is set."""
    entry.done.set()
    from h2o3_tpu import sched
    sched.scheduler().poke()


def _requeue_local(entry) -> None:
    """The remote side is gone (or never answered): pull the entry back
    into the LOCAL queue — a lost replica must cost a re-run, never a
    lost train."""
    from h2o3_tpu import sched
    from h2o3_tpu.log import warn
    warn("fleet-sched: remote %s for %s unreachable — requeueing "
         "locally", entry.remote_member, entry.job.key)
    entry.remote_member = None
    sched.scheduler().requeue(entry)


def _proxy_loop(entry, member: Dict[str, Any], remote_job_key: str,
                model_key: str, result_path: Optional[str],
                migrated: bool) -> None:
    """Mirror the remote job onto the LOCAL job key: status, progress
    and the terminal result all follow the migration on /3/Jobs."""
    from h2o3_tpu import jobs as jobs_mod
    job = entry.job
    base = str(member["base_url"]).rstrip("/")
    url = (f"{base}/3/Jobs/"
           f"{urllib.parse.quote(remote_job_key, safe='')}")
    poll_s = max(_knob_s("H2O3_FLEET_SCHED_POLL_S", 0.15), 0.02)
    fail_mono: Optional[float] = None
    cancel_sent = False
    while True:
        if job.cancel_requested and not cancel_sent:
            cancel_sent = True
            try:
                _post_json(f"{url}/cancel", {},
                           timeout_s=_submit_timeout_s(),
                           site="fleet.sched.cancel", attempts=1)
            except Exception:   # noqa: BLE001 — mirror whatever lands
                pass
        try:
            out = _get_json(url, timeout_s=_submit_timeout_s(),
                            site="fleet.sched.poll")
            fail_mono = None
        except Exception:   # noqa: BLE001 — bounded retry window below
            now = time.monotonic()
            if fail_mono is None:
                fail_mono = now
            if now - fail_mono > _proxy_fail_s():
                # replica death AFTER completion still counts: the
                # result artifact is the durable source of truth
                if result_path and os.path.exists(result_path):
                    _finalize_proxy_done(entry, model_key, result_path,
                                         migrated)
                    return
                _requeue_local(entry)
                return
            time.sleep(poll_s)
            continue
        j = (out.get("jobs") or [{}])[0]
        st = j.get("status")
        try:
            job.set_progress(float(j.get("progress") or 0.0))
        except Exception:   # noqa: BLE001 — progress is advisory
            pass
        if st == "DONE":
            _finalize_proxy_done(entry, model_key, result_path,
                                 migrated)
            return
        if st in ("FAILED", "CANCELLED"):
            job.status = (jobs_mod.FAILED if st == "FAILED"
                          else jobs_mod.CANCELLED)
            job.exception_msg = j.get("exception_msg") or (
                f"remote train on {entry.remote_member} ended {st}")
            job.end_time = time.time()
            job._end_mono = time.monotonic()
            job._done_evt.set()
            _proxy_done(entry)
            return
        if st in ("RUNNING", "RECOVERING") and \
                job.status == jobs_mod.QUEUED:
            job.mark_dispatched()            # queue-wait clock stops here
            if st == "RECOVERING":
                job.status = jobs_mod.RECOVERING
        time.sleep(poll_s)


def _finalize_proxy_done(entry, model_key: str,
                         result_path: Optional[str],
                         migrated: bool) -> None:
    from h2o3_tpu import dkv, jobs as jobs_mod, recovery
    job = entry.job
    model = None
    if result_path:
        deadline = time.monotonic() + _knob_s(
            "H2O3_FLEET_SCHED_RESULT_WAIT_S", 120.0)
        while not os.path.exists(result_path) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        try:
            from h2o3_tpu.persist import load_model
            model = load_model(result_path)
        except Exception as e:   # noqa: BLE001 — fail the job honestly
            _finalize_proxy_failure(
                entry, f"remote train completed on "
                       f"{entry.remote_member} but its result artifact "
                       f"could not be loaded: {e!r}")
            return
    if model is not None:
        model.key = model_key
        dkv.put(model_key, "model", model)
    try:
        # the train is complete — this process's manifest (if the train
        # started here before migrating) must not resurrect it at boot
        recovery.complete_training(model_key)
    except Exception:   # noqa: BLE001 — advisory cleanup
        pass
    if job.status == jobs_mod.QUEUED:
        job.mark_dispatched()
    job.result = model
    job.set_progress(1.0)
    job.status = jobs_mod.DONE
    job.end_time = time.time()
    job._end_mono = time.monotonic()
    job._done_evt.set()
    if migrated:
        _bb("migrate_done", str(entry.remote_member or ""),
            payload=f"job={job.key} model={model_key}",
            trace_id=getattr(job, "trace_id", None))
    _proxy_done(entry)


# ---------------- preempt-migrate --------------------------------------

def _migration_enabled() -> bool:
    return os.environ.get("H2O3_FLEET_SCHED_MIGRATE", "1") not in (
        "0", "false", "")


def _export_ckpt(builder) -> Optional[str]:
    """The preempted train's DKV ``<key>_ckpt`` as a durable artifact a
    different replica can resume from (PR 9's cross-process format)."""
    root = _xfer_dir()
    if root is None:
        return None
    from h2o3_tpu import dkv
    key = builder._model_key()
    ent = dkv.get_opt(f"{key}_ckpt")
    if ent is None:
        return None
    from h2o3_tpu.persist import save_model
    d = os.path.join(root, "fleet", "ckpts")
    os.makedirs(d, exist_ok=True)
    return save_model(ent[1], d, force=True,
                      filename=f"{key}_migrate.zip")


def _migrate_entry(entry) -> bool:
    """Installed as sched.core.MIGRATOR — called OUTSIDE the scheduler
    cv after a preempted entry unwound. True → the train now runs on
    another replica (the local entry proxies it); False → the caller
    requeues locally (PR 15 behavior)."""
    if not _migration_enabled():
        return False
    if not _submit_eligible(entry.builder, entry.kwargs):
        return False
    placement = _place_for_migrate(entry.estimate.bytes)
    if placement is None:
        return False
    ckpt_path = None
    try:
        ckpt_path = _export_ckpt(entry.builder)
    except Exception:   # noqa: BLE001 — a clean remote re-run still wins
        ckpt_path = None
    job = entry.job

    def _pre():
        # banks the run segment + counts the preempt exactly once — the
        # scheduler's local-requeue fallback does its own marking, so
        # nothing is touched until the hand-off is accepted
        job.mark_requeued()
        entry.preempt_cycles += 1
        entry.dispatch_mono = None

    if not _hand_off(entry, placement["member"],
                     checkpoint_path=ckpt_path, pre_proxy=_pre,
                     migrated=True):
        return False
    _count("migrations")
    _bb("migrate_start",
        str(placement["member"].get("member_id") or ""),
        payload=f"job={job.key} ckpt={int(bool(ckpt_path))}",
        trace_id=getattr(job, "trace_id", None),
        epoch=placement.get("epoch"))
    from h2o3_tpu.log import info
    info("fleet-sched: migrated %s to %s (ckpt=%s)", job.key,
         placement["member"].get("member_id"), bool(ckpt_path))
    return True


def _place_for_migrate(need_bytes: int) -> Optional[Dict[str, Any]]:
    """Placement for a preempted train: remote members only (it was
    just preempted here — local has no headroom by construction), epoch
    pinned like every placement decision."""
    view = current_view()
    if view is None:
        return None
    epoch = int(view.get("epoch") or 0)
    cands = [m for m in _eligible_members(view, local_member_id())
             if _fits(m["sched"], need_bytes)]
    if not cands:
        return None
    return {"member": max(cands, key=_headroom_key), "epoch": epoch}


# ---------------- elastic membership -----------------------------------

def _rebalance_min_interval_s() -> float:
    return _knob_s("H2O3_FLEET_SCHED_REBALANCE_S", 1.0)


def maybe_rebalance(reason: str = "gossip") -> None:
    """Throttled, off-thread rebalance trigger (join handlers, gossip
    ingest, the router ticker)."""
    now = time.monotonic()
    with _MU:
        if now - _REBAL["last"] < _rebalance_min_interval_s():
            return
        _REBAL["last"] = now
    _executor().submit(_safe_rebalance, reason)


def _safe_rebalance(reason: str) -> None:
    try:
        moved = rebalance_queued()
        if moved:
            from h2o3_tpu.log import info
            info("fleet-sched: rebalanced %d queued train(s) (%s)",
                 moved, reason)
    except Exception as e:   # noqa: BLE001 — rebalance is best-effort
        from h2o3_tpu.log import warn
        warn("fleet-sched: rebalance failed: %r", e)


def rebalance_queued() -> int:
    """Steal locally-queued eligible entries and hand them to members
    with headroom (a replica joining mid-grid absorbs queued children).
    Entries that fail to hand off go straight back to the local queue."""
    view = current_view()
    if view is None:
        return 0
    epoch = int(view.get("epoch") or 0)   # the view this decision pins
    cands = [m for m in _eligible_members(view, local_member_id())]
    if not cands:
        return 0
    from h2o3_tpu import sched
    s = sched.scheduler()

    def _eligible_entry(e) -> bool:
        return (e.remote_member is None
                and _submit_eligible(e.builder, e.kwargs))

    taken = s.steal_queued(_eligible_entry,
                           limit=max(2 * len(cands), 2))
    moved = 0
    for i, e in enumerate(taken):
        fitting = [m for m in cands if _fits(m["sched"],
                                             e.estimate.bytes)]
        handed = False
        if fitting:
            target = fitting[i % len(fitting)]
            ckpt = None
            if e.preempt_cycles > 0:
                try:
                    ckpt = _export_ckpt(e.builder)
                except Exception:   # noqa: BLE001 — clean re-run wins
                    ckpt = None
            handed = _hand_off(e, target, checkpoint_path=ckpt)
        if handed:
            moved += 1
        else:
            s.requeue(e)
    if moved:
        _count("rebalanced", moved)
        _bb("rebalance", local_member_id(),
            payload=f"moved={moved} members={len(cands)}", epoch=epoch)
        from h2o3_tpu.log import info
        info("fleet-sched: handed %d queued train(s) to %d member(s) "
             "(epoch %d)", moved, len(cands), epoch)
    return moved


def router_tick(table) -> None:
    """Router-ticker hook: when this process has queued work and the
    table shows members with headroom, trigger a rebalance."""
    try:
        from h2o3_tpu import sched
        if not sched.enabled():
            return
        if sched.scheduler().queue_depth() <= 0:
            return
        view = fleet_view_from_table(table)
        if _eligible_members(view, local_member_id()):
            maybe_rebalance("router-tick")
    except Exception:   # noqa: BLE001 — the ticker must never die here
        pass


def on_member_departed(member, reason: str) -> None:
    """MemberTable depart callback (router process): an EVICTED
    replica's RUNNING checkpointing trains are re-queued fleet-wide
    from their last chunk commit via the recovery manifests. The
    router races the survivors for the victim's lease like any other
    member — it holds no special role in the requeue anymore."""
    if reason != "evicted":
        return                # graceful leave drains its own work
    epoch = 0
    try:
        from h2o3_tpu import fleet
        r = fleet.active_router()
        if r is not None:
            for dep in reversed(r.table.departed()):
                if dep.get("member_id") == member.member_id:
                    epoch = int(dep.get("epoch") or 0)
                    break
    except Exception:   # noqa: BLE001 — epoch is a lease suffix only
        pass
    _executor().submit(_requeue_departed, member.member_id, epoch)


def _lease_dir() -> Optional[str]:
    root = _xfer_dir()
    return os.path.join(root, "leases") if root else None


def _lease_stale_s() -> float:
    return _knob_s("H2O3_FLEET_LEASE_STALE_S", 30.0)


def claim_departed(member_id: str, epoch: int = 0) -> bool:
    """Router-less evict-requeue arbitration (ISSUE 19 satellite): ANY
    survivor that learns of an eviction — from its own member table or
    from the gossiped fleet view — races an ``O_CREAT|O_EXCL`` lease
    file under the shared recovery root. Exactly one process wins and
    requeues the victim's RUNNING manifests; the others back off. A
    lease whose holder died mid-requeue goes stale after
    ``H2O3_FLEET_LEASE_STALE_S`` and is stolen (the steal window is
    deliberately wide — a rare double-resume of the same model key
    beats an orphaned train). Claim and steal are themselves blackbox
    events: the post-mortem shows WHO resumed the victim's work."""
    d = _lease_dir()
    if d is None:
        return False
    me = local_member_id()
    body = json.dumps({"claimant": me, "victim": member_id,
                       "epoch": int(epoch), "wall": time.time()})
    path = os.path.join(
        d, f"{member_id.replace('/', '_')}.{int(epoch)}.lease")
    try:
        os.makedirs(d, exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
        try:
            os.write(fd, body.encode())
        finally:
            os.close(fd)
        _bb("lease_claim", member_id, payload=f"claimant={me}",
            epoch=epoch)
        return True
    except FileExistsError:
        pass
    except OSError:
        return False
    try:
        with open(path) as f:
            held = json.loads(f.read() or "{}")
    except (OSError, ValueError):
        held = {}
    age = time.time() - float(held.get("wall") or 0.0)  # h2o3-lint: allow[monotonic-durations] lease age must compare across processes — wall time is the only shared clock
    if age < _lease_stale_s():
        return False              # a live claimant owns the requeue
    tmp = f"{path}.steal.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, path)
    except OSError:
        return False
    _bb("lease_steal", member_id,
        payload=f"claimant={me} from={held.get('claimant')} "
                f"age={age:.0f}s", epoch=epoch)
    return True


def _requeue_departed(member_id: str, epoch: int = 0) -> None:
    from h2o3_tpu import recovery
    from h2o3_tpu.log import info, warn
    if recovery.recovery_dir() is None:
        return
    if not claim_departed(member_id, epoch):
        return                    # another survivor holds the lease
    try:
        entries, _corrupt = recovery.scan(quarantine=False)
    except Exception as e:   # noqa: BLE001 — scan failure is not fatal
        warn("fleet-sched: evict-requeue scan failed: %r", e)
        return
    mine = [e for e in entries if e.get("member_id") == member_id]
    if not mine:
        return
    info("fleet-sched: evicted %s left %d in-flight train(s) — "
         "re-queueing fleet-wide", member_id, len(mine))
    for ent in mine:
        try:
            if _resubmit_manifest(ent):
                _count("evict_requeues")
                _bb("evict_requeue", member_id,
                    payload=f"model={ent.get('model_key')}",
                    trace_id=ent.get("trace_id") or None, epoch=epoch)
        except Exception as e:   # noqa: BLE001 — per-train isolation
            warn("fleet-sched: evict-requeue of %s failed: %r",
                 ent.get("model_key"), e)


def _resubmit_manifest(ent: Dict[str, Any]) -> bool:
    """One evicted replica's manifest → a live member (or this process
    as the last resort). The manifest carries the original priority
    class + share group (satellite 1), the trace id, and the newest
    durable checkpoint — the resume starts from the last chunk commit."""
    model_key = str(ent.get("model_key") or "")
    params = dict(ent.get("params") or {})
    params["model_id"] = model_key
    if ent.get("latest_ckpt"):
        params["checkpoint"] = ent["latest_ckpt"]
    payload = {
        "schema_version": SCHED_SCHEMA_VERSION,
        "algo": ent.get("algo"),
        "params": params,
        "y": ent.get("y"),
        "x": ent.get("x"),
        "frame_path": ent.get("frame_path"),
        "frame_key": ent.get("frame_key"),
        "priority": ent.get("priority") or "background",
        "share": ent.get("share") or "recovery",
        "trace_id": ent.get("trace_id"),
        "model_key": model_key,
        "result_path": _result_path(model_key),
        "resuming": True,
        "submitter": local_member_id(),
    }
    try:
        ntrees = int(params.get("ntrees", 0) or 0)
    except (TypeError, ValueError):
        ntrees = 0
    if ent.get("latest_ckpt") and ntrees and \
            int(ent.get("ckpt_trees") or 0) >= ntrees:
        payload["register_artifact"] = ent["latest_ckpt"]
    view = current_view()
    if view is not None:
        epoch = int(view.get("epoch") or 0)   # placement pins the epoch
        cands = sorted(_eligible_members(view, local_member_id()),
                       key=_headroom_key, reverse=True)
        for m in cands:
            try:
                out = _post_json(
                    f"{m['base_url']}/3/FleetSched/submit", payload,
                    timeout_s=_submit_timeout_s(),
                    site="fleet.sched.requeue", attempts=1)
            except Exception:   # noqa: BLE001 — try the next member
                continue
            if isinstance(out, dict) and out.get("ok"):
                from h2o3_tpu.log import info
                info("fleet-sched: %s re-queued on %s (epoch %d)",
                     model_key, m.get("member_id"), epoch)
                return True
    # no live member took it: this process resumes it (the router is a
    # fleet member too — a 1-survivor fleet must still finish the train)
    from h2o3_tpu import sched
    if not sched.enabled():
        return False
    from h2o3_tpu import recovery
    out = recovery._resume_entry(ent, wait=False)
    return bool(out.get("job_key") or out.get(
        "completed_from_artifact"))


# ---------------- cluster snapshot (satellite 3) -----------------------

def cluster_scheduler_snapshot() -> Dict[str, Any]:
    """GET /3/Scheduler?scope=cluster: this process's snapshot merged
    with every peer's through the PR-8 telemetry peer plane (same
    member-sourced peer list, dead peers flagged, never fatal)."""
    from h2o3_tpu import sched
    from h2o3_tpu.telemetry import snapshot as telesnap
    local = sched.scheduler().snapshot()
    replicas: Dict[str, Any] = {local_member_id(): local}
    failed: List[Dict[str, Any]] = []
    peers, departed = [], []
    try:
        peers, departed = telesnap.peer_view()
    except Exception as e:   # noqa: BLE001 — never fatal
        failed.append({"peer": "peer_view", "error": repr(e)})
    for peer in dict.fromkeys(peers):
        url = peer if peer.startswith("http") else f"http://{peer}"
        try:
            snap = _get_json(f"{url}/3/Scheduler",
                             timeout_s=telesnap.PEER_TIMEOUT_S,
                             site="fleet.sched.cluster")
            snap.pop("__meta", None)
            replicas[peer] = snap
        except Exception as e:   # noqa: BLE001 — dead peers are flagged
            failed.append({"peer": peer, "error": repr(e)})
    heads = [r.get("headroom_bytes") for r in replicas.values()
             if isinstance(r.get("headroom_bytes"), int)]
    totals = {
        "replicas": len(replicas),
        "queued": sum(len(r.get("queued") or [])
                      for r in replicas.values()),
        "running": sum(len(r.get("running") or [])
                       for r in replicas.values()),
        "headroom_bytes": (-1 if any(h < 0 for h in heads)
                           else sum(heads)) if heads else 0,
    }
    return {"scope": "cluster", "replicas": replicas, "totals": totals,
            "peers_failed": failed, "peers_evicted": departed,
            "counters": counters()}


# ---------------- wiring -----------------------------------------------

def install_hooks() -> None:
    """Route every local submission and preemption through the fleet
    (sched.core hooks). Installed by FleetAgent.start (replica side)
    and fleet._wire (router side); both hooks no-op cheaply when no
    fleet view exists."""
    from h2o3_tpu.sched import core as sched_core
    sched_core.PLACER = _placer_hook
    sched_core.MIGRATOR = _migrate_entry


def uninstall_hooks() -> None:
    from h2o3_tpu.sched import core as sched_core
    if sched_core.PLACER is _placer_hook:
        sched_core.PLACER = None
    if sched_core.MIGRATOR is _migrate_entry:
        sched_core.MIGRATOR = None


def reset() -> None:
    """Tests / fleet.reset(): drop hooks, gossip, caches and counters.
    In-flight proxy loops keep their entry references and finish."""
    uninstall_hooks()
    with _MU:
        _LOCAL["member_id"] = None
        _LOCAL["base_url"] = None
        _GOSSIP["view"] = None
        _GOSSIP["mono"] = 0.0
        _RR.clear()
        _FRAMES.clear()
        _SEEN_DEPARTED.clear()
        _REBAL["last"] = 0.0
        for k in list(_COUNTERS):
            _COUNTERS[k] = 0
