from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.frame.frame import Frame

__all__ = ["Vec", "Frame"]
