"""Frame — a named collection of Vecs (columnar distributed table).

Reference: water/fvec/Frame.java:65 — ordered column names over Vec keys in
the DKV, with cluster-wide lock semantics (water/Lockable.java:25). Here a
Frame is a host-side object holding row-sharded device columns; locking
disappears (single controller), lifecycle is Python GC + the registry used
by the REST layer.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.vec import T_ENUM, T_INT, T_REAL, T_STR, T_TIME, Vec
from h2o3_tpu.parallel.mesh import current_mesh


class Frame:
    def __init__(self, names: Sequence[str], vecs: Sequence[Vec], key: Optional[str] = None):
        assert len(names) == len(vecs)
        nrows = {v.nrow for v in vecs}
        if len(nrows) > 1:
            raise ValueError(f"column lengths differ: {nrows}")
        self._names: List[str] = list(names)
        self._vecs: List[Vec] = list(vecs)
        self.key = key

    # ---------------- construction ----------------

    @staticmethod
    def from_numpy(data: Union[np.ndarray, Dict[str, np.ndarray]],
                   names: Optional[Sequence[str]] = None, mesh=None) -> "Frame":
        mesh = mesh or current_mesh()
        if isinstance(data, dict):
            names = list(data.keys())
            cols = [np.asarray(c) for c in data.values()]
        else:
            data = np.asarray(data)
            if data.ndim == 1:
                data = data[:, None]
            cols = [data[:, i] for i in range(data.shape[1])]
            if names is None:
                names = [f"C{i + 1}" for i in range(len(cols))]
        return Frame(list(names), [Vec.from_numpy(c, mesh=mesh) for c in cols])

    @staticmethod
    def from_typed_columns(names: Sequence[str], cols, mesh=None,
                           key: Optional[str] = None) -> "Frame":
        """Assemble a Frame from fully-typed merged columns (duck-typed:
        ``.vtype``/``.data``/``.domain``, see ingest/chunk.py) with ONE
        host→device transfer per dtype group instead of one per column."""
        cols = list(cols)
        return Frame.from_typed_column_groups(
            names, [list(enumerate(cols))], len(cols), mesh=mesh, key=key)

    @staticmethod
    def from_typed_column_groups(names: Sequence[str], groups, ncol: int,
                                 mesh=None, key: Optional[str] = None,
                                 preset: Optional[Dict[int, Vec]] = None
                                 ) -> "Frame":
        """Streaming variant of :func:`from_typed_columns`: ``groups`` is
        an ITERABLE of ``[(column_index, EncodedColumn-like), ...]``
        lists. Each group's (async) host→device DMAs are issued before
        the next group is pulled from the iterable — so a generator can
        defer its expensive merge work (the enum domain union) until the
        cheap groups' transfers are already in flight, overlapping DMA
        with host-side merging (the ingest pipeline's last
        serialization point, ROADMAP "pack+transfer" lever).

        ``preset`` slots in columns already assembled elsewhere — the
        per-chunk device streamer (ingest/stream.py) hands its finished
        numeric/time Vecs over this way while enum/str columns still ride
        the grouped host merge."""
        from h2o3_tpu.frame.vec import (ENUM_NA, _numeric_host_copy,
                                        batch_device_put)
        mesh = mesh or current_mesh()
        vecs: List[Optional[Vec]] = [None] * ncol
        nrow = 0
        if preset:
            for i, v in preset.items():
                vecs[i] = v
                nrow = v.nrow
        for group in groups:
            f32_cols, f32_meta = [], []  # numeric + time: one f32 matrix
            i32_cols, i32_meta = [], []  # enum codes: one i32 matrix
            for i, c in group:
                nrow = len(c.data)
                if c.vtype == T_STR:
                    vecs[i] = Vec(None, nrow, T_STR,
                                  host_data=np.asarray(c.data, dtype=object))
                elif c.vtype == T_ENUM:
                    i32_cols.append(np.asarray(c.data, dtype=np.int32))
                    i32_meta.append((i, list(c.domain or ())))
                elif c.vtype == T_TIME:
                    ms = np.asarray(c.data, dtype=np.int64)
                    sec = np.where(ms == Vec.TIME_NA, np.nan,
                                   ms / 1000.0).astype(np.float32)
                    f32_cols.append(sec)
                    f32_meta.append((i, T_TIME, ms))
                else:
                    f64 = c.data
                    host = (f64 if f64.dtype == np.int64  # exact wide ints
                            else _numeric_host_copy(f64, c.vtype))
                    # raw f64 goes straight into the pack matrix — the
                    # assignment converts to f32 in the same pass
                    f32_cols.append(f64)
                    f32_meta.append((i, c.vtype, host))
            if f32_cols:
                devs = batch_device_put(f32_cols, np.float32(np.nan),
                                        np.float32, nrow, mesh)
                for (i, vt, host), d in zip(f32_meta, devs):
                    vecs[i] = Vec(d, nrow, vt, host_data=host)
            if i32_cols:
                devs = batch_device_put(i32_cols, np.int32(ENUM_NA),
                                        np.int32, nrow, mesh)
                for (i, dom), d in zip(i32_meta, devs):
                    vecs[i] = Vec(d, nrow, T_ENUM, domain=dom)
        return Frame(list(names), vecs, key=key)

    # ---------------- shape / access ----------------

    @property
    def nrow(self) -> int:
        return self._vecs[0].nrow if self._vecs else 0

    @property
    def ncol(self) -> int:
        return len(self._vecs)

    @property
    def names(self) -> List[str]:
        return list(self._names)

    @property
    def vecs(self) -> List[Vec]:
        return list(self._vecs)

    @property
    def types(self) -> Dict[str, str]:
        return {n: v.type for n, v in zip(self._names, self._vecs)}

    def vec(self, name_or_idx: Union[str, int]) -> Vec:
        if isinstance(name_or_idx, int):
            return self._vecs[name_or_idx]
        return self._vecs[self._names.index(name_or_idx)]

    def __contains__(self, name: str) -> bool:
        return name in self._names

    def __getitem__(self, sel) -> "Frame":
        if isinstance(sel, str):
            return Frame([sel], [self.vec(sel)])
        if isinstance(sel, (list, tuple)) and all(isinstance(s, str) for s in sel):
            return Frame(list(sel), [self.vec(s) for s in sel])
        if isinstance(sel, (slice, np.ndarray)):
            return self.rows(sel)
        raise TypeError(f"unsupported selector {sel!r}")

    def __setitem__(self, name: str, vec: Vec):
        if isinstance(vec, Frame):
            assert vec.ncol == 1
            vec = vec.vec(0)
        if name in self._names:
            self._vecs[self._names.index(name)] = vec
        else:
            self._names.append(name)
            self._vecs.append(vec)

    def drop(self, names: Union[str, Iterable[str]]) -> "Frame":
        if isinstance(names, str):
            names = [names]
        drop = set(names)
        keep = [(n, v) for n, v in zip(self._names, self._vecs) if n not in drop]
        return Frame([n for n, _ in keep], [v for _, v in keep])

    def cbind(self, other: "Frame") -> "Frame":
        return Frame(self._names + other._names, self._vecs + other._vecs)

    def rename(self, mapping: Dict[str, str]) -> "Frame":
        return Frame([mapping.get(n, n) for n in self._names], self._vecs)

    def resharded(self, mesh) -> "Frame":
        """Rebuild this frame's device columns under a DIFFERENT mesh
        (new row padding + data-axis layout). The multichip bench and
        the SPMD parity tests carve sub-meshes out of the device set and
        need the SAME logical table laid out per mesh — the reference's
        analog is re-homing chunks after cloud membership changes.

        Host-exact shadows (str/time/wide-int) are carried over; device
        payloads make one host round-trip (resharding across different
        paddings is a host repack anyway)."""
        new_vecs = []
        for v in self._vecs:
            if v.type == T_STR:
                new_vecs.append(Vec(None, v.nrow, T_STR,
                                    host_data=v.host_data))
            elif v.type == T_TIME:
                new_vecs.append(Vec.from_numpy(v.to_numpy(), vtype=T_TIME,
                                               mesh=mesh))
            else:
                new_vecs.append(Vec.from_numpy(v.to_numpy(), vtype=v.type,
                                               domain=v.domain, mesh=mesh))
        return Frame(self.names, new_vecs, key=self.key)

    # ---------------- row selection ----------------

    def rows(self, sel) -> "Frame":
        """Row subset by slice or host boolean/index array. Gather happens
        host-side then re-shards (the reference materialises subset frames
        with a deep-slice MRTask; a host gather keeps it simple — device
        gather is a later optimisation)."""
        idx = np.arange(self.nrow)[sel] if isinstance(sel, slice) else np.asarray(sel)
        if idx.dtype == bool:
            idx = np.nonzero(idx)[0]
        new_vecs = []
        for v in self._vecs:
            if v.type == T_STR:
                new_vecs.append(Vec.from_numpy(v.host_data[idx], vtype=T_STR))
            else:
                raw = v.to_numpy()[idx]
                new_vecs.append(Vec.from_numpy(raw, vtype=v.type, domain=v.domain))
        return Frame(self.names, new_vecs)

    def head(self, n: int = 10) -> "Frame":
        return self.rows(slice(0, min(n, self.nrow)))

    def split_frame(self, ratios: Sequence[float], seed: int = -1) -> List["Frame"]:
        """Random split (reference: hex/splitframe/ShuffleSplitFrame) —
        per-row uniform draw against cumulative ratios."""
        rng = np.random.default_rng(None if seed in (-1, None) else seed)
        u = rng.random(self.nrow)
        cuts = np.cumsum(list(ratios))
        if len(cuts) == 0 or cuts[-1] < 1.0 - 1e-9:
            cuts = np.append(cuts, 1.0)
        assign = np.searchsorted(cuts, u, side="right")
        return [self.rows(assign == i) for i in range(len(cuts))]

    # ---------------- materialisation ----------------

    def to_numpy(self) -> np.ndarray:
        """Dense float matrix of numeric view (enum → codes, NA → NaN)."""
        cols = []
        for v in self._vecs:
            if v.type == T_STR:
                cols.append(np.full(self.nrow, np.nan, dtype=np.float32))
            else:
                raw = v.to_numpy().astype(np.float64)
                if v.type == T_ENUM:
                    raw = np.where(raw < 0, np.nan, raw)
                cols.append(raw)
        return np.stack(cols, axis=1) if cols else np.empty((self.nrow, 0))

    def to_dict(self) -> Dict[str, np.ndarray]:
        return {n: v.to_numpy() for n, v in zip(self._names, self._vecs)}

    def as_matrix(self, names: Optional[Sequence[str]] = None):
        """Device float32 [padded_rows, ncol] matrix (enum codes as floats,
        NA→NaN) — the dense hand-off into model builders. String columns
        become all-NaN (no device representation)."""
        names = names or self._names
        cols = []
        plen = None
        for n in names:
            v = self.vec(n)
            if v.type == T_STR:
                cols.append(None)
            else:
                cols.append(v.as_float())
                plen = cols[-1].shape[0]
        if plen is None:
            raise ValueError("as_matrix needs at least one non-string column")
        cols = [jnp.full(plen, jnp.nan, dtype=jnp.float32) if c is None else c
                for c in cols]
        return jnp.stack(cols, axis=1)

    def summary(self) -> Dict[str, dict]:
        return {n: v.rollups() for n, v in zip(self._names, self._vecs)}

    def __repr__(self):
        return f"<Frame {self.key or ''} {self.nrow}x{self.ncol} {self.types}>"
