"""RollupStats — lazy per-column statistics, one fused jitted reduction.

Reference: water/fvec/RollupStats.java:17 computes min/max/mean/sigma/
nzCnt/NA-count (+ histogram & percentiles) as an MRTask over chunks with a
cluster CAS to dedupe computation. Here it is a single XLA reduction over
the sharded column; GSPMD inserts the cross-device psum automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _rollup_kernel(data, nrow):
    # nrow is TRACED (a device scalar), not a static argnum: the padded
    # shape is already bucketed by padded_len, so tracing nrow means one
    # compile per padded length instead of one per distinct frame length
    n = data.shape[0]
    valid = (jnp.arange(n) < nrow) & ~jnp.isnan(data)
    x = jnp.where(valid, data, 0.0)
    cnt = valid.sum()
    fcnt = jnp.maximum(cnt, 1).astype(jnp.float32)
    s = x.sum()
    mean = s / fcnt
    # two-pass sigma for stability (the reference uses streaming Welford
    # merges up the reduce tree; two fused passes are cheaper on TPU)
    var = jnp.where(valid, (data - mean) ** 2, 0.0).sum() / jnp.maximum(fcnt - 1.0, 1.0)
    mn = jnp.where(valid, data, jnp.inf).min()
    mx = jnp.where(valid, data, -jnp.inf).max()
    nz = (valid & (data != 0.0)).sum()
    pinf = (valid & jnp.isposinf(data)).sum()
    ninf = (valid & jnp.isneginf(data)).sum()
    return cnt, s, mean, jnp.sqrt(var), mn, mx, nz, pinf, ninf


def compute_rollups(vec) -> dict:
    from h2o3_tpu.frame.vec import T_ENUM, T_STR

    if vec.type == T_STR:
        isna = np.array([v is None or v == "" for v in vec.host_data])
        return {"na_count": int(isna.sum()), "rows": vec.nrow, "mean": np.nan,
                "sigma": np.nan, "min": np.nan, "max": np.nan, "nz_count": int((~isna).sum()),
                "pinfs": 0, "ninfs": 0, "is_const": False}
    data = vec.as_float()
    import time as _time
    from h2o3_tpu.telemetry import costmodel
    # performance accounting (ISSUE 11): the rollup reduction is the
    # frame-assembly jit seam the compile counter already sees; one
    # trace+lower per padded column shape, paired with the measured
    # kernel-to-host wall (the np.asarray fetches below block on it).
    # The COLD call per shape is skipped entirely: its wall is
    # dominated by the first-call backend compile (and the capture's
    # own trace+lower), which would poison the cumulative achieved
    # rate this plane exists to make honest.
    ck = ("frame.rollup", data.shape, str(data.dtype))
    warm = costmodel.cost_cached(ck)
    t0 = _time.perf_counter()
    cnt, s, mean, sigma, mn, mx, nz, pinf, ninf = [
        np.asarray(v) for v in _rollup_kernel(data, vec.nrow)]
    dt = _time.perf_counter() - t0
    cost = costmodel.executable_cost(
        ck, lambda: _rollup_kernel.lower(data, vec.nrow))
    if warm:
        costmodel.record("frame.rollup", cost, seconds=dt)
    cnt = int(cnt)
    out = {
        "rows": vec.nrow,
        "na_count": vec.nrow - cnt,
        "mean": float(mean) if cnt else np.nan,
        "sigma": float(sigma) if cnt > 1 else 0.0 if cnt else np.nan,
        "min": float(mn) if cnt else np.nan,
        "max": float(mx) if cnt else np.nan,
        "nz_count": int(nz),
        "pinfs": int(pinf),
        "ninfs": int(ninf),
    }
    out["is_const"] = cnt > 0 and out["min"] == out["max"]
    if vec.type == T_ENUM:
        out["cardinality"] = vec.cardinality
    return out


@jax.jit
def _quantile_kernel(data, probs):
    return jnp.nanquantile(data, probs)


def compute_percentiles(vec, probs) -> np.ndarray:
    """Exact quantiles via device sort (the reference iteratively refines a
    distributed histogram — hex/quantile/Quantile.java:87 — an on-device
    global sort is simpler and exact at TPU memory scales)."""
    data = vec.as_float()
    return np.asarray(_quantile_kernel(data, jnp.asarray(probs, dtype=jnp.float32)))


@jax.jit
def _weighted_quantile_kernel(data, w, probs):
    """Weighted type-7-style quantiles: sort, interpolate on the
    cumulative-weight axis (hex/quantile/Quantile.java weighted path).
    NaN data or NaN/zero weights are excluded from the curve."""
    order = jnp.argsort(data)          # NaN sorts last
    d = data[order]
    ws = jnp.where(jnp.isnan(w), 0.0, w)[order]
    valid = ~jnp.isnan(d)
    ws = jnp.where(valid, ws, 0.0)
    cw = jnp.cumsum(ws)
    tot = cw[-1]
    # replace NaN tail values with the LAST valid value so interp's
    # upper endpoint is finite (their weight is 0 — position unchanged)
    last_valid_idx = jnp.argmax(jnp.where(valid, jnp.arange(d.shape[0]),
                                          -1))
    d = jnp.where(valid, d, d[last_valid_idx])
    # position of each sorted point on the (0, 1] cumulative-weight axis,
    # centered per observation (matches numpy for unit weights)
    pos = (cw - 0.5 * ws) / jnp.maximum(tot, 1e-30)
    return jnp.interp(probs, pos, d)


def weighted_quantile(vec_or_array, probs, weights=None) -> np.ndarray:
    """Weighted quantiles of a Vec or array; NaN data rows are ignored."""
    data = (vec_or_array.as_float() if hasattr(vec_or_array, "as_float")
            else jnp.asarray(np.asarray(vec_or_array), jnp.float32))
    if weights is None:
        w = jnp.ones_like(data)
    elif hasattr(weights, "as_float"):
        w = weights.as_float()
        w = jnp.where(jnp.isnan(w), 0.0, w)
    else:
        w = jnp.asarray(np.asarray(weights), jnp.float32)
    # NaN data sorts last; weights zeroed in-kernel
    return np.asarray(_weighted_quantile_kernel(
        data, w, jnp.asarray(probs, jnp.float32)))


def stratified_quantile(vec, probs, strata_vec) -> dict:
    """Per-stratum quantiles (hex/quantile stratified mode): one device
    pass per stratum with the stratum mask as weights."""
    sv = strata_vec.as_float()
    vals = np.unique(np.asarray(sv)[~np.isnan(np.asarray(sv))])
    out = {}
    for v in vals:
        mask = (sv == float(v)).astype(jnp.float32)
        out[float(v)] = np.asarray(_weighted_quantile_kernel(
            vec.as_float(), mask, jnp.asarray(probs, jnp.float32)))
    return out
