"""Vec — one column of a distributed Frame.

Reference: water/fvec/Vec.java:157 — a Vec is a named column whose rows are
split into compressed Chunks stored in the DKV, with an ESPC row layout and
lazily-computed RollupStats. TPU re-design:

- the ~20 chunk compressor subtypes (water/fvec/C*.java, chosen by
  NewChunk.compress()) collapse into dtype choice on a single padded,
  row-sharded ``jax.Array`` — XLA wants flat dense typed buffers, not
  per-chunk byte-packing;
- the ESPC layout (water/fvec/Vec.java:163-171) becomes an even row
  partition over the mesh 'data' axis (static shapes for XLA), padded at
  the tail; validity is derived from ``row_index < nrow`` plus NA
  sentinels;
- types mirror Vec.T_* (water/fvec/Vec.java:207-212): real/int/enum/time/
  str. Enum domains are host-side tuples (the reference's String[] domain).

NA encoding: NaN for float data, -1 for enum codes. Time is stored on
device as float32 epoch-seconds (exact int64 millis kept host-side when
available). Strings are host-only (no device representation — same as the
reference, which never computes on strings distributedly except via Rapids
string ops, which we run host-side).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import current_mesh, padded_len
from h2o3_tpu.telemetry import record_d2h, record_h2d

T_REAL = "real"
T_INT = "int"
T_ENUM = "enum"
T_TIME = "time"
T_STR = "string"

ENUM_NA = -1

# reference default percentiles: water/fvec/Vec.java PERCENTILES
PERCENTILES = (0.001, 0.01, 0.1, 0.25, 1.0 / 3.0, 0.5, 2.0 / 3.0, 0.75, 0.9, 0.99, 0.999)


class Vec:
    def __init__(self, data, nrow: int, vtype: str = T_REAL,
                 domain: Optional[Sequence[str]] = None, host_data=None):
        self._dev = data            # padded, row-sharded jax.Array (None for str vecs)
        self._spilled = None        # (padded numpy, sharding) when evicted
        self._memblock = None
        self.nrow = int(nrow)
        self.type = vtype
        self.domain = tuple(domain) if domain is not None else None
        self.host_data = host_data  # numpy: exact values for str/time
        self._rollups = None
        if data is not None:
            self._register_mem()

    # -- device-memory management (water/Cleaner.java swap-to-disk
    #    analog: HBM payloads spill to host numpy under pressure and
    #    re-materialize on next access; see h2o3_tpu/memman.py) --------

    def _register_mem(self):
        import weakref
        from h2o3_tpu import memman
        ref = weakref.ref(self)

        def spill():
            v = ref()
            if v is not None:
                v._spill()

        try:
            nbytes = int(self._dev.nbytes)
        except (AttributeError, TypeError):
            nbytes = self.nrow * 4
        # allocation gate: evict LRU payloads if this one crosses the
        # watermark (the payload itself is already on device — XLA
        # allocated it — but the budget accounting evicts peers so the
        # NEXT allocation has room; MemoryManager.java's malloc gate)
        memman.manager().request(nbytes)
        self._memblock = memman.manager().register(nbytes, spill)

    def _spill(self):
        """Move the device payload to host and release the device ref."""
        if self._dev is None:
            return
        arr = np.asarray(jax.device_get(self._dev))
        record_d2h(arr.nbytes, fallback="frame")
        # _spill runs as the memman spill callback, i.e. UNDER
        # memman._LOCK (manager().request holds it while evicting) —
        # the writes are lock-protected interprocedurally, which the
        # per-module lock-discipline analysis cannot see
        self._spilled = (arr, getattr(self._dev, "sharding", None))  # h2o3-lint: allow[lock-discipline] runs under memman._LOCK via the spill callback
        self._dev = None  # h2o3-lint: allow[lock-discipline] runs under memman._LOCK via the spill callback
        self._memblock = None

    @property
    def data(self):
        # lock-free fast path: capture the reference FIRST — a
        # concurrent spill (another thread's memman.request) may null
        # _dev after the check, but the captured device array stays
        # valid (the spill only drops the Vec's own reference)
        dev = self._dev
        if dev is None and self._spilled is not None:
            from h2o3_tpu import memman
            with memman._LOCK:           # serialize vs concurrent spills
                dev = self._dev
                if dev is None and self._spilled is not None:
                    arr, sh = self._spilled
                    memman.manager().request(arr.nbytes)
                    try:
                        # the unspill upload deliberately happens under
                        # the memman lock: a concurrent request() must
                        # not evict the block being restored mid-flight
                        dev = (jax.device_put(arr, sh) if sh is not None  # h2o3-lint: allow[lock-discipline] unspill must serialize vs concurrent eviction
                               else jnp.asarray(arr))
                    except Exception:   # mesh changed: replicate
                        dev = jnp.asarray(arr)
                    self._dev = dev
                    self._spilled = None
                    self._register_mem()
        blk = self._memblock
        if blk is not None:
            from h2o3_tpu import memman
            memman.manager().touch(blk)
        return dev

    @data.setter
    def data(self, v):
        # setter races are the CALLER's contract (a Vec is published to
        # other threads only after construction/mutation completes —
        # frame ops build new Vecs, they do not mutate shared ones)
        self._dev = v  # h2o3-lint: allow[lock-discipline] single-owner mutation before publication
        self._spilled = None  # h2o3-lint: allow[lock-discipline] single-owner mutation before publication
        self._memblock = None
        if v is not None:
            self._register_mem()

    # ---------------- construction ----------------

    TIME_NA = np.iinfo(np.int64).min  # host sentinel for missing timestamps

    @staticmethod
    def from_numpy(arr: np.ndarray, vtype: Optional[str] = None,
                   domain: Optional[Sequence[str]] = None, mesh=None) -> "Vec":
        mesh = mesh or current_mesh()
        arr = np.asarray(arr)
        explicit = vtype is not None
        if vtype is None:
            if arr.dtype.kind in "OUS":
                return Vec._from_strings(arr, mesh)
            vtype = T_INT if arr.dtype.kind in "iub" else T_REAL
        nrow = len(arr)
        if vtype == T_STR:
            return Vec(None, nrow, T_STR, host_data=np.asarray(arr, dtype=object))
        if vtype == T_ENUM:
            codes = np.asarray(arr, dtype=np.int32)
            dev = _pad_and_put(codes, nrow, np.int32(ENUM_NA), mesh)
            return Vec(dev, nrow, T_ENUM, domain=domain)
        if vtype == T_TIME:
            host = np.asarray(arr, dtype=np.int64)
            sec = np.where(host == Vec.TIME_NA, np.nan, host / 1000.0).astype(np.float32)
            dev = _pad_and_put(sec, nrow, np.float32(np.nan), mesh)
            return Vec(dev, nrow, T_TIME, host_data=host)
        # wide int64 input (beyond float64's exact 2^53): the float64
        # round-trip would silently munge values, so the exact int64
        # array itself becomes the host copy (water/fvec/C8Chunk)
        if (vtype == T_INT and arr.dtype.kind in "iu" and arr.size
                and np.abs(arr, dtype=np.float64).max() >= float(1 << 53)
                # uint64 above int64 max can't ride the exact shadow —
                # asarray would wrap it negative; let it degrade to the
                # approximate float64 path below instead
                and (arr.dtype.kind == "i"
                     or arr.max() <= np.uint64(np.iinfo(np.int64).max))):
            f64 = np.asarray(arr, dtype=np.int64)
            dev = _pad_and_put(f64.astype(np.float32), nrow,
                               np.float32(np.nan), mesh)
            return Vec(dev, nrow, T_INT, host_data=f64.copy())
        f64 = np.asarray(arr, dtype=np.float64)
        f = f64.astype(np.float32)
        if not explicit and vtype == T_INT and not _is_integral(f64):
            vtype = T_REAL
        dev = _pad_and_put(f, nrow, np.float32(np.nan), mesh)
        return Vec(dev, nrow, vtype, host_data=_numeric_host_copy(f64, vtype))

    @staticmethod
    def _from_strings(arr: np.ndarray, mesh) -> "Vec":
        """String column → enum (codes + domain), mirroring the parser's
        categorical handling (water/parser/ParseDataset.java PackedDomains)."""
        arr = np.asarray(arr, dtype=object)
        isna = np.array([x is None or (isinstance(x, float) and np.isnan(x)) or x == ""
                         for x in arr])
        vals = np.array(["" if m else str(v) for v, m in zip(arr, isna)])
        domain = np.unique(vals[~isna]) if (~isna).any() else np.array([], dtype=str)
        codes = np.searchsorted(domain, vals).astype(np.int32)
        codes[isna] = ENUM_NA
        dev = _pad_and_put(codes, len(arr), np.int32(ENUM_NA), mesh)
        return Vec(dev, len(arr), T_ENUM, domain=[str(d) for d in domain])

    @staticmethod
    def constant(value: float, nrow: int, mesh=None) -> "Vec":
        return Vec.from_numpy(np.full(nrow, value, dtype=np.float32), mesh=mesh)

    # ---------------- properties ----------------

    def __len__(self) -> int:
        return self.nrow

    @property
    def is_numeric(self) -> bool:
        return self.type in (T_REAL, T_INT)

    @property
    def is_categorical(self) -> bool:
        return self.type == T_ENUM

    @property
    def cardinality(self) -> int:
        return len(self.domain) if self.domain is not None else -1

    def valid_mask(self):
        """Device bool mask of real (non-pad, non-NA) rows."""
        if self.data is None:
            raise ValueError("string Vec has no device representation")
        n = self.data.shape[0]
        inrange = jnp.arange(n) < self.nrow
        if self.type == T_ENUM:
            return inrange & (self.data >= 0)
        return inrange & ~jnp.isnan(self.data)

    def asfactor(self) -> "Vec":
        """Numeric → categorical conversion (h2o-py ``vec.asfactor()``;
        water/rapids/ast/prims/operators/AstAsFactor semantics): distinct
        finite values become the sorted domain, NA stays NA."""
        if self.type == T_ENUM:
            return self
        if self.type == T_STR:
            return Vec._from_strings(self.host_data, current_mesh())
        raw = self.to_numpy()
        finite = np.isfinite(raw)
        vals = np.unique(raw[finite])
        domain = tuple(str(int(v)) if float(v).is_integer() else str(v)
                       for v in vals)
        codes = np.searchsorted(vals, raw).astype(np.int32)
        codes[~finite] = ENUM_NA
        return Vec.from_numpy(codes, vtype=T_ENUM, domain=domain)

    def asnumeric(self) -> "Vec":
        """Categorical → numeric (h2o-py ``vec.asnumeric()``): domain labels
        parse back to numbers when possible, else the codes are used."""
        if self.type != T_ENUM:
            return self
        codes = self.to_numpy()
        try:
            lut = np.array([float(d) for d in self.domain], dtype=np.float32)
            out = np.where(codes >= 0, lut[np.maximum(codes, 0)], np.nan)
        except (ValueError, TypeError):
            out = np.where(codes >= 0, codes.astype(np.float32), np.nan)
        return Vec.from_numpy(out.astype(np.float32))

    def as_float(self):
        """Device float32 view with NA→NaN (enums become their codes)."""
        if self.data is None:
            raise ValueError("string Vec has no device representation; "
                             "drop or re-type string columns before compute")
        if self.type == T_ENUM:
            return jnp.where(self.data >= 0, self.data.astype(jnp.float32), jnp.nan)
        return self.data

    # ---------------- rollups ----------------

    def rollups(self) -> dict:
        """Lazy cached per-column stats — the RollupStats contract
        (water/fvec/RollupStats.java:7-16): computed on first ask, cached,
        invalidated on write. The reference races a DKV CAS to pick the
        computing node; single-controller JAX just computes once here."""
        if self._rollups is None:
            from h2o3_tpu.frame.rollups import compute_rollups
            self._rollups = compute_rollups(self)
        return self._rollups

    def invalidate_rollups(self):
        self._rollups = None

    def mean(self):
        return self.rollups()["mean"]

    def sigma(self):
        return self.rollups()["sigma"]

    def min(self):
        return self.rollups()["min"]

    def max(self):
        return self.rollups()["max"]

    def na_count(self):
        return self.rollups()["na_count"]

    def percentiles(self, probs=PERCENTILES):
        from h2o3_tpu.frame.rollups import compute_percentiles
        return compute_percentiles(self, probs)

    # ---------------- materialisation ----------------

    def to_numpy(self) -> np.ndarray:
        """Unpadded host copy. Enum → int codes (use .domain to decode);
        time → int64 millis; str → object array."""
        if self.type == T_STR:
            return self.host_data.copy()
        if self.host_data is not None:
            if self.type == T_TIME:
                return self.host_data.copy()
            # exact wide-int copy, NA as NaN (float64 holds ints to 2^53)
            return self.host_data.copy()
        if self._dev is None and self._spilled is not None:
            # spilled payload: serve the host copy directly instead of
            # re-uploading to device only to download again (that would
            # also churn the LRU in the exact memory-pressure paths)
            return np.asarray(self._spilled[0])[: self.nrow].copy()
        full = np.asarray(jax.device_get(self.data))
        # the transfer moves the PADDED device buffer — count what
        # actually crossed, not the sliced view (padding dominates on
        # small sharded frames)
        record_d2h(full.nbytes, fallback="frame")
        return full[: self.nrow]

    def to_strings(self) -> np.ndarray:
        """Decoded object array (enum codes → labels)."""
        if self.type == T_STR:
            return self.host_data.copy()
        raw = self.to_numpy()
        if self.type == T_ENUM:
            dom = np.array(list(self.domain) + [None], dtype=object)
            return dom[np.where(raw < 0, len(self.domain), raw)]
        return raw.astype(object)

    def with_data(self, new_data, vtype=None, domain=None) -> "Vec":
        v = Vec(new_data, self.nrow, vtype or self.type,
                domain if domain is not None else self.domain)
        return v


def _is_integral(f: np.ndarray) -> bool:
    finite = f[np.isfinite(f)]
    return bool(finite.size == 0 or np.all(finite == np.round(finite)))


def _numeric_host_copy(f64: np.ndarray, vtype: str):
    """float32 mantissa is 24 bits: large ints (IDs, counts, epoch
    millis that arrive as REAL) would be silently rounded on device, so
    keep an exact float64 host copy whenever the values are integral and
    exceed the mantissa (the reference keeps exact long chunks —
    water/fvec/C8Chunk). Order matters: the cheap max check gates the
    O(n) integrality scan."""
    if f64.size:
        import warnings
        with np.errstate(invalid="ignore"), warnings.catch_warnings():
            # all-NaN columns (fully-missing numerics) warn via the
            # warnings module, which errstate does not cover
            warnings.simplefilter("ignore", RuntimeWarning)
            m = np.nanmax(np.abs(f64))       # one scan, no mask-copy
        if np.isnan(m):
            return None                      # all-NA column
        if np.isfinite(m) and m > (1 << 24):
            if vtype == T_INT or _is_integral(f64):
                return f64
        elif np.isinf(m):
            # ±inf hid the finite max: fall back to the exact mask path
            finite = f64[np.isfinite(f64)]
            if finite.size and np.abs(finite).max() > (1 << 24):
                if vtype == T_INT or _is_integral(f64):
                    return f64
    return None


_SPLIT_COLS_JIT = None


def split_columns(mat, ncol: int):
    """Every column slice of a 2-D device matrix in ONE compiled
    dispatch. ``ncol`` separate ``mat[:, j]`` expressions each bake
    their index into a distinct XLA program — a cold parse paid one
    compile PER COLUMN (ISSUE 14 found ~70 ms of the 29-column bench
    frame's assembly was exactly that). jit's shape cache makes repeat
    shapes free, and outputs follow the input's (row) sharding."""
    assert mat.shape[1] == ncol, (mat.shape, ncol)
    global _SPLIT_COLS_JIT
    if _SPLIT_COLS_JIT is None:
        _SPLIT_COLS_JIT = jax.jit(
            lambda m: tuple(m[:, j] for j in range(m.shape[1])))
    return list(_SPLIT_COLS_JIT(mat))


def batch_device_put(columns, fill, dtype, nrow: int, mesh=None):
    """One host→device transfer for a whole dtype group of columns.

    Columns land in a single padded row-sharded [plen, ncol] matrix —
    one DMA instead of ncol — and come back as per-column device arrays
    (on-device slices along the unsharded axis, so no resharding). The
    ingest pipeline overlaps the (async) transfer with the host-side
    encode of the remaining groups."""
    mesh = mesh or current_mesh()
    plen = padded_len(nrow, mesh)
    mat = np.empty((plen, len(columns)), dtype=dtype)
    if plen > nrow:
        mat[nrow:] = fill              # only the pad tail needs filling

    def _pack(j):
        # assignment converts dtype in the same pass as the copy (a
        # separate astype would write every column twice)
        mat[:nrow, j] = columns[j]

    if nrow * len(columns) >= (1 << 22):
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(
                max_workers=min(len(columns), os.cpu_count() or 4, 8)) as ex:
            list(ex.map(_pack, range(len(columns))))  # GIL-free memcpy
    else:
        for j in range(len(columns)):
            _pack(j)
    record_h2d(mat.nbytes, fallback="frame")
    dev = _resilient_put(mat, mesh)
    return split_columns(dev, len(columns))


def batch_device_put_local(columns, fill, dtype, row_lo: int, row_hi: int,
                           nrow_global: int, mesh=None,
                           simulate: bool = False):
    """Multihost spelling of :func:`batch_device_put`: this process packs
    and transfers ONLY its own padded row block ``[row_lo, row_hi)`` of
    the global ``[plen, ncol]`` matrix — the shard-local H2D target of
    the multi-host parse (``columns`` hold just the local data rows).
    The recorded H2D bytes are the LOCAL block, which is what per-process
    ``h2o3_ingest_h2d_bytes`` attribution asserts. ``simulate`` is the
    parity-test shape (a forced multi-process plan on a single-process
    mesh, where ``make_array_from_process_local_data`` cannot apply):
    the local block scatters into a fill-padded global matrix and takes
    the ordinary single-process sharded put — rows outside the local
    span are fill, never data, so a simulated process still only ever
    touches its own bytes."""
    from h2o3_tpu.resilience import resilient_shard_rows
    mesh = mesh or current_mesh()
    plen = padded_len(nrow_global, mesh)
    nloc = row_hi - row_lo
    mat = np.empty((nloc, len(columns)), dtype=dtype)
    real = max(0, min(row_hi, nrow_global) - row_lo)
    if real < nloc:
        mat[real:] = fill              # pad tail inside the local span
    for j in range(len(columns)):
        mat[:real, j] = columns[j]
    record_h2d(mat.nbytes, pipeline="ingest")
    if simulate:
        full = np.full((plen, len(columns)), fill, dtype=dtype)
        full[row_lo:row_hi] = mat
        dev = resilient_shard_rows(full, mesh, pipeline="ingest")
    else:
        dev = resilient_shard_rows(mat, mesh, pipeline="ingest",
                                   global_rows=plen)
    return split_columns(dev, len(columns))


def _resilient_put(arr, mesh):
    """Row-sharded placement behind the fault seam + shared transient
    retry (resilience.resilient_shard_rows → mesh.DataParallelPartitioner):
    a transient H2D failure (injected or organic) re-issues the DMA with
    backoff instead of failing the whole parse/train, and a multi-process
    mesh assembles the global array from process-local rows."""
    from h2o3_tpu.resilience import resilient_shard_rows
    return resilient_shard_rows(arr, mesh)


def _pad_and_put(arr: np.ndarray, nrow: int, fill, mesh):
    plen = padded_len(nrow, mesh)
    if plen != nrow:
        arr = np.concatenate([arr, np.full(plen - nrow, fill, dtype=arr.dtype)])
    record_h2d(arr.nbytes, fallback="frame")
    return _resilient_put(arr, mesh)
