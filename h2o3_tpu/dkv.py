"""Keyed object store — the DKV's single-controller residue.

Reference: water/DKV.java:52 + water/Key.java — a cluster-coherent
distributed K/V store with home-node hashing, caching and invalidation.
Under single-controller JAX none of that machinery survives (SURVEY §5
"the DKV's locality/coherence role collapses"): device data already lives
in sharded jax.Arrays, so what remains is a thread-safe host-side map of
key → {frame, model, job} used by the REST layer and clients to address
objects by name (the /3/Frames/{key}, /3/Models/{key}, DELETE /3/DKV
surface)."""
from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

_LOCK = threading.RLock()
_STORE: Dict[str, Tuple[str, Any]] = {}
_COUNTER = itertools.count(1)


def put(key: str, kind: str, obj: Any) -> str:
    with _LOCK:
        _STORE[key] = (kind, obj)
    return key


def get(key: str, kind: Optional[str] = None) -> Any:
    with _LOCK:
        ent = _STORE.get(key)
    if ent is None:
        raise KeyError(f"key '{key}' not found in the store")
    if kind is not None and ent[0] != kind:
        raise KeyError(f"key '{key}' holds a {ent[0]}, not a {kind}")
    return ent[1]


def get_opt(key: str) -> Optional[Tuple[str, Any]]:
    with _LOCK:
        return _STORE.get(key)


def remove(key: str) -> bool:
    with _LOCK:
        return _STORE.pop(key, None) is not None


def keys(kind: Optional[str] = None) -> Iterable[str]:
    with _LOCK:
        return [k for k, (t, _) in _STORE.items()
                if kind is None or t == kind]


def clear() -> None:
    with _LOCK:
        _STORE.clear()
        _LOCKERS.clear()


def unique_key(prefix: str) -> str:
    return f"{prefix}_{next(_COUNTER)}"


# ---------------- cooperative key locking (water/Lockable.java:25) -----
#
# The reference write-locks a job's outputs and read-locks its inputs so
# concurrent jobs cannot overwrite in-use keys (parser write-locks its
# destination against double-parses; model builders read-lock their
# training frames). Same cooperative contract here, minus the
# distributed CAS: one lock table under the store mutex.
# _LOCKERS[key] = (write_locker_job_key or None, {read_locker_job_keys}).

_LOCKERS: Dict[str, Tuple[Optional[str], set]] = {}


class KeyLockedError(RuntimeError):
    pass


def write_lock(key: str, job_key: Optional[str]) -> None:
    """Exclusive lock (Lockable.write_lock): fails if ANY other job holds
    the key (IAE in the reference)."""
    with _LOCK:
        w, readers = _LOCKERS.get(key, (None, set()))
        others = (readers - {job_key}) if job_key else readers
        if (w is not None and w != job_key) or others:
            raise KeyLockedError(
                f"key '{key}' is locked by {w or sorted(others)} — "
                f"cannot write-lock for {job_key}")
        _LOCKERS[key] = (job_key or "<nojob>", readers)


def read_lock(key: str, job_key: Optional[str]) -> None:
    """Shared lock (Lockable.read_lock): fails only against a WRITE
    locker held by another job."""
    with _LOCK:
        w, readers = _LOCKERS.get(key, (None, set()))
        if w is not None and w != job_key:
            raise KeyLockedError(
                f"key '{key}' is write-locked by {w} — cannot read-lock "
                f"for {job_key}")
        readers = set(readers)
        readers.add(job_key or "<nojob>")
        _LOCKERS[key] = (w, readers)


def get_and_read_lock(key: str, kind: str, job_key: str) -> Any:
    """Atomic fetch + shared-lock under the store mutex (the serve
    registry's deploy path): between a plain get() and a later
    read_lock() a concurrent DELETE /3/Models could remove the key —
    the deployment would then serve a model the store no longer owns.
    One critical section closes the window."""
    with _LOCK:
        ent = _STORE.get(key)
        if ent is None:
            raise KeyError(f"key '{key}' not found in the store")
        if ent[0] != kind:
            raise KeyError(f"key '{key}' holds a {ent[0]}, not a {kind}")
        read_lock(key, job_key)
        return ent[1]


def unlock(key: str, job_key: Optional[str]) -> None:
    with _LOCK:
        w, readers = _LOCKERS.get(key, (None, set()))
        jk = job_key or "<nojob>"
        readers = set(readers) - {jk}
        if w == jk:
            w = None
        if w is None and not readers:
            _LOCKERS.pop(key, None)
        else:
            _LOCKERS[key] = (w, readers)


def unlock_all(job_key: Optional[str]) -> None:
    """Job teardown: release every lock the job holds (Scope.exit /
    Lockable unlock-on-completion)."""
    with _LOCK:
        for key in list(_LOCKERS):
            unlock(key, job_key)


def lockers_of(key: str) -> Tuple[Optional[str], set]:
    with _LOCK:
        w, readers = _LOCKERS.get(key, (None, set()))
        return w, set(readers)


def check_unlocked(key: str) -> None:
    """Deletion guard: DELETE /3/Frames|Models|DKV refuses keys a live
    job still holds (the reference blocks in write_lock-then-remove)."""
    with _LOCK:
        w, readers = _LOCKERS.get(key, (None, set()))
        if w is not None or readers:
            raise KeyLockedError(
                f"key '{key}' is in use (write={w}, readers="
                f"{sorted(readers)}) — cancel the owning job first")


class Scope:
    """water/Scope.java analog: track keys created inside a with-block
    and remove the untracked ones on exit (leak policing)."""

    def __init__(self):
        self._before: set = set()
        self._keep: set = set()

    def __enter__(self):
        with _LOCK:
            self._before = set(_STORE)
        return self

    def track_generic(self, key: str) -> str:
        return key        # tracked by snapshot; kept for API parity

    def untrack(self, key: str) -> str:
        self._keep.add(key)
        return key

    def __exit__(self, *exc):
        with _LOCK:
            created = set(_STORE) - self._before - self._keep
        for k in created:
            remove(k)
        return False


def unlock_everything() -> None:
    """Admin escape hatch (water/api/UnlockKeysHandler → Lockable
    unlock-all): drop every read and write lock regardless of holder."""
    with _LOCK:
        _LOCKERS.clear()
