"""Keyed object store — the DKV's single-controller residue.

Reference: water/DKV.java:52 + water/Key.java — a cluster-coherent
distributed K/V store with home-node hashing, caching and invalidation.
Under single-controller JAX none of that machinery survives (SURVEY §5
"the DKV's locality/coherence role collapses"): device data already lives
in sharded jax.Arrays, so what remains is a thread-safe host-side map of
key → {frame, model, job} used by the REST layer and clients to address
objects by name (the /3/Frames/{key}, /3/Models/{key}, DELETE /3/DKV
surface)."""
from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

_LOCK = threading.RLock()
_STORE: Dict[str, Tuple[str, Any]] = {}
_COUNTER = itertools.count(1)


def put(key: str, kind: str, obj: Any) -> str:
    with _LOCK:
        _STORE[key] = (kind, obj)
    return key


def get(key: str, kind: Optional[str] = None) -> Any:
    with _LOCK:
        ent = _STORE.get(key)
    if ent is None:
        raise KeyError(f"key '{key}' not found in the store")
    if kind is not None and ent[0] != kind:
        raise KeyError(f"key '{key}' holds a {ent[0]}, not a {kind}")
    return ent[1]


def get_opt(key: str) -> Optional[Tuple[str, Any]]:
    with _LOCK:
        return _STORE.get(key)


def remove(key: str) -> bool:
    with _LOCK:
        return _STORE.pop(key, None) is not None


def keys(kind: Optional[str] = None) -> Iterable[str]:
    with _LOCK:
        return [k for k, (t, _) in _STORE.items()
                if kind is None or t == kind]


def clear() -> None:
    with _LOCK:
        _STORE.clear()


def unique_key(prefix: str) -> str:
    return f"{prefix}_{next(_COUNTER)}"
