"""Model persistence — h2o.save_model / h2o.load_model + frame export.

Reference: water/persist/PersistManager.java (URI-scheme-dispatched
backends: file/NFS/S3/GCS/HDFS/HTTP), binary model save/load wired to
h2o.save_model/load_model (h2o-py/h2o/h2o.py), and Model.Parameters
_checkpoint continue-training (hex/Model.java:487).

TPU re-design: a model artifact is a single pickle-free zip —
``meta.json`` (params, feature/domain metadata, metrics) +
``arrays.npz`` (numpy tensors) — written by per-algo hooks
(Model._save_arrays/_save_extra_meta/_restore). The reference's woven
Icer serializers (water/Weaver.java) collapse into this explicit
JSON+npz contract; only ``file://`` paths are implemented (S3/GCS would
dispatch here the same way PersistManager does).
"""
from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Any, Dict, Optional

import numpy as np

FORMAT_VERSION = 1

# algo → model class, filled lazily to avoid import cycles
_MODEL_CLASSES: Dict[str, Any] = {}


def register_model_class(algo: str, cls) -> None:
    _MODEL_CLASSES[algo] = cls


def _model_class(algo: str):
    if not _MODEL_CLASSES:
        # import the algo modules once; each registers its model class
        from h2o3_tpu.models import (aggregator, anovaglm,  # noqa: F401
                                     coxph, deeplearning, drf, ensemble,
                                     gam, gbm, glm, glrm, isoforest,
                                     isoforextended, isotonic, kmeans,
                                     infogram, misc_models,
                                     modelselection, naivebayes, pca, psvm,
                                     rulefit, svd, targetencoder, uplift,
                                     word2vec)
    if algo not in _MODEL_CLASSES:
        raise ValueError(f"no registered model class for algo '{algo}'")
    return _MODEL_CLASSES[algo]


def _json_safe(obj):
    """Recursively convert to JSON-serializable python (numpy → lists,
    unknown objects dropped)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()
                if _is_safe(v)}
    return None


def _is_safe(v) -> bool:
    return isinstance(v, (type(None), bool, int, float, str, list, tuple,
                          dict, np.ndarray, np.integer, np.floating))


def _metrics_to_meta(m) -> Optional[Dict]:
    if m is None:
        return None
    from h2o3_tpu.models import metrics as mm
    kind = {mm.ModelMetricsRegression: "regression",
            mm.ModelMetricsBinomial: "binomial",
            mm.ModelMetricsMultinomial: "multinomial",
            mm.ModelMetricsAnomaly: "anomaly"}.get(type(m))
    if kind is None:
        return None
    import dataclasses
    return {"kind": kind,
            "fields": _json_safe(dataclasses.asdict(m))}


def _metrics_from_meta(meta: Optional[Dict]):
    if meta is None:
        return None
    from h2o3_tpu.models import metrics as mm
    cls = {"regression": mm.ModelMetricsRegression,
           "binomial": mm.ModelMetricsBinomial,
           "multinomial": mm.ModelMetricsMultinomial,
           "anomaly": mm.ModelMetricsAnomaly}[meta["kind"]]
    f = dict(meta["fields"])
    for k in ("confusion_matrix", "hit_ratios"):
        if k in f and f[k] is not None:
            f[k] = np.asarray(f[k])
    import dataclasses
    names = {fl.name for fl in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in f.items() if k in names})


def model_to_meta(model) -> Dict:
    """Model → JSON-safe metadata dict (shared by save_model and nested
    wrapper models like StackedEnsemble/GAM/RuleFit)."""
    return {
        "format_version": FORMAT_VERSION,
        "algo": model.algo,
        "key": model.key,
        "params": _json_safe(model.params),
        "feature_names": model.feature_names,
        "feature_is_cat": model.feature_is_cat,
        "cat_domains": {k: list(v) for k, v in model.cat_domains.items()},
        "response": model.response,
        "response_domain": (list(model.response_domain)
                            if model.response_domain else None),
        "nclasses": model.nclasses,
        "output": _json_safe(model.output),
        "training_frame_key": getattr(model, "training_frame_key", None),
        "scoring_history": _json_safe(model.scoring_history),
        "training_metrics": _metrics_to_meta(model.training_metrics),
        "validation_metrics": _metrics_to_meta(model.validation_metrics),
        "cross_validation_metrics": _metrics_to_meta(
            model.cross_validation_metrics),
        "extra": _json_safe(model._save_extra_meta()),
    }


def model_from_meta(meta: Dict, arrays: Dict):
    """Inverse of model_to_meta + _save_arrays: rebuild a live Model."""
    cls = _model_class(meta["algo"])
    model = cls._restore(meta, arrays)
    model.training_metrics = _metrics_from_meta(meta.get("training_metrics"))
    model.validation_metrics = _metrics_from_meta(
        meta.get("validation_metrics"))
    model.cross_validation_metrics = _metrics_from_meta(
        meta.get("cross_validation_metrics"))
    model.scoring_history = meta.get("scoring_history") or []
    model.training_frame_key = meta.get("training_frame_key")
    return model


def save_model(model, path: str = ".", force: bool = False,
               filename: Optional[str] = None) -> str:
    """Write a model artifact; returns the artifact path (h2o.save_model
    signature)."""
    if os.path.isdir(path) or not os.path.splitext(path)[1]:
        os.makedirs(path, exist_ok=True)
        out = os.path.join(path, filename or model.key)
    else:
        out = path
    if os.path.exists(out) and not force:
        raise FileExistsError(f"{out} exists (pass force=True to overwrite)")
    meta = model_to_meta(model)
    arrays = {k: np.asarray(v) for k, v in model._save_arrays().items()}
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    # tmp + rename (the save_frame contract): a kill -9 mid-write must
    # never leave a truncated artifact under the final name — the
    # restart-recovery scan picks the NEWEST <key>_t<n>.zip, so a
    # half-written newest would permanently shadow the intact one below
    with zipfile.ZipFile(out + ".tmp", "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("meta.json", json.dumps(meta))
        zf.writestr("arrays.npz", buf.getvalue())
    os.replace(out + ".tmp", out)
    return out


def load_model(path: str):
    """Read a model artifact back into a live Model (h2o.load_model).

    Reads route through the shared retry/backoff helper (jitter, bounded
    attempts) so flaky storage — NFS hiccups, the remote-URI cache mid-
    refresh — retries instead of failing the caller (PersistManager's
    reads are similarly retried by the HDFS/S3 client stacks)."""
    from h2o3_tpu import faults
    from h2o3_tpu.resilience import is_transient_io, retry_transient

    def _read():
        if faults.ACTIVE:
            faults.check("persist", key=path)
        with zipfile.ZipFile(path, "r") as zf:
            return (json.loads(zf.read("meta.json")),
                    dict(np.load(io.BytesIO(zf.read("arrays.npz")))))

    meta, arrays = retry_transient(_read, site="persist.load_model",
                                   classify=is_transient_io)
    if meta.get("format_version", 0) > FORMAT_VERSION:
        raise ValueError(f"artifact format {meta['format_version']} is newer "
                         f"than this build ({FORMAT_VERSION})")
    return model_from_meta(meta, arrays)


def export_file(frame, path: str, force: bool = False, sep: str = ",") -> str:
    """Frame → CSV on disk (h2o.export_file; reference
    water/api/FramesHandler export + persist layer)."""
    if os.path.exists(path) and not force:
        raise FileExistsError(f"{path} exists (pass force=True to overwrite)")
    cols = [v.to_strings() if v.type == "enum" or v.type == "string"
            else v.to_numpy() for v in frame.vecs]
    def q(s: str) -> str:
        # RFC 4180: embedded quotes double up inside a quoted cell
        return '"' + s.replace('"', '""') + '"'

    with open(path, "w") as f:
        f.write(sep.join(q(n) for n in frame.names) + "\n")
        for i in range(frame.nrow):
            cells = []
            for c in cols:
                x = c[i]
                if x is None or (isinstance(x, (float, np.floating))
                                 and np.isnan(x)):
                    cells.append("")
                elif isinstance(x, str):
                    cells.append(q(x))
                elif isinstance(x, (float, np.floating)):
                    cells.append(repr(float(x)))
                else:
                    cells.append(str(x))
            f.write(sep.join(cells) + "\n")
    return path


# ---------------- frame binary persistence ---------------------------
#
# Reference: water/fvec/Frame + persist binary .hex export consumed by
# POST /3/Frames/{id}/save and /3/Frames/load (FramesHandler.saveFrame/
# loadFrame → water/persist/PersistManager). The TPU artifact is the
# same JSON+npz zip contract as models: meta.json records column names/
# types/domains, frame.npz the column data (float64 for numeric/time
# codes, int32 enum codes, object->utf8 for strings).

def save_frame(frame, directory: str, force: bool = True,
               key: Optional[str] = None) -> str:
    """Binary frame artifact ``<dir>/<key>.zip``; returns the path.
    ``key`` overrides the artifact/frame key (the REST route passes the
    DKV id the client will load back by)."""
    from h2o3_tpu.frame.vec import T_ENUM, T_STR, T_TIME
    key = key or frame.key
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{key}.zip")
    if os.path.exists(path) and not force:
        raise FileExistsError(path)
    meta = {"format_version": FORMAT_VERSION, "key": key,
            "nrow": frame.nrow,
            "names": list(frame.names),
            "types": [v.type for v in frame.vecs],
            "domains": [list(v.domain) if v.domain else None
                        for v in frame.vecs]}
    arrays = {}
    for i, v in enumerate(frame.vecs):
        a = v.to_numpy()
        if v.type == T_STR:
            # numpy 'U' arrays strip NUL chars, so the NA sentinel rides
            # in a separate boolean mask instead of an in-band value
            arrays[f"c{i}"] = np.array(
                ["" if x is None else str(x) for x in a], dtype="U")
            arrays[f"na{i}"] = np.array([x is None for x in a], bool)
        else:
            arrays[f"c{i}"] = np.asarray(a)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    with zipfile.ZipFile(path + ".tmp", "w") as z:
        z.writestr("meta.json", json.dumps(meta))
        z.writestr("frame.npz", buf.getvalue())
    os.replace(path + ".tmp", path)
    return path


def load_frame(path: str, key: Optional[str] = None):
    """Load a binary frame artifact; ``path`` may be the zip file or the
    directory + key via ``<dir>/<key>.zip`` convention."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.vec import T_ENUM, T_STR, Vec
    if key is not None and os.path.isdir(path):
        path = os.path.join(path, f"{key}.zip")
    with zipfile.ZipFile(path) as z:
        meta = json.loads(z.read("meta.json"))
        npz = np.load(io.BytesIO(z.read("frame.npz")), allow_pickle=False)
        vecs = []
        for i, (t, dom) in enumerate(zip(meta["types"], meta["domains"])):
            a = npz[f"c{i}"]
            if t == T_STR:
                nam = (npz[f"na{i}"] if f"na{i}" in npz.files
                       else np.zeros(len(a), bool))
                a = np.array([None if na else x
                              for x, na in zip(a, nam)], dtype=object)
            if t == T_ENUM:
                vecs.append(Vec.from_numpy(a.astype(np.int32), vtype=t,
                                           domain=tuple(dom or ())))
            else:
                vecs.append(Vec.from_numpy(a, vtype=t))
    return Frame(meta["names"], vecs, key=meta["key"])
