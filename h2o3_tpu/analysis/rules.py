"""The h2o3-lint rules: this repo's invariants, machine-checked.

Each rule's docstring is its catalog entry (``tools/h2o3_lint.py
--rules`` prints them) and records the tightening decisions made when a
finding turned out to be a false positive — per the repo policy, FPs
tighten the rule instead of growing the baseline.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from h2o3_tpu.analysis.core import (Finding, ModuleInfo, Rule, SEV_ERROR,
                                    SEV_WARNING, ancestors, attach_parents,
                                    dotted_name)

# ======================================================================
# transfer-seam
# ======================================================================

# Modules allowed to touch the raw JAX transfer API: they ARE the seam.
_BLESSED_TRANSFER_MODULES = (
    # the one policy point for H2D: fault seam + retry + sharding
    "h2o3_tpu/resilience.py",
    # the counted D2H choke point (telemetry.device_get) + byte counters
    "h2o3_tpu/telemetry/collectors.py",
    # partitioner internals — called FROM resilience.resilient_shard_rows,
    # it owns device placement for sharded arrays
    "h2o3_tpu/parallel/mesh.py",
    # the frame-layer choke point: spill/unspill/to_numpy count their
    # own bytes inline (record_h2d/record_d2h with fallback="frame")
    # and the unspill must run under the memman lock — it IS a seam
    "h2o3_tpu/frame/vec.py",
)


class TransferSeamRule(Rule):
    """Raw ``jax.device_put`` / ``jax.device_get`` /
    ``(jax|x).block_until_ready`` outside the blessed seam modules.

    Every H2D must flow through ``resilience.resilient_device_put`` /
    ``resilient_shard_rows`` (fault-injectable, retried, counted) and
    every ad-hoc D2H through ``telemetry.device_get`` (byte-counted), or
    the transfer-budget guards (``train.streamed_h2d_guard``,
    ``h2o3_{h2d,d2h}_pipeline_bytes_total``) silently under-report.
    Deliberate pipeline barriers (the ingest double-buffer bound, the
    train-loop timing fences) carry inline allows with a reason.

    Scope decision: "np.asarray on a device value" is also a raw D2H,
    but whether an ``np.asarray`` argument is device-resident is not
    decidable syntactically — that spelling is only covered inside hot
    zones (host-sync-hot-loop), where data is device-resident by
    construction.
    """

    name = "transfer-seam"
    severity = SEV_ERROR

    _RAW = {"jax.device_put", "jax.device_get"}

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        if mod.relpath.endswith(_BLESSED_TRANSFER_MODULES):
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in self._RAW:
                seam = ("resilience.resilient_device_put"
                        if name.endswith("device_put")
                        else "telemetry.device_get")
                out.append(self.finding(
                    mod, node,
                    f"raw {name} outside the blessed seam modules — "
                    f"route through {seam} so the transfer is counted "
                    f"and fault-injectable"))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "block_until_ready"):
                out.append(self.finding(
                    mod, node,
                    "block_until_ready outside the blessed seam modules "
                    "— a hidden host sync; if it is a deliberate "
                    "pipeline barrier, add an inline allow with the "
                    "reason"))
        return out


# ======================================================================
# recompile-hazard
# ======================================================================

def _jit_static_names(deco: ast.AST, args: ast.arguments) -> Optional[Set[str]]:
    """If ``deco`` spells jax.jit (bare, or partial(jax.jit, ...) /
    jax.jit(...) with static_argnums/static_argnames), return the set of
    STATIC parameter names; None when deco is not a jit spelling."""
    posnames = [a.arg for a in args.posonlyargs + args.args]

    def _resolve(call: ast.Call) -> Set[str]:
        static: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        static.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, int):
                        if 0 <= n.value < len(posnames):
                            static.add(posnames[n.value])
        return static

    d = dotted_name(deco)
    if d in ("jax.jit", "jit"):
        return set()
    if isinstance(deco, ast.Call):
        head = dotted_name(deco.func)
        if head in ("jax.jit", "jit"):
            return _resolve(deco)
        if head in ("partial", "functools.partial") and deco.args:
            if dotted_name(deco.args[0]) in ("jax.jit", "jit"):
                return _resolve(deco)
    return None


def _is_static_test_ref(name_node: ast.Name) -> bool:
    """A traced-param reference that is actually trace-time static:
    ``x is None`` / ``x is not None``, ``isinstance(x, ...)``,
    ``x.shape/...``, ``len(x)`` — these resolve during tracing and
    neither fail nor force a recompile per value."""
    parent = getattr(name_node, "_h2o3_parent", None)
    if isinstance(parent, ast.Attribute) and parent.attr in (
            "shape", "ndim", "dtype", "size", "sharding", "weak_type"):
        return True
    if isinstance(parent, ast.Call):
        head = dotted_name(parent.func)
        if head in ("isinstance", "len", "callable", "type"):
            return True
    if isinstance(parent, ast.Compare):
        ops = parent.ops
        comps = [parent.left] + list(parent.comparators)
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in ops) and any(
                isinstance(c, ast.Constant) and c.value is None
                for c in comps):
            return True
    return False


class RecompileHazardRule(Rule):
    """``@jax.jit``-reachable code that hides a recompile hazard or a
    trace-time failure (the zero-recompile contract from PRs 2/3/7).

    Sub-checks:

    - **param-branch**: ``if``/``while``/ternary tests referencing a
      non-static parameter of a jitted function. On a traced value this
      raises at trace time; on a Python scalar it silently specializes
      the executable per VALUE — the exact warm-retrain recompile class
      PR 2's traced-rates work eliminated. Tests on ``x is None``,
      ``isinstance``, ``len(x)`` and ``.shape/.ndim/.dtype`` are exempt
      (static under tracing).
    - **loop-var-closure**: a jitted function DEFINED inside a loop that
      closes over the loop variable — a fresh closure constant (and a
      fresh compile) every iteration.
    - **np-on-param**: ``np.*`` called on a non-static parameter inside
      a jitted function — a host op on a tracer fails at trace time (or
      constant-folds the argument, hiding a per-call recompile).

    Tightening decisions: bucketed static specialization (the
    chunk-length-bucket pattern) passes params via static_argnums/names,
    which this rule honors; branches on them are exempt.
    """

    name = "recompile-hazard"
    severity = SEV_WARNING

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        attach_parents(mod.tree)
        out: List[Finding] = []
        # fn name -> static names, for `f = jax.jit(f, static_...)` rebinds
        rebound: Dict[str, Set[str]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                head = dotted_name(node.func)
                if head in ("jax.jit", "jit") and node.args and \
                        isinstance(node.args[0], ast.Name):
                    static: Set[str] = set()
                    for kw in node.keywords:
                        if kw.arg == "static_argnames":
                            for n in ast.walk(kw.value):
                                if isinstance(n, ast.Constant) and \
                                        isinstance(n.value, str):
                                    static.add(n.value)
                    rebound[node.args[0].id] = static
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            static: Optional[Set[str]] = None
            for deco in node.decorator_list:
                s = _jit_static_names(deco, node.args)
                if s is not None:
                    static = s
                    break
            if static is None and node.name in rebound:
                static = rebound[node.name]
            if static is None:
                continue
            out.extend(self._check_jitted(mod, node, static))
        return out

    def _check_jitted(self, mod: ModuleInfo, fn: ast.FunctionDef,
                      static: Set[str]) -> Iterable[Finding]:
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)} - static - {"self"}
        out: List[Finding] = []
        flagged_tests: Set[int] = set()
        for node in ast.walk(fn):
            tests: List[ast.AST] = []
            if isinstance(node, (ast.If, ast.While)):
                tests = [node.test]
            elif isinstance(node, ast.IfExp):
                tests = [node.test]
            for test in tests:
                if id(test) in flagged_tests:
                    continue
                for ref in ast.walk(test):
                    if isinstance(ref, ast.Name) and ref.id in params \
                            and not _is_static_test_ref(ref):
                        out.append(self.finding(
                            mod, node,
                            f"branch on non-static parameter '{ref.id}' "
                            f"inside jitted '{fn.name}' — a tracer here "
                            f"fails at trace time, a Python scalar "
                            f"recompiles per value; use jnp.where/"
                            f"lax.cond or declare it static"))
                        flagged_tests.add(id(test))
                        break
            if isinstance(node, ast.Call):
                head = dotted_name(node.func) or ""
                if head.startswith("np.") or head.startswith("numpy."):
                    for arg in node.args:
                        if isinstance(arg, ast.Name) and arg.id in params:
                            out.append(self.finding(
                                mod, node,
                                f"{head} on parameter '{arg.id}' inside "
                                f"jitted '{fn.name}' — host op on a "
                                f"tracer (use jnp, or hoist to the "
                                f"caller)"))
                            break
        # loop-var closure: this fn nested under a For whose target it reads
        loop_vars: Set[str] = set()
        for anc in ancestors(fn):
            if isinstance(anc, ast.For) and isinstance(anc.target, ast.Name):
                loop_vars.add(anc.target.id)
        if loop_vars:
            bound = params | static | {"self"}
            defaults = {a.arg for a in fn.args.args}  # params already in
            for ref in ast.walk(fn):
                if isinstance(ref, ast.Name) and isinstance(
                        ref.ctx, ast.Load) and ref.id in loop_vars \
                        and ref.id not in bound and ref.id not in defaults:
                    out.append(self.finding(
                        mod, fn,
                        f"jitted '{fn.name}' closes over loop variable "
                        f"'{ref.id}' — a fresh compile every iteration; "
                        f"pass it as a (traced or static) argument"))
                    break
        return out


# ======================================================================
# host-sync-hot-loop
# ======================================================================

# (module-relpath suffix) -> function names whose LOOP BODIES must not
# host-sync. These are the three hot loops the bench trajectory rests
# on: the GBM/DRF tree loop, the serve batcher's encode/dispatch stage
# (the COLLECTOR thread is the designated sync point and is not listed),
# and the streamed-chunk pipelines (their double-buffer bounds carry
# inline allows).
DEFAULT_HOT_ZONES: Dict[str, Tuple[str, ...]] = {
    "h2o3_tpu/models/gbm.py": ("_train_dense", "_train_streaming"),
    "h2o3_tpu/models/drf.py": ("_train_impl",),
    "h2o3_tpu/models/streaming.py": ("level_pass", "begin_tree"),
    "h2o3_tpu/serve/batcher.py": ("_batch_loop", "_take_batch", "submit"),
    "h2o3_tpu/ingest/stream.py": ("add",),
}


class HostSyncHotLoopRule(Rule):
    """Host synchronization inside a hot loop: ``.item()``, any
    ``device_get`` spelling (the counted seam is still a sync) and
    ``block_until_ready`` inside ``for``/``while`` bodies of the
    designated hot functions (tree loop, serve batcher dispatch stage,
    streamed-chunk pipeline).

    One sync per iteration serializes the pipelined dispatch the PR-2/3
    speculative-chunk work bought. Deliberate per-iteration barriers
    (the double-buffer depth bound in ingest/stream.add) carry inline
    allows naming the reason.

    Tightening decisions: ``float(x)``/``int(x)`` on arbitrary locals
    are NOT flagged (too many trace-time Python scalars). Bare
    ``np.asarray``/``np.array`` are NOT flagged either — the canonical
    FP was ingest/stream.add converting freshly TOKENIZED host columns
    (``np.asarray(c.data)``), which never touches the device; an
    np.asarray that wraps a device value always wraps a flagged
    ``device_get`` (or is itself the sync, which block_until_ready/
    device_get spellings catch at the call that produced the value).
    The serve collector thread is the designated sync point, so
    ``_collect_loop`` is not a hot zone.
    """

    name = "host-sync-hot-loop"
    severity = SEV_ERROR

    def __init__(self, zones: Optional[Dict[str, Tuple[str, ...]]] = None):
        self.zones = DEFAULT_HOT_ZONES if zones is None else zones

    _SYNC_DOTTED = {"jax.device_get", "telemetry.device_get"}

    def _zone_functions(self, mod: ModuleInfo) -> Tuple[str, ...]:
        for suffix, fns in self.zones.items():
            if mod.relpath.endswith(suffix):
                return fns
        return ()

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        fns = self._zone_functions(mod)
        if not fns:
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef) and (
                    node.name in fns or "*" in fns):
                for loop in ast.walk(node):
                    if isinstance(loop, (ast.For, ast.While)):
                        out.extend(self._check_loop_body(mod, node, loop))
        # dedupe (nested loops walk the same calls twice)
        seen: Set[Tuple[int, int, str]] = set()
        uniq = []
        for f in out:
            k = (f.line, f.col, f.message)
            if k not in seen:
                seen.add(k)
                uniq.append(f)
        return uniq

    def _check_loop_body(self, mod: ModuleInfo, fn: ast.FunctionDef,
                         loop: ast.AST) -> Iterable[Finding]:
        body = getattr(loop, "body", []) + getattr(loop, "orelse", [])
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                attr = node.func.attr if isinstance(
                    node.func, ast.Attribute) else ""
                if name in self._SYNC_DOTTED or attr == "device_get":
                    yield self.finding(
                        mod, node,
                        f"host sync '{name or attr}' inside the "
                        f"'{fn.name}' hot loop — one D2H per iteration "
                        f"serializes the pipelined dispatch; batch the "
                        f"fetch outside the loop or pipeline it")
                elif attr == "block_until_ready":
                    yield self.finding(
                        mod, node,
                        f"block_until_ready inside the '{fn.name}' hot "
                        f"loop — per-iteration barrier; if this is a "
                        f"deliberate depth bound, add an inline allow")
                elif attr == "item" and not node.args:
                    yield self.finding(
                        mod, node,
                        f".item() inside the '{fn.name}' hot loop — "
                        f"scalar D2H per iteration; keep it a device "
                        f"scalar or fetch once after the loop")


# ======================================================================
# lock-discipline
# ======================================================================

_LOCK_NAME_HINTS = ("lock", "mutex")
_LOCK_EXACT = {"_mu", "_cv", "_mutex", "_lock", "_LOCK", "_STATE_LOCK"}


def _is_lock_expr(expr: ast.AST) -> bool:
    """with-item expressions that acquire a lock: the terminal name
    contains lock/mutex or is one of the repo's conventional spellings
    (_mu, _cv). ``lock.acquire()``-style calls are not with-items."""
    name = dotted_name(expr)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1]
    low = terminal.lower()
    return terminal in _LOCK_EXACT or any(h in low for h in _LOCK_NAME_HINTS)


# Calls that must never run while a registry/jobs/batcher lock is held:
# device work and sleeps serialize every other thread on the lock for
# device-latency timescales; network I/O for unbounded ones.
_BLOCKING_UNDER_LOCK = {
    "time.sleep": "sleeps while holding it",
    "jax.device_put": "does device transfer while holding it",
    "jax.device_get": "does device transfer while holding it",
    "jax.block_until_ready": "blocks on device work while holding it",
    "telemetry.device_get": "does device transfer while holding it",
    "resilient_device_put": "does device transfer while holding it",
    "resilience.resilient_device_put": "does device transfer while "
                                       "holding it",
    "resilient_shard_rows": "does device transfer while holding it",
    "urllib.request.urlopen": "does network I/O while holding it",
    "urlopen": "does network I/O while holding it",
    "socket.create_connection": "does network I/O while holding it",
    "subprocess.run": "spawns a process while holding it",
    "subprocess.check_output": "spawns a process while holding it",
}


class LockDisciplineRule(Rule):
    """Threading hygiene for the registry/jobs/batcher planes.

    Sub-checks:

    - **blocking-under-lock**: ``time.sleep``, device dispatch/transfer
      or network I/O inside a ``with <lock>:`` block. A device fetch
      under the jobs or batcher lock serializes every REST poller on
      device latency — the class of bug fixed by hand in PRs 3/8.
    - **unlocked-guarded-write**: an attribute written both under a
      lock somewhere and with no lock elsewhere in the same module
      (``__init__``/module scope exempt — construction happens-before
      publication). Mixed discipline means one of the sites is wrong:
      either the lock is unnecessary or the bare write races.

    Tightening decisions: ``Condition.wait`` RELEASES the lock and is
    not a blocking call here. ``.join``/``queue.get`` are excluded
    (str.join/dict.get false positives). jax.jit/jnp.* CONSTRUCTION
    under a lock is allowed — only transfers/syncs are flagged.
    Event.set() after a bare write is a legitimate happens-before for
    the waiter, but not for concurrent third threads — writes claimed
    by a lock elsewhere must take it everywhere.
    """

    name = "lock-discipline"
    severity = SEV_ERROR

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        attach_parents(mod.tree)
        out: List[Finding] = []
        out.extend(self._blocking_under_lock(mod))
        out.extend(self._unlocked_guarded_writes(mod))
        return out

    # -- sub-check (a) --------------------------------------------------

    def _under_lock(self, node: ast.AST) -> bool:
        for anc in ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False      # a nested def runs later, not under it
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if _is_lock_expr(item.context_expr):
                        return True
        return False

    def _blocking_under_lock(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            why = _BLOCKING_UNDER_LOCK.get(name)
            if why is None and isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                why = "blocks on device work while holding it"
            if why is None:
                continue
            if self._under_lock(node):
                yield self.finding(
                    mod, node,
                    f"'{name or node.func.attr}' under a held lock — "
                    f"{why}; move the call outside the critical "
                    f"section")

    # -- sub-check (b) --------------------------------------------------

    def _unlocked_guarded_writes(self, mod: ModuleInfo) -> Iterable[Finding]:
        # attr name -> [(node, under_lock, in_init)]
        writes: Dict[str, List[Tuple[ast.AST, bool, bool]]] = {}
        for node in ast.walk(mod.tree):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            else:
                continue
            for t in targets:
                if not isinstance(t, ast.Attribute):
                    continue
                in_init = False
                in_func = False
                for anc in ancestors(node):
                    if isinstance(anc, ast.FunctionDef):
                        in_func = True
                        if anc.name == "__init__":
                            in_init = True
                        break
                if not in_func:
                    continue            # module-level constant setup
                writes.setdefault(t.attr, []).append(
                    (node, self._under_lock(node), in_init))
        for attr, sites in writes.items():
            locked = [s for s in sites if s[1]]
            bare = [s for s in sites if not s[1] and not s[2]]
            if not locked or not bare:
                continue
            for node, _, _ in bare:
                yield self.finding(
                    mod, node,
                    f"attribute '{attr}' is written under a lock "
                    f"elsewhere in this module but bare here — a "
                    f"concurrent reader under the lock can see a torn "
                    f"protocol; take the owning lock (or drop it "
                    f"everywhere and document why)")


# ======================================================================
# fault-seam
# ======================================================================

class FaultSeamRule(Rule):
    """Package-scope consistency of the fault-injection seams.

    Sub-checks:

    - **site-registry**: every literal site passed to ``faults.check``
      must be in ``faults.KNOWN_SITES``, and every registered site must
      be checked somewhere — a typo'd site silently never fires (chaos
      coverage holes), an unreferenced registered site is a dead seam
      that chaos specs target for nothing.
    - **ungated-check**: ``faults.check(...)`` not enclosed in an
      ``if faults.ACTIVE:`` branch — the checked-no-op contract (one
      attribute load + branch when unset, asserted by
      tests/test_resilience.py's ns-budget guard) only holds when call
      sites pre-gate.

    faults.py itself and test files are exempt from the gating check
    (tests drive check() directly on purpose).
    """

    name = "fault-seam"
    severity = SEV_ERROR
    scope = "package"

    def check_package(self, mods: Sequence[ModuleInfo]) -> List[Finding]:
        out: List[Finding] = []
        faults_mod = None
        for m in mods:
            if m.relpath.endswith("h2o3_tpu/faults.py"):
                faults_mod = m
                break
        registered: Set[str] = set()
        if faults_mod is not None:
            for node in ast.walk(faults_mod.tree):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                        for t in node.targets):
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Constant) and isinstance(
                                c.value, str):
                            registered.add(c.value)
        used: Dict[str, List[Tuple[ModuleInfo, ast.Call]]] = {}
        for m in mods:
            if m is faults_mod:
                continue
            attach_parents(m.tree)
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                if not (name == "faults.check"
                        or name.endswith(".faults.check")):
                    continue
                site = None
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    site = node.args[0].value
                    used.setdefault(site, []).append((m, node))
                if registered and site is not None \
                        and site not in registered:
                    out.append(self.finding(
                        m, node,
                        f"fault site '{site}' is not in "
                        f"faults.KNOWN_SITES — register it (an "
                        f"unregistered site works but is invisible to "
                        f"the chaos tooling's coverage accounting)"))
                if not self._gated(node):
                    out.append(self.finding(
                        m, node,
                        "faults.check() without an enclosing "
                        "'if faults.ACTIVE:' gate — breaks the "
                        "checked-no-op contract on the unset path"))
        if faults_mod is not None and registered:
            for site in sorted(registered - set(used)):
                out.append(Finding(
                    rule=self.name, path=faults_mod.relpath, line=1,
                    col=1, severity=self.severity,
                    message=f"registered fault site '{site}' is never "
                            f"checked anywhere in the package — a dead "
                            f"seam; wire a faults.check('{site}') at "
                            f"the matching dispatch point or drop it "
                            f"from KNOWN_SITES",
                    code=f"KNOWN_SITES:{site}"))
        return out

    @staticmethod
    def _gated(node: ast.Call) -> bool:
        for anc in ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            if isinstance(anc, ast.If):
                for ref in ast.walk(anc.test):
                    if isinstance(ref, ast.Attribute) and \
                            ref.attr == "ACTIVE":
                        return True
                    if isinstance(ref, ast.Name) and ref.id == "ACTIVE":
                        return True
        return False


# ======================================================================
# monotonic-durations
# ======================================================================

class MonotonicDurationsRule(Rule):
    """``time.time()`` used in duration/deadline arithmetic.

    Wall clock steps under NTP slew (and leaps at DST on some hosts):
    ``max_runtime_secs`` enforcement, retry backoff and watchdog stall
    detection built on ``time.time()`` subtraction silently mis-measure.
    Duration math must use ``time.monotonic()`` (or ``perf_counter``);
    ``time.time()`` stays ONLY where an epoch timestamp is reported
    (span wall anchors, manifest times, cross-process gossip ages —
    those carry inline allows naming why wall time is required).

    Detection: any ``+``/``-`` expression with a ``time.time()`` call
    (or a local/module name assigned directly from one) in either
    operand. Multiplication (``time.time() * 1000`` epoch-ms
    reporting) is exempt by construction.
    """

    name = "monotonic-durations"
    severity = SEV_WARNING

    @staticmethod
    def _is_walltime_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and dotted_name(node.func) == "time.time")

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        wall_names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and self._is_walltime_call(
                    node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        wall_names.add(t.id)

        def _has_wall(expr: ast.AST) -> bool:
            for n in ast.walk(expr):
                if self._is_walltime_call(n):
                    return True
                if isinstance(n, ast.Name) and n.id in wall_names and \
                        isinstance(n.ctx, ast.Load):
                    return True
            return False

        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.BinOp) and isinstance(
                    node.op, (ast.Add, ast.Sub)):
                if _has_wall(node.left) or _has_wall(node.right):
                    out.append(self.finding(
                        mod, node,
                        "duration/deadline arithmetic on time.time() — "
                        "wall clock steps under NTP slew; use "
                        "time.monotonic() for intervals (keep "
                        "time.time() only for reported epoch "
                        "timestamps, with an inline allow saying why)"))
        return out


# ======================================================================
# pallas-grid-spec
# ======================================================================

class PallasGridSpecRule(Rule):
    """``pl.pallas_call`` without an explicit ``grid=`` or without
    explicit ``in_specs=``/``out_specs=`` BlockSpecs, and a hardcoded
    ``interpret=True`` outside tests.

    Pre-landed guardrail for the compiled-TPU histogram kernel (ROADMAP
    "raw speed" item): a pallas_call that leans on the implicit
    whole-array default grid compiles, runs — and silently serializes
    the kernel into one grid step with every operand in VMEM at once,
    which is exactly the shape that falls over (or quietly crawls) the
    first time a real block size matters. Every kernel states its grid
    and block mapping explicitly so the tiling is a reviewed decision,
    not a default. A ``grid_spec=`` kwarg carries both and satisfies
    the rule; ``**kwargs`` forwarding is assumed to carry them (call
    wrappers like ops/pallas_compat.py must not be flagged for
    forwarding). ``interpret=True`` as a LITERAL pins the interpreter
    into production code — the repo's convention is an ``interpret=``
    parameter threaded from ``pallas_interpret()`` (env-gated) so TPU
    runs compile; tests/ may pin it (CPU CI has no Mosaic).
    """

    name = "pallas-grid-spec"
    severity = SEV_ERROR

    _CALL_NAMES = ("pl.pallas_call", "pallas_call",
                   "pallas.pallas_call",
                   "jax.experimental.pallas.pallas_call")

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        in_tests = mod.relpath.startswith("tests/") or \
            "/tests/" in mod.relpath
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in self._CALL_NAMES:
                continue
            kwargs = {kw.arg for kw in node.keywords if kw.arg}
            forwards = any(kw.arg is None for kw in node.keywords)
            has_grid = "grid" in kwargs or "grid_spec" in kwargs
            has_specs = ("grid_spec" in kwargs
                         or ("in_specs" in kwargs
                             and "out_specs" in kwargs))
            if not has_grid and not forwards:
                out.append(self.finding(
                    mod, node,
                    "pallas_call without an explicit grid= — the "
                    "implicit whole-array grid serializes the kernel "
                    "into one step with every operand in VMEM; state "
                    "the tiling"))
            if not has_specs and not forwards:
                out.append(self.finding(
                    mod, node,
                    "pallas_call without explicit in_specs/out_specs "
                    "BlockSpecs — block mapping must be a reviewed "
                    "decision, not the whole-array default"))
            if not in_tests:
                for kw in node.keywords:
                    if kw.arg == "interpret" and isinstance(
                            kw.value, ast.Constant) and \
                            kw.value.value is True:
                        out.append(self.finding(
                            mod, node,
                            "interpret=True hardcoded outside tests — "
                            "thread an interpret= parameter from "
                            "pallas_interpret() (env-gated) so TPU "
                            "runs compile the kernel"))
        return out


# ======================================================================
# fleet-peer-discipline
# ======================================================================

# Modules allowed to read the peer/seed env vars: they ARE the
# member-table seam (telemetry's env fallback + the fleet seed read).
_BLESSED_PEER_MODULES = (
    "h2o3_tpu/telemetry/snapshot.py",
    "h2o3_tpu/fleet/membership.py",
)

_PEER_ENV_VARS = ("H2O3_TELEMETRY_PEERS", "H2O3_FLEET_SEEDS")


class FleetPeerDisciplineRule(Rule):
    """Router/membership hygiene for the serving fleet (ISSUE 13 —
    pre-landed with the router per the ROADMAP).

    Sub-checks:

    - **static-peer-env**: reading ``H2O3_TELEMETRY_PEERS`` /
      ``H2O3_FLEET_SEEDS`` (``os.environ.get``/``os.getenv``/
      ``environ[...]``) outside the blessed member-table seam modules.
      A static peer list read anywhere else is exactly the
      operator-edits-an-env-var failure mode dynamic membership
      retires: peer sets must come from the member table
      (``fleet.router().table`` / ``telemetry.snapshot.peer_view``),
      which a dead replica LEAVES. Writes (launchers exporting the env
      to children) are not flagged — only reads create a second
      source of membership truth.
    - **unretried-peer-http**: a ``urlopen`` call inside
      ``h2o3_tpu/fleet/`` that (a) is not enclosed in a function or
      lambda passed to ``resilience.retry_transient`` or (b) carries
      no explicit ``timeout=``. Cross-replica calls ride the one
      shared retry/backoff policy with a bounded deadline, or a sick
      peer pins the caller (the telemetry scrape's own single-try
      fetch has its module-level deadline loop and is out of scope).
    - **epoch-blind-routing**: a routing decision point (a function
      whose name contains ``route`` or ``failover`` in
      ``fleet/router.py``) that never references a membership
      ``epoch``. Decisions made without pinning the view they were
      made under can act on (and retry into) a dead epoch — the
      resurrection class the member table's fencing exists to stop.

    Tightening decisions: a route/failover-named helper that never
    touches membership state (no ``table``/``live_members``/
    ``members`` reference — e.g. a failure-mode classifier like
    ``_safe_to_failover``) makes no routing decision and is exempt
    from the epoch check.
    """

    name = "fleet-peer-discipline"
    severity = SEV_ERROR

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        in_tests = mod.relpath.startswith("tests/") or \
            "/tests/" in mod.relpath
        if in_tests:
            return []
        out: List[Finding] = []
        if not mod.relpath.endswith(_BLESSED_PEER_MODULES):
            out.extend(self._static_peer_env(mod))
        if "h2o3_tpu/fleet/" in mod.relpath or \
                mod.relpath.startswith("fleet/"):
            out.extend(self._unretried_peer_http(mod))
        if mod.relpath.endswith("fleet/router.py"):
            out.extend(self._epoch_blind_routing(mod))
        return out

    # -- sub-check (a): static peer env reads ---------------------------

    def _static_peer_env(self, mod: ModuleInfo) -> Iterable[Finding]:
        attach_parents(mod.tree)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Constant)
                    and node.value in _PEER_ENV_VARS):
                continue
            parent = getattr(node, "_h2o3_parent", None)
            is_read = False
            if isinstance(parent, ast.Call):
                head = dotted_name(parent.func) or ""
                if head.endswith(("environ.get", "getenv")) \
                        and parent.args and parent.args[0] is node:
                    is_read = True
            elif isinstance(parent, ast.Subscript) and isinstance(
                    getattr(parent, "ctx", None), ast.Load):
                base = dotted_name(parent.value) or ""
                if base.endswith("environ"):
                    is_read = True
            if is_read:
                yield self.finding(
                    mod, node,
                    f"static peer list read ({node.value}) outside the "
                    f"member-table seam — peer sets must come from the "
                    f"membership layer (fleet.router().table / "
                    f"telemetry.snapshot.peer_view), which a dead "
                    f"replica actually leaves")

    # -- sub-check (b): unretried / deadline-less peer HTTP -------------

    @staticmethod
    def _retried_scopes(mod: ModuleInfo) -> Set[int]:
        """ids of FunctionDef/Lambda nodes whose body runs under
        retry_transient: lambdas passed directly, plus defs whose NAME
        is passed (the nested-closure spelling)."""
        retried_names: Set[str] = set()
        retried_nodes: Set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            head = dotted_name(node.func) or ""
            if not head.endswith("retry_transient"):
                continue
            if node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Lambda):
                    retried_nodes.add(id(arg0))
                elif isinstance(arg0, ast.Name):
                    retried_names.add(arg0.id)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in retried_names:
                retried_nodes.add(id(node))
        return retried_nodes

    def _unretried_peer_http(self, mod: ModuleInfo) -> Iterable[Finding]:
        attach_parents(mod.tree)
        retried = self._retried_scopes(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            head = dotted_name(node.func) or ""
            if not (head == "urlopen" or head.endswith(".urlopen")):
                continue
            if "timeout" not in {kw.arg for kw in node.keywords}:
                yield self.finding(
                    mod, node,
                    "cross-replica urlopen without an explicit "
                    "timeout= — a sick peer pins this caller; bound "
                    "every fleet HTTP call by the request deadline")
            under_retry = False
            for anc in ancestors(node):
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    if id(anc) in retried:
                        under_retry = True
                    break
            if not under_retry:
                yield self.finding(
                    mod, node,
                    "cross-replica urlopen outside "
                    "resilience.retry_transient — fleet HTTP rides the "
                    "one shared transient-retry policy (wrap the "
                    "calling closure in retry_transient; attempts=1 "
                    "where failover is the retry)")

    # -- sub-check (c): epoch-blind routing decisions -------------------

    def _epoch_blind_routing(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            low = node.name.lower()
            if "route" not in low and "failover" not in low:
                continue
            has_epoch = False
            touches_membership = False
            for ref in ast.walk(node):
                if isinstance(ref, ast.Attribute):
                    if "epoch" in ref.attr.lower():
                        has_epoch = True
                        break
                    if ref.attr in ("table", "live_members", "members"):
                        touches_membership = True
                elif isinstance(ref, ast.Name):
                    if "epoch" in ref.id.lower():
                        has_epoch = True
                        break
                    if ref.id in ("table", "live_members", "members"):
                        touches_membership = True
            if not touches_membership:
                continue        # a classifier/helper, not a decision
            if not has_epoch:
                yield self.finding(
                    mod, node,
                    f"routing decision point '{node.name}' never "
                    f"references a membership epoch — decisions must "
                    f"pin the view they were made under (and failover "
                    f"must re-read it) so a dead epoch is never "
                    f"routed into")


# ======================================================================
# sched-discipline
# ======================================================================

# the training-dispatch layer: work here enters the device through the
# scheduler (ModelBuilder.train -> sched.submit) or runs inline under
# an already-admitted parent. Since ISSUE 18 the fleet package is in
# scope too: its placement/migration paths are scheduler extensions,
# and its async work rides one bounded ThreadPoolExecutor.
_SCHED_SCOPE_PREFIXES = ("h2o3_tpu/models/", "h2o3_tpu/fleet/")
_SCHED_SCOPE_FILES = ("h2o3_tpu/automl.py",)

# fleet-side placement decisions: function-name markers and the
# membership references that make a function a *decision* (vs a helper)
_PLACEMENT_MARKERS = ("place", "rebalance", "resubmit")
_MEMBERSHIP_WORDS = ("table", "members", "live_members", "view",
                     "current_view", "eligible", "candidates")


class SchedDisciplineRule(Rule):
    """Scheduler-bypass hazards in the training-dispatch layer
    (``h2o3_tpu/models/``, ``automl.py``) and the fleet package
    (``h2o3_tpu/fleet/``): raw ``threading.Thread`` spawns, and fleet
    placement decisions that never pin a membership epoch.

    Since ISSUE 15, every train enters the device through the cluster
    scheduler: ``ModelBuilder.train`` enqueues (priority class +
    device-memory admission + checkpoint preemption), and nested builds
    run inline under the admitted parent's grant. A bare daemon thread
    in this layer escapes all three — no admission (it can OOM a peer
    the scheduler promised memory to), no Job supervision, no
    preemption point. Route new fan-out through ``sched.submit_context``
    + ``train(background=True)``, or an inline ThreadPoolExecutor when
    the work rides an admitted parent (the CV-fold pattern —
    executors ARE allowed; they stay inside the parent's run).

    Since ISSUE 18 the fleet scheduler places trains across replicas,
    so ``h2o3_tpu/fleet/`` is in scope: its proxy/rebalance fan-out
    must ride the bounded executor (same no-raw-Thread contract — the
    heartbeat loop carries a reasoned allow comment), and every fleet
    PLACEMENT decision (a function named ``*place*``/``*rebalance*``/
    ``*resubmit*`` that reads membership state) must pin the membership
    epoch it decided under, the same fence fleet-peer-discipline
    enforces for routing — a placement computed against a dead view
    would hand a train to an evicted replica.

    Scope decision: jobs.py (the run machinery), sched/ (the
    dispatcher) and the non-training layers (serve/ingest) spawn
    threads legitimately and are outside this rule's scope.
    """

    name = "sched-discipline"
    severity = SEV_ERROR

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        rel = mod.relpath
        if not (rel.startswith(_SCHED_SCOPE_PREFIXES)
                or rel in _SCHED_SCOPE_FILES):
            return []
        # bare `Thread(...)` only counts when imported from threading
        bare_thread = any(
            isinstance(n, ast.ImportFrom) and n.module == "threading"
            and any(a.name == "Thread" for a in n.names)
            for n in ast.walk(mod.tree))
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name == "threading.Thread" or (bare_thread
                                              and name == "Thread"):
                out.append(self.finding(
                    mod, node,
                    "raw threading.Thread in the training-dispatch "
                    "layer bypasses the scheduler — no admission, no "
                    "Job supervision, no preemption point; submit via "
                    "ModelBuilder.train(background=True) under a "
                    "sched.submit_context, or use an inline "
                    "ThreadPoolExecutor when the work rides an "
                    "admitted parent build"))
        if rel.startswith("h2o3_tpu/fleet/"):
            out.extend(self._epoch_blind_placement(mod))
        return out

    def _epoch_blind_placement(self, mod: ModuleInfo
                               ) -> Iterable[Finding]:
        """Fleet placement decisions must pin a membership epoch —
        structurally the same fence fleet-peer-discipline applies to
        routing/failover, extended to the functions that decide WHERE
        a train runs."""
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            low = node.name.lower()
            if not any(m in low for m in _PLACEMENT_MARKERS):
                continue
            has_epoch = False
            touches_membership = False
            for ref in ast.walk(node):
                if isinstance(ref, ast.Attribute):
                    if "epoch" in ref.attr.lower():
                        has_epoch = True
                        break
                    if ref.attr in _MEMBERSHIP_WORDS:
                        touches_membership = True
                elif isinstance(ref, ast.Name):
                    if "epoch" in ref.id.lower():
                        has_epoch = True
                        break
                    if ref.id in _MEMBERSHIP_WORDS:
                        touches_membership = True
            if not touches_membership:
                continue        # a payload helper, not a decision
            if not has_epoch:
                yield self.finding(
                    mod, node,
                    f"fleet placement decision '{node.name}' never "
                    f"references a membership epoch — a train placed "
                    f"against a dead view lands on an evicted replica; "
                    f"pin the epoch the decision was made under "
                    f"(the admission headroom it read belongs to that "
                    f"view)")


# ======================================================================
# blackbox-discipline
# ======================================================================

# the control-plane packages whose decision points must leave a
# flight-recorder record (ISSUE 19)
_BB_SCOPE_PREFIXES = ("h2o3_tpu/fleet/", "h2o3_tpu/sched/")

# function names that ARE the recording/counting plumbing, not
# decision points
_BB_EXEMPT_FUNCS = {"_count", "_bb", "counters", "reset"}


class BlackboxDisciplineRule(Rule):
    """Control-plane decision points in the fleet/scheduler packages
    that mutate placement/membership state without leaving a flight-
    recorder record (ISSUE 19).

    A function in ``h2o3_tpu/fleet/`` or ``h2o3_tpu/sched/`` counts as
    a decision point when it (a) bumps a fleet decision counter
    (``_count(...)``), (b) increments a scheduler metric counter
    (``_m_*.inc(...)``), or (c) advances a membership epoch (an
    augmented assignment to ``*_epoch``, or a plain non-constant
    assignment to a ``*_epoch`` attribute — the gossip-absorb /
    ring-publish seams align the fence instead of bumping it). Each
    of those is a state
    mutation a post-mortem needs to see: a SIGKILLed replica whose
    placement/eviction/preemption decisions only lived in in-memory
    counters tells no story. The fix is one advisory
    ``blackbox.record(...)`` (or the module's ``_bb(...)`` helper)
    next to the mutation.

    Scope decisions: the counting/recording plumbing itself
    (``_count``, ``_bb``, ``counters``, ``reset``) is exempt; tests
    are out of scope. Nested closures are checked as part of their
    enclosing function — the record may legitimately sit in the outer
    body around the closure's mutation.
    """

    name = "blackbox-discipline"
    severity = SEV_ERROR

    @staticmethod
    def _mutates(ref: ast.AST) -> bool:
        if isinstance(ref, ast.Call):
            head = dotted_name(ref.func) or ""
            parts = head.split(".")
            if parts[-1] == "_count":
                return True
            if parts[-1] == "inc" and len(parts) >= 2 \
                    and parts[-2].startswith("_m_"):
                return True
        elif isinstance(ref, ast.AugAssign):
            t = ref.target
            tname = t.attr if isinstance(t, ast.Attribute) else (
                t.id if isinstance(t, ast.Name) else "")
            if tname.endswith("_epoch"):
                return True
        elif isinstance(ref, ast.Assign) and len(ref.targets) == 1:
            # a PLAIN epoch assignment to an attribute (gossip absorb
            # aligning to a peer's epoch, a published-ring stamp) moves
            # the same causal fence as an AugAssign bump. Constant
            # right-hand sides (the ``= 0`` / ``= -1`` initializers in
            # __init__/reset) are not decisions; locals ending _epoch
            # are reads of the fence, not moves of it
            t = ref.targets[0]
            v = ref.value
            if isinstance(v, ast.UnaryOp):   # ``= -1`` sentinel
                v = v.operand
            if isinstance(t, ast.Attribute) \
                    and t.attr.endswith("_epoch") \
                    and not isinstance(v, ast.Constant):
                return True
        return False

    @staticmethod
    def _records(ref: ast.AST) -> bool:
        if not isinstance(ref, ast.Call):
            return False
        head = dotted_name(ref.func) or ""
        parts = head.split(".")
        if parts[-1] == "_bb":
            return True
        return (parts[-1] == "record" and len(parts) >= 2
                and "blackbox" in parts[-2])

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        if not mod.relpath.startswith(_BB_SCOPE_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name in _BB_EXEMPT_FUNCS:
                continue
            mutates = records = False
            for ref in ast.walk(node):
                mutates = mutates or self._mutates(ref)
                records = records or self._records(ref)
                if mutates and records:
                    break
            if mutates and not records:
                out.append(self.finding(
                    mod, node,
                    f"control-plane decision point '{node.name}' "
                    f"mutates placement/membership state (decision "
                    f"counter / metric inc / epoch bump) without a "
                    f"flight-recorder record — add an advisory "
                    f"blackbox.record()/_bb() next to the mutation so "
                    f"a post-mortem can see the decision"))
        return out


# ======================================================================
# registry
# ======================================================================

def all_rules(hot_zones: Optional[Dict[str, Tuple[str, ...]]] = None
              ) -> List[Rule]:
    return [
        TransferSeamRule(),
        RecompileHazardRule(),
        HostSyncHotLoopRule(zones=hot_zones),
        LockDisciplineRule(),
        FaultSeamRule(),
        MonotonicDurationsRule(),
        PallasGridSpecRule(),
        FleetPeerDisciplineRule(),
        SchedDisciplineRule(),
        BlackboxDisciplineRule(),
    ]


def rule_names() -> List[str]:
    return [r.name for r in all_rules()]
