"""Rule framework for h2o3-lint: findings, suppressions, baseline, runner.

Design constraints, in order:

1. **One parse per file.** Every rule visits the same cached
   ``ast.Module`` (``ModuleInfo``), so a whole-package run is dominated
   by one ``ast.parse`` pass — fast enough for tier-1
   (tests/test_lint.py runs it on every ``pytest`` invocation).
2. **Ratchet, not gate.** Pre-existing findings live in a checked-in
   baseline keyed on (rule, path, source-line text) — NOT line numbers,
   so unrelated edits don't churn it. New findings fail; fixed findings
   leave *stale* baseline entries which ALSO fail until removed, so the
   baseline shrinks monotonically.
3. **Explainable suppressions.** ``# h2o3-lint: allow[rule-a,rule-b]``
   on the finding's line silences exactly the named rules on exactly
   that line; an unknown rule name in a suppression is itself an error
   (a typo'd allow must not silently stop allowing).
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"

_ALLOW_RE = re.compile(r"#\s*h2o3-lint:\s*allow\[([^\]]*)\]")


@dataclass
class Finding:
    rule: str
    path: str              # posix-style path relative to the lint root
    line: int              # 1-based, informational (baseline ignores it)
    col: int
    message: str
    severity: str = SEV_ERROR
    code: str = ""         # stripped source line — the baseline identity

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.code)

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "severity": self.severity,
                "message": self.message, "code": self.code}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.severity}: {self.message}")


class ModuleInfo:
    """One parsed source file, shared by every rule."""

    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        # line -> allowed rule names. Parsed from real COMMENT tokens,
        # not a line regex — a docstring *describing* the suppression
        # syntax must not BE a suppression (the linter's own docs were
        # the first false positive)
        self.allows: Dict[int, List[str]] = {}
        try:
            toks = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _ALLOW_RE.search(tok.string)
                if m:
                    self.allows[tok.start[0]] = [
                        s.strip() for s in m.group(1).split(",")
                        if s.strip()]
        except tokenize.TokenError:
            pass    # ast.parse succeeded, so this should be unreachable

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class Rule:
    """Base class. Subclasses set ``name``/``severity``/``scope`` and
    implement ``check_module`` (scope "module") or ``check_package``
    (scope "package" — rules needing the whole-program view, e.g.
    fault-seam's registered-vs-used site matching). The class docstring
    is the rule's catalog entry (surfaced by ``--rules``); record
    tightening decisions there, not in the baseline."""

    name = ""
    severity = SEV_ERROR
    scope = "module"

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        return []

    def check_package(self, mods: Sequence[ModuleInfo]) -> List[Finding]:
        return []

    def finding(self, mod: ModuleInfo, node: ast.AST, message: str,
                severity: Optional[str] = None) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=self.name, path=mod.relpath, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message,
                       severity=severity or self.severity,
                       code=mod.line_text(line))


@dataclass
class LintReport:
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale: List[Dict[str, object]] = field(default_factory=list)
    files: int = 0
    rules: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.new and not self.stale

    def to_dict(self) -> Dict[str, object]:
        return {
            "ok": self.ok,
            "files": self.files,
            "rules": self.rules,
            "counts": {"new": len(self.new),
                       "baselined": len(self.baselined),
                       "suppressed": len(self.suppressed),
                       "stale_baseline_entries": len(self.stale)},
            "findings": [f.to_dict() for f in self.new],
            "stale_baseline_entries": self.stale,
        }


# ---------------- file discovery ---------------------------------------

def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


def parse_modules(paths: Iterable[str],
                  root: Optional[str] = None
                  ) -> Tuple[List[ModuleInfo], List[Finding]]:
    """Parse every file once. Unparseable files become ``parse-error``
    findings instead of aborting the run (the linter must never be the
    thing that wedges CI on a half-written file)."""
    root = os.path.abspath(root or os.getcwd())
    mods: List[ModuleInfo] = []
    errors: List[Finding] = []
    for path in iter_py_files(paths):
        abspath = os.path.abspath(path)
        rel = os.path.relpath(abspath, root)
        if rel.startswith(".."):
            rel = abspath
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                source = f.read()
            mods.append(ModuleInfo(abspath, rel, source))
        except (SyntaxError, ValueError, OSError) as e:
            errors.append(Finding(
                rule="parse-error", path=rel.replace(os.sep, "/"),
                line=getattr(e, "lineno", 1) or 1, col=1,
                message=f"could not parse: {e}", severity=SEV_ERROR))
    return mods, errors


# ---------------- suppressions -----------------------------------------

def apply_suppressions(findings: List[Finding], mods: Sequence[ModuleInfo],
                       known_rules: Sequence[str]
                       ) -> Tuple[List[Finding], List[Finding], List[Finding]]:
    """Split findings into (kept, suppressed); also emit errors for
    suppression comments naming unknown rules (anywhere in the file,
    even lines with no finding — a typo'd allow is latent either way)."""
    by_mod = {m.relpath: m for m in mods}
    known = set(known_rules) | {"parse-error", "lint-suppression"}
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    errors: List[Finding] = []
    for m in mods:
        for lineno, names in m.allows.items():
            for n in names:
                if n not in known:
                    errors.append(Finding(
                        rule="lint-suppression", path=m.relpath,
                        line=lineno, col=1,
                        message=f"unknown rule '{n}' in suppression "
                                f"(known: {', '.join(sorted(known_rules))})",
                        severity=SEV_ERROR, code=m.line_text(lineno)))
    for f in findings:
        mod = by_mod.get(f.path)
        allowed = mod.allows.get(f.line, []) if mod is not None else []
        if f.rule in allowed:
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed, errors


# ---------------- baseline ---------------------------------------------

BASELINE_VERSION = 1


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str] = None) -> Dict[Tuple[str, str, str], int]:
    """Baseline as a multiset: (rule, path, code) -> count."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str], int] = {}
    for ent in data.get("entries", []):
        key = (str(ent["rule"]), str(ent["path"]), str(ent["code"]))
        out[key] = out.get(key, 0) + int(ent.get("count", 1))
    return out


def save_baseline(findings: Sequence[Finding],
                  path: Optional[str] = None,
                  note: str = "") -> str:
    path = path or default_baseline_path()
    counts: Dict[Tuple[str, str, str], int] = {}
    lines: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
        lines.setdefault(f.key(), f.line)
    entries = [{"rule": k[0], "path": k[1], "code": k[2],
                "count": v, "line": lines[k]}
               for k, v in sorted(counts.items())]
    data = {"version": BASELINE_VERSION,
            "note": note or
            "Documented pre-existing findings. This file may only "
            "shrink: fix a finding, then delete its entry (or rerun "
            "tools/h2o3_lint.py --write-baseline). 'line' is "
            "informational; identity is (rule, path, code).",
            "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    return path


def match_baseline(findings: List[Finding],
                   baseline: Dict[Tuple[str, str, str], int]
                   ) -> Tuple[List[Finding], List[Finding],
                              List[Dict[str, object]]]:
    """Consume baseline entries multiset-style. Returns
    (new, baselined, stale_entries)."""
    remaining = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = [{"rule": k[0], "path": k[1], "code": k[2], "count": v}
             for k, v in sorted(remaining.items()) if v > 0]
    return new, old, stale


# ---------------- runner -----------------------------------------------

def run_lint(paths: Sequence[str], rules: Sequence[Rule],
             baseline: Optional[Dict[Tuple[str, str, str], int]] = None,
             root: Optional[str] = None) -> LintReport:
    mods, parse_errors = parse_modules(paths, root=root)
    raw: List[Finding] = list(parse_errors)
    for rule in rules:
        if rule.scope == "package":
            raw.extend(rule.check_package(mods))
        else:
            for m in mods:
                raw.extend(rule.check_module(m))
    kept, suppressed, supp_errors = apply_suppressions(
        raw, mods, [r.name for r in rules])
    kept.extend(supp_errors)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    new, old, stale = match_baseline(kept, baseline or {})
    return LintReport(new=new, baselined=old, suppressed=suppressed,
                      stale=stale, files=len(mods),
                      rules=[r.name for r in rules])


# ---------------- shared AST helpers (used by rules) -------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.device_put' for Attribute/Name chains; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def attach_parents(tree: ast.Module) -> None:
    """Annotate each node with ``._h2o3_parent`` (idempotent)."""
    if getattr(tree, "_h2o3_parented", False):
        return
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._h2o3_parent = parent  # type: ignore[attr-defined]
    tree._h2o3_parented = True  # type: ignore[attr-defined]


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_h2o3_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_h2o3_parent", None)
