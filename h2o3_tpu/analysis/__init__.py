"""h2o3_tpu.analysis — repo-native static analysis (`h2o3-lint`).

The platform's performance and resilience story rests on conventions
that nothing else enforces: every H2D/D2H flows through the
telemetry-counted + fault-injectable seams, jitted hot paths must not
hide recompile hazards or host syncs, and the threaded serve/jobs/
telemetry planes must not block on device work while holding locks.
H2O-3 enforces its equivalent invariants at build time (the javassist
``Weaver`` rejects non-conforming ``Iced`` classes at class load); this
package is the TPU rebuild's analog: an AST-based rule engine that runs
in tier-1 (tests/test_lint.py) and via ``tools/h2o3_lint.py``.

Layout:

- :mod:`h2o3_tpu.analysis.core`  — rule framework: ``Rule``/``Finding``,
  inline ``# h2o3-lint: allow[rule]`` suppressions, the checked-in
  baseline ratchet, and the single-parse-per-file runner.
- :mod:`h2o3_tpu.analysis.rules` — the rules encoding this repo's
  invariants (transfer-seam, recompile-hazard, host-sync-hot-loop,
  lock-discipline, fault-seam, monotonic-durations).
- ``baseline.json`` — documented pre-existing findings; it may only
  shrink (stale entries fail the run until removed).
"""
from h2o3_tpu.analysis.core import (Finding, LintReport, ModuleInfo, Rule,
                                    load_baseline, run_lint, save_baseline)
from h2o3_tpu.analysis.rules import all_rules, rule_names

__all__ = [
    "Finding", "LintReport", "ModuleInfo", "Rule", "all_rules",
    "load_baseline", "rule_names", "run_lint", "save_baseline",
]
