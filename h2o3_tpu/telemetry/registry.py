"""Process-wide metrics registry: counters, gauges, histograms with labels.

Reference: water/util/WaterMeter* and the per-request counters scattered
through water/api — the rebuild had grown the same scatter (serve/stats
mutexes, log.Profile dicts, tools/ private timers), so this is the one
producer everything else now feeds.

Design constraints, in order:

1. **Hot-path safe.** Metric mutation sits on the serve request path, so
   instance operations take one striped lock (64 stripes shared across
   every metric, hash-partitioned by identity) — never a registry-wide
   mutex. Handle lookup (``registry().counter(...)``) is the slow path;
   call sites hold the returned handle.
2. **Measurably free when off.** ``H2O3_TELEMETRY=0`` makes every
   mutation a single attribute-load + branch (no lock, no arithmetic);
   see tests/test_telemetry.py's ns-budget guard.
3. **Views, not copies.** Scrape-time ``collectors`` (callables run
   inside ``snapshot()``) let subsystems that keep their own state
   (device memory, live deployments) appear in the export without
   paying per-event mirroring.
"""
from __future__ import annotations

import os
import threading
from bisect import bisect_left
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_N_STRIPES = 64
_STRIPES = [threading.Lock() for _ in range(_N_STRIPES)]


def _stripe(key) -> threading.Lock:
    return _STRIPES[hash(key) % _N_STRIPES]


LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# default histogram bounds: latencies in seconds, log-ish spaced from
# 100µs to 100s — wide enough for both a serve tick and a cold compile
DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5,
                   1.0, 5.0, 10.0, 50.0, 100.0)


class _Metric:
    """Base: every metric holds a back-reference to its registry so the
    enabled check is one attribute chain, togglable at runtime."""
    __slots__ = ("name", "labels", "_reg", "_lock")
    kind = "untyped"

    def __init__(self, reg: "Registry", name: str, labels: LabelKey):
        self.name = name
        self.labels = labels
        self._reg = reg
        self._lock = _stripe((name, labels))


class Counter(_Metric):
    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self, reg, name, labels):
        super().__init__(reg, name, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self, reg, name, labels):
        super().__init__(reg, name, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self._value += n

    def set_max(self, v: float) -> None:
        """Monotonic high-watermark update (peak device memory)."""
        if not self._reg.enabled:
            return
        with self._lock:
            if v > self._value:
                self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Prometheus-style cumulative-bucket histogram (+ sum and count).
    ``observe`` is O(log buckets) via bisect under one striped lock."""
    __slots__ = ("bounds", "_counts", "_sum", "_count")
    kind = "histogram"

    def __init__(self, reg, name, labels,
                 bounds: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(reg, name, labels)
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)   # +1 = +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        if not self._reg.enabled:
            return
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le_bound, cumulative_count), ...] ending with +Inf."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for b, c in zip(self.bounds, counts[:-1]):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out


class Registry:
    """A family dict (name → help/kind) over instance dicts
    ((name, labelkey) → metric). One creation lock; mutation locks are
    the module-level stripes."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._mu = threading.Lock()
        self._families: Dict[str, Tuple[str, str]] = {}   # name → (kind, help)
        self._metrics: Dict[Tuple[str, LabelKey], _Metric] = {}
        self._collectors: List[Callable[[], Iterable[dict]]] = []

    # -- handle factories (slow path: call once, hold the handle) -------

    def _get(self, cls, name: str, labels, help_: str, **kw):
        key = (name, _label_key(labels))
        with self._mu:
            m = self._metrics.get(key)
            if m is None:
                fam = self._families.get(name)
                if fam is not None and fam[0] != cls.kind:
                    raise TypeError(
                        f"metric '{name}' already registered as {fam[0]}, "
                        f"requested {cls.kind}")
                self._families.setdefault(name, (cls.kind, help_))
                m = cls(self, name, key[1], **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric '{name}' is a {m.kind}, "
                                f"requested {cls.kind}")
        return m

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None,
                help: str = "") -> Counter:
        return self._get(Counter, name, labels, help)

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None,
              help: str = "") -> Gauge:
        return self._get(Gauge, name, labels, help)

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  help: str = "",
                  bounds: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, labels, help, bounds=bounds)

    # -- scrape-time views ---------------------------------------------

    def add_collector(self, fn: Callable[[], Iterable[dict]]) -> None:
        """Register a scrape-time view: ``fn()`` yields sample dicts
        ``{name, kind, labels, value, help?}`` evaluated inside
        ``snapshot()`` — zero hot-path cost for the producer."""
        with self._mu:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def remove_collector(self, fn) -> None:
        with self._mu:
            if fn in self._collectors:
                self._collectors.remove(fn)

    # -- snapshot -------------------------------------------------------

    def samples(self) -> List[dict]:
        """Flat sample list, metrics + collector views, stable order."""
        with self._mu:
            metrics = list(self._metrics.values())
            families = dict(self._families)
            collectors = list(self._collectors)
        out: List[dict] = []
        for m in sorted(metrics, key=lambda m: (m.name, m.labels)):
            help_ = families.get(m.name, ("", ""))[1]
            base = {"name": m.name, "kind": m.kind,
                    "labels": dict(m.labels), "help": help_}
            if isinstance(m, Histogram):
                out.append({**base, "sum": m.sum, "count": m.count,
                            "buckets": m.cumulative()})
            else:
                out.append({**base, "value": m.value})
        if not self.enabled:
            return out
        for fn in collectors:
            try:
                for s in fn():
                    s.setdefault("kind", "gauge")
                    s.setdefault("labels", {})
                    s.setdefault("help", "")
                    out.append(s)
            except Exception:      # a broken view must not sink a scrape
                continue
        return out

    def value(self, name: str,
              labels: Optional[Dict[str, str]] = None) -> float:
        """Current value of one counter/gauge (0.0 if never touched)."""
        m = self._metrics.get((name, _label_key(labels)))
        if m is None or isinstance(m, Histogram):
            return 0.0
        return m.value

    def snapshot(self) -> Dict[str, object]:
        """JSON-shaped snapshot (the /3/Telemetry body)."""
        flat: Dict[str, object] = {}
        for s in self.samples():
            key = s["name"]
            if s["labels"]:
                key += "{" + ",".join(f"{k}={v}" for k, v in
                                      sorted(s["labels"].items())) + "}"
            if s["kind"] == "histogram":
                flat[key] = {"sum": round(s["sum"], 6), "count": s["count"]}
            else:
                flat[key] = s["value"]
        return flat

    def reset(self) -> None:
        """Drop every metric and collector (test isolation only)."""
        with self._mu:
            self._metrics.clear()
            self._families.clear()
            self._collectors.clear()
        if self is _REGISTRY:
            # hot-path handle caches hold metrics of THIS registry —
            # stale handles would silently record into dropped objects.
            # Each cache registers its clear via on_reset at import
            for fn in _RESET_HOOKS:
                fn()


_RESET_HOOKS: List[Callable[[], None]] = []


def on_reset(fn: Callable[[], None]) -> Callable[[], None]:
    """Register a callback Registry.reset() runs on the process
    registry. Modules that cache metric HANDLES (spans, collectors,
    parallel.shardstats) register their cache's ``.clear`` here at
    import, so test resets cannot leave handles recording into dropped
    metric objects — no cross-module reach-ins from reset()."""
    _RESET_HOOKS.append(fn)
    return fn


def _env_enabled() -> bool:
    return os.environ.get("H2O3_TELEMETRY", "1") not in ("0", "false", "")


_REGISTRY = Registry(enabled=_env_enabled())


def registry() -> Registry:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY.enabled


def set_enabled(on: bool) -> None:
    _REGISTRY.enabled = bool(on)
