"""h2o3_tpu.telemetry — the unified observability backbone.

One process-wide metrics registry (counters/gauges/histograms with
labels, lock-striped for the serve hot path), one span API (nested
timing contexts with explicit cross-thread parent handoff), and
device-aware collectors (XLA compile counter, compile-cache hit/miss,
h2d/d2h transfer bytes, device memory) — the single producer behind
``GET /metrics`` (Prometheus), ``GET /3/Telemetry`` (JSON snapshot) and
``GET /3/Timeline?format=trace`` (Perfetto), and the data source the
profiler tools (tools/profile_*.py) and bench rounds read.

``H2O3_TELEMETRY=0`` turns every producer into a checked no-op (one
attribute load + branch — guarded by tests/test_telemetry.py's
ns-budget microbench).
"""
from h2o3_tpu.telemetry import costmodel
from h2o3_tpu.telemetry.collectors import (device_get, device_memory_bytes,
                                           install, installed, record_d2d,
                                           record_d2h, record_h2d,
                                           sample_device_memory)
from h2o3_tpu.telemetry.export import (chrome_trace, chrome_trace_bytes,
                                       prometheus_text, telemetry_snapshot)
from h2o3_tpu.telemetry.profiling import profile
from h2o3_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                         Registry, enabled, registry,
                                         set_enabled)
from h2o3_tpu.telemetry.snapshot import (cluster_samples, cluster_snapshot,
                                         local_snapshot, merge_snapshots)
from h2o3_tpu.telemetry.spans import (Span, clear_spans, current_span,
                                      finished_spans, last_error_span,
                                      open_span, record_span,
                                      set_ring_capacity, span,
                                      stage_seconds)
from h2o3_tpu.telemetry import trace

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Span",
    "chrome_trace", "chrome_trace_bytes", "clear_spans",
    "cluster_samples", "cluster_snapshot", "costmodel", "current_span",
    "device_get", "device_memory_bytes", "enabled", "finished_spans", "install",
    "installed", "last_error_span", "local_snapshot", "merge_snapshots",
    "open_span", "profile", "prometheus_text",
    "record_d2d", "record_d2h",
    "record_h2d", "record_span", "registry", "sample_device_memory",
    "set_enabled", "set_ring_capacity", "span", "stage_seconds",
    "telemetry_snapshot", "trace",
]


def counter(name, labels=None, help=""):
    """Shorthand: a counter handle from the global registry."""
    return registry().counter(name, labels, help)


def gauge(name, labels=None, help=""):
    return registry().gauge(name, labels, help)


def histogram(name, labels=None, help="", **kw):
    return registry().histogram(name, labels, help, **kw)
