"""Performance accounting: per-executable FLOP/byte attribution, honest
MFU and roofline placement (ISSUE 11).

The telemetry plane (PR 4/8) says where TIME goes; this module says what
the hardware COULD have done with it. At every jit seam the compile
counter already watches (GBM/DRF ``_compiled_chunk`` dispatch, the
streamed-GBM level kernels, serve bucket executables, the frame rollup
reduction) the lowered program's XLA cost analysis (``flops``, ``bytes
accessed``) is captured ONCE per cached executable and paired with
measured device time at the existing commit seams, yielding:

- ``achieved_flops`` / ``achieved_bytes_per_s`` — executed work over
  measured device-saturated wall time;
- ``arith_intensity`` (flops/byte) and the roofline regime — compute-
  vs memory-bound against the detected ridge point;
- ``MFU`` — achieved flops / peak flops, the number that survives
  hardware changes (ROADMAP: vs_baseline is a nominal constant).

Honesty riders, recorded rather than hidden:

- cost analysis runs on the UNOPTIMIZED lowered HLO: a ``lax.scan``
  body is counted once, so scan-shaped programs pass ``scale=`` (the
  trip count) and the non-scan prologue is overcounted by at most
  1/scale — callers note coverage via ``note=``;
- peaks come from a per-chip lookup table over
  ``jax.devices()[0].device_kind`` (bf16 MXU peak + HBM bandwidth),
  overridable via ``H2O3_PEAK_FLOPS`` / ``H2O3_PEAK_BYTES_PER_S`` for
  unknown hardware. ``peak_source`` is recorded per field; any
  ``nominal`` source (CPU / unknown kind without an override) flags the
  whole point ``informational`` — a CPU-virtual MFU is a trend line,
  not a utilization claim.

``H2O3_TELEMETRY=0`` keeps every producer a checked no-op:
``accumulator()`` returns None and ``executable_cost`` returns without
tracing anything.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from h2o3_tpu.telemetry.registry import on_reset, registry


class Cost(NamedTuple):
    """One executable's analytic work: flops + HBM bytes accessed."""
    flops: float
    bytes: float


# ------------------------------------------------------------- peaks

# per-chip peaks: (device_kind substring lowercase, peak FLOPS, HBM
# bytes/s). bf16 MXU peak — the precision the histogram/predict kernels
# actually run in; README "Performance accounting" records the sources.
# Ordered most-specific-first: "v5 lite"/"v5e" must match before "v5".
_PEAK_TABLE: Tuple[Tuple[str, float, float], ...] = (
    ("tpu v6 lite", 918e12, 1638e9),    # Trillium / v6e
    ("tpu v6e", 918e12, 1638e9),
    ("tpu v5 lite", 197e12, 819e9),     # v5e
    ("tpu v5e", 197e12, 819e9),
    ("tpu v5p", 459e12, 2765e9),
    ("tpu v5", 459e12, 2765e9),
    ("tpu v4", 275e12, 1228e9),
    ("tpu v3", 123e12, 900e9),
    ("tpu v2", 45e12, 700e9),
)

# unknown hardware (CPU backend, virtual devices, new TPU kinds without
# a table row or override): a nominal single-socket-class constant so
# trend lines still render — flagged informational, never a claim
NOMINAL_PEAK_FLOPS = 1e12
NOMINAL_PEAK_BYTES_PER_S = 100e9


def _device_kind() -> str:
    try:
        import jax
        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def _env_float(name: str) -> Optional[float]:
    v = os.environ.get(name)
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        from h2o3_tpu.log import warn
        warn("%s=%r is not a number — ignoring the override", name, v)
        return None


def device_peaks() -> Dict[str, object]:
    """Per-chip peak FLOPS and memory bandwidth with provenance:
    ``source`` per field is ``override`` (env), ``table`` (device_kind
    lookup) or ``nominal`` (unknown hardware); ``informational`` is set
    whenever any field fell back to nominal. Read fresh each call (env
    overrides are test/bench knobs)."""
    kind = _device_kind()
    t_flops = t_bytes = None
    for sub, fl, by in _PEAK_TABLE:
        if sub in kind.lower():
            t_flops, t_bytes = fl, by
            break
    out: Dict[str, object] = {"device_kind": kind}
    ov_f = _env_float("H2O3_PEAK_FLOPS")
    ov_b = _env_float("H2O3_PEAK_BYTES_PER_S")
    if ov_f is not None:
        out["flops"], out["flops_source"] = ov_f, "override"
    elif t_flops is not None:
        out["flops"], out["flops_source"] = t_flops, "table"
    else:
        out["flops"], out["flops_source"] = NOMINAL_PEAK_FLOPS, "nominal"
    if ov_b is not None:
        out["bytes_per_s"], out["bytes_source"] = ov_b, "override"
    elif t_bytes is not None:
        out["bytes_per_s"], out["bytes_source"] = t_bytes, "table"
    else:
        out["bytes_per_s"], out["bytes_source"] = (
            NOMINAL_PEAK_BYTES_PER_S, "nominal")
    out["peak_source"] = ("override" if "override" in
                          (out["flops_source"], out["bytes_source"])
                          else out["flops_source"])
    out["informational"] = ("nominal" in (out["flops_source"],
                                          out["bytes_source"]))
    return out


# ----------------------------------------------- executable cost cache

# (seam key) -> Cost | None (None = capture failed; don't retry every
# dispatch). Bounded: keys are per-(mesh, config, bucket) like the jit
# caches they mirror.
_COSTS: "OrderedDict[tuple, Optional[Cost]]" = OrderedDict()
# key -> the scale (scan trip count) the cached Cost was multiplied by,
# so consumers that want PER-ITERATION work (the training scheduler's
# admission working-set hint) can divide it back out
_COST_SCALES: Dict[tuple, float] = {}
_COSTS_LOCK = threading.Lock()
_COSTS_CAP = 512


def _extract_cost(lowered) -> Optional[Cost]:
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    return Cost(float(ca.get("flops", 0.0) or 0.0),
                float(ca.get("bytes accessed", 0.0) or 0.0))


def lowered_cost(lower: Callable[[], object],
                 scale: float = 1.0) -> Optional[Cost]:
    """Uncached capture: ``lower()`` returns a ``jax.stages.Lowered``
    (trace+lower only — NO backend compile, so the zero-recompile
    guards never see this). ``scale`` multiplies the analytic counts
    (scan trip count — the HLO analysis counts a while body once)."""
    if not registry().enabled:
        return None
    try:
        c = _extract_cost(lower())
    except Exception:
        return None
    if c is None:
        return None
    return Cost(c.flops * scale, c.bytes * scale)


def executable_cost(key: tuple, lower: Callable[[], object],
                    scale: float = 1.0) -> Optional[Cost]:
    """Cached per-executable cost: one trace+lower per ``key`` for the
    process lifetime — the warm path pays a dict lookup. A key that
    failed to capture stays None (no per-dispatch retries)."""
    if not registry().enabled:
        return None
    with _COSTS_LOCK:
        if key in _COSTS:
            _COSTS.move_to_end(key)
            return _COSTS[key]
    cost = lowered_cost(lower, scale=scale)
    with _COSTS_LOCK:
        _COSTS[key] = cost
        _COST_SCALES[key] = max(float(scale), 1.0)
        while len(_COSTS) > _COSTS_CAP:
            old, _ = _COSTS.popitem(last=False)
            _COST_SCALES.pop(old, None)
    return cost


def traced_cost(key: tuple, fn: Callable, *args, **kwargs
                ) -> Optional[Cost]:
    """``executable_cost`` for a plain traceable function: jit+lower it
    once per key (eager call sites like the streamed level kernels have
    no jitted handle to lower)."""
    scale = kwargs.pop("scale", 1.0)

    def _lower():
        import jax
        return jax.jit(fn).lower(*args, **kwargs)

    return executable_cost(key, _lower, scale=scale)


def cost_cache_size() -> int:
    with _COSTS_LOCK:
        return len(_COSTS)


def per_iteration_bytes_hint(prefix: str) -> Optional[float]:
    """Max PER-ITERATION HBM bytes accessed over cached executables
    whose key leads with ``prefix`` (e.g. ``"gbm.chunk"``): the cached
    Cost was multiplied by its scan trip count at capture, so dividing
    it back out yields what ONE tree/step touches — the training
    scheduler's admission working-set refinement (ISSUE 15). Bytes
    accessed bound the resident working set from above (every resident
    operand is read at least once per step), so the hint is a
    conservative OVER-estimate; None when nothing is cached yet (cold
    process — shape-based fallback applies)."""
    best = None
    with _COSTS_LOCK:
        for key, cost in _COSTS.items():
            if cost is None or not key or key[0] != prefix:
                continue
            per_it = cost.bytes / _COST_SCALES.get(key, 1.0)
            if best is None or per_it > best:
                best = per_it
    return best


def cost_cached(key: tuple) -> bool:
    """Whether ``key`` already holds a captured cost — call sites use
    this to detect a COLD call (first compile + first lower land in the
    same invocation) and keep its skewed wall time out of the measured
    device seconds."""
    with _COSTS_LOCK:
        return key in _COSTS


# ------------------------------------------------------- roofline math

def roofline_point(flops: float, bytes_: float, seconds: float,
                   n_devices: int = 1,
                   peaks: Optional[Dict] = None,
                   note: Optional[str] = None) -> Optional[Dict]:
    """Derive the roofline point for accumulated work over measured
    device time. ``n_devices`` scales the per-chip peaks (the lowered
    program is the GLOBAL module on a sharded mesh — its flops span
    every participating chip)."""
    if seconds <= 0 or (flops <= 0 and bytes_ <= 0):
        return None
    peaks = peaks or device_peaks()
    pk_f = float(peaks["flops"]) * max(int(n_devices), 1)
    pk_b = float(peaks["bytes_per_s"]) * max(int(n_devices), 1)
    ach_f = flops / seconds
    ach_b = bytes_ / seconds
    ai = (flops / bytes_) if bytes_ > 0 else None
    ridge = pk_f / pk_b        # flops/byte at the roofline knee
    regime = ("compute-bound" if ai is not None and ai >= ridge
              else "memory-bound")
    # significant-figure rounding: a tiny-but-real MFU (CPU backend,
    # huge peak override) must not decimal-round to a fake 0.0
    def _sig(x):
        return float(f"{x:.4g}")

    mfu = ach_f / pk_f
    bw_util = ach_b / pk_b
    # attainable ceiling at this intensity: min(peak, AI x bandwidth)
    attain = min(pk_f, ai * pk_b) if ai is not None else pk_f
    pt = {
        "flops_total": float(flops),
        "bytes_total": float(bytes_),
        "device_seconds": round(float(seconds), 6),
        "achieved_flops": round(ach_f, 1),
        "achieved_bytes_per_s": round(ach_b, 1),
        "arith_intensity": _sig(ai) if ai is not None else None,
        "ridge_intensity": _sig(ridge),
        "roofline_regime": regime,
        "mfu": _sig(mfu),
        "bw_utilization": _sig(bw_util),
        "roofline_utilization": _sig(ach_f / attain) if attain else None,
        "n_devices": int(n_devices),
        "peak_flops": pk_f,
        "peak_bytes_per_s": pk_b,
        "peak_source": peaks["peak_source"],
        "device_kind": peaks["device_kind"],
        "informational": bool(peaks["informational"]),
    }
    if note:
        pt["note"] = note
    return pt


# --------------------------------------------------- phase accumulation

# registry handles per phase, cached off the creation mutex (the GBM
# chunk loop touches these per dispatch). Cleared on Registry.reset().
_PHASE_HANDLES: Dict[str, tuple] = {}
on_reset(_PHASE_HANDLES.clear)


def _phase_counters(phase: str):
    h = _PHASE_HANDLES.get(phase)
    if h is None:
        reg = registry()
        lab = {"phase": phase}
        h = (reg.counter("h2o3_achieved_flops_total", lab,
                         help="executed flops by phase (cost_analysis "
                              "x dispatch count)"),
             reg.counter("h2o3_achieved_bytes_total", lab,
                         help="HBM bytes accessed by phase"),
             reg.counter("h2o3_device_seconds_total", lab,
                         help="measured device-saturated seconds by "
                              "phase"))
        _PHASE_HANDLES[phase] = h
    return h


def record(phase: str, cost: Optional[Cost],
           seconds: Optional[float] = None, n: int = 1) -> None:
    """One-shot accounting (the rollup / ingest-assembly seams): fold a
    cost (xN executions) and optionally its measured seconds into the
    phase counters. No-op when telemetry is disabled."""
    if not registry().enabled:
        return
    cf, cb, cs = _phase_counters(phase)
    if cost is not None and n > 0:
        cf.inc(cost.flops * n)
        cb.inc(cost.bytes * n)
    if seconds is not None and seconds > 0:
        cs.inc(float(seconds))


class PerfAccumulator:
    """Per-window (one train / one live deployment) accounting: ``add``
    at each dispatch, ``add_device_seconds`` at the commit seam,
    ``point()`` for the roofline point. Every add also lands in the
    process-wide ``h2o3_achieved_*`` counters, so the cluster snapshot
    plane merges the totals like any other metric."""

    def __init__(self, phase: str, n_devices: int = 1,
                 note: Optional[str] = None):
        self.phase = phase
        self.n_devices = max(int(n_devices), 1)
        self.note = note
        self._mu = threading.Lock()
        self.flops = 0.0
        self.bytes = 0.0
        self.device_s = 0.0
        self.capture_s = 0.0
        self.executions = 0

    def note_capture_seconds(self, seconds: float) -> None:
        """Host time the window spent CAPTURING costs (a cold key's
        trace+lower runs inside the measured loop). NOT subtracted from
        device seconds — in the pipelined loops the lower overlaps
        async device work, so subtracting could OVERSTATE MFU (the
        dishonest direction). Surfaced as ``capture_seconds`` on the
        point instead: a cold window's MFU is a visible lower bound,
        and warm windows (the bench's measured trains) carry ~0 here."""
        if seconds and seconds > 0:
            with self._mu:
                self.capture_s += float(seconds)

    def add(self, cost: Optional[Cost], n: int = 1) -> None:
        if cost is None or n <= 0:
            return
        with self._mu:
            self.flops += cost.flops * n
            self.bytes += cost.bytes * n
            self.executions += n
        record(self.phase, cost, n=n)

    def add_device_seconds(self, seconds: float) -> None:
        if seconds is None or seconds <= 0:
            return
        with self._mu:
            self.device_s += float(seconds)
        record(self.phase, None, seconds=seconds)

    def point(self, update_gauges: bool = True) -> Optional[Dict]:
        with self._mu:
            flops, by, secs, ex, cap = (self.flops, self.bytes,
                                        self.device_s, self.executions,
                                        self.capture_s)
        pt = roofline_point(flops, by, secs, n_devices=self.n_devices,
                            note=self.note)
        if pt is None:
            return None
        pt["executions"] = ex
        if cap > 0:
            # cold-window caveat: this much of device_seconds was spent
            # tracing/lowering for the capture itself (overlapped with
            # async device work to an unknown degree) — the MFU is a
            # lower bound; warm windows report 0 here
            pt["capture_seconds"] = round(cap, 6)
        if update_gauges and registry().enabled:
            reg = registry()
            lab = {"phase": self.phase}
            reg.gauge("h2o3_mfu", lab,
                      help="model flops utilization by phase (latest "
                           "window)").set(pt["mfu"])
            if pt["arith_intensity"] is not None:
                reg.gauge("h2o3_arith_intensity", lab,
                          help="flops per HBM byte by phase (latest "
                               "window)").set(pt["arith_intensity"])
        return pt

    def finish(self) -> Optional[Dict]:
        return self.point(update_gauges=True)


def accumulator(phase: str, n_devices: int = 1,
                note: Optional[str] = None) -> Optional[PerfAccumulator]:
    """A phase accumulator, or None when telemetry is disabled — call
    sites guard with ``if acc is not None`` so the disabled path is one
    attribute load + branch."""
    if not registry().enabled:
        return None
    return PerfAccumulator(phase, n_devices=n_devices, note=note)


# ------------------------------------------------------------- summary

def summary() -> Dict[str, object]:
    """Process-wide accounting view (``GET /3/Telemetry/perf``): the
    detected peaks plus a roofline point per phase derived from the
    cumulative ``h2o3_achieved_*`` counters. Phases without measured
    device seconds report their raw totals with ``mfu: None`` instead
    of inventing a rate. Points here are computed against SINGLE-chip
    peaks (the counters don't carry mesh width); the per-train points
    in ``model.output["perf"]`` scale peaks by the mesh the train ran
    under."""
    peaks = device_peaks()
    out: Dict[str, object] = {"enabled": registry().enabled,
                              "peak": peaks, "phases": {}}
    if not registry().enabled:
        return out
    totals: Dict[str, Dict[str, float]] = {}
    for s in registry().samples():
        name = s.get("name")
        if name not in ("h2o3_achieved_flops_total",
                        "h2o3_achieved_bytes_total",
                        "h2o3_device_seconds_total"):
            continue
        phase = (s.get("labels") or {}).get("phase", "")
        t = totals.setdefault(phase, {"flops": 0.0, "bytes": 0.0,
                                      "seconds": 0.0})
        fld = {"h2o3_achieved_flops_total": "flops",
               "h2o3_achieved_bytes_total": "bytes",
               "h2o3_device_seconds_total": "seconds"}[name]
        t[fld] += float(s.get("value", 0.0) or 0.0)
    phases: Dict[str, Dict] = {}
    for phase, t in sorted(totals.items()):
        pt = roofline_point(t["flops"], t["bytes"], t["seconds"],
                            peaks=peaks)
        if pt is None:
            pt = {"flops_total": t["flops"], "bytes_total": t["bytes"],
                  "device_seconds": t["seconds"], "mfu": None,
                  "roofline_regime": None,
                  "informational": bool(peaks["informational"])}
        phases[phase] = pt
    out["phases"] = phases
    return out
