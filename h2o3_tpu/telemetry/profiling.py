"""Shared xprof/jax.profiler capture helper (the SNIPPETS [1] shape).

``tools/profile_train.py --xprof-trace`` grew an inline trace-dir dance
(arg parsing, default dirs, graceful degradation when the profiler is
unavailable); the other profilers needed the same thing, so the pattern
lives here once:

    from h2o3_tpu.telemetry.profiling import profile
    with profile("warm_train"):            # no-op unless a dir resolves
        gbm.train(...)

``profile(name, trace_dir=...)`` wraps the block in
``jax.profiler.trace`` writing to ``<dir>/<name>`` — open the dump with
xprof/tensorboard (``python -m xprof.server DIR`` or
``tensorboard --logdir DIR``) for kernel-level attribution (per-level
fused-histogram kernels, the ICI psum all-reduce on the device
timeline). Trace-dir resolution, in priority order:

1. the explicit ``trace_dir=`` argument;
2. ``--xprof-trace [DIR]`` on ``sys.argv`` (the shared tools/ CLI
   contract; bare ``--xprof-trace`` mints a /tmp dir);
3. the ``XPROF_TRACE_DIR`` env var;
4. nothing → the context manager is a no-op (zero overhead).

Capture failures degrade to a warning — profiling must never sink the
run being profiled. An in-flight capture's directory is readable via
``last_trace_dir()`` (the tools put it in their JSON output).
"""
from __future__ import annotations

import os
import sys
import time
from typing import Optional

_LAST_DIR: list = [None]


def trace_dir_from_argv(argv: Optional[list] = None,
                        flag: str = "--xprof-trace") -> Optional[str]:
    """The shared CLI spelling: ``--xprof-trace [DIR]`` (bare flag mints
    a /tmp dir), else ``XPROF_TRACE_DIR``, else None."""
    argv = sys.argv if argv is None else argv
    if flag in argv:
        i = argv.index(flag)
        if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
            return argv[i + 1]
        return os.path.join("/tmp", f"h2o3_xprof_{int(time.time())}")
    return os.environ.get("XPROF_TRACE_DIR") or None


def last_trace_dir() -> Optional[str]:
    """Directory of the most recent successful capture (None if the
    last ``profile()`` was a no-op or failed to start)."""
    return _LAST_DIR[0]


class profile:
    """``with profile("name"):`` — jax.profiler capture of the block
    into ``<trace_dir>/<name>``; a checked no-op when no dir resolves
    or the profiler refuses (double-start, missing backend support)."""

    def __init__(self, name: str, trace_dir: Optional[str] = None,
                 log=None):
        self.name = str(name)
        self.trace_dir = trace_dir if trace_dir is not None \
            else trace_dir_from_argv()
        self.dir: Optional[str] = None
        self._log = log or (lambda *a: print(*a, file=sys.stderr,
                                             flush=True))
        self._active = False

    def __enter__(self) -> "profile":
        _LAST_DIR[0] = None
        if not self.trace_dir:
            return self
        self.dir = os.path.join(self.trace_dir, self.name)
        try:
            import jax
            os.makedirs(self.dir, exist_ok=True)
            jax.profiler.start_trace(self.dir)
            self._active = True
            _LAST_DIR[0] = self.dir
            self._log(f"xprof: tracing '{self.name}' -> {self.dir}")
        except Exception as e:   # profiling must never sink the run
            self._log(f"xprof trace unavailable: {e!r}")
            self.dir = None
        return self

    def __exit__(self, *exc) -> bool:
        if self._active:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:
                self._log(f"xprof stop failed: {e!r}")
                _LAST_DIR[0] = None
                self.dir = None
            self._active = False
        return False
