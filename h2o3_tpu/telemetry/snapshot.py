"""Cross-process telemetry aggregation: snapshots, merge, cluster view.

PR 4's registry is deliberately process-local; PR 7 made multi-chip
SPMD the default train path and the serving-fleet plan runs N serve
replicas as separate processes — so the cluster debugging surface needs
ONE merged view. The reference gets this for free from its cloud
(every node's WaterMeter rides the heartbeat, water/H2O.java CLOUD
membership); single-controller JAX processes share nothing, so the
aggregation is pull-based REST:

- ``local_snapshot()`` serializes THIS process's registry (raw, not
  cumulative, histogram buckets — mergeable) + the finished-span ring
  as one JSON-able dict; served at ``GET /3/Telemetry/snapshot``.
- ``merge_snapshots([snap, ...])`` folds N process snapshots into one
  registry-shaped sample list: counters/histograms SUM (same name +
  labels; histogram buckets merge bucket-wise when the bounds agree),
  gauges get a ``process=<id>`` label (a queue depth does not add
  across processes — label, don't lie).
- ``cluster_samples()`` pulls every peer's snapshot (peer list from
  ``H2O3_TELEMETRY_PEERS="host:port,host:port"`` — the env the
  multihost worker / replica launcher exports) and merges it with the
  local registry; ``GET /3/Telemetry/cluster`` and
  ``GET /metrics?scope=cluster`` render it.

Single-process behavior is bit-unchanged: with no peers configured the
cluster path short-circuits to the local samples (no HTTP, no merge
pass), and plain ``GET /metrics`` never touches this module.
``H2O3_TELEMETRY=0`` keeps the whole thing a checked no-op (snapshots
report ``enabled: false`` with no samples).
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from h2o3_tpu.telemetry import spans
from h2o3_tpu.telemetry.registry import registry

SNAPSHOT_VERSION = 1

# subscribers fed every snapshot a cluster scrape merges (signature:
# (snapshot_dict, is_self_process)). The serve fleet-circuit store
# (serve/fleet.py) registers here so an open circuit on one replica
# propagates to every peer within one telemetry scrape — telemetry
# itself never imports serve. Consumer errors are swallowed: gossip
# must not break the metrics scrape it rides on.
PEER_SNAPSHOT_CONSUMERS: List[Callable[[dict, bool], None]] = []

# Pluggable peer SOURCE (ISSUE 13): when the fleet membership layer is
# active it registers a callable returning
# (live peer addresses, departed-member records) — the cluster scrape
# then follows the member table instead of the static env list, so a
# replica that leaves or is evicted stops contributing its
# ``process=``-labeled gauge series on the NEXT scrape (no TTL linger)
# and shows up flagged in the scrape meta (``peers_evicted``) instead.
# None = the H2O3_TELEMETRY_PEERS env fallback below.
PEER_SOURCE: Optional[Callable[[], Tuple[List[str], List[dict]]]] = None


def _notify_peer_consumers(snap: dict, self_process: bool) -> None:
    for cb in list(PEER_SNAPSHOT_CONSUMERS):
        try:
            cb(snap, self_process)
        except Exception:   # noqa: BLE001 — gossip is advisory
            pass

def _env_peer_timeout() -> float:
    """Peer poll budget (``H2O3_TELEMETRY_PEER_TIMEOUT`` seconds,
    default 2.0): a dead replica must not stall the live cluster scrape
    (Prometheus timeouts are seconds-scale). A malformed value falls
    back instead of breaking import — telemetry loads with the app."""
    try:
        t = float(os.environ.get("H2O3_TELEMETRY_PEER_TIMEOUT", "2.0"))
        return t if t > 0 else 2.0
    except ValueError:
        return 2.0


PEER_TIMEOUT_S = _env_peer_timeout()

# hard cap on one peer's snapshot body: real snapshots are tens of KB
# (a few hundred metric families); anything beyond this is a
# misconfigured peer entry pointing at a non-telemetry service
PEER_MAX_BYTES = 16 << 20

_MAX_SNAPSHOT_SPANS = 2048


def process_identity() -> Dict[str, object]:
    """Who this snapshot came from. jax.process_index() when the
    distributed runtime is up (the multihost worker case), else the
    OS pid — stable within a scrape either way."""
    ident: Dict[str, object] = {"pid": os.getpid()}
    try:
        import jax
        ident["process_index"] = int(jax.process_index())
        ident["process_count"] = int(jax.process_count())
    except Exception:
        pass
    import socket
    try:
        ident["host"] = socket.gethostname()
    except OSError:
        ident["host"] = "?"
    return ident


def _raw_buckets(sample: dict) -> Tuple[List[float], List[int]]:
    """Cumulative [(le, cum), ...] → (bounds, per-bucket raw counts)
    including the +Inf bucket — the mergeable wire shape."""
    bounds, raw, prev = [], [], 0
    for le, cum in sample["buckets"]:
        if le != float("inf"):
            bounds.append(float(le))
        raw.append(int(cum) - prev)
        prev = int(cum)
    return bounds, raw


def _cumulate(bounds: List[float], raw: List[int]) -> List[Tuple[float, int]]:
    out, acc = [], 0
    for b, c in zip(bounds, raw[:-1]):
        acc += c
        out.append((float(b), acc))
    out.append((float("inf"), acc + (raw[-1] if raw else 0)))
    return out


def local_snapshot(max_spans: int = _MAX_SNAPSHOT_SPANS) -> Dict[str, object]:
    """This process's registry + finished-span ring as one mergeable
    JSON-able snapshot (the ``GET /3/Telemetry/snapshot`` body)."""
    reg = registry()
    out: Dict[str, object] = {
        "version": SNAPSHOT_VERSION,
        "time": time.time(),
        "enabled": reg.enabled,
        "process": process_identity(),
        "samples": [],
        "spans": [],
    }
    # serve circuit gossip (ISSUE 9): rides the snapshot even when the
    # metrics registry is disabled — load shedding is a serving-health
    # property, not a metric. Only consulted when serve is already
    # imported (a process with no deployments publishes nothing).
    svc = sys.modules.get("h2o3_tpu.serve.service")
    if svc is not None:
        try:
            out["circuit"] = svc.circuit_states()
        except Exception:   # noqa: BLE001 — snapshot must render
            out["circuit"] = []
    if not reg.enabled:
        return out
    samples = []
    for s in reg.samples():
        e = {"name": s["name"], "kind": s["kind"],
             "labels": dict(s["labels"]), "help": s.get("help", "")}
        if s["kind"] == "histogram":
            bounds, raw = _raw_buckets(s)
            e.update(sum=float(s["sum"]), count=int(s["count"]),
                     bounds=bounds, bucket_counts=raw)
        else:
            e["value"] = float(s.get("value", 0.0))
        samples.append(e)
    out["samples"] = samples
    ser = []
    for sp in spans.finished_spans(max_spans):
        if sp.duration_s is None:
            continue
        ser.append({"name": sp.name, "span_id": sp.span_id,
                    "parent_id": sp.parent_id, "t_wall": sp.t_wall,
                    "duration_s": sp.duration_s,
                    "thread_id": sp.thread_id,
                    "trace_id": sp.trace_id,
                    "attrs": {k: v for k, v in sp.attrs.items()
                              if isinstance(v, (int, float, str, bool))}})
    out["spans"] = ser
    return out


def _proc_label(snap: dict) -> str:
    """Human-meaningful process label for merged gauges. The jax
    process_index only identifies anything inside a REAL multi-process
    runtime (process_count > 1); N standalone serve replicas all report
    index 0, so they label by pid@host instead."""
    p = snap.get("process") or {}
    if int(p.get("process_count", 1) or 1) > 1 and "process_index" in p:
        return str(p["process_index"])
    return f"{p.get('pid', '?')}@{p.get('host', '?')}"


def merge_snapshots(snaps: List[dict]) -> List[dict]:
    """Fold N process snapshots into one registry-shaped sample list
    (the shape ``export.prometheus_text(samples=...)`` renders).

    - counters: summed over processes per (name, labels);
    - histograms: bucket-wise summed when every process agrees on the
      bounds (they will — the bounds are compiled in), else kept as
      per-process series labeled ``process=``;
    - gauges: always labeled ``process=`` (instantaneous per-process
      state does not add — a summed queue depth would be a lie).
    """
    counters: Dict[Tuple, dict] = {}
    hists: Dict[Tuple, dict] = {}
    gauges: List[dict] = []
    # exposition requires every line of one metric NAME contiguous —
    # order families by first appearance, and group every series of a
    # family together even when a later peer contributes new label sets.
    # A name's kind is fixed by its FIRST appearance; a peer reporting
    # the same name under a different kind (version skew) falls back to
    # per-process series like the histogram bound mismatch below —
    # merging across kinds would emit duplicate/orphaned series
    fam_order: List[Tuple[str, str]] = []      # (kind-tag, name)
    fam_keys: Dict[str, List[Tuple]] = {}      # name -> series keys
    fam_kind: Dict[str, str] = {}              # name -> kind-tag
    skew: List[dict] = []                      # kind-skew fallback

    # process labels must be unique per SNAPSHOT: pid collisions across
    # hosts (or a process listed as its own peer) would otherwise emit
    # duplicate gauge series, which is invalid exposition output
    used_procs: Dict[str, int] = {}
    for i, snap in enumerate(snaps):
        proc = _proc_label(snap)
        if used_procs.setdefault(proc, i) != i:
            proc = f"{proc}@{i}"
            used_procs[proc] = i
        for s in snap.get("samples") or []:
            labels = dict(s.get("labels") or {})
            key = (s["name"], tuple(sorted(labels.items())))
            kind = s.get("kind", "gauge")
            if kind == "counter":
                if fam_kind.setdefault(s["name"], "c") != "c":
                    # a scalar has no legal spelling inside a histogram
                    # family (only _bucket/_sum/_count sample names are
                    # accepted under TYPE histogram) — drop it rather
                    # than invalidate the whole scrape
                    continue
                cur = counters.get(key)
                if cur is None:
                    counters[key] = {"name": s["name"], "kind": "counter",
                                     "labels": labels,
                                     "help": s.get("help", ""),
                                     "value": float(s.get("value", 0.0))}
                    if s["name"] not in fam_keys:
                        fam_order.append(("c", s["name"]))
                    fam_keys.setdefault(s["name"], []).append(key)
                else:
                    cur["value"] += float(s.get("value", 0.0))
            elif kind == "histogram":
                bounds = tuple(s.get("bounds") or ())
                raw = list(s.get("bucket_counts") or [])
                if fam_kind.setdefault(s["name"], "h") != "h":
                    # histogram into a scalar family: the suffixed
                    # _bucket/_sum/_count lines are distinct (untyped)
                    # sample names, so a process-labeled fallback
                    # series renders validly
                    skew.append({"name": s["name"], "kind": "histogram",
                                 "labels": {**labels, "process": proc},
                                 "help": s.get("help", ""),
                                 "bounds": bounds, "raw": raw,
                                 "sum": float(s.get("sum", 0.0)),
                                 "count": int(s.get("count", 0))})
                    continue
                # merge is deferred to OUTPUT time: contributions per
                # series key are collected per process, so a bound
                # mismatch (version skew) can degrade EVERY process of
                # that key to labeled series — eagerly merging would
                # leave the first-seen processes' sum unlabeled,
                # masquerading as the cluster aggregate
                cur = hists.get(key)
                entry = {"name": s["name"], "proc": proc,
                         "labels": labels, "help": s.get("help", ""),
                         "bounds": bounds, "raw": raw,
                         "sum": float(s.get("sum", 0.0)),
                         "count": int(s.get("count", 0))}
                if cur is None:
                    hists[key] = [entry]
                    if s["name"] not in fam_keys:
                        fam_order.append(("h", s["name"]))
                    fam_keys.setdefault(s["name"], []).append(key)
                else:
                    cur.append(entry)
            else:   # gauge / untyped: per-process, labeled
                gauges.append({"name": s["name"], "kind": kind,
                               "labels": {**labels, "process": proc},
                               "help": s.get("help", ""),
                               "value": float(s.get("value", 0.0))})

    out: List[dict] = []
    for tag, name in fam_order:
        for key in sorted(fam_keys[name]):
            if tag == "c":
                out.append(counters[key])
            else:
                contribs = hists[key]
                h0 = contribs[0]
                if all(c["bounds"] == h0["bounds"]
                       and len(c["raw"]) == len(h0["raw"])
                       for c in contribs):
                    out.append({"name": h0["name"], "kind": "histogram",
                                "labels": h0["labels"],
                                "help": h0["help"],
                                "sum": sum(c["sum"] for c in contribs),
                                "count": sum(c["count"]
                                             for c in contribs),
                                "buckets": _cumulate(
                                    list(h0["bounds"]),
                                    [sum(col) for col in zip(
                                        *(c["raw"] for c in contribs))])})
                else:
                    # bound mismatch (version skew): EVERY contribution
                    # becomes a per-process series — none may pose as
                    # the cluster aggregate
                    for c in contribs:
                        out.append({"name": c["name"],
                                    "kind": "histogram",
                                    "labels": {**c["labels"],
                                               "process": c["proc"]},
                                    "help": c["help"],
                                    "sum": c["sum"], "count": c["count"],
                                    "buckets": _cumulate(list(c["bounds"]),
                                                         c["raw"])})
    for e in sorted(skew, key=lambda s: (s["name"],
                                         sorted(s["labels"].items()))):
        if e["kind"] == "histogram":
            out.append({"name": e["name"], "kind": "histogram",
                        "labels": e["labels"], "help": e["help"],
                        "sum": e["sum"], "count": e["count"],
                        "buckets": _cumulate(list(e["bounds"]), e["raw"])})
        else:
            out.append(e)
    # scalar-in-histogram-family gauges are dropped at OUTPUT time (the
    # family may register only after the gauge was scanned): a bare
    # ``name{...} v`` line under ``# TYPE name histogram`` would fail
    # the whole scrape in strict parsers
    out.extend(sorted((g for g in gauges
                       if fam_kind.get(g["name"]) != "h"),
                      key=lambda s: (s["name"],
                                     sorted(s["labels"].items()))))
    # exposition requires every series of one NAME contiguous. Skewed
    # and gauge series whose name also has a counter/histogram family
    # were appended at the end above — regroup by name (first-appearance
    # order, stable within a name) so kind skew degrades one metric
    # instead of invalidating the whole scrape
    grouped: Dict[str, List[dict]] = {}
    order: List[str] = []
    for e in out:
        if e["name"] not in grouped:
            order.append(e["name"])
        grouped.setdefault(e["name"], []).append(e)
    return [e for n in order for e in grouped[n]]


# ------------------------------------------------------------- peers

def peer_view() -> Tuple[List[str], List[dict]]:
    """(live peer addresses, departed-member records). With a
    registered ``PEER_SOURCE`` (fleet membership) the addresses track
    the member table — members that left/were evicted drop immediately
    and are returned as flagged departures for the scrape meta. Without
    one, the static ``H2O3_TELEMETRY_PEERS`` env fallback (this is the
    blessed read — the fleet-peer-discipline lint rule keeps it the
    only one): comma-separated host:port entries a replica launcher or
    the multihost worker exports. The list should EXCLUDE the local
    process — a shared everyone-gets-the-same-list spelling still works
    but double-counts local counters in this process's cluster view
    (flagged in ``peers_self``). Empty by default — the single-process
    aggregation path must cost nothing."""
    src = PEER_SOURCE
    if src is not None:
        try:
            addrs, departed = src()
            return list(addrs), list(departed)
        except Exception:   # noqa: BLE001 — a broken source must not
            pass            # take the scrape down with it
    raw = os.environ.get("H2O3_TELEMETRY_PEERS", "")
    return [p.strip() for p in raw.split(",") if p.strip()], []


def peers() -> List[str]:
    """Peer processes to pull snapshots from (see :func:`peer_view`)."""
    return peer_view()[0]


def fetch_peer_snapshot(peer: str,
                        timeout: float = PEER_TIMEOUT_S,
                        max_spans: int = 0) -> dict:
    """One peer's ``GET /3/Telemetry/snapshot`` body (raises on any
    network/parse failure — the caller decides how dead peers show).
    Defaults to the SPANLESS spelling (``?n=0``): the metric merge never
    reads spans, so a scrape must not pay the peer's span-ring
    serialization + transfer.

    The socket timeout is PER OPERATION, so the body is read in
    single-recv slices under a wall-clock deadline (2x the per-op
    budget) — a sick peer dribbling bytes forever gets dropped instead
    of pinning this fetch (and its scrape thread) indefinitely. The
    body is also SIZE-capped: a misconfigured peer entry pointing at
    something fat and fast (a log stream, a file server) must not let
    one scrape buffer gigabytes inside the observing process."""
    import urllib.request   # deferred: only the cluster scrape pays it
    url = peer if peer.startswith(("http://", "https://")) \
        else f"http://{peer}"
    deadline = time.monotonic() + 2.0 * timeout
    with urllib.request.urlopen(
            f"{url}/3/Telemetry/snapshot?n={int(max_spans)}",
            timeout=timeout) as r:
        chunks = []
        total = 0
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"peer {peer} snapshot read exceeded {2.0 * timeout}s")
            b = r.read1(1 << 16)
            if not b:
                break
            chunks.append(b)
            total += len(b)
            if total > PEER_MAX_BYTES:
                raise ValueError(
                    f"peer {peer} snapshot body exceeded "
                    f"{PEER_MAX_BYTES} bytes — not a telemetry peer?")
    return json.loads(b"".join(chunks).decode())


def cluster_samples(extra_snapshots: Optional[List[dict]] = None
                    ) -> Tuple[List[dict], Dict[str, object]]:
    """(merged samples, meta) over the local process + every reachable
    peer. ``extra_snapshots`` lets tests/embedded callers merge
    snapshots they already hold without a loopback server. With no
    peers and no extras this is exactly the local ``samples()`` pass —
    no merge, no HTTP (the single-process fast path).

    Peers are fetched CONCURRENTLY (scrape latency is bounded by the
    slowest single peer, not the fleet size), and the merged output
    carries scrape-health gauges (``h2o3_telemetry_processes`` /
    ``h2o3_telemetry_peers_failed``) so a Prometheus consumer can tell
    a partial scrape — where summed counters legitimately DIP — from a
    counter reset."""
    plist, departed = peer_view()
    meta: Dict[str, object] = {"processes": 1, "peers": len(plist),
                               "peers_ok": [], "peers_failed": [],
                               "peers_self": [],
                               # members that left/were evicted: their
                               # series stopped merging at that epoch —
                               # flagged so a dashboard can tell an
                               # expired replica from a vanished one
                               "peers_evicted": departed}
    if not plist and not extra_snapshots:
        return registry().samples(), meta
    snaps = [local_snapshot(max_spans=0)]
    if plist:
        import concurrent.futures as cf
        ex = cf.ThreadPoolExecutor(max_workers=min(len(plist), 16))
        try:
            # dedup preserves order: a duplicated peer entry (launcher
            # config bug) must not merge the same snapshot twice. The
            # timeout is passed EXPLICITLY so a runtime PEER_TIMEOUT_S
            # change reaches the socket ops and the fetch's own
            # deadline, not just the aggregate one below
            futs = {p: ex.submit(fetch_peer_snapshot, p, PEER_TIMEOUT_S)
                    for p in dict.fromkeys(plist)}
            # the urlopen timeout is PER SOCKET OPERATION — a sick peer
            # dribbling its body a few bytes at a time never trips it.
            # An aggregate wall-clock deadline (2x the per-op budget:
            # connect + slow body both get headroom) keeps the whole
            # scrape bounded, per the module contract. The pool runs at
            # most 16 fetches at once, so past 16 peers the budget
            # scales by the number of waves — healthy peers queued
            # behind a full first wave must not be starved into
            # peers_failed by a deadline they never got a slice of
            n_waves = -(-len(futs) // 16)
            deadline = time.monotonic() + 2.0 * PEER_TIMEOUT_S * n_waves
            for p in futs:
                try:
                    snap = futs[p].result(
                        timeout=max(0.0, deadline - time.monotonic()))
                    # a peer that is THIS process (a launcher exporting
                    # one shared peer list to every replica) still
                    # merges — the test/debug self-peer spelling relies
                    # on it — but is flagged so the double-counted
                    # counters are diagnosable from the scrape meta
                    is_self = snap.get("process") == snaps[0].get("process")
                    if is_self:
                        meta["peers_self"].append(p)
                    snaps.append(snap)
                    meta["peers_ok"].append(p)
                    # feed gossip consumers (fleet circuit state): the
                    # scrape that merges the metrics IS the propagation
                    # vehicle — one scrape, fleet-wide visibility
                    _notify_peer_consumers(snap, is_self)
                except Exception as e:   # dead replica: report, never sink
                    meta["peers_failed"].append({"peer": p,
                                                 "error": repr(e)})
        finally:
            # past-deadline fetch threads self-terminate (the read loop
            # in fetch_peer_snapshot carries its own deadline) — the
            # scrape does not wait for them
            ex.shutdown(wait=False, cancel_futures=True)
    for s in extra_snapshots or []:
        # test/embedded-injected snapshots gossip the same way the
        # HTTP-fetched ones do
        _notify_peer_consumers(s, s.get("process") == snaps[0]
                               .get("process"))
    snaps.extend(extra_snapshots or [])
    meta["processes"] = len(snaps)
    merged = merge_snapshots(snaps)
    merged.append({"name": "h2o3_telemetry_processes", "kind": "gauge",
                   "labels": {}, "value": float(len(snaps)),
                   "help": "processes merged into this cluster scrape"})
    merged.append({"name": "h2o3_telemetry_peers_failed", "kind": "gauge",
                   "labels": {}, "value": float(len(meta["peers_failed"])),
                   "help": "configured peers that failed this scrape "
                           "(nonzero = partial scrape; summed counters "
                           "may dip without a real reset)"})
    return merged, meta


def cluster_snapshot() -> Dict[str, object]:
    """The ``GET /3/Telemetry/cluster`` JSON body: merged flat metric
    map + per-process identities + pull health."""
    from h2o3_tpu.telemetry.export import _flatten
    samples, meta = cluster_samples()
    return {
        "enabled": registry().enabled,
        "processes": meta["processes"],
        "peers": meta["peers"],
        "peers_ok": meta["peers_ok"],
        "peers_failed": meta["peers_failed"],
        "peers_self": meta["peers_self"],
        "peers_evicted": meta.get("peers_evicted", []),
        "metrics": _flatten(samples),
    }
