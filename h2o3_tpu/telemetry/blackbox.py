"""Cluster flight recorder: crash-durable control-plane event journal.

The reference's observability spine is ``water/TimeLine.java`` — a
fixed-size per-node ring of fixed-width event records that *survives
the node* and is snapshotted cluster-wide into one merged timeline
(``water/init/TimelineSnapshot.java``). This is that layer for the
rebuild: every process appends typed 256-byte records into an
mmap-backed ring file under the shared recovery/fleet root, so a
SIGKILLed replica's last events (placement decisions, checkpoint
commits, eviction, fault firings) remain readable post-mortem by any
survivor — the kernel flushes the dirty MAP_SHARED pages whether or
not the writer got to say goodbye. (A machine-level crash losing the
page cache is out of scope, same as the recovery manifests.)

Ring layout (little-endian, one file per member, ``<member>.bbx``):

- 4096-byte header page: magic ``H2O3BBX1``, record size, capacity,
  total-events-written cursor (``seq``), writer member id. The cursor
  is bumped AFTER the record bytes land, so a torn write at death
  costs at most the one record being appended.
- ``capacity`` x 256-byte records: mono ns, wall ns, seq, membership
  epoch, incarnation, kind code, flags, trace id (32B), member/subject
  (44B), payload (144B).

Appends are single-writer striped — one ring per process, one lock,
no cross-process coordination — and follow the PR-4 span-path budget
discipline: ``record()`` is a checked no-op behind the registry
enabled flag when ``H2O3_TELEMETRY=0`` (ns-budget guarded in
tests/test_blackbox.py) and stays under the 2 µs/event enabled-path
budget (one struct.pack + one memoryview splice under a lock).

Knobs: ``H2O3_BLACKBOX_DIR`` pins the ring directory (default:
``<recovery_dir>/blackbox`` — no recovery root and no explicit dir
means no ring, and ``record()`` degrades to a cached no-op);
``H2O3_BLACKBOX_EVENTS`` sizes the ring (default 4096, min 64).

``cluster_timeline()`` merges the local ring, live peers' rings over
the telemetry peer plane (``GET /3/Blackbox``), and dead members'
ring files from the shared root into one epoch-fenced causal order:
sort key (epoch, skew-corrected wall ns, member, seq). Per-member
wall-clock skew is estimated from the heartbeat exchange (the agent
stamps its wall clock on every beat; the router records the offset)
and members beyond ``SKEW_FLAG_S`` are flagged rather than silently
re-ordered. ``tools/blackbox_read.py`` decodes any ring file offline.
"""
from __future__ import annotations

import json
import mmap
import os
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from h2o3_tpu.telemetry.registry import on_reset, registry

__all__ = [
    "KIND_CODES", "KIND_NAMES", "Ring", "blackbox_dir", "cluster_timeline",
    "cluster_trace_bytes", "events_recorded", "local_events", "read_ring",
    "record", "reset", "ring_path", "set_identity",
]

MAGIC = b"H2O3BBX1"
HEADER = struct.Struct("<8sIIQ44s")       # magic, rec_size, cap, seq, member
HEADER_SIZE = 4096                        # one page; records start aligned
RECORD = struct.Struct("<QQQIIHH32s44s144s")
RECORD_SIZE = RECORD.size                 # 256
DEFAULT_EVENTS = 4096
SKEW_FLAG_S = 0.25        # |heartbeat-estimated skew| beyond this is flagged
PEER_CAP_BYTES = 4 << 20  # per-peer /3/Blackbox response size cap

# Event kinds: stable small codes on disk, names everywhere else. New
# kinds append — never renumber, post-mortem readers may be older.
KIND_CODES: Dict[str, int] = {
    "member_join": 1, "member_suspect": 2, "member_evict": 3,
    "member_leave": 4, "incarnation_fence": 5, "member_flip": 6,
    "placement": 10, "remote_submit_sent": 11, "remote_submit_accepted": 12,
    "migrate_start": 13, "migrate_done": 14, "rebalance": 15,
    "evict_requeue": 16, "lease_claim": 17, "lease_steal": 18,
    "sched_enqueue": 20, "sched_admit": 21, "sched_preempt": 22,
    "sched_requeue": 23, "sched_reject": 24,
    "circuit_open": 30, "circuit_close": 31, "circuit_half_open": 32,
    "circuit_gossip": 33,
    "ckpt_commit": 40, "manifest_written": 41, "manifest_claimed": 42,
    "manifest_abandoned": 43, "manifest_done": 44,
    "fault_fired": 50,
    "job_state": 60,
    # router plane (ISSUE 20): tier membership, ring publication and
    # deadline-class lane shedding at the replicated front door
    "router_join": 70, "router_handoff": 71, "ring_published": 72,
    "lane_shed": 73,
}
KIND_NAMES: Dict[int, str] = {v: k for k, v in KIND_CODES.items()}

_MU = threading.Lock()
_RING: Any = None          # None = unresolved, False = off, Ring = live
_IDENT = {"epoch": 0, "incarnation": 0}


def _sanitize(member_id: str) -> str:
    return "".join(c if (c.isalnum() or c in "@._-") else "_"
                   for c in member_id)[:44]


def _default_member_id() -> str:
    try:
        from h2o3_tpu.fleet import sched as fleet_sched
        return fleet_sched.local_member_id()
    except Exception:  # noqa: BLE001 — recorder must not need the fleet
        return f"{os.getpid()}@{socket.gethostname()}"


def blackbox_dir() -> Optional[str]:
    """Ring directory: ``H2O3_BLACKBOX_DIR``, else a ``blackbox/``
    subdirectory of the shared recovery root (so chaos rounds that
    share a recovery dir share the flight-recorder root for free),
    else None — disabled."""
    d = os.environ.get("H2O3_BLACKBOX_DIR")
    if d:
        return d
    try:
        from h2o3_tpu import recovery
        root = recovery.recovery_dir()
    except Exception:  # noqa: BLE001 — advisory
        root = None
    return os.path.join(root, "blackbox") if root else None


def _capacity() -> int:
    try:
        return max(int(os.environ.get("H2O3_BLACKBOX_EVENTS",
                                      str(DEFAULT_EVENTS))), 64)
    except ValueError:
        return DEFAULT_EVENTS


class Ring:
    """One member's mmap-backed event ring (the single writer)."""

    def __init__(self, path: str, capacity: int, member_id: str):
        self.path = path
        self.capacity = capacity
        self.member_id = member_id
        self._mu = threading.Lock()
        total = HEADER_SIZE + capacity * RECORD_SIZE
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            adopt_seq = 0
            st = os.fstat(fd)
            if st.st_size >= HEADER_SIZE:
                head = os.pread(fd, HEADER.size, 0)
                if len(head) == HEADER.size:
                    magic, rs, cap, seq, _ = HEADER.unpack(head)
                    if (magic == MAGIC and rs == RECORD_SIZE
                            and cap == capacity
                            and st.st_size == total):
                        adopt_seq = seq   # restart: keep writing after
            if st.st_size != total:
                os.ftruncate(fd, total)
            self._mm = mmap.mmap(fd, total)  # MAP_SHARED by default
        finally:
            os.close(fd)
        self.seq = adopt_seq
        if adopt_seq == 0:
            self._mm[:HEADER.size] = HEADER.pack(
                MAGIC, RECORD_SIZE, capacity, 0,
                member_id.encode()[:44].ljust(44, b"\0"))

    def append(self, kind: int, wall_ns: int, mono_ns: int, epoch: int,
               incarnation: int, trace: bytes, member: bytes,
               payload: bytes) -> None:
        with self._mu:
            seq = self.seq
            off = HEADER_SIZE + (seq % self.capacity) * RECORD_SIZE
            self._mm[off:off + RECORD_SIZE] = RECORD.pack(
                mono_ns, wall_ns, seq, epoch, incarnation, kind, 0,
                trace, member, payload)
            self.seq = seq + 1
            # cursor AFTER the record: a SIGKILL between the two writes
            # loses only the record being appended, never a stale view
            self._mm[16:24] = struct.pack("<Q", self.seq)

    def tail(self, n: int) -> List[Dict[str, Any]]:
        """Last ``n`` events, oldest first, decoded from the live map."""
        with self._mu:
            seq = self.seq
            valid = min(seq, self.capacity)
            lo = seq - min(valid, n)
            out = []
            for i in range(lo, seq):
                off = HEADER_SIZE + (i % self.capacity) * RECORD_SIZE
                ev = _decode(self._mm[off:off + RECORD_SIZE])
                if ev is not None:
                    out.append(ev)
            return out

    def close(self) -> None:
        with self._mu:
            try:
                self._mm.close()
            except (BufferError, ValueError):
                pass


def _decode(raw: bytes) -> Optional[Dict[str, Any]]:
    (mono_ns, wall_ns, seq, epoch, incarnation, kind, _flags, trace,
     member, payload) = RECORD.unpack(raw)
    if mono_ns == 0 and wall_ns == 0 and kind == 0:
        return None                       # empty / torn slot
    return {
        "seq": seq, "t_mono_ns": mono_ns, "t_wall": wall_ns / 1e9,
        "epoch": epoch, "incarnation": incarnation,
        "kind": KIND_NAMES.get(kind, f"kind_{kind}"),
        "trace_id": trace.rstrip(b"\0").decode("utf-8", "replace"),
        "member": member.rstrip(b"\0").decode("utf-8", "replace"),
        "payload": payload.rstrip(b"\0").decode("utf-8", "replace"),
    }


def read_ring(path: str, last: Optional[int] = None) -> Dict[str, Any]:
    """Decode a ring file (live or post-mortem): header + events in
    seq order, oldest first. Raises ValueError on a non-ring file."""
    with open(path, "rb") as f:
        head = f.read(HEADER.size)
        if len(head) < HEADER.size:
            raise ValueError(f"{path}: truncated blackbox header")
        magic, rec_size, cap, seq, member = HEADER.unpack(head)
        if magic != MAGIC:
            raise ValueError(f"{path}: not a blackbox ring (bad magic)")
        if rec_size != RECORD_SIZE:
            raise ValueError(f"{path}: record size {rec_size} != "
                             f"{RECORD_SIZE} (format drift)")
        f.seek(HEADER_SIZE)
        body = f.read(cap * rec_size)
    valid = min(seq, cap)
    lo = seq - valid
    if last is not None:
        lo = max(lo, seq - last)
    events = []
    for i in range(lo, seq):
        off = (i % cap) * rec_size
        ev = _decode(body[off:off + rec_size])
        if ev is not None:
            events.append(ev)
    return {"path": path, "capacity": cap, "seq": seq,
            "member_id": member.rstrip(b"\0").decode("utf-8", "replace"),
            "events": events}


# ---------------------------------------------------------------- writer API

def _open_ring() -> Any:
    """Resolve the process ring once; cache False when disabled so the
    hot path stays one global read + one attribute check."""
    global _RING
    with _MU:
        if _RING is not None:
            return _RING
        d = blackbox_dir()
        if not d:
            _RING = False
            return False
        try:
            os.makedirs(d, exist_ok=True)
            member = _default_member_id()
            path = os.path.join(d, f"{_sanitize(member)}.bbx")
            _RING = Ring(path, _capacity(), member)
        except Exception:  # noqa: BLE001 — recorder must never sink its host
            _RING = False
        return _RING


def set_identity(epoch: Optional[int] = None,
                 incarnation: Optional[int] = None) -> None:
    """Stamp the membership epoch / incarnation that subsequent records
    carry (the fleet agent calls this on join and on every view)."""
    if epoch is not None:
        _IDENT["epoch"] = int(epoch)
    if incarnation is not None:
        _IDENT["incarnation"] = int(incarnation)


def record(kind: str, member: str = "", payload: str = "",
           trace_id: Optional[str] = None, epoch: Optional[int] = None,
           incarnation: Optional[int] = None) -> None:
    """Append one event. Checked no-op when telemetry is disabled
    (before any lock/alloc — ns-budget guarded) and when no ring
    directory is configured (cached False). ``member`` is the event's
    subject (e.g. the evicted member), not the writer; ``trace_id``
    defaults from the ambient trace binding."""
    if not registry().enabled:
        return
    ring = _RING
    if ring is None:
        ring = _open_ring()
    if ring is False:
        return
    try:
        if trace_id is None:
            from h2o3_tpu.telemetry import trace as _trace
            trace_id = _trace.current_trace_id() or ""
        ring.append(
            KIND_CODES.get(kind, 0) or 0,
            time.time_ns(), time.monotonic_ns(),
            _IDENT["epoch"] if epoch is None else int(epoch),
            _IDENT["incarnation"] if incarnation is None else int(incarnation),
            trace_id.encode()[:32].ljust(32, b"\0"),
            member.encode()[:44].ljust(44, b"\0"),
            payload.encode()[:144].ljust(144, b"\0"))
    except Exception:  # noqa: BLE001 — flight recorder is advisory
        pass


def local_events(n: int = 256) -> List[Dict[str, Any]]:
    ring = _RING if _RING is not None else _open_ring()
    if ring is False or ring is None:
        return []
    return ring.tail(n)


def events_recorded() -> int:
    ring = _RING
    return ring.seq if isinstance(ring, Ring) else 0


def ring_path() -> Optional[str]:
    ring = _RING if _RING is not None else _open_ring()
    return ring.path if isinstance(ring, Ring) else None


def reset() -> None:
    """Close the process ring and forget the cached resolution (tests
    flip H2O3_BLACKBOX_DIR / recovery dirs at runtime)."""
    global _RING
    with _MU:
        ring, _RING = _RING, None
        _IDENT["epoch"] = 0
        _IDENT["incarnation"] = 0
    if isinstance(ring, Ring):
        ring.close()


on_reset(reset)


# ------------------------------------------------------------ cluster merge

def _member_skews() -> Dict[str, float]:
    """Heartbeat-estimated wall-clock skew per member (router table),
    seconds; positive = member's clock runs ahead of ours."""
    try:
        from h2o3_tpu import fleet
        r = fleet.active_router()
        if r is None:
            return {}
        return {m.member_id: m.skew_s for m in r.table.members()
                if getattr(m, "skew_s", None) is not None}
    except Exception:  # noqa: BLE001 — advisory
        return {}


def _fetch_peer_ring(base_url: str, n: int,
                     timeout_s: float) -> Dict[str, Any]:
    """GET a live peer's decoded ring tail with the peer-plane
    discipline: bounded timeout, bounded body."""
    from urllib.request import urlopen
    base = base_url if base_url.startswith(("http://", "https://")) \
        else f"http://{base_url}"
    url = f"{base.rstrip('/')}/3/Blackbox?n={int(n)}"
    with urlopen(url, timeout=timeout_s) as resp:  # noqa: S310 — fleet-internal
        body = resp.read(PEER_CAP_BYTES + 1)
    if len(body) > PEER_CAP_BYTES:
        raise ValueError(f"{url}: blackbox response over "
                         f"{PEER_CAP_BYTES} byte cap")
    return json.loads(body.decode())


def cluster_timeline(n: int = 256, include_peers: bool = True,
                     timeout_s: Optional[float] = None) -> Dict[str, Any]:
    """The fleet-wide causal timeline: local ring + live peers' rings
    (telemetry peer plane) + dead members' ring files from the shared
    root, merged in epoch-fenced order — sort key (epoch,
    skew-corrected wall ns, member, seq). Dead members are marked;
    members whose heartbeat-estimated skew exceeds ``SKEW_FLAG_S``
    are flagged instead of silently trusted."""
    from h2o3_tpu.telemetry import snapshot as telesnap
    if timeout_s is None:
        timeout_s = telesnap.PEER_TIMEOUT_S
    self_member = _default_member_id()
    skews = _member_skews()
    members: Dict[str, Dict[str, Any]] = {}
    events: List[Dict[str, Any]] = []
    peers_failed: List[str] = []

    def _add(member_id: str, evs: List[Dict[str, Any]],
             dead: bool) -> None:
        skew = skews.get(member_id, 0.0)
        members[member_id] = {
            "dead": dead, "skew_s": round(skew, 6),
            "skew_flagged": abs(skew) > SKEW_FLAG_S, "events": len(evs)}
        for ev in evs:
            events.append({**ev, "member_ring": member_id, "dead": dead,
                           "t_corrected": ev["t_wall"] - skew})

    _add(self_member, local_events(n), False)
    live_ids = {self_member}
    if include_peers:
        try:
            peers, _departed = telesnap.peer_view()
        except Exception:  # noqa: BLE001 — advisory
            peers = []
        for url in peers:
            try:
                got = _fetch_peer_ring(url, n, timeout_s)
            except Exception:  # noqa: BLE001 — a dead peer is expected here
                peers_failed.append(url)
                continue
            mid = str(got.get("member_id") or url)
            live_ids.add(mid)
            # a self-peer spelling (shared everyone-gets-the-same-list
            # launcher config) resolves to our own member id — the
            # local ring already covered it
            if mid not in members:
                _add(mid, list(got.get("events") or []), False)
    # dead members: every ring file in the shared root whose writer is
    # not in the live set still tells its side of the story
    d = blackbox_dir()
    if d and os.path.isdir(d):
        for name in sorted(os.listdir(d)):
            if not name.endswith(".bbx"):
                continue
            try:
                rg = read_ring(os.path.join(d, name), last=n)
            except (OSError, ValueError):
                continue
            mid = rg["member_id"] or name[:-4]
            if mid in live_ids or mid in members:
                continue
            _add(mid, rg["events"], True)
    events.sort(key=lambda e: (e["epoch"], e["t_corrected"],
                               e["member_ring"], e["seq"]))
    return {"scope": "cluster", "self": self_member, "members": members,
            "events": events, "peers_failed": peers_failed,
            "skew_flag_s": SKEW_FLAG_S}


def cluster_trace_bytes(n: int = 256) -> bytes:
    """Chrome-trace (chrome://tracing / Perfetto) export of the merged
    cluster timeline: instant events, one pid per member ring, dead
    members' process names marked."""
    tl = cluster_timeline(n)
    out = []
    pids = {mid: i + 1 for i, mid in enumerate(sorted(tl["members"]))}
    for mid, info in tl["members"].items():
        label = mid + (" (dead)" if info["dead"] else "")
        out.append({"name": "process_name", "ph": "M", "pid": pids[mid],
                    "tid": 0, "args": {"name": label}})
    for ev in tl["events"]:
        out.append({
            "name": ev["kind"], "ph": "i", "s": "g",
            "pid": pids[ev["member_ring"]], "tid": 0,
            "ts": ev["t_corrected"] * 1e6,
            "args": {"member": ev["member"], "payload": ev["payload"],
                     "trace_id": ev["trace_id"], "epoch": ev["epoch"],
                     "seq": ev["seq"], "dead": ev["dead"]}})
    return json.dumps({"traceEvents": out,
                       "displayTimeUnit": "ms"}).encode()


def follow_trace(trace_id: str, rings: List[Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
    """One trace id's events across decoded rings (``read_ring``
    outputs), merged in (epoch, wall, seq) order — the offline spine
    of ``tools/blackbox_read.py --trace``."""
    hits: List[Tuple[Tuple, Dict[str, Any]]] = []
    for rg in rings:
        mid = rg.get("member_id", "?")
        for ev in rg.get("events", ()):
            if ev.get("trace_id") == trace_id:
                hits.append(((ev["epoch"], ev["t_wall"], mid, ev["seq"]),
                             {**ev, "member_ring": mid}))
    return [ev for _, ev in sorted(hits, key=lambda kv: kv[0])]
