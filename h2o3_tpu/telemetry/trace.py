"""Trace-id propagation: one id links a request across the pipeline.

A trace id is a 32-hex-char string (the W3C ``traceparent`` trace-id
field). The REST layer accepts an incoming ``traceparent`` header (or
mints a fresh id), binds it to the handler thread, and echoes it back in
the response — so a slow response's id can be chased through the serve
batcher's coalesced batch span, the /3/Serve/stats slow-request
exemplars, and the /3/Timeline span ring, all of which carry the same
id. Background jobs capture the id of the thread that created them and
re-bind it on the worker thread (jobs.py), so a train job's spans link
back to the POST that started it.

Binding is THREAD-LOCAL (like the span stack): ``bind(tid)`` installs,
``unbind()`` removes, ``current_trace_id()`` reads. Spans snapshot the
current id at creation (falling back to their parent's), which is how
the id crosses the batcher's explicit parent handoff without any extra
plumbing — a child recorded on the collector thread against a parent
that carries an id inherits it.

Everything here is plain thread-local string bookkeeping — it stays live
under ``H2O3_TELEMETRY=0`` (ids cost nanoseconds and the REST echo
contract should not silently change with the metrics knob); only the
span/metric RECORDING of ids is gated, along with the rest of telemetry.
"""
from __future__ import annotations

import os
import re
import threading
from typing import Optional

_TLS = threading.local()

# W3C trace-context: version 00 is exactly four fields; HIGHER versions
# must still parse by their first four fields (future versions may
# append more, "-"-separated), and version ff is explicitly invalid
_TRACEPARENT_RX = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace_id>[0-9a-f]{32})"
    r"-(?P<parent_id>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})"
    r"(?P<rest>$|-.*)")

TRACEPARENT_HEADER = "traceparent"


def new_trace_id() -> str:
    """A fresh 128-bit trace id (32 lowercase hex chars)."""
    return os.urandom(16).hex()


def parse_traceparent(header: Optional[str]) -> Optional[str]:
    """Trace id from a W3C ``traceparent`` header value; None when the
    header is absent/malformed or carries the all-zero invalid id.
    Future-version headers (version > 00) parse by their first four
    fields; version ``ff`` is invalid per the spec."""
    if not header:
        return None
    m = _TRACEPARENT_RX.match(header.strip().lower())
    if m is None:
        return None
    if m.group("version") == "ff":
        return None
    if m.group("version") == "00" and m.group("rest"):
        return None          # version 00 is EXACTLY four fields
    if m.group("parent_id") == "0" * 16:
        return None          # all-zero parent-id invalidates the header
    tid = m.group("trace_id")
    return None if tid == "0" * 32 else tid


def format_traceparent(trace_id: str, span_id: int = 0) -> str:
    """A ``traceparent`` response/egress header for this trace; the
    16-hex parent-id field carries the span id (0 → a fresh random-ish
    nonzero filler, the field must not be all zeros)."""
    pid = span_id & ((1 << 64) - 1)
    if pid == 0:
        pid = int.from_bytes(os.urandom(8), "big") or 1
    return f"00-{trace_id}-{pid:016x}-01"


def bind(trace_id: Optional[str]) -> Optional[str]:
    """Bind a trace id to THIS thread (None unbinds). Returns the id."""
    if trace_id is None:
        _TLS.trace_id = None
        return None
    _TLS.trace_id = str(trace_id)
    return _TLS.trace_id


def unbind() -> None:
    _TLS.trace_id = None


def current_trace_id() -> Optional[str]:
    """The trace id bound to this thread, or None."""
    return getattr(_TLS, "trace_id", None)


class trace_context:
    """``with trace_context(tid):`` — bind for a block, restoring the
    previous binding on exit (handler threads are pooled/reused)."""

    __slots__ = ("_tid", "_prev")

    def __init__(self, trace_id: Optional[str]):
        self._tid = trace_id
        self._prev: Optional[str] = None

    def __enter__(self) -> Optional[str]:
        self._prev = current_trace_id()
        bind(self._tid)
        return self._tid

    def __exit__(self, *exc) -> bool:
        bind(self._prev)
        return False
