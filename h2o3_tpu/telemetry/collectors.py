"""Device-aware collectors: the telemetry the JVM-era tools can't see.

- **Compile counter** (production promotion of tests/_compile_counter.py):
  a ``jax.monitoring`` duration listener counts every XLA backend
  compile (``/jax/core/compile/backend_compile_duration``) into
  ``h2o3_xla_compiles_total`` + a duration histogram — the warm-path
  zero-compile guarantee the test harness proves is now a metric
  production can watch.
- **Compile-cache hit/miss**: the persistent-compile-cache events
  (``/jax/compilation_cache/cache_hits`` / ``cache_misses``).
- **Transfer bytes**: ``record_h2d``/``record_d2h`` counters called from
  the frame layer's transfer choke points (``batch_device_put`` /
  ``Vec.to_numpy`` / spill).
- **Device memory**: a scrape-time view over ``memory_stats()`` (TPU)
  falling back to summing ``jax.live_arrays()`` (CPU backend), plus a
  peak gauge updated at every scrape and h2d record.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from h2o3_tpu.telemetry.registry import on_reset, registry

_INSTALL_LOCK = threading.Lock()
_INSTALLED = [False]

BACKEND_COMPILE_SUFFIX = "backend_compile_duration"
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _compiles():
    return registry().counter(
        "h2o3_xla_compiles_total",
        help="XLA backend compiles in this process")


def _cache_hits():
    return registry().counter(
        "h2o3_compile_cache_hits_total",
        help="persistent compile cache hits")


def _cache_misses():
    return registry().counter(
        "h2o3_compile_cache_misses_total",
        help="persistent compile cache misses")


def _duration_listener(key: str, dur: float, **_kw) -> None:
    if key.endswith(BACKEND_COMPILE_SUFFIX):
        _compiles().inc()
        registry().histogram(
            "h2o3_xla_compile_seconds",
            help="XLA backend compile durations").observe(float(dur))


def _event_listener(key: str, **_kw) -> None:
    if key == CACHE_HIT_EVENT:
        _cache_hits().inc()
    elif key == CACHE_MISS_EVENT:
        _cache_misses().inc()


def install() -> bool:
    """Register the jax.monitoring listeners + the device-memory view.
    Idempotent; safe to call from cluster boot, bench, server start and
    tests. Returns True when the listeners are (already) live."""
    with _INSTALL_LOCK:
        if _INSTALLED[0]:
            return True
        try:
            import jax
            jax.monitoring.register_event_duration_secs_listener(
                _duration_listener)
            jax.monitoring.register_event_listener(_event_listener)
        except Exception:          # jax without monitoring: gate, don't die
            return False
        # touch the counters so a zero-compile process still exports them
        _compiles(), _cache_hits(), _cache_misses()
        registry().add_collector(_device_memory_samples)
        _INSTALLED[0] = True
        return True


def installed() -> bool:
    return _INSTALLED[0]


# ---------------------------------------------------------------- bytes

# transfer counters sit at the frame-layer choke points — hold the
# handles instead of paying the registry creation mutex per transfer.
# Cleared by Registry.reset() on the global registry.
_BYTE_HANDLES: Dict[str, object] = {}
on_reset(_BYTE_HANDLES.clear)

# pipelines a transfer can be attributed to (the label set is closed so
# a typo'd span name can't mint unbounded label cardinality)
_PIPELINES = frozenset(
    {"ingest", "train", "serve", "analytics", "rapids", "frame"})


def _byte_counter(name: str, help_: str, pipeline: Optional[str] = None):
    key = name if pipeline is None else f"{name}|{pipeline}"
    c = _BYTE_HANDLES.get(key)
    if c is None:
        labels = {"pipeline": pipeline} if pipeline is not None else None
        c = registry().counter(name, labels, help=help_)
        _BYTE_HANDLES[key] = c
    return c


def _infer_pipeline() -> Optional[str]:
    """Attribute a transfer to the pipeline whose span is open on this
    thread (ingest.parse / train.* / serve.* roots all thread their
    stage work), so Vec.to_numpy-style chokepoints need no plumbing."""
    from h2o3_tpu.telemetry.spans import current_span
    sp = current_span()
    if sp is None:
        return None
    head = sp.name.split(".", 1)[0]
    return head if head in _PIPELINES else None


def _record_bytes(direction: str, nbytes: int,
                  pipeline: Optional[str],
                  fallback: Optional[str] = None) -> None:
    help_ = f"{direction} transfer bytes"
    _byte_counter(f"h2o3_{direction}_bytes_total", help_).inc(float(nbytes))
    p = pipeline if pipeline in _PIPELINES else _infer_pipeline()
    if p is None and fallback in _PIPELINES:
        # sharded frame-layer transfers issued with NO span open
        # (Frame.resharded, ad-hoc host fetches) used to vanish from
        # the pipeline-labeled counters (ISSUE 8) — the caller's
        # fallback label catches them WITHOUT overriding span inference
        p = fallback
    if p is not None:
        _byte_counter(f"h2o3_{direction}_pipeline_bytes_total",
                      f"{direction} transfer bytes by pipeline",
                      p).inc(float(nbytes))


def record_h2d(nbytes: int, pipeline: Optional[str] = None,
               fallback: Optional[str] = None) -> None:
    """Host→device transfer bytes (batch_device_put / _pad_and_put /
    the streamed chunk uploads). ``pipeline`` attributes the bytes to
    ingest/train/serve/analytics/rapids; when omitted, the open span on
    the calling thread decides, then ``fallback``."""
    if not registry().enabled:
        return
    _record_bytes("h2d", nbytes, pipeline, fallback)


def record_d2h(nbytes: int, pipeline: Optional[str] = None,
               fallback: Optional[str] = None) -> None:
    """Device→host fetch bytes (Vec.to_numpy / spill / device_get)."""
    if not registry().enabled:
        return
    _record_bytes("d2h", nbytes, pipeline, fallback)


def record_d2d(nbytes: int, pipeline: Optional[str] = None) -> None:
    """Device→device move bytes: the stitched sharded-ingest assembly's
    boundary-fragment moves and model-axis replica copies (ISSUE 8 —
    these used to escape the transfer counters entirely, hiding a
    misaligned chunk-home mapping's real cost)."""
    if not registry().enabled:
        return
    _record_bytes("d2d", nbytes, pipeline)


def _tree_nbytes(host) -> int:
    """Byte count of a fetched pytree of numpy arrays/scalars."""
    import numpy as np
    total = 0
    stack = [host]
    while stack:
        x = stack.pop()
        if isinstance(x, dict):
            stack.extend(x.values())
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        else:
            total += getattr(x, "nbytes", 0) or np.asarray(x).nbytes
    return total


def device_get(x, pipeline: Optional[str] = None):
    """Counted ``jax.device_get`` behind the ``d2h`` fault seam: the
    d2h byte counters see ad-hoc fetches (analytics/rapids, model
    finalize), not just the frame-layer choke points, and chaos specs
    can fail the fetch path. Returns the host pytree unchanged."""
    import jax
    from h2o3_tpu import faults
    if faults.ACTIVE:
        faults.check("d2h", pipeline=pipeline)
    host = jax.device_get(x)
    if registry().enabled:
        record_d2h(_tree_nbytes(host), pipeline=pipeline)
    return host


# ---------------------------------------------------------- device memory

def device_memory_bytes() -> Dict[str, Optional[float]]:
    """Live/peak device memory. TPU backends expose memory_stats();
    the CPU backend doesn't, so fall back to summing live jax arrays
    (an upper-bound view of OUR allocations, good enough to trend)."""
    live = peak = None
    try:
        import jax
        stats = [d.memory_stats() for d in jax.local_devices()]
        stats = [s for s in stats if s]
        if stats:
            live = float(sum(s.get("bytes_in_use", 0) for s in stats))
            peak = float(sum(s.get("peak_bytes_in_use", 0) for s in stats))
        else:
            live = float(sum(getattr(a, "nbytes", 0)
                             for a in jax.live_arrays()))
    except Exception:
        pass
    return {"live": live, "peak": peak}


def sample_device_memory() -> Dict[str, Optional[float]]:
    """Measure device memory now and fold it into the peak gauge —
    called at scrape time and from bench round boundaries."""
    mem = device_memory_bytes()
    reg = registry()
    if reg.enabled and mem["live"] is not None:
        g = reg.gauge("h2o3_device_peak_bytes",
                      help="peak observed live device bytes")
        g.set_max(mem["peak"] if mem["peak"] is not None else mem["live"])
    return mem


def _device_memory_samples() -> List[dict]:
    mem = sample_device_memory()
    out = []
    if mem["live"] is not None:
        out.append({"name": "h2o3_device_live_bytes", "kind": "gauge",
                    "value": mem["live"],
                    "help": "live device memory bytes"})
    return out
