"""Exposition formats: Prometheus text, JSON snapshot, Chrome trace.

- ``prometheus_text()`` — the ``GET /metrics`` body (text/plain;
  version=0.0.4): HELP/TYPE headers per family, cumulative ``_bucket``
  series with ``le`` labels for histograms.
- ``telemetry_snapshot()`` — the ``GET /3/Telemetry`` body: flat JSON
  metrics + span-stage aggregates + device memory.
- ``chrome_trace()`` — the ``GET /3/Timeline?format=trace`` body: the
  span ring as Chrome-trace "X" (complete) events; loads directly in
  Perfetto / chrome://tracing.
"""
from __future__ import annotations

import json
import math
from typing import Dict, List, Optional

from h2o3_tpu.telemetry import spans
from h2o3_tpu.telemetry.registry import registry


def _esc(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _labels_text(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _num(v: float) -> str:
    if v != v:
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text(samples: Optional[List[dict]] = None) -> str:
    """Prometheus exposition format 0.0.4 over every registry sample —
    or over an explicit sample list (the cluster-merged view from
    telemetry/snapshot.py renders through the same formatter, so the
    aggregated output can never drift from the single-process one)."""
    lines: List[str] = []
    seen_header = set()
    for s in (registry().samples() if samples is None else samples):
        name, kind, labels = s["name"], s["kind"], s["labels"]
        if name not in seen_header:
            seen_header.add(name)
            if s.get("help"):
                lines.append(f"# HELP {name} {_esc(s['help'])}")
            lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            for le, cum in s["buckets"]:
                le_lab = 'le="%s"' % _num(le)
                lines.append(f"{name}_bucket"
                             f"{_labels_text(labels, le_lab)} {cum}")
            lines.append(f"{name}_sum{_labels_text(labels)} "
                         f"{_num(s['sum'])}")
            lines.append(f"{name}_count{_labels_text(labels)} {s['count']}")
        else:
            lines.append(f"{name}{_labels_text(labels)} {_num(s['value'])}")
    return "\n".join(lines) + "\n"


def _flatten(samples) -> Dict[str, object]:
    """Samples → the flat {name{labels}: value} map (Registry.snapshot
    shape, computed from an existing samples() pass)."""
    flat: Dict[str, object] = {}
    for s in samples:
        key = s["name"]
        if s["labels"]:
            key += "{" + ",".join(f"{k}={v}" for k, v in
                                  sorted(s["labels"].items())) + "}"
        if s["kind"] == "histogram":
            flat[key] = {"sum": round(s["sum"], 6), "count": s["count"]}
        else:
            flat[key] = s["value"]
    return flat


def telemetry_snapshot() -> Dict[str, object]:
    """The /3/Telemetry JSON body: flat metrics + stage aggregates +
    device memory — one H2O-style snapshot of where the time, bytes
    and compiles went. ONE samples() pass feeds every section (each
    pass runs the collector views, including a device-memory walk that
    is O(live arrays) on the CPU backend)."""
    samp = registry().samples()

    def val(name, default=0.0):
        for s in samp:
            if s["name"] == name and not s["labels"]:
                return s.get("value", default)
        return default

    live = val("h2o3_device_live_bytes", None)
    peak = val("h2o3_device_peak_bytes", None)
    return {
        "enabled": registry().enabled,
        "metrics": _flatten(samp),
        "stages": spans.stage_seconds(samples=samp),
        "device_memory": {"live": live, "peak": peak or live},
        "compiles": val("h2o3_xla_compiles_total"),
        "compile_cache": {
            "hits": val("h2o3_compile_cache_hits_total"),
            "misses": val("h2o3_compile_cache_misses_total"),
        },
        "h2d_bytes": val("h2o3_h2d_bytes_total"),
        "d2h_bytes": val("h2o3_d2h_bytes_total"),
    }


def chrome_trace(limit: Optional[int] = None) -> Dict[str, object]:
    """Chrome-trace JSON of the finished-span ring. Thread names become
    Perfetto track names; parent links ride in args (flow events would
    need begin/end pairs — complete events keep the export dead simple
    and still render nesting by track + time containment)."""
    evs = []
    for sp in spans.finished_spans(limit or 0) if limit else \
            spans.finished_spans():
        if sp.duration_s is None:
            continue
        args = {"span_id": sp.span_id}
        if sp.parent_id:
            args["parent_id"] = sp.parent_id
        if sp.trace_id:
            args["trace_id"] = sp.trace_id
        for k, v in sp.attrs.items():
            if isinstance(v, (int, float, str, bool)):
                args[k] = v
        evs.append({
            "name": sp.name,
            "cat": sp.name.split(".", 1)[0],
            "ph": "X",
            "ts": sp.t_wall * 1e6,               # µs epoch
            "dur": sp.duration_s * 1e6,
            "pid": 1,
            "tid": sp.thread_id % (1 << 31),
            "args": args,
        })
    return {"traceEvents": evs, "displayTimeUnit": "ms"}


def chrome_trace_bytes(limit: Optional[int] = None) -> bytes:
    return json.dumps(chrome_trace(limit)).encode()
