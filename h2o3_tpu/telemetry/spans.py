"""End-to-end spans: nested timing contexts with cross-thread handoff.

A ``span("train.gbm.loop", job=...)`` context manager times a stage and
records it three ways:

- a per-name duration histogram in the metrics registry
  (``h2o3_span_seconds{span=...}``) — the aggregate view the profiler
  tools and /metrics read;
- an entry in a bounded ring of finished spans — the raw view behind
  ``GET /3/Timeline?format=trace`` (Chrome-trace/Perfetto export);
- for ROOT spans (no parent), an event in the existing
  ``log.timeline_record`` ring — so Flow's /3/Timeline finally shows
  ingest and serve activity, not just model builds.

Parentage: within a thread, nesting is implicit (a thread-local stack).
Across threads — the micro-batcher's submit/batch/collect trio, the
training job thread — the parent is handed off EXPLICITLY: capture
``current_span()`` (or the ``Span`` yielded by the context manager) in
one thread and pass it as ``span(..., parent=handle)`` or
``record_span(..., parent=handle)`` in another. A ``Span`` handle stays
valid after it finishes; linking to a finished parent is fine (the
batcher's collector thread finishes children after the batch root).

Pipelines that already keep wall-clock stage timers (ingest's
LAST_PROFILE, gbm's train_profile) record those SAME intervals via
``record_span`` — one clock feeds both the legacy dicts and the spans,
so the REST-reported and tool-reported stage splits cannot disagree.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Dict, List, Optional

from h2o3_tpu.telemetry.registry import on_reset, registry
from h2o3_tpu.telemetry.trace import current_trace_id


def _env_ring_cap() -> int:
    """Finished-span ring capacity (``H2O3_SPAN_RING``, default 8192).
    Bounded below at 16 so a typo cannot silently discard every span."""
    try:
        return max(int(os.environ.get("H2O3_SPAN_RING", "8192")), 16)
    except ValueError:
        return 8192


_RING_CAP = _env_ring_cap()
# eviction is EXPLICIT (no deque maxlen): a full ring pops the oldest
# span and counts it in h2o3_spans_dropped_total, so trace loss under
# load is a visible metric instead of a silent wraparound (PR-4 gap)
_RING: "collections.deque" = collections.deque()
_RING_LOCK = threading.Lock()
_DROPPED_HANDLE: List[object] = []


def _dropped_counter():
    if not _DROPPED_HANDLE:
        _DROPPED_HANDLE.append(registry().counter(
            "h2o3_spans_dropped_total",
            help="finished spans evicted from the full span ring "
                 "(raise H2O3_SPAN_RING to keep more)"))
    return _DROPPED_HANDLE[0]


def set_ring_capacity(cap: int) -> None:
    """Resize the finished-span ring (test/boot use; normally set once
    via H2O3_SPAN_RING). Shrinking drops-and-counts the oldest spans."""
    global _RING_CAP
    cap = max(int(cap), 16)
    dropped = 0
    with _RING_LOCK:
        _RING_CAP = cap
        while len(_RING) > cap:
            _RING.popleft()
            dropped += 1
    if dropped:
        _dropped_counter().inc(dropped)
_IDS = itertools.count(1)
_TLS = threading.local()

# span-duration histogram bounds: 10µs (a serve decode) … 1000s (a cold
# AutoML build)
_SPAN_BOUNDS = (1e-5, 1e-4, 1e-3, 0.01, 0.1, 1.0, 10.0, 100.0, 1000.0)

# per-name histogram handle cache: span finish sits on the serve hot
# path, and going through Registry._get would serialize every finishing
# thread on the registry-wide creation mutex. A racy double-create is
# harmless (Registry._get dedups to one instance). Cleared by
# Registry.reset() on the global registry.
_HIST_CACHE: Dict[str, object] = {}
on_reset(_HIST_CACHE.clear)
on_reset(_DROPPED_HANDLE.clear)


def _span_hist(name: str):
    h = _HIST_CACHE.get(name)
    if h is None:
        h = registry().histogram(
            "h2o3_span_seconds", {"span": name},
            help="finished span durations by span name",
            bounds=_SPAN_BOUNDS)
        _HIST_CACHE[name] = h
    return h


class Span:
    __slots__ = ("name", "attrs", "span_id", "parent_id", "thread_id",
                 "t_wall", "t0", "duration_s", "trace_id")

    def __init__(self, name: str, parent: Optional["Span"] = None,
                 attrs: Optional[Dict] = None):
        self.name = name
        self.attrs = attrs or {}
        self.span_id = next(_IDS)
        self.parent_id = parent.span_id if parent is not None else 0
        self.thread_id = threading.get_ident()
        self.t_wall = time.time()
        self.t0 = time.perf_counter()
        self.duration_s: Optional[float] = None
        # trace linkage: the thread's bound trace id wins (the REST
        # handler / job thread bound it), else inherit the parent's —
        # which is how a child recorded on the batcher's collector
        # thread keeps the submitting request's trace
        self.trace_id: Optional[str] = current_trace_id() or (
            parent.trace_id if parent is not None else None)

    def finish(self) -> "Span":
        if self.duration_s is None:
            self.duration_s = time.perf_counter() - self.t0
            _record_finished(self)
        return self

    def __repr__(self):
        d = f"{self.duration_s * 1e3:.2f}ms" if self.duration_s else "open"
        return f"<Span {self.name}#{self.span_id} {d}>"


def _stack() -> List[Span]:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current_span() -> Optional[Span]:
    """The innermost open span on THIS thread (the handoff handle)."""
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


def _note_error_span(name: str, exc: BaseException) -> None:
    """Remember the INNERMOST span a given exception unwound through:
    the innermost context exits first, so only the first note per
    exception identity sticks — outer spans exiting with the same
    exception don't overwrite it. Job supervision reads this to report
    the failed pipeline stage on /3/Jobs."""
    cur = getattr(_TLS, "last_error", None)
    if cur is None or cur[0] != id(exc):
        _TLS.last_error = (id(exc), name)


def last_error_span(exc: Optional[BaseException] = None) -> Optional[str]:
    """Name of the innermost span the given (or most recent) exception
    failed inside on THIS thread; None if no span saw it."""
    cur = getattr(_TLS, "last_error", None)
    if cur is None:
        return None
    if exc is not None and cur[0] != id(exc):
        return None
    return cur[1]


# timeline throttle: the Flow ring is 2048 entries — at serve rates
# (hundreds of serve.request/serve.batch roots per second) unthrottled
# feeding would wrap it in seconds, evicting the train/ingest events the
# endpoint exists to show. One event per span NAME per second keeps
# serve activity visible without monopolizing the ring (the full-rate
# record stays in the span ring for ?format=trace). Racy reads are fine:
# worst case two threads both pass the gate and two events land.
_TL_LAST: Dict[str, float] = {}
_TL_MIN_INTERVAL_S = 1.0


def _record_finished(sp: Span) -> None:
    if not registry().enabled:
        return
    _span_hist(sp.name).observe(sp.duration_s)
    dropped = 0
    with _RING_LOCK:
        _RING.append(sp)
        while len(_RING) > _RING_CAP:
            _RING.popleft()
            dropped += 1
    if dropped:
        _dropped_counter().inc(dropped)
    if sp.parent_id == 0:
        # root spans feed the Flow timeline ring (train_start/train_done
        # style events now cover ingest and serve too)
        now = time.monotonic()   # rate-limit interval, not an epoch
        if now - _TL_LAST.get(sp.name, 0.0) < _TL_MIN_INTERVAL_S:
            return
        _TL_LAST[sp.name] = now
        from h2o3_tpu import log
        extra = " ".join(f"{k}={v}" for k, v in sp.attrs.items())
        if sp.trace_id:
            extra = (extra + " " if extra else "") + f"trace={sp.trace_id}"
        log.timeline_record(
            sp.name, f"{sp.duration_s * 1e3:.1f} ms"
            + (f" {extra}" if extra else ""))


class _SpanContext:
    """Context manager wrapper: pushes/pops the thread-local stack so
    nested ``span()`` calls parent implicitly."""
    __slots__ = ("_span", "_name", "_parent", "_attrs")

    def __init__(self, name, parent, attrs):
        self._name = name
        self._parent = parent
        self._attrs = attrs
        self._span: Optional[Span] = None

    def __enter__(self) -> Optional[Span]:
        if not registry().enabled:
            return None
        parent = self._parent if self._parent is not None \
            else current_span()
        sp = Span(self._name, parent, self._attrs)
        _stack().append(sp)
        self._span = sp
        return sp

    def __exit__(self, exc_type=None, exc_value=None, tb=None):
        sp = self._span
        if sp is None:
            return False
        if exc_value is not None:
            sp.attrs["error"] = True
            _note_error_span(sp.name, exc_value)
        st = _stack()
        # pop by identity — an exception may have skipped inner pops
        while st:
            top = st.pop()
            if top is sp:
                break
            top.finish()
        sp.finish()
        return False


def span(name: str, parent: Optional[Span] = None, **attrs) -> _SpanContext:
    """``with span("ingest.parse", rows=n) as sp: ...`` — times the
    block; nesting is implicit per thread, ``parent=`` makes it
    explicit (cross-thread handoff)."""
    return _SpanContext(name, parent, attrs)


def open_span(name: str, parent: Optional[Span] = None,
              **attrs) -> Optional[Span]:
    """Start a span WITHOUT entering the thread-local stack — for spans
    that end on a different thread (the batcher's per-batch root).
    Finish with ``sp.finish()``. Returns None when telemetry is off."""
    if not registry().enabled:
        return None
    return Span(name, parent, attrs)


def record_span(name: str, start_wall: float, duration_s: float,
                parent: Optional[Span] = None, **attrs) -> Optional[Span]:
    """Record an already-measured interval as a finished span (one clock
    feeding both a legacy profile dict and the span ring). ``parent``
    defaults to the calling thread's current span."""
    if not registry().enabled:
        return None
    sp = Span(name, parent if parent is not None else current_span(), attrs)
    sp.t_wall = start_wall
    sp.duration_s = float(duration_s)
    _record_finished(sp)
    return sp


def finished_spans(n: Optional[int] = None) -> List[Span]:
    """The most recent ``n`` finished spans (default: the whole ring).
    ``n=0`` means ZERO spans — the spanless-snapshot spelling — not
    "everything"."""
    if n is None:
        n = _RING_CAP
    if n <= 0:
        return []
    with _RING_LOCK:
        return list(_RING)[-n:]


def clear_spans() -> None:
    """Test isolation only."""
    with _RING_LOCK:
        _RING.clear()


def stage_seconds(prefix: str = "",
                  samples: Optional[List[dict]] = None
                  ) -> Dict[str, Dict[str, float]]:
    """Aggregate stage totals from the span-duration histograms:
    ``{span_name: {count, seconds}}`` — the view the profiler tools
    read, identical by construction to what /metrics exports. Pass an
    existing ``registry().samples()`` list to avoid a second scrape
    (each scrape runs the collector views, incl. a device-memory
    walk)."""
    out: Dict[str, Dict[str, float]] = {}
    for s in (samples if samples is not None else registry().samples()):
        if s["name"] != "h2o3_span_seconds" or s["kind"] != "histogram":
            continue
        name = s["labels"].get("span", "")
        if prefix and not name.startswith(prefix):
            continue
        out[name] = {"count": s["count"], "seconds": round(s["sum"], 6)}
    return out
