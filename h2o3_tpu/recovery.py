"""Restart-safe training recovery — the locked-cloud failure model.

Reference: h2o-3's answer to node loss is the locked cloud
(water/Paxos.java:145 — a lost member does NOT rejoin; the cluster
restarts and reloads checkpoints from persistent store, SURVEY L1/L2).
PR 6 built the in-process half of that story: per-tree in-training
checkpoints whose resume state makes a continued train BIT-identical to
an uninterrupted one. This module adds the half that survives losing
the PROCESS itself:

- **Recovery manifest** (``record_training``): every live training job
  that writes in-training checkpoints also records a small JSON
  manifest to a durable ``H2O3_RECOVERY_DIR`` — model key, algo,
  params, response/feature columns, the checkpoint dir, the SPMD mesh
  shape, the creating request's trace id — plus a one-time binary
  artifact of the training frame (``persist.save_frame``), so a fresh
  process can rebuild the exact training inputs. The manifest is
  dropped when the train reaches a deliberate terminal state (DONE /
  CANCELLED); a crash/kill leaves it behind — that IS the recovery
  signal.
- **Boot-time scan** (``recover_at_boot``, wired into
  ``cluster_boot``): a fresh process lists the manifests, pairs each
  with the NEWEST ``<key>_t<n>.zip`` artifact in its
  ``in_training_checkpoints_dir``, re-registers a Job (status
  ``RECOVERING``, the original trace id re-bound) and resumes the
  train through the normal ``checkpoint=`` path — the PR 6
  data-signature guard still applies, so a changed frame recomputes
  margins instead of silently continuing on stale state. Resume runs
  under the NEW process's mesh; GBM/DRF resumes are bit-identical to
  the uninterrupted train (tests/test_restart_recovery.py).
- **Checkpoint GC**: orphaned on-disk checkpoint artifacts (dead jobs
  whose manifests are gone, completed trains' durable artifacts past
  their useful life) previously accumulated forever; boot GC removes
  entries older than ``H2O3_RECOVERY_GC_AGE_SECS`` **except** the ones
  the recovery scan just claimed.

Failure policy: everything here is advisory and loud. A corrupt
manifest is renamed ``*.corrupt`` and WARNED about; a resume that
raises is reported and skipped; nothing in this module may wedge
process startup (the ``boot`` fault-injection site exercises exactly
that contract). When ``H2O3_RECOVERY_DIR`` is unset the whole
machinery is a checked no-op — one env lookup per call (the
``H2O3_TELEMETRY=0`` idiom, budget-guard tested).
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

MANIFEST_VERSION = 1

# cap on remembered checkpoint dirs (ckpt_dirs.json): GC only scans
# dirs a manifest once named; an unbounded list would itself be a leak
_MAX_CKPT_DIRS = 256

_CKPT_RE = re.compile(r"^(?P<key>.+)_t(?P<trees>\d+)\.zip$")

# resume-context marker: ModelBuilder.train checks it to register the
# resumed job as RECOVERING (and schemas surface it on /3/Jobs)
_RESUME_CTX = threading.local()

# last boot-recovery report (GET /3/Recovery) + live resume jobs so
# tests/boot can join background resumes
_LAST_REPORT: Optional[Dict[str, Any]] = None
_LIVE_JOBS: List[Any] = []
# the background _finish threads (they dkv.put the resumed model AFTER
# the job turns terminal — waiters must join these, not just the jobs,
# or they race the model registration)
_LIVE_FINISHERS: List[Any] = []


# ---------------- gating -----------------------------------------------

def recovery_dir() -> Optional[str]:
    """The durable recovery root, or None when the subsystem is off.
    Read from the environment on every call so tests/ops can flip it
    at runtime; the unset path is one dict lookup."""
    d = os.environ.get("H2O3_RECOVERY_DIR")
    return d.strip() or None if d is not None else None


def enabled() -> bool:
    return recovery_dir() is not None


def gc_age_secs() -> float:
    """Orphaned-checkpoint age threshold (default 7 days); malformed
    values fall back instead of breaking boot."""
    try:
        v = float(os.environ.get("H2O3_RECOVERY_GC_AGE_SECS",
                                 "604800") or 604800)
        return v if v > 0 else 604800.0
    except ValueError:
        return 604800.0


def max_resume_attempts() -> int:
    """Boot-resume attempt cap per manifest (default 3): a train that
    fails DETERMINISTICALLY (bad interaction, NaN loss) must not be
    re-trained on every boot forever — after the cap its manifest is
    renamed ``*.abandoned`` with a loud warn."""
    try:
        v = int(os.environ.get("H2O3_RECOVERY_MAX_ATTEMPTS", "3") or 3)
        return v if v > 0 else 3
    except ValueError:
        return 3


def is_resuming() -> bool:
    return bool(getattr(_RESUME_CTX, "on", False))


# ---------------- paths ------------------------------------------------

def _manifests_dir(root: str) -> str:
    return os.path.join(root, "manifests")


def _frames_dir(root: str) -> str:
    return os.path.join(root, "frames")


def _manifest_path(root: str, model_key: str) -> str:
    return os.path.join(_manifests_dir(root), f"{model_key}.json")


def _ckpt_dirs_path(root: str) -> str:
    return os.path.join(root, "ckpt_dirs.json")


def _atomic_write_json(path: str, obj) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _remember_ckpt_dir(root: str, ckpt_dir: str) -> None:
    """Append to the GC's dir registry: orphans from COMPLETED trains
    have no manifest left to name their dir, so GC needs its own
    memory of every checkpoint dir recovery ever saw."""
    path = _ckpt_dirs_path(root)
    dirs: List[str] = []
    try:
        with open(path) as f:
            got = json.load(f)
        if isinstance(got, list):
            dirs = [str(d) for d in got]
    except (OSError, ValueError):
        pass
    ad = os.path.abspath(ckpt_dir)
    if ad in dirs:
        return
    dirs.append(ad)
    _atomic_write_json(path, dirs[-_MAX_CKPT_DIRS:])


# ---------------- manifest lifecycle -----------------------------------

def record_training(builder, job, frame, y, spec) -> Optional[str]:
    """Record a live training job to the recovery dir. Called by
    ``ModelBuilder.train`` when recovery is enabled AND the train
    writes in-training checkpoints. Advisory: failures warn, never
    fail the train they protect. Returns the manifest's model key (the
    completion hook's handle), or None."""
    root = recovery_dir()
    if root is None:
        return None
    try:
        from h2o3_tpu.parallel.mesh import (current_mesh, n_data_shards,
                                            n_model_shards)
        from h2o3_tpu.persist import _json_safe, save_frame
        from h2o3_tpu.telemetry.snapshot import process_identity
        model_key = builder._model_key()
        os.makedirs(_manifests_dir(root), exist_ok=True)
        os.makedirs(_frames_dir(root), exist_ok=True)
        frame_key = getattr(frame, "key", None) or f"{model_key}_frame"
        # the artifact name carries a content fingerprint (the PR-6
        # (nrow, Σy, Σw) signature): frame keys are USER-assignable
        # (destination_frame), and re-importing different data under
        # last week's key must not make recovery resume on the stale
        # artifact — same key + same data reuses it, same key +
        # different data writes its own
        sig_suffix = ""
        try:
            from h2o3_tpu.models.gbm import _spec_signature
            sig_suffix = "." + hashlib.sha1(
                _spec_signature(spec).tobytes()).hexdigest()[:10]
        except Exception:   # noqa: BLE001 — fingerprint is best-effort
            pass
        frame_path = os.path.join(_frames_dir(root),
                                  f"{frame_key}{sig_suffix}.zip")
        if not os.path.exists(frame_path):
            # one durable copy of the training inputs; re-records (a
            # recovery resume is itself recorded, grid trains share
            # frames) reuse the artifact instead of rewriting the
            # dataset every train
            got = save_frame(frame, _frames_dir(root), key=frame_key)
            if got != frame_path:
                os.replace(got, frame_path)
        ckpt_dir = builder.params.get("in_training_checkpoints_dir")
        _remember_ckpt_dir(root, ckpt_dir)
        mesh = current_mesh()
        # the submission's priority class + fair-share group (satellite
        # of ISSUE 18): a crash/evict re-submit keeps its class instead
        # of landing behind every bulk job in `background`
        pr_name, share = None, None
        try:
            from h2o3_tpu import sched as _sched
            entry = getattr(builder, "_sched_entry", None)
            if entry is not None:
                pr_name = _sched.PRIORITY_NAMES.get(entry.priority)
                share = entry.share
            if pr_name is None:
                pr_name = _sched.context_priority()
            if share is None:
                share = _sched.context_share()
        except Exception:   # noqa: BLE001 — class carry is best-effort
            pass
        try:
            from h2o3_tpu.fleet.sched import local_member_id
            member_id = local_member_id()
        except Exception:   # noqa: BLE001
            member_id = None
        attempts = 0
        if is_resuming():
            # the resume re-records its own manifest under the same
            # model key — carry the boot-attempt count over so a train
            # that fails deterministically cannot reset its own cap
            try:
                with open(_manifest_path(root, model_key)) as f:
                    attempts = int(json.load(f)
                                   .get("resume_attempts", 0) or 0)
            except (OSError, ValueError, TypeError):
                pass
        manifest = {
            "version": MANIFEST_VERSION,
            "model_key": model_key,
            "algo": builder.algo,
            "job_key": job.key,
            "trace_id": getattr(job, "trace_id", None),
            "y": y,
            "x": list(spec.names),
            "params": _json_safe(builder.params),
            "frame_key": frame_key,
            "frame_path": frame_path,
            "ckpt_dir": os.path.abspath(ckpt_dir),
            "mesh": {"n_data": n_data_shards(mesh),
                     "n_model": n_model_shards(mesh)},
            "process": process_identity(),
            "priority": pr_name,
            "share": share,
            "member_id": member_id,
            "resume_attempts": attempts,
            "time": time.time(),
        }
        _atomic_write_json(_manifest_path(root, model_key), manifest)
        from h2o3_tpu import telemetry
        telemetry.counter(
            "h2o3_recovery_manifests_total", {"algo": builder.algo},
            help="training recovery manifests recorded").inc()
        from h2o3_tpu.telemetry import blackbox
        blackbox.record("manifest_written", member=model_key,
                        payload=f"algo={builder.algo} job={job.key}",
                        trace_id=manifest["trace_id"])
        return model_key
    except Exception as e:   # noqa: BLE001 — advisory only
        try:
            from h2o3_tpu.log import warn
            warn("recovery: failed to record training manifest: %s", e)
        except Exception:
            pass
        return None


def complete_training(model_key: str) -> None:
    """Drop a manifest when its train reaches a DELIBERATE terminal
    state (DONE/CANCELLED). Crashes never call this — the surviving
    manifest is what the next boot recovers from."""
    root = recovery_dir()
    if root is None or not model_key:
        return
    try:
        os.remove(_manifest_path(root, model_key))
    except OSError:
        return
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.record("manifest_done", member=model_key)
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass


# ---------------- boot-time scan ---------------------------------------

def latest_checkpoint(ckpt_dir: Optional[str], model_key: str
                      ) -> Optional[Tuple[str, int]]:
    """Newest ``<model_key>_t<n>.zip`` in the checkpoint dir as
    (path, trees), or None when nothing resumable exists."""
    if not ckpt_dir or not os.path.isdir(ckpt_dir):
        return None
    best: Optional[Tuple[str, int]] = None
    prefix = f"{model_key}_t"
    for fn in os.listdir(ckpt_dir):
        if not fn.startswith(prefix):
            continue
        m = _CKPT_RE.match(fn)
        if m is None or m.group("key") != model_key:
            continue
        trees = int(m.group("trees"))
        if best is None or trees > best[1]:
            best = (os.path.join(ckpt_dir, fn), trees)
    return best


def scan(quarantine: bool = True) -> Tuple[List[Dict[str, Any]],
                                           List[str]]:
    """Read every manifest; returns (entries, corrupt_paths). A corrupt
    manifest is WARNED about and renamed ``*.corrupt`` (evidence kept,
    never rescanned) — boot must proceed regardless.
    ``quarantine=False`` is the read-only spelling for the REST
    inspection route: a monitoring poll must not rename a corrupt
    manifest aside before the NEXT BOOT's scan gets to report it."""
    root = recovery_dir()
    if root is None:
        return [], []
    mdir = _manifests_dir(root)
    if not os.path.isdir(mdir):
        return [], []
    entries: List[Dict[str, Any]] = []
    corrupt: List[str] = []
    for fn in sorted(os.listdir(mdir)):
        if not fn.endswith(".json"):
            continue
        path = os.path.join(mdir, fn)
        try:
            with open(path) as f:
                ent = json.load(f)
            if not isinstance(ent, dict) or not ent.get("model_key") \
                    or not ent.get("algo"):
                raise ValueError("missing model_key/algo")
            if int(ent.get("version", 0)) > MANIFEST_VERSION:
                raise ValueError(
                    f"manifest version {ent.get('version')} is newer "
                    f"than this build ({MANIFEST_VERSION})")
        except Exception as e:   # noqa: BLE001 — corrupt file, not code
            from h2o3_tpu.log import warn
            if quarantine:
                warn("recovery: corrupt manifest %s (%s) — renamed "
                     "aside, boot continues", path, e)
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    pass
            corrupt.append(path)
            continue
        ent["manifest_path"] = path
        lc = latest_checkpoint(ent.get("ckpt_dir"), ent["model_key"])
        ent["latest_ckpt"], ent["ckpt_trees"] = \
            (lc if lc is not None else (None, None))
        entries.append(ent)
    return entries, corrupt


def gc_checkpoints(claimed_keys,
                   claimed_frames=None) -> Dict[str, Any]:
    """Age/ownership-based checkpoint GC: remove ``*_t<n>.zip``
    artifacts older than ``H2O3_RECOVERY_GC_AGE_SECS`` from every dir
    the recovery layer has seen — EXCEPT artifacts whose model key the
    current scan claimed (those are about to be resumed from). Frame
    artifacts in the recovery dir age out under the same rule when no
    surviving manifest references them (``claimed_frames``)."""
    root = recovery_dir()
    report: Dict[str, Any] = {"removed": [], "kept_claimed": 0,
                              "age_secs": gc_age_secs()}
    if root is None:
        return report
    dirs: List[str] = []
    try:
        with open(_ckpt_dirs_path(root)) as f:
            got = json.load(f)
        if isinstance(got, list):
            dirs = [str(d) for d in got]
    except (OSError, ValueError):
        pass
    claimed = set(claimed_keys or ())
    now = time.time()
    age = report["age_secs"]
    for d in dirs:
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for fn in names:
            m = _CKPT_RE.match(fn)
            if m is None:
                continue
            if m.group("key") in claimed:
                report["kept_claimed"] += 1
                continue
            path = os.path.join(d, fn)
            try:
                if now - os.path.getmtime(path) > age:  # h2o3-lint: allow[monotonic-durations] file mtimes are wall-clock epochs persisted across restarts — monotonic cannot age them
                    os.remove(path)
                    report["removed"].append(path)
            except OSError:
                continue
    fdir = _frames_dir(root)
    keep_frames = {os.path.abspath(p) for p in (claimed_frames or ())}
    try:
        frame_names = os.listdir(fdir)
    except OSError:
        frame_names = []
    for fn in frame_names:
        if not fn.endswith(".zip"):
            continue
        path = os.path.join(fdir, fn)
        if os.path.abspath(path) in keep_frames:
            report["kept_claimed"] += 1
            continue
        try:
            if now - os.path.getmtime(path) > age:  # h2o3-lint: allow[monotonic-durations] file mtimes are wall-clock epochs persisted across restarts
                os.remove(path)
                report["removed"].append(path)
        except OSError:
            continue
    if report["removed"]:
        from h2o3_tpu.log import info
        info("recovery GC: removed %d orphaned checkpoint artifact(s) "
             "older than %.0fs", len(report["removed"]), age)
    return report


# ---------------- resume -----------------------------------------------

def _estimator_class(algo: str):
    if algo == "gbm":
        from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
        return H2OGradientBoostingEstimator
    if algo == "drf":
        from h2o3_tpu.models.drf import H2ORandomForestEstimator
        return H2ORandomForestEstimator
    if algo == "xgboost":
        from h2o3_tpu.models.xgboost import H2OXGBoostEstimator
        return H2OXGBoostEstimator
    raise ValueError(f"recovery has no resume path for algo '{algo}'")


def _resume_entry(ent: Dict[str, Any], wait: bool) -> Dict[str, Any]:
    """Re-register and resume one interrupted train. The resumed Job
    starts in status RECOVERING with the ORIGINAL trace id bound, so
    /3/Jobs and every span the resume records link back to the request
    that started the interrupted train."""
    from h2o3_tpu import dkv, faults
    if faults.ACTIVE:
        faults.check("boot", key=ent["model_key"])
    from h2o3_tpu.persist import load_frame
    from h2o3_tpu.telemetry import trace as _trace
    # the manifest records the mesh the committed prefix was built
    # under: the sharded histogram psum's accumulation order is part of
    # the bit-parity contract, so a resume under a DIFFERENT mesh shape
    # (nodepool resize between boots) still completes but must not
    # claim bit-identity — warn loudly and flag the resume
    mesh_changed = False
    want = ent.get("mesh") or {}
    if want:
        from h2o3_tpu.parallel.mesh import (current_mesh, n_data_shards,
                                            n_model_shards)
        mesh = current_mesh()
        have = {"n_data": n_data_shards(mesh),
                "n_model": n_model_shards(mesh)}
        mesh_changed = any(
            int(want.get(k, have[k]) or have[k]) != have[k]
            for k in ("n_data", "n_model"))
        if mesh_changed:
            from h2o3_tpu.log import warn
            warn("recovery: '%s' trained on a %sx%s mesh, resuming on "
                 "%dx%d — the resumed model is NOT guaranteed "
                 "bit-identical to the uninterrupted train",
                 ent["model_key"], want.get("n_data"),
                 want.get("n_model"), have["n_data"], have["n_model"])
    params = dict(ent.get("params") or {})
    for k in ("training_frame", "validation_frame", "response_column"):
        params.pop(k, None)
    # a kill can land AFTER the final checkpoint committed but BEFORE
    # the manifest dropped: the newest artifact then already holds
    # every requested tree, and retraining through checkpoint= would
    # fail _resolve_checkpoint's ntrees-must-grow check on every boot.
    # Register the finished artifact directly instead.
    target = int(params.get("ntrees", 0) or 0)
    if ent.get("latest_ckpt") and target \
            and int(ent.get("ckpt_trees") or 0) >= target:
        from h2o3_tpu.log import info
        from h2o3_tpu.persist import load_model
        model = load_model(ent["latest_ckpt"])
        model.key = ent["model_key"]
        dkv.put(ent["model_key"], "model", model)
        complete_training(ent["model_key"])
        info("recovery: '%s' was already fully trained (%d trees) — "
             "registered the final checkpoint artifact, no retrain",
             ent["model_key"], target)
        return {"model_key": ent["model_key"], "algo": ent["algo"],
                "job_key": None,
                "trace_id": ent.get("trace_id"),
                "checkpoint": ent["latest_ckpt"],
                "ckpt_trees": ent.get("ckpt_trees"),
                "mesh_changed": False, "job_status": "DONE",
                "completed_from_artifact": True}
    frame = load_frame(ent["frame_path"])
    # the resumed train keeps the ORIGINAL model key (model_id), so its
    # own in-training checkpoints land under the same artifact names —
    # a crash DURING recovery resumes from the newest of those
    params["model_id"] = ent["model_key"]
    if ent.get("latest_ckpt"):
        params["checkpoint"] = ent["latest_ckpt"]
    # else: killed before the first interval checkpoint committed — the
    # recovery is a clean rerun of the ORIGINAL request, including any
    # user-supplied checkpoint= base continuation the manifest params
    # carry (dropping it would silently rebuild from f0 without the
    # base model's trees; same seed + same base → same model)
    est = _estimator_class(ent["algo"])(**params)
    trace_id = ent.get("trace_id") or _trace.new_trace_id()
    _RESUME_CTX.on = True
    try:
        # the resume keeps the ORIGINAL submission's priority class +
        # share group when the manifest carries them (ISSUE 18
        # satellite: an interactive train that died must not queue
        # behind every bulk job); older manifests fall back to the
        # ISSUE-15 background/recovery class
        from h2o3_tpu import sched
        pr = ent.get("priority")
        if pr not in sched.PRIORITY_LEVELS:
            pr = "background"
        with sched.submit_context(priority=pr,
                                  share=ent.get("share") or "recovery"), \
                _trace.trace_context(trace_id):
            est.train(y=ent.get("y"), x=ent.get("x") or None,
                      training_frame=frame, background=True)
    finally:
        _RESUME_CTX.on = False
    job = est.job
    _LIVE_JOBS.append(job)

    def _finish():
        try:
            model = job.join()
            model.key = ent["model_key"]
            dkv.put(ent["model_key"], "model", model)
            from h2o3_tpu.log import info
            info("recovery: resumed %s '%s' to %s trees (job %s)",
                 ent["algo"], ent["model_key"],
                 getattr(model, "ntrees_built", "?"), job.key)
        except Exception as e:   # noqa: BLE001 — loud, never fatal
            from h2o3_tpu.log import warn
            warn("recovery: resume of '%s' FAILED: %s",
                 ent["model_key"], e)

    if wait:
        _finish()
    else:
        th = threading.Thread(target=_finish, daemon=True,
                              name=f"recovery-{ent['model_key']}")
        th.start()
        _LIVE_FINISHERS.append(th)
    return {"model_key": ent["model_key"], "algo": ent["algo"],
            "job_key": job.key, "trace_id": trace_id,
            "checkpoint": ent.get("latest_ckpt"),
            "ckpt_trees": ent.get("ckpt_trees"),
            "mesh_changed": mesh_changed,
            "job_status": job.status}


def recover_at_boot(wait: bool = False) -> Dict[str, Any]:
    """The boot-time entrypoint (cluster_boot.run_boot_recovery / tests):
    scan → GC → resume every interrupted train. Per-entry failures warn
    and continue — recovery must NEVER wedge startup. ``wait=True``
    blocks until every resume finishes (tests/chaos); the k8s boot path
    resumes in the background so the REST port comes up immediately."""
    global _LAST_REPORT
    t0 = time.monotonic()
    report: Dict[str, Any] = {"enabled": enabled(), "resumed": [],
                              "failed": [], "abandoned": [],
                              "corrupt": [], "gc": None, "seconds": 0.0}
    if not enabled():
        _LAST_REPORT = report
        return report
    from h2o3_tpu import telemetry
    from h2o3_tpu.log import info, warn
    entries, corrupt = scan()
    report["corrupt"] = corrupt
    report["gc"] = gc_checkpoints(
        {e["model_key"] for e in entries},
        claimed_frames={e["frame_path"] for e in entries
                        if e.get("frame_path")})
    if entries:
        info("recovery: %d interrupted train(s) found in %s",
             len(entries), recovery_dir())
    cap = max_resume_attempts()
    for ent in entries:
        attempts = int(ent.get("resume_attempts", 0) or 0)
        mpath = ent.get("manifest_path")
        if attempts >= cap:
            # a manifest that survived `cap` boot resumes is failing
            # deterministically — stop re-training it every restart;
            # evidence kept aside (same contract as *.corrupt)
            warn("recovery: '%s' already failed %d boot resume "
                 "attempt(s) — abandoning (renamed *.abandoned; "
                 "checkpoints kept for manual checkpoint= resume)",
                 ent.get("model_key"), attempts)
            try:
                if mpath:
                    os.replace(mpath, mpath + ".abandoned")
            except OSError:
                pass
            report["abandoned"].append(ent.get("model_key"))
            try:
                from h2o3_tpu.telemetry import blackbox
                blackbox.record("manifest_abandoned",
                                member=str(ent.get("model_key") or ""),
                                payload=f"attempts={attempts}",
                                trace_id=ent.get("trace_id"))
            except Exception:   # noqa: BLE001 — flight recorder is advisory
                pass
            continue
        # count the attempt BEFORE resuming: a crash mid-resume must
        # still advance the cap
        ent["resume_attempts"] = attempts + 1
        try:
            _atomic_write_json(mpath, {
                k: v for k, v in ent.items()
                if k not in ("manifest_path", "latest_ckpt",
                             "ckpt_trees")})
        except OSError:
            pass
        try:
            from h2o3_tpu.telemetry import blackbox
            blackbox.record("manifest_claimed",
                            member=str(ent.get("model_key") or ""),
                            payload=f"attempt={attempts + 1} "
                                    f"ckpt_trees={ent.get('ckpt_trees')}",
                            trace_id=ent.get("trace_id"))
        except Exception:   # noqa: BLE001 — flight recorder is advisory
            pass
        try:
            report["resumed"].append(_resume_entry(ent, wait))
            telemetry.counter(
                "h2o3_recovery_resumed_total", {"algo": ent["algo"]},
                help="interrupted trains resumed at boot").inc()
        except Exception as e:   # noqa: BLE001 — never wedge startup
            warn("recovery: could not resume '%s': %s — continuing "
                 "boot", ent.get("model_key"), e)
            report["failed"].append({"model_key": ent.get("model_key"),
                                     "error": repr(e)})
            telemetry.counter(
                "h2o3_recovery_failed_total",
                help="boot-time resume attempts that failed").inc()
    report["seconds"] = round(time.monotonic() - t0, 3)
    _LAST_REPORT = report
    return report


def wait_for_recoveries(timeout: Optional[float] = None) -> None:
    """Join every background resume started this process (tests).
    Joins the _finish THREADS, not just the jobs: the resumed model is
    dkv.put by _finish after its job.join returns, so a job-only wait
    races the model registration."""
    for th in list(_LIVE_FINISHERS):
        th.join(timeout)
    for job in list(_LIVE_JOBS):
        try:
            job.join(timeout)
        except RuntimeError:
            pass   # the failed-resume warn already fired in _finish


def last_report() -> Optional[Dict[str, Any]]:
    return _LAST_REPORT
