"""Memory-pressure: budget, LRU spill, streaming GBM + GLM training
(water/Cleaner.java + MemoryManager.java analogs, SURVEY §7.1.7)."""
import os

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import memman


@pytest.fixture(autouse=True)
def _restore_budget():
    yield
    memman.reset()     # back to unlimited for other tests


def _frame(n=60_000, f=8, seed=0, classification=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    logit = X[:, 0] - 0.7 * X[:, 1] + 0.4 * X[:, 2]
    cols = {f"x{i}": X[:, i] for i in range(f)}
    if classification:
        y = (rng.random(n) < 1 / (1 + np.exp(-logit)))
        cols["resp"] = np.array(["n", "y"], dtype=object)[y.astype(int)]
    else:
        cols["resp"] = (logit + 0.2 * rng.normal(size=n)).astype(np.float32)
    return h2o.Frame.from_numpy(cols)


def test_lru_spill_and_rematerialize():
    memman.reset(budget=1_000_000)      # ~1MB device budget
    vecs = []
    for i in range(8):
        v = h2o.Frame.from_numpy(
            {"c": np.arange(50_000, dtype=np.float64) + i}).vec("c")
        vecs.append(v)
    st = memman.manager().stats()
    assert st["spill_count"] > 0        # early vecs were evicted
    # spilled vec re-materializes transparently with exact values
    first = vecs[0]
    assert first._dev is None or True   # may or may not be the evictee
    got = np.asarray(first.to_numpy())
    assert got[1] == 1.0 and got[-1] == 49_999.0


def test_streaming_gbm_trains_beyond_budget():
    # budget ~0.5MB << 60k x 8 x 4B = 1.9MB design: forces X_host mode
    memman.reset(budget=500_000)
    fr = _frame(classification=True)
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, nbins=16,
                                       seed=1, score_tree_interval=0)
    gbm.train(y="resp", training_frame=fr)
    m = gbm.model
    assert m.output.get("streamed") is True
    assert m.training_metrics.auc > 0.75
    # the model predicts densely like any other tree model
    memman.reset()
    pred = m.predict(fr)
    assert pred.nrow == fr.nrow


def test_streaming_glm_matches_dense():
    fr = _frame(n=40_000, classification=False, seed=3)
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    memman.reset()                       # dense reference fit
    dense = H2OGeneralizedLinearEstimator(family="gaussian", Lambda=[0.0])
    dense.train(y="resp", training_frame=fr)
    dense_coef = dense.model.coef()
    memman.reset(budget=400_000)         # force streaming
    st = H2OGeneralizedLinearEstimator(family="gaussian", Lambda=[0.0])
    st.train(y="resp", training_frame=fr)
    assert st.model.output.get("streamed") is True
    sc = st.model.coef()
    for k, v in dense_coef.items():
        assert abs(sc[k] - v) < 5e-3, (k, sc[k], v)


def test_cloud_memory_report():
    memman.reset(budget=123_456_789)
    from h2o3_tpu.api import schemas
    cloud = schemas.cloud_v3()
    node = cloud["nodes"][0]
    assert node.get("device_budget_bytes") == 123_456_789
    assert "spill_count" in node


def test_streaming_unsupported_algo_fails_fast():
    memman.reset(budget=300_000)
    fr = _frame(n=30_000, classification=True, seed=9)
    from h2o3_tpu.models.drf import H2ORandomForestEstimator
    drf = H2ORandomForestEstimator(ntrees=2, max_depth=3)
    with pytest.raises(RuntimeError, match="streaming"):
        drf.train(y="resp", training_frame=fr)
