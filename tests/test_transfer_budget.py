"""Transfer-minimal pipelines (ISSUE 5): budgets asserted via the
telemetry byte counters, not eyeballed — streamed per-chunk ingest
equivalence + overlap, streamed-GBM once-per-tree uploads + dense/
streamed bit parity, multinomial finalize without the O(n·K) host
fetch, and pipeline-labeled transfer attribution. All CPU-backend
safe. The two multi-second streamed-GBM trains ride the established
slow tier (conftest: sharded-parity-class tests run with --runslow /
-m slow), keeping the default tier inside its wall-clock budget.
"""
import importlib
import os

import numpy as np
import numpy.testing as npt
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import memman, telemetry

parse_mod = importlib.import_module("h2o3_tpu.ingest.parse")


@pytest.fixture(autouse=True)
def _restore_budget():
    yield
    memman.reset()


def _counter(name, labels=None):
    return telemetry.registry().value(name, labels)


# ------------------------------------------------------------ ingest


def _mixed_csv(path, n=12_000, seed=0):
    rng = np.random.default_rng(seed)
    cities = ["ames", "berlin", "cairo", "delhi"]
    with open(path, "w") as f:
        f.write("a,b,c,t,e\n")
        for _ in range(n):
            a = f"{rng.normal():.6g}" if rng.random() > 0.01 else "NA"
            b = str(int(rng.integers(-100, 100)))
            c = f"{rng.normal() * 1e6:.6g}"
            t = f"2020-01-{1 + int(rng.integers(0, 28)):02d}"
            e = cities[int(rng.integers(0, 4))]
            f.write(f"{a},{b},{c},{t},{e}\n")


def test_parse_streamed_equivalence(tmp_path, monkeypatch):
    """Per-chunk device-put path produces bit-identical columns (host
    AND device views) to the host-merge path, and reports the overlap
    ratio + ingest-labeled h2d bytes."""
    import jax
    path = str(tmp_path / "mixed.csv")
    _mixed_csv(path)
    monkeypatch.setattr(parse_mod, "_PARALLEL_PARSE_BYTES", 1 << 12)
    # the suite's conftest forces an 8-device mesh, where auto-streaming
    # stays off (single-shard gate) — force it for the equivalence check
    monkeypatch.setenv("H2O3_INGEST_STREAM", "1")
    setup = parse_mod.parse_setup(path)
    ingest_h2d0 = _counter("h2o3_h2d_pipeline_bytes_total",
                           {"pipeline": "ingest"})
    fr_stream = parse_mod.parse([path], setup)
    prof = dict(parse_mod.LAST_PROFILE)
    assert prof["streamed"] is True
    assert prof["chunks"] > 1
    assert prof["h2d_overlap_ratio"] is not None
    assert 0.0 <= prof["h2d_overlap_ratio"] <= 1.0
    # the per-chunk puts are attributed to the ingest pipeline
    assert _counter("h2o3_h2d_pipeline_bytes_total",
                    {"pipeline": "ingest"}) > ingest_h2d0
    monkeypatch.setenv("H2O3_INGEST_STREAM", "0")
    fr_merge = parse_mod.parse([path], setup)
    assert dict(parse_mod.LAST_PROFILE)["streamed"] is False
    for name in fr_stream.names:
        v1, v2 = fr_stream.vec(name), fr_merge.vec(name)
        assert v1.type == v2.type and v1.domain == v2.domain
        a1, a2 = v1.to_numpy(), v2.to_numpy()
        if a1.dtype.kind == "O":
            assert (a1 == a2).all(), name
        else:
            npt.assert_array_equal(a1, a2, err_msg=name)
        if v1.data is not None:
            npt.assert_array_equal(
                np.asarray(jax.device_get(v1.data)),
                np.asarray(jax.device_get(v2.data)),
                err_msg=f"{name} device")


def test_parse_streamed_wide_int_falls_back_exact(tmp_path, monkeypatch):
    """Wide ints (beyond float64's 2^53) must keep their exact int64
    merge — the streamer hands those columns back to the host path."""
    path = str(tmp_path / "wide.csv")
    base = (1 << 60) + 7
    n = 4000
    with open(path, "w") as f:
        f.write("id,v\n")
        for i in range(n):
            f.write(f"{base + i},{i % 97}\n")
    monkeypatch.setattr(parse_mod, "_PARALLEL_PARSE_BYTES", 1 << 10)
    monkeypatch.setenv("H2O3_INGEST_STREAM", "1")
    fr = parse_mod.parse([path], parse_mod.parse_setup(path))
    got = fr.vec("id").to_numpy()
    assert got.dtype == np.int64
    assert got[0] == base and got[-1] == base + n - 1


# ------------------------------------------------------- streamed GBM


def _gbm_frame(n, f, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    logit = X[:, 0] - 0.7 * X[:, 1] + 0.4 * X[:, 2]
    cols = {f"x{i}": X[:, i] for i in range(f)}
    cols["resp"] = np.array(["n", "y"], dtype=object)[
        (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)]
    return h2o.Frame.from_numpy(cols)


_GBM_PARAMS = dict(ntrees=3, max_depth=3, nbins=16, seed=1,
                   score_tree_interval=0, stopping_rounds=0)


@pytest.mark.slow
def test_streamed_gbm_bit_parity_with_dense():
    """A fully-resident streamed train is BIT-IDENTICAL to the dense
    device path: same trees (feat/thr/values) and same predictions —
    the streamed kernels, margin updates and lr scaling reproduce the
    dense arithmetic exactly (ISSUE 5 satellite).

    Pinned to a 1-data-shard mesh: the dense path reduces histograms
    with an n-shard psum whose accumulation order differs from the
    streamed chunk sum, so exact equality is only defined shard-free
    (the suite's conftest forces an 8-device virtual mesh)."""
    import jax
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.parallel import mesh as mesh_mod
    old_mesh = mesh_mod.current_mesh()
    mesh_mod.set_mesh(mesh_mod.make_mesh(n_data=1,
                                         devices=jax.devices()[:1]))
    try:
        memman.reset()
        fr = _gbm_frame(8000, 6)
        dense = H2OGradientBoostingEstimator(**_GBM_PARAMS)
        dense.train(y="resp", training_frame=fr)
        assert not dense.model.output.get("streamed")
        # budget: too small for frame+design (forces streaming), large
        # enough that the resident window holds the whole design matrix
        memman.reset(budget=460_000)
        fr2 = _gbm_frame(8000, 6)
        st = H2OGradientBoostingEstimator(**_GBM_PARAMS)
        st.train(y="resp", training_frame=fr2)
        assert st.model.output.get("streamed") is True
        sp = st.model.output["stream_profile"]
        assert sp["resident_chunks"] == sp["chunks"] == 1
        da, sa = dense.model._save_arrays(), st.model._save_arrays()
        for k in ("feat", "thr", "value", "na_left", "is_split"):
            npt.assert_array_equal(da[k], sa[k], err_msg=k)
        memman.reset()
        pd = dense.model.predict(fr).vec("py").to_numpy()
        ps = st.model.predict(fr).vec("py").to_numpy()
        npt.assert_array_equal(pd, ps)
    finally:
        mesh_mod.set_mesh(old_mesh)


@pytest.mark.slow
def test_streamed_gbm_uploads_once_per_tree():
    """Multi-chunk streamed train under a resident-window budget: h2d
    bytes per tree stay ≤ 1.1× the dataset's device footprint (each
    chunk crosses the bus once per TRAIN, not once per level — the old
    path paid levels × footprint per tree)."""
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    if not telemetry.enabled():
        pytest.skip("telemetry disabled")
    n, f = 32_768, 8
    x_bytes = n * f * 4
    memman.reset(budget=int(2.2 * x_bytes))
    fr = _gbm_frame(n, f, seed=3)
    train_h2d0 = _counter("h2o3_h2d_pipeline_bytes_total",
                          {"pipeline": "train"})
    gbm = H2OGradientBoostingEstimator(**_GBM_PARAMS)
    gbm.train(y="resp", training_frame=fr)
    m = gbm.model
    assert m.output.get("streamed") is True
    sp = m.output["stream_profile"]
    assert sp["chunks"] > 1, sp
    assert sp["resident_chunks"] == sp["chunks"], sp
    # steady-state per-tree traffic excludes the once-per-train window
    # upload — which itself must stay ~one dataset footprint (X plus the
    # y/w/margin working vectors)
    assert sp["h2d_bytes_per_tree"] <= 1.1 * sp["device_footprint_bytes"], sp
    assert sp["h2d_resident_bytes"] <= 1.6 * sp["device_footprint_bytes"], sp
    assert sp["h2d_bytes"] <= (sp["h2d_resident_bytes"]
                               + 1.1 * _GBM_PARAMS["ntrees"]
                               * sp["device_footprint_bytes"]), sp
    # the uploads are attributed to the train pipeline
    assert _counter("h2o3_h2d_pipeline_bytes_total",
                    {"pipeline": "train"}) > train_h2d0


# ------------------------------------------------- multinomial metrics


def _host_multinomial_reference(p, y, w):
    """Pure-numpy reference of the pre-change host implementation."""
    n, K = p.shape
    py = p[np.arange(n), y]
    ll = -(w * np.log(np.clip(py, 1e-7, 1.0))).sum() / w.sum()
    pred = p.argmax(1)
    err = (w * (pred != y)).sum() / w.sum()
    cm = np.zeros((K, K))
    np.add.at(cm, (y, pred), w)
    mse = (w * (1.0 - py) ** 2).sum() / w.sum()
    ranks = np.argsort(-p, axis=1, kind="stable")
    hits = ranks == y[:, None]
    hr = np.cumsum(hits.mean(axis=0))[: min(K, 10)]
    return ll, err, cm, mse, hr


def test_multinomial_finalize_no_onk_fetch():
    """Device-side multinomial metrics: the counted d2h bytes during
    finalize stay far below one [n, K] probability fetch, and every
    aggregate matches the host reference."""
    from sklearn import metrics as skm
    from h2o3_tpu.models.metrics import make_multinomial_metrics
    if not telemetry.enabled():
        pytest.skip("telemetry disabled")
    rng = np.random.default_rng(5)
    n, K = 20_000, 4
    y = rng.integers(0, K, n)
    logits = rng.normal(0, 1, (n, K))
    logits[np.arange(n), y] += 1.2
    p = (np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
         ).astype(np.float32)
    w = np.ones(n, np.float32)
    d2h0 = _counter("h2o3_d2h_bytes_total")
    m = make_multinomial_metrics(p, y, w)
    fetched = _counter("h2o3_d2h_bytes_total") - d2h0
    probs_bytes = n * K * 4
    assert fetched < 0.25 * probs_bytes, (fetched, probs_bytes)
    ll, err, cm, mse, hr = _host_multinomial_reference(
        p.astype(np.float64), y, w.astype(np.float64))
    assert m.logloss == pytest.approx(ll, rel=1e-4)
    assert m.error == pytest.approx(err, abs=1e-6)
    npt.assert_allclose(m.confusion_matrix, cm, atol=0.5)
    assert m.mse == pytest.approx(mse, rel=1e-4)
    npt.assert_allclose(m.hit_ratios, hr, atol=1e-5)
    # OVR AUC via the on-device 2^17-bucket sketch: macro average within
    # the sketch's quantisation bound of sklearn's exact computation
    ref_auc = skm.roc_auc_score(y, p, multi_class="ovr", average="macro")
    assert m.auc == pytest.approx(ref_auc, abs=2e-3)


def test_multinomial_gbm_trains_with_device_metrics():
    """End-to-end: a multinomial GBM's finalize runs on the device
    metric kernels (hit ratios / cm / auc populated, no crash)."""
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    rng = np.random.default_rng(9)
    n = 3000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["resp"] = np.array(["a", "b", "c"], dtype=object)[y]
    fr = h2o.Frame.from_numpy(cols)
    gbm = H2OGradientBoostingEstimator(ntrees=2, max_depth=3, seed=1)
    gbm.train(y="resp", training_frame=fr)
    mm = gbm.model.training_metrics
    assert mm.confusion_matrix.shape == (3, 3)
    assert len(mm.hit_ratios) == 3
    assert 0.0 < mm.logloss < 1.2
    assert mm.auc is not None and 0.5 < mm.auc <= 1.0


# --------------------------------------------------- pipeline labels


def test_transfer_bytes_pipeline_attribution():
    """record_h2d/record_d2h label bytes by pipeline — explicitly or
    inferred from the open span on the calling thread."""
    if not telemetry.enabled():
        pytest.skip("telemetry disabled")
    r = telemetry.registry()
    a0 = r.value("h2o3_d2h_pipeline_bytes_total", {"pipeline": "analytics"})
    telemetry.record_d2h(100, pipeline="analytics")
    assert r.value("h2o3_d2h_pipeline_bytes_total",
                   {"pipeline": "analytics"}) == a0 + 100
    s0 = r.value("h2o3_d2h_pipeline_bytes_total", {"pipeline": "serve"})
    with telemetry.span("serve.decode"):
        telemetry.record_d2h(50)
    assert r.value("h2o3_d2h_pipeline_bytes_total",
                   {"pipeline": "serve"}) == s0 + 50
    t0 = r.value("h2o3_d2h_bytes_total")
    telemetry.record_d2h(25)           # no span, no label: total only
    assert r.value("h2o3_d2h_bytes_total") == t0 + 25
