"""ARFF / SVMLight / parquet / ORC parsers + parallel CSV byte-range
parse (reference: water/parser/{ARFFParser,SVMLightParser}, h2o-parsers,
ParseDataset.java:623 chunked parse)."""
import os

import numpy as np
import pytest

import h2o3_tpu as h2o


def test_arff_roundtrip(tmp_path):
    p = tmp_path / "t.arff"
    p.write_text("""% comment
@RELATION test
@ATTRIBUTE sepal_len NUMERIC
@ATTRIBUTE species {setosa, versicolor, virginica}
@ATTRIBUTE note STRING
@DATA
5.1, setosa, 'hello'
4.9, virginica, world
?, versicolor, ?
""")
    fr = h2o.import_file(str(p))
    assert fr.names == ["sepal_len", "species", "note"]
    assert fr.nrow == 3
    x = fr.vec("sepal_len").to_numpy()
    np.testing.assert_allclose(x[:2], [5.1, 4.9])
    assert np.isnan(x[2])
    assert fr.vec("species").domain == ("setosa", "versicolor",
                                        "virginica")
    assert fr.vec("species").to_strings()[1] == "virginica"


def test_svmlight(tmp_path):
    p = tmp_path / "t.svm"
    p.write_text("""1 1:0.5 3:2.0
-1 2:1.5  # comment
1 1:1.0 2:-1.0 3:0.25
""")
    fr = h2o.import_file(str(p))
    assert fr.nrow == 3
    assert fr.ncol == 4            # target + 3 dense features
    np.testing.assert_allclose(fr.vec("C1").to_numpy(), [1, -1, 1])
    np.testing.assert_allclose(fr.vec("C2").to_numpy(), [0.5, 0, 1.0])
    np.testing.assert_allclose(fr.vec("C4").to_numpy(), [2.0, 0, 0.25])


def test_parquet_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(0)
    n = 500
    tbl = pa.table({
        "num": rng.normal(size=n),
        "int": rng.integers(0, 100, n),
        "cat": pa.array(np.array(["a", "b", "c"], dtype=object)[
            rng.integers(0, 3, n)]).dictionary_encode(),
        "txt": [f"s{i}" for i in range(n)],
    })
    p = str(tmp_path / "t.parquet")
    pq.write_table(tbl, p)
    fr = h2o.import_file(p)
    assert fr.nrow == n
    np.testing.assert_allclose(fr.vec("num").to_numpy(),
                               tbl.column("num").to_numpy(), rtol=1e-6)
    assert fr.vec("cat").is_categorical
    assert set(fr.vec("cat").domain) == {"a", "b", "c"}
    assert fr.vec("txt").to_strings()[3] == "s3"


def test_orc_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.orc as po
    n = 200
    rng = np.random.default_rng(1)
    tbl = pa.table({"x": rng.normal(size=n),
                    "y": rng.integers(0, 5, n)})
    p = str(tmp_path / "t.orc")
    po.write_table(tbl, p)
    fr = h2o.import_file(p)
    assert fr.nrow == n
    np.testing.assert_allclose(fr.vec("x").to_numpy(),
                               tbl.column("x").to_numpy(), rtol=1e-6)


def test_avro_truncated_rejected(tmp_path):
    """avro is now parsed natively (tests/test_formats2.py); a magic-only
    truncated file must fail cleanly, not crash the tokenizer."""
    p = tmp_path / "t.avro"
    p.write_bytes(b"Obj\x01")
    with pytest.raises(ValueError, match="truncated or malformed"):
        h2o.import_file(str(p))


def test_parallel_csv_matches_serial(tmp_path):
    import importlib
    parse_mod = importlib.import_module("h2o3_tpu.ingest.parse")
    rng = np.random.default_rng(2)
    n = 40000
    lines = ["a,b,c"]
    cats = np.array(["x", "y", "z"])
    for i in range(n):
        lines.append(f"{rng.normal():.6f},{cats[i % 3]},{i}")
    p = str(tmp_path / "big.csv")
    with open(p, "w") as f:
        f.write("\n".join(lines) + "\n")
    serial = h2o.import_file(p)
    old = parse_mod._PARALLEL_PARSE_BYTES
    parse_mod._PARALLEL_PARSE_BYTES = 1 << 16     # force the fan-out
    try:
        par = h2o.import_file(p)
    finally:
        parse_mod._PARALLEL_PARSE_BYTES = old
    assert par.nrow == serial.nrow == n
    np.testing.assert_allclose(par.vec("a").to_numpy(),
                               serial.vec("a").to_numpy())
    np.testing.assert_allclose(par.vec("c").to_numpy(),
                               serial.vec("c").to_numpy())
    assert list(par.vec("b").to_strings()[:6]) == list(serial.vec("b").to_strings()[:6])


def test_file_uri_scheme(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text("a,b\n1,2\n3,4\n")
    fr = h2o.import_file(f"file://{p}")
    assert fr.nrow == 2
    np.testing.assert_allclose(fr.vec("a").to_numpy(), [1, 3])
