"""Device-resident train path (ISSUE 2): sketch parity, compile-count
regression guards, pipelined scoring semantics, and the no-full-X-fetch
contract.

- the device-side global sketch (ops/binning.bin_matrix_device) must
  produce BIT-IDENTICAL edges/codes to the host bin_matrix on numeric,
  categorical, NA, tied, and infinite inputs — it replicates np.quantile's
  float64 lerp on device-gathered rank neighbours;
- a warm train must trigger ZERO XLA compiles, and ntrees/sample-rate/
  learn-rate grid variants must reuse the bucket executables (traced
  rates + chunk-length buckets);
- interval scoring is pipelined (chunk k+1 dispatched before chunk k's
  scalars are fetched) — the scoring history cadence and the early-stop
  tree count must match the serial semantics exactly;
- the default train path never device_gets anything within 2x of the
  full X matrix (the old global-sketch path fetched all of X).
"""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

from _compile_counter import count_compiles  # noqa: E402 — shared harness


# --------------------------------------------------- device sketch parity


def _pad(col, pad):
    out = np.full(pad, np.nan, np.float32)
    out[: len(col)] = col
    return out


def _parity_case(X, names, is_cat, nrow, nbins, nbins_cats, hist):
    import jax.numpy as jnp
    from h2o3_tpu.ops.binning import bin_matrix, bin_matrix_device
    bmh = bin_matrix(np.asarray(X), names, is_cat, nrow, nbins=nbins,
                     nbins_cats=nbins_cats, histogram_type=hist)
    bmd = bin_matrix_device(jnp.asarray(X), names, is_cat, nrow, nbins=nbins,
                            nbins_cats=nbins_cats, histogram_type=hist)
    assert bmh.n_bins == bmd.n_bins
    for f in range(len(names)):
        assert np.array_equal(bmh.edges[f], bmd.edges[f]), \
            (hist, names[f], bmh.edges[f], bmd.edges[f])
    assert np.array_equal(np.asarray(bmh.codes.rm), np.asarray(bmd.codes.rm))


@pytest.mark.parametrize("hist", ["quantiles_global", "uniform_adaptive"])
def test_device_sketch_edges_match_host(hist):
    rng = np.random.default_rng(7)
    n, pad = 3000, 3072
    X = np.stack([
        _pad(rng.normal(size=n).astype(np.float32), pad),        # numeric
        _pad(np.round(rng.normal(size=n) * 2).astype(np.float32),
             pad),                                               # heavy ties
        _pad(rng.integers(0, 5, n).astype(np.float32), pad),     # cat id bins
        _pad(rng.integers(0, 200, n).astype(np.float32), pad),   # wide cat
        _pad(rng.normal(size=n).astype(np.float32), pad),        # NA-heavy
        np.full(pad, np.nan, np.float32),                        # all-NA
        _pad(np.full(n, 3.25, np.float32), pad),                 # constant
    ], axis=1)
    X[rng.random(pad) < 0.3, 4] = np.nan
    X[11, 0] = np.inf
    X[12, 0] = -np.inf          # non-finite must not skew ranks
    names = list("abcdefg")
    is_cat = [False, False, True, True, False, False, False]
    _parity_case(X, names, is_cat, n, nbins=16, nbins_cats=64, hist=hist)


def test_device_sketch_trains_global_hist():
    rng = np.random.default_rng(1)
    n = 3000
    x = rng.normal(size=(n, 3)).astype(np.float32)
    y = x[:, 0] * 2 + rng.normal(size=n) * 0.1
    fr = h2o.Frame.from_numpy({"a": x[:, 0], "b": x[:, 1], "c": x[:, 2],
                               "y": y})
    gbm = H2OGradientBoostingEstimator(ntrees=20, max_depth=3, seed=1,
                                       learn_rate=0.3,
                                       histogram_type="quantiles_global",
                                       nbins=24)
    gbm.train(y="y", training_frame=fr)
    assert gbm.model.training_metrics.r2 > 0.9


def test_default_path_never_fetches_full_x(monkeypatch):
    """Acceptance bar: no device_get within 2x of the full X matrix on
    the default (non-scoring) train path — the sketch, score, and
    finalize fetches are all O(F·nbins) / O(trees) / scalars."""
    import jax
    rng = np.random.default_rng(2)
    n, F = 50_000, 8
    cols = {f"c{i}": rng.normal(size=n).astype(np.float32) for i in range(F)}
    cols["y"] = (cols["c0"] * 3 + rng.normal(size=n)).astype(np.float32)
    fr = h2o.Frame.from_numpy(cols)
    x_bytes = n * F * 4
    fetches = []
    real_get = jax.device_get

    def spy(tree):
        tot = 0
        for leaf in jax.tree.leaves(tree):
            tot += getattr(leaf, "nbytes", 0) or 0
        fetches.append(tot)
        return real_get(tree)

    monkeypatch.setattr(jax, "device_get", spy)
    gbm = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=3,
                                       histogram_type="quantiles_global",
                                       nbins=20)
    gbm.train(y="y", training_frame=fr)
    monkeypatch.undo()
    assert gbm.model.ntrees_built == 8
    assert fetches, "expected some scalar/summary fetches"
    assert max(fetches) < x_bytes // 2, \
        f"a device_get moved {max(fetches)} bytes (X is {x_bytes})"


# ------------------------------------------------ compile-count regression


def _small_frame(seed=5, n=4096, F=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + rng.normal(size=n) > 0).astype(np.float32)
    cols = {f"c{i}": X[:, i] for i in range(F)}
    cols["y"] = y
    return h2o.Frame.from_numpy(cols)


def _train(fr, **kw):
    p = dict(ntrees=10, max_depth=3, seed=1, distribution="bernoulli",
             min_rows=1.0)
    p.update(kw)
    g = H2OGradientBoostingEstimator(**p)
    g.train(y="y", training_frame=fr)
    return g.model


def test_warm_train_zero_recompiles():
    fr = _small_frame()
    _train(fr)                       # cold: compiles everything
    events = []
    with count_compiles(events):
        m = _train(fr)               # identical warm run
    assert m.ntrees_built == 10
    assert len(events) == 0, f"warm train compiled {len(events)} modules"


def test_grid_variants_reuse_bucket_executables():
    """Chunk lengths round up to a bucket with the tail masked by the
    traced n_active, and sample/col/learn rates ride as traced scalars —
    so a grid variant whose bucket is warm compiles NOTHING."""
    fr = _small_frame(seed=6)
    _train(fr, ntrees=10)            # warms bucket {10}
    events = []
    with count_compiles(events):
        m = _train(fr, ntrees=9, sample_rate=0.7, learn_rate=0.05,
                   col_sample_rate=0.8)
    assert m.ntrees_built == 9       # bucket 10, one masked tree
    assert len(events) == 0, f"variant compiled {len(events)} modules"


def test_cold_compile_budget():
    """Time-to-first-model guard: a cold train must stay under a fixed
    compile-module budget (measured ~51 on this path; generous headroom
    for jaxlib drift — catching 2x regressions is the point)."""
    fr = _small_frame(seed=9, n=2560, F=4)
    events = []
    with count_compiles(events):
        _train(fr, ntrees=7, max_depth=2, distribution="gaussian")
    assert len(events) <= 90, f"cold train compiled {len(events)} modules"


# ------------------------------------------------------ pipelined scoring


def test_scoring_history_cadence_pipelined():
    fr = _small_frame(seed=8)
    m = _train(fr, ntrees=6, score_tree_interval=2)
    hist = [e["ntrees"] for e in m.scoring_history]
    assert hist == [2, 4, 6]
    assert m.ntrees_built == 6
    assert all(np.isfinite(e["deviance"]) for e in m.scoring_history)


def test_early_stop_discards_speculative_chunk():
    """With early stopping the pipeline dispatches one chunk ahead; a
    stop verdict must discard it — built trees end exactly at the last
    SCORED interval, like the serial loop."""
    rng = np.random.default_rng(3)
    n = 3000
    x = rng.normal(size=n).astype(np.float32)
    y = 2 * x + rng.normal(size=n).astype(np.float32) * 0.01
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    g = H2OGradientBoostingEstimator(ntrees=200, max_depth=3, learn_rate=0.3,
                                     stopping_rounds=2,
                                     stopping_tolerance=5e-2,
                                     score_tree_interval=5, seed=3)
    g.train(y="y", training_frame=fr)
    m = g.model
    assert m.ntrees_built < 200
    assert m.ntrees_built % 5 == 0
    assert m.scoring_history[-1]["ntrees"] == m.ntrees_built


def test_stopping_metric_auc_trains():
    """stopping_metric='auc' used to crash on an import of a kernel that
    no longer existed; it now early-stops on the device-sketch AUC."""
    fr = _small_frame(seed=12)
    m = _train(fr, ntrees=60, stopping_rounds=2, stopping_metric="auc",
               score_tree_interval=5, stopping_tolerance=0.5)
    assert m.ntrees_built <= 60
    assert any("auc" in e for e in m.scoring_history)
    aucs = [e["auc"] for e in m.scoring_history if "auc" in e]
    assert all(0.0 <= a <= 1.0 for a in aucs)


def test_auc_device_matches_exact_sweep():
    from h2o3_tpu.models.metrics import auc_device, make_binomial_metrics
    rng = np.random.default_rng(4)
    n = 20_000
    y = (rng.random(n) < 0.4).astype(np.float32)
    p = np.clip(0.4 * y + rng.random(n) * 0.8, 0, 1).astype(np.float32)
    w = np.ones(n, np.float32)
    exact = make_binomial_metrics(p, y, w).auc
    sketch = float(np.asarray(auc_device(p, y, w)))
    assert abs(exact - sketch) < 5e-3


# ------------------------------------------------- combinator compile cache


def _sum_shard(x):
    import jax.numpy as jnp
    return jnp.nansum(x)


def test_map_reduce_caches_named_fns_and_skips_lambdas():
    from h2o3_tpu.parallel.map_reduce import (_cacheable,
                                              _compiled_map_reduce,
                                              map_reduce)
    assert _cacheable(_sum_shard, "sum")
    assert not _cacheable(lambda x: x, "sum")        # identity-keyed: skip
    assert not _cacheable(_sum_shard, [1, 2])        # unhashable: skip

    def nested(x):
        return x
    assert not _cacheable(nested, "sum")             # per-call def: skip

    rng = np.random.default_rng(1)
    data = rng.normal(size=4096).astype(np.float32)
    fr = h2o.Frame.from_numpy({"c": data})
    v = fr.vec("c")
    before = _compiled_map_reduce.cache_info().hits
    r1 = float(map_reduce(_sum_shard, v.data))
    r2 = float(map_reduce(_sum_shard, v.data))       # cached callable
    assert _compiled_map_reduce.cache_info().hits > before
    assert abs(r1 - float(np.nansum(data))) < 1e-2
    assert r1 == r2
    # lambda path still works (uncached, the pre-cache behavior)
    r3 = float(map_reduce(lambda x: _sum_shard(x), v.data))
    assert abs(r3 - r1) < 1e-6


# ------------------------------------------------------- ingest grouping


def test_from_typed_column_groups_matches_from_typed_columns():
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.ingest.chunk import EncodedColumn
    from h2o3_tpu.frame.vec import T_ENUM, T_REAL, T_TIME
    rng = np.random.default_rng(10)
    n = 1000
    num = EncodedColumn(T_REAL, rng.normal(size=n))
    enum = EncodedColumn(T_ENUM, rng.integers(0, 3, n).astype(np.int32),
                         domain=["a", "b", "c"])
    ms = (np.datetime64("2020-01-01", "ms").astype(np.int64)
          + rng.integers(0, 10**9, n))
    tm = EncodedColumn(T_TIME, ms)
    names = ["n", "e", "t"]
    a = Frame.from_typed_columns(names, [num, enum, tm])
    pulled = []

    def groups():
        pulled.append("num")
        yield [(0, num), (2, tm)]
        pulled.append("enum")
        yield [(1, enum)]

    b = Frame.from_typed_column_groups(names, groups(), 3)
    assert pulled == ["num", "enum"]
    assert a.names == b.names
    for nm in names:
        va, vb = a.vec(nm), b.vec(nm)
        assert va.type == vb.type
        assert va.domain == vb.domain
        assert np.array_equal(va.to_numpy(), vb.to_numpy(), equal_nan=True)
