"""Metrics golden tests vs sklearn (the reference asserts metric bounds in
h2o-test-accuracy; we can be tighter: exact cross-checks)."""
import numpy as np
import pytest
from sklearn import metrics as skm

from h2o3_tpu.models.metrics import (make_binomial_metrics,
                                     make_multinomial_metrics,
                                     make_regression_metrics)


def test_regression_metrics_match_sklearn():
    rng = np.random.default_rng(0)
    y = rng.normal(10, 3, 2000)
    p = y + rng.normal(0, 1, 2000)
    m = make_regression_metrics(p, y)
    assert m.mse == pytest.approx(skm.mean_squared_error(y, p), rel=1e-4)
    assert m.mae == pytest.approx(skm.mean_absolute_error(y, p), rel=1e-4)
    assert m.r2 == pytest.approx(skm.r2_score(y, p), rel=1e-3)


def test_auc_matches_sklearn_with_ties():
    rng = np.random.default_rng(1)
    y = (rng.random(5000) < 0.3).astype(float)
    # coarse scores → many ties
    p = np.round(rng.random(5000) * 0.5 + y * 0.3, 2)
    m = make_binomial_metrics(p, y)
    assert m.auc == pytest.approx(skm.roc_auc_score(y, p), abs=1e-5)
    assert m.logloss == pytest.approx(skm.log_loss(y, np.clip(p, 1e-15, 1 - 1e-15)),
                                      rel=1e-4)
    assert m.gini == pytest.approx(2 * m.auc - 1)


def test_auc_weighted():
    rng = np.random.default_rng(2)
    y = (rng.random(1000) < 0.4).astype(float)
    p = rng.random(1000)
    w = rng.integers(1, 5, 1000).astype(float)
    m = make_binomial_metrics(p, y, w)
    assert m.auc == pytest.approx(skm.roc_auc_score(y, p, sample_weight=w), abs=1e-5)


def test_binomial_confusion_and_f1():
    y = np.array([0, 0, 1, 1, 1, 0, 1, 0])
    p = np.array([0.1, 0.4, 0.35, 0.8, 0.9, 0.2, 0.7, 0.6])
    m = make_binomial_metrics(p, y)
    # best F1 threshold must reproduce sklearn's best over the PR curve
    prec, rec, thr = skm.precision_recall_curve(y, p)
    f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-30)
    assert m.max_f1 == pytest.approx(np.nanmax(f1), abs=1e-6)
    tn, fp, fn, tp = m.confusion_matrix.ravel()
    assert tn + fp + fn + tp == 8


def test_multinomial_metrics():
    rng = np.random.default_rng(3)
    K, n = 4, 3000
    y = rng.integers(0, K, n)
    logits = rng.normal(0, 1, (n, K))
    logits[np.arange(n), y] += 1.5
    p = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    m = make_multinomial_metrics(p, y)
    assert m.logloss == pytest.approx(skm.log_loss(y, p), rel=1e-4)
    assert m.error == pytest.approx(1 - skm.accuracy_score(y, p.argmax(1)), abs=1e-6)
    np.testing.assert_allclose(m.confusion_matrix,
                               skm.confusion_matrix(y, p.argmax(1)), atol=0.5)
    assert m.hit_ratios[0] == pytest.approx(skm.accuracy_score(y, p.argmax(1)), abs=1e-6)
