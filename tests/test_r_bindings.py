"""R client package artifacts: the generated estimator surface must
stay in sync with the live builder registry (gen_R analog of the
python bindings parity test). No R interpreter ships in this image
(limitation recorded in h2o-r/h2o/DESCRIPTION), so structural checks —
brace/paren balance, one function per algo, parameter-name parity with
the live metadata — are the testable contract."""
import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GEN = os.path.join(ROOT, "h2o-r", "h2o", "R", "estimators_gen.R")


def test_generated_estimators_cover_registry():
    from h2o3_tpu.api.server import _builders, _model_builder_meta
    from tools.gen_R import R_NAME
    src = open(GEN).read()
    assert src.count("{") == src.count("}")
    assert src.count("(") == src.count(")")
    fns = set(re.findall(r"^(h2o\.\w+) <- function", src, re.M))
    expected = {R_NAME[a] for a in _builders() if a in R_NAME}
    assert fns == expected, fns ^ expected
    # spot-check parameter parity for gbm against live metadata
    meta = _model_builder_meta({}, None, "gbm")
    params = {p["name"] for p in
              meta["model_builders"]["gbm"]["parameters"]}
    gbm_src = src.split("h2o.gbm <- function", 1)[1].split("\n}\n", 1)[0]
    for name in ("ntrees", "max_depth", "learn_rate", "histogram_type",
                 "sample_rate"):
        assert name in params, name
        assert re.search(rf"^\s*{name} = ", gbm_src, re.M), name
    # validation_frame is a standard generated argument
    assert re.search(r"^\s*validation_frame = NULL", gbm_src, re.M)


def test_handwritten_plumbing_has_no_estimator_dupes():
    base = open(os.path.join(ROOT, "h2o-r", "h2o", "R", "h2o.R")).read()
    gen = open(GEN).read()
    gen_fns = set(re.findall(r"^(h2o\.\w+) <- function", gen, re.M))
    base_fns = set(re.findall(r"^(h2o\.\w+) <- function", base, re.M))
    assert not (gen_fns & base_fns), gen_fns & base_fns
