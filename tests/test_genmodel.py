"""genmodel breadth: GLM/KMeans/DeepLearning MOJO round-trips, POJO
codegen, EasyPredict row API.

Reference: hex/genmodel/algos/{glm,kmeans,deeplearning} readers (wire
contracts), hex/tree/TreeJCodeGen.java (POJO),
hex/genmodel/easy/EasyPredictModelWrapper.java (row API).
"""
import os

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.genmodel import EasyPredictModelWrapper, pojo_source
from h2o3_tpu.mojo import export_mojo, read_mojo


def _frame_with_cats(n=600, seed=0):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    c = np.array(["lo", "mid", "hi"], dtype=object)[
        rng.integers(0, 3, n)]
    logit = 1.2 * x0 - 0.8 * x1 + np.where(c == "hi", 1.0,
                                           np.where(c == "mid", 0.2, -0.5))
    y = np.array(["n", "p"], dtype=object)[
        (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)]
    fr = h2o.Frame.from_numpy({"x0": x0, "c": c, "x1": x1, "y": y})
    return fr, x0, x1, c, y


def test_glm_mojo_roundtrip(tmp_path):
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    fr, x0, x1, c, y = _frame_with_cats()
    est = H2OGeneralizedLinearEstimator(family="binomial", Lambda=0.0)
    est.train(y="y", training_frame=fr)
    m = est.model
    path = str(tmp_path / "glm.zip")
    export_mojo(m, path)
    scorer = read_mojo(path)
    # MOJO rows are cats-first: [c, x0, x1]
    dom = list(m.cat_domains["c"])
    want = np.asarray(m._predict_matrix(
        __import__("jax").numpy.asarray(
            np.stack([x0, np.array([dom.index(v) for v in c], np.float32),
                      x1], 1))))
    for i in range(0, 100, 7):
        row = np.array([dom.index(c[i]), x0[i], x1[i]], np.float64)
        got = scorer.score(row)
        assert abs(got[2] - want[i, 1]) < 1e-5, (i, got, want[i])


def test_kmeans_mojo_roundtrip(tmp_path):
    from h2o3_tpu.models.kmeans import H2OKMeansEstimator
    rng = np.random.default_rng(1)
    X = np.concatenate([rng.normal(-3, 0.3, (200, 2)),
                        rng.normal(3, 0.3, (200, 2))]).astype(np.float32)
    fr = h2o.Frame.from_numpy({"a": X[:, 0], "b": X[:, 1]})
    est = H2OKMeansEstimator(k=2, seed=1)
    est.train(training_frame=fr)
    path = str(tmp_path / "km.zip")
    export_mojo(est.model, path)
    scorer = read_mojo(path)
    pred = est.model.predict(fr)
    ours = np.asarray(pred.vec(0).to_numpy()[:400])
    got = np.array([scorer.score(X[i].astype(np.float64))[0]
                    for i in range(400)])
    assert (got == ours).mean() > 0.99


def test_deeplearning_mojo_roundtrip(tmp_path):
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
    fr, x0, x1, c, y = _frame_with_cats(seed=2)
    est = H2ODeepLearningEstimator(hidden=[16], epochs=5, seed=3,
                                   input_dropout_ratio=0.0)
    est.train(y="y", training_frame=fr)
    m = est.model
    path = str(tmp_path / "dl.zip")
    export_mojo(m, path)
    scorer = read_mojo(path)
    import jax.numpy as jnp
    dom = list(m.cat_domains["c"])
    X = np.stack([x0, np.array([dom.index(v) for v in c], np.float32),
                  x1], 1)
    want = np.asarray(m._predict_matrix(jnp.asarray(X)))
    for i in range(0, 60, 9):
        row = np.array([dom.index(c[i]), x0[i], x1[i]], np.float64)
        got = scorer.score(row)
        assert abs(got[2] - want[i, 1]) < 1e-4, (i, got[2], want[i, 1])


def test_pojo_codegen_shape(tmp_path):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    fr, *_ = _frame_with_cats(seed=4)
    est = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=5)
    est.train(y="y", training_frame=fr)
    src = pojo_source(est.model, class_name="TestPojo")
    assert "public class TestPojo" in src
    assert "static float tree_0(double[] data)" in src
    assert "public static double[] score0" in src
    assert src.count("static float tree_") == 3
    # well-formed nesting
    assert src.count("{") == src.count("}")
    # javac available? compile-check (golden-shape otherwise)
    import shutil
    import subprocess
    if shutil.which("javac"):
        p = tmp_path / "TestPojo.java"
        p.write_text(src)
        subprocess.run(["javac", str(p)], check=True)


def test_easypredict_row_api():
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    fr, x0, x1, c, y = _frame_with_cats(seed=6)
    est = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=7)
    est.train(y="y", training_frame=fr)
    wrap = EasyPredictModelWrapper(est.model)
    out = wrap.predict_row({"x0": 1.0, "c": "hi", "x1": -0.5})
    assert out["label"] in ("n", "p")
    probs = out["classProbabilities"]
    assert abs(sum(probs.values()) - 1.0) < 1e-5
    # unknown level and missing column → NA handling, still scores
    out2 = wrap.predict_row({"x0": 0.0, "c": "never-seen"})
    assert out2["label"] in ("n", "p")
    # EasyPredict over a loaded MOJO scorer too
    import tempfile
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    glm = H2OGeneralizedLinearEstimator(family="binomial", Lambda=0.0)
    glm.train(y="y", training_frame=fr)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "glm.zip")
        export_mojo(glm.model, path)
        scorer = read_mojo(path)
    scorer.cat_domains = {"c": glm.model.cat_domains["c"]}
    scorer.response_domain = list(glm.model.response_domain)
    wrap2 = EasyPredictModelWrapper(scorer)
    out3 = wrap2.predict_row({"c": "hi", "x0": 1.0, "x1": 0.0})
    assert out3["label"] in ("n", "p")


def test_glm_pojo_shape(tmp_path):
    from h2o3_tpu.genmodel import export_pojo, pojo_source_glm
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    fr, *_ = _frame_with_cats(seed=8)
    glm = H2OGeneralizedLinearEstimator(family="binomial", Lambda=0.0)
    glm.train(y="y", training_frame=fr)
    src = pojo_source_glm(glm.model, class_name="GlmPojo")
    assert "public class GlmPojo" in src
    assert "BETA" in src and "CAT_OFFSETS" in src
    assert src.count("{") == src.count("}")
    p = export_pojo(glm.model, str(tmp_path / "GlmPojo.java"),
                    class_name="GlmPojo")
    assert os.path.exists(p)


def test_frames_pagination_rest():
    """FrameV3 row/column windows (water/api/FramesHandler pagination)."""
    import json
    import urllib.request
    import h2o3_tpu
    from h2o3_tpu import dkv
    from h2o3_tpu.api import start_server
    h2o3_tpu.init()
    srv = start_server(port=0)
    fr = h2o.Frame.from_numpy(
        {f"c{i}": np.arange(100, dtype=np.float32) + i for i in range(6)})
    dkv.put("pagefr", "frame", fr)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/3/Frames/pagefr"
            f"?row_count=5&row_offset=10&column_count=2&column_offset=3",
            timeout=60) as resp:
        fw = json.loads(resp.read())["frames"][0]
    assert fw["row_offset"] == 10 and fw["column_offset"] == 3
    assert [c["label"] for c in fw["columns"]] == ["c3", "c4"]
    assert fw["columns"][0]["data"][0] == 13.0   # row 10 of c3 = 10+3
    srv.stop()
    dkv.remove("pagefr")
