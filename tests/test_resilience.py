"""Fault-tolerant pipelines (ISSUE 6): deterministic fault injection,
retry/backoff, checkpoint/resume bit-parity, OOM graceful degradation,
the serve circuit breaker and job supervision.

Every test configures faults explicitly and clears them on exit (the
autouse fixture makes a leaked spec impossible); the no-op guard
asserts the unset path stays checked-no-op, the same method as the
PR-4 telemetry overhead guard."""
import os
import statistics
import time

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import dkv, faults, resilience, serve, telemetry
from h2o3_tpu.estimators import (H2OGradientBoostingEstimator,
                                 H2ORandomForestEstimator)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    yield
    faults.configure(None)
    serve.shutdown_all()


def _reg_frame(n=400, seed=0):
    rng = np.random.default_rng(seed)
    cols = {"x1": rng.normal(size=n), "x2": rng.normal(size=n),
            "x3": rng.normal(size=n)}
    cols["y"] = cols["x1"] * 2.0 - cols["x2"] + rng.normal(size=n) * 0.1
    return h2o.Frame.from_numpy(cols)


def _cls_frame(n=400, seed=0):
    rng = np.random.default_rng(seed)
    cols = {"x1": rng.normal(size=n), "x2": rng.normal(size=n)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[
        (cols["x1"] + rng.normal(size=n) * 0.3 > 0).astype(int)]
    return h2o.Frame.from_numpy(cols)


def _tree_arrays(model):
    import jax
    return {k: np.asarray(jax.device_get(getattr(model, k)))
            for k in ("_feat", "_thr", "_na_left", "_is_split", "_value")}


def _assert_trees_equal(a, b):
    ta, tb = _tree_arrays(a), _tree_arrays(b)
    for k in ta:
        assert ta[k].shape == tb[k].shape, k
        assert (ta[k] == tb[k]).all(), f"{k} differs"
    assert float(np.asarray(a.f0).reshape(-1)[0]) == \
        float(np.asarray(b.f0).reshape(-1)[0])


# --------------------------------------------------- spec + gating

def test_fault_spec_parsing_and_determinism():
    faults.configure("h2d:every=3:exc=Unavailable:times=2,"
                     "execute@train:every=1:exc=ResourceExhausted:after=5")
    rules = faults.describe()
    assert rules[0]["site"] == "h2d" and rules[0]["every"] == 3
    assert rules[0]["times"] == 2 and rules[0]["exc"] == "Unavailable"
    assert rules[1]["pipeline"] == "train" and rules[1]["after"] == 5
    # deterministic: 3rd and 6th checks fire, then the rule exhausts
    fired = []
    for i in range(12):
        try:
            faults.check("h2d")
            fired.append(False)
        except faults.Unavailable:
            fired.append(True)
    assert fired == [False, False, True, False, False, True] + [False] * 6
    with pytest.raises(ValueError):
        faults.configure("h2d:bogus_option=1")
    faults.configure(None)
    assert faults.ACTIVE is None and faults.spec() is None


def test_fault_hooks_checked_noop_when_unset():
    """The overhead contract (same method as the telemetry ns-budget
    guard): with no spec configured the call-site gate is one module
    attribute load + branch, and even an unguarded check() returns
    immediately."""
    faults.configure(None)
    N = 20_000

    def per_call_ns():
        t0 = time.perf_counter_ns()
        for _ in range(N):
            if faults.ACTIVE:
                faults.check("h2d")
        return (time.perf_counter_ns() - t0) / N

    gate_ns = statistics.median(per_call_ns() for _ in range(5))
    assert gate_ns < 2_000, f"unset fault gate too slow: {gate_ns:.0f}ns"


# --------------------------------------------------- fault matrix

def test_ingest_h2d_fault_recovers():
    """ingest × h2d: every chunk upload hiccup retries with backoff and
    the parse still produces correct data."""
    before = telemetry.registry().value("h2o3_retry_total",
                                        {"site": "h2d"})
    faults.configure("h2d:every=3:exc=Unavailable:times=3")
    fr = _reg_frame(n=600, seed=3)
    assert fr.nrow == 600
    col = fr.vec("x1").to_numpy()
    assert np.isfinite(col).all()
    after = telemetry.registry().value("h2o3_retry_total",
                                       {"site": "h2d"})
    assert after > before, "no retry was recorded"


def test_train_transient_fault_retries_bit_identical():
    """train × {compile, execute}: transient faults retry and the final
    model is BIT-identical to the fault-free run."""
    fr = _reg_frame()
    a = H2OGradientBoostingEstimator(ntrees=6, max_depth=3, seed=7)
    a.train(y="y", training_frame=fr)
    for site in ("compile", "execute"):
        faults.configure(f"{site}@train:every=1:times=2:exc=Unavailable")
        b = H2OGradientBoostingEstimator(ntrees=6, max_depth=3, seed=7)
        b.train(y="y", training_frame=fr)
        faults.configure(None)
        _assert_trees_equal(a.model, b.model)
    assert telemetry.registry().value(
        "h2o3_retry_total", {"site": "train.execute"}) > 0
    # recovery events are visible on /metrics
    text = telemetry.prometheus_text()
    assert "h2o3_retry_total" in text
    assert "h2o3_fault_injected_total" in text


def test_train_collective_fault_retries_on_multishard_mesh():
    """train × collective (ISSUE 7): a transient ICI failure on the
    per-level histogram-psum seam retries via resilience.retry_transient
    and the model stays bit-identical to the fault-free run. The
    ``collective`` site only arms when the mesh has >1 data shard — the
    suite's 8-virtual-device mesh qualifies."""
    import jax
    from h2o3_tpu.parallel.mesh import current_mesh, n_data_shards
    if n_data_shards(current_mesh()) < 2:
        pytest.skip("needs a multi-data-shard mesh")
    fr = _reg_frame(seed=5)
    a = H2OGradientBoostingEstimator(ntrees=6, max_depth=3, seed=7)
    a.train(y="y", training_frame=fr)
    before = telemetry.registry().value("h2o3_retry_total",
                                        {"site": "train.execute"})
    faults.configure("collective@train:every=1:times=2:exc=Unavailable")
    b = H2OGradientBoostingEstimator(ntrees=6, max_depth=3, seed=7)
    b.train(y="y", training_frame=fr)
    faults.configure(None)
    _assert_trees_equal(a.model, b.model)
    after = telemetry.registry().value("h2o3_retry_total",
                                       {"site": "train.execute"})
    assert after > before, "collective fault did not exercise the retry"
    # on a SINGLE-shard mesh the collective site never fires (there is
    # no ICI to fail): same spec, single-device mesh, zero injections
    from h2o3_tpu.parallel.mesh import make_mesh, set_mesh
    old = current_mesh()
    set_mesh(make_mesh(n_data=1, devices=jax.devices()[:1]))
    try:
        faults.configure("collective@train:every=1:exc=Unavailable")
        fr1 = _reg_frame(seed=5)
        c = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=7)
        c.train(y="y", training_frame=fr1)
        assert faults.fired_total() == 0
    finally:
        faults.configure(None)
        set_mesh(old)


def test_serve_transient_fault_single_retry():
    """serve × execute: one transient device failure recovers via the
    single in-batch retry — the client never sees it and the circuit
    stays closed."""
    fr = _cls_frame()
    m = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=1)
    m.train(y="y", training_frame=fr)
    dkv.put("res_m_retry", "model", m.model)
    dep = serve.deploy("res_m_retry", max_delay_ms=1.0)
    try:
        faults.configure("execute@serve:key=res_m_retry:every=1:times=1"
                         ":exc=Unavailable")
        out = dep.predict_rows([{"x1": 0.5, "x2": -0.2}])
        assert out[0]["label"] in ("no", "yes")
        assert dep.stats.retries == 1
        assert dep.breaker.state == "closed"
    finally:
        serve.undeploy("res_m_retry")
        dkv.remove("res_m_retry")


# --------------------------------------------------- OOM degradation

def test_oom_degrades_dense_to_streamed():
    """A device OOM mid-train degrades to the streamed resident-window
    path (warn + h2o3_degrade_total) and the train COMPLETES."""
    fr = _reg_frame()
    before = telemetry.registry().value("h2o3_degrade_total",
                                        {"algo": "gbm"})
    faults.configure("execute@train:every=1:times=1:exc=ResourceExhausted")
    est = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=5)
    est.train(y="y", training_frame=fr)
    model = est.model
    assert model.output.get("streamed") is True
    assert model.ntrees_built == 4
    assert np.isfinite(model.training_metrics.mse)
    after = telemetry.registry().value("h2o3_degrade_total",
                                       {"algo": "gbm"})
    assert after == before + 1
    # degraded model still predicts
    pred = model.predict(fr).vec("predict").to_numpy()
    assert np.isfinite(pred).all()


def test_oom_without_streamed_fallback_reraises():
    """Configs the streamed path cannot take (multinomial) surface the
    ORIGINAL OOM instead of a confusing NotImplementedError."""
    rng = np.random.default_rng(2)
    cols = {"x1": rng.normal(size=300), "x2": rng.normal(size=300)}
    cols["y"] = np.array(["a", "b", "c"], dtype=object)[
        rng.integers(0, 3, 300)]
    fr = h2o.Frame.from_numpy(cols)
    faults.configure("execute@train:every=1:times=1:exc=ResourceExhausted")
    est = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=5)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        est.train(y="y", training_frame=fr)


# --------------------------------------------------- checkpoint/resume

def test_gbm_mid_train_kill_then_resume_bit_identical(tmp_path):
    """The acceptance scenario: transient faults every Nth H2D PLUS one
    mid-train kill — training fails, the in-training checkpoint holds
    the committed prefix, and resuming from it yields a model
    BIT-identical to the fault-free run."""
    fr = _reg_frame()
    kw = dict(ntrees=9, max_depth=3, seed=11, learn_rate=0.2)
    a = H2OGradientBoostingEstimator(**kw)
    a.train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "ckpts")
    # kill the 3rd chunk dispatch (after=2 execute checks pass first);
    # chunks are 3 trees (tree_interval), so trees 1-6 commit
    faults.configure("execute@train:every=1:after=2:times=1:exc=Fatal")
    b = H2OGradientBoostingEstimator(
        in_training_checkpoints_dir=ckdir,
        in_training_checkpoints_tree_interval=3, **kw)
    with pytest.raises(RuntimeError, match="FATAL"):
        b.train(y="y", training_frame=fr)
    faults.configure(None)
    ckpts = sorted(os.listdir(ckdir))
    assert ckpts, "mid-train kill left no checkpoint"
    # a KILLED train keeps its DKV entry (that is the recovery state);
    # clean it here so the module teardown stays tidy
    killed_keys = [k for k in dkv.keys("model") if k.endswith("_ckpt")]
    assert killed_keys, "killed train left no DKV checkpoint"
    for k in killed_keys:
        dkv.remove(k)
    latest = os.path.join(ckdir, ckpts[-1])

    # resume: total ntrees unchanged; also inject a transient H2D fault
    # so the resume itself exercises the retry path
    faults.configure("h2d:every=5:times=1:exc=Unavailable")
    c = H2OGradientBoostingEstimator(checkpoint=latest, **kw)
    c.train(y="y", training_frame=fr)
    _assert_trees_equal(a.model, c.model)
    # predictions bit-match too
    pa = a.model.predict(fr).vec("predict").to_numpy()
    pc = c.model.predict(fr).vec("predict").to_numpy()
    assert (np.asarray(pa) == np.asarray(pc)).all()


def test_gbm_in_training_checkpoints_lifecycle(tmp_path):
    """Checkpoints land on disk at the tree_interval cadence with
    resume state attached; the transient DKV <key>_ckpt entry is
    dropped once the train COMPLETES (the finished model supersedes
    it — no phantom partial models accumulate in the store)."""
    fr = _reg_frame()
    ckdir = str(tmp_path / "dk")
    est = H2OGradientBoostingEstimator(
        ntrees=6, max_depth=2, seed=3,
        in_training_checkpoints_dir=ckdir,
        in_training_checkpoints_tree_interval=2)
    est.train(y="y", training_frame=fr)
    files = sorted(os.listdir(ckdir))
    assert [f for f in files if f.endswith("_t2.zip")]
    assert [f for f in files if f.endswith("_t6.zip")]
    # a completed train leaves no DKV checkpoint entry behind
    assert dkv.get_opt(f"{est.model.key}_ckpt") is None
    # the durable artifact carries the resume state
    ck = h2o.load_model(os.path.join(
        ckdir, [f for f in files if f.endswith("_t2.zip")][0]))
    assert ck.ntrees_built == 2
    assert getattr(ck, "_resume_margin", None) is not None
    assert getattr(ck, "_resume_sig", None) is not None
    # continue-on-DIFFERENT-data: the stale margin must NOT be reused
    # (signature mismatch → recompute from trees, train still works)
    fr2 = _reg_frame(n=fr.nrow, seed=99)
    res = H2OGradientBoostingEstimator(ntrees=4, max_depth=2, seed=3,
                                       checkpoint=ck)
    res.train(y="y", training_frame=fr2)
    assert res.model.ntrees_built == 4


def test_drf_checkpoint_resume_bit_identical(tmp_path):
    fr = _cls_frame()
    kw = dict(ntrees=8, max_depth=4, seed=5)
    a = H2ORandomForestEstimator(**kw)
    a.train(y="y", training_frame=fr)
    ckdir = str(tmp_path / "drf")
    b = H2ORandomForestEstimator(
        in_training_checkpoints_dir=ckdir,
        in_training_checkpoints_tree_interval=3, **kw)
    b.train(y="y", training_frame=fr)
    _assert_drf_equal(a.model, b.model)
    ck = [f for f in sorted(os.listdir(ckdir)) if "_t6" in f][0]
    c = H2ORandomForestEstimator(checkpoint=os.path.join(ckdir, ck), **kw)
    c.train(y="y", training_frame=fr)
    _assert_drf_equal(a.model, c.model)
    # resumed OOB accumulators → identical training (OOB) metrics
    assert a.model.training_metrics.auc == c.model.training_metrics.auc
    dkv.remove(f"{b.model.key}_ckpt")


def _assert_drf_equal(a, b):
    import jax
    for k in ("_feat", "_thr", "_value", "_is_split", "_na_left"):
        ea = np.asarray(jax.device_get(getattr(a, k)))
        eb = np.asarray(jax.device_get(getattr(b, k)))
        assert ea.shape == eb.shape and (ea == eb).all(), k


def test_checkpoint_params_are_real_not_compat():
    """The three fault-tolerance params moved out of the accepted-then-
    ignored warn inventory (the VERDICT-r5 blocker class)."""
    from h2o3_tpu.models.compat_params import COMPAT_PARAMS
    for p in ("checkpoint", "in_training_checkpoints_dir",
              "in_training_checkpoints_tree_interval"):
        assert p not in COMPAT_PARAMS.get("gbm", {}), p
    assert "checkpoint" not in COMPAT_PARAMS.get("drf", {})
    # and they are real defaults on the builders
    from h2o3_tpu.models.drf import DRF_DEFAULTS
    from h2o3_tpu.models.gbm import GBM_DEFAULTS
    assert "checkpoint" in GBM_DEFAULTS and "checkpoint" in DRF_DEFAULTS
    assert "in_training_checkpoints_dir" in GBM_DEFAULTS


def test_checkpoint_validation_rejects_mismatch(tmp_path):
    fr = _reg_frame()
    a = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1)
    a.train(y="y", training_frame=fr)
    path = h2o.save_model(a.model, str(tmp_path), force=True)
    # ntrees must exceed the checkpoint's
    with pytest.raises(RuntimeError, match="must exceed"):
        H2OGradientBoostingEstimator(
            ntrees=4, max_depth=3, seed=1, checkpoint=path
        ).train(y="y", training_frame=fr)
    with pytest.raises(RuntimeError, match="max_depth"):
        H2OGradientBoostingEstimator(
            ntrees=8, max_depth=4, seed=1, checkpoint=path
        ).train(y="y", training_frame=fr)


# --------------------------------------------------- serve circuit

def test_circuit_breaker_open_halfopen_close_lifecycle():
    """Persistent device failure → open (fast 503 + Retry-After) while a
    healthy deployment keeps serving; clearing the fault → half-open
    probe → closed."""
    fr = _cls_frame()
    m = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=1)
    m.train(y="y", training_frame=fr)
    dkv.put("cb_sick", "model", m.model)
    dkv.put("cb_ok", "model", m.model)
    sick = serve.deploy("cb_sick", circuit_failures=2,
                        circuit_open_ms=250, max_delay_ms=1.0)
    ok = serve.deploy("cb_ok", max_delay_ms=1.0)
    row = {"x1": 0.5, "x2": -0.2}
    try:
        faults.configure("execute@serve:key=cb_sick:every=1:exc=Internal")
        opened = False
        for _ in range(6):
            try:
                sick.predict_rows([row], timeout_ms=500)
            except serve.ServeCircuitOpenError as e:
                opened = True
                assert e.retry_after_s > 0
                assert serve.ServeCircuitOpenError.http_status == 503
                break
            except Exception:   # noqa: BLE001 — device errors expected
                pass
        assert opened and sick.breaker.state == "open"
        # open = FAST failure: no queueing, sub-tick latency
        t0 = time.perf_counter()
        with pytest.raises(serve.ServeCircuitOpenError):
            sick.predict_rows([row], timeout_ms=5000)
        assert time.perf_counter() - t0 < 0.1
        # the healthy deployment is untouched by its neighbor's faults
        assert ok.predict_rows([row])[0]["label"] in ("no", "yes")
        assert ok.breaker.state == "closed"
        # health is visible in /3/Serve/stats
        snap = serve.stats()["models"]
        assert snap["cb_sick"]["circuit"]["state"] == "open"
        assert snap["cb_sick"]["circuit"]["open_count"] == 1
        assert snap["cb_ok"]["circuit"]["state"] == "closed"
        # and on the metrics surface (2 = open)
        assert sick.stats._reg.value("h2o3_circuit_state",
                                     {"model": "cb_sick"}) == 2
        # fault clears → cooldown expiry admits a probe that closes it
        faults.configure(None)
        time.sleep(0.3)
        assert sick.predict_rows([row])[0]["label"] in ("no", "yes")
        assert sick.breaker.state == "closed"
    finally:
        serve.undeploy("cb_sick")
        serve.undeploy("cb_ok")
        dkv.remove("cb_sick")
        dkv.remove("cb_ok")


def test_circuit_halfopen_failed_probe_reopens():
    from h2o3_tpu.serve.circuit import CircuitBreaker
    cb = CircuitBreaker(model="probe_t", failure_threshold=2,
                        open_secs=0.05)
    cb.record_failure()
    cb.record_failure()
    assert cb.state == "open"
    assert cb.allow_request() is not None          # still cooling down
    time.sleep(0.06)
    assert cb.allow_request() is None              # the probe
    assert cb.state == "half_open"
    assert cb.allow_request() is not None          # probe in flight
    cb.record_failure()                            # probe fails
    assert cb.state == "open"
    time.sleep(0.06)
    assert cb.allow_request() is None
    cb.record_success()
    assert cb.state == "closed"


# --------------------------------------------------- deploy error path

def test_failed_deploy_releases_pin_model_stays_deletable():
    """Satellite regression: a deploy that fails AFTER
    dkv.get_and_read_lock must release its pin — the model stays
    deletable; a failed RE-deploy over a live deployment keeps the
    live pin."""
    fr = _reg_frame()
    m = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1)
    m.train(y="y", training_frame=fr)
    dkv.put("pin_m", "model", m.model)
    try:
        with pytest.raises(ValueError, match="max_batch"):
            serve.deploy("pin_m", max_batch=10 ** 6)
        dkv.check_unlocked("pin_m")        # raises if the pin leaked
        # live deployment: failed re-deploy keeps the existing pin
        serve.deploy("pin_m")
        with pytest.raises(ValueError, match="max_batch"):
            serve.deploy("pin_m", max_batch=10 ** 6)
        with pytest.raises(dkv.KeyLockedError):
            dkv.check_unlocked("pin_m")
        serve.undeploy("pin_m")
        dkv.check_unlocked("pin_m")
        assert dkv.remove("pin_m")
    finally:
        serve.undeploy("pin_m")
        dkv.remove("pin_m")


# --------------------------------------------------- job supervision

def test_job_structured_failure_info():
    from h2o3_tpu import jobs
    from h2o3_tpu.api import schemas

    def boom(job):
        with telemetry.span("train.unit_test"):
            raise ValueError("synthetic failure for structured info")

    j = jobs.Job("structured failure probe")
    j.run(boom)
    assert j.status == jobs.FAILED
    assert j.exception_type == "ValueError"
    assert "synthetic failure" in j.exception_msg
    # the INNERMOST span the exception unwound through is the stage
    assert j.failed_stage == "train.unit_test"
    body = schemas.job_v3(j)
    assert body["exception_type"] == "ValueError"
    assert "synthetic failure" in body["exception_msg"]
    assert body["failed_stage"] == "train.unit_test"
    assert body["status"] == "FAILED"
    assert "stalled" in body and "failed_stage" in body


def test_watchdog_enforces_max_runtime(monkeypatch):
    from h2o3_tpu import jobs
    monkeypatch.setenv("H2O3_JOB_WATCH_TICK", "0.05")
    j = jobs.Job("runaway", max_runtime_secs=0.15)

    def loop(job):
        while not job.cancel_requested:
            time.sleep(0.02)
        return "stopped"

    j.run(loop, background=True)
    j._thread.join(3.0)
    assert j.cancel_requested
    assert j.status == jobs.CANCELLED
    assert "max_runtime_secs" in (j.cancel_reason or "")


def test_watchdog_marks_stalled_jobs(monkeypatch):
    from h2o3_tpu import jobs
    monkeypatch.setenv("H2O3_JOB_WATCH_TICK", "0.05")
    j = jobs.Job("staller", stall_timeout_secs=0.1)
    done = []

    def body(job):
        time.sleep(0.4)            # no progress heartbeats
        for _ in range(5):         # heartbeats resume
            job.set_progress(0.9)
            time.sleep(0.02)
        done.append(True)

    j.run(body, background=True)
    deadline = time.time() + 2.0
    saw_stall = False
    while time.time() < deadline and not saw_stall:
        saw_stall = j.stalled
        time.sleep(0.02)
    assert saw_stall, "watchdog never marked the silent job stalled"
    j._thread.join(3.0)
    assert done and j.status == jobs.DONE
    assert not j.stalled           # cleared when the heartbeat resumed


def test_streamed_train_cancel_propagates(monkeypatch):
    """Cancel lands between streamed tree levels via the
    StreamedChunks.cancel_check hook and the job finalizes as
    CANCELLED with the committed trees."""
    from h2o3_tpu import memman
    fr = _reg_frame(n=1200, seed=4)
    # force streaming: tiny device budget
    monkeypatch.setattr(memman.manager(), "budget", 60_000)
    est = H2OGradientBoostingEstimator(ntrees=50, max_depth=3, seed=2)
    est.train(y="y", training_frame=fr, background=True)
    est.job.cancel()
    # scheduler-run jobs own no thread — join() waits on the terminal
    # latch (and raises only on FAILED)
    est.job.join(30.0)
    assert est.job.status in ("CANCELLED", "DONE")


# --------------------------------------------------- persist retries

def test_persist_load_model_retries_flaky_read(tmp_path):
    fr = _reg_frame()
    est = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1)
    est.train(y="y", training_frame=fr)
    path = h2o.save_model(est.model, str(tmp_path), force=True)
    faults.configure("persist:every=1:times=1:exc=IOError")
    m = h2o.load_model(path)       # first attempt faults, retry loads
    assert m.ntrees_built == 2
    assert telemetry.registry().value(
        "h2o3_retry_total", {"site": "persist.load_model"}) > 0


def test_persist_uri_download_retries(monkeypatch, tmp_path):
    """localize() retries a flaky remote download through the shared
    backoff helper."""
    from h2o3_tpu.ingest import persist_uri
    monkeypatch.setattr(persist_uri, "_CACHE_DIR", str(tmp_path))
    calls = {"n": 0}

    def flaky_urlretrieve(uri, tmp):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionResetError("connection reset by peer")
        with open(tmp, "w") as f:
            f.write("a,b\n1,2\n")

    monkeypatch.setattr(persist_uri.urllib.request, "urlretrieve",
                        flaky_urlretrieve)
    out = persist_uri.localize("http://unit.test/flaky.csv")
    assert os.path.exists(out) and calls["n"] == 2
    with open(out) as f:
        assert f.read().startswith("a,b")


def test_compressed_ingest_decompress_retries(tmp_path):
    """The ``decompress`` fault seam: a transient storage hiccup on the
    compressed-ingest read retries through the shared backoff and the
    import still succeeds bit-for-bit."""
    from h2o3_tpu.ingest.compress import gzip_compress_members
    csv = "a,b\n" + "".join(f"{i},{i * 0.5}\n" for i in range(200))
    gz = tmp_path / "t.csv.gz"
    gz.write_bytes(gzip_compress_members(csv.encode(), member_bytes=256))
    faults.configure("decompress@ingest:every=1:times=1:exc=IOError")
    fr = h2o.import_file(str(gz))       # first attempt faults, retry wins
    assert fr.nrow == 200
    assert np.asarray(fr.vec("b").to_numpy()).reshape(-1)[3] == 1.5
    assert telemetry.registry().value(
        "h2o3_retry_total", {"site": "ingest.decompress"}) > 0
    assert faults.fired_total() == 1


def test_transient_classification():
    assert resilience.is_transient(faults.Unavailable("UNAVAILABLE: x"))
    assert resilience.is_transient(RuntimeError("INTERNAL: device halt"))
    assert not resilience.is_transient(
        faults.ResourceExhausted("RESOURCE_EXHAUSTED"))
    assert not resilience.is_transient(faults.Fatal("FATAL"))
    assert resilience.is_oom(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert resilience.is_transient_io(IOError("disk hiccup"))
    assert not resilience.is_transient_io(FileNotFoundError("gone"))


def test_retry_transient_backoff_and_counters():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise faults.Unavailable("UNAVAILABLE: injected")
        return "ok"

    out = resilience.retry_transient(flaky, site="unit.test",
                                     sleep=sleeps.append)
    assert out == "ok" and calls["n"] == 3 and len(sleeps) == 2
    assert telemetry.registry().value(
        "h2o3_retry_total", {"site": "unit.test"}) == 2
    # non-transient propagates immediately
    with pytest.raises(faults.Fatal):
        resilience.retry_transient(
            lambda: (_ for _ in ()).throw(faults.Fatal("FATAL")),
            site="unit.test2", sleep=sleeps.append)


# --------------------------------------------------- REST surface

def test_faults_rest_roundtrip():
    from h2o3_tpu.api.server import H2OApiServer
    srv = H2OApiServer(port=0)
    srv.start()
    try:
        import json
        import urllib.request
        base = f"http://127.0.0.1:{srv.port}"

        def call(method, path, data=None):
            req = urllib.request.Request(base + path, method=method,
                                         data=data)
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        out = call("POST", "/3/Faults?spec=h2d:every=9:exc=Unavailable")
        assert out["spec"].startswith("h2d:every=9")
        assert out["rules"][0]["every"] == 9
        out = call("GET", "/3/Faults")
        assert out["rules"][0]["site"] == "h2d"
        out = call("DELETE", "/3/Faults")
        assert out["spec"] is None
        assert faults.ACTIVE is None
    finally:
        srv.stop()
