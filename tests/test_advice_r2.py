"""Regression tests for the round-2 ADVICE.md findings.

- checkpoint compatibility must also check nclasses / response_domain /
  cat_domains (medium: silent margin corruption under jit's clamped
  indexing);
- DRF with sample_rate=1.0 (no OOB rows) falls back to in-bag training
  metrics instead of leaving them None;
- validation frames are adapted through the TRAINING domains (enum code
  remap) rather than their own;
- GBM with offset computes f0 on the offset-adjusted scale (Newton);
- export_file escapes embedded quotes per RFC 4180;
- weighted-median Laplace init; quantile / huber families train.
"""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.drf import H2ORandomForestEstimator
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator


def _train_frame(n=600, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-2 * x))).astype(np.int32)
    return h2o.Frame.from_numpy({"x": x, "y": y.astype(np.float32)})


def test_checkpoint_nclasses_mismatch_raises():
    fr2 = _train_frame()
    base = H2OGradientBoostingEstimator(ntrees=5, max_depth=3,
                                        distribution="bernoulli", seed=1)
    base.train(y="y", training_frame=fr2)
    rng = np.random.default_rng(1)
    n = 600
    fr3 = h2o.Frame.from_numpy({
        "x": rng.normal(size=n).astype(np.float32),
        "y": rng.integers(0, 3, n).astype(np.float32)})
    cont = H2OGradientBoostingEstimator(ntrees=10, max_depth=3,
                                        distribution="multinomial",
                                        checkpoint=base.model)
    with pytest.raises(RuntimeError, match="distribution|classes"):
        cont.train(y="y", training_frame=fr3)


def test_checkpoint_domain_mismatch_raises():
    rng = np.random.default_rng(2)
    n = 600

    def make(levels):
        cat = rng.integers(0, len(levels), n)
        x = rng.normal(size=n).astype(np.float32)
        y = (rng.random(n) < np.where(cat == 0, 0.8, 0.2)).astype(np.float32)
        fr = h2o.Frame.from_numpy({"c": np.array([levels[i] for i in cat]),
                                   "x": x, "y": y})
        return fr

    fr_a = make(["a", "b", "c"])
    fr_b = make(["b", "c", "d"])   # different enum domain
    base = H2OGradientBoostingEstimator(ntrees=5, max_depth=3,
                                        distribution="bernoulli", seed=1)
    base.train(y="y", training_frame=fr_a)
    cont = H2OGradientBoostingEstimator(ntrees=10, max_depth=3,
                                        distribution="bernoulli",
                                        checkpoint=base.model)
    with pytest.raises(RuntimeError, match="categorical domains"):
        cont.train(y="y", training_frame=fr_b)


def test_drf_no_oob_falls_back_to_inbag():
    fr = _train_frame()
    fr["y"] = fr.vec("y").asfactor()   # binomial DRF
    drf = H2ORandomForestEstimator(ntrees=5, max_depth=4, sample_rate=1.0,
                                   seed=3)
    drf.train(y="y", training_frame=fr)
    assert drf.model.training_metrics is not None
    assert drf.model.output.get("oob_metrics") is False
    assert drf.model.auc() is not None


def test_validation_frame_enum_domain_remap():
    """A validation frame whose enum levels arrive in a different order
    must score identically to one with the training order."""
    rng = np.random.default_rng(5)
    n = 800
    lv = ["lo", "mid", "hi"]
    cat = rng.integers(0, 3, n)
    y = (rng.random(n) < np.where(cat == 2, 0.85, 0.15)).astype(np.float32)
    labels = np.array([lv[i] for i in cat])
    fr = h2o.Frame.from_numpy({"c": labels, "y": y})
    # validation frame: same rows, but the enum domain EXPLICITLY reordered
    # — codes built against this domain are wrong unless remapped through
    # the training domain
    from h2o3_tpu.frame.vec import T_ENUM, Vec
    train_dom = fr.vec("c").domain
    reordered = tuple(reversed(train_dom))
    lut = {lab: i for i, lab in enumerate(reordered)}
    codes_v = np.array([lut[l] for l in labels], dtype=np.int32)
    fr_v = h2o.Frame(["c", "y"],
                     [Vec.from_numpy(codes_v, vtype=T_ENUM, domain=reordered),
                      fr.vec("y")])
    assert fr_v.vec("c").domain != train_dom
    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=3,
                                       distribution="bernoulli", seed=1)
    gbm.train(y="y", training_frame=fr, validation_frame=fr_v)
    vm = gbm.model.validation_metrics
    # the validation rows are a permutation of the training rows, so
    # validation logloss must equal training logloss
    tm = gbm.model.training_metrics
    assert abs(vm.logloss - tm.logloss) < 1e-5


def test_gbm_offset_aware_f0():
    """With a constant response and a known offset, f0 must absorb the
    offset exactly (gaussian: f0 = weighted mean of y - offset)."""
    rng = np.random.default_rng(6)
    n = 500
    off = rng.normal(size=n).astype(np.float32) * 3.0
    y = (off + 2.0).astype(np.float32)   # y - offset ≡ 2
    fr = h2o.Frame.from_numpy({"x": rng.normal(size=n).astype(np.float32),
                               "off": off, "y": y})
    gbm = H2OGradientBoostingEstimator(ntrees=1, max_depth=2,
                                       distribution="gaussian",
                                       offset_column="off", seed=1)
    gbm.train(y="y", training_frame=fr)
    assert abs(float(np.asarray(gbm.model.f0)) - 2.0) < 1e-3


def test_export_file_escapes_quotes(tmp_path):
    vals = np.array(['plain', 'has "quote"', 'comma, inside'])
    fr = h2o.Frame.from_numpy({"s": vals,
                               "v": np.arange(3).astype(np.float32)})
    path = str(tmp_path / "q.csv")
    h2o.export_file(fr, path)
    back = h2o.import_file(path)
    assert back.nrow == 3
    got = list(back.vec("s").to_strings())
    assert got == list(vals), got


def test_quantile_distribution_trains():
    rng = np.random.default_rng(7)
    n = 2000
    x = rng.normal(size=n).astype(np.float32)
    y = (2 * x + rng.standard_exponential(n)).astype(np.float32)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    gbm = H2OGradientBoostingEstimator(ntrees=40, max_depth=3,
                                       distribution="quantile",
                                       quantile_alpha=0.8, seed=1,
                                       learn_rate=0.2, min_rows=5.0)
    gbm.train(y="y", training_frame=fr)
    pred = gbm.model.predict(fr).vec("predict").to_numpy()
    cover = float(np.mean(y <= pred))
    assert 0.7 < cover < 0.9, cover   # ~alpha of rows under the prediction


def test_huber_distribution_trains():
    rng = np.random.default_rng(8)
    n = 2000
    x = rng.normal(size=n).astype(np.float32)
    y = (3 * x).astype(np.float32)
    y[:40] += 100.0  # gross outliers — huber should shrug them off
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    gbm = H2OGradientBoostingEstimator(ntrees=50, max_depth=3,
                                       distribution="huber", seed=1,
                                       learn_rate=0.2, min_rows=5.0)
    gbm.train(y="y", training_frame=fr)
    pred = gbm.model.predict(fr).vec("predict").to_numpy()
    clean = np.arange(n) >= 40
    mse_clean = float(np.mean((pred[clean] - y[clean]) ** 2))
    assert mse_clean < 1.0, mse_clean


def test_weighted_median_laplace():
    from h2o3_tpu.models.distributions import get_distribution
    import jax.numpy as jnp
    d = get_distribution("laplace")
    y = jnp.asarray(np.array([0.0, 1.0, 10.0], np.float32))
    w = jnp.asarray(np.array([1.0, 1.0, 5.0], np.float32))
    # cumulative weights 1,2,7; half-total 3.5 → the 10.0 element
    assert float(d.init_f0(y, w)) == 10.0


def test_adaptive_thr_tables_finite_with_constant_feature():
    """Unsplittable nodes must store finite thresholds: inf in the
    routing tables becomes inf*0=NaN inside the kernel's one-hot LUT
    matmul on TPU, silently misrouting every row at that level."""
    rng = np.random.default_rng(21)
    n = 1000
    const = np.zeros(n, np.float32)          # constant -> zero span
    x = rng.normal(size=n).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-2 * x))).astype(np.float32)
    fr = h2o.Frame.from_numpy({"const": const, "x": x, "y": y})
    gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=4,
                                       distribution="bernoulli", seed=1)
    gbm.train(y="y", training_frame=fr)
    thr = np.asarray(gbm.model._thr)
    assert np.isfinite(thr).all(), "non-finite thresholds in tree tables"
    assert gbm.model.training_metrics.auc > 0.8


def test_glm_lambda_search_selects_by_validation():
    """With a validation frame, lambda_search must pick the submodel by
    validation deviance (training deviance always favors the smallest
    lambda on the warm-started path)."""
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    rng = np.random.default_rng(23)
    n, F = 120, 40                           # overfit-prone: wide + noisy
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + 2.0 * rng.normal(size=n)).astype(np.float32)
    Xv = rng.normal(size=(4 * n, F)).astype(np.float32)
    yv = (Xv[:, 0] + 2.0 * rng.normal(size=4 * n)).astype(np.float32)
    tr = h2o.Frame.from_numpy({**{f"x{i}": X[:, i] for i in range(F)}, "y": y})
    va = h2o.Frame.from_numpy({**{f"x{i}": Xv[:, i] for i in range(F)},
                               "y": yv})
    glm = H2OGeneralizedLinearEstimator(family="gaussian", alpha=1.0,
                                        lambda_search=True, nlambdas=20)
    glm.train(y="y", training_frame=tr, validation_frame=va)
    path = glm.model.output["lambda_path"]
    assert all("validation_deviance" in s for s in path)
    lams = [s["lambda"] for s in path]
    # chosen lambda should NOT be the smallest (which overfits here)
    assert glm.model.lambda_best > min(lams), (glm.model.lambda_best,
                                               min(lams))
