"""IsolationForest + NaiveBayes tests (reference: hex/tree/isofor,
hex/naivebayes test style)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.isoforest import H2OIsolationForestEstimator
from h2o3_tpu.models.naivebayes import H2ONaiveBayesEstimator


def test_isolation_forest_ranks_outliers():
    rng = np.random.default_rng(0)
    n = 2000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    X[:20] = X[:20] * 0.2 + 8.0          # far cluster of outliers
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    iso = H2OIsolationForestEstimator(ntrees=60, sample_size=256,
                                      max_depth=8, seed=1)
    iso.train(training_frame=fr)
    pred = iso.model.predict(fr)
    assert pred.names == ["predict", "mean_length"]
    score = pred.vec("predict").to_numpy()
    # the planted outliers should dominate the top anomaly scores
    top = np.argsort(-score)[:30]
    hits = np.sum(top < 20)
    assert hits >= 15, hits
    # outliers isolate in fewer splits than inliers
    ml = pred.vec("mean_length").to_numpy()
    assert ml[:20].mean() < ml[20:].mean()


def test_isolation_forest_save_load(tmp_path):
    rng = np.random.default_rng(3)
    X = rng.normal(size=(400, 3)).astype(np.float32)
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    iso = H2OIsolationForestEstimator(ntrees=10, max_depth=6, seed=1)
    iso.train(training_frame=fr)
    p = h2o.save_model(iso.model, str(tmp_path), filename="iso")
    m2 = h2o.load_model(p)
    s1 = iso.model.predict(fr).vec("predict").to_numpy()
    s2 = m2.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(s1, s2, rtol=1e-6)


def test_naive_bayes_vs_sklearn():
    from sklearn.naive_bayes import GaussianNB
    rng = np.random.default_rng(5)
    n = 3000
    y = rng.integers(0, 3, n)
    centers = np.array([[0, 0], [3, 1], [-2, 2]])
    X = (centers[y] + rng.normal(size=(n, 2))).astype(np.float32)
    labels = np.array(["a", "b", "c"], dtype=object)[y]
    fr = h2o.Frame.from_numpy({"x1": X[:, 0], "x2": X[:, 1], "y": labels})
    nb = H2ONaiveBayesEstimator()
    nb.train(y="y", training_frame=fr)
    acc_ours = 1 - nb.model.training_metrics.error
    sk = GaussianNB().fit(X, y)
    acc_sk = sk.score(X, y)
    assert abs(acc_ours - acc_sk) < 0.02, (acc_ours, acc_sk)
    probs = nb.model.predict(fr)
    assert probs.names == ["predict", "pa", "pb", "pc"]


def test_naive_bayes_categorical_features_laplace():
    rng = np.random.default_rng(7)
    n = 2000
    lv = np.array(["u", "v", "w"])
    cat = rng.integers(0, 3, n)
    yv = (rng.random(n) < np.where(cat == 0, 0.9, 0.2)).astype(int)
    fr = h2o.Frame.from_numpy({
        "c": lv[cat],
        "y": np.array(["no", "yes"], dtype=object)[yv]})
    nb = H2ONaiveBayesEstimator(laplace=1.0)
    nb.train(y="y", training_frame=fr)
    assert nb.model.training_metrics.auc > 0.75
    # conditional table rows are probability distributions
    P = nb.model.cat_probs["c"]
    np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-5)
