"""Fleet scheduler (ISSUE 18): cluster-wide training placement,
preempt-migrate, elastic membership.

The contract under test: a train submitted to ANY replica runs on the
member with admission headroom (local wins ties; no headroom anywhere
queues locally with the fleet snapshot recorded as evidence); a
preempted train's checkpoint hands to a replica with headroom and
resumes BIT-identically; a replica joining mid-wave absorbs queued
children; an evicted replica's RUNNING checkpointing trains re-queue
fleet-wide from their last chunk commit. Degradation is explicit: no
fleet (or heartbeats without sched fields, satellite 2) means
local-only placement with zero errors or misroutes.

The two-process spellings (real fleet over REST, SIGKILL) are marked
slow to protect the tier-1 budget — the in-process REST round-trip and
the local evict-fallback enforce the same parity acceptance cheaply,
mirroring tests/test_restart_recovery.py's split.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import dkv, faults, fleet, jobs, memman, recovery, sched
from h2o3_tpu import serve
from h2o3_tpu.fleet import sched as fleet_sched
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator as GBM

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HB_MS = "150"


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("H2O3_RECOVERY_DIR", raising=False)
    fleet.reset()            # also resets fleet_sched hooks + counters
    sched.reset()
    yield
    serve.shutdown_all()
    fleet.reset()
    memman.reset()
    sched.reset()
    faults.configure(None)


def _frame(n=4000, F=6, seed=0, key=None):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    logit = X[:, 0] - 0.5 * X[:, 1]
    cols = {f"x{i}": X[:, i] for i in range(F)}
    cols["y"] = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)),
                         "a", "b")
    fr = h2o.Frame.from_numpy(cols)
    fr.key = key
    return fr


def _tree_arrays(model):
    import jax
    return {k: np.asarray(jax.device_get(getattr(model, k)))
            for k in ("_feat", "_thr", "_value")}


def _assert_trees_equal(a, b, msg=""):
    ta, tb = _tree_arrays(a), _tree_arrays(b)
    for k in ta:
        assert ta[k].shape == tb[k].shape, f"{msg}{k} shape"
        assert np.array_equal(ta[k], tb[k], equal_nan=True), \
            f"{msg}diverged in {k}"


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _m(mid, headroom=-1, running=0, accepting=True, state="alive",
       routable=True):
    return {"member_id": mid, "base_url": "http://127.0.0.1:9",
            "state": state, "routable": routable,
            "sched": {"schema_version": 1, "headroom_bytes": headroom,
                      "queue_depth": {}, "running": running,
                      "accepting": accepting}}


def _gossip(members, epoch=7):
    fleet_sched.observe_fleet_view(
        {"epoch": epoch, "members": members}, "self@test")
    fleet_sched.set_local_member("self@test", None)


# ---------------- satellite 2: versioned heartbeat payload -------------


def test_sched_payload_schema_versioned_roundtrip():
    p = fleet_sched.local_sched_payload()
    assert p["schema_version"] == fleet_sched.SCHED_SCHEMA_VERSION
    parsed = fleet_sched.parse_sched_payload(p)
    assert parsed is not None
    assert parsed["headroom_bytes"] == p["headroom_bytes"]
    assert parsed["running"] == p["running"]
    assert set(parsed["queue_depth"]) == {
        "interactive", "bulk", "background"}
    # unknown keys are IGNORED (a newer minor schema interops)
    extra = dict(p, future_field={"x": 1}, other=3)
    assert fleet_sched.parse_sched_payload(extra) == parsed


@pytest.mark.parametrize("raw", [
    None, "garbage", 42, [],
    {},                                        # no schema_version
    {"schema_version": "x"},                   # unparseable version
    {"schema_version": 0, "headroom_bytes": 1, "running": 0},
    {"schema_version": 1},                     # missing sched fields
    {"schema_version": 1, "headroom_bytes": True, "running": 0},
    {"schema_version": 1, "headroom_bytes": 5, "running": "no"},
])
def test_malformed_sched_payload_means_no_headroom(raw):
    assert fleet_sched.parse_sched_payload(raw) is None


def test_member_without_sched_fields_is_local_only(monkeypatch):
    """Satellite 2 degradation: a replica whose heartbeat predates the
    sched schema is never placed onto — even when local is FULL the
    submission queues locally (with the snapshot as evidence)."""
    old = {"member_id": "old@h", "base_url": "http://127.0.0.1:9",
           "state": "alive", "routable": True, "sched": None}
    older = dict(old, member_id="older@h", sched={"load": 0.3})
    _gossip([old, older], epoch=3)
    monkeypatch.setattr(fleet_sched, "_local_headroom_bytes", lambda: 0)
    placement, snap = fleet_sched.place_for_submit(
        "interactive", "default", 10_000)
    assert placement is None
    assert snap is not None and snap["no_headroom"] is True
    assert snap["epoch"] == 3
    assert snap["members"] == []       # neither was placement-eligible


# ---------------- placement ------------------------------------------


def test_fleet_absent_places_local():
    assert fleet_sched.current_view() is None
    assert fleet_sched.place_for_submit(
        "interactive", "default", 1234) == (None, None)


def test_full_local_places_on_member_with_headroom(monkeypatch):
    _gossip([_m("a@h", headroom=5_000, running=2),
             _m("b@h", headroom=50_000, running=0),
             _m("c@h", headroom=-1, running=3, accepting=False),
             _m("d@h", headroom=50_000, state="suspect")], epoch=11)
    monkeypatch.setattr(fleet_sched, "_local_headroom_bytes", lambda: 0)
    placement, snap = fleet_sched.place_for_submit(
        "interactive", "default", 20_000)
    assert snap is None
    # a@h does not fit, c@h is not accepting, d@h is not alive
    assert placement["member"]["member_id"] == "b@h"
    assert placement["epoch"] == 11    # the decision pins the epoch


def test_idle_local_wins_ties():
    _gossip([_m("a@h", headroom=-1)])
    # the real (idle) scheduler advertises unlimited local headroom
    assert fleet_sched.place_for_submit(
        "interactive", "default", 1000) == (None, None)


def test_no_headroom_anywhere_queues_local_with_snapshot(monkeypatch):
    _gossip([_m("a@h", headroom=100), _m("b@h", headroom=200)], epoch=5)
    monkeypatch.setattr(fleet_sched, "_local_headroom_bytes", lambda: 0)
    placement, snap = fleet_sched.place_for_submit(
        "interactive", "default", 1_000_000)
    assert placement is None
    assert snap["no_headroom"] is True and snap["epoch"] == 5
    assert {m["member_id"]: m["headroom_bytes"]
            for m in snap["members"]} == {"a@h": 100, "b@h": 200}


def test_grid_wave_spreads_round_robin():
    """bulk + non-default share (a grid/AutoML wave) fans children
    across local + every fitting member instead of serializing."""
    _gossip([_m("m2@x"), _m("m1@x")])
    picks = []
    for _ in range(4):
        placement, _snap = fleet_sched.place_for_submit(
            "bulk", "wave_rr", 1000)
        picks.append(placement["member"]["member_id"]
                     if placement else None)
    # slots are [local, m1, m2] (members in stable id order)
    assert picks == [None, "m1@x", "m2@x", None]


# ---------------- remote submission over REST (one process) -----------


def test_remote_submit_rest_roundtrip(tmp_path, monkeypatch):
    """POST /3/FleetSched/submit end to end: the target trains under
    the ORIGINAL priority class + share group, registers the model in
    its DKV, and exports the result artifact the submitter's proxy
    finalizes from — bit-identical to a direct local train."""
    from h2o3_tpu.api.server import H2OApiServer
    monkeypatch.setenv("H2O3_RECOVERY_DIR", str(tmp_path / "rec"))
    fr = _frame(n=1500, seed=2, key="fsub_frame")
    kw = dict(ntrees=4, max_depth=3, seed=2, min_rows=1.0)
    ref = GBM(**kw)
    ref.train(y="y", training_frame=fr)
    exported = fleet_sched._export_frame(fr)
    assert exported is not None
    frame_path, frame_key = exported
    srv = H2OApiServer(port=0).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        payload = {
            "schema_version": 1, "algo": "gbm",
            "params": dict(kw, model_id="fsub_gbm"),
            "y": "y", "x": None,
            "frame_path": frame_path, "frame_key": frame_key,
            "priority": "bulk", "share": "waveX",
            "trace_id": "tr-fsub", "model_key": "fsub_gbm",
            "result_path": fleet_sched._result_path("fsub_gbm"),
            "resuming": False, "submitter": "test@h"}
        out = _post(f"{base}/3/FleetSched/submit", payload)
        assert out["ok"] is True and out["job_key"]
        # job status travels on /3/Jobs (the proxy's poll surface)
        deadline = time.monotonic() + 300
        while True:
            j = _get(f"{base}/3/Jobs/{out['job_key']}")["jobs"][0]
            if j["status"] in ("DONE", "FAILED", "CANCELLED"):
                break
            assert time.monotonic() < deadline, "remote train hung"
            time.sleep(0.05)
        assert j["status"] == "DONE", j
        got = dkv.get("fsub_gbm", "model")
        assert got.ntrees_built == kw["ntrees"]
        _assert_trees_equal(ref.model, got, "remote submit: ")
        # the result artifact lands for the submitter's proxy
        rp = fleet_sched._result_path("fsub_gbm")
        deadline = time.monotonic() + 60
        while not os.path.exists(rp):
            assert time.monotonic() < deadline, "result never exported"
            time.sleep(0.05)
        from h2o3_tpu.persist import load_model
        _assert_trees_equal(ref.model, load_model(rp), "artifact: ")
        assert fleet_sched.counters()["remote_received"] >= 1
        # an unsupported algo is a 400, not a zombie job
        bad = dict(payload, algo="weirdo", model_key="bad_key",
                   params={})
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/3/FleetSched/submit", bad)
        assert ei.value.code == 400
    finally:
        srv.stop()
        dkv.remove("fsub_gbm")


# ---------------- satellite 3: cluster scheduler snapshot --------------


def test_cluster_scope_merges_replicas_and_flags_dead_peers():
    from h2o3_tpu.api.server import H2OApiServer
    srv = H2OApiServer(port=0).start()
    try:
        r = fleet.router()
        m = r.table.join("dead@h", "http://127.0.0.1:9",
                         heartbeat_s=30.0, routable=True)
        r.table.heartbeat("dead@h", m.incarnation, routable=True)
        snap = _get(f"http://127.0.0.1:{srv.port}"
                    "/3/Scheduler?scope=cluster")
        assert snap["scope"] == "cluster"
        assert snap["totals"]["replicas"] >= 1
        # the dead peer is FLAGGED, never fatal
        assert any("127.0.0.1:9" in f["peer"]
                   for f in snap["peers_failed"])
        assert "counters" in snap
        # the default scope is untouched
        local = _get(f"http://127.0.0.1:{srv.port}/3/Scheduler")
        assert local["__meta"]["schema_name"] == "SchedulerV3"
    finally:
        srv.stop()
        fleet.reset()


# ---------------- satellite 1 + evict fallback (one process) ----------


_EV_KW = dict(ntrees=12, max_depth=3, seed=4, min_rows=1.0,
              score_tree_interval=0, stopping_rounds=0)


def test_manifest_carries_priority_share_and_local_evict_fallback(
        tmp_path, monkeypatch):
    """The recovery manifest records the ORIGINAL priority class, share
    group and owning member (satellite 1); resubmitting it with no
    live member falls back to a LOCAL resume — a 1-survivor fleet
    still finishes the train, bit-identically."""
    recdir = tmp_path / "rec"
    monkeypatch.setenv("H2O3_RECOVERY_DIR", str(recdir))
    fr = _frame(n=1200, seed=6, key="fev_frame")
    ref = GBM(**_EV_KW)
    ref.train(y="y", training_frame=fr)

    fleet_sched.set_local_member("victim@h", None)
    faults.configure("execute@train:every=1:after=1:times=1:exc=Fatal")
    crashed = GBM(model_id="fev_gbm",
                  in_training_checkpoints_dir=str(tmp_path / "ck"),
                  in_training_checkpoints_tree_interval=3, **_EV_KW)
    with pytest.raises(RuntimeError):
        with sched.submit_context(priority="bulk", share="tenantE"):
            crashed.train(y="y", training_frame=fr)
    faults.configure(None)

    ents, _ = recovery.scan(quarantine=False)
    assert len(ents) == 1
    ent = ents[0]
    assert ent["priority"] == "bulk"          # satellite 1
    assert ent["share"] == "tenantE"
    assert ent["member_id"] == "victim@h"
    assert ent["ckpt_trees"] and ent["ckpt_trees"] < _EV_KW["ntrees"]

    # the fleet has no other member: the resubmit resumes LOCALLY from
    # the last chunk commit
    assert fleet_sched._resubmit_manifest(ent) is True
    recovery.wait_for_recoveries(timeout=300)
    got = dkv.get("fev_gbm", "model")
    assert got.ntrees_built == _EV_KW["ntrees"]
    _assert_trees_equal(ref.model, got, "evict fallback: ")
    assert os.listdir(recdir / "manifests") == []
    dkv.remove("fev_gbm")


# ---------------- two-process fleet (slow tier) ------------------------


def _replica_src(router_port):
    """An idle fleet replica: REST surface + agent, no work of its
    own — everything it trains arrives via /3/FleetSched/submit."""
    return textwrap.dedent(f"""
        import sys, threading
        sys.path.insert(0, {_REPO!r})
        from h2o3_tpu.api.server import H2OApiServer
        from h2o3_tpu.fleet import FleetAgent
        srv = H2OApiServer(port=0).start()
        agent = FleetAgent(f"http://127.0.0.1:{{srv.port}}",
                           router_url="http://127.0.0.1:{router_port}")
        agent.start()
        print("REPLICA_READY", srv.port, flush=True)
        threading.Event().wait()
    """)


def _spawn_replica(router, recdir, n=1, spawn_deadline_s=300.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               H2O3_RECOVERY_DIR=str(recdir),
               H2O3_FLEET_HEARTBEAT_MS=HB_MS,
               H2O3_FLEET_SEEDS=f"127.0.0.1:{router}")
    src = _replica_src(router)
    procs = [subprocess.Popen([sys.executable, "-c", src], env=env,
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
             for _ in range(n)]
    return procs


def _wait_members(router, want, procs, deadline_s=300.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        live = router.table.live_members()
        if len(live) >= want:
            return live
        assert not any(p.poll() is not None for p in procs), \
            "a replica died during spawn"
        time.sleep(0.25)
    raise AssertionError(
        f"only {len(router.table.live_members())}/{want} replicas "
        f"joined before the deadline")


def _kill_all(procs):
    for p in procs:
        try:
            p.kill()
            p.wait(timeout=30)
        except Exception:
            pass


_MIG_KW = dict(ntrees=18, max_depth=3, seed=7, min_rows=1.0,
               score_tree_interval=2, stopping_rounds=0)


@pytest.mark.slow
def test_cross_replica_migrate_parity(tmp_path, monkeypatch):
    """Acceptance: a bulk train preempted on replica A hands its DKV
    checkpoint to replica B (real process, REST) and resumes
    BIT-identically; the local job follows the remote run on /3/Jobs
    and finishes DONE with the migrated model as its result."""
    from h2o3_tpu.api.server import H2OApiServer
    recdir = tmp_path / "rec"
    monkeypatch.setenv("H2O3_RECOVERY_DIR", str(recdir))
    monkeypatch.setenv("H2O3_FLEET_HEARTBEAT_MS", HB_MS)
    fr = _frame(n=2000, seed=3, key="fmig_frame")
    vfr = _frame(n=400, seed=9)               # keys the preemptor OFF
    twin = GBM(**_MIG_KW)                     # the fleet (no frame key)
    twin.train(y="y", training_frame=fr)

    srv = H2OApiServer(port=0).start()
    router = fleet.router()
    procs = _spawn_replica(srv.port, recdir)
    try:
        _wait_members(router, 1, procs)
        memman.reset(budget=500_000)
        victim = GBM(model_id="fmig_gbm", **_MIG_KW)
        with sched.submit_context(priority="bulk"):
            victim.train(y="y", training_frame=fr, background=True)
        deadline = time.monotonic() + 120
        while victim.job.status == jobs.QUEUED:
            assert time.monotonic() < deadline, "victim never ran"
            time.sleep(0.005)
        # the interactive preemptor carries a validation frame, so it
        # is NOT placement-eligible: it preempts locally by design
        hi = GBM(ntrees=3, max_depth=3, seed=1, min_rows=1.0)
        hi.train(y="y", training_frame=fr, validation_frame=vfr,
                 background=True)
        hi.job.join(300.0)
        victim.job.join(600.0)
        assert hi.job.status == jobs.DONE, hi.job.exception_msg
        assert victim.job.status == jobs.DONE, victim.job.exception_msg
        assert victim.job.preempt_count >= 1, "victim never preempted"
        assert fleet_sched.counters()["migrations"] >= 1, \
            "the preempted train never migrated"
        assert victim._sched_entry.remote_member is not None
        resumed = victim.job.result
        assert resumed.ntrees_built == _MIG_KW["ntrees"]
        _assert_trees_equal(twin.model, resumed, "migrate: ")
        # the ORIGINAL local job key reports DONE over /3/Jobs
        j = _get(f"http://127.0.0.1:{srv.port}"
                 f"/3/Jobs/{victim.job.key}")["jobs"][0]
        assert j["status"] == "DONE"
    finally:
        _kill_all(procs)
        fleet.reset()
        memman.reset()


@pytest.mark.slow
def test_elastic_join_absorbs_queued_children(tmp_path, monkeypatch):
    """Acceptance: a grid-style wave queued on a budget that fits one
    train fans onto a replica that joins MID-wave — every child
    completes, at least one on the new member."""
    from h2o3_tpu.api.server import H2OApiServer
    recdir = tmp_path / "rec"
    monkeypatch.setenv("H2O3_RECOVERY_DIR", str(recdir))
    monkeypatch.setenv("H2O3_FLEET_HEARTBEAT_MS", HB_MS)
    fr = _frame(n=4000, seed=0, key="fjoin_frame")
    srv = H2OApiServer(port=0).start()
    router = fleet.router()
    procs = []
    try:
        memman.reset(budget=500_000)
        ests = [GBM(ntrees=3, max_depth=3, seed=i, min_rows=1.0)
                for i in range(4)]
        with sched.submit_context(priority="bulk", share="wave1"):
            for e in ests:
                e.train(y="y", training_frame=fr, background=True)
        # no members yet: everything queued/running locally
        assert all(e._sched_entry.remote_member is None for e in ests)
        procs = _spawn_replica(srv.port, recdir)
        _wait_members(router, 1, procs)
        deadline = time.monotonic() + 600
        for e in ests:
            e.job.join(max(deadline - time.monotonic(), 1.0))
        assert all(e.job.status == jobs.DONE for e in ests), \
            [(e.job.status, e.job.exception_msg) for e in ests]
        assert all(e.job.result.ntrees_built == 3 for e in ests)
        moved = [e for e in ests
                 if e._sched_entry.remote_member is not None]
        assert moved, "the joining replica absorbed no queued child"
        assert fleet_sched.counters()["rebalanced"] >= len(moved)
    finally:
        _kill_all(procs)
        fleet.reset()
        memman.reset()


_EVICT_KW = dict(ntrees=40, max_depth=3, seed=11, min_rows=1.0,
                 score_tree_interval=0, stopping_rounds=0)


@pytest.mark.slow
def test_evicted_replica_requeues_running_train(tmp_path, monkeypatch):
    """Acceptance: SIGKILL a replica mid-train — its recovery manifest
    (original priority/share + last chunk commit) re-queues fleet-wide;
    with no other member the router itself resumes it, bit-identical."""
    from h2o3_tpu.api.server import H2OApiServer
    recdir = tmp_path / "rec"
    ck = tmp_path / "ck"
    monkeypatch.setenv("H2O3_RECOVERY_DIR", str(recdir))
    monkeypatch.setenv("H2O3_FLEET_HEARTBEAT_MS", HB_MS)
    fr = _frame(n=2000, seed=5, key="fevict_frame")
    ref = GBM(**_EVICT_KW)
    ref.train(y="y", training_frame=fr)
    exported = fleet_sched._export_frame(fr)
    assert exported is not None
    frame_path, frame_key = exported

    srv = H2OApiServer(port=0).start()
    router = fleet.router()
    procs = _spawn_replica(srv.port, recdir)
    try:
        live = _wait_members(router, 1, procs)
        child = live[0]
        payload = {
            "schema_version": 1, "algo": "gbm",
            "params": dict(_EVICT_KW, model_id="fevict_gbm",
                           in_training_checkpoints_dir=str(ck),
                           in_training_checkpoints_tree_interval=5),
            "y": "y", "x": None,
            "frame_path": frame_path, "frame_key": frame_key,
            "priority": "bulk", "share": "tenantK",
            "trace_id": "tr-evict", "model_key": "fevict_gbm",
            "result_path": fleet_sched._result_path("fevict_gbm"),
            "resuming": False, "submitter": "parent@h"}
        out = _post(f"{child.base_url}/3/FleetSched/submit", payload)
        assert out["ok"] is True
        # SIGKILL the replica at its first durable chunk commit
        deadline = time.monotonic() + 300
        while not (ck.exists() and any(
                f.startswith("fevict_gbm_t") for f in os.listdir(ck))):
            assert time.monotonic() < deadline, "no checkpoint landed"
            time.sleep(0.05)
        os.kill(procs[0].pid, signal.SIGKILL)
        procs[0].wait(timeout=30)
        # the manifest carries the original class/share + owner
        ents, _ = recovery.scan(quarantine=False)
        mine = [e for e in ents if e["model_key"] == "fevict_gbm"]
        assert mine and mine[0]["priority"] == "bulk"
        assert mine[0]["share"] == "tenantK"
        assert mine[0]["member_id"] == child.member_id
        # eviction fires the fleet-wide requeue (local fallback here)
        deadline = time.monotonic() + 60
        while router.table.get(child.member_id) is not None:
            assert time.monotonic() < deadline, "never evicted"
            time.sleep(0.05)
        deadline = time.monotonic() + 60
        while fleet_sched.counters()["evict_requeues"] < 1:
            assert time.monotonic() < deadline, "never re-queued"
            time.sleep(0.05)
        recovery.wait_for_recoveries(timeout=600)
        got = dkv.get("fevict_gbm", "model")
        assert got.ntrees_built == _EVICT_KW["ntrees"]
        _assert_trees_equal(ref.model, got, "evict requeue: ")
    finally:
        _kill_all(procs)
        fleet.reset()
        dkv.remove("fevict_gbm")
