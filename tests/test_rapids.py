"""Rapids engine tests — parser, frame algebra, group-by, merge, sort
(VERDICT r3 task #8 done-criterion: group_by aggregation + inner merge
with golden results)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import dkv
from h2o3_tpu.rapids import exec_rapids, group_by, merge, parse_rapids


@pytest.fixture(autouse=True)
def _clean_store():
    yield
    dkv.clear()


def _reg(key, fr):
    dkv.put(key, "frame", fr)
    return key


def test_parser_shapes():
    node = parse_rapids("(mean (cols_py fr1 'x') True)")
    assert node[0] == "call"
    ops = node[1]
    assert ops[0] == ("id", "mean")
    inner = ops[1]
    assert inner[1][0] == ("id", "cols_py")
    assert inner[1][2] == ("str", "x")


def test_mean_and_arithmetic():
    fr = h2o.Frame.from_numpy({"x": np.array([1.0, 2.0, 3.0, np.nan]),
                               "y": np.array([10.0, 20.0, 30.0, 40.0])})
    _reg("fr1", fr)
    # mean is frame-valued (AstMean semantics); getrow flattens it
    r = exec_rapids("(getrow (mean (cols_py fr1 'x') True 0))")
    assert r["scalar"][0] == pytest.approx(2.0)
    r = exec_rapids("(tmp= py_1 (+ (cols_py fr1 'y') 5))")
    out = dkv.get("py_1", "frame")
    np.testing.assert_allclose(out.vec(0).to_numpy(), [15, 25, 35, 45])
    r = exec_rapids("(sum (* (cols_py fr1 'y') 2) True)")
    assert r["scalar"] == pytest.approx(200.0)


def test_rows_selection_and_comparison():
    fr = h2o.Frame.from_numpy({"a": np.arange(10).astype(np.float32)})
    _reg("f", fr)
    r = exec_rapids("(tmp= s1 (rows f (> (cols_py f 'a') 6)))")
    out = dkv.get("s1", "frame")
    np.testing.assert_allclose(out.vec(0).to_numpy(), [7, 8, 9])
    r = exec_rapids("(tmp= s2 (rows f [2:3]))")
    out = dkv.get("s2", "frame")
    np.testing.assert_allclose(out.vec(0).to_numpy(), [2, 3, 4])


def test_group_by_goldens():
    g = np.array(["a", "b", "a", "b", "c"], dtype=object)
    v = np.array([1.0, 2.0, 3.0, 4.0, 10.0], dtype=np.float32)
    fr = h2o.Frame.from_numpy({"g": g, "v": v})
    out = group_by(fr, ["g"], [("sum", "v"), ("mean", "v"), ("nrow", None),
                               ("max", "v")])
    labels = out.vec("g").to_strings()
    rows = {lab: i for i, lab in enumerate(labels)}
    sums = out.vec("sum_v").to_numpy()
    means = out.vec("mean_v").to_numpy()
    cnts = out.vec("nrow").to_numpy()
    maxs = out.vec("max_v").to_numpy()
    assert sums[rows["a"]] == 4.0 and sums[rows["b"]] == 6.0
    assert means[rows["c"]] == 10.0
    assert cnts[rows["a"]] == 2 and cnts[rows["c"]] == 1
    assert maxs[rows["b"]] == 4.0
    # via the AST surface (GB op, as h2o-py GroupBy emits)
    _reg("gfr", fr)
    r = exec_rapids('(tmp= gb1 (GB gfr [0] "sum" 1 "all" "nrow" [] "all"))')
    out2 = dkv.get("gb1", "frame")
    assert out2.nrow == 3
    assert set(out2.names) == {"g", "sum_v", "nrow"}


def test_inner_and_left_merge_goldens():
    left = h2o.Frame.from_numpy({
        "k": np.array(["x", "y", "z", "y"], dtype=object),
        "a": np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32)})
    right = h2o.Frame.from_numpy({
        "k": np.array(["y", "x", "w"], dtype=object),
        "b": np.array([10.0, 20.0, 30.0], dtype=np.float32)})
    inner = merge(left, right, ["k"], ["k"])
    got = {(lab, a): b for lab, a, b in zip(inner.vec("k").to_strings(),
                                            inner.vec("a").to_numpy(),
                                            inner.vec("b").to_numpy())}
    assert got == {("x", 1.0): 20.0, ("y", 2.0): 10.0, ("y", 4.0): 10.0}
    lj = merge(left, right, ["k"], ["k"], all_x=True)
    assert lj.nrow == 4
    zrow = [i for i, lab in enumerate(lj.vec("k").to_strings())
            if lab == "z"][0]
    assert np.isnan(lj.vec("b").to_numpy()[zrow])


def test_sort_and_unary():
    fr = h2o.Frame.from_numpy({"x": np.array([3.0, 1.0, 2.0]),
                               "y": np.array([30.0, 10.0, 20.0])})
    _reg("sf", fr)
    exec_rapids("(tmp= sorted1 (sort sf [0] [1]))")
    out = dkv.get("sorted1", "frame")
    np.testing.assert_allclose(out.vec("x").to_numpy(), [1, 2, 3])
    np.testing.assert_allclose(out.vec("y").to_numpy(), [10, 20, 30])
    r = exec_rapids("(sum (abs (- (cols_py sf 'x') 2)) True)")
    assert r["scalar"] == pytest.approx(2.0)


def test_ifelse_cbind_rbind():
    fr = h2o.Frame.from_numpy({"x": np.array([1.0, -2.0, 3.0])})
    _reg("f3", fr)
    exec_rapids("(tmp= pos1 (ifelse (> (cols_py f3 'x') 0) 1 0))")
    out = dkv.get("pos1", "frame")
    np.testing.assert_allclose(out.vec(0).to_numpy(), [1, 0, 1])
    exec_rapids("(tmp= cb1 (cbind f3 pos1))")
    cb = dkv.get("cb1", "frame")
    assert cb.ncol == 2
    exec_rapids("(tmp= rb1 (rbind f3 f3))")
    rb = dkv.get("rb1", "frame")
    assert rb.nrow == 6


def test_drop_column_negative_indices():
    fr = h2o.Frame.from_numpy({"a": np.array([1.0]), "b": np.array([2.0]),
                               "c": np.array([3.0])})
    _reg("d3", fr)
    # h2o-py drop emits -(idx+1): drop column 0 -> -1
    exec_rapids("(tmp= dr1 (cols_py d3 [-1]))")
    out = dkv.get("dr1", "frame")
    assert out.names == ["b", "c"]


def test_one_col_left_broadcast():
    fr = h2o.Frame.from_numpy({"a": np.array([1.0, 2.0]),
                               "b": np.array([10.0, 20.0])})
    _reg("bc", fr)
    exec_rapids("(tmp= bc1 (+ (cols_py bc 'a') bc))")
    out = dkv.get("bc1", "frame")
    np.testing.assert_allclose(out.vec("a").to_numpy(), [2, 4])
    np.testing.assert_allclose(out.vec("b").to_numpy(), [11, 22])


def test_rbind_preserves_enum_labels():
    f1 = h2o.Frame.from_numpy({"c": np.array(["x", "y"], dtype=object)})
    f2 = h2o.Frame.from_numpy({"c": np.array(["z", "x"], dtype=object)})
    _reg("rb_a", f1)
    _reg("rb_b", f2)
    exec_rapids("(tmp= rb2 (rbind rb_a rb_b))")
    out = dkv.get("rb2", "frame")
    assert list(out.vec("c").to_strings()) == ["x", "y", "z", "x"]


def test_colnames_partial_rename():
    fr = h2o.Frame.from_numpy({"a": np.array([1.0]), "b": np.array([2.0])})
    _reg("cn", fr)
    exec_rapids("(tmp= cn1 (colnames= cn [1] ['bee']))")
    out = dkv.get("cn1", "frame")
    assert out.names == ["a", "bee"]


def test_outer_merge_keeps_right_keys():
    left = h2o.Frame.from_numpy({
        "k": np.array(["x", "y"], dtype=object),
        "a": np.array([1.0, 2.0], dtype=np.float32)})
    right = h2o.Frame.from_numpy({
        "k": np.array(["y", "w"], dtype=object),
        "b": np.array([10.0, 30.0], dtype=np.float32)})
    out = merge(left, right, ["k"], ["k"], all_x=True, all_y=True)
    labels = list(out.vec("k").to_strings())
    assert "w" in labels   # right-only key survives, not NA
    wrow = labels.index("w")
    assert np.isnan(out.vec("a").to_numpy()[wrow])
    assert out.vec("b").to_numpy()[wrow] == 30.0


def test_rapids_string_time_misc_prims():
    import numpy as np
    from h2o3_tpu import dkv
    from h2o3_tpu.rapids import exec_rapids
    import h2o3_tpu as h2o
    # string ops
    fr = h2o.Frame.from_numpy({"s": np.asarray(
        [" Apple ", "BANANA", None], dtype=object)})
    dkv.put("sfr", "frame", fr)
    out = exec_rapids('(tmp= o1 (tolower (cols_py sfr "s")))')
    got = dkv.get("o1", "frame").vec(0).to_strings()
    assert got[0] == " apple " and got[2] is None
    exec_rapids('(tmp= o2 (trim (cols_py sfr "s")))')
    assert dkv.get("o2", "frame").vec(0).to_strings()[0] == "Apple"
    exec_rapids('(tmp= o3 (nchar (cols_py sfr "s")))')
    assert dkv.get("o3", "frame").vec(0).to_numpy()[1] == 6
    exec_rapids('(tmp= o4 (replaceall (tolower (cols_py sfr "s")) "a" "_" 0))')
    assert dkv.get("o4", "frame").vec(0).to_strings()[1] == "b_n_n_"
    # time ops: 2021-03-04 05:06:07 UTC
    import datetime as dtm
    ms = dtm.datetime(2021, 3, 4, 5, 6, 7,
                      tzinfo=dtm.timezone.utc).timestamp() * 1e3
    tfr = h2o.Frame.from_numpy({"t": np.asarray([ms])})
    dkv.put("tfr", "frame", tfr)
    for op, want in (("year", 2021), ("month", 3), ("day", 4),
                     ("hour", 5), ("minute", 6), ("second", 7),
                     ("dayOfWeek", 3)):       # 2021-03-04 is a Thursday
        exec_rapids(f'(tmp= tt (%s tfr))' % op)
        assert dkv.get("tt", "frame").vec(0).to_numpy()[0] == want, op
    # table + cumsum + which + na.omit + scale + round + cor
    nfr = h2o.Frame.from_numpy({"x": np.asarray([1.0, 2.0, np.nan, 2.0])})
    dkv.put("nfr", "frame", nfr)
    exec_rapids('(tmp= tb (table nfr))')
    tb = dkv.get("tb", "frame")
    assert list(tb.vec("Count").to_numpy()) == [1.0, 2.0]
    exec_rapids('(tmp= no (na.omit nfr))')
    assert dkv.get("no", "frame").nrow == 3
    exec_rapids('(tmp= cs (cumsum (na.omit nfr)))')
    assert list(dkv.get("cs", "frame").vec(0).to_numpy()) == [1, 3, 5]
    r = exec_rapids('(cor (cols_py (na.omit nfr) "x") (cols_py (na.omit nfr) "x"))')
    assert abs(r["scalar"] - 1.0) < 1e-9


def test_rapids_iso_week_and_time_na():
    import datetime as dtm
    import numpy as np
    import h2o3_tpu as h2o
    from h2o3_tpu import dkv
    from h2o3_tpu.rapids import exec_rapids
    # 2021-01-01 is ISO week 53 of 2020; 2021-01-04 (Mon) is week 1
    days = [dtm.datetime(2021, 1, 1, tzinfo=dtm.timezone.utc),
            dtm.datetime(2021, 1, 4, tzinfo=dtm.timezone.utc),
            dtm.datetime(2021, 7, 1, tzinfo=dtm.timezone.utc)]
    ms = np.asarray([d.timestamp() * 1e3 for d in days] + [np.nan])
    fr = h2o.Frame.from_numpy({"t": ms})
    dkv.put("wfr", "frame", fr)
    exec_rapids('(tmp= wk (week wfr))')
    got = dkv.get("wk", "frame").vec(0).to_numpy()
    want = [d.isocalendar()[1] for d in days]
    assert list(got[:3]) == want, (got, want)
    assert np.isnan(got[3])
