"""KMeans / PCA / XGBoost-compat estimator tests — sklearn parity goldens
(VERDICT r3 tasks #5c and #7)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.kmeans import H2OKMeansEstimator
from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator
from h2o3_tpu.models.xgboost import H2OXGBoostEstimator


def test_kmeans_recovers_blobs():
    rng = np.random.default_rng(0)
    n = 3000
    centers = np.array([[0.0, 0.0], [6.0, 6.0], [-6.0, 6.0]])
    yv = rng.integers(0, 3, n)
    X = (centers[yv] + rng.normal(size=(n, 2))).astype(np.float32)
    fr = h2o.Frame.from_numpy({"x1": X[:, 0], "x2": X[:, 1]})
    km = H2OKMeansEstimator(k=3, max_iterations=20, seed=1,
                            standardize=False)
    km.train(training_frame=fr)
    C = np.sort(np.round(km.model.centers()).astype(int), axis=0)
    np.testing.assert_array_equal(C, np.sort(centers, axis=0).astype(int))
    # assignments agree with ground truth up to label permutation
    pred = km.model.predict(fr).vec("predict").to_numpy().astype(int)
    from scipy.optimize import linear_sum_assignment
    cm = np.zeros((3, 3))
    for a, b in zip(pred, yv):
        cm[a, b] += 1
    r, c = linear_sum_assignment(-cm)
    acc = cm[r, c].sum() / n
    assert acc > 0.99, acc


def test_kmeans_vs_sklearn_inertia():
    from sklearn.cluster import KMeans as SKKMeans
    rng = np.random.default_rng(3)
    X = rng.normal(size=(2000, 5)).astype(np.float32) * [1, 2, 3, 1, 1]
    cols = {f"x{i}": X[:, i] for i in range(5)}
    fr = h2o.Frame.from_numpy(cols)
    km = H2OKMeansEstimator(k=8, max_iterations=30, seed=2,
                            standardize=False)
    km.train(training_frame=fr)
    sk = SKKMeans(n_clusters=8, n_init=3, random_state=0).fit(X)
    # within 15% of sklearn's inertia (different init; same objective)
    assert km.model.tot_withinss < sk.inertia_ * 1.15, \
        (km.model.tot_withinss, sk.inertia_)


def test_kmeans_save_load(tmp_path):
    rng = np.random.default_rng(5)
    X = rng.normal(size=(500, 3)).astype(np.float32)
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    km = H2OKMeansEstimator(k=4, seed=1)
    km.train(training_frame=fr)
    p = h2o.save_model(km.model, str(tmp_path), filename="km")
    m2 = h2o.load_model(p)
    np.testing.assert_allclose(m2.centers(), km.model.centers(), rtol=1e-6)
    p1 = km.model.predict(fr).vec("predict").to_numpy()
    p2 = m2.predict(fr).vec("predict").to_numpy()
    np.testing.assert_array_equal(p1, p2)


def test_pca_matches_sklearn():
    from sklearn.decomposition import PCA as SKPCA
    rng = np.random.default_rng(7)
    n = 3000
    Z = rng.normal(size=(n, 2)).astype(np.float32)
    A = np.array([[1.0, 0.5, 0.1, 0.0], [0.0, 1.0, 0.5, 0.2]],
                 dtype=np.float32)
    X = Z @ A + 0.01 * rng.normal(size=(n, 4)).astype(np.float32)
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    pca = H2OPrincipalComponentAnalysisEstimator(k=2, transform="demean")
    pca.train(training_frame=fr)
    sk = SKPCA(n_components=2).fit(X)
    # eigenvalues ≈ sklearn explained variance (ddof differences ~1/n)
    np.testing.assert_allclose(pca.model.eigval, sk.explained_variance_,
                               rtol=2e-2)
    # components match up to sign
    for j in range(2):
        ours = pca.model.eigvec[:, j]
        theirs = sk.components_[j]
        dot = abs(float(np.dot(ours, theirs)))
        assert dot > 0.999, (j, dot)
    # scores frame
    S = pca.model.predict(fr)
    assert S.names == ["PC1", "PC2"]
    sk_scores = sk.transform(X)
    got = np.stack([S.vec("PC1").to_numpy(), S.vec("PC2").to_numpy()], 1)
    for j in range(2):
        corr = np.corrcoef(got[:, j], sk_scores[:, j])[0, 1]
        assert abs(corr) > 0.999


def test_pca_importance_sums_to_one_with_all_components():
    rng = np.random.default_rng(9)
    X = rng.normal(size=(1000, 3)).astype(np.float32)
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    pca = H2OPrincipalComponentAnalysisEstimator(k=3,
                                                 transform="standardize")
    pca.train(training_frame=fr)
    imp = pca.model.importance
    assert abs(imp["cumulative_proportion"][-1] - 1.0) < 1e-3


def test_xgboost_estimator_param_mapping():
    xgb = H2OXGBoostEstimator(ntrees=7, max_depth=4, eta=0.2, subsample=0.8,
                              colsample_bytree=0.7, reg_lambda=2.0,
                              reg_alpha=0.1, min_child_weight=3.0,
                              gamma=0.01, seed=5)
    p = xgb.params
    assert p["learn_rate"] == 0.2
    assert p["sample_rate"] == 0.8
    assert p["col_sample_rate_per_tree"] == 0.7
    assert p["reg_lambda"] == 2.0
    assert p["reg_alpha"] == 0.1
    assert p["min_rows"] == 3.0
    assert p["min_split_improvement"] == 0.01


def test_xgboost_trains_binomial():
    rng = np.random.default_rng(11)
    n = 3000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    logit = 2 * X[:, 0] - X[:, 1]
    yv = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.array(["n", "p"], dtype=object)[yv]
    fr = h2o.Frame.from_numpy(cols)
    xgb = H2OXGBoostEstimator(ntrees=30, max_depth=4, eta=0.3, seed=1)
    xgb.train(y="y", training_frame=fr)
    assert xgb.model.training_metrics.auc > 0.9
    # xgboost-style L2 default (reg_lambda=1.0) shrinks leaves vs GBM
    assert xgb.model.params["reg_lambda"] == 1.0


def test_xgboost_dart_raises():
    with pytest.raises(NotImplementedError):
        H2OXGBoostEstimator(booster="dart")


def test_xgboost_gbm_spelled_params_win():
    xgb = H2OXGBoostEstimator(learn_rate=0.05, sample_rate=0.6)
    assert xgb.params["learn_rate"] == 0.05
    assert xgb.params["sample_rate"] == 0.6


def test_pca_use_all_factor_levels():
    rng = np.random.default_rng(13)
    n = 500
    lv = np.array(["a", "b", "c"])
    cat = rng.integers(0, 3, n)
    fr = h2o.Frame.from_numpy({"c": lv[cat],
                               "x": rng.normal(size=n).astype(np.float32)})
    p1 = H2OPrincipalComponentAnalysisEstimator(k=2)
    p1.train(training_frame=fr)
    p2 = H2OPrincipalComponentAnalysisEstimator(k=2,
                                                use_all_factor_levels=True)
    p2.train(training_frame=fr)
    assert len(p1.model.exp_names) == 3   # c.b, c.c, x
    assert len(p2.model.exp_names) == 4   # c.a, c.b, c.c, x
    assert p2.model.predict(fr).nrow == n
