"""Round-5 genmodel closure: CoxPH/word2vec/GLRM/isofor/GAM/ensemble
MOJO writers + readers, EasyPredict config modes. No JVM exists in this
image, so parity is reader-contract ROUND-TRIP (writer output parsed by
our readers) — the golden-file-vs-jar limitation is recorded per
artifact docstring (hex/genmodel/algos/*)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.mojo import export_mojo, read_mojo


def _reg_frame(n=400, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = 1.5 * x1 - 0.5 * x2 + 0.1 * rng.normal(size=n)
    return h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": y}), x1, x2


def test_coxph_mojo_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    n = 500
    x = rng.normal(size=n)
    t = rng.exponential(np.exp(-0.8 * x))
    ev = (rng.random(n) < 0.8).astype(np.float64)
    fr = h2o.Frame.from_numpy({"x": x, "stop": t, "event": ev})
    from h2o3_tpu.models.coxph import H2OCoxProportionalHazardsEstimator
    cox = H2OCoxProportionalHazardsEstimator(stop_column="stop",
                                             event_column="event")
    cox.train(x=["x"], training_frame=fr)
    p = str(tmp_path / "cox.zip")
    export_mojo(cox.model, p)
    s = read_mojo(p)
    lp = s.score(np.array([1.0]))
    beta = cox.model.beta[0]
    means = cox.model.impute_means.get("x", 0.0)
    assert abs(lp[0] - beta * (1.0 - means)) < 1e-5


def test_word2vec_mojo_roundtrip(tmp_path):
    from h2o3_tpu.frame.vec import T_STR, Vec
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.word2vec import H2OWord2vecEstimator
    words = ("alpha beta gamma . beta gamma delta . ").split() * 30
    wf = Frame(["C1"], [Vec.from_numpy(np.array(words, dtype=object),
                                       vtype=T_STR)])
    est = H2OWord2vecEstimator(vec_size=6, epochs=2, min_word_freq=1,
                               seed=3)
    est.train(training_frame=wf)
    p = str(tmp_path / "w2v.zip")
    export_mojo(est.model, p)
    s = read_mojo(p)
    v = s.transform("beta")
    ref = est.model.vectors[est.model._index["beta"]]
    np.testing.assert_allclose(v, ref, rtol=1e-6)
    assert np.isnan(s.transform("nope")).all()


def test_glrm_mojo_roundtrip(tmp_path):
    fr, _, _ = _reg_frame()
    from h2o3_tpu.models.glrm import H2OGeneralizedLowRankEstimator
    gl = H2OGeneralizedLowRankEstimator(k=2, max_iterations=40, seed=2)
    gl.train(training_frame=fr)
    p = str(tmp_path / "glrm.zip")
    export_mojo(gl.model, p)
    s = read_mojo(p)
    xrow = s.score(np.array([0.5, -0.2, 0.1]))
    assert xrow.shape == (2,) and np.isfinite(xrow).all()


def test_isofor_mojo_writes_trees(tmp_path):
    fr, _, _ = _reg_frame(seed=5)
    from h2o3_tpu.models.isoforest import H2OIsolationForestEstimator
    iso = H2OIsolationForestEstimator(ntrees=5, max_depth=4, seed=1)
    iso.train(training_frame=fr)
    p = str(tmp_path / "if.zip")
    export_mojo(iso.model, p)
    import zipfile
    with zipfile.ZipFile(p) as z:
        names = z.namelist()
    assert sum(n.startswith("trees/") and n.endswith(".bin")
               and "_aux" not in n for n in names) == 5
    assert "model.ini" in names


def test_gam_and_ensemble_mojo_write(tmp_path):
    fr, x1, x2 = _reg_frame(seed=7)
    from h2o3_tpu.models.gam import H2OGeneralizedAdditiveEstimator
    gam = H2OGeneralizedAdditiveEstimator(gam_columns=["x1"], num_knots=5,
                                          family="gaussian")
    gam.train(y="y", x=["x1", "x2"], training_frame=fr)
    pg = str(tmp_path / "gam.zip")
    export_mojo(gam.model, pg)
    import zipfile, json
    with zipfile.ZipFile(pg) as z:
        knots = json.loads(z.read("knots.json"))
    assert "x1" in knots and len(knots["x1"]) == 5

    from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    b1 = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, nfolds=2,
                                      seed=1,
                                      keep_cross_validation_predictions=True)
    b1.train(y="y", training_frame=fr)
    b2 = H2OGeneralizedLinearEstimator(family="gaussian", nfolds=2,
                                       seed=1,
                                       keep_cross_validation_predictions=True)
    b2.train(y="y", training_frame=fr)
    se = H2OStackedEnsembleEstimator(base_models=[b1.model, b2.model])
    se.train(y="y", training_frame=fr)
    pe = str(tmp_path / "se.zip")
    export_mojo(se.model, pe)
    with zipfile.ZipFile(pe) as z:
        names = z.namelist()
    assert "models/metalearner.zip" in names
    assert "models/base_0.zip" in names and "models/base_1.zip" in names


def test_easypredict_modes(tmp_path):
    rng = np.random.default_rng(2)
    n = 300
    x = rng.normal(size=n)
    g = np.array(["a", "b"], dtype=object)[rng.integers(0, 2, n)]
    y = np.where(g == "b", x, -x) + 0.1 * rng.normal(size=n)
    fr = h2o.Frame.from_numpy({"x": x, "g": g, "y": y})
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    gbm = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1,
                                       score_tree_interval=0)
    gbm.train(y="y", training_frame=fr)
    from h2o3_tpu.genmodel import EasyPredictModelWrapper
    # strict unknown-level mode raises; default maps to NA and counts
    strict = EasyPredictModelWrapper(
        gbm.model, convert_unknown_categorical_levels_to_na=False)
    with pytest.raises(ValueError, match="unknown categorical"):
        strict.predict_row({"x": 1.0, "g": "zzz"})
    soft = EasyPredictModelWrapper(gbm.model)
    out = soft.predict_row({"x": 1.0, "g": "zzz"})
    assert "value" in out
    assert soft.unknown_categorical_levels_seen == {"g": 1}
    # contributions + leaf pass-through
    rich = EasyPredictModelWrapper(gbm.model, enable_contributions=True,
                                   enable_leaf_assignment=True)
    out2 = rich.predict_row({"x": 1.0, "g": "a"})
    contrib = out2["contributions"]
    total = sum(contrib.values())
    assert abs(total - out2["value"]) < 1e-3
    assert len(out2["leafNodeAssignments"]) == 4


def test_coxph_mojo_with_categoricals(tmp_path):
    """Cats-first layout round trip (the review's expanded-vs-raw
    misalignment scenario)."""
    rng = np.random.default_rng(9)
    n = 500
    g = np.array(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)]
    x = rng.normal(size=n)
    t = rng.exponential(np.exp(-0.5 * x - (g == "b") * 0.8))
    ev = np.ones(n)
    fr = h2o.Frame.from_numpy({"g": g, "x": x, "stop": t, "event": ev})
    from h2o3_tpu.models.coxph import H2OCoxProportionalHazardsEstimator
    cox = H2OCoxProportionalHazardsEstimator(stop_column="stop",
                                             event_column="event")
    cox.train(x=["g", "x"], training_frame=fr)
    p = str(tmp_path / "coxc.zip")
    export_mojo(cox.model, p)
    s = read_mojo(p)
    # row in MOJO column order: cats first (g), then nums (x)
    lp_b = s.score(np.array([1.0, 0.0]))[0]     # g='b', x=0
    lp_a = s.score(np.array([0.0, 0.0]))[0]     # g='a' (dropped level)
    co = cox.model.coef()
    assert abs((lp_b - lp_a) - co["g.b"]) < 1e-5


def test_isofor_mojo_scores(tmp_path):
    fr, _, _ = _reg_frame(seed=11)
    from h2o3_tpu.models.isoforest import H2OIsolationForestEstimator
    iso = H2OIsolationForestEstimator(ntrees=6, max_depth=4, seed=2)
    iso.train(training_frame=fr)
    p = str(tmp_path / "if2.zip")
    export_mojo(iso.model, p)
    s = read_mojo(p)
    # inlier (near data) should have a LONGER mean path than an outlier
    inlier = s.score(np.array([0.0, 0.0, 0.0]))[0]
    outlier = s.score(np.array([40.0, -40.0, 0.0]))[0]
    assert np.isfinite(inlier) and np.isfinite(outlier)
    assert outlier <= inlier + 1e-9


def test_pca_mojo_roundtrip(tmp_path):
    from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator
    from h2o3_tpu.mojo import read_mojo
    rng = np.random.default_rng(4)
    X = rng.normal(size=(300, 5)).astype(np.float64)
    X[:, 3] = X[:, 0] * 2 + 0.1 * rng.normal(size=300)
    fr = h2o.Frame.from_numpy({f"c{i}": X[:, i] for i in range(5)})
    pca = H2OPrincipalComponentAnalysisEstimator(k=3, seed=1)
    pca.train(training_frame=fr)
    p = pca.model.download_mojo(str(tmp_path))
    scorer = read_mojo(p)
    want = np.asarray(pca.model.predict(fr).to_numpy())[:5, :3]
    got = np.stack([scorer.score(X[i]) for i in range(5)])
    np.testing.assert_allclose(np.abs(got[:, :3]), np.abs(want),
                               rtol=1e-4, atol=1e-4)


def test_isotonic_mojo_roundtrip(tmp_path):
    from h2o3_tpu.models.isotonic import H2OIsotonicRegressionEstimator
    from h2o3_tpu.mojo import read_mojo
    rng = np.random.default_rng(5)
    x = np.sort(rng.uniform(0, 10, 400))
    y = np.log1p(x) + 0.1 * rng.normal(size=400)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    iso = H2OIsotonicRegressionEstimator()
    iso.train(y="y", training_frame=fr)
    path = iso.model.download_mojo(str(tmp_path))
    scorer = read_mojo(path)
    pred = np.asarray(iso.model.predict(fr).to_numpy()).ravel()[:10]
    got = np.array([scorer.score(np.array([v]))[0] for v in x[:10]])
    np.testing.assert_allclose(got, pred, rtol=1e-5, atol=1e-5)
    assert np.isnan(scorer.score(np.array([np.nan]))[0])


def test_psvm_mojo_roundtrip_exact_and_rff(tmp_path):
    from h2o3_tpu.models.psvm import H2OSupportVectorMachineEstimator
    from h2o3_tpu.mojo import read_mojo
    rng = np.random.default_rng(6)
    n = 300
    X = rng.normal(size=(n, 3))
    yl = np.where(X[:, 0] + X[:, 1] > 0, "p", "n").astype(object)
    fr = h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(3)}, "y": yl})
    for extra in ({}, {"rank_ratio": 0.2}):      # exact then RFF
        svm = H2OSupportVectorMachineEstimator(
            gamma=0.7, hyper_param=1.0, max_iterations=120, seed=2,
            **extra)
        svm.train(y="y", training_frame=fr)
        path = svm.model.download_mojo(str(tmp_path))
        scorer = read_mojo(path)
        dec_model = np.asarray(
            svm.model.decision_function(np.asarray(X, np.float32)))[:20]
        p1 = np.array([scorer.score(X[i])[2] for i in range(20)])
        dec_scored = np.log(p1 / (1 - p1)) / 2.0
        np.testing.assert_allclose(dec_scored, dec_model, rtol=2e-2,
                                   atol=2e-2)


def test_pca_psvm_mojo_categorical_refusal(tmp_path):
    """Categorical-design PCA/PSVM models must refuse MOJO export with
    a clear message (raw-row wire format cannot carry the expansion)
    instead of writing a silently broken artifact."""
    from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator
    rng = np.random.default_rng(7)
    fr = h2o.Frame.from_numpy({
        "num": rng.normal(size=100),
        "cat": np.array(["a", "b", "c"], dtype=object)[
            rng.integers(0, 3, 100)]})
    pca = H2OPrincipalComponentAnalysisEstimator(k=2, seed=1)
    pca.train(training_frame=fr)
    with pytest.raises(NotImplementedError, match="numeric-only"):
        pca.model.download_mojo(str(tmp_path))


def test_targetencoder_mojo_roundtrip(tmp_path):
    from h2o3_tpu.models.targetencoder import H2OTargetEncoderEstimator
    from h2o3_tpu.mojo import read_mojo
    rng = np.random.default_rng(8)
    n = 400
    lv = np.array(["a", "b", "c", "d"], dtype=object)
    c = rng.integers(0, 4, n)
    y = 0.2 * c + rng.normal(scale=0.1, size=n)
    fr = h2o.Frame.from_numpy({"cat": lv[c], "y": y})
    te = H2OTargetEncoderEstimator(blending=True, noise=0,
                                   inflection_point=5, smoothing=10)
    te.train(x=["cat"], y="y", training_frame=fr)
    path = te.model.download_mojo(str(tmp_path))
    scorer = read_mojo(path)
    enc = te.model.transform(fr)
    te_col = np.asarray(enc.vec("cat_te").to_numpy())[:n]
    for code in range(4):
        i = int(np.nonzero(c == code)[0][0])
        got = scorer.score(np.array([float(code), np.nan]))[0]
        assert abs(got - te_col[i]) < 1e-6, (code, got, te_col[i])
    # unseen / NA level falls back to the prior
    assert abs(scorer.score(np.array([np.nan, np.nan]))[0]
               - te.model.prior) < 1e-12
