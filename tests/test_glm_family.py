"""GAM, ANOVA-GLM, ModelSelection, RuleFit tests (reference: hex/gam,
hex/anovaglm, hex/modelselection, hex/rulefit test style)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.anovaglm import H2OANOVAGLMEstimator
from h2o3_tpu.models.gam import H2OGeneralizedAdditiveEstimator
from h2o3_tpu.models.modelselection import H2OModelSelectionEstimator
from h2o3_tpu.models.rulefit import H2ORuleFitEstimator


def _smooth_frame(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-3, 3, n)
    z = rng.normal(size=n)
    y = np.sin(x) + 0.5 * z + rng.normal(scale=0.2, size=n)
    return h2o.Frame.from_numpy({"x": x, "z": z, "y": y}), x, z, y


def test_gam_beats_linear_on_smooth_signal():
    fr, x, z, y = _smooth_frame()
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    lin = H2OGeneralizedLinearEstimator(Lambda=[0.0])
    lin.train(y="y", x=["x", "z"], training_frame=fr)
    gam = H2OGeneralizedAdditiveEstimator(gam_columns=["x"], num_knots=8)
    gam.train(y="y", x=["x", "z"], training_frame=fr)
    assert gam.model.rmse() < lin.model.rmse() * 0.8, (
        gam.model.rmse(), lin.model.rmse())
    # prediction shape + determinism
    p1 = gam.model.predict(fr).vec("predict").to_numpy()
    p2 = gam.model.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(p1, p2)


def test_gam_save_load(tmp_path):
    fr, *_ = _smooth_frame(n=500, seed=2)
    gam = H2OGeneralizedAdditiveEstimator(gam_columns=["x"], num_knots=6)
    gam.train(y="y", x=["x", "z"], training_frame=fr)
    p = h2o.save_model(gam.model, str(tmp_path), filename="gam")
    m2 = h2o.load_model(p)
    p1 = gam.model.predict(fr).vec("predict").to_numpy()
    p2 = m2.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_anovaglm_identifies_significant_terms():
    rng = np.random.default_rng(5)
    n = 1500
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    noise = rng.normal(size=n)          # irrelevant predictor
    y = 2.0 * x1 + 0.0 * x2 + rng.normal(scale=0.5, size=n)
    fr = h2o.Frame.from_numpy({"x1": x1, "noise": noise, "y": y})
    an = H2OANOVAGLMEstimator(highest_interaction_term=1)
    an.train(y="y", x=["x1", "noise"], training_frame=fr)
    table = {r["term"]: r for r in an.model.anova_table}
    assert table["x1"]["p_value"] < 1e-6
    assert table["noise"]["p_value"] > 0.01
    # interaction term appears when requested
    an2 = H2OANOVAGLMEstimator(highest_interaction_term=2)
    an2.train(y="y", x=["x1", "noise"], training_frame=fr)
    assert any(":" in r["term"] for r in an2.model.anova_table)


def test_modelselection_maxr_finds_true_predictors():
    rng = np.random.default_rng(7)
    n = 1000
    X = rng.normal(size=(n, 5))
    y = 3 * X[:, 0] - 2 * X[:, 2] + rng.normal(scale=0.3, size=n)
    fr = h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(5)}, "y": y})
    ms = H2OModelSelectionEstimator(mode="maxr", max_predictor_number=3)
    ms.train(y="y", training_frame=fr)
    res = ms.model.result()
    assert len(res) == 3
    assert res[0]["predictors"] == ["x0"]           # strongest first
    assert set(res[1]["predictors"]) == {"x0", "x2"}
    # r2 increases with size
    assert res[0]["r2"] < res[1]["r2"] <= res[2]["r2"] + 1e-9


def test_modelselection_backward():
    rng = np.random.default_rng(9)
    n = 800
    X = rng.normal(size=(n, 4))
    y = X[:, 1] * 2 + rng.normal(scale=0.3, size=n)
    fr = h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)}, "y": y})
    ms = H2OModelSelectionEstimator(mode="backward", min_predictor_number=1)
    ms.train(y="y", training_frame=fr)
    res = ms.model.result()
    assert res[0]["predictors"] == ["x1"]           # survives to size 1


def test_rulefit_binomial():
    rng = np.random.default_rng(11)
    n = 2000
    X = rng.normal(size=(n, 4))
    # axis-aligned boxes → ideal for rules
    label = ((X[:, 0] > 0.5) & (X[:, 1] < 0)) | (X[:, 2] > 1.0)
    yl = np.where(label, "yes", "no").astype(object)
    fr = h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)}, "y": yl})
    rf = H2ORuleFitEstimator(max_rule_length=3, rule_generation_ntrees=20,
                             seed=1)
    rf.train(y="y", training_frame=fr)
    assert rf.model.auc() > 0.95
    imp = rf.model.rule_importance()
    assert len(imp) >= 1
    pred = rf.model.predict(fr)
    assert pred.names[0] == "predict"


def test_rulefit_regression_and_save_load(tmp_path):
    rng = np.random.default_rng(13)
    n = 1200
    X = rng.normal(size=(n, 3))
    y = np.where(X[:, 0] > 0, 3.0, -1.0) + 0.5 * X[:, 1] \
        + rng.normal(scale=0.3, size=n)
    fr = h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(3)}, "y": y})
    rf = H2ORuleFitEstimator(max_rule_length=2, rule_generation_ntrees=16,
                             seed=1)
    rf.train(y="y", training_frame=fr)
    assert rf.model.r2() > 0.7
    p = h2o.save_model(rf.model, str(tmp_path), filename="rf")
    m2 = h2o.load_model(p)
    p1 = rf.model.predict(fr).vec("predict").to_numpy()
    p2 = m2.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_anovaglm_single_term_uses_null_model():
    rng = np.random.default_rng(15)
    n = 600
    x1 = rng.normal(size=n)
    y = 2.0 * x1 + rng.normal(scale=0.5, size=n)
    fr = h2o.Frame.from_numpy({"x1": x1, "y": y})
    an = H2OANOVAGLMEstimator(highest_interaction_term=1)
    an.train(y="y", x=["x1"], training_frame=fr)
    # the reduced model is the null model, so a strong predictor must be
    # hugely significant (the empty-x bug reported p=1.0 here)
    assert an.model.anova_table[0]["p_value"] < 1e-10


def test_modelselection_sizes_are_exact():
    rng = np.random.default_rng(17)
    n = 500
    X = rng.normal(size=(n, 6))
    y = X[:, 0] + X[:, 1] + X[:, 2] + rng.normal(scale=0.2, size=n)
    fr = h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(6)}, "y": y})
    ms = H2OModelSelectionEstimator(mode="maxr", max_predictor_number=4)
    ms.train(y="y", training_frame=fr)
    for r in ms.model.result():
        assert len(r["predictors"]) == r["size"]
        assert len(set(r["predictors"])) == r["size"]  # no duplicates


def test_gam_spline_bases():
    """bs spline-type codes (hex/gam: 0=CR, 2=I-spline monotone,
    3=M-spline) fit a known smooth; I-splines give a monotone smooth."""
    import numpy as np
    import h2o3_tpu as h2o
    from h2o3_tpu.models.gam import H2OGeneralizedAdditiveEstimator
    rng = np.random.default_rng(0)
    n = 3000
    x = rng.uniform(-2.5, 2.5, n).astype(np.float32)
    y = (np.sin(1.5 * x) + 0.15 * rng.normal(size=n)).astype(np.float32)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    for bs in (0, 3):
        est = H2OGeneralizedAdditiveEstimator(
            family="gaussian", gam_columns=["x"], num_knots=8, bs=[bs])
        est.train(y="y", training_frame=fr)
        m = est.model.model_performance(fr)
        assert m.r2 > 0.85, (bs, m.r2)
    # monotone target + I-splines: fitted curve is non-decreasing
    y2 = (np.tanh(2 * x) + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr2 = h2o.Frame.from_numpy({"x": x, "y": y2})
    est = H2OGeneralizedAdditiveEstimator(
        family="gaussian", gam_columns=["x"], num_knots=8, bs=[2])
    est.train(y="y", training_frame=fr2)
    xs = np.linspace(-2.4, 2.4, 101).astype(np.float32)
    sf = h2o.Frame.from_numpy({"x": xs})
    ps = np.asarray(est.model.predict(sf).vec(0).to_numpy()[:101])
    assert (np.diff(ps) >= -1e-4).all()
    perf = est.model.model_performance(fr2)
    assert perf.r2 > 0.8, perf.r2


def test_modelselection_maxrsweep_matches_exhaustive():
    """maxrsweep's sweep-operator forward path finds the same subsets as
    exhaustive least squares, with matching R² (hex/modelselection
    maxrsweep vs maxr equivalence on orthogonal-ish designs)."""
    import itertools
    import numpy as np
    import h2o3_tpu as h2o
    from h2o3_tpu.models.modelselection import H2OModelSelectionEstimator
    rng = np.random.default_rng(3)
    n, p = 1500, 6
    X = rng.normal(size=(n, p)).astype(np.float32)
    beta = np.array([3.0, 0.0, 1.5, 0.0, -2.0, 0.1])
    y = (X @ beta + 0.3 * rng.normal(size=n)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(p)}
    cols["y"] = y
    fr = h2o.Frame.from_numpy(cols)
    est = H2OModelSelectionEstimator(mode="maxrsweep",
                                     max_predictor_number=3)
    est.train(y="y", training_frame=fr)
    res = est.model.result()
    assert [r["size"] for r in res] == [1, 2, 3]
    # exhaustive ground truth per size via numpy lstsq
    Xd = X.astype(np.float64)
    yd = y.astype(np.float64)

    def sse_of(idx):
        A = np.concatenate([np.ones((n, 1)), Xd[:, list(idx)]], axis=1)
        r = yd - A @ np.linalg.lstsq(A, yd, rcond=None)[0]
        return float(r @ r)

    for r in res:
        k = r["size"]
        best = min(itertools.combinations(range(p), k), key=sse_of)
        got = tuple(sorted(int(c[1:]) for c in r["predictors"]))
        assert got == tuple(sorted(best)), (k, got, best)
        assert abs(r["sse"] - sse_of(best)) < 1e-3 * sse_of(best)
    # r2 monotone nondecreasing with size
    r2s = [r["r2"] for r in res]
    assert r2s == sorted(r2s)
    # the final refit model predicts
    pred = est.model.predict(fr)
    assert pred.nrow == n


def test_gam_thin_plate_bs1():
    """bs=1 thin-plate smooths (hex/gam ThinPlate*): fits a nonlinear
    signal better than a line, and scores consistently across frames."""
    rng = np.random.default_rng(8)
    n = 1200
    x = rng.uniform(-3, 3, n)
    z = rng.normal(size=n)
    y = np.sin(1.5 * x) + 0.2 * z + 0.05 * rng.normal(size=n)
    fr = h2o.Frame.from_numpy({"x": x, "z": z, "y": y})
    from h2o3_tpu.models.gam import H2OGeneralizedAdditiveEstimator
    gam = H2OGeneralizedAdditiveEstimator(gam_columns=["x"], bs=[1],
                                          num_knots=8, family="gaussian",
                                          Lambda=[1e-4])
    gam.train(y="y", x=["x", "z"], training_frame=fr)
    m = gam.model
    mse = m.training_metrics.mse
    assert mse < 0.05, mse           # line alone would leave ~0.45
    # score-time expansion must match train-time (knot-derived scales)
    pred = np.asarray(m.predict(fr).vec("predict").to_numpy())
    assert np.corrcoef(pred, y)[0, 1] > 0.97


def test_glrm_regularizer_zoo():
    from h2o3_tpu.models.glrm import _prox
    import jax.numpy as jnp
    M = jnp.asarray([[0.4, -1.2, 0.3], [2.0, 0.1, -0.2]])
    os_ = np.asarray(_prox(M, "one_sparse", 0.1))
    assert (np.count_nonzero(os_, axis=1) == 1).all()
    uo = np.asarray(_prox(M, "unit_one_sparse", 0.1))
    assert set(np.unique(uo)) <= {0.0, 1.0}
    assert (uo.sum(axis=1) == 1).all()
    sx = np.asarray(_prox(M, "simplex", 0.1))
    assert np.allclose(sx.sum(axis=1), 1.0, atol=1e-5)
    assert (sx >= -1e-7).all()
    # end-to-end: simplex X regularizer yields soft-clustering weights
    rng = np.random.default_rng(3)
    A = np.concatenate([rng.normal(0, 0.1, (60, 4)) + [2, 0, 0, 0],
                        rng.normal(0, 0.1, (60, 4)) + [0, 2, 0, 0]])
    fr = h2o.Frame.from_numpy({f"c{i}": A[:, i] for i in range(4)})
    from h2o3_tpu.models.glrm import H2OGeneralizedLowRankEstimator
    gl = H2OGeneralizedLowRankEstimator(k=2, regularization_x="simplex",
                                        gamma_x=0.1, max_iterations=60,
                                        seed=1)
    gl.train(training_frame=fr)
    assert gl.model is not None
