"""Model persistence (persist.py): save/load round-trip equality and
_checkpoint continue-training (reference: water/persist/PersistManager.java,
hex/Model.java:487 _checkpoint, h2o.save_model/load_model)."""
import os

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator


def _make_frame(n=2000, seed=21):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    X[rng.random((n, 5)) < 0.05] = np.nan
    y = ((X[:, 0] > 0) ^ (np.nan_to_num(X[:, 1]) > 0.2)).astype(np.float32)
    cols = {f"f{i}": X[:, i] for i in range(5)}
    cols["cls"] = np.array([f"c{int(v)}" for v in y], dtype=object)
    return h2o.Frame.from_numpy(cols)


def test_save_load_roundtrip_binomial(tmp_path):
    fr = _make_frame()
    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=4, seed=3,
                                       min_rows=5.0)
    gbm.train(y="cls", training_frame=fr)
    m = gbm.model
    path = h2o.save_model(m, str(tmp_path))
    assert os.path.exists(path)
    m2 = h2o.load_model(path)
    # predictions identical
    p1 = m.predict(fr)
    p2 = m2.predict(fr)
    np.testing.assert_array_equal(p1.vec("pc1").to_numpy(),
                                  p2.vec("pc1").to_numpy())
    np.testing.assert_array_equal(p1.vec("predict").to_numpy(),
                                  p2.vec("predict").to_numpy())
    # metadata survives
    assert m2.response_domain == m.response_domain
    assert m2.training_metrics.auc == pytest.approx(m.training_metrics.auc)
    assert m2.auc() == pytest.approx(m.auc())
    assert m2.output["variable_importances"]["variable"] == \
        m.output["variable_importances"]["variable"]
    # scoring a fresh metrics pass must work from the loaded model
    perf = m2.model_performance(fr)
    assert perf.auc == pytest.approx(m.training_metrics.auc, abs=1e-6)


def test_save_load_regression_multinomial(tmp_path):
    rng = np.random.default_rng(8)
    n = 1500
    X = rng.normal(size=(n, 4)).astype(np.float32)
    # regression
    fr = h2o.Frame.from_numpy({"a": X[:, 0], "b": X[:, 1],
                               "y": (2 * X[:, 0] - X[:, 1]).astype(np.float32)})
    g = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=1)
    g.train(y="y", training_frame=fr)
    p = h2o.save_model(g.model, str(tmp_path), filename="reg")
    m2 = h2o.load_model(p)
    np.testing.assert_array_equal(g.model.predict(fr).vec("predict").to_numpy(),
                                  m2.predict(fr).vec("predict").to_numpy())
    # multinomial
    yk = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    fr3 = h2o.Frame.from_numpy({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
                                "y": np.array([f"k{v}" for v in yk], dtype=object)})
    g3 = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1,
                                      distribution="multinomial")
    g3.train(y="y", training_frame=fr3)
    p3 = h2o.save_model(g3.model, str(tmp_path), filename="multi")
    m3 = h2o.load_model(p3)
    np.testing.assert_array_equal(
        g3.model.predict(fr3).vec("predict").to_numpy(),
        m3.predict(fr3).vec("predict").to_numpy())


def test_checkpoint_continuation(tmp_path):
    fr = _make_frame(seed=22)
    base = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=5,
                                        min_rows=5.0, score_tree_interval=0)
    base.train(y="cls", training_frame=fr)
    path = h2o.save_model(base.model, str(tmp_path))

    # continue from the saved artifact to 20 total trees
    cont = H2OGradientBoostingEstimator(ntrees=20, max_depth=3, seed=5,
                                        min_rows=5.0, score_tree_interval=0,
                                        checkpoint=path)
    cont.train(y="cls", training_frame=fr)
    assert cont.model.ntrees_built == 20

    # a fresh 20-tree run on the same seed should closely agree (binned vs
    # raw-threshold margins reorder float sums → tolerance, not equality)
    fresh = H2OGradientBoostingEstimator(ntrees=20, max_depth=3, seed=5,
                                         min_rows=5.0, score_tree_interval=0)
    fresh.train(y="cls", training_frame=fr)
    pc = cont.model.predict(fr).vec("pc1").to_numpy()
    pf = fresh.model.predict(fr).vec("pc1").to_numpy()
    np.testing.assert_allclose(pc, pf, atol=0.02)
    assert abs(cont.model.training_metrics.auc -
               fresh.model.training_metrics.auc) < 5e-3
    # continuation must actually improve on the base model
    assert cont.model.training_metrics.logloss < \
        base.model.training_metrics.logloss


def test_checkpoint_validation_errors(tmp_path):
    fr = _make_frame(seed=23)
    base = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=5)
    base.train(y="cls", training_frame=fr)
    # ntrees must exceed the checkpoint's (train() propagates via Job.join)
    c1 = H2OGradientBoostingEstimator(ntrees=5, max_depth=3,
                                      checkpoint=base.model)
    with pytest.raises(RuntimeError, match="must exceed"):
        c1.train(y="cls", training_frame=fr)
    # max_depth must match
    c2 = H2OGradientBoostingEstimator(ntrees=10, max_depth=4,
                                      checkpoint=base.model)
    with pytest.raises(RuntimeError, match="max_depth"):
        c2.train(y="cls", training_frame=fr)
    # feature set must match
    fr2 = fr.drop("f4")
    c3 = H2OGradientBoostingEstimator(ntrees=10, max_depth=3,
                                      checkpoint=base.model)
    with pytest.raises(RuntimeError, match="feature set"):
        c3.train(y="cls", training_frame=fr2)


def test_export_file(tmp_path):
    fr = _make_frame(n=50)
    path = str(tmp_path / "out.csv")
    h2o.export_file(fr, path)
    back = h2o.import_file(path)
    assert back.nrow == 50
    assert back.names == fr.names
    np.testing.assert_allclose(back.vec("f0").to_numpy(),
                               fr.vec("f0").to_numpy(), rtol=1e-6)
