"""GLM tweedie family + non-canonical links (round-5 closure tail).

Reference: hex/glm/GLMModel.java Link enum + family↔link validation
(GLMModel.java:560-591), tweedie variance/link powers
(GLMModel.java:376-377,648,690-795). Goldens: sklearn TweedieRegressor
(same unpenalized likelihoods, log link).
"""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator


def _coefs_close(ours, sk_coef, sk_icpt, names, tol=5e-3):
    for n, c in zip(names, sk_coef):
        assert abs(ours[n] - c) < tol, (n, ours[n], c)
    assert abs(ours["Intercept"] - sk_icpt) < tol


def test_tweedie_vs_sklearn():
    from sklearn.linear_model import TweedieRegressor
    rng = np.random.default_rng(0)
    n = 3000
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    mu = np.exp(0.3 + 0.6 * x1 - 0.5 * x2)
    p = 1.5
    lam = mu ** (2 - p) / (2 - p)
    N = rng.poisson(lam)
    shp = (2 - p) / (p - 1)
    y = np.where(N > 0,
                 rng.gamma(np.maximum(shp * N, 1e-9),
                           (p - 1) * mu ** (p - 1)),
                 0.0)
    fr = h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": y})
    glm = H2OGeneralizedLinearEstimator(
        family="tweedie", tweedie_variance_power=1.5,
        tweedie_link_power=0.0, Lambda=[0.0], standardize=False)
    glm.train(y="y", training_frame=fr)
    sk = TweedieRegressor(power=1.5, alpha=0.0, link="log",
                          max_iter=2000, tol=1e-9).fit(
        np.stack([x1, x2], 1), y)
    _coefs_close(glm.model.coef(), sk.coef_, sk.intercept_, ["x1", "x2"])
    # μ predictions positive, deviance recorded
    pred = glm.model.predict(fr).vec("predict").to_numpy()
    assert np.all(np.asarray(pred) > 0)
    assert glm.model.residual_deviance < glm.model.null_deviance


def test_tweedie_power_link_identity():
    """link power 1 (η = μ): the mean is linear in x. Simulate real
    compound Poisson-gamma data (p=1.5, φ=1) so the tweedie MLE is the
    generating coefficients."""
    rng = np.random.default_rng(1)
    n = 4000
    x = rng.normal(size=n)
    mu = np.maximum(3.0 + 0.8 * x, 0.1)
    p = 1.5
    lam = mu ** (2 - p) / (2 - p)
    N = rng.poisson(lam)
    shp = (2 - p) / (p - 1)
    y = np.where(N > 0,
                 rng.gamma(np.maximum(shp * N, 1e-9),
                           (p - 1) * mu ** (p - 1)),
                 0.0)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    glm = H2OGeneralizedLinearEstimator(
        family="tweedie", tweedie_variance_power=1.5,
        tweedie_link_power=1.0, Lambda=[0.0], standardize=False)
    glm.train(y="y", training_frame=fr)
    co = glm.model.coef()
    assert abs(co["x"] - 0.8) < 0.1
    assert abs(co["Intercept"] - 3.0) < 0.15


def test_gaussian_log_link():
    from sklearn.linear_model import TweedieRegressor
    rng = np.random.default_rng(2)
    n = 3000
    x = rng.normal(size=n)
    y = np.exp(0.2 + 0.5 * x) + 0.1 * rng.normal(size=n)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    glm = H2OGeneralizedLinearEstimator(family="gaussian", link="log",
                                        Lambda=[0.0], standardize=False)
    glm.train(y="y", training_frame=fr)
    sk = TweedieRegressor(power=0, alpha=0.0, link="log",
                          max_iter=2000, tol=1e-9).fit(x[:, None], y)
    _coefs_close(glm.model.coef(), sk.coef_, sk.intercept_, ["x"],
                 tol=1e-2)


def test_poisson_identity_link():
    rng = np.random.default_rng(3)
    n = 4000
    x = rng.normal(size=n)
    lam = np.maximum(3.0 + 1.0 * x, 0.05)
    y = rng.poisson(lam).astype(float)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    glm = H2OGeneralizedLinearEstimator(family="poisson", link="identity",
                                        Lambda=[0.0], standardize=False)
    glm.train(y="y", training_frame=fr)
    co = glm.model.coef()
    assert abs(co["x"] - 1.0) < 0.12
    assert abs(co["Intercept"] - 3.0) < 0.15


def test_gamma_inverse_link():
    rng = np.random.default_rng(4)
    n = 4000
    x = rng.normal(size=n)
    mu = 1.0 / np.maximum(1.0 + 0.3 * x, 0.2)
    y = rng.gamma(5.0, mu / 5.0)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    glm = H2OGeneralizedLinearEstimator(family="gamma", link="inverse",
                                        Lambda=[0.0], standardize=False)
    glm.train(y="y", training_frame=fr)
    co = glm.model.coef()
    # truth is clamped below at 0.2 so expect mild attenuation
    assert abs(co["x"] - 0.3) < 0.08
    assert abs(co["Intercept"] - 1.0) < 0.08


def test_incompatible_link_rejected():
    fr = h2o.Frame.from_numpy({"x": np.arange(32, dtype=float),
                               "y": np.arange(32, dtype=float)})
    glm = H2OGeneralizedLinearEstimator(family="poisson", link="logit")
    # the ValueError surfaces through the Job wrapper as RuntimeError
    with pytest.raises((ValueError, RuntimeError),
                       match="Incompatible link"):
        glm.train(y="y", training_frame=fr)


def test_tweedie_save_load_predict(tmp_path):
    """tweedie powers must survive the artifact roundtrip — predict
    reconstructs the family from restored params."""
    rng = np.random.default_rng(5)
    n = 1000
    x = rng.normal(size=n)
    y = np.maximum(np.exp(0.3 + 0.5 * x) + 0.1 * rng.normal(size=n), 0.0)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    glm = H2OGeneralizedLinearEstimator(
        family="tweedie", tweedie_variance_power=1.5,
        tweedie_link_power=0.0, Lambda=[0.0])
    glm.train(y="y", training_frame=fr)
    p0 = np.asarray(glm.model.predict(fr).vec("predict").to_numpy())
    path = h2o.save_model(glm.model, str(tmp_path), filename="twm")
    m2 = h2o.load_model(path)
    p1 = np.asarray(m2.predict(fr).vec("predict").to_numpy())
    np.testing.assert_allclose(p0, p1, rtol=1e-5)


def test_ordinal_oprobit_ologlog():
    """Family.ordinal link variants (GLMModel.java:589 ologit/oprobit/
    ologlog): ordered-probit data recovered best by oprobit; all
    variants produce valid ordered probabilities."""
    rng = np.random.default_rng(6)
    n = 3000
    x = rng.normal(size=n)
    eta = 1.2 * x
    z = eta + rng.normal(size=n)          # probit latent
    cuts = np.array([-0.8, 0.6])
    yo = np.digitize(z, cuts)             # 3 ordered classes
    fr = h2o.Frame.from_numpy(
        {"x": x, "y": np.array([f"c{v}" for v in yo])})
    got = {}
    for link in ("ologit", "oprobit", "ologlog"):
        glm = H2OGeneralizedLinearEstimator(family="ordinal", link=link)
        glm.train(y="y", training_frame=fr)
        got[link] = glm.model
        full = glm.model.predict(fr)
        P = np.stack([np.asarray(full.vec(f"pc{k}").to_numpy())
                      for k in range(3)], axis=1)
        np.testing.assert_allclose(P.sum(1), 1.0, atol=1e-5)
        assert (P >= 0).all()
    # oprobit on probit-generated data recovers the slope scale ~1.2
    co = got["oprobit"].coef()
    assert abs(co["x"] - 1.2) < 0.15
    # bad ordinal link rejected
    glm = H2OGeneralizedLinearEstimator(family="ordinal", link="inverse")
    with pytest.raises((ValueError, RuntimeError), match="ologit"):
        glm.train(y="y", training_frame=fr)
