"""DRF tests — the coverage round 2 shipped without (VERDICT r2 Weak #2).

Mirrors the reference's hex/tree/drf test style: sklearn RandomForest
ballpark parity, OOB sanity (OOB error worse than in-bag), seed
reproducibility, and multinomial probability normalization.
"""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.drf import H2ORandomForestEstimator


def _binomial_frame(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    logit = 2 * x1 - 1.5 * x2
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    cls = np.array(["no", "yes"], dtype=object)[y]
    fr = h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": cls})
    return fr, np.stack([x1, x2], 1), y


def test_drf_binomial_vs_sklearn():
    from sklearn.ensemble import RandomForestClassifier
    from sklearn.metrics import roc_auc_score
    fr, X, y = _binomial_frame()
    drf = H2ORandomForestEstimator(ntrees=40, max_depth=8, seed=1)
    drf.train(y="y", training_frame=fr)
    p = drf.model.predict(fr).vec("pyes").to_numpy()
    auc = roc_auc_score(y, p)
    sk = RandomForestClassifier(n_estimators=40, max_depth=8,
                                random_state=0).fit(X, y)
    sk_auc = roc_auc_score(y, sk.predict_proba(X)[:, 1])
    # same ballpark (in-sample; exact-split RF will edge out histogram RF)
    assert auc > sk_auc - 0.05, (auc, sk_auc)
    assert auc > 0.9


def test_drf_oob_worse_than_inbag():
    """OOB metrics must look like held-out metrics: worse than scoring the
    training data with the full forest."""
    fr, X, y = _binomial_frame(seed=3)
    drf = H2ORandomForestEstimator(ntrees=30, max_depth=6, seed=2)
    drf.train(y="y", training_frame=fr)
    assert drf.model.output["oob_metrics"] is True
    oob_ll = drf.model.training_metrics.logloss
    inbag = drf.model.model_performance(fr)
    assert oob_ll > inbag.logloss, (oob_ll, inbag.logloss)
    # but still a real model
    assert drf.model.training_metrics.auc > 0.85


def test_drf_regression_vs_sklearn():
    from sklearn.ensemble import RandomForestRegressor
    rng = np.random.default_rng(5)
    n = 3000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (2 * X[:, 0] + np.sin(2 * X[:, 1]) * 2 + 0.1 * rng.normal(size=n))
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = y.astype(np.float32)
    fr = h2o.Frame.from_numpy(cols)
    # mtries=4 (all features) to match sklearn's max_features=1.0 default;
    # H2O's regression default is p/3 which would handicap the comparison
    drf = H2ORandomForestEstimator(ntrees=40, max_depth=10, seed=1, mtries=4)
    drf.train(y="y", training_frame=fr)
    pred = drf.model.predict(fr).vec("predict").to_numpy()
    mse = float(np.mean((pred - y) ** 2))
    sk = RandomForestRegressor(n_estimators=40, max_depth=10,
                               random_state=0).fit(X, y)
    sk_mse = float(np.mean((sk.predict(X) - y) ** 2))
    # sklearn's exact-split RF nearly memorizes in-sample; histogram splits
    # with 63 bins land close but not equal — same-ballpark check
    var = float(np.var(y))
    assert mse < 0.05 * var, (mse, sk_mse, var)


def test_drf_multinomial_probs_normalized():
    rng = np.random.default_rng(7)
    n = 2000
    centers = np.array([[0, 0], [3, 3], [-3, 3]])
    y = rng.integers(0, 3, n)
    X = centers[y] + rng.normal(size=(n, 2))
    labels = np.array(["a", "b", "c"], dtype=object)[y]
    fr = h2o.Frame.from_numpy({"x1": X[:, 0], "x2": X[:, 1], "y": labels})
    drf = H2ORandomForestEstimator(ntrees=20, max_depth=6, seed=1)
    drf.train(y="y", training_frame=fr)
    pf = drf.model.predict(fr)
    assert pf.names == ["predict", "pa", "pb", "pc"]
    probs = np.stack([pf.vec(c).to_numpy() for c in ("pa", "pb", "pc")], 1)
    np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-5)
    acc = (pf.vec("predict").to_numpy() == y).mean()
    assert acc > 0.85


def test_drf_seed_reproducible():
    fr, _, _ = _binomial_frame(n=1200, seed=11)
    kw = dict(ntrees=10, max_depth=5, seed=99)
    d1 = H2ORandomForestEstimator(**kw)
    d1.train(y="y", training_frame=fr)
    d2 = H2ORandomForestEstimator(**kw)
    d2.train(y="y", training_frame=fr)
    p1 = d1.model.predict(fr).vec("pyes").to_numpy()
    p2 = d2.model.predict(fr).vec("pyes").to_numpy()
    np.testing.assert_allclose(p1, p2)


def test_drf_depth_cap_raises():
    fr, _, _ = _binomial_frame(n=200, seed=13)
    drf = H2ORandomForestEstimator(ntrees=2, max_depth=17)
    with pytest.raises(RuntimeError, match="max_depth"):
        drf.train(y="y", training_frame=fr)


def test_drf_mtries_importances_spread():
    """Per-node mtries must let weaker-but-real features into the trees:
    with 2 informative features and mtries=1, both appear in importances."""
    rng = np.random.default_rng(17)
    n = 2000
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    y = (a + 0.8 * b + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = h2o.Frame.from_numpy({"a": a, "b": b, "y": y})
    drf = H2ORandomForestEstimator(ntrees=20, max_depth=5, mtries=1, seed=3)
    drf.train(y="y", training_frame=fr)
    vi = drf.model.output["variable_importances"]
    pct = dict(zip(vi["variable"], vi["percentage"]))
    assert pct["a"] > 0.2 and pct["b"] > 0.1, pct
