"""Multi-chip GBM through the PRODUCT path (VERDICT.md Weak #2): the
shipped H2OGradientBoostingEstimator must train across the mesh and
produce the same model as a single-device run.

Reference contract: Rabit allreduce inside the training loop
(hex/tree/xgboost/rabit/RabitTrackerH2O.java) / MRTask reduce tree
(water/MRTask.java:871-926) — here the psum inside grow_tree, reached via
the estimator's shard_mapped chunk step."""
import jax
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.parallel.mesh import current_mesh, make_mesh, set_mesh

pytestmark = pytest.mark.slow  # heavy tier: driver runs with --runslow

def _train(mesh, X, y, **params):
    old = current_mesh()
    set_mesh(mesh)
    try:
        cols = {f"f{i}": X[:, i] for i in range(X.shape[1])}
        cols["y"] = y
        fr = h2o.Frame.from_numpy(cols)
        gbm = H2OGradientBoostingEstimator(seed=7, **params)
        gbm.train(y="y", training_frame=fr)
        pred = gbm.model.predict(fr)
        return gbm.model, pred
    finally:
        set_mesh(old)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_estimator_mesh_first_tree_exact():
    """First tree from the initial margin, balanced y (so f0=0 and the
    bernoulli (g,h) are dyadic → psum is order-independent): the (4,2)-mesh
    estimator must reproduce the single-device tree BIT-FOR-BIT."""
    rng = np.random.default_rng(11)
    n, F = 2048, 6
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[rng.random((n, F)) < 0.05] = np.nan
    y = ((X[:, 0] > 0) ^ (np.nan_to_num(X[:, 1]) > 0.3)).astype(np.float32)
    assert y.mean() == 0.5 or True  # balance not required to be exact; f0
    # dyadicity only matters when it is — force balance by trimming:
    idx1 = np.nonzero(y == 1)[0]
    idx0 = np.nonzero(y == 0)[0]
    k = min(len(idx0), len(idx1), 1000)
    sel = np.sort(np.concatenate([idx0[:k], idx1[:k]]))
    X, y = X[sel], y[sel]
    params = dict(ntrees=1, max_depth=4, nbins=16, distribution="bernoulli",
                  min_rows=2.0, sample_rate=1.0, score_tree_interval=0,
                  stopping_rounds=0)

    m1, _ = _train(make_mesh(n_data=1, n_model=1,
                             devices=jax.devices()[:1]), X, y, **params)
    m8, _ = _train(make_mesh(n_data=4, n_model=2), X, y, **params)

    np.testing.assert_array_equal(np.asarray(m1._feat), np.asarray(m8._feat))
    np.testing.assert_array_equal(np.asarray(m1._is_split),
                                  np.asarray(m8._is_split))
    np.testing.assert_array_equal(np.asarray(m1._thr), np.asarray(m8._thr))
    np.testing.assert_allclose(np.asarray(m1._value), np.asarray(m8._value),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_estimator_mesh_full_run_parity():
    """Full boosting run: per-shard psum reduce order differs from the
    single-device sum in the last ulp, so deep-tree splits near the gain
    threshold may flip (the reference tolerates the same MRTask float
    nondeterminism — SURVEY.md §7.3). The MODEL must agree: predictions
    close, metrics near-identical, and the vast majority of split nodes
    identical."""
    rng = np.random.default_rng(11)
    n, F = 2048, 6
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[rng.random((n, F)) < 0.05] = np.nan
    y = ((X[:, 0] > 0) ^ (np.nan_to_num(X[:, 1]) > 0.3)).astype(np.float32)
    params = dict(ntrees=7, max_depth=4, nbins=16, distribution="bernoulli",
                  min_rows=2.0, sample_rate=1.0, score_tree_interval=0,
                  stopping_rounds=0)

    m1, p1 = _train(make_mesh(n_data=1, n_model=1,
                              devices=jax.devices()[:1]), X, y, **params)
    m8, p8 = _train(make_mesh(n_data=4, n_model=2), X, y, **params)

    same_feat = (np.asarray(m1._feat) == np.asarray(m8._feat)).mean()
    assert same_feat > 0.9, same_feat
    np.testing.assert_allclose(p1.vec("p1").to_numpy(), p8.vec("p1").to_numpy(),
                               atol=0.03)
    assert abs(m1.training_metrics.auc - m8.training_metrics.auc) < 2e-3
    assert abs(m1.training_metrics.logloss - m8.training_metrics.logloss) < 2e-3
    assert m8.training_metrics.auc > 0.9


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_estimator_mesh_sampled_run():
    """Row/column sampling across shards (shard-decorrelated RNG): not
    bit-identical to single-device, but must train a good model."""
    rng = np.random.default_rng(12)
    n, F = 4096, 8
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.2 * rng.normal(size=n) > 0
         ).astype(np.float32)
    m, _ = _train(make_mesh(n_data=8, n_model=1), X, y,
                  ntrees=20, max_depth=4, nbins=32, distribution="bernoulli",
                  sample_rate=0.7, col_sample_rate=0.8, min_rows=2.0)
    assert m.training_metrics.auc > 0.85


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_estimator_mesh_multinomial():
    """Enum-response multinomial through the sharded estimator path."""
    rng = np.random.default_rng(13)
    n = 2048
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    old = current_mesh()
    set_mesh(make_mesh(n_data=8, n_model=1))
    try:
        cols = {f"f{i}": X[:, i] for i in range(4)}
        cols["y"] = np.array([f"c{c}" for c in y], dtype=object)
        fr = h2o.Frame.from_numpy(cols)
        gbm = H2OGradientBoostingEstimator(seed=7, ntrees=5, max_depth=3,
                                           distribution="multinomial",
                                           min_rows=2.0)
        gbm.train(y="y", training_frame=fr)
        m = gbm.model
        pred = m.predict(fr)
        assert pred.vec("predict").domain == ("c0", "c1", "c2")
        assert {"pc0", "pc1", "pc2"} <= set(pred.names)
    finally:
        set_mesh(old)
    assert m.training_metrics.logloss < 0.7
