"""Regression tests for round-1 defects (VERDICT.md Weak / ADVICE.md).

- bins_to_thresholds overflow → +inf (all-non-NA-left splits must not
  route max-value rows into the NA branch at scoring time);
- Model convenience accessors exist and delegate from the builder;
- nbins_cats: group-per-category binning for mid-cardinality enums;
- offset_column threads into GBM margins (train + score);
- pallas histogram kernel parity vs the scatter reference (interpret mode).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.tree import bins_to_thresholds
from h2o3_tpu.ops.binning import bin_matrix, split_threshold
from h2o3_tpu.ops.histogram import _hist_scatter3
from h2o3_tpu.ops.hist_pallas import hist_pallas_from_rowmajor


def test_bins_to_thresholds_overflow_is_inf():
    # feature 0 has 2 edges; a split at t=3 (beyond the edges) must send all
    # non-NA rows left (threshold +inf), not clamp to the last edge
    edges = [np.array([0.5, 1.5], dtype=np.float32)]
    feat = np.array([0, 0, 0], dtype=np.int32)
    sbin = np.array([1, 2, 3], dtype=np.int32)
    thr = bins_to_thresholds(sbin, feat, edges)
    assert thr[0] == np.float32(0.5)
    assert thr[1] == np.float32(1.5)
    assert thr[2] == np.inf


def test_split_threshold_overflow_is_inf():
    class BM:
        edges = [np.array([0.5], dtype=np.float32)]
    assert split_threshold(BM, 0, 1) == 0.5
    assert split_threshold(BM, 0, 2) == np.inf


def test_train_vs_repredict_with_na_low_cardinality():
    """NA-informative low-cardinality feature: predict() on the training
    frame must reproduce the training metrics (the round-1 clamp bug gave
    logloss 0.665 vs 0.632 here)."""
    rng = np.random.default_rng(3)
    n = 4000
    x = rng.integers(0, 3, n).astype(np.float32)      # few unique values
    x[rng.random(n) < 0.3] = np.nan                    # NA informative
    p = np.where(np.isnan(x), 0.8, np.where(x >= 2, 0.7, 0.2))
    y = (rng.random(n) < p).astype(np.int32)
    fr = h2o.Frame.from_numpy({"x": x, "noise": rng.normal(size=n).astype(np.float32),
                               "y": y.astype(np.float32)})
    gbm = H2OGradientBoostingEstimator(ntrees=20, max_depth=3, nbins=20,
                                       distribution="bernoulli", seed=1,
                                       min_rows=5.0)
    gbm.train(y="y", training_frame=fr)
    pred = gbm.model.predict(fr)
    p1 = pred.vec("p1").to_numpy()
    eps = 1e-15
    ll = -np.mean(y * np.log(np.clip(p1, eps, 1)) +
                  (1 - y) * np.log(np.clip(1 - p1, eps, 1)))
    train_ll = gbm.model.training_metrics.logloss
    assert abs(ll - train_ll) < 1e-3, (ll, train_ll)


def test_model_accessors_exist():
    rng = np.random.default_rng(0)
    n = 500
    x = rng.normal(size=n).astype(np.float32)
    y = (x + rng.normal(size=n) * 0.5 > 0).astype(np.int32)
    fr = h2o.Frame.from_numpy({"x": x, "y": y.astype(np.float32)})
    gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=3,
                                       distribution="bernoulli", seed=1)
    gbm.train(y="y", training_frame=fr)
    m = gbm.model
    assert hasattr(type(m), "auc") and callable(m.auc)
    assert 0.5 < m.auc() <= 1.0
    assert m.logloss() > 0
    # builder delegates to the model (h2o-py style)
    assert gbm.auc() == m.auc()
    assert "GBMModel" in repr(m)


def test_nbins_cats_identity_binning():
    rng = np.random.default_rng(1)
    n = 2000
    codes = rng.integers(0, 30, n)  # cardinality 30 > nbins 20
    X = codes[:, None].astype(np.float32)
    bm = bin_matrix(X, ["c"], [True], n, nbins=20, nbins_cats=1024)
    # group-per-category: 30 bins, 29 half-step edges
    assert bm.n_bins == 30
    assert len(bm.edges[0]) == 29
    got = np.asarray(jax.device_get(bm.codes.rm))[:n, 0]
    assert (got == codes).all()
    # beyond nbins_cats → quantile grouping, bounded bins
    big = rng.integers(0, 5000, n)[:, None].astype(np.float32)
    bm2 = bin_matrix(big, ["c"], [True], n, nbins=20, nbins_cats=64)
    assert bm2.n_bins <= 64


def test_offset_column_honored():
    rng = np.random.default_rng(2)
    n = 3000
    x = rng.normal(size=n).astype(np.float32)
    off = np.where(rng.random(n) < 0.5, 5.0, -5.0).astype(np.float32)
    y = (2.0 * x + off + rng.normal(size=n) * 0.1).astype(np.float32)
    fr = h2o.Frame.from_numpy({"x": x, "off": off, "y": y})
    gbm = H2OGradientBoostingEstimator(ntrees=40, max_depth=4,
                                       distribution="gaussian", seed=1,
                                       offset_column="off", min_rows=5.0)
    gbm.train(y="y", training_frame=fr)
    pred = gbm.model.predict(fr).vec("predict").to_numpy()
    resid = float(np.mean((pred - y) ** 2))
    # without the offset in the margin the offset variance (~25) dominates
    assert resid < 2.0, resid
    # training metrics must reflect the offset margin too
    assert gbm.model.training_metrics.mse < 2.0


def test_offset_multinomial_raises():
    rng = np.random.default_rng(4)
    n = 300
    fr = h2o.Frame.from_numpy({
        "x": rng.normal(size=n).astype(np.float32),
        "off": np.ones(n, np.float32),
        "y": rng.integers(0, 3, n).astype(np.float32)})
    gbm = H2OGradientBoostingEstimator(ntrees=2, distribution="multinomial",
                                       offset_column="off")
    with pytest.raises(Exception):
        gbm.train(y="y", training_frame=fr)
        if gbm.job.status == "FAILED":
            raise RuntimeError(gbm.job.exception)


@pytest.mark.parametrize("rows,F,n_nodes,nbins1", [
    (1000, 5, 4, 17),    # padded rows (1000→1024) + padded features (5→8)
    (512, 8, 1, 33),     # exact tile fit, single node
])
def test_pallas_interpret_parity(rows, F, n_nodes, nbins1):
    """The flagship pallas kernel vs the scatter reference, including the
    NA bin (= nbins1-1) and row/feature padding (ADVICE low / VERDICT Weak
    #4: the kernel previously had zero test coverage)."""
    rng = np.random.default_rng(7)
    codes = rng.integers(0, nbins1, (rows, F)).astype(np.int32)  # incl. NA bin
    nid = rng.integers(0, n_nodes, rows).astype(np.int32)
    g = rng.normal(size=rows).astype(np.float32)
    h = rng.random(rows).astype(np.float32)
    w = (rng.random(rows) < 0.9).astype(np.float32)
    ghw = jnp.stack([jnp.asarray(g), jnp.asarray(h), jnp.asarray(w)])
    ref = jnp.stack(_hist_scatter3(jnp.asarray(codes), jnp.asarray(nid),
                                   ghw, n_nodes, nbins1), axis=-1)
    got = hist_pallas_from_rowmajor(
        jnp.asarray(codes), jnp.asarray(nid), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(w), n_nodes, nbins1, tile=256, mxu_dtype=jnp.float32,
        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)
    # bf16 one-hots are exact; (g,h,w) round to bf16 before f32 accumulate
    got_bf = hist_pallas_from_rowmajor(
        jnp.asarray(codes), jnp.asarray(nid), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(w), n_nodes, nbins1, tile=256, mxu_dtype=jnp.bfloat16,
        interpret=True)
    np.testing.assert_allclose(np.asarray(got_bf), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)
