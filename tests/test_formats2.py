"""Avro + xlsx ingest (round-5 gate closure).

Reference: h2o-parsers/h2o-avro-parser (flat record → columns),
water/parser/XlsxParser.java. The test files are encoded BY HAND from
the format specs (zigzag varints / OOXML), independent of the readers.
"""
import io
import json
import struct
import zipfile
import zlib

import numpy as np
import pytest

import h2o3_tpu as h2o


def _zz(n: int) -> bytes:
    """Avro zigzag varint encoding."""
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avstr(s: str) -> bytes:
    raw = s.encode()
    return _zz(len(raw)) + raw


def _make_avro(tmp_path, codec=b"null"):
    schema = {
        "type": "record", "name": "row", "fields": [
            {"name": "a", "type": "double"},
            {"name": "b", "type": "long"},
            {"name": "s", "type": {"type": "enum", "name": "col",
                                   "symbols": ["red", "blue"]}},
            {"name": "m", "type": ["null", "double"]},
        ]}
    rows = [(1.5, 7, 0, None), (-2.25, -3, 1, 9.5), (0.0, 40, 0, None)]
    body = bytearray()
    for a, b, s, m in rows:
        body += struct.pack("<d", a)
        body += _zz(b)
        body += _zz(s)
        if m is None:
            body += _zz(0)
        else:
            body += _zz(1) + struct.pack("<d", m)
    payload = bytes(body)
    if codec == b"deflate":
        co = zlib.compressobj(9, zlib.DEFLATED, -15)
        payload = co.compress(payload) + co.flush()
    sync = b"0123456789abcdef"
    buf = bytearray(b"Obj\x01")
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec}
    buf += _zz(len(meta))
    for k, v in meta.items():
        buf += _avstr(k) + _zz(len(v)) + v
    buf += _zz(0)
    buf += sync
    buf += _zz(len(rows)) + _zz(len(payload)) + payload + sync
    p = tmp_path / f"t_{codec.decode()}.avro"
    p.write_bytes(bytes(buf))
    return str(p)


@pytest.mark.parametrize("codec", [b"null", b"deflate"])
def test_avro_roundtrip(tmp_path, codec):
    path = _make_avro(tmp_path, codec)
    fr = h2o.import_file(path)
    assert fr.nrow == 3 and fr.ncol == 4
    np.testing.assert_allclose(fr.vec("a").to_numpy(), [1.5, -2.25, 0.0])
    np.testing.assert_allclose(fr.vec("b").to_numpy(), [7, -3, 40])
    sv = fr.vec("s")
    assert sv.type == "enum"
    dom = sv.domain
    codes = np.asarray(sv.to_numpy()).astype(int)
    assert [dom[c] for c in codes] == ["red", "blue", "red"]
    mv = np.asarray(fr.vec("m").to_numpy())
    assert np.isnan(mv[0]) and mv[1] == 9.5 and np.isnan(mv[2])


def _make_xlsx(tmp_path):
    sheet = """<?xml version="1.0"?>
<worksheet xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main">
<sheetData>
<row r="1"><c r="A1" t="s"><v>0</v></c><c r="B1" t="s"><v>1</v></c>
<c r="C1" t="s"><v>2</v></c></row>
<row r="2"><c r="A2"><v>1.5</v></c><c r="B2" t="s"><v>3</v></c>
<c r="C2"><v>10</v></c></row>
<row r="3"><c r="A3"><v>-2</v></c><c r="B3" t="s"><v>4</v></c></row>
</sheetData></worksheet>"""
    shared = """<?xml version="1.0"?>
<sst xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main">
<si><t>num</t></si><si><t>cat</t></si><si><t>z</t></si>
<si><t>dog</t></si><si><t>cat</t></si></sst>"""
    p = tmp_path / "t.xlsx"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("xl/worksheets/sheet1.xml", sheet)
        z.writestr("xl/sharedStrings.xml", shared)
        z.writestr("[Content_Types].xml", "<Types/>")
    return str(p)


def test_xlsx_parse(tmp_path):
    fr = h2o.import_file(_make_xlsx(tmp_path))
    assert fr.nrow == 2 and fr.ncol == 3
    np.testing.assert_allclose(fr.vec("num").to_numpy(), [1.5, -2.0])
    cv = fr.vec("cat")
    dom = cv.domain
    codes = np.asarray(cv.to_numpy()).astype(int)
    assert [dom[c] for c in codes] == ["dog", "cat"]
    zv = np.asarray(fr.vec("z").to_numpy())
    assert zv[0] == 10 and np.isnan(zv[1])


def test_legacy_xls_still_gated(tmp_path):
    p = tmp_path / "old.xls"
    p.write_bytes(b"\xd0\xcf\x11\xe0junk")
    with pytest.raises(NotImplementedError, match="xlrd"):
        h2o.import_file(str(p))
