"""Import the UNMODIFIED reference h2o-py client package.

The reference client (h2o-py/h2o/backend/connection.py) is pure REST —
it only needs `requests` plus the py2/3 compat package `future`, which
is not in this image. The shim below provides the handful of names
h2o-py pulls from `future` (all trivial on py3) WITHOUT modifying the
reference tree; everything else is the client exactly as shipped.
"""
import os
import sys
import types

H2O_PY_PATH = "/root/reference/h2o-py"


def available() -> bool:
    """Whether the reference h2o-py checkout exists on this host. Driver
    containers don't all mount /root/reference; tests against the real
    client must skip (not error) where it is absent."""
    return os.path.isdir(os.path.join(H2O_PY_PATH, "h2o"))


def _mkmod(name, **attrs):
    m = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(m, k, v)
    sys.modules[name] = m
    return m


def install():
    if "future" not in sys.modules:
        def with_metaclass(meta, *bases):
            return meta("NewBase", bases or (object,), {})

        fut = _mkmod("future")
        fut.__path__ = []
        fut.utils = _mkmod(
            "future.utils", PY2=False, PY3=True,
            with_metaclass=with_metaclass,
            viewitems=lambda d: d.items(), viewkeys=lambda d: d.keys(),
            viewvalues=lambda d: d.values())
        fb = _mkmod("future.builtins")
        fb.__path__ = []
        _mkmod("future.builtins.iterators", range=range, filter=filter,
               map=map, zip=zip)
        _mkmod("future.builtins.misc", chr=chr, input=input, open=open,
               next=next, round=round, super=super)
    if H2O_PY_PATH not in sys.path:
        sys.path.insert(0, H2O_PY_PATH)


def import_h2o():
    if not available():
        import pytest
        pytest.skip(f"reference h2o-py tree not present at {H2O_PY_PATH}")
    install()
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", SyntaxWarning)
        import h2o
    return h2o
