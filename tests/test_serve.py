"""Serving subsystem (ISSUE 3): micro-batched correctness vs
model.predict, zero-recompile warm serve path, deadline/backpressure
admission control, deploy/undeploy lifecycle over REST, the vectorized
row codec's unknown-level policy, and the jobs-registry satellites."""
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import dkv, serve
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

from _compile_counter import count_compiles  # noqa: E402 — shared harness


@pytest.fixture(autouse=True, scope="module")
def _serve_cleanup():
    yield
    serve.shutdown_all()


def _train_frame(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    num = rng.normal(size=n).astype(np.float32)
    num2 = rng.uniform(-2, 2, size=n).astype(np.float32)
    carrier = rng.integers(0, 3, size=n)
    logit = num * 1.2 - num2 + (carrier == 0) * 0.8
    y = (rng.random(n) < 1 / (1 + np.exp(-logit)))
    fr = h2o.Frame.from_numpy({
        "dist": num, "hour": num2,
        "carrier": np.array(["AA", "UA", "DL"])[carrier],
        "delayed": np.where(y, "YES", "NO")})
    return fr


def _rows_of(fr, idx):
    rows = []
    for i in idx:
        rows.append({"dist": float(fr.vec("dist").to_numpy()[i]),
                     "hour": float(fr.vec("hour").to_numpy()[i]),
                     "carrier": fr.vec("carrier").to_strings()[i]})
    return rows


@pytest.fixture(scope="module")
def gbm_model():
    fr = _train_frame()
    g = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=1,
                                     min_rows=1.0)
    g.train(y="delayed", training_frame=fr)
    g.model.key = "serve_gbm"
    return fr, g.model


# ------------------------------------------------ correctness + parity


def test_microbatched_predictions_bit_match_predict(gbm_model):
    fr, model = gbm_model
    dep = serve.deploy("serve_gbm", model=model,
                       buckets=(1, 8, 64), max_batch=64, max_delay_ms=1.0)
    try:
        idx = list(range(200))
        rows = _rows_of(fr, idx)
        ref = model.predict(fr.rows(np.asarray(idx)))
        ref_p = {d: np.asarray(ref.vec(f"p{d}").to_numpy())[:len(idx)]
                 for d in model.response_domain}
        ref_lbl = [ref.vec("predict").to_strings()[i]
                   for i in range(len(idx))]

        # N concurrent clients × M rows each through the micro-batcher
        per = 20
        outs = {}
        errs = []

        def client(ci):
            try:
                outs[ci] = dep.predict_rows(rows[ci * per: (ci + 1) * per])
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(len(rows) // per)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        preds = [p for ci in range(len(threads)) for p in outs[ci]]
        assert len(preds) == len(idx)
        for i, p in enumerate(preds):
            assert p["label"] == ref_lbl[i]
            for d in model.response_domain:
                # acceptance bar: BIT-identical to model.predict
                assert p["classProbabilities"][d] == float(ref_p[d][i]), \
                    (i, d, p, float(ref_p[d][i]))
        # the batcher actually coalesced concurrent clients
        snap = dep.stats.snapshot()
        assert snap["rows"] == len(idx)
        assert snap["batches"] >= 1
    finally:
        serve.undeploy("serve_gbm")


def test_warm_serve_path_zero_recompiles_mixed_batch_sizes(gbm_model):
    fr, model = gbm_model
    dep = serve.deploy("serve_gbm", model=model,
                       buckets=(1, 8, 64), max_batch=64, max_delay_ms=0.5)
    try:
        rows = _rows_of(fr, range(64))
        dep.predict_rows(rows[:2])   # settle any lazy first-use host work
        events = []
        with count_compiles(events):
            for n in (1, 3, 8, 17, 64, 5, 1, 33):
                got = dep.predict_rows(rows[:n])
                assert len(got) == n
        assert len(events) == 0, \
            f"warm serve path compiled {len(events)} modules"
        assert dep.scorer.jitted
        assert set(dep.scorer.warm_seconds) == {1, 8, 64}
    finally:
        serve.undeploy("serve_gbm")


def test_unknown_levels_and_missing_columns_na(gbm_model):
    fr, model = gbm_model
    dep = serve.deploy("serve_gbm", model=model, buckets=(1, 8),
                       max_batch=8)
    try:
        # unknown carrier level + missing column both map to NA and
        # still score (EasyPredict RowData contract)
        out = dep.predict_rows([{"dist": 500.0, "carrier": "ZZ"},
                                {"dist": 500.0}])
        assert len(out) == 2
        for p in out:
            s = sum(p["classProbabilities"].values())
            assert abs(s - 1.0) < 1e-6
        assert dep.codec.unknown_categorical_levels_seen.get("carrier") == 1
    finally:
        serve.undeploy("serve_gbm")


def test_bad_row_fails_only_its_own_request(gbm_model):
    """One client's malformed row must not poison the other requests
    coalesced into the same tick — it resolves with a 400-mappable
    ServeBadRequestError while innocents score normally."""
    fr, model = gbm_model
    dep = serve.deploy("serve_gbm", model=model, buckets=(1, 8, 64),
                       max_batch=64, max_delay_ms=30.0)
    try:
        good_rows = _rows_of(fr, range(3))
        results = {}

        def client(name, rows):
            try:
                results[name] = dep.predict_rows(rows)
            except Exception as e:  # noqa: BLE001
                results[name] = e

        threads = [
            threading.Thread(target=client, args=("good", good_rows)),
            threading.Thread(target=client,
                             args=("bad", [{"dist": "not-a-number"}])),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert isinstance(results["bad"], serve.ServeBadRequestError)
        assert serve.ServeBadRequestError.http_status == 400
        assert isinstance(results["good"], list) and \
            len(results["good"]) == 3
    finally:
        serve.undeploy("serve_gbm")


def test_python_deploy_pins_store_resident_model(gbm_model):
    """Model.deploy() must take the same DKV pin as the REST path when
    the model lives in the store — and a FAILED re-deploy must not drop
    the live deployment's pin."""
    fr, model = gbm_model
    dkv.put("serve_gbm", "model", model)
    try:
        dep = model.deploy(buckets=(1, 8), max_batch=8)
        with pytest.raises(dkv.KeyLockedError):
            dkv.check_unlocked("serve_gbm")      # pinned
        # bad re-deploy config fails WITHOUT unpinning the live one
        with pytest.raises(ValueError, match="max_batch"):
            serve.deploy("serve_gbm", max_batch=9999, buckets=(1, 8))
        with pytest.raises(dkv.KeyLockedError):
            dkv.check_unlocked("serve_gbm")      # still pinned
        assert serve.deployment("serve_gbm") is dep
        assert dep.predict_rows([{"dist": 1.0}])  # still serving
        serve.undeploy("serve_gbm")
        dkv.check_unlocked("serve_gbm")          # pin released
    finally:
        serve.undeploy("serve_gbm")
        dkv.remove("serve_gbm")


def test_deploy_rejects_one_dim_classifier_output():
    """A model declaring K>1 classes whose batch predict yields a 1-D
    margin (uplift-style: predict() override is the only scoring path)
    must be rejected at deploy, not 500 on every request."""
    class FakeUplift:
        algo = "upliftdrf"
        feature_names = ["a", "b"]
        cat_domains = {}
        response_domain = ("0", "1")
        nclasses = 2
        params = {}

        def _predict_matrix(self, X, offset=None):
            import jax.numpy as jnp
            return jnp.zeros(X.shape[0])         # 1-D uplift margin

    with pytest.raises(ValueError, match="not row-servable"):
        serve.deploy("fake_uplift", model=FakeUplift(), buckets=(1, 8),
                     max_batch=8)
    assert serve.deployment("fake_uplift") is None


def test_deploy_prunes_buckets_beyond_max_batch(gbm_model):
    fr, model = gbm_model
    dep = serve.deploy("serve_gbm", model=model, max_batch=64)
    try:
        # default bucket set is 1/8/64/512/4096; batches cap at 64 rows,
        # so the unreachable 512/4096 executables are never compiled
        assert dep.info()["compiled_buckets"] == [1, 8, 64]
    finally:
        serve.undeploy("serve_gbm")


# --------------------------------------------- admission control / deadlines


def _gated_batcher(gate, stats=None, **kw):
    from h2o3_tpu.serve.batcher import MicroBatcher
    from h2o3_tpu.serve.stats import ServeStats

    def encode(rows, pad):
        X = np.zeros((pad, 1), np.float32)
        X[: len(rows), 0] = [r["x"] for r in rows]
        return X

    def dispatch(X, n):
        gate.wait()
        return X[:, 0] * 2.0

    def decode(scores, n):
        # the batcher's decode contract is a DecodedBatch-like object:
        # per-request row/column views over one vectorized pass
        vals = np.asarray(scores)[:n]

        class _Decoded:
            def rows(self, off, k):
                return [{"value": float(v)} for v in vals[off:off + k]]

            def columns(self, off, k):
                return {"value": [float(v) for v in vals[off:off + k]]}

        return _Decoded()

    kw.setdefault("max_batch", 4)
    kw.setdefault("max_delay_ms", 1.0)
    return MicroBatcher(encode=encode, dispatch=dispatch, decode=decode,
                        stats=stats or ServeStats(),
                        bucket_for=lambda n: kw["max_batch"], **kw)


def test_deadline_expiry_raises_and_counts():
    from h2o3_tpu.serve.batcher import ServeDeadlineError
    from h2o3_tpu.serve.stats import ServeStats
    gate = threading.Event()          # closed: device "hangs"
    stats = ServeStats()
    mb = _gated_batcher(gate, stats=stats)
    try:
        with pytest.raises(ServeDeadlineError):
            mb.submit([{"x": 1.0}], timeout_ms=80)
        assert stats.snapshot()["timeouts"] == 1
    finally:
        gate.set()
        mb.close()


def test_queue_backpressure_rejects_with_503():
    from h2o3_tpu.serve.batcher import (ServeOverloadedError,
                                        ServeDeadlineError)
    from h2o3_tpu.serve.stats import ServeStats
    gate = threading.Event()          # closed: the first batch blocks
    stats = ServeStats()
    mb = _gated_batcher(gate, stats=stats, max_batch=2, queue_limit=4)
    results = {}

    def bg(i):
        try:
            results[i] = mb.submit([{"x": float(i)}, {"x": float(i)}],
                                   timeout_ms=10_000)
        except Exception as e:  # noqa: BLE001
            results[i] = e

    try:
        t0 = threading.Thread(target=bg, args=(0,))
        t0.start()
        # wait until the batcher picked request 0 and is blocked in
        # dispatch (pending drains to 0)
        for _ in range(200):
            if mb.pending_rows == 0 and stats.queue_depth >= 2:
                break
            time.sleep(0.005)
        threads = [threading.Thread(target=bg, args=(i,))
                   for i in (1, 2)]
        for t in threads:
            t.start()
        for _ in range(200):           # queue now holds 4 rows (limit)
            if mb.pending_rows == 4:
                break
            time.sleep(0.005)
        assert mb.pending_rows == 4
        with pytest.raises(ServeOverloadedError):
            mb.submit([{"x": 9.0}], timeout_ms=1_000)
        assert stats.snapshot()["rejected"] == 1
        assert serve.ServeOverloadedError.http_status == 503
        assert serve.ServeDeadlineError is ServeDeadlineError
        gate.set()                    # release the device
        t0.join(5)
        for t in threads:
            t.join(5)
        for i in (0, 1, 2):
            assert isinstance(results[i], list), results[i]
            assert results[i][0]["value"] == 2.0 * i
    finally:
        gate.set()
        mb.close()


def test_batcher_coalesces_within_tick():
    gate = threading.Event()
    gate.set()                         # device immediate
    from h2o3_tpu.serve.stats import ServeStats
    stats = ServeStats()
    mb = _gated_batcher(gate, stats=stats, max_batch=8, max_delay_ms=30.0)
    try:
        outs = []
        threads = [threading.Thread(
            target=lambda i=i: outs.append(
                mb.submit([{"x": float(i)}])[0]["value"]))
            for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = stats.snapshot()
        assert sorted(outs) == [0.0, 2.0, 4.0, 6.0]
        # 4 concurrent 1-row requests within one 30ms tick → far fewer
        # batches than requests
        assert snap["batches"] <= 2, snap
        assert snap["mean_batch_occupancy"] >= 2.0, snap
    finally:
        mb.close()


# ------------------------------------------------------------ REST surface


@pytest.fixture(scope="module")
def server(gbm_model):
    from h2o3_tpu.api import start_server
    fr, model = gbm_model
    dkv.put("serve_gbm", "model", model)
    srv = start_server(port=0)
    yield srv
    srv.stop()
    serve.shutdown_all()
    dkv.clear()


def _req(server, method, path, data=None, raw_json=None):
    url = f"http://127.0.0.1:{server.port}{path}"
    body = None
    headers = {}
    if raw_json is not None:
        body = json.dumps(raw_json).encode()
        headers["Content-Type"] = "application/json"
    elif data is not None:
        body = urllib.parse.urlencode(
            {k: (json.dumps(v) if isinstance(v, (list, dict)) else v)
             for k, v in data.items()}).encode()
        headers["Content-Type"] = "application/x-www-form-urlencoded"
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers)
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read().decode())


def test_rest_deploy_score_stats_undeploy_lifecycle(server, gbm_model):
    fr, model = gbm_model
    # deploy with knobs
    dep = _req(server, "POST", "/3/Serve/models/serve_gbm",
               data={"max_batch": 64, "max_delay_ms": 1.0,
                     "buckets": [1, 8, 64]})
    assert dep["model_id"]["name"] == "serve_gbm"
    assert dep["compiled_buckets"] == [1, 8, 64]
    # listed
    lst = _req(server, "GET", "/3/Serve/models")
    assert [d["model"] for d in lst["deployments"]] == ["serve_gbm"]
    # a deployed model's DKV key is pinned: DELETE → 409
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(server, "DELETE", "/3/Models/serve_gbm")
    assert ei.value.code == 409
    # score rows (JSON body)
    rows = _rows_of(fr, range(5))
    out = _req(server, "POST", "/3/Predictions/models/serve_gbm/rows",
               raw_json={"rows": rows})
    assert len(out["predictions"]) == 5
    p0 = out["predictions"][0]
    assert p0["label"] in ("YES", "NO")
    assert set(p0["classProbabilities"]) == {"YES", "NO"}
    # stats surface
    st = _req(server, "GET", "/3/Serve/stats")
    ms = st["models"]["serve_gbm"]
    assert ms["rows"] >= 5 and ms["requests"] >= 1
    assert ms["p99_ms"] is not None and ms["p99_ms"] >= ms["p50_ms"]
    assert set(ms["stage_ms"]) >= {"encode", "queue", "device", "decode"}
    # undeploy → scoring 404s with guidance, model deletable again
    _req(server, "DELETE", "/3/Serve/models/serve_gbm")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(server, "POST", "/3/Predictions/models/serve_gbm/rows",
             raw_json={"rows": rows})
    assert ei.value.code == 404
    _req(server, "DELETE", "/3/Models/serve_gbm")


def test_rest_deploy_unknown_model_404(server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(server, "POST", "/3/Serve/models/not_a_model")
    assert ei.value.code == 404


# -------------------------------------------- columnar response path


def test_columnar_bit_matches_row_dicts(gbm_model):
    """predict_columnar returns the same values as predict_rows from one
    vectorized decode — 'predict' + one p<label> column per class (the
    H2O predictions-frame column convention; ISSUE 5)."""
    fr, model = gbm_model
    dep = serve.deploy("serve_gbm", model=model, max_batch=128,
                       max_delay_ms=0.5)
    try:
        rows = _rows_of(fr, range(160))      # spans two sub-batches
        rd = dep.predict_rows(rows)
        cd = dep.predict_columnar(rows)
        assert sorted(cd) == ["pNO", "pYES", "predict"]
        assert len(cd["predict"]) == len(rows)
        for i in range(len(rows)):
            assert cd["predict"][i] == rd[i]["label"]
            assert cd["pYES"][i] == rd[i]["classProbabilities"]["YES"]
            assert cd["pNO"][i] == rd[i]["classProbabilities"]["NO"]
    finally:
        serve.undeploy("serve_gbm")


def test_columnar_and_row_requests_share_a_batch(gbm_model):
    """Mixed-format requests coalesce into the same device batch and
    each gets its own shape back."""
    fr, model = gbm_model
    dep = serve.deploy("serve_gbm", model=model, max_batch=64,
                       max_delay_ms=20.0)
    try:
        rows = _rows_of(fr, range(8))
        outs = {}

        def go(fmt):
            outs[fmt] = (dep.predict_columnar(rows) if fmt == "col"
                         else dep.predict_rows(rows))

        ts = [threading.Thread(target=go, args=(f,))
              for f in ("col", "row")]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(outs["row"]) == 8
        assert len(outs["col"]["predict"]) == 8
        for i in range(8):
            assert outs["col"]["predict"][i] == outs["row"][i]["label"]
    finally:
        serve.undeploy("serve_gbm")


def test_rest_predictions_columnar_format(server, gbm_model):
    fr, model = gbm_model
    # the lifecycle test may have DELETEd the store entry — re-put
    dkv.put("serve_gbm", "model", model)
    _req(server, "POST", "/3/Serve/models/serve_gbm")
    try:
        rows = _rows_of(fr, range(6))
        out = _req(server, "POST",
                   "/3/Predictions/models/serve_gbm/rows?format=columnar",
                   raw_json={"rows": rows})
        assert out["__meta"]["schema_name"] == "ServePredictionsColumnarV3"
        assert out["nrow"] == 6
        cols = out["columns"]
        assert sorted(cols) == ["pNO", "pYES", "predict"]
        assert all(len(v) == 6 for v in cols.values())
        # bit-match against the row-dict shape on the same rows
        ref = _req(server, "POST", "/3/Predictions/models/serve_gbm/rows",
                   raw_json={"rows": rows})["predictions"]
        for i in range(6):
            assert cols["predict"][i] == ref[i]["label"]
            assert cols["pYES"][i] == ref[i]["classProbabilities"]["YES"]
        # unknown format → 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(server, "POST",
                 "/3/Predictions/models/serve_gbm/rows?format=bogus",
                 raw_json={"rows": rows})
        assert ei.value.code == 400
    finally:
        serve.undeploy("serve_gbm")


# ------------------------------------------------- vectorized row codec


def test_rows_to_matrix_unknown_int_codes_honor_policy():
    from h2o3_tpu.genmodel import rows_to_matrix
    cols = ["c", "x"]
    doms = {"c": ("a", "b", "c")}
    seen = {}
    m = rows_to_matrix([{"c": "b", "x": 1.5},
                        {"c": "zz", "x": None},        # unknown label
                        {"c": 7, "x": 2.0},            # int code OOB
                        {"c": 2.0, "x": "3.5"},        # valid int code
                        {"c": 1.5, "x": 4.0}],         # non-integral code
                       cols, doms, unknown_seen=seen)
    assert m[0, 0] == 1.0 and m[0, 1] == 1.5
    assert np.isnan(m[1, 0]) and np.isnan(m[1, 1])
    assert np.isnan(m[2, 0])                 # OOB int code → NA (fixed)
    assert m[3, 0] == 2.0 and m[3, 1] == 3.5
    assert np.isnan(m[4, 0])                 # non-integral code → NA
    assert seen == {"c": 3}
    # strict mode raises on the same inputs
    with pytest.raises(ValueError, match="unknown categorical"):
        rows_to_matrix([{"c": 7}], cols, doms,
                       convert_unknown_categorical_levels_to_na=False)


def test_easypredict_row_matches_rows_to_matrix(gbm_model):
    fr, model = gbm_model
    from h2o3_tpu.genmodel import EasyPredictModelWrapper, rows_to_matrix
    wrap = EasyPredictModelWrapper(model)
    rows = _rows_of(fr, range(7))
    rows[3]["carrier"] = "??"            # unknown level
    del rows[5]["hour"]                  # missing column
    batch = rows_to_matrix(rows, wrap.columns, wrap.cat_domains)
    for i, r in enumerate(rows):
        single = wrap._row_to_array(r)
        assert np.array_equal(single, batch[i], equal_nan=True)
    assert wrap.unknown_categorical_levels_seen == {"carrier": 1}


# ------------------------------------------------------ jobs satellites


def test_job_update_is_thread_safe():
    from h2o3_tpu.jobs import Job
    job = Job("race", work=10_000.0)
    threads = [threading.Thread(
        target=lambda: [job.update(1.0) for _ in range(1000)])
        for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert job._worked == 8000.0         # lost updates would undershoot
    assert abs(job.progress - 0.8) < 1e-9


def test_job_registry_evicts_terminal_beyond_keep(monkeypatch):
    from h2o3_tpu import jobs as jobs_mod
    monkeypatch.setenv("H2O3_JOBS_KEEP", "5")
    live = jobs_mod.Job("live one")      # RUNNING — never evicted
    done = []
    for i in range(12):
        j = jobs_mod.Job(f"t{i}")
        j.run(lambda _j: None)           # terminal (DONE)
        done.append(j)
    # the oldest terminal jobs are gone, the newest stay (eviction rides
    # on registration, so the LAST job to finish can make it keep+1)
    assert jobs_mod.get_job(live.key) is live
    assert jobs_mod.get_job(done[0].key) is None
    remaining = [j for j in done if jobs_mod.get_job(j.key) is not None]
    assert 0 < len(remaining) <= 6
    assert remaining[-1] is done[-1]
    # the registry stays bounded under mass churn; running jobs survive
    for i in range(20):
        jobs_mod.Job(f"u{i}").run(lambda _j: None)
    terminal = [j for j in jobs_mod.list_jobs()
                if j.status != jobs_mod.RUNNING]
    assert len(terminal) <= 6
    assert jobs_mod.get_job(live.key) is live
