"""Multi-chip SPMD training as the DEFAULT path (ISSUE 7).

The suite runs on an 8-virtual-device CPU mesh (root conftest forces
``--xla_force_host_platform_device_count=8``), so these tests exercise
the real sharded product path: frames land data-mesh-sharded, the GBM/
DRF chunk steps shard_map over the mesh with one histogram psum per
level, and (on a mesh with a model axis) split search shards over the
feature blocks.

Contracts covered:
- sharded-vs-single-device GBM/DRF predictions and AUC agree within
  tolerance (the reference's "same answer on 1 or N nodes" invariant —
  psum reduce order may flip last-ulp split ties, exactly like MRTask
  float nondeterminism, so predictions are compared with tolerance);
- model-axis split search is BIT-identical to the unsharded search at
  equal data sharding (tie-breaking is feature-major in both);
- warm sharded retrains compile 0 XLA modules (the zero-recompile
  contract extends to the SPMD path);
- ``H2O3_SPMD=0`` collapses the default mesh to one device (escape
  hatch), and shard-aligned streamed ingest reproduces the host-merge
  parse bit-for-bit on a wide mesh.
"""
import jax
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.drf import H2ORandomForestEstimator
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.parallel.mesh import (DataParallelPartitioner, current_mesh,
                                    logical_to_physical, make_mesh,
                                    partitioner, set_mesh, spmd_enabled)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-virtual-device test mesh")


def _data(n=1024, F=6, seed=11):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[rng.random((n, F)) < 0.05] = np.nan
    y = ((np.nan_to_num(X[:, 0]) > 0)
         ^ (np.nan_to_num(X[:, 1]) > 0.3)).astype(np.float32)
    return X, y


def _train(est_cls, mesh, X, y, classification=True, **params):
    old = current_mesh()
    set_mesh(mesh)
    try:
        cols = {f"f{i}": X[:, i] for i in range(X.shape[1])}
        cols["y"] = (np.array(["n", "y"], dtype=object)[y.astype(int)]
                     if classification else y)
        fr = h2o.Frame.from_numpy(cols)
        est = est_cls(seed=7, **params)
        est.train(y="y", training_frame=fr)
        pred = est.model.predict(fr)
        col = "py" if classification else "predict"
        return est.model, np.asarray(pred.vec(col).to_numpy(),
                                     dtype=np.float64), fr
    finally:
        set_mesh(old)


GBM_PARAMS = dict(ntrees=5, max_depth=4, nbins=16, min_rows=2.0,
                  distribution="bernoulli", score_tree_interval=0,
                  stopping_rounds=0)
DRF_PARAMS = dict(ntrees=5, max_depth=4, nbins=16, min_rows=2.0)


def test_gbm_sharded_matches_single_device():
    """Default-path GBM on the full (4,2) mesh (data psum + model-axis
    split search) vs one device: probabilities close, AUC within 2e-3."""
    X, y = _data()
    m1, p1, _ = _train(H2OGradientBoostingEstimator,
                       make_mesh(n_data=1, devices=jax.devices()[:1]),
                       X, y, **GBM_PARAMS)
    m8, p8, _ = _train(H2OGradientBoostingEstimator,
                       make_mesh(n_data=4, n_model=2), X, y, **GBM_PARAMS)
    spmd8 = dict(m8.output["spmd"])
    # collective/straggler attribution rides along on sharded trains
    # (ISSUE 8) — layout keys unchanged
    coll = spmd8.pop("collective", None)
    assert spmd8 == {"n_data": 4, "n_model": 2,
                     "model_axis_split_search": True}
    assert coll is None or coll["n_shards"] == 8
    assert m1.output["spmd"]["n_data"] == 1
    np.testing.assert_allclose(p1, p8, rtol=0, atol=1e-5)
    assert abs(m1.training_metrics.auc - m8.training_metrics.auc) < 2e-3


def test_gbm_model_axis_split_search_bit_identical():
    """(4,1) vs (4,2): the data sharding (and therefore every psum'd
    histogram) is identical, so sharding the split SEARCH over the model
    axis must pick bit-identical splits (feature-major tie-break in both
    layouts)."""
    X, y = _data(seed=23)
    m41, _, _ = _train(H2OGradientBoostingEstimator,
                       make_mesh(n_data=4, n_model=1,
                                 devices=jax.devices()[:4]),
                       X, y, **GBM_PARAMS)
    m42, _, _ = _train(H2OGradientBoostingEstimator,
                       make_mesh(n_data=4, n_model=2), X, y, **GBM_PARAMS)
    np.testing.assert_array_equal(np.asarray(m41._feat),
                                  np.asarray(m42._feat))
    np.testing.assert_array_equal(np.asarray(m41._thr),
                                  np.asarray(m42._thr))
    np.testing.assert_array_equal(np.asarray(m41._is_split),
                                  np.asarray(m42._is_split))
    # deepest-level leaf stats read a different (mathematically equal)
    # feature's bin sums on the winner shard — last-ulp tolerance
    np.testing.assert_allclose(np.asarray(m41._value),
                               np.asarray(m42._value), rtol=1e-5,
                               atol=1e-7)


def test_drf_sharded_matches_single_device():
    X, y = _data(seed=5)
    m1, p1, _ = _train(H2ORandomForestEstimator,
                       make_mesh(n_data=1, devices=jax.devices()[:1]),
                       X, y, **DRF_PARAMS)
    m8, p8, _ = _train(H2ORandomForestEstimator,
                       make_mesh(n_data=4, n_model=2), X, y, **DRF_PARAMS)
    assert m8.output["spmd"]["n_data"] == 4
    # DRF row-sampling keys fold in the shard index (decorrelated
    # bootstraps), so trees legitimately differ across mesh layouts —
    # the MODEL must still agree: vote fractions close, AUC close
    assert np.mean(np.abs(p1 - p8)) < 0.12
    assert abs(m1.training_metrics.auc - m8.training_metrics.auc) < 0.05


def test_warm_sharded_retrain_zero_recompiles():
    """Zero-recompile contract on the SPMD path: an identical retrain on
    the sharded default mesh reuses every executable."""
    from tests._compile_counter import count_compiles
    X, y = _data(seed=9)
    cols = {f"f{i}": X[:, i] for i in range(X.shape[1])}
    cols["y"] = np.array(["n", "y"], dtype=object)[y.astype(int)]
    fr = h2o.Frame.from_numpy(cols)
    H2OGradientBoostingEstimator(seed=7, **GBM_PARAMS).train(
        y="y", training_frame=fr)
    with count_compiles([]) as compiles:
        est = H2OGradientBoostingEstimator(seed=7, **GBM_PARAMS)
        est.train(y="y", training_frame=fr)
    assert est.model.output["spmd"]["n_data"] > 1
    assert len(compiles) == 0, f"warm sharded retrain compiled {compiles}"


def test_spmd_escape_hatch_collapses_default_mesh(monkeypatch):
    """H2O3_SPMD=0 restores single-chip behavior: the lazily-built
    default mesh spans exactly one device and training reports an
    unsharded layout."""
    old = current_mesh()
    monkeypatch.setenv("H2O3_SPMD", "0")
    assert not spmd_enabled()
    set_mesh(None)              # force the lazy default to rebuild
    try:
        assert dict(current_mesh().shape) == {"data": 1, "model": 1}
        X, y = _data(n=256, seed=3)
        m, _, _ = _train(H2OGradientBoostingEstimator, current_mesh(),
                         X, y, ntrees=2, max_depth=3, nbins=8,
                         distribution="bernoulli")
        assert m.output["spmd"] == {"n_data": 1, "n_model": 1,
                                    "model_axis_split_search": False}
    finally:
        set_mesh(old)


def test_partitioner_layer():
    """DataParallelPartitioner: logical→physical rules, row placement,
    chunk homing and shard bounds."""
    part = partitioner()
    assert isinstance(part, DataParallelPartitioner)
    assert logical_to_physical(("rows",))[0] == "data"
    assert tuple(logical_to_physical(("rows", "features"))) == \
        ("data", "model")
    assert logical_to_physical(("bins",))[0] is None
    nd = part.n_data
    # chunk homes are monotone in chunk order and cover every shard
    homes = [part.chunk_home(k, 4 * nd) for k in range(4 * nd)]
    assert homes == sorted(homes)
    assert set(homes) == set(range(nd))
    # shard_rows places a padded host array row-sharded over 'data'
    arr = np.arange(8 * nd, dtype=np.float32)[:, None]
    dev = part.shard_rows(arr)
    assert dict(dev.sharding.mesh.shape)["data"] == nd
    np.testing.assert_array_equal(np.asarray(dev), arr)
    bounds = part.row_bounds(8 * nd)
    assert bounds[0] == (0, 8) and bounds[-1][1] == 8 * nd


def test_shard_aligned_chunk_streamer_matches_host_merge():
    """ingest/stream.py on a wide mesh: per-chunk puts land on home
    shard devices and the assembled columns are bit-equal to a host
    concat, with the aligned-row ratio ~1 for row-ordered chunks."""
    from h2o3_tpu.ingest.stream import ChunkDeviceStreamer
    from h2o3_tpu.frame.vec import T_REAL

    class _Col:
        vtype = T_REAL
        exact = None

        def __init__(self, data):
            self.data = np.asarray(data, np.float64)

    mesh = current_mesh()
    rng = np.random.default_rng(2)
    n_chunks, rows_c = 16, 100
    full = rng.normal(size=(n_chunks * rows_c, 2))
    st = ChunkDeviceStreamer([0, 1], [T_REAL, T_REAL], n_chunks, mesh)
    assert st.nd > 1
    for k in range(n_chunks):
        seg = full[k * rows_c:(k + 1) * rows_c]
        st.add(k, [_Col(seg[:, 0]), _Col(seg[:, 1])])
    vecs = st.assemble()
    for j in (0, 1):
        got = np.asarray(vecs[j].data)[: full.shape[0]]
        np.testing.assert_array_equal(got, full[:, j].astype(np.float32))
        assert vecs[j].data.sharding.spec[0] == "data"
    assert st.aligned_row_ratio == 1.0
    prof = st.shard_profile()
    assert len(prof) == st.nd
    assert sum(s["chunks"] for s in prof) == n_chunks
    assert all(s["h2d_bytes"] > 0 for s in prof)


class _CancelAfter:
    """Job stand-in whose cancel_requested flips after N progress
    heartbeats — drives the inner-loop polling deterministically."""

    def __init__(self, beats):
        from h2o3_tpu.jobs import Job
        self._job = Job("test-cancel", work=1.0)
        self._beats = beats
        if beats <= 0:          # the watchdog-already-fired shape
            self._job.cancel(reason="test")

    def __getattr__(self, name):
        return getattr(self._job, name)

    def set_progress(self, p):
        self._beats -= 1
        if self._beats <= 0:
            self._job.cancel(reason="test")
        return self._job.set_progress(p)


def test_kmeans_polls_cancel_in_lloyd_loop():
    from h2o3_tpu.models.kmeans import H2OKMeansEstimator
    rng = np.random.default_rng(0)
    cols = {f"x{i}": rng.normal(size=2000) for i in range(4)}
    fr = h2o.Frame.from_numpy(cols)
    est = H2OKMeansEstimator(k=6, max_iterations=200, seed=1)
    spec = est._make_spec(fr, None, None)
    job = _CancelAfter(beats=3)
    model = est._train_impl(spec, None, job)
    assert job.cancel_requested
    assert model.iterations <= 5, \
        f"Lloyd loop ran {model.iterations} iterations past the cancel"


def test_glm_polls_cancel_in_irls_loop():
    """A cancel landing before the IRLS loop (the watchdog's
    max_runtime path) must stop the fit after at most one step — the
    partial coefficients differ from the converged fit."""
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    rng = np.random.default_rng(4)
    n = 1500
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    yb = (1.0 / (1.0 + np.exp(-(1.8 * x1 - 2.2 * x2))) >
          rng.random(n)).astype(int)
    cols = {"x1": x1, "x2": x2,
            "y": np.array(["n", "y"], dtype=object)[yb]}
    fr = h2o.Frame.from_numpy(cols)

    full = H2OGeneralizedLinearEstimator(family="binomial")
    full.train(y="y", training_frame=fr)

    est = H2OGeneralizedLinearEstimator(family="binomial")
    spec = est._make_spec(fr, "y", None)
    job = _CancelAfter(beats=0)         # pre-cancelled (watchdog shape)
    model = est._train_impl(spec, None, job)
    partial = model.coef()
    conv = full.model.coef()
    diff = max(abs(partial[k] - conv[k]) for k in conv)
    assert diff > 1e-3, \
        "pre-cancelled GLM still converged — inner IRLS loop not polling"
