"""Parser tests — mirror the reference's parser suite
(h2o-core/src/test/java/water/parser/ParserTest*.java): separator/header/
type guessing, NA strings, quoted fields, enum domains, multi-file."""
import os

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.ingest.parse import guess_separator, parse, parse_setup


CSV = """id,age,name,salary,joined
1,34,alice,1000.5,2020-01-01
2,28,bob,NA,2021-06-15
3,,carol,2000.25,2019-11-30
4,45,dave,1500.0,2022-03-10
"""


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "people.csv"
    p.write_text(CSV)
    return str(p)


def test_parse_setup_guesses(csv_file):
    s = parse_setup(csv_file)
    assert s.separator == ","
    assert s.header is True
    assert s.column_names == ["id", "age", "name", "salary", "joined"]
    assert s.column_types == ["int", "int", "enum", "real", "time"]


def test_parse_values(csv_file):
    fr = h2o.import_file(csv_file)
    assert fr.nrow == 4 and fr.ncol == 5
    np.testing.assert_allclose(fr.vec("id").to_numpy(), [1, 2, 3, 4])
    age = fr.vec("age").to_numpy()
    assert np.isnan(age[2])
    assert fr.vec("age").na_count() == 1
    assert fr.vec("salary").na_count() == 1
    assert fr.vec("name").domain == ("alice", "bob", "carol", "dave")
    t = fr.vec("joined")
    assert t.type == "time"
    assert t.to_numpy()[0] == np.datetime64("2020-01-01", "ms").astype(np.int64)


def test_no_header_and_tab_sep(tmp_path):
    p = tmp_path / "t.tsv"
    p.write_text("1\t2.5\tx\n3\t4.5\ty\n")
    fr = h2o.import_file(str(p))
    assert fr.names == ["C1", "C2", "C3"]
    assert fr.types == {"C1": "int", "C2": "real", "C3": "enum"}
    assert fr.nrow == 2


def test_quoted_fields_and_custom_na(tmp_path):
    p = tmp_path / "q.csv"
    p.write_text('a,b\n"hello, world",1\nmissing,2\n')
    fr = h2o.import_file(str(p), na_strings=["missing"])
    assert fr.nrow == 2
    assert fr.vec("a").na_count() == 1
    assert "hello, world" in fr.vec("a").domain


def test_multi_file_parse(tmp_path):
    p1 = tmp_path / "a.csv"
    p2 = tmp_path / "b.csv"
    p1.write_text("x,y\n1,a\n2,b\n")
    p2.write_text("x,y\n3,c\n4,a\n")
    s = parse_setup([str(p1), str(p2)])
    fr = parse([str(p1), str(p2)], s)
    assert fr.nrow == 4
    np.testing.assert_allclose(fr.vec("x").to_numpy(), [1, 2, 3, 4])
    assert set(fr.vec("y").domain) == {"a", "b", "c"}


def test_guess_separator_variants():
    assert guess_separator("a;b;c\n1;2;3\n") == ";"
    assert guess_separator("a|b\n1|2\n") == "|"


def test_forced_col_types(csv_file):
    fr = h2o.import_file(csv_file, col_types=["enum", None, None, None, None])
    assert fr.vec("id").type == "enum"
    assert fr.vec("id").domain == ("1", "2", "3", "4")


def test_time_na_counts(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("d\n2020-01-01\nNA\n2021-05-05\n")
    fr = h2o.import_file(str(p))
    v = fr.vec("d")
    assert v.type == "time"
    assert v.na_count() == 1
    assert v.rollups()["min"] > 1.5e9  # epoch seconds, not the NA sentinel


def test_skipped_columns(tmp_path):
    p = tmp_path / "s.csv"
    p.write_text("a,b,c\n1,2,3\n4,5,6\n")
    s = parse_setup(str(p))
    s.skipped_columns = [1]
    fr = parse(str(p), s)
    assert fr.names == ["a", "c"]


def test_remote_persist_via_arrow_fs(monkeypatch):
    """s3://gs://hdfs:// persist backends ride pyarrow.fs
    (water/persist/PersistS3 et al. analogs): exercise the REAL
    download-to-cache path against pyarrow's in-memory mock filesystem,
    then parse the localized file end-to-end."""
    from pyarrow import fs as pafs

    from h2o3_tpu.ingest import persist_uri

    mock = pafs._MockFileSystem()
    mock.create_dir("bucket")
    with mock.open_output_stream("bucket/remote.csv") as f:
        f.write(b"a,b\n1,2\n3,4\n5,6\n")
    monkeypatch.setattr(persist_uri, "_remote_fs",
                        lambda uri: (mock, "bucket/remote.csv"))
    # distinct URIs → distinct cache entries; both funnel through the
    # mocked remote
    for uri in ("s3://bucket/remote.csv", "gs://bucket/remote.csv"):
        local = persist_uri.localize(uri)
        assert os.path.exists(local)
        fr = h2o.import_file(uri)
        assert fr.nrow == 3 and fr.ncol == 2
        assert fr.vec(0).to_numpy()[:3].tolist() == [1.0, 3.0, 5.0]


def test_remote_persist_unavailable_message():
    """hdfs without libhdfs must fail with the gated-backend error, not
    a raw traceback (persist backends degrade with a clear message)."""
    from h2o3_tpu.ingest import persist_uri
    try:
        persist_uri.localize("hdfs://namenode:8020/data.csv")
    except NotImplementedError as e:
        assert "hdfs" in str(e)
    except Exception as e:  # pragma: no cover - env-dependent
        raise AssertionError(f"expected NotImplementedError, got {e!r}")
