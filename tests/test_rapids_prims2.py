"""Round-4 Rapids prim batch: reducers/advmath, mungers, string,
fold-column and reshaping prims the h2o-py client can emit
(water/rapids/ast/prims/{reducers,advmath,mungers,string,misc})."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import dkv
from h2o3_tpu.rapids import exec_rapids


@pytest.fixture()
def fr():
    f = h2o.Frame.from_numpy({
        "x": np.array([3.0, 1.0, 2.0, np.nan, 5.0]),
        "y": np.array([10.0, 20.0, 30.0, 40.0, 50.0]),
        "s": np.array(["  ab", "cd  ", "a b", None, "xyz"], dtype=object)})
    dkv.put("p2", "frame", f)
    return f


def _frame(r):
    return dkv.get(r["key"]["name"], "frame")


def test_reducers(fr):
    assert exec_rapids("(any.na p2)")["scalar"] == 1.0
    assert exec_rapids("(naCnt p2)")["scalar"][0] == 1.0
    assert exec_rapids("(all (> (cols_py p2 'y') 5))")["scalar"] == 1.0
    assert exec_rapids("(any (> (cols_py p2 'y') 45))")["scalar"] == 1.0


def test_skew_kurt():
    rng = np.random.default_rng(0)
    x = rng.normal(size=5000).astype(np.float64)
    dkv.put("sk", "frame", h2o.Frame.from_numpy({"x": x}))
    sk = exec_rapids("(skewness sk True)")["scalar"][0]
    ku = exec_rapids("(kurtosis sk True)")["scalar"][0]
    assert abs(sk) < 0.2
    assert abs(ku - 3.0) < 0.3


def test_quantile_and_hist(fr):
    r = exec_rapids("(quantile (cols_py p2 'y') [0.0 0.5 1.0] 'interpolate' _)")
    q = _frame(r)
    got = np.asarray(q.vec("yQuantiles").to_numpy()[:3])
    np.testing.assert_allclose(got, [10, 30, 50])
    r = exec_rapids("(hist (cols_py p2 'y') 4)")
    hf = _frame(r)
    assert "counts" in hf.names and hf.nrow >= 4


def test_match_relevel_cut():
    f = h2o.Frame.from_numpy({
        "c": np.array(["a", "b", "c", "b"], dtype=object)})
    dkv.put("mr", "frame", f)
    r = exec_rapids("(match (cols_py mr 'c') ['b' 'c'] _ 1)")
    out = _frame(r)
    vals = np.asarray(out.vec(0).to_numpy()[:4])
    assert np.isnan(vals[0]) and vals[1] == 1 and vals[2] == 2
    r = exec_rapids("(relevel (cols_py mr 'c') 'c')")
    rl = _frame(r)
    assert rl.vec(0).domain[0] == "c"
    f2 = h2o.Frame.from_numpy({"x": np.array([0.5, 1.5, 2.5, 3.5])})
    dkv.put("cu", "frame", f2)
    r = exec_rapids("(cut (cols_py cu 'x') [0 1 2 3 4] [] False True 3)")
    cf = _frame(r)
    assert cf.vec(0).type == "enum"
    np.testing.assert_array_equal(cf.vec(0).to_numpy()[:4], [0, 1, 2, 3])


def test_string_prims(fr):
    r = exec_rapids("(strlen (cols_py p2 's'))")
    ln = np.asarray(_frame(r).vec(0).to_numpy()[:5])
    assert ln[0] == 4 and np.isnan(ln[3])
    r = exec_rapids("(lstrip (cols_py p2 's') ' ')")
    assert _frame(r).vec(0).to_strings()[0] == "ab"
    r = exec_rapids("(countmatches (cols_py p2 's') ['a'])")
    cm = np.asarray(_frame(r).vec(0).to_numpy()[:5])
    assert cm[0] == 1 and cm[2] == 1
    r = exec_rapids("(grep (cols_py p2 's') 'a' False False True)")
    g = np.asarray(_frame(r).vec(0).to_numpy()[:5])
    np.testing.assert_array_equal(g, [1, 0, 1, 0, 0])
    r = exec_rapids("(strsplit (cols_py p2 's') ' ')")
    sp = _frame(r)
    assert sp.ncol >= 2


def test_fold_columns(fr):
    r = exec_rapids("(kfold_column p2 3 42)")
    f = np.asarray(_frame(r).vec(0).to_numpy()[:5])
    assert set(f).issubset({0.0, 1.0, 2.0})
    r = exec_rapids("(modulo_kfold_column p2 2)")
    np.testing.assert_array_equal(_frame(r).vec(0).to_numpy()[:5],
                                  [0, 1, 0, 1, 0])
    r = exec_rapids("(stratified_kfold_column (cols_py p2 'y') 2 7)")
    assert _frame(r).nrow == 5


def test_melt_pivot():
    f = h2o.Frame.from_numpy({"id": np.array([1.0, 2.0]),
                              "a": np.array([10.0, 20.0]),
                              "b": np.array([30.0, 40.0])})
    dkv.put("mp", "frame", f)
    r = exec_rapids("(melt mp [0] [1 2] 'variable' 'value' False)")
    m = _frame(r)
    assert m.nrow == 4 and set(m.names) == {"id", "variable", "value"}
    dkv.put("mm", "frame", m)
    r = exec_rapids("(pivot mm 'id' 'variable' 'value')")
    p = _frame(r)
    assert p.nrow == 2 and "a" in p.names and "b" in p.names
    np.testing.assert_allclose(p.vec("a").to_numpy()[:2], [10, 20])


def test_topn_rank_dropdup():
    f = h2o.Frame.from_numpy({"g": np.array([1.0, 1.0, 2.0, 2.0, 2.0]),
                              "v": np.array([5.0, 3.0, 9.0, 1.0, 9.0])})
    dkv.put("tr", "frame", f)
    r = exec_rapids("(topn tr 1 40 0)")
    t = _frame(r)
    assert 9.0 in np.asarray(t.vec(1).to_numpy()[: t.nrow])
    r = exec_rapids("(rank_within_groupby tr [0] [1] [1] 'rk' 0)")
    rk = _frame(r)
    vals = np.asarray(rk.vec("rk").to_numpy()[:5])
    assert vals[1] == 1.0 and vals[0] == 2.0     # within group 1: 3 < 5
    r = exec_rapids("(dropdup tr [0] 'first')")
    dd = _frame(r)
    assert dd.nrow == 2


def test_misc(fr):
    r = exec_rapids("(t (cols_py p2 ['x' 'y']))")
    t = _frame(r)
    assert t.nrow == 2 and t.ncol == 5
    r = exec_rapids("(h2o.runif p2 42)")
    u = np.asarray(_frame(r).vec(0).to_numpy()[:5])
    assert ((0 <= u) & (u < 1)).all()
    r = exec_rapids("(difflag1 (cols_py p2 'y'))")
    d = np.asarray(_frame(r).vec(0).to_numpy()[:5])
    assert np.isnan(d[0]) and d[1] == 10.0
    assert exec_rapids("(columnsByType p2 'numeric')")["scalar"] == [0.0, 1.0]
    # x has 1 NA and s has 1 None out of 5 rows (20% >= 10%): only y kept
    assert exec_rapids("(filterNACols p2 0.1)")["scalar"] == [1.0]
    r = exec_rapids("(h2o.fillna (cols_py p2 'x') 'forward' 0 1)")
    fl = np.asarray(_frame(r).vec(0).to_numpy()[:5])
    assert fl[3] == 2.0
    r = exec_rapids("(rep_len 7 4)")
    assert _frame(r).nrow == 4
    assert exec_rapids("(flatten (cols_py p2 'y'))")["scalar"] == 10.0


def test_distance():
    a = h2o.Frame.from_numpy({"x": np.array([0.0, 3.0]),
                              "y": np.array([0.0, 4.0])})
    b = h2o.Frame.from_numpy({"x": np.array([0.0]),
                              "y": np.array([0.0])})
    dkv.put("da", "frame", a)
    dkv.put("db", "frame", b)
    r = exec_rapids("(distance da db 'l2')")
    d = np.asarray(_frame(r).vec(0).to_numpy()[:2])
    np.testing.assert_allclose(d, [0.0, 5.0])


def test_tf_idf_golden():
    """(tf-idf fr doc_id_idx text_idx preprocess case_sensitive) vs the
    reference's golden values (h2o-py tests/testdir_algos/tf-idf/
    pyunit_PUBDEV-6938_tf-idf.py; IDF = log((N+1)/(DF+1)),
    hex/tfidf/InverseDocumentFrequencyTask.java)."""
    f = h2o.Frame.from_numpy({
        "DocID": np.array([0.0, 1.0, 2.0]),
        "Document": np.array(["A B C", "A a a Z", "C c B C"], dtype=object)})
    dkv.put("tfidf_in", "frame", f)
    out = _frame(exec_rapids("(tf-idf tfidf_in 0 1 True True)"))
    assert out.names == ["DocID", "Token", "TF", "IDF", "TF-IDF"]
    toks = list(out.vec(1).to_strings()[: out.nrow])
    assert toks == ["A", "A", "B", "B", "C", "C", "Z", "a", "c"]
    np.testing.assert_allclose(out.vec(0).to_numpy()[: out.nrow],
                               [0, 1, 0, 2, 0, 2, 1, 1, 2])
    np.testing.assert_allclose(out.vec(2).to_numpy()[: out.nrow],
                               [1, 1, 1, 1, 1, 2, 1, 2, 1])
    np.testing.assert_allclose(
        out.vec(3).to_numpy()[: out.nrow],
        [0.28768, 0.28768, 0.28768, 0.28768, 0.28768, 0.28768,
         0.69314, 0.69314, 0.69314], atol=1e-4)
    np.testing.assert_allclose(
        out.vec(4).to_numpy()[: out.nrow],
        [0.28768, 0.28768, 0.28768, 0.28768, 0.28768, 0.57536,
         0.69314, 1.38629, 0.69314], atol=1e-4)
    # case-insensitive merges A/a and C/c
    out2 = _frame(exec_rapids("(tf-idf tfidf_in 0 1 True False)"))
    toks2 = list(out2.vec(1).to_strings()[: out2.nrow])
    assert toks2 == ["a", "a", "b", "b", "c", "c", "z"]
    np.testing.assert_allclose(out2.vec(2).to_numpy()[: out2.nrow],
                               [1, 3, 1, 1, 1, 3, 1])
    # preprocess=False consumes an already-tokenized (doc, word) frame
    f2 = h2o.Frame.from_numpy({
        "DocID": np.array([0.0, 0, 0, 1, 1, 1, 1, 2, 2, 2, 2]),
        "Words": np.array(list("ABC") + ["A", "a", "a", "Z"]
                          + ["C", "c", "B", "C"], dtype=object)})
    dkv.put("tfidf_pre", "frame", f2)
    out3 = _frame(exec_rapids("(tf-idf tfidf_pre 0 1 False True)"))
    assert list(out3.vec(1).to_strings()[: out3.nrow]) == toks
    np.testing.assert_allclose(out3.vec(2).to_numpy()[: out3.nrow],
                               [1, 1, 1, 1, 1, 2, 1, 2, 1])
