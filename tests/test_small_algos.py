"""ExtendedIsolationForest, Isotonic, SVD, Aggregator tests + expanded
metrics tables (reference test style: hex/tree/isoforextended, hex/isotonic,
hex/svd, hex/aggregator unit tests; AUC2/GainsLift golden checks)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.aggregator import H2OAggregatorEstimator
from h2o3_tpu.models.isoforextended import \
    H2OExtendedIsolationForestEstimator
from h2o3_tpu.models.isotonic import H2OIsotonicRegressionEstimator
from h2o3_tpu.models.svd import H2OSingularValueDecompositionEstimator


def test_extended_isolation_forest_ranks_outliers():
    rng = np.random.default_rng(0)
    n = 1500
    X = rng.normal(size=(n, 4)).astype(np.float32)
    X[:15] = X[:15] * 0.2 + 7.0
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    eif = H2OExtendedIsolationForestEstimator(
        ntrees=60, sample_size=128, extension_level=3, seed=1)
    eif.train(training_frame=fr)
    pred = eif.model.predict(fr)
    score = pred.vec("anomaly_score").to_numpy()
    top = np.argsort(-score)[:25]
    assert np.sum(top < 15) >= 12
    assert eif.model.training_metrics.mean_score > 0


def test_extended_isolation_forest_save_load(tmp_path):
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 3)).astype(np.float32)
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    eif = H2OExtendedIsolationForestEstimator(ntrees=8, sample_size=64,
                                              extension_level=1, seed=1)
    eif.train(training_frame=fr)
    p = h2o.save_model(eif.model, str(tmp_path), filename="eif")
    m2 = h2o.load_model(p)
    s1 = eif.model.predict(fr).vec("anomaly_score").to_numpy()
    s2 = m2.predict(fr).vec("anomaly_score").to_numpy()
    np.testing.assert_allclose(s1, s2, rtol=1e-6)


def test_isotonic_matches_sklearn():
    from sklearn.isotonic import IsotonicRegression
    rng = np.random.default_rng(7)
    n = 2000
    x = rng.uniform(-3, 3, n).astype(np.float64)
    y = (np.tanh(x) + rng.normal(scale=0.3, size=n)).astype(np.float64)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    iso = H2OIsotonicRegressionEstimator()
    iso.train(y="y", x=["x"], training_frame=fr)
    ours = iso.model.predict(fr).vec("predict").to_numpy()
    sk = IsotonicRegression(out_of_bounds="clip").fit(x, y)
    theirs = sk.predict(x)
    np.testing.assert_allclose(ours, theirs, atol=1e-4)


def test_isotonic_weighted():
    from sklearn.isotonic import IsotonicRegression
    rng = np.random.default_rng(9)
    n = 500
    x = rng.uniform(0, 1, n)
    y = x + rng.normal(scale=0.2, size=n)
    w = rng.uniform(0.5, 2.0, n)
    fr = h2o.Frame.from_numpy({"x": x, "y": y, "w": w})
    iso = H2OIsotonicRegressionEstimator(weights_column="w")
    iso.train(y="y", x=["x"], training_frame=fr)
    ours = iso.model.predict(fr).vec("predict").to_numpy()
    sk = IsotonicRegression(out_of_bounds="clip").fit(x, y, sample_weight=w)
    np.testing.assert_allclose(ours, sk.predict(x), atol=1e-4)


def test_svd_matches_numpy():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(500, 6)).astype(np.float64)
    X[:, 3] = X[:, 0] * 2 + X[:, 1]          # rank structure
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(6)})
    svd = H2OSingularValueDecompositionEstimator(nv=4, transform="none")
    svd.train(training_frame=fr)
    _, s, vt = np.linalg.svd(X, full_matrices=False)
    np.testing.assert_allclose(svd.model.d, s[:4], rtol=2e-3)
    # right singular vectors match up to sign
    for j in range(4):
        dot = abs(np.dot(svd.model.v[:, j], vt[j]))
        assert dot > 0.99, (j, dot)
    # u columns orthonormal-ish
    U = svd.model.predict(fr).to_numpy()
    G = U.T @ U
    np.testing.assert_allclose(G, np.eye(4), atol=5e-2)


def test_svd_save_load(tmp_path):
    rng = np.random.default_rng(13)
    X = rng.normal(size=(200, 4)).astype(np.float32)
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    svd = H2OSingularValueDecompositionEstimator(nv=2)
    svd.train(training_frame=fr)
    p = h2o.save_model(svd.model, str(tmp_path), filename="svd")
    m2 = h2o.load_model(p)
    np.testing.assert_allclose(m2.d, svd.model.d, rtol=1e-6)
    u1 = svd.model.predict(fr).to_numpy()
    u2 = m2.predict(fr).to_numpy()
    np.testing.assert_allclose(u1, u2, rtol=1e-5)


def test_aggregator_reduces_and_counts():
    rng = np.random.default_rng(17)
    n = 5000
    X = rng.normal(size=(n, 3)).astype(np.float32)
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    agg = H2OAggregatorEstimator(target_num_exemplars=100,
                                 rel_tol_num_exemplars=0.5, seed=1)
    agg.train(training_frame=fr)
    m = agg.model
    k = len(m.exemplar_idx)
    assert 10 <= k < n
    assert m.counts.sum() == n
    out = m.aggregated_frame(fr)
    assert out.nrow == k
    assert out.names[-1] == "counts"


# ------------------------- metrics tables (thresholds, gains/lift, mAUC)

def test_threshold_table_and_max_criteria():
    from h2o3_tpu.models.metrics import make_binomial_metrics
    rng = np.random.default_rng(23)
    n = 3000
    y = rng.integers(0, 2, n)
    p = np.clip(0.7 * y + 0.3 * rng.uniform(size=n), 0, 1)
    mm = make_binomial_metrics(p.astype(np.float32), y.astype(np.float32))
    t = mm.thresholds_and_metric_scores
    assert t is not None
    assert len(t["threshold"]) <= 400
    for col in ("f1", "accuracy", "precision", "recall", "tps", "fps",
                "tnr", "fpr"):
        assert len(t[col]) == len(t["threshold"])
    mc = t["max_criteria_and_metric_scores"]
    assert mc["f1"]["value"] == pytest.approx(mm.max_f1, abs=1e-6)
    # accuracy at its max threshold must beat base rate
    assert mc["accuracy"]["value"] >= max(y.mean(), 1 - y.mean())


def test_gains_lift_golden():
    from h2o3_tpu.models.metrics import make_gains_lift
    # perfectly separating score → first groups capture all positives
    n = 1600
    y = np.zeros(n); y[:100] = 1
    s = np.linspace(1, 0, n)          # descending score, positives first
    gl = make_gains_lift(s, y, groups=16)
    assert gl is not None
    # 100 positives within the first 100 rows = first group of 100 rows
    assert gl["cumulative_capture_rate"][0] == pytest.approx(1.0)
    assert gl["lift"][0] == pytest.approx(16.0, rel=1e-6)
    assert gl["kolmogorov_smirnov"] == pytest.approx(1.0, abs=1e-9)
    # sklearn-checkable overall response rate
    assert gl["cumulative_response_rate"][-1] == pytest.approx(100 / n)


def test_multinomial_auc_macro():
    from h2o3_tpu.models.metrics import make_multinomial_metrics
    from sklearn.metrics import roc_auc_score
    rng = np.random.default_rng(29)
    n, K = 2000, 3
    y = rng.integers(0, K, n)
    logits = rng.normal(size=(n, K)) + 2.0 * np.eye(K)[y]
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    mm = make_multinomial_metrics(probs.astype(np.float32), y)
    assert mm.auc is not None
    sk = roc_auc_score(y, probs, multi_class="ovr", average="macro")
    assert mm.auc == pytest.approx(sk, abs=1e-3)


def test_svd_categorical_predict_roundtrip():
    rng = np.random.default_rng(31)
    n = 300
    cats = np.array(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)]
    fr = h2o.Frame.from_numpy({
        "x0": rng.normal(size=n), "c": cats, "x1": rng.normal(size=n)})
    svd = H2OSingularValueDecompositionEstimator(nv=2)
    svd.train(training_frame=fr)
    U = svd.model.predict(fr).to_numpy()   # use_all_factor_levels expansion
    assert U.shape == (n, 2)
    assert np.isfinite(U).all()


def test_isotonic_nan_feature_does_not_poison_metrics():
    rng = np.random.default_rng(33)
    x = rng.uniform(0, 1, 50)
    y = x + rng.normal(scale=0.1, size=50)
    x[3] = np.nan
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    iso = H2OIsotonicRegressionEstimator()
    iso.train(y="y", x=["x"], training_frame=fr)
    assert np.isfinite(iso.model.training_metrics.mse)


def test_anomaly_metrics_survive_save_load(tmp_path):
    from h2o3_tpu.models.isoforest import H2OIsolationForestEstimator
    rng = np.random.default_rng(37)
    X = rng.normal(size=(300, 3)).astype(np.float32)
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    iso = H2OIsolationForestEstimator(ntrees=8, max_depth=5, seed=1)
    iso.train(training_frame=fr)
    assert iso.model.training_metrics is not None
    p = h2o.save_model(iso.model, str(tmp_path), filename="iso")
    m2 = h2o.load_model(p)
    assert m2.training_metrics is not None
    assert m2.training_metrics.mean_score == pytest.approx(
        iso.model.training_metrics.mean_score)


def test_binned_auc_path_matches_sklearn_at_scale():
    # n > _EXACT_SWEEP_ROWS exercises the 2^17-bucket histogram sketch
    from sklearn.metrics import roc_auc_score
    from h2o3_tpu.models.metrics import make_binomial_metrics
    rng = np.random.default_rng(47)
    n = 300_000
    y = rng.integers(0, 2, n).astype(np.float32)
    p = np.clip(0.35 * y + rng.normal(0.3, 0.25, n), 0, 1).astype(
        np.float32)
    mm = make_binomial_metrics(p, y)
    sk = roc_auc_score(y, p)
    assert mm.auc == pytest.approx(sk, abs=2e-4)
    t = mm.thresholds_and_metric_scores
    assert len(t["threshold"]) <= 400
    assert t["gains_lift"] is not None
    assert 1.0 <= t["gains_lift"]["lift"][0] < 3.0
