"""Round-5 GLM closure: ordinal, negativebinomial, quasibinomial,
fractionalbinomial, beta_constraints, DataInfo interactions
(hex/glm/GLMModel.java:814 families, hex/DataInfo.java:16)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator


def test_negative_binomial_vs_statsmodels_shape():
    rng = np.random.default_rng(0)
    n = 4000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    mu = np.exp(0.5 + 0.8 * x1 - 0.4 * x2)
    theta = 1.5
    # NB sampling: gamma-poisson mixture with Var = mu + theta*mu^2
    lam = rng.gamma(1.0 / theta, theta * mu)
    y = rng.poisson(lam).astype(np.float64)
    fr = h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": y})
    glm = H2OGeneralizedLinearEstimator(family="negativebinomial",
                                        theta=theta, Lambda=[0.0])
    glm.train(y="y", training_frame=fr)
    co = glm.model.coef()
    assert abs(co["x1"] - 0.8) < 0.08
    assert abs(co["x2"] + 0.4) < 0.08
    assert abs(co["Intercept"] - 0.5) < 0.12


def test_quasibinomial_and_fractional():
    rng = np.random.default_rng(1)
    n = 3000
    x = rng.normal(size=n)
    p = 1 / (1 + np.exp(-(0.3 + 1.2 * x)))
    yfrac = np.clip(p + 0.05 * rng.normal(size=n), 0.0, 1.0)
    fr = h2o.Frame.from_numpy({"x": x, "y": yfrac})
    for fam in ("fractionalbinomial", "quasibinomial"):
        glm = H2OGeneralizedLinearEstimator(family=fam, Lambda=[0.0])
        glm.train(y="y", training_frame=fr)
        co = glm.model.coef()
        assert abs(co["x"] - 1.2) < 0.15, (fam, co)


def test_ordinal_proportional_odds():
    rng = np.random.default_rng(2)
    n = 6000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    eta = 1.0 * x1 - 0.5 * x2
    u = rng.logistic(size=n)
    z = eta + u
    yk = np.digitize(z, [-1.0, 1.0])      # 3 ordered classes
    lab = np.array(["low", "mid", "high"], dtype=object)[yk]
    fr = h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": lab})
    # force the label order low<mid<high via codes: from_numpy sorts
    # alphabetically (high,low,mid) — use numeric codes instead
    fr2 = h2o.Frame.from_numpy({"x1": x1, "x2": x2,
                                "y": np.array(["a_low", "b_mid", "c_high"],
                                              dtype=object)[yk]})
    glm = H2OGeneralizedLinearEstimator(family="ordinal", Lambda=[0.0])
    glm.train(y="y", training_frame=fr2)
    co = glm.model.coef()
    # proportional-odds slopes recover the data-generating coefficients
    # (sign: P(y<=k)=sigmoid(th - eta) shares eta's sign convention)
    assert abs(co["x1"] - 1.0) < 0.15, co
    assert abs(co["x2"] + 0.5) < 0.15, co
    assert co["Intercept_0"] < co["Intercept_1"]
    pred = glm.model.predict(fr2)
    assert pred.ncol == 4
    # ordered accuracy beats chance comfortably
    from h2o3_tpu.models.model_base import adapt_test_matrix
    import jax
    probs = np.asarray(jax.device_get(
        glm.model._predict_matrix(adapt_test_matrix(glm.model, fr2))))[:n]
    acc = (probs.argmax(1) == yk).mean()
    # logistic noise with unit-scale eta puts Bayes accuracy near ~0.55
    assert acc > 0.48


def test_beta_constraints_box():
    rng = np.random.default_rng(3)
    n = 3000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = 2.0 * x1 - 1.0 * x2 + 0.1 * rng.normal(size=n)
    fr = h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": y})
    glm = H2OGeneralizedLinearEstimator(
        family="gaussian", Lambda=[0.0], alpha=[0.0],
        beta_constraints=[{"names": "x1", "lower_bounds": 0.0,
                           "upper_bounds": 1.5},
                          {"names": "x2", "lower_bounds": -0.5,
                           "upper_bounds": 0.5}])
    glm.train(y="y", training_frame=fr)
    co = glm.model.coef()
    assert co["x1"] <= 1.5 + 1e-4 and co["x1"] >= 1.4   # hits the bound
    assert -0.5 - 1e-4 <= co["x2"] <= -0.45


def test_datainfo_interactions():
    rng = np.random.default_rng(4)
    n = 4000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = 0.5 * x1 + 0.3 * x2 + 1.5 * x1 * x2 + 0.1 * rng.normal(size=n)
    fr = h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": y})
    glm = H2OGeneralizedLinearEstimator(family="gaussian", Lambda=[0.0],
                                        interactions=["x1", "x2"])
    glm.train(y="y", training_frame=fr)
    co = glm.model.coef()
    assert "x1_x2" in co
    assert abs(co["x1_x2"] - 1.5) < 0.05
    # scoring path expands the same interaction
    pred = glm.model.predict(fr)
    pv = np.asarray(pred.vec("predict").to_numpy())
    assert np.corrcoef(pv, y)[0, 1] > 0.99


def test_interactions_with_categorical():
    rng = np.random.default_rng(5)
    n = 3000
    g = np.array(["a", "b"], dtype=object)[rng.integers(0, 2, n)]
    x = rng.normal(size=n)
    y = np.where(g == "b", 2.0 * x, -1.0 * x) + 0.1 * rng.normal(size=n)
    fr = h2o.Frame.from_numpy({"g": g, "x": x, "y": y})
    glm = H2OGeneralizedLinearEstimator(family="gaussian", Lambda=[0.0],
                                        interactions=["g", "x"])
    glm.train(y="y", training_frame=fr)
    co = glm.model.coef()
    inter = [k for k in co if "_" in k and k.startswith("g.")]
    assert inter, co
    pred = np.asarray(glm.model.predict(fr).vec("predict").to_numpy())
    assert np.corrcoef(pred, y)[0, 1] > 0.99
