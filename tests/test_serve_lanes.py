"""Deadline-class serving lanes (ISSUE 20).

The serving mirror of the training scheduler's priority classes:

- lane names/order are sched/core.py's, asserted;
- per-lane queue budgets: bulk sheds fast (ServeLaneShedError, 503 +
  Retry-After) beyond its fraction while interactive admission is
  untouched;
- priority pickup: an interactive request admitted BEHIND a bulk
  backlog boards the next batch;
- the starvation bar: under a saturating bulk flood, interactive p99
  stays within 2x its no-load band (the
  ``serve.interactive_p99_under_bulk_ms`` acceptance gate, in-process);
- per-lane stats (requests/shed/percentiles) in the stats snapshot;
- REST: ``X-H2O3-Lane`` tags the request, an unknown lane is a 400
  (never a silent ride on the interactive class).
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import dkv, serve
from h2o3_tpu.serve import lanes
from h2o3_tpu.serve.batcher import (MicroBatcher, ServeLaneShedError,
                                    ServeOverloadedError)
from h2o3_tpu.serve.stats import ServeStats


# ----------------------------------------------------------- lane model

def test_lane_order_mirrors_scheduler_priorities():
    from h2o3_tpu.sched.core import PRIORITY_LEVELS
    assert lanes.LANE_LEVELS == PRIORITY_LEVELS
    assert list(lanes.LANES) == sorted(lanes.LANES,
                                       key=lanes.LANE_LEVELS.get)
    assert lanes.DEFAULT_LANE == "interactive"


def test_normalize_defaults_and_rejects_unknown():
    assert lanes.normalize(None) == "interactive"
    assert lanes.normalize("") == "interactive"
    assert lanes.normalize(" Bulk ") == "bulk"
    with pytest.raises(ValueError, match="unknown lane"):
        lanes.normalize("express")


def test_budget_fractions_and_env_override(monkeypatch):
    assert lanes.budget_fraction("interactive") == 1.0
    assert lanes.budget_fraction("bulk") == 0.5
    assert lanes.budget_fraction("background") == 0.25
    monkeypatch.setenv("H2O3_SERVE_LANE_BULK", "0.8")
    assert lanes.budget_fraction("bulk") == 0.8
    monkeypatch.setenv("H2O3_SERVE_LANE_BULK", "7.0")   # out of range
    assert lanes.budget_fraction("bulk") == 0.5         # falls back
    monkeypatch.setenv("H2O3_SERVE_LANE_BULK", "junk")  # ignored
    assert lanes.budget_fraction("bulk") == 0.5


def test_default_lane_from_path():
    assert lanes.default_for_path(
        "/3/Predictions/models/m/rows") == "interactive"
    assert lanes.default_for_path("/3/Frames/f1") == "bulk"
    assert lanes.default_for_path("/3/DownloadDataset") == "bulk"


# ------------------------------------------------------ batcher budgets

def _lane_batcher(gate=None, stats=None, order=None, sleep_s=0.0, **kw):
    def encode(rows, pad):
        X = np.zeros((pad, 1), np.float32)
        X[: len(rows), 0] = [r["x"] for r in rows]
        return X

    def dispatch(X, n):
        if gate is not None:
            gate.wait()
        if order is not None:
            order.append([float(v) for v in X[:n, 0]])
        if sleep_s:
            time.sleep(sleep_s)
        return X[:, 0] * 2.0

    def decode(scores, n):
        vals = np.asarray(scores)[:n]

        class _Decoded:
            def rows(self, off, k):
                return [{"value": float(v)} for v in vals[off:off + k]]

            def columns(self, off, k):
                return {"value": [float(v) for v in vals[off:off + k]]}

        return _Decoded()

    kw.setdefault("max_batch", 2)
    kw.setdefault("max_delay_ms", 1.0)
    return MicroBatcher(encode=encode, dispatch=dispatch, decode=decode,
                        stats=stats or ServeStats(),
                        bucket_for=lambda n: kw["max_batch"], **kw)


def test_bulk_sheds_at_its_budget_interactive_still_admitted():
    """queue_limit=4 → bulk cap 2 rows. A blocked device + 2 queued
    bulk rows: the next bulk row sheds (503 subclass, Retry-After,
    counted per-lane) while an interactive row is still admitted into
    the remaining whole-queue headroom."""
    gate = threading.Event()
    stats = ServeStats()
    mb = _lane_batcher(gate, stats=stats, max_batch=2, queue_limit=4)
    results = {}

    def bg(tag, rows, lane):
        try:
            results[tag] = mb.submit(rows, timeout_ms=10_000, lane=lane)
        except Exception as e:  # noqa: BLE001
            results[tag] = e

    try:
        t0 = threading.Thread(target=bg, args=(
            "warm", [{"x": 0.0}, {"x": 0.0}], None))
        t0.start()
        for _ in range(400):       # batch 0 picked, stuck at the gate
            if mb.pending_rows == 0 and stats.queue_depth >= 2:
                break
            time.sleep(0.005)
        tb = threading.Thread(target=bg, args=(
            "bulk0", [{"x": 1.0}, {"x": 2.0}], "bulk"))
        tb.start()
        for _ in range(400):       # bulk lane now AT its 2-row cap
            if mb.pending_rows == 2:
                break
            time.sleep(0.005)
        with pytest.raises(ServeLaneShedError) as ei:
            mb.submit([{"x": 3.0}], timeout_ms=1_000, lane="bulk")
        assert ei.value.retry_after_s > 0
        assert ei.value.http_status == 503
        assert isinstance(ei.value, ServeOverloadedError)
        # background's budget (0.25 → 1 row) is separate from bulk's
        with pytest.raises(ServeLaneShedError):
            mb.submit([{"x": 4.0}, {"x": 5.0}], timeout_ms=1_000,
                      lane="background")
        # interactive rides the whole-queue limit, untouched by lanes
        ti = threading.Thread(target=bg, args=(
            "inter", [{"x": 6.0}], "interactive"))
        ti.start()
        for _ in range(400):
            if mb.pending_rows == 3:
                break
            time.sleep(0.005)
        assert mb.pending_rows == 3    # the interactive row queued
        gate.set()
        for t in (t0, tb, ti):
            t.join(5)
        assert [r["value"] for r in results["inter"]] == [12.0]
        assert [r["value"] for r in results["bulk0"]] == [2.0, 4.0]
        snap = stats.snapshot()["lanes"]
        assert snap["bulk"]["shed"] == 1
        assert snap["background"]["shed"] == 1
        assert snap["bulk"]["requests"] == 1
        assert snap["interactive"]["requests"] == 2
    finally:
        gate.set()
        mb.close()


def test_interactive_admitted_behind_bulk_boards_next_batch():
    """Priority pickup: with a bulk request queued FIRST, a later
    interactive request still dispatches ahead of it — the serving
    mirror of the scheduler's priority dispatch."""
    gate = threading.Event()
    order = []
    mb = _lane_batcher(gate, order=order, max_batch=2, queue_limit=8)
    results = {}

    def bg(tag, rows, lane):
        try:
            results[tag] = mb.submit(rows, timeout_ms=10_000, lane=lane)
        except Exception as e:  # noqa: BLE001
            results[tag] = e

    try:
        t0 = threading.Thread(target=bg, args=(
            "warm", [{"x": 1.0}, {"x": 1.0}], None))
        t0.start()
        for _ in range(400):
            if mb.pending_rows == 0 and mb.stats.queue_depth >= 2:
                break
            time.sleep(0.005)
        tb = threading.Thread(target=bg, args=(
            "bulk", [{"x": 10.0}, {"x": 10.0}], "bulk"))
        tb.start()
        for _ in range(400):
            if mb.pending_rows == 2:
                break
            time.sleep(0.005)
        ti = threading.Thread(target=bg, args=(
            "inter", [{"x": 20.0}, {"x": 20.0}], "interactive"))
        ti.start()
        for _ in range(400):
            if mb.pending_rows == 4:
                break
            time.sleep(0.005)
        gate.set()
        for t in (t0, tb, ti):
            t.join(5)
        assert order[0] == [1.0, 1.0]
        # the interactive batch dispatched BEFORE the earlier-queued bulk
        assert order[1] == [20.0, 20.0]
        assert order[2] == [10.0, 10.0]
    finally:
        gate.set()
        mb.close()


def test_interactive_p99_holds_under_saturating_bulk_flood():
    """The acceptance bar, in-process: a saturating bulk flood (sheds
    expected and allowed) must not push interactive p99 past 2x its
    no-load band. Uses a simulated 2ms device so the bound reflects
    queueing policy, not host jitter."""
    def run_round(flood):
        stats = ServeStats()
        mb = _lane_batcher(stats=stats, sleep_s=0.002, max_batch=8,
                           queue_limit=16, max_delay_ms=1.0)
        stop = threading.Event()
        shed = [0]

        def bulk_hammer():
            while not stop.is_set():
                try:
                    mb.submit([{"x": 1.0}] * 8, timeout_ms=2_000,
                              lane="bulk")
                except ServeLaneShedError:
                    shed[0] += 1
                    # honor the shed verdict minimally — a zero-sleep
                    # spin here measures GIL thrash, not lane isolation
                    time.sleep(0.001)
                except Exception:  # noqa: BLE001 — flood is best-effort
                    pass

        threads = [threading.Thread(target=bulk_hammer)
                   for _ in range(4 if flood else 0)]
        for t in threads:
            t.start()
        try:
            time.sleep(0.05)
            for _ in range(120):
                mb.submit([{"x": 2.0}], timeout_ms=10_000,
                          lane="interactive")
        finally:
            stop.set()
            for t in threads:
                t.join(5)
            mb.close()
        (p99,) = stats.lane_percentiles_ms("interactive", [99])
        return p99, shed[0]

    solo_p99, _ = run_round(flood=False)
    under_p99, sheds = run_round(flood=True)
    assert solo_p99 is not None and under_p99 is not None
    assert sheds > 0, "the flood never saturated the bulk budget"
    # 2x the solo band (with a floor absorbing sub-ms timer jitter on
    # loaded CI hosts — the solo band itself is only a few ms)
    assert under_p99 <= max(2.0 * solo_p99, solo_p99 + 25.0), \
        f"interactive p99 {under_p99:.1f}ms vs solo {solo_p99:.1f}ms"


def test_lane_percentiles_reservoir():
    stats = ServeStats()
    for i in range(100):
        stats.record_request(float(i + 1), 1, lane="bulk")
    p50, p99 = stats.lane_percentiles_ms("bulk", [50, 99])
    assert 45 <= p50 <= 55
    assert 95 <= p99 <= 100
    assert stats.lane_percentiles_ms("background", [50]) == [None]
    lanes_snap = stats.snapshot()["lanes"]
    assert lanes_snap["bulk"]["requests"] == 100
    assert lanes_snap["bulk"]["p50_ms"] == p50


# ----------------------------------------------------------------- REST

def _train_tiny():
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    rng = np.random.default_rng(11)
    a = rng.normal(size=600).astype(np.float32)
    b = rng.uniform(-2, 2, size=600).astype(np.float32)
    y = rng.random(600) < 1 / (1 + np.exp(-(a - b)))
    fr = h2o.Frame.from_numpy({
        "a": a, "b": b, "cls": np.where(y, "YES", "NO")})
    g = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=5,
                                     min_rows=1.0)
    g.train(y="cls", training_frame=fr)
    g.model.key = "serve_lanes_gbm"
    dkv.put(g.model.key, "model", g.model)
    return fr, g.model


def test_rest_lane_header_tags_and_unknown_lane_is_400():
    from h2o3_tpu.api.server import H2OApiServer
    fr, model = _train_tiny()
    serve.deploy(model.key, max_delay_ms=1.0, max_batch=64,
                 buckets=[1, 8, 64])
    s = H2OApiServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{s.port}"
        a = fr.vec("a").to_numpy()
        b = fr.vec("b").to_numpy()
        rows = [{"a": float(a[i]), "b": float(b[i])} for i in range(3)]

        def post(lane):
            req = urllib.request.Request(
                f"{base}/3/Predictions/models/{model.key}/rows",
                data=json.dumps({"rows": rows}).encode(), method="POST",
                headers={"Content-Type": "application/json",
                         "X-H2O3-Lane": lane})
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read().decode())

        out = post("bulk")
        assert len(out["predictions"]) == 3
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("express")
        assert ei.value.code == 400
        assert "unknown lane" in ei.value.read().decode()
        # the bulk request landed in the bulk lane's stats
        lanes_snap = serve.deployment(model.key).stats \
            .snapshot()["lanes"]
        assert lanes_snap["bulk"]["requests"] >= 1
    finally:
        try:
            s.stop()
        except Exception:
            pass
        serve.undeploy(model.key)
