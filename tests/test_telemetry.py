"""Unified telemetry subsystem tests (ISSUE 4).

Covers: span nesting + cross-thread parent handoff, Prometheus
exposition validity, the /metrics + /3/Telemetry + /3/Timeline REST
round-trip (with one span from each of ingest, train and serve in a
single process — the acceptance smoke), production compile-counter
parity with the tests/_compile_counter.py harness on a warm retrain
(both must say 0), the serve-path stage_ms ≈ request-latency contract,
and the registry overhead guard (counter increments under a fixed ns
budget; a disabled registry short-circuits to no-ops).
"""
import json
import os
import re
import statistics
import threading
import time
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import telemetry
from h2o3_tpu.telemetry.registry import Registry

from _compile_counter import count_compiles


@pytest.fixture(autouse=True)
def _telemetry_on():
    """Every test in this module assumes the registry is live; restore
    whatever a test toggled."""
    was = telemetry.enabled()
    telemetry.set_enabled(True)
    telemetry.install()
    yield
    telemetry.set_enabled(was)


# ------------------------------------------------------------ registry

def test_counter_gauge_histogram_basics():
    reg = Registry(enabled=True)
    c = reg.counter("c_total", {"k": "v"}, help="h")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    # same (name, labels) → same instance; different labels → different
    assert reg.counter("c_total", {"k": "v"}) is c
    assert reg.counter("c_total", {"k": "w"}) is not c
    g = reg.gauge("g")
    g.set(7)
    g.inc(-2)
    g.set_max(3)     # below current → no change
    assert g.value == 5.0
    g.set_max(11)
    assert g.value == 11.0
    h = reg.histogram("h_seconds", bounds=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 3 and abs(h.sum - 5.55) < 1e-9
    cum = h.cumulative()
    assert cum[0] == (0.1, 1) and cum[1] == (1.0, 2)
    assert cum[2][1] == 3 and cum[2][0] == float("inf")
    # kind collision is an error, not silent corruption
    with pytest.raises(TypeError):
        reg.gauge("c_total", {"k": "v"})


def test_registry_value_and_snapshot():
    reg = Registry(enabled=True)
    reg.counter("a_total").inc(4)
    assert reg.value("a_total") == 4.0
    assert reg.value("missing") == 0.0
    snap = reg.snapshot()
    assert snap["a_total"] == 4.0


def test_scrape_time_collector_views():
    reg = Registry(enabled=True)
    reg.add_collector(lambda: [{"name": "view_gauge", "value": 42.0}])
    names = {s["name"]: s for s in reg.samples()}
    assert names["view_gauge"]["value"] == 42.0
    # a broken collector must not sink the scrape
    def boom():
        raise RuntimeError("x")
    reg.add_collector(boom)
    assert any(s["name"] == "view_gauge" for s in reg.samples())


# ------------------------------------------------------------ spans

def test_span_nesting_implicit_parent():
    with telemetry.span("t.outer") as outer:
        assert telemetry.current_span() is outer
        with telemetry.span("t.inner") as inner:
            assert inner.parent_id == outer.span_id
        assert telemetry.current_span() is outer
    assert telemetry.current_span() is None
    assert outer.duration_s is not None and inner.duration_s is not None
    assert inner.parent_id == outer.span_id


def test_span_stack_survives_exceptions():
    with pytest.raises(ValueError):
        with telemetry.span("t.exc_outer"):
            with telemetry.span("t.exc_inner"):
                raise ValueError("boom")
    assert telemetry.current_span() is None


def test_span_cross_thread_parent_handoff():
    """The batcher pattern: a root opened on one thread, children
    recorded on another against the explicit handle."""
    root = telemetry.open_span("t.handoff_root")
    seen = {}

    def worker():
        with telemetry.span("t.handoff_child", parent=root) as ch:
            seen["child"] = ch
        seen["recorded"] = telemetry.record_span(
            "t.handoff_recorded", time.time(), 0.001, parent=root)

    th = threading.Thread(target=worker)
    th.start()
    th.join()
    root.finish()
    assert seen["child"].parent_id == root.span_id
    assert seen["recorded"].parent_id == root.span_id
    assert seen["child"].thread_id != root.thread_id
    # the finished spans all landed in the ring and the histogram
    names = {s.name for s in telemetry.finished_spans()}
    assert {"t.handoff_root", "t.handoff_child",
            "t.handoff_recorded"} <= names
    stages = telemetry.stage_seconds("t.handoff")
    assert stages["t.handoff_child"]["count"] >= 1


def test_root_spans_feed_flow_timeline():
    from h2o3_tpu.log import timeline_events
    with telemetry.span("t.timeline_root", tag="x"):
        pass
    kinds = [e["kind"] for e in timeline_events()]
    assert "t.timeline_root" in kinds


# ------------------------------------------ Prometheus exposition format

_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'   # value may escape \" \\ \n
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                     # metric name
    rf"(\{{{_LABEL}(,{_LABEL})*\}})?"                # optional label set
    r" (NaN|[-+]?Inf|[-+]?[0-9.eE+-]+)$")            # value


def test_prometheus_text_is_valid_exposition():
    telemetry.counter("expo_total", {"model": 'we"ird\nname'}).inc()
    telemetry.histogram("expo_seconds", bounds=(0.5, 5.0)).observe(1.0)
    text = telemetry.prometheus_text()
    assert text.endswith("\n")
    seen_types = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE"):
            _, _, name, kind = ln.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram", "untyped")
            assert name not in seen_types, "duplicate TYPE header"
            seen_types[name] = kind
            continue
        if ln.startswith("#"):
            assert ln.startswith("# HELP"), f"bad comment line: {ln!r}"
            continue
        assert _METRIC_LINE.match(ln), f"invalid sample line: {ln!r}"
    # histogram series contract: cumulative buckets end at _count
    hist_lines = [l for l in text.splitlines()
                  if l.startswith("expo_seconds")]
    buckets = [int(l.rsplit(" ", 1)[1]) for l in hist_lines
               if l.startswith("expo_seconds_bucket")]
    assert buckets == sorted(buckets), "buckets must be cumulative"
    count = int([l for l in hist_lines
                 if l.startswith("expo_seconds_count")][0].rsplit(" ", 1)[1])
    assert buckets[-1] == count


# ----------------------------------------------------- disabled = no-op

def test_disabled_registry_short_circuits():
    c = telemetry.counter("disabled_probe_total")
    c.inc()
    telemetry.set_enabled(False)
    try:
        c.inc(100)
        assert c.value == 1.0, "disabled counter must not mutate"
        with telemetry.span("t.disabled") as sp:
            assert sp is None
        assert telemetry.record_span("t.disabled", time.time(), 1.0) is None
        assert telemetry.open_span("t.disabled") is None
    finally:
        telemetry.set_enabled(True)
    c.inc()
    assert c.value == 2.0


def test_counter_overhead_ns_budget():
    """The CI overhead guard: one increment must stay cheap enough for
    the serve hot path, and a disabled registry must be a checked no-op.
    Budgets are far above the expected cost (~0.2-0.5µs) to absorb CI
    noise while still catching an accidental O(registry) regression."""
    c = telemetry.counter("bench_probe_total")
    N = 20_000

    def per_inc_ns():
        t0 = time.perf_counter_ns()
        for _ in range(N):
            c.inc()
        return (time.perf_counter_ns() - t0) / N

    enabled_ns = statistics.median(per_inc_ns() for _ in range(5))
    assert enabled_ns < 10_000, f"enabled inc too slow: {enabled_ns:.0f}ns"
    telemetry.set_enabled(False)
    try:
        before = c.value
        disabled_ns = statistics.median(per_inc_ns() for _ in range(5))
        assert c.value == before, "disabled inc mutated state"
        assert disabled_ns < 5_000, \
            f"disabled inc not a no-op: {disabled_ns:.0f}ns"
    finally:
        telemetry.set_enabled(True)


def test_serve_stats_survive_disabled_telemetry():
    """With H2O3_TELEMETRY=0 the serve stats surface must keep working
    (private always-on registry) while nothing reaches the export."""
    from h2o3_tpu.serve.stats import ServeStats
    telemetry.set_enabled(False)
    try:
        st = ServeStats(model="dark_model")
        st.record_request(1.5, 2)
        st.record_batch(2, 8, {"encode": 0.1, "queue": 0.2,
                               "device": 0.3, "decode": 0.1})
        snap = st.snapshot()
        assert snap["requests"] == 1 and snap["rows"] == 2
        assert snap["p50_ms"] == 1.5
        assert abs(sum(snap["stage_ms"].values()) - 0.7) < 1e-6
    finally:
        telemetry.set_enabled(True)
    assert "dark_model" not in telemetry.prometheus_text()


# --------------------------------------------------- pipeline coverage

def _tiny_frame(n=600, f=4, seed=3):
    import h2o3_tpu as h2o
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    cols = {f"f{i}": X[:, i] for i in range(f)}
    cols["label"] = np.where(X[:, 0] > 0, "Y", "N")
    return h2o.Frame.from_numpy(cols), X


def _train_gbm(fr, **kw):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    gbm = H2OGradientBoostingEstimator(
        ntrees=3, max_depth=3, seed=1, min_rows=1.0,
        score_tree_interval=0, stopping_rounds=0, **kw)
    gbm.train(y="label", training_frame=fr)
    return gbm.model


def test_warm_retrain_compile_parity_with_harness():
    """The PRODUCTION compile counter must agree with the
    tests/_compile_counter.py harness on a warm retrain: both say 0 —
    the same guarantee the test shim proved, now watchable in prod."""
    fr, _ = _tiny_frame(seed=5)
    _train_gbm(fr)                       # cold: compiles
    before = telemetry.registry().value("h2o3_xla_compiles_total")
    harness = []
    with count_compiles(harness):
        _train_gbm(fr)                   # warm: must not compile
    prod = telemetry.registry().value(
        "h2o3_xla_compiles_total") - before
    assert len(harness) == int(prod), \
        f"harness={len(harness)} production={prod} disagree"
    assert prod == 0, f"warm retrain compiled {prod} modules"


def test_serve_stage_ms_sums_to_request_latency():
    """Sequential single-row requests: the per-stage attribution must
    account for (most of) the measured request latency — the stages and
    the latency are recorded independently, so a large gap means a
    stage went missing."""
    from h2o3_tpu import serve
    fr, X = _tiny_frame(seed=7)
    model = _train_gbm(fr)
    model.key = "tel_serve_gbm"
    dep = serve.deploy(model.key, model=model, max_batch=8,
                       max_delay_ms=0.5)
    try:
        rows = [{f"f{i}": float(X[j, i]) for i in range(4)}
                for j in range(16)]
        dep.predict_rows(rows[:2])       # warm the lazies
        compiles0 = telemetry.registry().value("h2o3_xla_compiles_total")
        n = 30
        for j in range(n):
            dep.predict_rows([rows[j % 16]])
        # warm serve path: 0 compiles through the PRODUCTION counter
        assert telemetry.registry().value(
            "h2o3_xla_compiles_total") == compiles0
        snap = dep.stats.snapshot()
        assert snap["requests"] >= n
        # total stage time vs total request latency over the same run
        lat_total_ms = snap["p50_ms"] * snap["requests"]  # lower bound-ish
        stage_total_ms = sum(snap["stage_ms"].values())
        # stages are per-batch, requests per-client; sequential 1-row
        # traffic makes them 1:1 — require the sums to be the same
        # order: stage sum within [30%, 170%] of p50*n
        assert 0.3 * lat_total_ms < stage_total_ms < 1.7 * lat_total_ms, \
            (snap["stage_ms"], snap["p50_ms"], snap["requests"])
        # and the serve spans exist with per-batch counts
        stages = telemetry.stage_seconds("serve.")
        for name in ("serve.encode", "serve.device", "serve.decode",
                     "serve.queue", "serve.batch", "serve.request"):
            assert stages.get(name, {}).get("count", 0) >= 1, name
    finally:
        serve.undeploy(model.key)


def test_rest_round_trip_covers_all_pipelines(tmp_path):
    """The acceptance smoke: one process drives ingest → train → serve,
    then /metrics parses as Prometheus text, /3/Telemetry returns the
    JSON snapshot, and /3/Timeline?format=trace yields Chrome-trace
    JSON with at least one span from EACH pipeline."""
    from h2o3_tpu import serve
    from h2o3_tpu.api import server as apisrv
    from h2o3_tpu.ingest.parse import parse, parse_setup

    # ingest: a real parse through the streaming pipeline
    csv = tmp_path / "tel.csv"
    rng = np.random.default_rng(0)
    with open(csv, "w") as f:
        f.write("a,b,label\n")
        for i in range(400):
            f.write(f"{rng.normal():.4f},{rng.normal():.4f},"
                    f"{'Y' if rng.random() > 0.5 else 'N'}\n")
    fr = parse([str(csv)], parse_setup([str(csv)]))

    # train + serve
    model = _train_gbm(fr)
    model.key = "tel_rest_gbm"
    dep = serve.deploy(model.key, model=model, max_batch=8,
                       max_delay_ms=0.5)
    srv = apisrv.start_server(port=0)
    try:
        dep.predict_rows([{"a": 0.1, "b": -0.2}])
        base = f"http://127.0.0.1:{srv.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=30) as r:
                return r.read(), r.headers.get("Content-Type", "")

        text, ct = get("/metrics")
        assert ct.startswith("text/plain")
        body = text.decode()
        assert "h2o3_xla_compiles_total" in body
        assert "h2o3_h2d_bytes_total" in body
        assert 'h2o3_serve_requests_total{model="tel_rest_gbm"}' in body
        for ln in body.splitlines():
            if ln and not ln.startswith("#"):
                assert _METRIC_LINE.match(ln), ln

        tele, _ = get("/3/Telemetry")
        snap = json.loads(tele)
        assert snap["enabled"] is True
        assert snap["h2d_bytes"] > 0
        assert any(k.startswith("ingest.") for k in snap["stages"])
        assert any(k.startswith("train.") for k in snap["stages"])
        assert any(k.startswith("serve.") for k in snap["stages"])

        trace, ct = get("/3/Timeline?format=trace")
        assert ct.startswith("application/json")
        tr = json.loads(trace)
        evs = tr["traceEvents"]
        cats = {e["cat"] for e in evs}
        assert {"ingest", "train", "serve"} <= cats, cats
        for e in evs:                        # Perfetto-loadable shape
            assert e["ph"] == "X"
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float))
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        # parent links ride in args and resolve within the export
        ids = {e["args"]["span_id"] for e in evs}
        child = [e for e in evs if e["args"].get("parent_id")]
        assert child, "expected at least one nested span"

        # H2O-shaped default timeline (nodeidx-less, EventV3 fields)
        tl, _ = get("/3/Timeline")
        tld = json.loads(tl)
        assert tld["__meta"]["schema_name"] == "TimelineV3"
        assert "self" in tld and "now" in tld
        assert tld["events"], "timeline must show pipeline activity"
        for e in tld["events"][:5]:
            for k in ("date", "nanos", "who", "event", "bytes"):
                assert k in e, (k, e)
        kinds = {e["event"] for e in tld["events"]}
        assert "ingest.parse" in kinds
        assert any(k.startswith("train.") or k in ("train_start",
                                                   "train_done")
                   for k in kinds)
    finally:
        srv.stop()
        serve.undeploy(model.key)
