"""Frame/Vec/rollups tests — mirror the reference's fvec unit tests
(h2o-core/src/test/java/water/fvec/, e.g. VecTest, RollupStatsTest) on the
8-virtual-device CPU mesh."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.vec import Vec


def test_mesh_has_8_devices():
    import jax
    assert len(jax.devices()) == 8
    mesh = h2o.current_mesh()
    assert mesh.shape["data"] * mesh.shape["model"] == 8


def test_vec_roundtrip_numeric():
    x = np.array([1.0, 2.5, np.nan, 4.0, -7.0])
    v = Vec.from_numpy(x)
    assert v.nrow == 5
    out = v.to_numpy()
    np.testing.assert_allclose(out, x.astype(np.float32), equal_nan=True)


def test_vec_sharded_over_data_axis():
    v = Vec.from_numpy(np.arange(1000.0))
    shardings = {d for d in v.data.sharding.device_set}
    assert len(shardings) == h2o.current_mesh().shape["data"]


def test_rollups_match_numpy():
    rng = np.random.default_rng(0)
    x = rng.normal(3.0, 2.0, size=10_000).astype(np.float32)
    x[::17] = np.nan
    v = Vec.from_numpy(x)
    r = v.rollups()
    valid = x[~np.isnan(x)]
    assert r["na_count"] == int(np.isnan(x).sum())
    assert r["rows"] == 10_000
    np.testing.assert_allclose(r["mean"], valid.mean(), rtol=1e-5)
    np.testing.assert_allclose(r["sigma"], valid.std(ddof=1), rtol=1e-4)
    np.testing.assert_allclose(r["min"], valid.min(), rtol=1e-6)
    np.testing.assert_allclose(r["max"], valid.max(), rtol=1e-6)
    assert r["nz_count"] == int((valid != 0).sum())


def test_rollups_int_and_const():
    v = Vec.from_numpy(np.array([5, 5, 5, 5]))
    assert v.type == "int"
    assert v.rollups()["is_const"]
    assert v.mean() == 5.0


def test_enum_vec_from_strings():
    v = Vec.from_numpy(np.array(["b", "a", "b", "", "c"], dtype=object))
    assert v.type == "enum"
    assert v.domain == ("a", "b", "c")
    assert v.na_count() == 1
    codes = v.to_numpy()
    np.testing.assert_array_equal(codes, [1, 0, 1, -1, 2])
    dec = v.to_strings()
    assert list(dec) == ["b", "a", "b", None, "c"]


def test_percentiles():
    x = np.arange(1, 10_001, dtype=np.float32)
    v = Vec.from_numpy(x)
    p = v.percentiles(probs=(0.25, 0.5, 0.75))
    np.testing.assert_allclose(p, np.quantile(x, [0.25, 0.5, 0.75]), rtol=1e-3)


def test_frame_basic_ops():
    fr = h2o.Frame.from_numpy({"a": np.arange(100.0), "b": np.arange(100.0) * 2})
    assert fr.nrow == 100 and fr.ncol == 2
    assert fr.names == ["a", "b"]
    sub = fr["b"]
    assert sub.ncol == 1
    fr["c"] = Vec.from_numpy(np.ones(100))
    assert fr.ncol == 3
    d = fr.drop("a")
    assert d.names == ["b", "c"]


def test_frame_rows_and_split():
    fr = h2o.Frame.from_numpy({"a": np.arange(1000.0)})
    sub = fr.rows(np.arange(1000) % 3 == 0)
    assert sub.nrow == 334
    np.testing.assert_allclose(sub.vec("a").to_numpy()[:4], [0, 3, 6, 9])
    tr, te = fr.split_frame([0.8], seed=42)
    assert tr.nrow + te.nrow == 1000
    assert 700 < tr.nrow < 900


def test_map_reduce_combinator():
    """MRTask parity: distributed sum via explicit shard_map + psum."""
    from h2o3_tpu.parallel import map_reduce
    import jax.numpy as jnp
    v = Vec.from_numpy(np.arange(1024.0))
    total = map_reduce(lambda x: jnp.sum(x), v.data)
    assert float(total) == float(np.arange(1024.0).sum())


def test_map_cols_combinator():
    from h2o3_tpu.parallel import map_cols
    v = Vec.from_numpy(np.arange(64.0))
    out = map_cols(lambda x: x * 2.0, v.data)
    np.testing.assert_allclose(np.asarray(out)[:64], np.arange(64.0) * 2)


def test_wide_int_exact_roundtrip():
    """IDs beyond float32 mantissa (2^24) must round-trip exactly."""
    x = np.array([16777217, 16777219, 1, 2], dtype=np.int64)
    v = Vec.from_numpy(x)
    assert v.type == "int"
    np.testing.assert_array_equal(v.to_numpy(), x.astype(np.float64))


def test_explicit_vtype_not_overridden():
    v = Vec.from_numpy(np.array([1, 2, 3]), vtype="real")
    assert v.type == "real"


def test_string_vec_clear_errors_and_as_matrix():
    import h2o3_tpu as h2o
    sv = Vec.from_numpy(np.array(["a", "b"], dtype=object), vtype="string")
    with pytest.raises(ValueError, match="string"):
        sv.as_float()
    fr = h2o.Frame(["s", "x"], [sv, Vec.from_numpy(np.array([1.0, 2.0]))])
    m = np.asarray(fr.as_matrix())
    assert np.isnan(m[:2, 0]).all()
    np.testing.assert_allclose(m[:2, 1], [1.0, 2.0])
