"""Logging + profiling subsystem tests (VERDICT r3 task #10)."""
import numpy as np

import h2o3_tpu as h2o
from h2o3_tpu.log import Profile, buffered_lines, info
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator


def test_profile_phases_accumulate():
    import time
    p = Profile()
    with p.phase("a"):
        time.sleep(0.01)
    with p.phase("b"):
        time.sleep(0.01)
    with p.phase("a"):
        time.sleep(0.01)
    d = p.to_dict()
    assert list(d) == ["a", "b"]
    assert d["a"] > d["b"] > 0
    assert "total=" in p.summary()


def test_training_attaches_profile_and_logs():
    rng = np.random.default_rng(0)
    n = 500
    fr = h2o.Frame.from_numpy({
        "x": rng.normal(size=n).astype(np.float32),
        "y": rng.normal(size=n).astype(np.float32)})
    gbm = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=1)
    gbm.train(y="y", training_frame=fr)
    prof = gbm.model.output["profile"]
    assert "spec" in prof and "train" in prof
    assert prof["train"] > 0
    lines = buffered_lines()
    assert any("gbm train done" in l for l in lines)


def test_logs_endpoint():
    import json
    import urllib.request
    from h2o3_tpu.api import start_server
    srv = start_server(port=0)
    try:
        info("logs endpoint smoke line")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/3/Logs") as r:
            out = json.loads(r.read().decode())
        assert "logs endpoint smoke line" in out["log"]
    finally:
        srv.stop()
