"""Grid search + StackedEnsemble tests (VERDICT r3 task #9 done-criteria:
grid over GBM depth/lr with leaderboard-ordered results; SE beats its
best base model on a golden task)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import dkv
from h2o3_tpu.models.drf import H2ORandomForestEstimator
from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.grid import H2OGridSearch


def _task(n=2500, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    logit = (1.5 * X[:, 0] - X[:, 1] + 0.8 * X[:, 2] * X[:, 3]
             + 0.4 * np.sin(2 * X[:, 4]))
    yv = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = np.array(["n", "p"], dtype=object)[yv]
    return h2o.Frame.from_numpy(cols)


def test_grid_cartesian_leaderboard():
    fr = _task(n=1500)
    grid = H2OGridSearch(
        H2OGradientBoostingEstimator(ntrees=15, seed=1),
        hyper_params={"max_depth": [2, 4], "learn_rate": [0.05, 0.3]})
    grid.train(y="y", training_frame=fr)
    assert len(grid.models) == 4
    grid.get_grid(sort_by="auc")
    aucs = [m.training_metrics.auc for m in grid.models]
    assert aucs == sorted(aucs, reverse=True)
    lb = grid.leaderboard("auc")
    assert lb[0]["auc"] >= lb[-1]["auc"]
    assert "max_depth" in lb[0] and "learn_rate" in lb[0]
    # models addressable via the store
    assert dkv.get(grid.model_ids[0], "model") is grid.models[0]


def test_grid_random_discrete_budget():
    fr = _task(n=1000, seed=3)
    grid = H2OGridSearch(
        H2OGradientBoostingEstimator(ntrees=5, seed=1),
        hyper_params={"max_depth": [2, 3, 4, 5], "learn_rate": [0.1, 0.2,
                                                               0.3]},
        search_criteria={"strategy": "RandomDiscrete", "max_models": 3,
                         "seed": 42})
    grid.train(y="y", training_frame=fr)
    assert len(grid.models) == 3


def test_grid_survives_failures():
    fr = _task(n=600, seed=5)
    grid = H2OGridSearch(
        H2OGradientBoostingEstimator(ntrees=3, seed=1),
        hyper_params={"max_depth": [3], "distribution": ["bernoulli",
                                                         "not_a_dist"]})
    grid.train(y="y", training_frame=fr)
    assert len(grid.models) == 1
    assert len(grid.failures) == 1


def test_stacked_ensemble_beats_best_base():
    fr = _task(n=3000, seed=7)
    gbm = H2OGradientBoostingEstimator(ntrees=25, max_depth=3, nfolds=3,
                                       seed=1)
    gbm.train(y="y", training_frame=fr)
    drf = H2ORandomForestEstimator(ntrees=25, max_depth=6, nfolds=3, seed=1)
    drf.train(y="y", training_frame=fr)
    se = H2OStackedEnsembleEstimator(base_models=[gbm.model, drf.model])
    se.train(y="y", training_frame=fr)
    se_auc = se.model.training_metrics.auc
    base_best = max(gbm.model.cross_validation_metrics.auc,
                    drf.model.cross_validation_metrics.auc)
    # SE should at least match the best base's CV AUC on this task
    assert se_auc >= base_best - 0.01, (se_auc, base_best)
    # scoring chain works on a fresh frame
    te = _task(n=500, seed=11)
    pred = se.model.predict(te)
    assert pred.names == ["predict", "pn", "pp"]
    probs = pred.vec("pp").to_numpy()
    assert np.all((probs >= 0) & (probs <= 1))


def test_stacked_ensemble_requires_cv():
    fr = _task(n=600, seed=9)
    g1 = H2OGradientBoostingEstimator(ntrees=3, seed=1)
    g1.train(y="y", training_frame=fr)
    g2 = H2OGradientBoostingEstimator(ntrees=3, seed=2)
    g2.train(y="y", training_frame=fr)
    se = H2OStackedEnsembleEstimator(base_models=[g1.model, g2.model])
    with pytest.raises(RuntimeError, match="holdout"):
        se.train(y="y", training_frame=fr)
