"""Shared XLA compile-count harness (PR 2): context manager collecting
one entry per backend compile via jax.monitoring — used by the
zero-recompile guards in tests/test_train_perf.py (warm train path) and
tests/test_serve.py (warm serve path)."""
import contextlib


@contextlib.contextmanager
def count_compiles(out: list):
    """Collect one entry per XLA backend compile (jax.monitoring)."""
    import jax
    from jax._src import monitoring as _monitoring

    active = [True]

    def listener(key, _dur, **_kw):
        if active[0] and key.endswith("backend_compile_duration"):
            out.append(key)

    jax.monitoring.register_event_duration_secs_listener(listener)
    try:
        yield out
    finally:
        active[0] = False       # neutralize even if unregistering fails
        unreg = getattr(_monitoring,
                        "_unregister_event_duration_listener_by_callback",
                        None)
        if unreg is not None:   # private API — may vanish in a jax bump
            unreg(listener)
