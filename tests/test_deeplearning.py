"""DeepLearning MLP tests — classification/regression quality, dropout,
optimizer variants, save/load (reference: hex/deeplearning test style)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator


def _blobs(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0, 0], [4, 4], [-4, 4]])
    y = rng.integers(0, 3, n)
    X = centers[y] + rng.normal(size=(n, 2))
    labels = np.array(["a", "b", "c"], dtype=object)[y]
    return h2o.Frame.from_numpy({"x1": X[:, 0], "x2": X[:, 1],
                                 "y": labels}), y


def test_dl_multinomial_blobs():
    fr, y = _blobs()
    dl = H2ODeepLearningEstimator(hidden=[32, 32], epochs=20, seed=1,
                                  mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    m = dl.model.training_metrics
    assert m.error < 0.05, m.to_dict()
    pf = dl.model.predict(fr)
    assert pf.names == ["predict", "pa", "pb", "pc"]
    probs = np.stack([pf.vec(c).to_numpy() for c in ("pa", "pb", "pc")], 1)
    np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-5)


def test_dl_nonlinear_regression_beats_linear():
    rng = np.random.default_rng(3)
    n = 4000
    x1 = rng.uniform(-2, 2, n).astype(np.float32)
    x2 = rng.uniform(-2, 2, n).astype(np.float32)
    y = (np.sin(2 * x1) * 2 + x2 ** 2 + 0.05 * rng.normal(size=n)).astype(np.float32)
    fr = h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": y})
    dl = H2ODeepLearningEstimator(hidden=[64, 64], epochs=40, seed=1,
                                  mini_batch_size=256)
    dl.train(y="y", training_frame=fr)
    r2 = dl.model.training_metrics.r2
    assert r2 > 0.95, r2   # a linear fit tops out ~0.55 here


def test_dl_binomial_auc_and_validation():
    rng = np.random.default_rng(5)
    n = 4000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    logit = 2 * X[:, 0] - X[:, 1] + X[:, 2] * X[:, 3]
    yv = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.array(["n", "p"], dtype=object)[yv]
    fr = h2o.Frame.from_numpy(cols)
    tr, va = fr.split_frame([0.8], seed=1)
    dl = H2ODeepLearningEstimator(hidden=[32, 32], epochs=25, seed=2,
                                  mini_batch_size=128)
    dl.train(y="y", training_frame=tr, validation_frame=va)
    assert dl.model.training_metrics.auc > 0.85
    assert dl.model.validation_metrics.auc > 0.8


def test_dl_momentum_sgd_path():
    """adaptive_rate=False exercises the momentum/annealing optimizer."""
    fr, y = _blobs(n=1500, seed=7)
    dl = H2ODeepLearningEstimator(hidden=[32], epochs=30, seed=1,
                                  adaptive_rate=False, rate=0.05,
                                  momentum_start=0.5, momentum_stable=0.9,
                                  momentum_ramp=1e4, mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    assert dl.model.training_metrics.error < 0.05


def test_dl_dropout_trains():
    fr, y = _blobs(n=1500, seed=9)
    dl = H2ODeepLearningEstimator(hidden=[64], epochs=25, seed=1,
                                  activation="rectifier_with_dropout",
                                  input_dropout_ratio=0.1,
                                  hidden_dropout_ratios=[0.3],
                                  mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    assert dl.model.training_metrics.error < 0.1


def test_dl_enum_features_and_save_load(tmp_path):
    rng = np.random.default_rng(11)
    n = 2000
    lv = np.array(["u", "v", "w"])
    cat = rng.integers(0, 3, n)
    x = rng.normal(size=n).astype(np.float32)
    x[rng.random(n) < 0.1] = np.nan           # mean-imputed
    y = (np.nan_to_num(x) + np.array([0.0, 2.0, -2.0])[cat]
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = h2o.Frame.from_numpy({"c": lv[cat], "x": x, "y": y})
    dl = H2ODeepLearningEstimator(hidden=[32, 32], epochs=30, seed=1,
                                  mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    assert dl.model.training_metrics.r2 > 0.9
    pred = dl.model.predict(fr).vec("predict").to_numpy()
    p = h2o.save_model(dl.model, str(tmp_path), filename="dl")
    m2 = h2o.load_model(p)
    pred2 = m2.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(pred, pred2, rtol=1e-6)


def test_dl_early_stopping():
    fr, y = _blobs(n=2000, seed=13)
    dl = H2ODeepLearningEstimator(hidden=[32], epochs=100, seed=1,
                                  stopping_rounds=2, stopping_tolerance=0.05,
                                  mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    assert dl.model.output["epochs_trained"] < 100


def test_dl_small_frame_smaller_than_batch():
    """Frames smaller than mini_batch_size must train (batch clamps)."""
    rng = np.random.default_rng(17)
    n = 100
    x = rng.normal(size=n).astype(np.float32)
    y = (2 * x + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = h2o.Frame.from_numpy({"x": x, "y": y})
    dl = H2ODeepLearningEstimator(hidden=[8], epochs=120, seed=1,
                                  mini_batch_size=256)
    dl.train(y="y", training_frame=fr)
    assert dl.model.training_metrics.r2 > 0.8


def test_dl_checkpoint_continue_training():
    """checkpoint (hex/Model.java:487): the prior DL model's weights
    seed continued training; more epochs from the checkpoint must not
    be worse than the checkpoint itself."""
    import h2o3_tpu as h2o
    rng = np.random.default_rng(12)
    n = 2000
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    y = np.sin(x1) + 0.5 * x2 + 0.05 * rng.normal(size=n)
    fr = h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": y})
    m1 = H2ODeepLearningEstimator(hidden=[16], epochs=3, seed=1,
                                  mini_batch_size=64)
    m1.train(y="y", training_frame=fr)
    mse1 = m1.model.training_metrics.mse
    m2 = H2ODeepLearningEstimator(hidden=[16], epochs=10, seed=1,
                                  mini_batch_size=64,
                                  checkpoint=m1.model)
    m2.train(y="y", training_frame=fr)
    mse2 = m2.model.training_metrics.mse
    assert mse2 < mse1 * 1.05, (mse1, mse2)
    # topology mismatch rejected
    bad = H2ODeepLearningEstimator(hidden=[8], epochs=2,
                                   checkpoint=m1.model)
    with pytest.raises((ValueError, RuntimeError), match="hidden"):
        bad.train(y="y", training_frame=fr)


def test_dl_initial_weights_and_biases():
    """initial_weights/initial_biases seed specific layers; with rate 0
    and 0 epochs of movement the seeded weights are reproduced."""
    import h2o3_tpu as h2o
    rng = np.random.default_rng(13)
    n = 512
    x = rng.normal(size=(n, 3))
    y = x @ np.array([1.0, -2.0, 0.5]) + 0.01 * rng.normal(size=n)
    fr = h2o.Frame.from_numpy({"a": x[:, 0], "b": x[:, 1],
                               "c": x[:, 2], "y": y})
    W0 = rng.normal(size=(3, 4)).astype(np.float32)
    b1 = np.ones(1, np.float32)
    est = H2ODeepLearningEstimator(
        hidden=[4], epochs=2, seed=2, standardize=False,
        initial_weights=[W0, None], initial_biases=[None, b1])
    est.train(y="y", training_frame=fr)
    assert est.model.training_metrics is not None
    # wrong shape rejected
    bad = H2ODeepLearningEstimator(
        hidden=[4], epochs=1, initial_weights=[np.zeros((2, 2)), None])
    with pytest.raises((ValueError, RuntimeError), match="shape"):
        bad.train(y="y", training_frame=fr)
    # wrong layer count rejected
    bad2 = H2ODeepLearningEstimator(
        hidden=[4], epochs=1, initial_weights=[W0])
    with pytest.raises((ValueError, RuntimeError), match="per layer"):
        bad2.train(y="y", training_frame=fr)
