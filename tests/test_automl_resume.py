"""AutoML fault tolerance + exploitation step family
(hex/faulttolerance/Recovery.java; ai/h2o/automl/AutoML.java:403-457)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.automl import EXPLOITATION_STEPS, H2OAutoML

pytestmark = pytest.mark.slow  # heavy tier: driver runs with --runslow

def _frame(n=600, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x1 - x2)))).astype(int)
    return h2o.Frame.from_numpy({
        "x1": x1, "x2": x2,
        "y": np.array(["n", "p"], dtype=object)[y]})


def test_automl_resume_kill_restart(tmp_path):
    fr = _frame()
    a1 = H2OAutoML(max_models=2, nfolds=0, seed=7, project_name="amlrec",
                   recovery_dir=str(tmp_path))
    a1.train(y="y", training_frame=fr)
    assert len(a1.models) >= 2
    done_keys = sorted(m.key for m in a1.models)
    # 'crash': a brand-new AutoML object with the same project/recovery
    a2 = H2OAutoML(max_models=4, nfolds=0, seed=7, project_name="amlrec",
                   recovery_dir=str(tmp_path))
    a2.train(y="y", training_frame=fr)
    resumed = [e for e in a2.event_log if e["stage"] == "resume"
               and "reloaded" in e["message"]]
    assert resumed, a2.event_log
    keys2 = sorted(m.key for m in a2.models)
    for k in done_keys:
        assert k in keys2          # earlier work reused, not retrained
    assert len(a2.models) >= 3
    lb = a2.leaderboard
    assert len(lb) >= 3


def test_automl_resume_ignores_changed_config(tmp_path):
    fr = _frame(seed=2)
    a1 = H2OAutoML(max_models=1, nfolds=0, seed=3, project_name="amlcfg",
                   recovery_dir=str(tmp_path))
    a1.train(y="y", training_frame=fr)
    a2 = H2OAutoML(max_models=1, nfolds=0, seed=99, project_name="amlcfg",
                   recovery_dir=str(tmp_path))   # different seed
    a2.train(y="y", training_frame=fr)
    assert any("config changed" in e["message"] for e in a2.event_log
               if e["stage"] == "resume")


def test_exploitation_step_family_is_data():
    assert set(EXPLOITATION_STEPS) >= {"gbm", "xgboost", "drf", "glm"}
    # providers derive refinement steps from a leader's params
    class FakeLeader:
        params = {"ntrees": 10, "learn_rate": 0.2, "max_depth": 4}
        output = {"automl_family": "gbm"}
    steps = EXPLOITATION_STEPS["gbm"](FakeLeader(), None)
    assert steps[0]["params"]["ntrees"] == 20
    assert steps[0]["params"]["learn_rate"] == 0.1


def test_exploitation_runs_per_family():
    fr = _frame(seed=4)
    aml = H2OAutoML(max_models=8, max_runtime_secs=120, nfolds=0, seed=5,
                    project_name="amlexp", exploitation_ratio=0.3,
                    modeling_plan=["gbm", "glm"],
                    include_algos=["GBM", "GLM"])
    aml.train(y="y", training_frame=fr)
    steps = [m.output.get("automl_step") for m in aml.models]
    assert any("lr_annealing" in (s or "") or "lambda_refine" in (s or "")
               for s in steps), steps
