"""Parallel model building: grid `parallelism` knob + concurrent
CV-main (hex/grid/GridSearch.java parallelism,
hex/ModelBuilder.java:884 cv+main overlap)."""
import time

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.grid import H2OGridSearch

# this file exists to exercise REAL build-thread concurrency: lift the
# suite-wide clamp (conftest.py H2O3_MAX_BUILD_THREADS=1) — inside a
# fixture, NOT at module level: pytest imports every test module at
# collection time, so a module-level env write would leak the un-clamp
# into the whole suite.
import os as _os

pytestmark = pytest.mark.slow  # heavy tier: driver runs with --runslow


@pytest.fixture(autouse=True)
def _unclamped_build_threads(monkeypatch):
    # 2, not unlimited: the concurrent code path (thread overlap, result
    # ordering, budget accounting) is fully exercised with two workers,
    # while 4+ threads dispatching jitted steps on the 1-core CPU
    # backend reproduce the XLA abort() this cap exists to avoid
    monkeypatch.setitem(_os.environ, "H2O3_MAX_BUILD_THREADS", "2")

def _frame(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = (rng.random(n) < 1 / (1 + np.exp(-(x1 - x2)))).astype(int)
    cls = np.array(["a", "b"], dtype=object)[y]
    return h2o.Frame.from_numpy({"x1": x1, "x2": x2, "y": cls})


def test_parallel_grid_matches_sequential():
    fr = _frame()
    hyper = {"ntrees": [2, 3], "max_depth": [2, 3]}
    seq = H2OGridSearch(H2OGradientBoostingEstimator(seed=7), hyper,
                        grid_id="gseq")
    t0 = time.time()
    seq.train(y="y", training_frame=fr)
    t_seq = time.time() - t0
    par = H2OGridSearch(H2OGradientBoostingEstimator(seed=7), hyper,
                        grid_id="gpar", parallelism=4)
    t0 = time.time()
    par.train(y="y", training_frame=fr)
    t_par = time.time() - t0
    assert len(par.models) == len(seq.models) == 4
    # identical points produce identical metrics regardless of ordering
    seq_auc = sorted(round(m.training_metrics.auc, 6) for m in seq.models)
    par_auc = sorted(round(m.training_metrics.auc, 6) for m in par.models)
    assert seq_auc == par_auc
    # models keep deterministic index-ordered keys
    assert [m.key for m in par.models] == [f"gpar_model_{i}"
                                           for i in range(4)]
    print(f"grid wall: sequential {t_seq:.1f}s, parallel {t_par:.1f}s")


def test_parallel_grid_max_models_budget():
    fr = _frame(seed=2)
    par = H2OGridSearch(H2OGradientBoostingEstimator(seed=1),
                        {"ntrees": [1, 2, 3, 4, 5, 6]},
                        search_criteria={"max_models": 2},
                        parallelism=3)
    par.train(y="y", training_frame=fr)
    # in-flight slack allows at most parallelism-1 extras
    assert 2 <= len(par.models) <= 4


def test_concurrent_cv_main():
    fr = _frame(seed=3)
    est = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=5,
                                       nfolds=3, parallelism=3)
    est.train(y="y", training_frame=fr)
    m = est.model
    assert m.cross_validation_metrics is not None
    assert len(m.output["cross_validation_models"]) == 3
    # same pooled-holdout metrics as the sequential CV path
    est2 = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=5,
                                        nfolds=3)
    est2.train(y="y", training_frame=fr)
    assert abs(m.cross_validation_metrics.auc
               - est2.model.cross_validation_metrics.auc) < 1e-6


# moved from test_platform.py: under the suite-wide thread
# clamp this parity test would silently compare sequential to
# sequential; here the autouse fixture lifts the clamp so the
# CONCURRENT fold path is the one compared
def test_parallel_cv_matches_sequential():
    rng = np.random.default_rng(2)
    n = 4000
    X = rng.normal(size=(n, 3))
    y = X[:, 0] * 2 + rng.normal(scale=0.3, size=n)
    fr = h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(3)}, "y": y})
    seq = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1,
                                       nfolds=3, fold_assignment="modulo")
    seq.train(y="y", training_frame=fr)
    par = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1,
                                       nfolds=3, fold_assignment="modulo",
                                       parallelism=3)
    par.train(y="y", training_frame=fr)
    assert seq.model.cross_validation_metrics.mse == pytest.approx(
        par.model.cross_validation_metrics.mse, rel=1e-5)
