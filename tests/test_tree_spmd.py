"""Distributed tree build parity: the tp×dp-sharded grow_tree_spmd must
produce the identical tree to single-device grow_tree (the reference's
"same answer on 1 or N nodes" invariant — DL MNIST README table trains
identically on 1-8 nodes; trees are exactly deterministic here)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from h2o3_tpu.models.tree import TreeConfig, grow_tree, grow_tree_spmd

pytestmark = pytest.mark.slow  # heavy tier: driver runs with --runslow

@pytest.fixture
def tree_problem():
    rng = np.random.default_rng(3)
    rows, F, nbins = 512, 8, 16
    codes = jnp.asarray(rng.integers(0, nbins, (rows, F)), jnp.int32)
    x = np.asarray(codes)
    margin = np.zeros(rows)
    logit = (x[:, 0] > 8) * 2.0 + (x[:, 3] > 4) * 1.0 - 1.5
    y = (rng.random(rows) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    p = 0.5
    g = jnp.asarray(p - y)
    h = jnp.full(rows, p * (1 - p), jnp.float32)
    w = jnp.ones(rows, jnp.float32)
    cfg = TreeConfig(max_depth=4, n_bins=nbins, n_features=F, min_rows=5.0,
                     hist_method="scatter")
    return codes, g, h, w, cfg


def test_spmd_tree_matches_single_device(tree_problem):
    codes, g, h, w, cfg = tree_problem
    F = codes.shape[1]
    col_mask = jnp.ones(F, bool)
    ref_tree, ref_nid = jax.jit(
        lambda *a: grow_tree(*a, cfg, col_mask))(codes, g, h, w)

    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("data", "model"))
    f = jax.jit(jax.shard_map(
        lambda c, gg, hh, ww, cm: grow_tree_spmd(c, gg, hh, ww, cfg, cm),
        mesh=mesh,
        in_specs=(P("data", "model"), P("data"), P("data"), P("data"), P("model")),
        out_specs=({"feat": P(), "split_bin": P(), "na_left": P(),
                    "is_split": P(), "value": P()}, P("data")),
        check_vma=False))
    codes_s = jax.device_put(codes, NamedSharding(mesh, P("data", "model")))
    spmd_tree, spmd_nid = f(codes_s, g, h, w, col_mask)

    np.testing.assert_array_equal(np.asarray(ref_tree["feat"]),
                                  np.asarray(spmd_tree["feat"]))
    np.testing.assert_array_equal(np.asarray(ref_tree["split_bin"]),
                                  np.asarray(spmd_tree["split_bin"]))
    np.testing.assert_array_equal(np.asarray(ref_tree["is_split"]),
                                  np.asarray(spmd_tree["is_split"]))
    np.testing.assert_allclose(np.asarray(ref_tree["value"]),
                               np.asarray(spmd_tree["value"]), rtol=2e-4,
                               atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ref_nid), np.asarray(spmd_nid))


def test_sharded_histogram_matches_local(tree_problem):
    codes, g, h, w, cfg = tree_problem
    from h2o3_tpu.ops.histogram import build_histograms, build_histograms_sharded
    nid = jnp.asarray(np.random.default_rng(0).integers(0, 4, codes.shape[0]),
                      jnp.int32)
    ghw = jnp.stack([g, h, w])
    local = build_histograms(codes, nid, ghw, 4, cfg.n_bins + 1, "scatter")
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    shard = build_histograms_sharded(codes, nid, ghw, 4, cfg.n_bins + 1,
                                     mesh, "scatter")
    for a, b in zip(local, shard):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_matmul_and_scatter_kernels_agree(tree_problem):
    codes, g, h, w, cfg = tree_problem
    from h2o3_tpu.ops.histogram import build_histograms
    nid = jnp.asarray(np.random.default_rng(1).integers(0, 8, codes.shape[0]),
                      jnp.int32)
    ghw = jnp.stack([g, h, w])
    a = build_histograms(codes, nid, ghw, 8, cfg.n_bins + 1, "scatter")
    b = build_histograms(codes, nid, ghw, 8, cfg.n_bins + 1, "matmul")
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-4,
                                   atol=1e-4)
