"""Router tier + client affinity + multi-format failover (ISSUE 20).

Covers the tentpole's contracts:

- 2-router gossip convergence: snapshot/absorb reuses the member
  table's epoch/incarnation fencing verbatim — agents keep their
  ORIGINAL incarnation across routers, stale gossip cannot roll a
  record back, higher incarnations win;
- warm boot: a bounced router answers its FIRST routed request (no
  empty-table 503 window, zero compiles) after pulling a peer's
  snapshot — or, with no peers, the disk snapshot;
- client affinity parity: the client-side ring picks the SAME home as
  the router for 10k keys, across a churn event;
- columnar and streamed scoring ride the same single-failover path as
  the row shape, with bit-parity to direct scoring;
- the REST tier surface: GET /3/Fleet/ring (epoch-stamped),
  GET /3/Fleet/snapshot, POST /3/Fleet/gossip (two-way convergence);
- agent-side beat failover: a dead first seed rotates to the next
  router without a rejoin.
"""
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import dkv, fleet, serve
from h2o3_tpu.fleet.membership import MemberTable
from h2o3_tpu.fleet.router import (ConsistentHashRing, FleetRouter,
                                   RouterTier)
from h2o3_tpu.fleet.affinity import AffinityClient, RingView
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

from _compile_counter import count_compiles  # noqa: E402 — shared harness

HB = 0.15


@pytest.fixture(autouse=True, scope="module")
def _fleet_cleanup():
    yield
    serve.shutdown_all()
    fleet.reset()


def _train_frame(n=1200, seed=3):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n).astype(np.float32)
    b = rng.uniform(-2, 2, size=n).astype(np.float32)
    logit = a - b * 0.8
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    return h2o.Frame.from_numpy({
        "a": a, "b": b, "cls": np.where(y, "YES", "NO")})


@pytest.fixture(scope="module")
def gbm_model():
    fr = _train_frame()
    g = H2OGradientBoostingEstimator(ntrees=6, max_depth=3, seed=2,
                                     min_rows=1.0)
    g.train(y="cls", training_frame=fr)
    g.model.key = "fleet_tier_gbm"
    dkv.put(g.model.key, "model", g.model)
    return fr, g.model


def _rows(fr, k=4):
    a = fr.vec("a").to_numpy()
    b = fr.vec("b").to_numpy()
    return [{"a": float(a[i]), "b": float(b[i])} for i in range(k)]


def _join_beating(table, mid, base_url, deployments=(), load=0.0):
    m = table.join(mid, base_url, heartbeat_s=30.0,
                   deployments=deployments)
    table.heartbeat(mid, m.incarnation, routable=True, load=load,
                    deployments=deployments)
    return m


# ------------------------------------------------- gossip convergence

def test_two_router_gossip_convergence_and_epoch_fencing():
    a, b = MemberTable(), MemberTable()
    m1 = _join_beating(a, "r1@h", "http://127.0.0.1:1", ("m",), 0.2)
    m2 = _join_beating(a, "r2@h", "http://127.0.0.1:2", ("m",), 0.5)
    # router B absorbs A's snapshot: full convergence, incarnations
    # PRESERVED (the agents' beat tokens must keep working against B)
    n = b.absorb(a.snapshot(), source="routerA")
    assert n == 2
    assert {m.member_id for m in b.live_members()} == {"r1@h", "r2@h"}
    assert b.get("r1@h").incarnation == m1.incarnation
    assert b.get("r2@h").incarnation == m2.incarnation
    assert b.get("r2@h").load == 0.5
    assert b.epoch >= a.epoch
    # an agent failing its beat stream over to B beats with its
    # ORIGINAL token — accepted, no rejoin
    b.heartbeat("r1@h", m1.incarnation, load=0.9)
    assert b.get("r1@h").load == 0.9
    # stale gossip (lower incarnation) is fenced like a stale beat
    stale = a.snapshot()
    stale["members"] = [dict(r, incarnation=0, load=0.0)
                        for r in stale["members"]]
    assert b.absorb(stale, source="routerA") == 0
    assert b.get("r1@h").load == 0.9
    # a rejoin on A (higher incarnation) WINS on B via gossip
    m1b = a.join("r1@h", "http://127.0.0.1:1", routable=True)
    assert b.absorb(a.snapshot(), source="routerA") >= 1
    assert b.get("r1@h").incarnation == m1b.incarnation
    # ... and the old life's token is now fenced on BOTH routers
    with pytest.raises(fleet.StaleEpochError):
        b.heartbeat("r1@h", m1.incarnation)


def test_absorb_keeps_freshest_beat_and_skips_terminal_states():
    a, b = MemberTable(), MemberTable()
    m = _join_beating(a, "f1@h", "http://127.0.0.1:1", (), 0.1)
    b.absorb(a.snapshot(), source="a")
    # B hears a LOCAL beat after the snapshot was cut: the local
    # record is fresher, so re-absorbing the older snapshot changes
    # nothing (gossip can't roll back load). Freshness is compared by
    # record AGE (local clocks, no sync) — age the snapshot explicitly
    # so the verdict doesn't race the suite's scheduling jitter
    snap = a.snapshot()
    for rec in snap["members"]:
        rec["age_s"] = 5.0   # snapshot has been in gossip flight a while
    b.heartbeat("f1@h", m.incarnation, load=0.7)
    assert b.absorb(snap, source="a") == 0
    assert b.get("f1@h").load == 0.7
    # terminal states never absorb
    assert b.absorb({"epoch": 99, "members": [
        {"member_id": "z@h", "incarnation": 5, "age_s": 0.0,
         "state": "evicted", "base_url": "http://x"}]}) == 0
    assert b.get("z@h") is None


# ----------------------------------------------------- warm boot

def test_bounced_router_warm_boots_from_peer_and_answers_first_request(
        gbm_model):
    """The ISSUE 20 bugfix regression: a restarted router used to come
    up with an empty member table and 503 until replica beats rebuilt
    it. Warm-booted from a peer, its FIRST routed request routes (no
    shed window) and compiles zero XLA modules."""
    from h2o3_tpu.api.server import H2OApiServer
    fr, model = gbm_model
    serve.deploy(model.key, max_delay_ms=1.0, max_batch=64,
                 buckets=[1, 8, 64])
    fleet.reset()
    s1 = H2OApiServer(port=0).start()
    try:
        peer_url = f"http://127.0.0.1:{s1.port}"
        # the surviving router (the process singleton behind s1's REST
        # surface) holds one live replica
        r_live = fleet.router()
        _join_beating(r_live.table, "wb1@h", peer_url, (model.key,))
        # the "bounced" router: fresh process state — empty table
        bounced = FleetRouter(table=MemberTable())
        assert bounced.table.members() == []
        with pytest.raises(fleet.FleetUnavailableError):
            bounced.route(model.key)     # the pre-fix 503 window
        tier = RouterTier(bounced, "http://127.0.0.1:59999",
                          peers=[peer_url])
        src = tier.warm_boot()
        assert src == f"peer:{peer_url}"
        # first routed request: routes immediately, zero compiles
        compiles = []
        with count_compiles(compiles):
            out = bounced.predict_rows(model.key, _rows(fr, 4),
                                       key="bounce")
        assert out["predictions"]
        assert out["_fleet"]["member"] == "wb1@h"
        assert compiles == [], \
            f"first routed request after warm boot compiled {compiles}"
    finally:
        try:
            s1.stop()
        except Exception:
            pass
        fleet.reset()
        serve.undeploy(model.key)


def test_warm_boot_disk_fallback_when_no_peer_answers(monkeypatch,
                                                      tmp_path):
    monkeypatch.setenv("H2O3_RECOVERY_DIR", str(tmp_path))
    a = FleetRouter(table=MemberTable())
    _join_beating(a.table, "d1@h", "http://127.0.0.1:1", ("m",))
    tier_a = RouterTier(a, "http://127.0.0.1:59998", peers=[])
    tier_a.gossip_once()        # persists the snapshot to disk
    assert os.path.exists(tmp_path / "fleet_router_snapshot.json")
    # the bounced router finds no peer — the disk snapshot still
    # spares it the empty-table window
    b = FleetRouter(table=MemberTable())
    tier_b = RouterTier(b, "http://127.0.0.1:59997",
                        peers=["http://127.0.0.1:9"])
    assert tier_b.warm_boot() == "disk"
    assert {m.member_id for m in b.table.live_members()} == {"d1@h"}


# --------------------------------------------------- client affinity

def test_client_affinity_parity_10k_keys_across_churn():
    """The client-side ring picks the SAME home as the router for
    every key — before and after a churn event (the ring endpoint +
    RingView reuse ConsistentHashRing, so parity is bit-exact)."""
    t = MemberTable()
    for i in range(4):
        _join_beating(t, f"aff{i}@h", f"http://127.0.0.1:{5000 + i}")
    r = FleetRouter(table=t)
    snap = r.ring_snapshot()
    view = RingView(snap["epoch"], snap["points"], snap["members"])
    keys = [f"model|row-{i}" for i in range(10_000)]
    for k in keys:
        member, _ = r.route("model", key=k.split("|", 1)[1])
        assert member.member_id == view.home(k)
    # churn: one member leaves; a NEW view re-converges, and only the
    # departed member's key share re-homed
    t.leave("aff2@h")
    snap2 = r.ring_snapshot()
    assert snap2["epoch"] > snap["epoch"]
    view2 = RingView(snap2["epoch"], snap2["points"], snap2["members"])
    moved = [k for k in keys if view2.home(k) != view.home(k)]
    assert all(view.home(k) == "aff2@h" for k in moved)
    for k in keys[:2000]:
        member, _ = r.route("model", key=k.split("|", 1)[1])
        assert member.member_id == view2.home(k)


def test_affinity_routing_key_matches_router_spelling():
    assert AffinityClient.routing_key("m", "k1") == "m|k1"
    assert AffinityClient.routing_key("m", None) == "m"


# ------------------------------------- multi-format failover + parity

def test_columnar_and_stream_ride_the_failover_path():
    """Before ISSUE 20 only the row shape failed over — columnar and
    streamed scoring died with the replica. All three formats now take
    the same single-failover path, with the format forwarded."""
    t = MemberTable()
    for i in range(2):
        _join_beating(t, f"ff{i}@h", f"http://127.0.0.1:{i}", ("m",))
    for fmt in ("columnar", "stream"):
        calls = []

        def dispatch(member, model, rows, deadline, fmt=None, lane=None):
            calls.append((member.member_id, fmt))
            if len(calls) == 1:
                raise ConnectionRefusedError("connection refused")
            return {"answered": fmt}

        r = FleetRouter(table=t, dispatch=dispatch)
        out = r.predict_rows("m", [{}], key="k", fmt=fmt)
        assert out["_fleet"]["failover"] is True
        assert len({c[0] for c in calls}) == 2      # two replicas
        assert all(c[1] == fmt for c in calls)      # format forwarded
        assert out["answered"] == fmt


def test_default_dispatch_signature_stays_4_positional():
    """Pre-existing injected dispatches take exactly (member, model,
    rows, deadline) — the default rows/interactive path must not pass
    extra kwargs at them."""
    t = MemberTable()
    _join_beating(t, "sig@h", "http://127.0.0.1:1", ("m",))

    def old_dispatch(member, model, rows, deadline):
        return {"ok": True}

    r = FleetRouter(table=t, dispatch=old_dispatch)
    assert r.predict_rows("m", [{}], key="k")["ok"] is True


def test_rest_columnar_and_stream_parity_with_direct(gbm_model):
    """Routed columnar == direct columnar; routed NDJSON stream decodes
    to the same per-row values as direct rows — bit-parity through the
    proxy hop for every format."""
    from h2o3_tpu.api.server import H2OApiServer
    fr, model = gbm_model
    serve.deploy(model.key, max_delay_ms=1.0, max_batch=64,
                 buckets=[1, 8, 64])
    fleet.reset()
    s1 = H2OApiServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{s1.port}"
        r = fleet.router()
        _join_beating(r.table, "fmt1@h", base, (model.key,))
        rows = _rows(fr, 4)

        def post(path, payload, raw=False):
            req = urllib.request.Request(
                f"{base}{path}", data=json.dumps(payload).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = resp.read().decode()
                return (body, resp.headers) if raw \
                    else (json.loads(body), resp.headers)

        direct_rows = serve.predict_rows(model.key, rows)
        direct_cols = serve.predict_columnar(model.key, rows)
        out, hdrs = post(f"/3/Fleet/models/{model.key}/rows",
                         {"rows": rows, "format": "columnar"})
        assert out["columns"] == json.loads(
            json.dumps(direct_cols, default=str))
        # the routed response carries the fleet epoch (the affinity
        # client's staleness signal)
        assert int(hdrs["X-H2O3-Fleet-Epoch"]) == r.table.epoch
        nd, hdrs = post(f"/3/Fleet/models/{model.key}/rows",
                        {"rows": rows, "format": "stream"}, raw=True)
        streamed = [json.loads(ln) for ln in nd.splitlines() if ln]
        assert [p["label"] for p in streamed] == \
            [p["label"] for p in direct_rows]
        assert [p["classProbabilities"] for p in streamed] == \
            [p["classProbabilities"] for p in direct_rows]
        # direct stream (replica endpoint) is byte-identical to routed
        nd2, _ = post(f"/3/Predictions/models/{model.key}/rows"
                      f"?format=stream", {"rows": rows}, raw=True)
        assert nd2 == nd
    finally:
        try:
            s1.stop()
        except Exception:
            pass
        fleet.reset()
        serve.undeploy(model.key)


# ----------------------------------------------------- REST tier plane

def test_rest_ring_snapshot_and_gossip_endpoints(gbm_model):
    from h2o3_tpu.api.server import H2OApiServer
    fr, model = gbm_model
    serve.deploy(model.key, max_delay_ms=1.0, max_batch=64,
                 buckets=[1, 8, 64])
    fleet.reset()
    s1 = H2OApiServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{s1.port}"
        r = fleet.router()
        _join_beating(r.table, "ring1@h", base, (model.key,))

        def get(path):
            with urllib.request.urlopen(f"{base}{path}", timeout=10) as x:
                return json.loads(x.read().decode())

        ring = get("/3/Fleet/ring")
        assert ring["epoch"] == r.table.epoch
        assert ring["points"] >= 1
        assert [m["member_id"] for m in ring["members"]] == ["ring1@h"]
        snap = get("/3/Fleet/snapshot")
        assert snap["snapshot"]["members"][0]["member_id"] == "ring1@h"
        assert model.key in [d["model"]
                             for d in snap["registry"]["deployments"]]
        # gossip: a peer pushes ITS view, gets ours back — one
        # exchange converges both sides
        peer = MemberTable()
        _join_beating(peer, "peer1@h", "http://127.0.0.1:7777", ("m",))
        req = urllib.request.Request(
            f"{base}/3/Fleet/gossip",
            data=json.dumps({"source": "http://127.0.0.1:59996",
                             "snapshot": peer.snapshot()}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as x:
            out = json.loads(x.read().decode())
        assert out["absorbed"] == 1
        assert r.table.get("peer1@h") is not None
        assert "ring1@h" in [m["member_id"]
                             for m in out["snapshot"]["members"]]
    finally:
        try:
            s1.stop()
        except Exception:
            pass
        fleet.reset()
        serve.undeploy(model.key)


# ----------------------------------------------- agent-side failover

def test_agent_join_rotates_past_dead_seed(monkeypatch, gbm_model):
    from h2o3_tpu.api.server import H2OApiServer
    from h2o3_tpu.fleet.agent import FleetAgent
    fr, model = gbm_model
    fleet.reset()
    s1 = H2OApiServer(port=0).start()
    try:
        live = f"127.0.0.1:{s1.port}"
        # first seed answers nothing: join must rotate to the live one
        monkeypatch.setenv("H2O3_FLEET_SEEDS", f"127.0.0.1:9,{live}")
        agent = FleetAgent("http://127.0.0.1:59995",
                           member_id="rot1@h", prewarm=False)
        out = agent.join()
        assert out["incarnation"] >= 1
        assert agent.router_url() == f"http://{live}"
        assert fleet.router().table.get("rot1@h") is not None
    finally:
        try:
            s1.stop()
        except Exception:
            pass
        fleet.reset()
