"""Performance accounting plane (ISSUE 11).

Five layers:

1. **Cost capture**: ``cost_analysis`` flops agree with the analytic
   count for a known matmul, the scan ``scale=`` contract multiplies a
   loop body correctly, and the per-key cache never re-lowers.
2. **Roofline math**: MFU lands in (0, 1] under explicit peak
   overrides, ``peak_source``/``informational`` provenance is honest,
   and the regime classification follows the ridge point.
3. **Train/serve wiring**: GBM/DRF trains carry
   ``model.output["perf"]`` roofline points computed from executable
   costs x measured loop time; warm retrains report IDENTICAL
   executable costs without re-lowering; deployments expose a ``perf``
   block; ``GET /3/Telemetry/perf`` serves the summary.
4. **Cluster merge**: the new ``h2o3_achieved_*`` counters sum across
   process snapshots and the ``h2o3_mfu`` gauge gets process labels —
   the PR-8 plane carries the accounting with zero special cases.
5. **The bench-trajectory gate** (tools/perf_gate.py): passes the
   checked-in BENCH_r* history (the tier-1 CI wiring), fails a
   synthetic regressed round, tolerates in-band noise, and skips
   cleanly below two rounds.

Plus the standing contract: ``H2O3_TELEMETRY=0`` keeps every producer
a checked ns-budget no-op.
"""
import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np
import pytest

import h2o3_tpu as h2o  # noqa: F401 — installs the shard_map shim
from h2o3_tpu import telemetry
from h2o3_tpu.telemetry import costmodel
from h2o3_tpu.telemetry import snapshot as telesnap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import perf_gate  # noqa: E402

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _frame(n=6000, F=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    logit = X[:, 0] - 0.5 * X[:, 1]
    cols = {f"f{i}": X[:, i] for i in range(F)}
    cols["y"] = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)),
                         "a", "b")
    return h2o.Frame.from_numpy(cols)


def _train(fr, **kw):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    params = dict(ntrees=6, max_depth=3, seed=2, nbins=16,
                  score_tree_interval=0, stopping_rounds=0)
    params.update(kw)
    g = H2OGradientBoostingEstimator(**params)
    g.train(y="y", training_frame=fr)
    return g.model


# ------------------------------------------------------- cost capture

def test_cost_analysis_matches_analytic_matmul():
    """flops from the lowered program within tolerance of 2*M*K*N for a
    plain matmul — the accounting is grounded in the same numbers a
    hand roofline model would use."""
    import jax
    M, K, N = 256, 128, 64
    f = jax.jit(lambda a, b: a @ b)
    a = np.ones((M, K), np.float32)
    b = np.ones((K, N), np.float32)
    cost = costmodel.lowered_cost(lambda: f.lower(a, b))
    assert cost is not None
    analytic = 2.0 * M * K * N
    assert abs(cost.flops - analytic) / analytic < 0.05, cost
    # the operands + output must cross HBM at least once
    assert cost.bytes >= (M * K + K * N + M * N) * 4


def test_scan_scale_multiplies_body_cost():
    """HLO cost analysis counts a scan body ONCE; scale= restores the
    executed trip count (the GBM chunk contract)."""
    import jax
    import jax.numpy as jnp
    T = 7
    M = 64

    def step(c, _):
        return c @ c * 0.5, ()

    def prog(c):
        out, _ = jax.lax.scan(step, c, jnp.arange(T))
        return out

    f = jax.jit(prog)
    c0 = np.eye(M, dtype=np.float32)
    one = costmodel.lowered_cost(lambda: f.lower(c0))
    scaled = costmodel.lowered_cost(lambda: f.lower(c0), scale=T)
    body = 2.0 * M * M * M
    # unscaled ~= one body; scaled ~= T bodies
    assert body * 0.9 < one.flops < body * 1.5, one
    assert abs(scaled.flops - T * one.flops) < 1e-6


def test_executable_cost_caches_and_never_relowers():
    calls = [0]

    def lower():
        import jax
        calls[0] += 1
        return jax.jit(lambda x: x * 2.0).lower(np.ones(8, np.float32))

    key = ("test.cache", 8)
    c1 = costmodel.executable_cost(key, lower)
    c2 = costmodel.executable_cost(key, lower)
    assert calls[0] == 1
    assert c1 == c2 and c1 is not None


# ------------------------------------------------------ roofline math

def test_mfu_in_unit_interval_with_peak_overrides(monkeypatch):
    monkeypatch.setenv("H2O3_PEAK_FLOPS", "1e15")
    monkeypatch.setenv("H2O3_PEAK_BYTES_PER_S", "1e12")
    peaks = costmodel.device_peaks()
    assert peaks["peak_source"] == "override"
    pt = costmodel.roofline_point(flops=1e12, bytes_=1e10, seconds=0.5,
                                  peaks=peaks)
    assert 0.0 < pt["mfu"] <= 1.0
    assert pt["arith_intensity"] == 100.0
    # AI 100 >= ridge 1e15/1e12 = 1000? no: 1e15/1e12 = 1000 -> memory
    assert pt["ridge_intensity"] == 1000.0
    assert pt["roofline_regime"] == "memory-bound"
    pt2 = costmodel.roofline_point(flops=1e13, bytes_=1e9, seconds=0.5,
                                   peaks=peaks)
    assert pt2["roofline_regime"] == "compute-bound"


def test_peak_provenance_is_honest(monkeypatch):
    monkeypatch.delenv("H2O3_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("H2O3_PEAK_BYTES_PER_S", raising=False)
    peaks = costmodel.device_peaks()
    import jax
    if jax.default_backend() == "tpu":
        assert peaks["flops_source"] in ("table", "nominal")
    else:
        # CPU backend: nominal peaks, flagged informational — a
        # CPU-virtual MFU must never read as a utilization claim
        assert peaks["flops_source"] == "nominal"
        assert peaks["informational"] is True
    monkeypatch.setenv("H2O3_PEAK_FLOPS", "not_a_number")
    assert costmodel.device_peaks()["flops_source"] != "override"


# ----------------------------------------------------- train wiring

def test_gbm_perf_output_and_warm_cost_identity(monkeypatch):
    """model.output['perf'] carries a cost_analysis-grounded roofline
    point, and a warm (zero-recompile) retrain reports the IDENTICAL
    executable cost without re-lowering anything."""
    monkeypatch.setenv("H2O3_PEAK_FLOPS", "1e18")   # MFU <= 1 anywhere
    monkeypatch.setenv("H2O3_PEAK_BYTES_PER_S", "1e15")
    fr = _frame()
    m1 = _train(fr)
    perf1 = m1.output.get("perf")
    assert perf1, "trained GBM carries no perf block"
    pt = perf1["train"]
    assert pt["flops_total"] > 0 and pt["bytes_total"] > 0
    assert pt["device_seconds"] > 0
    assert 0.0 < pt["mfu"] <= 1.0
    assert pt["roofline_regime"] in ("compute-bound", "memory-bound")
    assert pt["peak_source"] == "override"
    assert "loop" in perf1["phases"]
    # warm retrain: same config -> same cached executable -> identical
    # cost, no new lowering (the cost cache does not grow)
    cache0 = costmodel.cost_cache_size()
    m2 = _train(fr)
    assert costmodel.cost_cache_size() == cache0, \
        "warm retrain re-lowered an executable for cost capture"
    pt2 = m2.output["perf"]["train"]
    assert pt2["flops_total"] == pt["flops_total"]
    assert pt2["bytes_total"] == pt["bytes_total"]


def test_drf_perf_output():
    from h2o3_tpu.models.drf import H2ORandomForestEstimator
    fr = _frame(seed=3)
    d = H2ORandomForestEstimator(ntrees=5, max_depth=3, seed=4)
    d.train(y="y", training_frame=fr)
    pt = (d.model.output.get("perf") or {}).get("train")
    assert pt and pt["flops_total"] > 0 and pt["device_seconds"] > 0


def test_streamed_gbm_perf_output():
    """The memory-pressure path accounts its level kernels (coverage
    noted honestly — routing/leaf-apply are not costed)."""
    from h2o3_tpu import memman
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    rng = np.random.default_rng(5)
    n, F = 12_000, 6
    X = rng.normal(size=(n, F)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(F)}
    cols["resp"] = np.where(X[:, 0] > 0, "y", "n")
    try:
        memman.reset(budget=int(2.2 * n * F * 4))
        fr = h2o.Frame.from_numpy(cols)
        gbm = H2OGradientBoostingEstimator(
            ntrees=3, max_depth=3, nbins=16, seed=3,
            score_tree_interval=0, stopping_rounds=0)
        gbm.train(y="resp", training_frame=fr)
        m = gbm.model
        assert m.output.get("streamed")
        pt = (m.output.get("perf") or {}).get("train")
        assert pt and pt["flops_total"] > 0
        assert pt.get("note") == "level-histogram kernels only"
        assert "levels" in m.output["perf"]["phases"]
    finally:
        memman.reset()


# ----------------------------------------------------- serve + REST

def test_serve_perf_block_and_rest_endpoint():
    import urllib.request

    from h2o3_tpu import serve
    from h2o3_tpu.api import server as apisrv
    fr = _frame(n=4000, seed=7)
    model = _train(fr, ntrees=4)
    model.key = "perf_acct_gbm"
    dep = serve.deploy(model.key, model=model, max_batch=64,
                       max_delay_ms=0.5)
    srv = apisrv.start_server(port=0)
    try:
        names = [f"f{i}" for i in range(5)]
        rows = [{nm: float(i) for nm in names} for i in range(200)]
        for s in range(0, 200, 40):
            dep.predict_rows(rows[s:s + 40])
        deadline = time.time() + 10
        while time.time() < deadline:
            perf = dep.perf_snapshot()
            if perf is not None and perf["executions"] >= 1:
                break
            time.sleep(0.05)
        assert perf is not None
        assert perf["flops_total"] > 0 and perf["device_seconds"] > 0
        assert perf["mfu"] is not None
        base = f"http://127.0.0.1:{srv.port}"
        st = json.loads(urllib.request.urlopen(
            base + "/3/Serve/stats", timeout=30).read())
        assert st["models"]["perf_acct_gbm"]["perf"]["flops_total"] > 0
        ts = json.loads(urllib.request.urlopen(
            base + "/3/Telemetry/perf", timeout=30).read())
        assert ts["__meta"]["schema_name"] == "TelemetryPerfV3"
        assert "serve" in ts["phases"]
        assert "train.loop" in ts["phases"]
        assert ts["peak"]["peak_source"] in ("table", "override",
                                             "nominal")
    finally:
        srv.stop()
        serve.undeploy(model.key)


# ----------------------------------------------------- cluster merge

def _perf_snapshot(pid, flops, mfu):
    return {
        "version": 1, "time": time.time(), "enabled": True,
        "process": {"pid": pid},
        "samples": [
            {"name": "h2o3_achieved_flops_total", "kind": "counter",
             "labels": {"phase": "train.loop"}, "help": "",
             "value": flops},
            {"name": "h2o3_device_seconds_total", "kind": "counter",
             "labels": {"phase": "train.loop"}, "help": "",
             "value": 1.0},
            {"name": "h2o3_mfu", "kind": "gauge",
             "labels": {"phase": "train.loop"}, "help": "",
             "value": mfu},
        ],
        "spans": [],
    }


def test_perf_metrics_merge_across_processes():
    """The new counters ride the PR-8 snapshot plane: flops sum into
    ONE series; the per-process MFU gauges keep their identity under a
    process label (an average of MFUs would be a lie — shards can run
    different phases)."""
    merged = telesnap.merge_snapshots([
        _perf_snapshot(11, 5e9, 0.25), _perf_snapshot(22, 7e9, 0.35)])
    by = {}
    for m in merged:
        by.setdefault(m["name"], []).append(m)
    (fl,) = by["h2o3_achieved_flops_total"]
    assert fl["value"] == 12e9
    assert fl["labels"] == {"phase": "train.loop"}
    gs = by["h2o3_mfu"]
    assert len(gs) == 2
    assert {g["labels"]["process"] for g in gs} == {"11@?", "22@?"}
    assert sorted(g["value"] for g in gs) == [0.25, 0.35]


# ------------------------------------------------- disabled = no-op

def test_disabled_telemetry_keeps_accounting_a_noop():
    telemetry.set_enabled(False)
    try:
        assert costmodel.accumulator("train.loop") is None

        def exploding_lower():
            raise AssertionError("lower() ran under H2O3_TELEMETRY=0")

        assert costmodel.executable_cost(("off",), exploding_lower) is None
        assert costmodel.lowered_cost(exploding_lower) is None
        costmodel.record("train.loop", costmodel.Cost(1e9, 1e9),
                         seconds=1.0)      # must not touch the registry
        assert costmodel.summary()["enabled"] is False

        N = 20_000

        def per_call_ns():
            t0 = time.perf_counter_ns()
            for _ in range(N):
                costmodel.record("train.loop", None)
            return (time.perf_counter_ns() - t0) / N

        ns = statistics.median(per_call_ns() for _ in range(5))
        assert ns < 5_000, f"disabled record not a no-op: {ns:.0f}ns"
    finally:
        telemetry.set_enabled(True)


# ------------------------------------------------------ perf gate

def _write_rounds(tmp_path, values, extra=None):
    for i, v in enumerate(values, start=1):
        rec = {"metric": "gbm_hist_training_throughput", "value": v,
               "unit": "rows/sec/chip", "vs_baseline": v / 25e6}
        if extra:
            rec.update(extra[i - 1])
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"n": i, "parsed": rec}))
    return str(tmp_path)


def test_perf_gate_passes_improving_trajectory(tmp_path):
    rep = perf_gate.run(_write_rounds(tmp_path, [1e6, 2e6, 3e6]))
    assert rep["ok"] and not rep["skipped"]
    assert rep["metrics"]["value"]["checked"]


def test_perf_gate_fails_regressed_round(tmp_path):
    rep = perf_gate.run(_write_rounds(tmp_path, [1e6, 3e6, 2e6]))
    assert not rep["ok"]
    v = rep["violations"][0]
    assert v["metric"] == "value" and v["round"] == 3
    assert v["best"] == 3e6


def test_perf_gate_noise_band_tolerates_small_dips(tmp_path):
    # 5% dip inside the 10% band: not a regression
    rep = perf_gate.run(_write_rounds(tmp_path, [1e6, 2e6, 1.9e6]))
    assert rep["ok"], rep["violations"]
    # the ratchet anchors on the BEST round, not the previous one: two
    # consecutive in-band dips that compound past the band DO fail
    rep = perf_gate.run(_write_rounds(tmp_path,
                                      [1e6, 2e6, 1.9e6, 1.75e6]))
    assert not rep["ok"]


def test_perf_gate_lower_is_better_metrics(tmp_path):
    d = _write_rounds(tmp_path, [1e6, 2e6, 3e6], extra=[
        {"serve": {"p50_ms": 2.0}},
        {"serve": {"p50_ms": 1.5}},
        {"serve": {"p50_ms": 4.0}},   # latency doubled off best: fail
    ])
    rep = perf_gate.run(d)
    assert not rep["ok"]
    assert any(v["metric"] == "serve.p50_ms" for v in rep["violations"])


def test_perf_gate_skips_below_two_rounds(tmp_path):
    rep = perf_gate.run(str(tmp_path))
    assert rep["ok"] and rep["skipped"]
    rep = perf_gate.run(_write_rounds(tmp_path, [1e6]))
    assert rep["ok"] and rep["skipped"]


def test_perf_gate_excludes_informational_rounds(tmp_path):
    """An off-TPU smoke round (informational: true) must neither fail
    the hardware ratchet with its tiny CPU numbers nor become a fake
    'best' — it is excluded and listed (ISSUE 12)."""
    d = _write_rounds(tmp_path, [1e6, 2e6, 5e3, 3e6], extra=[
        {}, {}, {"informational": True, "backend": "cpu"}, {}])
    rep = perf_gate.run(d)
    assert rep["ok"], rep["violations"]
    assert rep["informational_rounds"] == ["BENCH_r03.json"]
    assert rep["metrics"]["value"]["points"] == 3
    # the per-point peak-provenance flag must NOT exclude a round: it
    # also fires on real TPUs missing from the peak table, and dropping
    # those would let hardware regressions slip the ratchet
    d2 = _write_rounds(tmp_path, [1e6, 2e6, 4e3], extra=[
        {}, {}, {"train.perf_informational": True}])
    rep2 = perf_gate.run(d2)
    assert not rep2["ok"] and rep2["violations"][0]["round"] == 3


def test_perf_gate_repo_trajectory_tier1():
    """The CI wiring (satellite): the checked-in BENCH_r*.json history
    must pass the gate on every tier-1 run. Skips cleanly when fewer
    than two rounds are checked in."""
    rounds = perf_gate.load_rounds(REPO)
    if len(rounds) < 2:
        pytest.skip("fewer than two checked-in bench rounds")
    rep = perf_gate.run(REPO)
    assert rep["ok"], (
        "checked-in bench trajectory regressed:\n"
        + "\n".join(str(v) for v in rep["violations"]))


def test_perf_gate_cli_json_and_exit_codes(tmp_path):
    tool = os.path.join(REPO, "tools", "perf_gate.py")
    good = _write_rounds(tmp_path, [1e6, 2e6])
    r = subprocess.run([sys.executable, tool, "--dir", good, "--json"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["ok"] is True
    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    _write_rounds(bad_dir, [3e6, 1e6])
    r = subprocess.run([sys.executable, tool, "--dir", str(bad_dir),
                        "--json"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    rep = json.loads(r.stdout)
    assert rep["ok"] is False and rep["violations"]
