"""Platform features: parallel CV, segments, weighted quantile, UDFs,
grid recovery, timeline (reference: hex/CVModelBuilder, hex/segments,
hex/quantile weighted, water/udf, hex/faulttolerance/Recovery,
water/TimeLine)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator


def _reg_frame(n=800, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    y = X[:, 0] * 2 + rng.normal(scale=0.3, size=n)
    return h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(3)}, "y": y})


def test_parallel_cv_matches_sequential():
    fr = _reg_frame()
    seq = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1,
                                       nfolds=3, fold_assignment="modulo")
    seq.train(y="y", training_frame=fr)
    par = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1,
                                       nfolds=3, fold_assignment="modulo",
                                       parallelism=3)
    par.train(y="y", training_frame=fr)
    assert seq.model.cross_validation_metrics.mse == pytest.approx(
        par.model.cross_validation_metrics.mse, rel=1e-5)


def test_train_segments():
    from h2o3_tpu.segments import train_segments
    rng = np.random.default_rng(3)
    n = 900
    seg = np.array(["A", "B", "C"], dtype=object)[rng.integers(0, 3, n)]
    x = rng.normal(size=n)
    slope = np.where(seg == "A", 1.0, np.where(seg == "B", 2.0, -1.0))
    y = slope * x + rng.normal(scale=0.1, size=n)
    fr = h2o.Frame.from_numpy({"seg": seg, "x": x, "y": y})
    sm = train_segments(
        lambda: H2OGeneralizedLinearEstimator(Lambda=[0.0]),
        segment_columns=["seg"], y="y", training_frame=fr)
    assert len(sm) == 3
    coefs = {r["segment"]["seg"]: r["model"].coef()["x"] for r in sm}
    assert coefs["A"] == pytest.approx(1.0, abs=0.1)
    assert coefs["B"] == pytest.approx(2.0, abs=0.1)
    assert coefs["C"] == pytest.approx(-1.0, abs=0.1)


def test_weighted_quantile():
    from h2o3_tpu.frame.rollups import weighted_quantile
    rng = np.random.default_rng(5)
    x = rng.normal(size=4000)
    # unit weights ≈ numpy quantile
    q = weighted_quantile(x, [0.1, 0.5, 0.9])
    np.testing.assert_allclose(
        q, np.quantile(x, [0.1, 0.5, 0.9]), atol=0.02)
    # integer weights ≈ repetition
    w = rng.integers(1, 4, len(x)).astype(float)
    q_w = weighted_quantile(x, [0.25, 0.75], weights=w)
    rep = np.repeat(x, w.astype(int))
    np.testing.assert_allclose(q_w, np.quantile(rep, [0.25, 0.75]),
                               atol=0.02)


def test_custom_distribution_and_metric():
    import jax.numpy as jnp
    from h2o3_tpu.models.distributions import (Distribution,
                                               register_custom_distribution)

    class Cauchyish(Distribution):
        """UDF family: pseudo-huber-flavoured robust loss."""
        name = "cauchyish"

        def init_f0(self, y, w):
            return (w * y).sum() / w.sum()

        def grad_hess(self, f, y):
            r = f - y
            return r / (1 + r * r), jnp.ones_like(f)

        def predict(self, f):
            return f

        def deviance(self, w, y, mu):
            return (w * jnp.log1p((y - mu) ** 2)).sum() / w.sum()

    register_custom_distribution("cauchyish", Cauchyish)
    fr = _reg_frame(seed=7)

    def mape(pred, y, w):
        return float(np.mean(np.abs(pred - y)))

    gbm = H2OGradientBoostingEstimator(
        ntrees=40, max_depth=3, seed=1, distribution="custom:cauchyish",
        custom_metric_func=mape)
    gbm.train(y="y", training_frame=fr)
    assert gbm.model.r2() > 0.5   # robust loss underfits vs L2; wiring is the point
    cm = gbm.model.output["custom_metric"]
    assert cm["name"] == "mape" and cm["value"] < 1.0


def test_grid_recovery_resume(tmp_path):
    from h2o3_tpu.models.grid import H2OGridSearch
    fr = _reg_frame(seed=9)
    rec = str(tmp_path / "recovery")
    g1 = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=4, seed=1),
                       {"max_depth": [2, 3]}, grid_id="g1",
                       recovery_dir=rec)
    g1.train(y="y", training_frame=fr)
    assert len(g1.models) == 2
    import os
    assert os.path.exists(os.path.join(rec, "g1.json"))
    # a fresh grid over the same space resumes from artifacts: models
    # load instead of retraining (keys preserved from the manifest)
    g2 = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=4, seed=1),
                       {"max_depth": [2, 3]}, grid_id="g1",
                       recovery_dir=rec)
    g2.train(y="y", training_frame=fr)
    assert len(g2.models) == 2
    m1 = {m.output.get("grid_hyper_params", {}).get("max_depth"):
          m.predict(fr).vec("predict").to_numpy() for m in g1.models}
    m2 = {m.output.get("grid_hyper_params", {}).get("max_depth"):
          m.predict(fr).vec("predict").to_numpy() for m in g2.models}
    for k in m1:
        np.testing.assert_allclose(m1[k], m2[k], rtol=1e-6)


def test_timeline_records_training():
    from h2o3_tpu.log import timeline_events
    before = len(timeline_events())
    fr = _reg_frame(seed=11, n=200)
    gbm = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1)
    gbm.train(y="y", training_frame=fr)
    ev = timeline_events()
    assert len(ev) >= before + 2
    kinds = [e["kind"] for e in ev[-10:]]
    assert "train_start" in kinds and "train_done" in kinds


def test_weighted_quantile_nan_handling():
    from h2o3_tpu.frame.rollups import weighted_quantile
    x = np.concatenate([np.arange(100.0), [np.nan] * 5])
    q = weighted_quantile(x, [0.5, 0.99, 1.0])
    assert np.isfinite(q).all()
    np.testing.assert_allclose(q[0], 49.5, atol=1.0)
    np.testing.assert_allclose(q[2], 99.0, atol=1e-6)
    # NaN weights are excluded, not propagated
    w = np.ones(105)
    w[3] = np.nan
    q2 = weighted_quantile(x, [0.5], weights=w)
    assert np.isfinite(q2).all()
