"""Platform features: parallel CV, segments, weighted quantile, UDFs,
grid recovery, timeline (reference: hex/CVModelBuilder, hex/segments,
hex/quantile weighted, water/udf, hex/faulttolerance/Recovery,
water/TimeLine)."""
import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator


def _reg_frame(n=800, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    y = X[:, 0] * 2 + rng.normal(scale=0.3, size=n)
    return h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(3)}, "y": y})


def test_train_segments():
    from h2o3_tpu.segments import train_segments
    rng = np.random.default_rng(3)
    n = 900
    seg = np.array(["A", "B", "C"], dtype=object)[rng.integers(0, 3, n)]
    x = rng.normal(size=n)
    slope = np.where(seg == "A", 1.0, np.where(seg == "B", 2.0, -1.0))
    y = slope * x + rng.normal(scale=0.1, size=n)
    fr = h2o.Frame.from_numpy({"seg": seg, "x": x, "y": y})
    sm = train_segments(
        lambda: H2OGeneralizedLinearEstimator(Lambda=[0.0]),
        segment_columns=["seg"], y="y", training_frame=fr)
    assert len(sm) == 3
    coefs = {r["segment"]["seg"]: r["model"].coef()["x"] for r in sm}
    assert coefs["A"] == pytest.approx(1.0, abs=0.1)
    assert coefs["B"] == pytest.approx(2.0, abs=0.1)
    assert coefs["C"] == pytest.approx(-1.0, abs=0.1)


def test_weighted_quantile():
    from h2o3_tpu.frame.rollups import weighted_quantile
    rng = np.random.default_rng(5)
    x = rng.normal(size=4000)
    # unit weights ≈ numpy quantile
    q = weighted_quantile(x, [0.1, 0.5, 0.9])
    np.testing.assert_allclose(
        q, np.quantile(x, [0.1, 0.5, 0.9]), atol=0.02)
    # integer weights ≈ repetition
    w = rng.integers(1, 4, len(x)).astype(float)
    q_w = weighted_quantile(x, [0.25, 0.75], weights=w)
    rep = np.repeat(x, w.astype(int))
    np.testing.assert_allclose(q_w, np.quantile(rep, [0.25, 0.75]),
                               atol=0.02)


def test_custom_distribution_and_metric():
    import jax.numpy as jnp
    from h2o3_tpu.models.distributions import (Distribution,
                                               register_custom_distribution)

    class Cauchyish(Distribution):
        """UDF family: pseudo-huber-flavoured robust loss."""
        name = "cauchyish"

        def init_f0(self, y, w):
            return (w * y).sum() / w.sum()

        def grad_hess(self, f, y):
            r = f - y
            return r / (1 + r * r), jnp.ones_like(f)

        def predict(self, f):
            return f

        def deviance(self, w, y, mu):
            return (w * jnp.log1p((y - mu) ** 2)).sum() / w.sum()

    register_custom_distribution("cauchyish", Cauchyish)
    fr = _reg_frame(seed=7)

    def mape(pred, y, w):
        return float(np.mean(np.abs(pred - y)))

    gbm = H2OGradientBoostingEstimator(
        ntrees=40, max_depth=3, seed=1, distribution="custom:cauchyish",
        custom_metric_func=mape)
    gbm.train(y="y", training_frame=fr)
    assert gbm.model.r2() > 0.5   # robust loss underfits vs L2; wiring is the point
    cm = gbm.model.output["custom_metric"]
    assert cm["name"] == "mape" and cm["value"] < 1.0


def test_grid_recovery_resume(tmp_path):
    from h2o3_tpu.models.grid import H2OGridSearch
    fr = _reg_frame(seed=9)
    rec = str(tmp_path / "recovery")
    g1 = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=4, seed=1),
                       {"max_depth": [2, 3]}, grid_id="g1",
                       recovery_dir=rec)
    g1.train(y="y", training_frame=fr)
    assert len(g1.models) == 2
    import os
    assert os.path.exists(os.path.join(rec, "g1.json"))
    # a fresh grid over the same space resumes from artifacts: models
    # load instead of retraining (keys preserved from the manifest)
    g2 = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=4, seed=1),
                       {"max_depth": [2, 3]}, grid_id="g1",
                       recovery_dir=rec)
    g2.train(y="y", training_frame=fr)
    assert len(g2.models) == 2
    m1 = {m.output.get("grid_hyper_params", {}).get("max_depth"):
          m.predict(fr).vec("predict").to_numpy() for m in g1.models}
    m2 = {m.output.get("grid_hyper_params", {}).get("max_depth"):
          m.predict(fr).vec("predict").to_numpy() for m in g2.models}
    for k in m1:
        np.testing.assert_allclose(m1[k], m2[k], rtol=1e-6)


def test_timeline_records_training():
    from h2o3_tpu.log import timeline_events
    before = len(timeline_events())
    fr = _reg_frame(seed=11, n=200)
    gbm = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1)
    gbm.train(y="y", training_frame=fr)
    ev = timeline_events()
    assert len(ev) >= before + 2
    kinds = [e["kind"] for e in ev[-10:]]
    assert "train_start" in kinds and "train_done" in kinds


def test_weighted_quantile_nan_handling():
    from h2o3_tpu.frame.rollups import weighted_quantile
    x = np.concatenate([np.arange(100.0), [np.nan] * 5])
    q = weighted_quantile(x, [0.5, 0.99, 1.0])
    assert np.isfinite(q).all()
    np.testing.assert_allclose(q[0], 49.5, atol=1.0)
    np.testing.assert_allclose(q[2], 99.0, atol=1e-6)
    # NaN weights are excluded, not propagated
    w = np.ones(105)
    w[3] = np.nan
    q2 = weighted_quantile(x, [0.5], weights=w)
    assert np.isfinite(q2).all()


def test_multinomial_glm_vs_sklearn():
    from sklearn.linear_model import LogisticRegression
    rng = np.random.default_rng(21)
    n, K = 2000, 3
    X = rng.normal(size=(n, 4))
    W = rng.normal(size=(4, K)) * 1.5
    y = (X @ W + rng.normal(scale=0.5, size=(n, K))).argmax(1)
    lbl = np.array(["a", "b", "c"], dtype=object)[y]
    fr = h2o.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)}, "y": lbl})
    glm = H2OGeneralizedLinearEstimator(Lambda=[0.0], max_iterations=100)
    glm.train(y="y", training_frame=fr)
    P = np.stack([glm.model.predict(fr).vec(f"p{c}").to_numpy()
                  for c in ("a", "b", "c")], 1)
    sk = LogisticRegression(penalty=None, max_iter=2000).fit(X, y)
    assert np.abs(P - sk.predict_proba(X)).max() < 5e-3
    coefs = glm.model.coef()
    assert set(coefs) == {"a", "b", "c"}


def test_multinomial_glm_save_load(tmp_path):
    rng = np.random.default_rng(23)
    n = 500
    X = rng.normal(size=(n, 2))
    y = np.array(["p", "q", "r"], dtype=object)[
        np.clip(np.digitize(X[:, 0], [-0.5, 0.5]), 0, 2)]
    fr = h2o.Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    glm = H2OGeneralizedLinearEstimator(Lambda=[0.0])
    glm.train(y="y", training_frame=fr)
    p = h2o.save_model(glm.model, str(tmp_path), filename="mglm")
    m2 = h2o.load_model(p)
    p1 = glm.model.predict(fr).vec("pp").to_numpy()
    p2 = m2.predict(fr).vec("pp").to_numpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


def test_partial_dependence_monotone_feature():
    from h2o3_tpu.analytics import partial_dependence
    fr = _reg_frame(seed=31)
    gbm = H2OGradientBoostingEstimator(ntrees=20, max_depth=3, seed=1)
    gbm.train(y="y", training_frame=fr)
    pd = partial_dependence(gbm.model, fr, ["x0", "x1"], nbins=10)
    m = np.asarray(pd["x0"]["mean_response"])
    # y = 2*x0 + noise → PD along x0 rises strongly
    assert m[-1] - m[0] > 2.0
    m1 = np.asarray(pd["x1"]["mean_response"])
    assert (m1.max() - m1.min()) < (m.max() - m.min()) * 0.5


def test_create_frame_and_tabulate():
    from h2o3_tpu.analytics import create_frame, tabulate
    fr = create_frame(rows=1000, cols=8, categorical_fraction=0.25,
                      missing_fraction=0.05, seed=1, has_response=True)
    assert fr.nrow == 1000
    assert fr.ncol == 9
    types = set(fr.types.values())
    assert "enum" in types and "real" in types
    t = tabulate(fr, fr.names[0], "response", nbins_x=5)
    assert sum(sum(r) for r in t["counts"]) <= 1000
    assert len(t["mean_y_per_x"]) == len(t["x_labels"])


def test_deeplearning_autoencoder_detects_anomalies():
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
    rng = np.random.default_rng(41)
    n = 1200
    # inliers on a 2-D manifold inside 5-D space
    z = rng.normal(size=(n, 2))
    W = rng.normal(size=(2, 5))
    X = z @ W + rng.normal(scale=0.05, size=(n, 5))
    X[:15] = rng.uniform(-6, 6, size=(15, 5))    # off-manifold outliers
    fr = h2o.Frame.from_numpy({f"x{i}": X[:, i] for i in range(5)})
    ae = H2ODeepLearningEstimator(autoencoder=True, hidden=[2],
                                  epochs=60, seed=1, activation="tanh")
    ae.train(training_frame=fr)                  # no y needed
    an = ae.model.anomaly(fr).vec("Reconstruction.MSE").to_numpy()
    top = np.argsort(-an)[:20]
    assert np.sum(top < 15) >= 10, np.sum(top < 15)
    rec = ae.model.predict(fr)
    assert rec.ncol == 5
    assert rec.names[0] == "reconstr_x0"
    assert ae.model.output["reconstruction_mse"] < 1.0
