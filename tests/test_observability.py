"""Cluster-wide observability tests (ISSUE 8).

Covers the distributed telemetry plane built on PR 4's process-local
subsystem: multi-snapshot merge correctness (counters sum, histogram
buckets merge, gauges get process labels, Prometheus exposition stays
parse-valid), the /3/Telemetry/cluster + /metrics?scope=cluster REST
surface (with single-process /metrics bit-unchanged), trace-id
propagation end-to-end (traceparent header → serve batcher →
/3/Serve/stats slow-request exemplar → /3/Timeline batch span, all one
id), SPMD collective/straggler metrics on the 8-virtual-device CPU
mesh, the configurable span ring + eviction counter, the shared xprof
profiling helper, and the overhead guards (no-peer aggregation is the
plain local path; H2O3_TELEMETRY=0 keeps the sharded-train observation
a checked no-op).
"""
import json
import re
import statistics
import threading
import time
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import telemetry
from h2o3_tpu.telemetry import snapshot as telesnap
from h2o3_tpu.telemetry import trace as teletrace

_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    rf"(\{{{_LABEL}(,{_LABEL})*\}})?"
    r" (NaN|[-+]?Inf|[-+]?[0-9.eE+-]+)$")


@pytest.fixture(autouse=True)
def _telemetry_on():
    was = telemetry.enabled()
    telemetry.set_enabled(True)
    telemetry.install()
    yield
    telemetry.set_enabled(was)


# --------------------------------------------------- trace-id plumbing

def test_traceparent_parse_format_roundtrip():
    tid = teletrace.new_trace_id()
    assert re.fullmatch(r"[0-9a-f]{32}", tid)
    hdr = teletrace.format_traceparent(tid, span_id=0x2A)
    assert teletrace.parse_traceparent(hdr) == tid
    assert "000000000000002a" in hdr
    # malformed / invalid inputs never raise
    assert teletrace.parse_traceparent(None) is None
    assert teletrace.parse_traceparent("nonsense") is None
    assert teletrace.parse_traceparent("00-" + "0" * 32
                                       + "-00000000000000ab-01") is None
    # all-zero parent-id invalidates the whole header per the spec
    assert teletrace.parse_traceparent(
        f"00-{tid}-" + "0" * 16 + "-01") is None
    # W3C version semantics: ff is invalid; a FUTURE version parses by
    # its first four fields even with trailing fields; version 00 with
    # trailing fields is malformed
    base4 = f"{tid}-00000000000000ab-01"
    assert teletrace.parse_traceparent(f"ff-{base4}") is None
    assert teletrace.parse_traceparent(f"01-{base4}-extra") == tid
    assert teletrace.parse_traceparent(f"00-{base4}-extra") is None
    # bare format never emits an all-zero parent field
    assert "-0000000000000000-" not in teletrace.format_traceparent(tid)


def test_trace_context_binds_and_restores():
    assert teletrace.current_trace_id() is None
    with teletrace.trace_context("aa" * 16):
        assert teletrace.current_trace_id() == "aa" * 16
        with teletrace.trace_context("bb" * 16):
            assert teletrace.current_trace_id() == "bb" * 16
        assert teletrace.current_trace_id() == "aa" * 16
    assert teletrace.current_trace_id() is None


def test_spans_inherit_trace_id_across_thread_handoff():
    """The batcher pattern: the root carries the submitting thread's
    trace; children recorded on another thread against the explicit
    parent inherit it."""
    tid = teletrace.new_trace_id()
    with teletrace.trace_context(tid):
        root = telemetry.open_span("t.trace_root")
    assert root.trace_id == tid
    got = {}

    def worker():
        got["child"] = telemetry.record_span(
            "t.trace_child", time.time(), 0.001, parent=root)

    th = threading.Thread(target=worker)
    th.start()
    th.join()
    root.finish()
    assert got["child"].trace_id == tid


def test_job_propagates_trace_to_worker_thread():
    from h2o3_tpu.jobs import Job
    tid = teletrace.new_trace_id()
    seen = {}
    with teletrace.trace_context(tid):
        job = Job("trace probe")
    assert job.trace_id == tid

    def body(j):
        seen["tid"] = teletrace.current_trace_id()
        return 1

    job.run(body, background=True)
    job.join()
    assert seen["tid"] == tid
    # jobs created with no bound trace still get one (chaseable id)
    j2 = Job("unbound probe")
    assert re.fullmatch(r"[0-9a-f]{32}", j2.trace_id)


# ------------------------------------------------ snapshot merge layer

def _seeded_registry_snapshot(counter_v, gauge_v, hist_obs, pid):
    """A synthetic process snapshot in the wire shape."""
    return {
        "version": 1, "time": time.time(), "enabled": True,
        "process": {"pid": pid},
        "samples": [
            {"name": "obs_total", "kind": "counter",
             "labels": {"k": "v"}, "help": "h", "value": counter_v},
            {"name": "obs_gauge", "kind": "gauge", "labels": {},
             "help": "", "value": gauge_v},
            {"name": "obs_seconds", "kind": "histogram",
             "labels": {}, "help": "",
             "bounds": [1.0, 5.0],
             "bucket_counts": hist_obs,
             "sum": sum(b * c for b, c in zip((0.5, 3.0, 9.0), hist_obs)),
             "count": sum(hist_obs)},
        ],
        "spans": [],
    }


def test_merge_counters_sum_gauges_label_histograms_bucket_merge():
    s1 = _seeded_registry_snapshot(3.0, 7.0, [1, 2, 0], pid=111)
    s2 = _seeded_registry_snapshot(4.0, 9.0, [0, 1, 3], pid=222)
    merged = telesnap.merge_snapshots([s1, s2])
    by = {}
    for m in merged:
        by.setdefault(m["name"], []).append(m)
    # counters: ONE summed series
    (c,) = by["obs_total"]
    assert c["value"] == 7.0 and c["labels"] == {"k": "v"}
    # histograms: bucket-wise merge, cumulative output ends at count
    (h,) = by["obs_seconds"]
    assert h["count"] == 7
    assert h["buckets"][-1] == (float("inf"), 7)
    assert h["buckets"][0] == (1.0, 1)       # 1+0 raw in first bucket
    assert h["buckets"][1] == (5.0, 4)       # +2+1
    # gauges: one series PER process, labeled pid@host (standalone
    # replicas all report jax process_index 0 — pid is what identifies)
    gs = by["obs_gauge"]
    assert {g["labels"]["process"] for g in gs} == {"111@?", "222@?"}
    assert sorted(g["value"] for g in gs) == [7.0, 9.0]


def test_merge_is_valid_prometheus_exposition():
    s1 = _seeded_registry_snapshot(1.0, 2.0, [1, 0, 0], pid=1)
    s2 = _seeded_registry_snapshot(2.0, 3.0, [0, 1, 0], pid=2)
    # peer-only series must not interleave families: give s2 a label
    # set s1 lacks plus an extra family between them
    s2["samples"].insert(1, {"name": "obs_total", "kind": "counter",
                             "labels": {"k": "w"}, "help": "h",
                             "value": 1.0})
    s2["samples"].insert(2, {"name": "obs_other_total",
                             "kind": "counter", "labels": {},
                             "help": "", "value": 1.0})
    text = telemetry.prometheus_text(
        samples=telesnap.merge_snapshots([s1, s2]))
    assert text.endswith("\n")
    for ln in text.splitlines():
        if ln and not ln.startswith("#"):
            assert _METRIC_LINE.match(ln), ln
    # every line of one metric family is CONTIGUOUS (text-format spec;
    # strict parsers reject interleaved groups)
    fam_seen, prev = set(), None
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name = re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*", ln).group(0)
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf):
                name = name[:-len(suf)]
                break
        if name != prev:
            assert name not in fam_seen, f"family {name} interleaved"
            fam_seen.add(name)
            prev = name
    # histogram cumulative contract survives the merge
    buckets = [int(l.rsplit(" ", 1)[1]) for l in text.splitlines()
               if l.startswith("obs_seconds_bucket")]
    assert buckets == sorted(buckets)
    count = int([l for l in text.splitlines()
                 if l.startswith("obs_seconds_count")][0].rsplit(" ", 1)[1])
    assert buckets[-1] == count


def test_merge_kind_skew_falls_back_to_process_series():
    # version skew: a peer reports the same NAME under a different kind.
    # The first-seen kind keeps the merged family; the conflicting
    # samples become per-process series (like a histogram bound
    # mismatch) instead of duplicate/orphaned output
    s1 = _seeded_registry_snapshot(3.0, 7.0, [1, 2, 0], pid=111)
    s2 = _seeded_registry_snapshot(4.0, 9.0, [0, 1, 3], pid=222)
    s2["samples"].append({"name": "obs_seconds", "kind": "counter",
                          "labels": {}, "help": "", "value": 5.0})
    s2["samples"].append({"name": "obs_total", "kind": "histogram",
                          "labels": {"k": "v"}, "help": "",
                          "bounds": [1.0], "bucket_counts": [1, 0],
                          "sum": 0.5, "count": 1})
    merged = telesnap.merge_snapshots([s1, s2])
    by = {}
    for m in merged:
        by.setdefault(m["name"], []).append(m)
    # the counter family still sums across processes exactly once...
    assert sorted(m["kind"] for m in by["obs_total"]) == \
        ["counter", "histogram"]
    (c,) = [m for m in by["obs_total"] if m["kind"] == "counter"]
    assert c["value"] == 7.0 and "process" not in c["labels"]
    # ...a histogram skewed into a scalar family survives as one
    # process-labeled series (its suffixed lines render validly)...
    (hskew,) = [m for m in by["obs_total"] if m["kind"] == "histogram"]
    assert hskew["labels"]["process"] == "222@?" and hskew["count"] == 1
    # ...but a SCALAR skewed into a histogram family is dropped: a bare
    # name line has no legal spelling under TYPE histogram
    assert [m["kind"] for m in by["obs_seconds"]] == ["histogram"]
    (h,) = by["obs_seconds"]
    assert h["count"] == 7
    # gauge-vs-counter skew: gauges are always per-process series, but
    # the shared NAME must still render contiguously with its family —
    # while a gauge skewed into a HISTOGRAM family is dropped even when
    # the gauge was scanned before the family registered
    s2["samples"].append({"name": "obs_total", "kind": "gauge",
                          "labels": {}, "help": "", "value": 1.5})
    s1["samples"].insert(0, {"name": "obs_seconds", "kind": "gauge",
                             "labels": {"q": "z"}, "help": "",
                             "value": 9.9})
    merged = telesnap.merge_snapshots([s1, s2])
    assert all(m["kind"] == "histogram"
               for m in merged if m["name"] == "obs_seconds")
    # still renders (no KeyError, no duplicate sample lines) and every
    # metric NAME stays contiguous — kind skew degrades one metric, it
    # must not invalidate the whole scrape
    text = telemetry.prometheus_text(samples=merged)
    lines = [l for l in text.splitlines() if l and not l.startswith("#")]
    assert len(lines) == len(set(lines))
    # only _bucket/_sum/_count sample names may appear inside the
    # histogram family — a bare scalar line there fails strict parsers
    assert not any(re.match(r"obs_seconds[{ ]", ln) for ln in lines)
    fam_seen, prev = set(), None
    for ln in lines:
        name = re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*", ln).group(0)
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf):
                name = name[:-len(suf)]
                break
        if name != prev:
            assert name not in fam_seen, f"family {name} interleaved"
            fam_seen.add(name)
            prev = name


def test_peer_timeout_env_is_fault_tolerant(monkeypatch):
    monkeypatch.setenv("H2O3_TELEMETRY_PEER_TIMEOUT", "2s")
    assert telesnap._env_peer_timeout() == 2.0
    monkeypatch.setenv("H2O3_TELEMETRY_PEER_TIMEOUT", "-1")
    assert telesnap._env_peer_timeout() == 2.0
    monkeypatch.setenv("H2O3_TELEMETRY_PEER_TIMEOUT", "0.25")
    assert telesnap._env_peer_timeout() == 0.25


def test_merge_histogram_bound_mismatch_labels_every_process():
    """Version skew on histogram bounds: EVERY process's series must
    come out process-labeled — an unlabeled first-seen series would
    read as the cluster aggregate while holding one process's data."""
    s1 = _seeded_registry_snapshot(1.0, 1.0, [1, 0, 0], pid=111)
    s2 = _seeded_registry_snapshot(1.0, 1.0, [0, 1, 0], pid=222)
    s3 = _seeded_registry_snapshot(1.0, 1.0, [0, 0, 1], pid=333)
    for s in s1["samples"]:          # s1 = the old-version process
        if s["name"] == "obs_seconds":
            s["bounds"] = [2.0, 10.0]
    merged = telesnap.merge_snapshots([s1, s2, s3])
    hs = [m for m in merged if m["name"] == "obs_seconds"]
    assert len(hs) == 3
    assert {m["labels"].get("process") for m in hs} == \
        {"111@?", "222@?", "333@?"}
    # matching families still merge into one unlabeled series
    (c,) = [m for m in merged if m["name"] == "obs_total"]
    assert c["value"] == 3.0 and "process" not in c["labels"]


def test_trickling_peer_cannot_stall_cluster_scrape(monkeypatch):
    """The urlopen timeout is per socket operation — a sick peer that
    accepts and dribbles bytes never trips it. The aggregate deadline
    must bound the whole scrape and report the peer as failed."""
    import socket
    import threading
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    stop = threading.Event()

    def dribble():
        try:
            conn, _ = srv.accept()
            conn.recv(65536)
            conn.sendall(b"HTTP/1.1 200 OK\r\n"
                         b"Content-Length: 100000\r\n\r\n")
            while not stop.is_set():
                try:
                    conn.sendall(b"x")
                except OSError:
                    break
                stop.wait(0.1)
        except OSError:
            pass

    threading.Thread(target=dribble, daemon=True).start()
    monkeypatch.setattr(telesnap, "PEER_TIMEOUT_S", 0.5)
    monkeypatch.setenv("H2O3_TELEMETRY_PEERS", f"127.0.0.1:{port}")
    t0 = time.perf_counter()
    _samples, meta = telesnap.cluster_samples()
    took = time.perf_counter() - t0
    stop.set()
    srv.close()
    assert took < 5.0, took          # deadline is 2x0.5s, not unbounded
    assert meta["peers_failed"] and not meta["peers_ok"]
    assert meta["processes"] == 1


def test_local_snapshot_round_trips_registry():
    telemetry.counter("snap_probe_total").inc(5)
    telemetry.histogram("snap_probe_seconds",
                        bounds=(0.5, 5.0)).observe(1.0)
    with telemetry.span("t.snap_probe"):
        pass
    snap = json.loads(json.dumps(telemetry.local_snapshot()))
    names = {s["name"] for s in snap["samples"]}
    assert {"snap_probe_total", "snap_probe_seconds"} <= names
    h = [s for s in snap["samples"]
         if s["name"] == "snap_probe_seconds"][0]
    assert h["bounds"] == [0.5, 5.0]
    assert sum(h["bucket_counts"]) == h["count"]
    assert any(sp["name"] == "t.snap_probe" for sp in snap["spans"])
    assert "pid" in snap["process"]
    # merging a snapshot with itself doubles counters exactly
    merged = telesnap.merge_snapshots([snap, snap])
    c = [m for m in merged if m["name"] == "snap_probe_total"][0]
    assert c["value"] == 10.0


def test_disabled_snapshot_is_empty():
    telemetry.set_enabled(False)
    try:
        snap = telemetry.local_snapshot()
        assert snap["enabled"] is False
        assert snap["samples"] == [] and snap["spans"] == []
    finally:
        telemetry.set_enabled(True)


def test_no_peer_cluster_path_is_local_identity():
    """Single-process overhead guard: with no peers configured the
    cluster path returns the plain local samples (no merge pass) and
    stays cheap."""
    import os
    assert not os.environ.get("H2O3_TELEMETRY_PEERS")
    samples, meta = telemetry.cluster_samples()
    assert meta["processes"] == 1 and meta["peers"] == 0
    local = telemetry.registry().samples()
    assert [s["name"] for s in samples] == [s["name"] for s in local]
    t0 = time.perf_counter()
    for _ in range(20):
        telemetry.cluster_samples()
    per_call = (time.perf_counter() - t0) / 20
    # one registry scrape's cost, not an HTTP/merge pass
    assert per_call < 0.25, per_call


# ----------------------------------------------------- span ring knobs

def test_span_ring_capacity_and_dropped_counter():
    from h2o3_tpu.telemetry import spans as spans_mod
    old_cap = spans_mod._RING_CAP
    before = telemetry.registry().value("h2o3_spans_dropped_total")
    try:
        telemetry.set_ring_capacity(32)
        for _ in range(100):
            telemetry.record_span("t.ring_probe", time.time(), 1e-4)
        assert len(telemetry.finished_spans()) <= 32
        # n=0 means a SPANLESS view (the cluster-scrape spelling), not
        # the whole ring
        assert telemetry.finished_spans(0) == []
        assert telemetry.local_snapshot(max_spans=0)["spans"] == []
        dropped = telemetry.registry().value(
            "h2o3_spans_dropped_total") - before
        assert dropped >= 100 - 32, dropped
    finally:
        telemetry.set_ring_capacity(old_cap)


def test_span_ring_env_parsing(monkeypatch):
    from h2o3_tpu.telemetry import spans as spans_mod
    monkeypatch.setenv("H2O3_SPAN_RING", "4096")
    assert spans_mod._env_ring_cap() == 4096
    monkeypatch.setenv("H2O3_SPAN_RING", "2")      # floor at 16
    assert spans_mod._env_ring_cap() == 16
    monkeypatch.setenv("H2O3_SPAN_RING", "junk")   # default, not a crash
    assert spans_mod._env_ring_cap() == 8192


# -------------------------------------------- shared profiling helper

def test_profile_helper_noop_without_dir(monkeypatch):
    from h2o3_tpu.telemetry import profiling
    monkeypatch.delenv("XPROF_TRACE_DIR", raising=False)
    with profiling.profile("noop", trace_dir=None) as p:
        assert p.dir is None
    assert profiling.last_trace_dir() is None


def test_profile_helper_argv_and_env(monkeypatch, tmp_path):
    from h2o3_tpu.telemetry import profiling
    assert profiling.trace_dir_from_argv(["x", "--xprof-trace",
                                          "/tmp/t"]) == "/tmp/t"
    bare = profiling.trace_dir_from_argv(["x", "--xprof-trace"])
    assert bare and bare.startswith("/tmp/")
    monkeypatch.setenv("XPROF_TRACE_DIR", str(tmp_path))
    assert profiling.trace_dir_from_argv(["x"]) == str(tmp_path)


def test_profile_helper_captures_trace(tmp_path):
    """A real (CPU-backend) jax.profiler capture through the helper —
    degrading gracefully is allowed, but a successful capture must
    leave artifacts in <dir>/<name>."""
    import os
    from h2o3_tpu.telemetry import profiling
    import jax
    import jax.numpy as jnp
    with profiling.profile("unit", trace_dir=str(tmp_path)) as p:
        jnp.ones(8).sum().block_until_ready()
    if p.dir is not None:       # capture started: artifacts must exist
        assert profiling.last_trace_dir() == str(tmp_path / "unit")
        assert os.path.isdir(p.dir) and os.listdir(p.dir)


# ------------------------------------- serve exemplars + REST round trip

def _tiny_frame(n=600, f=4, seed=3):
    import h2o3_tpu as h2o
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    cols = {f"f{i}": X[:, i] for i in range(f)}
    cols["label"] = np.where(X[:, 0] > 0, "Y", "N")
    return h2o.Frame.from_numpy(cols), X


def _train_gbm(fr, **kw):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    gbm = H2OGradientBoostingEstimator(
        ntrees=3, max_depth=3, seed=1, min_rows=1.0,
        score_tree_interval=0, stopping_rounds=0, **kw)
    gbm.train(y="label", training_frame=fr)
    return gbm.model


def test_slow_request_exemplars_top_k():
    from h2o3_tpu.serve.stats import ServeStats, _SLOW_K
    st = ServeStats(model="exemplar_probe")
    for i in range(30):
        st.record_request(float(i), 1, trace_id=f"{i:032x}")
    slow = st.slow_requests()
    assert len(slow) == _SLOW_K
    lats = [e["latency_ms"] for e in slow]
    assert lats == sorted(lats, reverse=True)
    assert lats[0] == 29.0 and lats[-1] == 30.0 - _SLOW_K
    assert slow[0]["trace_id"] == f"{29:032x}"
    assert st.snapshot()["slow_requests"] == slow


def test_trace_id_rest_to_batcher_to_timeline(tmp_path):
    """The e2e acceptance: a serve request's traceparent header, its
    /3/Serve/stats slow-request exemplar, its serve.request span AND
    its serve.batch /3/Timeline span all carry the SAME trace id."""
    from h2o3_tpu import serve
    from h2o3_tpu.api import server as apisrv
    fr, X = _tiny_frame(seed=11)
    model = _train_gbm(fr)
    model.key = "obs_trace_gbm"
    dep = serve.deploy(model.key, model=model, max_batch=8,
                       max_delay_ms=0.5)
    srv = apisrv.start_server(port=0)
    tid = "c1" * 16
    try:
        base = f"http://127.0.0.1:{srv.port}"
        req = urllib.request.Request(
            base + "/3/Predictions/models/obs_trace_gbm/rows",
            data=json.dumps({"rows": [
                {f"f{i}": float(X[0, i]) for i in range(4)}]}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{tid}-00000000000000ab-01"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            assert r.headers.get("X-H2O3-Trace-Id") == tid
            assert tid in (r.headers.get("traceparent") or "")
        # a request with NO traceparent still gets a fresh echoed id
        req2 = urllib.request.Request(
            base + "/3/Predictions/models/obs_trace_gbm/rows",
            data=json.dumps({"rows": [
                {f"f{i}": float(X[1, i]) for i in range(4)}]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req2, timeout=30) as r:
            fresh = r.headers.get("X-H2O3-Trace-Id")
            assert fresh and fresh != tid

        st = json.loads(urllib.request.urlopen(
            base + "/3/Serve/stats", timeout=30).read())
        slow = st["models"]["obs_trace_gbm"]["slow_requests"]
        assert any(e["trace_id"] == tid for e in slow), slow

        tr = json.loads(urllib.request.urlopen(
            base + "/3/Timeline?format=trace", timeout=30).read())
        evs = tr["traceEvents"]
        req_spans = [e for e in evs if e["name"] == "serve.request"
                     and e["args"].get("trace_id") == tid]
        assert req_spans, "serve.request span lost the trace id"
        batch_spans = [e for e in evs if e["name"] == "serve.batch"
                       and tid in (e["args"].get("trace_ids") or "")]
        assert batch_spans, "serve.batch span lost the trace id"
    finally:
        srv.stop()
        serve.undeploy(model.key)


def test_cluster_endpoint_merges_two_snapshots_over_rest():
    """GET /3/Telemetry/cluster with this server listed as its own peer:
    2 snapshots merge (counters exactly double, gauges process-labeled)
    and the prometheus rendering of the merged view stays parse-valid.
    Single-process /metrics output is unchanged (no process labels)."""
    import os
    from h2o3_tpu.api import server as apisrv
    telemetry.counter("cluster_probe_total").inc(3)
    telemetry.gauge("cluster_probe_gauge").set(4)
    srv = apisrv.start_server(port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        plain = urllib.request.urlopen(base + "/metrics",
                                       timeout=30).read().decode()
        assert 'process="' not in plain
        local_v = telemetry.registry().value("cluster_probe_total")
        os.environ["H2O3_TELEMETRY_PEERS"] = f"127.0.0.1:{srv.port}"
        try:
            cl = json.loads(urllib.request.urlopen(
                base + "/3/Telemetry/cluster", timeout=30).read())
            assert cl["processes"] == 2
            assert cl["peers_ok"] == [f"127.0.0.1:{srv.port}"]
            # the self-peer spelling merges (that is what makes this a
            # 2-process test) but is FLAGGED: a launcher shipping one
            # shared peer list to every replica double-counts, and the
            # scrape meta must say so
            assert cl["peers_self"] == [f"127.0.0.1:{srv.port}"]
            assert cl["metrics"]["cluster_probe_total"] == 2 * local_v
            # gauges appear per process, never summed (the self-peer's
            # duplicate process label is disambiguated, not collapsed)
            glabels = [k for k in cl["metrics"]
                       if k.startswith("cluster_probe_gauge{")]
            assert len(glabels) == 2 and all("process=" in k
                                             for k in glabels), glabels
            ptext = urllib.request.urlopen(
                base + "/metrics?scope=cluster",
                timeout=30).read().decode()
            for ln in ptext.splitlines():
                if ln and not ln.startswith("#"):
                    assert _METRIC_LINE.match(ln), ln
            assert "cluster_probe_total" in ptext
            # scrape-health gauges ride in the merged exposition so a
            # Prometheus consumer can tell partial scrapes from resets
            assert cl["metrics"]["h2o3_telemetry_processes"] == 2.0
            assert cl["metrics"]["h2o3_telemetry_peers_failed"] == 0.0
            assert "h2o3_telemetry_processes 2" in ptext
            # dead peers are reported, never fatal — and flagged in the
            # health gauge
            os.environ["H2O3_TELEMETRY_PEERS"] += ",127.0.0.1:1"
            cl2 = json.loads(urllib.request.urlopen(
                base + "/3/Telemetry/cluster", timeout=30).read())
            assert cl2["peers_failed"] and cl2["processes"] == 2
            assert cl2["metrics"]["h2o3_telemetry_peers_failed"] == 1.0
        finally:
            del os.environ["H2O3_TELEMETRY_PEERS"]
    finally:
        srv.stop()


# ------------------------------- SPMD collective / straggler metrics

def test_sharded_train_records_collective_metrics():
    """On the 8-virtual-device CPU mesh a sharded GBM train must leave
    the straggler gauge + collective-wait/shard-step histograms in the
    registry and the per-train summary in model.output['spmd']."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    fr, _ = _tiny_frame(n=1600, seed=13)
    model = _train_gbm(fr)
    spmd = model.output["spmd"]
    assert spmd["n_data"] == len(jax.devices())
    coll = spmd.get("collective")
    assert coll is not None
    assert coll["n_shards"] == spmd["n_data"]
    assert coll["straggler_ratio"] >= 1.0
    assert 0.0 <= coll["collective_wait_share"] <= 1.0
    names = {s["name"] for s in telemetry.registry().samples()}
    assert {"h2o3_straggler_ratio", "h2o3_collective_wait_ms",
            "h2o3_shard_step_ms"} <= names
    assert telemetry.registry().value("h2o3_straggler_ratio",
                                      {"algo": "gbm"}) >= 1.0


def test_disabled_telemetry_sharded_train_records_no_collective():
    """H2O3_TELEMETRY=0: the sharded train path must not observe shard
    readiness at all — no collective summary, no registry writes."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    telemetry.set_enabled(False)
    try:
        fr, _ = _tiny_frame(n=800, seed=17)
        model = _train_gbm(fr)
        assert model.output["spmd"]["n_data"] == len(jax.devices())
        assert "collective" not in model.output["spmd"]
    finally:
        telemetry.set_enabled(True)


def test_observe_step_noop_guards():
    """H2O3_TELEMETRY=0 keeps the sharded-path observation a checked
    no-op (ns budget), and single-shard outputs observe nothing."""
    import jax
    import jax.numpy as jnp
    from h2o3_tpu.parallel.shardstats import observe_sharded_step
    arr = jnp.ones(8)
    telemetry.set_enabled(False)
    try:
        N = 5_000
        def per_call_ns():
            t0 = time.perf_counter_ns()
            for _ in range(N):
                observe_sharded_step(arr, 0.0, algo="gbm")
            return (time.perf_counter_ns() - t0) / N
        ns = statistics.median(per_call_ns() for _ in range(3))
        assert ns < 20_000, f"disabled observe not a no-op: {ns:.0f}ns"
    finally:
        telemetry.set_enabled(True)
    # single-device array → nothing to observe even when enabled
    single = jax.device_put(np.ones(8), jax.devices()[0])
    assert observe_sharded_step(single, time.perf_counter()) is None
    # host junk → None, not a crash
    assert observe_sharded_step({"x": 3}, 0.0) is None


def test_sharded_ingest_d2d_bytes_attributed():
    """PR 7's stitched assembly: boundary D2D moves + the pad upload
    now land in the pipeline-labeled transfer counters (ISSUE 8)."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from h2o3_tpu.ingest.stream import ChunkDeviceStreamer
    from h2o3_tpu.ingest.chunk import EncodedColumn
    from h2o3_tpu.frame.vec import T_REAL
    from h2o3_tpu.parallel.mesh import current_mesh
    mesh = current_mesh()
    reg = telemetry.registry()
    d2d0 = reg.value("h2o3_d2d_pipeline_bytes_total",
                     {"pipeline": "ingest"})
    st = ChunkDeviceStreamer([0], [T_REAL], n_chunks=3, mesh=mesh)
    rng = np.random.default_rng(0)
    # 3 chunks of 37 rows: chunk boundaries straddle the 8-shard row
    # partition, forcing boundary fragments to move D2D at assembly
    for ci in range(3):
        st.add(ci, [EncodedColumn(T_REAL,
                                  rng.normal(size=37).astype(np.float64))])
    out = st.assemble()
    assert 0 in out
    moved = st._moved_rows
    assert moved > 0, "expected boundary-straddling fragments"
    d2d = reg.value("h2o3_d2d_pipeline_bytes_total",
                    {"pipeline": "ingest"}) - d2d0
    assert d2d >= moved * 4, (d2d, moved)   # ≥ one f32 lane per moved row
    assert reg.value("h2o3_d2d_bytes_total") >= d2d


def test_stale_observation_records_nothing():
    """A chunk whose shards were all ready before the first poll (the
    host sat in e.g. a cold compile between dispatch and observation)
    carries no order signal: it must be reported stale, kept OUT of
    the step/wait/straggler metrics, and excluded from the per-train
    aggregates instead of contributing a fabricated ~1.0 ratio."""
    import jax
    from h2o3_tpu.parallel.mesh import partitioner
    from h2o3_tpu.parallel.shardstats import (merge_observations,
                                              observe_sharded_step)
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    partn = partitioner()
    arr = partn.shard_rows(np.ones((8 * 16, 3), np.float32))
    jax.block_until_ready(arr)           # long done before the "poll"
    reg = telemetry.registry()
    g0 = reg.value("h2o3_straggler_ratio", {"algo": "stale_probe"})
    obs = observe_sharded_step(arr, time.perf_counter() - 5.0,
                               algo="stale_probe")
    assert obs == {"n_shards": len(jax.devices()), "stale": True}
    assert reg.value("h2o3_straggler_ratio",
                     {"algo": "stale_probe"}) == g0
    # merge: stale chunks counted, aggregates from fresh chunks only
    fresh = {"n_shards": 8, "slowest_ms": 10.0, "median_ms": 5.0,
             "straggler_ratio": 2.0, "collective_wait_ms": 4.0,
             "collective_wait_share": 0.4}
    merged = merge_observations([obs, fresh, None])
    assert merged["chunks_observed"] == 1
    assert merged["chunks_stale"] == 1
    assert merged["straggler_ratio"] == 2.0
    # every chunk stale → counts only, no invented ratios (n_shards
    # stays present: test_spmd_parity indexes it whenever coll exists)
    all_stale = merge_observations([obs, dict(obs)])
    assert all_stale == {"chunks_observed": 0, "chunks_stale": 2,
                         "n_shards": len(jax.devices())}


def test_partially_censored_observation_uses_live_shards(monkeypatch):
    """Shards already done at the first poll sweep (the host was
    delayed, but not long enough for the WHOLE step to finish) are
    censored: step/wait/ratio come from the live completions only, so
    host-delay time never lands in the step histogram or drags the
    straggler ratio toward a fabricated 1.0."""
    import jax
    from h2o3_tpu.parallel import shardstats
    from h2o3_tpu.parallel.mesh import partitioner
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    nd = len(jax.devices())
    arr = partitioner().shard_rows(np.ones((nd * 16, 3), np.float32))
    jax.block_until_ready(arr)
    # host delay D=50ms: all but the two slowest shards were already
    # done at the first sweep and read the identical censored D
    fake = [0.050] * (nd - 2) + [0.060, 0.120]
    monkeypatch.setattr(shardstats, "_shard_ready_times",
                        lambda shards, t0: (fake, set(range(nd - 2))))
    obs = shardstats.observe_sharded_step(arr, 0.0, algo="censor_probe")
    assert obs["n_shards"] == nd and obs["shards_censored"] == nd - 2
    # live shards only: slowest 120ms, median of [60, 120] = 90ms
    assert obs["slowest_ms"] == 120.0 and obs["median_ms"] == 90.0
    assert obs["straggler_ratio"] == round(120.0 / 90.0, 4)
    # the censored 50ms host-delay readings never hit the histogram
    reg = telemetry.registry()
    sample = next(s for s in reg.samples()
                  if s["name"] == "h2o3_shard_step_ms"
                  and s["labels"].get("algo") == "censor_probe")
    assert sample["count"] == 2
    # fewer than two live completions → stale, nothing recorded
    monkeypatch.setattr(shardstats, "_shard_ready_times",
                        lambda shards, t0: (fake, set(range(nd - 1))))
    assert shardstats.observe_sharded_step(
        arr, 0.0, algo="censor_probe2") == {"n_shards": nd,
                                            "stale": True}


def test_slow_request_exemplars_age_on_wall_clock():
    """Exemplar generations must rotate on wall clock too: at low QPS
    the 4096-request reservoir wrap can take days, and a cold-start
    compile-era top-k would otherwise mask every later spike."""
    from h2o3_tpu.serve.stats import _SLOW_WINDOW_S, ServeStats
    st = ServeStats(model="exemplar_age_probe")
    st.record_request(500.0, 1, trace_id="a" * 32)   # warmup-era entry
    # first window elapses: next request rotates it into the previous
    # generation — still scrapeable for one full window
    st._slow_t0 -= _SLOW_WINDOW_S + 1
    st.record_request(1.0, 1, trace_id="b" * 32)
    lats = {e["latency_ms"] for e in st.slow_requests()}
    assert 500.0 in lats and 1.0 in lats
    # second window: the warmup entry ages out entirely; a later spike
    # smaller than it now tops the exemplars instead of being masked
    st._slow_t0 -= _SLOW_WINDOW_S + 1
    st.record_request(2.0, 1, trace_id="c" * 32)
    st.record_request(150.0, 1, trace_id="d" * 32)   # the real spike
    slow = st.slow_requests()
    lats = [e["latency_ms"] for e in slow]
    assert 500.0 not in lats
    assert slow[0]["latency_ms"] == 150.0
    assert slow[0]["trace_id"] == "d" * 32


def test_failed_requests_enter_slow_exemplars():
    """A deadline blowout or device error is slower than every
    successful request — it must appear in the slow-request exemplars
    (flagged error=) while leaving the success-only reservoir and
    request counters untouched."""
    from h2o3_tpu.serve.stats import ServeStats
    st = ServeStats(model="fail_probe")
    st.record_request(5.0, 1, trace_id="a" * 32)
    st.record_failed_exemplar(250.0, 2, "b" * 32, "deadline")
    slow = st.slow_requests()
    assert slow[0]["latency_ms"] == 250.0
    assert slow[0]["error"] == "deadline"
    assert slow[0]["trace_id"] == "b" * 32
    assert "error" not in slow[1]            # successes stay unflagged
    snap = st.snapshot()
    assert snap["requests"] == 1             # failure not double-counted
    assert snap["p99_ms"] is not None and snap["p99_ms"] <= 5.0


def test_fat_peer_body_is_size_capped(monkeypatch):
    """A peer entry misconfigured to point at something fat and fast (a
    log stream, a file server) must fail the fetch at PEER_MAX_BYTES
    instead of buffering gigabytes inside the observing process."""
    import socket
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    stop = threading.Event()
    blob = b"x" * (1 << 20)

    def firehose():
        try:
            conn, _ = srv.accept()
            conn.recv(65536)
            conn.sendall(b"HTTP/1.1 200 OK\r\n"
                         b"Content-Length: 1000000000\r\n\r\n")
            while not stop.is_set():
                try:
                    conn.sendall(blob)
                except OSError:
                    break
        except OSError:
            pass

    threading.Thread(target=firehose, daemon=True).start()
    monkeypatch.setattr(telesnap, "PEER_MAX_BYTES", 4 << 20)
    with pytest.raises(ValueError, match="exceeded"):
        telesnap.fetch_peer_snapshot(f"127.0.0.1:{port}", timeout=5.0)
    stop.set()
    srv.close()
    # and the scrape path reports it as a failed peer, never fatal
    monkeypatch.setenv("H2O3_TELEMETRY_PEERS", f"127.0.0.1:{port}")
    _samples, meta = telesnap.cluster_samples()
    assert meta["peers_failed"]
